"""Shared lockdep-on-for-this-module fixture (test_chaos, test_live).

The fault harness and the live twin suites double as RACE DRIVERS:
running them with HM_LOCKDEP=1 makes every lock they churn through an
instrumented one, and the module teardown asserts the observed global
lock-order graph is clean — no potential deadlock cycle, no declared-
hierarchy inversion, no leaf violation — even though no deadlock fired.

`blocking` violations are excluded from the assertion: the live path's
feed-append + clock-row commit inside the engine lock is the KNOWN,
ROADMAP-documented emission-serialization cost (the per-doc emission
lock split is the successor work); lockdep still records them so
`report()` shows the debt.
"""

import os

import pytest

from hypermerge_tpu.analysis import lockdep


def lockdep_suite():
    """Module-scoped autouse fixture factory: enable lockdep for every
    lock created while this module's tests run, and assert a clean
    graph at teardown."""

    @pytest.fixture(autouse=True, scope="module")
    def _lockdep_suite():
        was_env = os.environ.get("HM_LOCKDEP")
        was = lockdep.enabled()
        os.environ["HM_LOCKDEP"] = "1"
        lockdep.enable(True)
        lockdep.reset()
        yield
        lockdep.enable(was)
        if was_env is None:
            os.environ.pop("HM_LOCKDEP", None)
        else:
            os.environ["HM_LOCKDEP"] = was_env
        lockdep.assert_clean(
            allow_kinds=("blocking",),
            msg="the suite's lock churn surfaced lockdep findings:",
        )

    return _lockdep_suite
