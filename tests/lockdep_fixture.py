"""Shared lockdep-on-for-this-module fixture (test_chaos, test_live,
test_write_plane).

The fault harness and the live twin suites double as RACE DRIVERS:
running them with HM_LOCKDEP=1 makes every lock they churn through an
instrumented one — the per-doc `doc.emit` emission domains and the
`store.wal` journal lock included — and the module teardown asserts
the observed global lock-order graph is clean: no potential deadlock
cycle, no declared-hierarchy inversion, no leaf violation, and no
same-class `doc.emit` nesting (the no-cross-doc-lock-across-push
invariant of the write plane), even though no deadlock fired.

Since the write-plane split (PR 14) `blocking` violations are asserted
too: the only no-block class left is `live.engine` (tick/dirty-set
coordination), and ANY blocking call under it is a regression of the
`lock.held_blocking_ms.live_engine == 0` gate. Blocking under a doc's
own emission domain is by-design (a durable ack stalls exactly one
doc) and is not a violation.
"""

import os

import pytest

from hypermerge_tpu.analysis import lockdep


def lockdep_suite():
    """Module-scoped autouse fixture factory: enable lockdep for every
    lock created while this module's tests run, and assert a clean
    graph at teardown."""

    @pytest.fixture(autouse=True, scope="module")
    def _lockdep_suite():
        was_env = os.environ.get("HM_LOCKDEP")
        was = lockdep.enabled()
        os.environ["HM_LOCKDEP"] = "1"
        lockdep.enable(True)
        lockdep.reset()
        yield
        lockdep.enable(was)
        if was_env is None:
            os.environ.pop("HM_LOCKDEP", None)
        else:
            os.environ["HM_LOCKDEP"] = was_env
        lockdep.assert_clean(
            msg="the suite's lock churn surfaced lockdep findings:",
        )

    return _lockdep_suite
