"""FileFeedStorage: block-count index shortcut + torn-tail healing."""

import os
import struct

from hypermerge_tpu.storage.feed import FileFeedStorage


def _mk(tmp_path, blocks):
    path = str(tmp_path / "ab" / "feed")
    s = FileFeedStorage(path)
    for b in blocks:
        s.append(b)
    return path


def test_len_index_shortcut(tmp_path):
    path = _mk(tmp_path, [b"one", b"two", b"three"])
    assert os.path.exists(path + ".len")
    s2 = FileFeedStorage(path)
    assert len(s2) == 3  # count via .len + stat, no scan
    assert not s2._scanned
    assert s2.get(1) == b"two"  # offsets built on demand


def test_stale_len_index_falls_back_to_scan(tmp_path):
    path = _mk(tmp_path, [b"aa", b"bb"])
    with open(path + ".len", "wb") as fh:
        fh.write(struct.pack("<QQ", 99, 12345))  # wrong end offset
    s2 = FileFeedStorage(path)
    assert len(s2) == 2  # mismatch detected -> full scan
    assert s2.get(0) == b"aa"


def test_torn_tail_with_stale_len_heals(tmp_path):
    path = _mk(tmp_path, [b"aa", b"bb"])
    # simulate a crash mid-append: partial block bytes, .len not updated
    with open(path, "ab") as fh:
        fh.write(b"\x50\x00\x00\x00parti")  # claims 80 bytes, has 5
    s2 = FileFeedStorage(path)
    assert len(s2) == 2  # size mismatch -> scan -> torn tail dropped
    # appending over the torn tail truncates it and re-indexes
    s2.append(b"cc")
    s3 = FileFeedStorage(path)
    assert len(s3) == 3
    assert [s3.get(i) for i in range(3)] == [b"aa", b"bb", b"cc"]


def test_legacy_log_without_len_index(tmp_path):
    path = _mk(tmp_path, [b"x", b"y"])
    os.remove(path + ".len")
    s2 = FileFeedStorage(path)
    assert len(s2) == 2  # full scan fallback
    s2.append(b"z")  # append recreates the index
    assert os.path.exists(path + ".len")
    assert len(FileFeedStorage(path)) == 3
