"""Round-13 observability (ISSUE 9): the unified telemetry layer.

Pins, in order: registry exactness under concurrency (the per-thread
shards are also the fix for the unlocked ``stats[...] +=`` races the
old ad-hoc dicts carried), trace-ring wraparound, golden Chrome-trace
and Prometheus exporters, the migrated stats dicts' key/shape
compatibility, the Telemetry IPC query, HM_TRACE env activation, the
acceptance trace (spans from live + pipeline + net + storage in one
run), and the hot-path overhead regression (disabled spans are a
shared no-op; a registry counter bump stays micro-budget on the
config2 live-edit path)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from hypermerge_tpu import telemetry
from hypermerge_tpu.telemetry import trace as ttrace
from hypermerge_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture
def tracer():
    """Isolated tracing window: fresh ring, enabled, restored after."""
    was_on = ttrace.enabled()
    ttrace.reset()
    ttrace.enable()
    yield ttrace
    if not was_on:
        ttrace.disable()
    ttrace.reset()


# ---------------------------------------------------------------------------
# registry


def test_counter_concurrent_adds_exact():
    reg = MetricsRegistry()
    c = reg.counter("t.hammer")
    N, T = 20000, 8

    def worker():
        for _ in range(N):
            c.add(1)

    ts = [threading.Thread(target=worker) for _ in range(T)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # EXACT, not approximate: each thread owns its shard, merge on read
    assert c.value() == N * T


def test_float_counter_concurrent_adds_exact():
    """The t_resync_ms shape: float accumulation from many threads
    (the old dict += from reader threads could lose increments)."""
    reg = MetricsRegistry()
    c = reg.counter("t.ms")
    N, T = 5000, 6

    def worker():
        for _ in range(N):
            c.add(0.5)

    ts = [threading.Thread(target=worker) for _ in range(T)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value() == N * T * 0.5


def test_histogram_concurrent_observes_exact():
    reg = MetricsRegistry()
    h = reg.histogram("t.h", buckets=(1.0, 10.0))
    T, N = 6, 3000

    def worker(i):
        for j in range(N):
            h.observe((0.5, 5.0, 50.0)[(i + j) % 3])

    ts = [
        threading.Thread(target=worker, args=(i,)) for i in range(T)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    v = h.value()
    assert v["count"] == T * N
    assert sum(v["buckets"]) == T * N
    total = T * N // 3
    assert v["buckets"] == [total, total, total]


def test_registry_get_or_create_and_labels():
    reg = MetricsRegistry()
    a = reg.counter("x.c", inst="1")
    b = reg.counter("x.c", inst="1")
    other = reg.counter("x.c", inst="2")
    assert a is b and a is not other
    a.add(3)
    other.add(4)
    reg.gauge("x.g").set(7)
    snap = reg.snapshot()
    # aggregated across label sets, int-ness preserved
    assert snap["x.c"] == 7 and isinstance(snap["x.c"], int)
    assert snap["x.g"] == 7


def test_retire_folds_into_closed_aggregate():
    """Open/close cycles must not grow the registry a label set per
    lifecycle — retire() folds counters into inst="closed" while the
    process totals (snapshot) stay exact."""
    reg = MetricsRegistry()
    for i in range(5):
        c = reg.counter("live.ticks", inst=str(i))
        g = reg.gauge("live.live_docs", inst=str(i))
        c.add(10)
        g.set(3)
        reg.retire(c, g)
        reg.retire(c, g)  # idempotent: no double-fold
    assert reg.snapshot()["live.ticks"] == 50
    # one aggregate series survives, not five (+ no dead gauges)
    assert len(reg.series()) == 1


def test_engine_close_retires_series():
    from hypermerge_tpu import telemetry
    from hypermerge_tpu.repo import Repo

    repo = Repo(memory=True)
    eng = repo.back.live
    if eng is None:
        repo.close()
        pytest.skip("live engine off (HM_LIVE=0)")
    labeled = {
        (m.name, m.labels)
        for m in telemetry.REGISTRY.series()
        if m in set(eng._m.values())
    }
    assert labeled  # registered while open
    repo.close()
    live_series = set(eng._m.values())
    assert not any(
        m in live_series for m in telemetry.REGISTRY.series()
    )
    # the historical dict stays readable after close (handle-based)
    assert "ticks" in eng.stats


def test_reset_zeroes_in_place_keeping_handles():
    """reset() must not blind module-level cached handles (net.tcp.*,
    pipeline.* are created once at import): series zero in place and
    keep reporting."""
    reg = MetricsRegistry()
    c = reg.counter("x.c")
    g = reg.gauge("x.g")
    c.add(5)
    g.set(3)
    reg.reset()
    assert reg.snapshot() == {"x.c": 0, "x.g": 0}
    c.add(2)  # the cached handle is still live and visible
    assert reg.snapshot()["x.c"] == 2


def test_snapshot_rounds_floats():
    reg = MetricsRegistry()
    reg.counter("x.t").add(0.1)
    reg.counter("x.t").add(0.2)
    v = reg.snapshot()["x.t"]
    assert v == round(v, 6)


# ---------------------------------------------------------------------------
# trace ring


def test_trace_ring_wraparound():
    r = ttrace._Ring(16)
    for i in range(40):
        r.add(("X", f"s{i}", "", float(i), 1.0, 0, None))
    got = r.events()
    # the LAST 16 events, oldest first
    assert [e[1] for e in got] == [f"s{i}" for i in range(24, 40)]
    assert len(r) == 16


def test_span_begin_end_tags(tracer):
    sp = telemetry.begin("t.window", cat="net", a=1)
    time.sleep(0.001)
    sp.end(b=2)
    with telemetry.span("t.block", cat="live"):
        pass
    telemetry.instant("t.point", cat="storage", k="v")
    evs = telemetry.trace_events()
    by_name = {e[1]: e for e in evs}
    ph, name, cat, ts, dur, tid, args = by_name["t.window"]
    assert ph == "X" and cat == "net"
    assert dur >= 1000  # the 1ms sleep, in µs
    assert args == {"a": 1, "b": 2}  # begin tags merged with end tags
    assert by_name["t.block"][0] == "X"
    assert by_name["t.point"][0] == "i"
    assert by_name["t.point"][6] == {"k": "v"}


def test_disabled_span_is_shared_noop():
    was_on = ttrace.enabled()
    ttrace.disable()
    try:
        # no allocation: every disabled span() IS the same singleton
        assert telemetry.span("a") is telemetry.span("b")
        assert telemetry.begin("c") is telemetry.NOOP
        n0 = telemetry.event_count()
        with telemetry.span("d"):
            pass
        telemetry.instant("e")
        assert telemetry.event_count() == n0  # nothing recorded
        # and cheap: 100k disabled spans well under any hot-path budget
        t0 = time.perf_counter()
        for _ in range(100_000):
            with telemetry.span("f"):
                pass
        assert time.perf_counter() - t0 < 1.0
    finally:
        if was_on:
            ttrace.enable()


# ---------------------------------------------------------------------------
# golden exporters


def test_chrome_trace_golden(tracer, tmp_path):
    with telemetry.span("live.tick", cat="live", docs=3):
        pass
    telemetry.instant("net.resync", cat="net", ms=5)
    path = str(tmp_path / "t.json")
    telemetry.flush_trace(path)
    doc = json.load(open(path))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert {m["name"] for m in meta} >= {"process_name", "thread_name"}
    (x,) = [e for e in evs if e["ph"] == "X"]
    assert x["name"] == "live.tick" and x["cat"] == "live"
    assert x["args"] == {"docs": 3}
    assert {"ts", "dur", "pid", "tid"} <= set(x)
    (i,) = [e for e in evs if e["ph"] == "i"]
    assert i["name"] == "net.resync" and i["s"] == "t"


def test_prometheus_golden():
    reg = MetricsRegistry()
    reg.counter("live.ticks", inst="1").add(3)
    reg.gauge("live.live_docs").set(2)
    h = reg.histogram("live.tick_s", buckets=(0.01, 0.1))
    for v in (0.005, 0.05, 5.0):
        h.observe(v)
    from hypermerge_tpu.telemetry import prometheus_text

    assert prometheus_text(reg) == (
        "# TYPE hm_live_live_docs gauge\n"
        "hm_live_live_docs 2\n"
        "# TYPE hm_live_tick_s histogram\n"
        'hm_live_tick_s_bucket{le="0.01"} 1\n'
        'hm_live_tick_s_bucket{le="0.1"} 2\n'
        'hm_live_tick_s_bucket{le="+Inf"} 3\n'
        "hm_live_tick_s_sum 5.055\n"
        "hm_live_tick_s_count 3\n"
        "# TYPE hm_live_ticks counter\n"
        'hm_live_ticks{inst="1"} 3\n'
    )


# ---------------------------------------------------------------------------
# migrated stats dicts: shape compatibility + races closed


def test_live_engine_stats_keys_unchanged():
    from hypermerge_tpu.repo import Repo

    repo = Repo(memory=True)
    try:
        eng = repo.back.live
        if eng is None:
            pytest.skip("live engine off (HM_LIVE=0)")
        assert list(eng.stats) == [
            "adopted", "refused", "ticks", "tick_docs", "tick_changes",
            "inc_changes", "kernel_runs", "device_dispatches",
            "local_changes", "adopt_retries", "demoted", "readopted",
            "live_bytes", "live_docs",
            "t_live_append", "t_live_apply", "t_live_kernel",
            "t_live_decode", "t_live_diff",
            "t_adopt_pack", "t_adopt_kernel", "t_adopt_decode",
            "t_adopt_reach", "t_adopt_lock_free", "t_adopt_lock_held",
        ]
        # int counters stay ints (bench JSON bit-compatibility)
        assert isinstance(eng.stats["adopted"], int)
        assert isinstance(eng.stats["t_live_append"], float)
    finally:
        repo.close()


def test_replication_stats_shape_and_race_closed():
    from hypermerge_tpu.net.replication import ReplicationManager

    rm = ReplicationManager(feeds=None, on_discovery=lambda *a: None)
    try:
        assert set(rm.stats) == {
            "resyncs", "t_resync_ms", "antientropy_sweeps",
            # round 19: wire frame counters exposed for the fleet
            # bench's per-peer frame-amplification measurement
            "frames_tx", "frames_rx",
        }
        # the exact race the migration closes: t_resync_ms += from
        # many reader threads at once
        T, N = 8, 2000

        def worker():
            for _ in range(N):
                rm._m["t_resync_ms"].add(1.0)

        ts = [threading.Thread(target=worker) for _ in range(T)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert rm.stats["t_resync_ms"] == T * N
    finally:
        rm.close()


def test_supervisor_stats_shape():
    from hypermerge_tpu.net.resilience import SessionSupervisor

    sup = SessionSupervisor(dial=lambda a: None, deliver=lambda d, x: None)
    assert sup.stats == {"dials": 0, "reconnects": 0}
    sup.stop()


# ---------------------------------------------------------------------------
# the IPC/serve seam


def test_backend_answers_telemetry_query():
    from hypermerge_tpu.backend.repo_backend import RepoBackend

    from helpers import wait_until

    back = RepoBackend(memory=True)
    try:
        got = []
        back.subscribe(got.append)
        back.handle_query(7, {"type": "Telemetry"})
        wait_until(
            lambda: any(
                m.get("type") == "Reply" and m.get("queryId") == 7
                for m in got
            )
        )
        (reply,) = [m for m in got if m.get("type") == "Reply"]
        payload = reply["payload"]
        assert isinstance(payload["counters"], dict)
        assert "time" in payload and "tracing" in payload
        # JSON-serializable end to end (it rides the unix socket)
        json.dumps(payload)
    finally:
        back.close()


# ---------------------------------------------------------------------------
# HM_TRACE env activation (subprocess: import-time hook + atexit write)


def test_hm_trace_env_writes_file_at_exit(tmp_path):
    out = str(tmp_path / "trace.json")
    env = {
        **os.environ,
        "HM_TRACE": out,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
    }
    code = (
        "from hypermerge_tpu import telemetry\n"
        "assert telemetry.tracing_enabled()\n"
        "with telemetry.span('live.tick', cat='live'):\n"
        "    pass\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    doc = json.load(open(out))
    assert any(
        e.get("name") == "live.tick" and e.get("ph") == "X"
        for e in doc["traceEvents"]
    )


# ---------------------------------------------------------------------------
# acceptance: one run's trace carries live + pipeline + net + storage


def test_trace_spans_every_subsystem(tracer, tmp_path):
    """A bulk cold open + a TCP live-edit burst under tracing produces
    spans from the live, pipeline, net, and storage subsystems in one
    Perfetto-loadable file (ISSUE 9 acceptance)."""
    from hypermerge_tpu.net.tcp import TcpSwarm
    from hypermerge_tpu.ops.corpus import make_corpus
    from hypermerge_tpu.repo import Repo

    from helpers import wait_until

    path = str(tmp_path / "repo")
    urls = make_corpus(path, 16, 16)
    repo = Repo(path=path)
    repo.open_many(urls)
    repo.back.fetch_bulk_summaries()
    repo.close()

    ra, rb = Repo(memory=True), Repo(memory=True)
    sa, sb = TcpSwarm(), TcpSwarm()
    try:
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        u = ra.create({"edits": []})
        h = rb.open(u)
        for i in range(10):
            ra.change(u, lambda d, i=i: d["edits"].append(i))
        wait_until(
            lambda: (h.value() or {}).get("edits", [])[9:] == [9],
            timeout=30,
        )
    finally:
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()

    cats = {e[2] for e in telemetry.trace_events()}
    assert {"live", "pipeline", "net", "storage"} <= cats, cats
    out = str(tmp_path / "t.json")
    telemetry.flush_trace(out)
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    assert {e.get("cat") for e in evs if e.get("ph") == "X"} >= {
        "pipeline", "storage"
    }
    # every event carries the fields Perfetto requires
    for e in evs:
        assert {"ph", "name", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert "ts" in e and "dur" in e


# ---------------------------------------------------------------------------
# overhead regression (the config2 live-edit hot path budget)


def test_counter_overhead_config2_budget():
    """Registry on vs off on the live-edit hot path, bounded delta:
    one edit on the config2 path bumps ~10 counters (tick + append +
    apply + frame counters), so the per-add cost must stay micro-scale
    or telemetry would show up in config2_edits_per_s. Pin per-add
    under 2µs (min over trials — the scheduler can't make code FASTER)
    and under 30x a raw dict bump; at the bound, telemetry costs
    <20µs/edit, ~2% of config2's ~1ms/edit."""
    reg = MetricsRegistry()
    c = reg.counter("hot.path")
    d = {"hot.path": 0}
    N = 50_000

    def t_counter():
        add = c.add
        t0 = time.perf_counter()
        for _ in range(N):
            add(1)
        return time.perf_counter() - t0

    def t_dict():
        t0 = time.perf_counter()
        for _ in range(N):
            d["hot.path"] += 1
        return time.perf_counter() - t0

    counter_s = min(t_counter() for _ in range(5))
    dict_s = min(t_dict() for _ in range(5))
    assert counter_s / N < 2e-6, f"{counter_s / N * 1e9:.0f}ns/add"
    assert counter_s < max(dict_s * 30, N * 1e-6), (
        f"counter {counter_s:.4f}s vs dict {dict_s:.4f}s"
    )


def test_counter_contention_bounded():
    """Sharded adds must not serialize: 8 threads hammering ONE
    counter finish in wall time comparable to one thread's work (a
    lock-per-add implementation would blow this bound under the GIL's
    contention pathologies)."""
    reg = MetricsRegistry()
    c = reg.counter("hot.contended")
    T, N = 8, 20_000

    def worker():
        add = c.add
        for _ in range(N):
            add(1)

    ts = [threading.Thread(target=worker) for _ in range(T)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    assert c.value() == T * N
    assert wall < 5.0, f"contended adds took {wall:.2f}s"
