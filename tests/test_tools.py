"""The CLI tools and chat example must actually run (the reference's
tools/examples rotted against old APIs — SURVEY §1.7; ours are driven
in CI)."""

import json
import os
import subprocess
import sys
import time

from hypermerge_tpu.repo import Repo

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO_ROOT,
}


def _run(args, **kw):
    return subprocess.run(
        [sys.executable, *args],
        capture_output=True,
        text=True,
        timeout=120,
        env=ENV,
        cwd=REPO_ROOT,
        **kw,
    )


def test_ls_and_watch_once(tmp_path):
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"title": "doc one", "n": 1})
    repo.change(url, lambda d: d.__setitem__("n", 2))
    repo.close()

    out = _run(["tools/ls.py", path, "--audit"])
    assert out.returncode == 0, out.stderr
    assert url in out.stdout
    assert "integrity=OK" in out.stdout
    assert "residency=" in out.stdout  # read-serving column (ISSUE 11)

    out = _run(["tools/watch.py", path, url, "--once"])
    assert out.returncode == 0, out.stderr
    state = json.loads(out.stdout.strip().splitlines()[-1])
    assert state["doc"]["n"] == 2


def _line_reader(stream):
    """Background reader so a silent process can't block the test past
    its deadline (readline would otherwise hang forever)."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue()

    def pump():
        for line in stream:
            q.put(line)
        q.put(None)

    threading.Thread(target=pump, daemon=True).start()

    def next_line(timeout):
        import queue as _q

        try:
            return q.get(timeout=timeout)
        except _q.Empty:
            return None

    return next_line


def test_cat_cp_and_serve(tmp_path):
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"kind": "doc"})
    repo.close()

    # cp a file in, cat it back out
    src = tmp_path / "payload.bin"
    src.write_bytes(b"\x01\x02" * 5000)
    out = _run(["tools/cp.py", path, str(src)])
    assert out.returncode == 0, out.stderr
    file_url = out.stdout.strip().splitlines()[-1]
    assert file_url.startswith("hyperfile:/")
    out = _run(["tools/cat.py", path, file_url])
    assert out.returncode == 0, out.stderr
    assert "10000 bytes" in out.stderr
    cp_back = str(tmp_path / "back.bin")
    out = _run(["tools/cp.py", path, file_url, cp_back])
    assert out.returncode == 0, out.stderr
    assert open(cp_back, "rb").read() == b"\x01\x02" * 5000

    # cat a doc
    out = _run(["tools/cat.py", path, url])
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout.strip().splitlines()[-1])["kind"] == "doc"

    # serve + remote watch over TCP
    serve = subprocess.Popen(
        [sys.executable, "tools/serve.py", path, "--port", "0"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=ENV,
        cwd=REPO_ROOT,
    )
    try:
        line = ""
        deadline = time.time() + 60
        while time.time() < deadline and "serving" not in line:
            line = serve.stdout.readline()
        assert "serving" in line, "serve never announced"
        addr = line.rsplit(" on ", 1)[1].strip()
        out = _run([
            "tools/watch.py", str(tmp_path / "peer"), url,
            "--connect", addr, "--once",
        ])
        assert out.returncode == 0, out.stderr
        state = json.loads(out.stdout.strip().splitlines()[-1])
        assert state["doc"]["kind"] == "doc"
    finally:
        serve.kill()
        serve.wait(timeout=10)


def test_chat_example_end_to_end(tmp_path):
    """serve + join over real TCP; bob's message reaches alice."""
    serve = subprocess.Popen(
        [sys.executable, "examples/chat/chat.py", "serve", "--port", "0",
         "--name", "alice"],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=ENV,
        cwd=REPO_ROOT,
    )
    try:
        read_serve = _line_reader(serve.stdout)
        url = None
        addr = None
        deadline = time.time() + 60
        while time.time() < deadline and (url is None or addr is None):
            line = read_serve(timeout=1.0)
            if line is None:
                continue
            if line.startswith("channel: "):
                url = line.split(" ", 1)[1].strip()
            elif line.startswith("peers join with: "):
                addr = line.split(": ", 1)[1].split(" ")[0].strip()
        assert url and addr, "serve did not announce"

        join = subprocess.Popen(
            [sys.executable, "examples/chat/chat.py", "join", addr, url,
             "--name", "bob"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=ENV,
            cwd=REPO_ROOT,
        )
        try:
            join.stdin.write("hello from bob\n")
            join.stdin.flush()
            got = []
            deadline = time.time() + 60
            while time.time() < deadline:
                line = read_serve(timeout=1.0)
                if line is None:
                    continue
                got.append(line)
                if "hello from bob" in line:
                    break
            assert any("hello from bob" in l for l in got), got
        finally:
            join.stdin.close()
            join.wait(timeout=30)
    finally:
        serve.stdin.close()
        try:
            serve.wait(timeout=30)
        except subprocess.TimeoutExpired:
            serve.kill()


def test_simple_example_converges():
    """examples/simple mirrors the reference's two-repo watch demo
    (reference examples/simple/src/simple.ts)."""
    out = subprocess.run(
        [sys.executable, "examples/simple/simple.py"],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "converged: {'numbers': [1, 2, 3, 4, 5]" in out.stdout


def test_meta_tool_docs_and_files(tmp_path):
    """tools/meta.py surfaces repo.meta — actor list, clock, history
    for docs; size/mime for hyperfiles (reference tools/Meta.ts)."""
    from hypermerge_tpu.utils.ids import validate_doc_url

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"n": 0})
    repo.change(url, lambda d: d.__setitem__("n", 1))
    repo.change(url, lambda d: d.__setitem__("m", 2))
    import io
    import tempfile

    repo.start_file_server(tempfile.mktemp(suffix=".sock"))
    header = repo.files.write(
        io.BytesIO(b"\xab" * 4096), "application/x-blob"
    )
    file_url = header.url
    repo.close()

    out = _run(["tools/meta.py", path, url])
    assert out.returncode == 0, out.stderr
    meta = json.loads(out.stdout.strip().splitlines()[-1])
    assert meta["type"] == "Document"
    assert meta["history"] == 3
    doc_id = validate_doc_url(url)
    assert doc_id in meta["actors"]
    assert any(c.startswith(doc_id) for c in meta["clock"])

    out = _run(["tools/meta.py", path, file_url])
    assert out.returncode == 0, out.stderr
    fmeta = json.loads(out.stdout.strip().splitlines()[-1])
    assert fmeta["type"] == "File"
    assert fmeta["bytes"] == 4096
    assert fmeta["mimeType"] == "application/x-blob"

    # unknown (but well-formed) url: null + non-zero exit
    from hypermerge_tpu.utils import keys as keymod

    bogus = "hyperfile:/" + keymod.create().public_key
    out = _run(["tools/meta.py", path, bogus])
    assert out.returncode == 1
    assert out.stdout.strip().splitlines()[-1] == "null"


def test_meta_tool_unknown_doc_times_out_to_null(tmp_path):
    from hypermerge_tpu.utils import keys as keymod
    from hypermerge_tpu.utils.ids import to_doc_url

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    repo.create({"x": 1})
    repo.close()
    unknown = to_doc_url(keymod.create().public_key)
    out = _run(["tools/meta.py", path, unknown, "--timeout", "3"])
    assert out.returncode == 1
    assert out.stdout.strip().splitlines()[-1] == "null"


def test_scrub_cli_repairs_crashed_repo(tmp_path):
    from hypermerge_tpu.storage.feed import FileFeedStorage

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"n": 0})
    for i in range(4):
        repo.change(url, lambda d, i=i: d.__setitem__("n", i))
    if repo.back.live is not None:
        repo.back.live.flush_now()
    actor = next(
        iter(repo.back.docs[url.split("/")[-1]].clock)
    )
    repo.close()

    # crash damage: a torn feed tail + the crash marker
    feed_path = os.path.join(path, "feeds", actor[:2], actor)
    with open(feed_path, "ab") as fh:
        fh.write(b"\x40\x00\x00\x00torn")
    open(os.path.join(path, "repo.dirty"), "wb").close()

    out = _run(["tools/scrub.py", path, "--dry-run", "--json"])
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["bytes_truncated"] > 0, report
    # dry run: damage (and the crash marker) still present
    assert os.path.exists(os.path.join(path, "repo.dirty"))

    out = _run(["tools/scrub.py", path, "--audit", "--json"])
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["bytes_truncated"] > 0, report
    assert report["audit"]["not_ok"] == {}, report

    # repaired for real: reopen reads the full doc, audits clean
    out = _run(["tools/ls.py", path, "--audit"])
    assert out.returncode == 0, out.stderr
    assert "integrity=OK" in out.stdout
    assert "scrub=" in out.stdout


def test_ls_surfaces_recovery_status(tmp_path):
    from hypermerge_tpu.storage.feed import FileFeedStorage

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"n": 0})
    for i in range(5):
        repo.change(url, lambda d, i=i: d.__setitem__("n", i))
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.close()

    # unclean shutdown marker: the next open (ls itself) recovers
    open(os.path.join(path, "repo.dirty"), "wb").close()
    out = _run(["tools/ls.py", path])
    assert out.returncode == 0, out.stderr
    assert "crash recovery ran" in out.stdout
    assert "scrub=ok" in out.stdout or "scrub=recovered" in out.stdout


def test_top_over_ipc_seam(tmp_path):
    """tools/top.py's client polls the backend Telemetry query over
    the net/ipc.py unix socket and renders per-subsystem rates."""
    import importlib.util
    import threading

    from hypermerge_tpu.net.ipc import serve_backend

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    repo.create({"n": 1})
    repo.close()

    sock = str(tmp_path / "b.sock")
    t = threading.Thread(
        target=serve_backend,
        args=(sock,),
        kwargs=dict(repo_path=path, once=True),
        daemon=True,
    )
    t.start()
    for _ in range(200):
        if os.path.exists(sock):
            break
        time.sleep(0.05)
    spec = importlib.util.spec_from_file_location(
        "hm_top", os.path.join(REPO_ROOT, "tools", "top.py")
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    client = top.IpcTelemetry(sock)
    try:
        p1 = client.poll()
        p2 = client.poll()
        assert isinstance(p1["counters"], dict) and p1["counters"]
        assert p2["time"] >= p1["time"]
        table = top.format_rows(
            p1, p2, max(p2["time"] - p1["time"], 1e-3)
        )
        # the unix-socket chatter itself shows up as net counters
        assert "[net]" in table
        assert "net.tcp.frames_rx" in table
    finally:
        client.close()
    t.join(15)
    assert not t.is_alive()


def test_meta_dht_probe():
    """tools/meta.py --dht boots an ephemeral node, bootstraps off the
    fleet, and reports node id + bucket occupancy — the from-outside
    'is the DHT reachable' probe."""
    from hypermerge_tpu.net.discovery import DhtNode

    a = DhtNode()
    b = DhtNode(bootstrap=[a.address])
    try:
        b.bootstrap_now()
        out = _run([
            "tools/meta.py", "--dht",
            "--bootstrap", f"127.0.0.1:{a.address[1]}",
        ])
        assert out.returncode == 0, out.stderr
        probe = json.loads(out.stdout.strip())
        assert len(probe["node_id"]) == 40
        assert probe["nodes"] >= 1
        assert probe["buckets"]  # at least one occupied bucket
    finally:
        a.close()
        b.close()


def test_meta_dht_probe_unreachable_exits_nonzero():
    from hypermerge_tpu.net.discovery import DhtNode

    dead = DhtNode()
    port = dead.address[1]
    dead.close()
    out = subprocess.run(
        [
            sys.executable, "tools/meta.py", "--dht",
            "--bootstrap", f"127.0.0.1:{port}",
        ],
        capture_output=True,
        text=True,
        timeout=120,
        env={**ENV, "HM_DHT_RPC_TIMEOUT_S": "0.2"},
        cwd=REPO_ROOT,
    )
    assert out.returncode == 1
    assert json.loads(out.stdout.strip())["nodes"] == 0


def test_ipc_dht_daemon_and_ls_swarm_columns(tmp_path, monkeypatch):
    """A net/ipc.py daemon joined via --dht replicates with a fleet
    peer discovered through announce/lookup only, and tools/ls.py
    --sock renders the dht: header plus the peers=/announce= columns
    from the daemon's Telemetry payload."""
    import threading

    from hypermerge_tpu.net.discovery import DhtNode, DhtSwarm
    from hypermerge_tpu.net.ipc import serve_backend

    monkeypatch.setenv("HM_DHT_ANNOUNCE_S", "0.2")
    monkeypatch.setenv("HM_DHT_LOOKUP_S", "0.2")
    monkeypatch.setenv("HM_NET_PING_S", "0")
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"fleet": True})
    repo.close()

    boot = DhtNode()
    sock = str(tmp_path / "b.sock")
    t = threading.Thread(
        target=serve_backend,
        args=(sock,),
        kwargs=dict(
            repo_path=path, once=True, dht=True,
            dht_bootstrap=[f"127.0.0.1:{boot.address[1]}"],
        ),
        daemon=True,
    )
    t.start()
    for _ in range(200):
        if os.path.exists(sock):
            break
        time.sleep(0.05)

    peer = Repo(memory=True)
    sw = DhtSwarm(bootstrap=[boot.address])
    peer.set_swarm(sw)
    try:
        # pure-DHT discovery: the peer finds the daemon via lookup
        assert peer.open(url).value(timeout=60) is not None
        out = subprocess.run(
            [sys.executable, "tools/ls.py", path, "--sock", sock],
            capture_output=True,
            text=True,
            timeout=120,
            env={**ENV, "HM_RECOVER": "0"},
            cwd=REPO_ROOT,
        )
        assert out.returncode == 0, out.stderr
        assert "dht: node" in out.stdout
        assert "announce=yes" in out.stdout
        assert "peers=1" in out.stdout
    finally:
        peer.close()
        sw.destroy()
        boot.close()
        t.join(20)


def test_meta_stats_snapshot(tmp_path):
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    repo.create({"n": 1})
    repo.close()
    out = _run(["tools/meta.py", path, "--stats"])
    assert out.returncode == 0, out.stderr
    snap = json.loads(out.stdout.strip())
    # registry-sourced names, not per-object dict scrapes
    assert "storage.barriers" in snap
    assert any(k.startswith("live.") for k in snap)


def test_profile_trace_timeline(tmp_path):
    """scripts/profile_trace.py replays an HM_TRACE file into the
    busy-vs-wall timeline."""
    from hypermerge_tpu import telemetry
    from hypermerge_tpu.telemetry import trace as ttrace

    ttrace.reset()
    ttrace.enable()
    try:
        for _ in range(3):
            with telemetry.span("live.tick", cat="live"):
                time.sleep(0.002)
        with telemetry.span("pipeline.pack", cat="pipeline"):
            time.sleep(0.005)
        telemetry.instant("live.demote", cat="live")
        trace_path = str(tmp_path / "t.json")
        telemetry.flush_trace(trace_path)
    finally:
        ttrace.disable()
        ttrace.reset()

    out = _run(["scripts/profile_trace.py", trace_path, "--threads"])
    assert out.returncode == 0, out.stderr
    assert "live.tick" in out.stdout and "x3" in out.stdout
    assert "concurrency" in out.stdout
    out = _run(["scripts/profile_trace.py", trace_path, "--by", "cat"])
    assert "pipeline" in out.stdout


def test_serve_ipc_read_queries(tmp_path):
    """tools/serve.py --ipc answers Read queries through the serving
    tier and Telemetry queries with the residency block — one daemon
    replicates to peers AND serves point reads off HBM state."""
    import socket as socketmod

    from hypermerge_tpu import msgs
    from hypermerge_tpu.models import Text
    from hypermerge_tpu.net.tcp import TcpDuplex
    from hypermerge_tpu.utils.ids import validate_doc_url

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"title": "served"})
    repo.change(url, lambda d: d.__setitem__("t", Text("from-hbm")))
    repo.close()
    doc_id = validate_doc_url(url)

    sock_path = str(tmp_path / "serve.sock")
    serve = subprocess.Popen(
        [
            sys.executable, "tools/serve.py", path,
            "--port", "0", "--ipc", sock_path,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=ENV,
        cwd=REPO_ROOT,
    )
    try:
        next_line = _line_reader(serve.stdout)
        deadline = time.monotonic() + 60
        announced = False
        while time.monotonic() < deadline:
            line = next_line(timeout=1.0)
            if line and "serving" in line:
                announced = True
                break
        assert announced, "serve never announced"

        sock = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
        sock.connect(sock_path)
        duplex = TcpDuplex(sock, is_client=True)
        import threading as threadingmod

        replies = {}
        got = threadingmod.Event()

        def on_msg(msg):
            if isinstance(msg, dict) and msg.get("type") == "Reply":
                replies[msg["queryId"]] = msg.get("payload")
                got.set()

        duplex.on_message(on_msg)
        duplex.send(
            msgs.query_msg(
                1,
                msgs.read_query(
                    doc_id, {"kind": "text", "path": ["t"]}
                ),
            )
        )
        assert got.wait(30), "no Read reply"
        assert replies[1] == {"value": "from-hbm"}
        got.clear()
        duplex.send(msgs.query_msg(2, msgs.telemetry_query()))
        assert got.wait(30), "no Telemetry reply"
        tele = replies[2]
        assert "serve" in tele and doc_id in tele["serve"]["resident"]
        assert any(
            k.startswith("serve.") for k in tele["counters"]
        )
        duplex.close()

        # ls --sock lists the DAEMON's live residency (the in-process
        # column would be cold); HM_RECOVER=0 because the daemon holds
        # the dirty marker of its live session
        out = subprocess.run(
            [sys.executable, "tools/ls.py", path, "--sock", sock_path],
            capture_output=True,
            text=True,
            timeout=120,
            env={**ENV, "HM_RECOVER": "0"},
            cwd=REPO_ROOT,
        )
        assert out.returncode == 0, out.stderr
        assert "residency=resident(" in out.stdout
    finally:
        serve.kill()
        serve.wait(timeout=10)


def test_scrub_cli_surfaces_journal_state(tmp_path, monkeypatch):
    """The scrub CLI reports the group-commit journal: record/dirty
    counts, replay verdicts, and whether the generation stamp bounded
    the scan — and the dry run preserves the stamp byte-for-byte so
    the later real pass is STILL bounded."""
    monkeypatch.setenv("HM_FSYNC", "1")
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"n": 0})
    repo.change(url, lambda d: d.__setitem__("n", 7))
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.back._stores.flush_now()
    repo.back.durability.flush_now()
    del repo  # crash: marker + journal stay behind

    out = _run(["tools/scrub.py", path, "--dry-run", "--json"])
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    wal = report["wal"]
    assert wal["present"] == 1 and wal["session_match"] == 1, wal
    assert wal["bounded"] == 1 and wal["dirty_feeds"] >= 1, wal

    out = _run(["tools/scrub.py", path])
    assert out.returncode == 0, out.stderr
    assert "journal:" in out.stdout
    assert "scan bounded to the session ledger" in out.stdout


def test_ls_surfaces_wal_column(tmp_path, monkeypatch):
    """ls.py's wal= column: a crashed session's docs show their
    journal verdict (checkpointed/replayed); docs untouched by the
    crashed session show clean."""
    monkeypatch.setenv("HM_FSYNC", "1")
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url_touched = repo.create({"n": 0})
    url_clean = repo.create({"n": 1})
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.close()  # clean

    repo2 = Repo(path=path)
    repo2.change(url_touched, lambda d: d.__setitem__("n", 42))
    if repo2.back.live is not None:
        repo2.back.live.flush_now()
    repo2.back._stores.flush_now()
    repo2.back.durability.flush_now()
    del repo2  # crash

    out = _run(["tools/ls.py", path])
    assert out.returncode == 0, out.stderr
    lines = {
        line.split()[0]: line
        for line in out.stdout.splitlines()
        if line.startswith("hypermerge:/")
    }
    assert "wal=checkpointed" in lines[url_touched] or (
        "wal=replayed" in lines[url_touched]
    ), lines[url_touched]
    assert "wal=clean" in lines[url_clean], lines[url_clean]


def test_top_groups_wal_counters(tmp_path):
    """storage.wal.* counters render as their own [wal] rate group."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hm_top", os.path.join(REPO_ROOT, "tools", "top.py")
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    cur = {
        "counters": {
            "storage.wal.appends": 100,
            "storage.wal.fsyncs": 4,
            "storage.wal.bytes": 12800,
            "storage.fsyncs": 9,
        }
    }
    prev = {
        "counters": {
            "storage.wal.appends": 50,
            "storage.wal.fsyncs": 2,
            "storage.wal.bytes": 6400,
            "storage.fsyncs": 9,
        }
    }
    table = top.format_rows(prev, cur, 1.0)
    assert "[wal]" in table
    assert "storage.wal.appends" in table
    assert "(+50.0/s)" in table
    # the non-journal storage counter stays in [storage]
    assert "[storage]" in table


def test_top_service_group(tmp_path):
    """The [service] group renders the overload controller's report
    block: ladder state line, counter rates, per-tenant quota table —
    and claims the service.* counters away from auto-grouping."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "hm_top", os.path.join(REPO_ROOT, "tools", "top.py")
    )
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    cur = {
        "counters": {
            "service.shed_reads": 120,
            "service.brownout_reads": 30,
            "service.transitions": 3,
            "storage.fsyncs": 9,
        },
        "service": {
            "state": 2,
            "state_name": "shed",
            "pressure": 1.42,
            "ack_stretch_ms": 25.0,
            "transitions": 3,
            "shed_reads": 120,
            "brownout_reads": 30,
            "deferred_installs": 7,
            "tenants": {
                "conn3": {
                    "admitted": 50,
                    "refused": 120,
                    "quota_occupancy": 0.97,
                },
            },
        },
    }
    prev = {"counters": {"service.shed_reads": 20}}
    table = top.format_rows(prev, cur, 1.0)
    assert "[service]" in table
    assert "state shed" in table
    assert "pressure 1.42" in table
    assert "ack_stretch 25.0ms" in table
    assert "service.shed_reads" in table
    assert "(+100.0/s)" in table
    assert "tenant conn3" in table
    assert "quota 0.97" in table
    # exactly ONE [service] header: the counters don't ALSO
    # auto-group
    assert table.count("[service]") == 1


def test_ls_service_status_line(tmp_path):
    """tools/ls.py prints the service: header off the Telemetry
    payload when the backend runs the overload controller (the
    HM_SERVICE=1 default)."""
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    repo.create({"n": 1})
    repo.close()
    out = _run(["tools/ls.py", path])
    assert out.returncode == 0, out.stderr
    assert "service: healthy pressure=" in out.stdout
    assert "tenants=0" in out.stdout
