"""Corpus slab (storage/slab.py): one file of framed sidecar segments.

Pins the properties the cold-open IO path leans on: byte-identical
loads vs the per-feed layout, O(1) file opens, lazy migration of legacy
`.cols2` sidecars, torn-tail healing on both the slab and its index,
tombstones on destroy, and compaction reclaiming superseded bytes."""

import os
import random

import numpy as np
import pytest

from helpers import Site, plainify, random_mutation
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.storage.colcache import (
    FeedColumnCache,
    SlabColumnStorage,
    file_column_storage_fn,
)
from hypermerge_tpu.storage.slab import (
    KIND_IMAGE,
    CorpusSlab,
)
from hypermerge_tpu.utils.ids import validate_doc_url

INF = float("inf")


def _history(seed, n_mut=15):
    r = random.Random(seed)
    site = Site("actor00")
    for _ in range(n_mut):
        random_mutation(site, r)
    return list(site.opset.history)


def _fill(tmp_path, names=("feedA", "feedB"), seed=1):
    fn = file_column_storage_fn(str(tmp_path))
    want = {}
    for i, name in enumerate(names):
        cc = FeedColumnCache(fn(name), writer="actor00")
        for c in _history(seed + i):
            cc.append_change(c)
        want[name] = cc.columns().ensure_rows().copy()
        cc.close()
    if fn.slab is not None:
        fn.slab.close()
    return want


def test_slab_prefetch_is_advisory_and_safe(tmp_path):
    """prefetch() (the pipeline io stage's read-ahead hint) must be a
    pure no-op semantically: unknown names, empty slabs, and platforms
    without madvise all pass through; reads after a hint are
    byte-identical."""
    want = _fill(tmp_path)
    fn = file_column_storage_fn(str(tmp_path))
    slab = fn.slab
    assert slab is not None
    slab.prefetch(list(want) + ["no-such-feed"])
    for name, rows in want.items():
        cc = FeedColumnCache(fn(name), writer="actor00")
        assert np.array_equal(cc.columns().ensure_rows(), rows)
        cc.close()
    slab.close()
    # empty slab: nothing mapped, still fine
    empty = CorpusSlab(str(tmp_path / "none" / "cols.slab"))
    empty.prefetch(["whatever"])
    empty.close()


def test_slab_roundtrip_and_single_file(tmp_path):
    want = _fill(tmp_path)
    assert os.path.exists(tmp_path / "cols.slab")
    assert not list(tmp_path.glob("*/*.cols2"))
    fn = file_column_storage_fn(str(tmp_path))
    for name, rows in want.items():
        cc = FeedColumnCache(fn(name), writer="actor00")
        assert np.array_equal(cc.columns().ensure_rows(), rows)
        cc.close()
    fn.slab.close()


def test_slab_checkpoint_load_is_plane_backed(tmp_path):
    """Compacted feeds load as planes with plane_meta (what both the
    numpy and native bulk packs consume)."""
    fn = file_column_storage_fn(str(tmp_path))
    cc = FeedColumnCache(fn("feedX"), writer="actor00")
    for c in _history(7):
        cc.append_change(c)
    cc.compact()
    cc.close()
    fn.slab.close()

    fn2 = file_column_storage_fn(str(tmp_path))
    cc2 = FeedColumnCache(fn2("feedX"), writer="actor00")
    fc = cc2.columns()
    assert fc.planes is not None
    assert fc.plane_meta is not None
    cc2.close()
    fn2.slab.close()


def test_legacy_cols2_migrates_on_first_read(tmp_path, monkeypatch):
    """A per-feed `.cols2` sidecar written by an older version folds
    into the slab on first read and the legacy file is removed."""
    monkeypatch.setenv("HM_SLAB", "0")
    want = _fill(tmp_path, names=("feedL",), seed=3)["feedL"]
    legacy = tmp_path / "fe" / "feedL.cols2"
    assert legacy.exists()

    monkeypatch.setenv("HM_SLAB", "1")
    fn = file_column_storage_fn(str(tmp_path))
    storage = fn("feedL")
    assert isinstance(storage, SlabColumnStorage)
    cc = FeedColumnCache(storage, writer="actor00")
    assert np.array_equal(cc.columns().ensure_rows(), want)
    cc.close()
    assert not legacy.exists(), "legacy sidecar not migrated"
    assert fn.slab.feed_live("feedL")
    fn.slab.close()

    # second open: slab-only
    fn2 = file_column_storage_fn(str(tmp_path))
    cc2 = FeedColumnCache(fn2("feedL"), writer="actor00")
    assert np.array_equal(cc2.columns().ensure_rows(), want)
    cc2.close()
    fn2.slab.close()


def test_torn_slab_tail_healed(tmp_path):
    want = _fill(tmp_path)
    p = tmp_path / "cols.slab"
    with open(p, "ab") as fh:
        fh.write(b"\x01\x00\x04torn-segment-header-without-payload")
    # index is now BEHIND the garbage; loads must ignore the torn tail
    fn = file_column_storage_fn(str(tmp_path))
    for name, rows in want.items():
        cc = FeedColumnCache(fn(name), writer="actor00")
        assert np.array_equal(cc.columns().ensure_rows(), rows)
        cc.close()
    # and the next append lands cleanly over it
    cc = FeedColumnCache(fn("feedC"), writer="actor00")
    for c in _history(9, n_mut=4):
        cc.append_change(c)
    got = cc.columns().ensure_rows().copy()
    cc.close()
    fn.slab.close()
    fn2 = file_column_storage_fn(str(tmp_path))
    cc2 = FeedColumnCache(fn2("feedC"), writer="actor00")
    assert np.array_equal(cc2.columns().ensure_rows(), got)
    cc2.close()
    fn2.slab.close()


def test_missing_or_torn_index_rebuilds(tmp_path):
    want = _fill(tmp_path)
    # interleave: a record for feedA lands AFTER feedB's image
    fni = file_column_storage_fn(str(tmp_path))
    cci = FeedColumnCache(fni("feedA"), writer="actor00")
    for c in _history(8, n_mut=3):
        cci.append_change(c)
    want["feedA"] = cci.columns().ensure_rows().copy()
    cci.close()
    fni.slab.close()

    os.remove(tmp_path / "cols.slab.idx")
    fn = file_column_storage_fn(str(tmp_path))
    for name, rows in want.items():
        cc = FeedColumnCache(fn(name), writer="actor00")
        assert np.array_equal(cc.columns().ensure_rows(), rows)
        cc.close()
    fn.slab.close()
    assert os.path.exists(tmp_path / "cols.slab.idx")  # rebuilt
    # ...and the rebuild is offset-ordered: the next open must accept it
    # (a feed-grouped dump would fail the monotonic check and force a
    # full slab scan on EVERY open)
    probe = CorpusSlab(str(tmp_path / "cols.slab"))
    entries, usable, torn_at = probe._read_index(
        os.path.getsize(tmp_path / "cols.slab")
    )
    assert usable and entries, "rebuilt index rejected on reopen"
    assert torn_at is None
    probe.close()

    # torn index tail: truncate mid-entry
    raw = (tmp_path / "cols.slab.idx").read_bytes()
    (tmp_path / "cols.slab.idx").write_bytes(raw[: len(raw) - 7])
    fn2 = file_column_storage_fn(str(tmp_path))
    for name, rows in want.items():
        cc = FeedColumnCache(fn2(name), writer="actor00")
        assert np.array_equal(cc.columns().ensure_rows(), rows)
        cc.close()
    fn2.slab.close()


def test_index_repairs_forward_after_lost_entry(tmp_path):
    """A crash between the slab append and the index append leaves the
    index one entry short: open() must recover the segment by scanning
    forward from the last indexed extent."""
    want = _fill(tmp_path, names=("feedA",), seed=5)["feedA"]
    slab = CorpusSlab(str(tmp_path / "cols.slab"))
    idx_before = (tmp_path / "cols.slab.idx").read_bytes()
    slab.append(KIND_IMAGE, "feedZ", b"HMc3" + b"\x00" * 16)  # bogus-ish
    slab.close()
    (tmp_path / "cols.slab.idx").write_bytes(idx_before)

    slab2 = CorpusSlab(str(tmp_path / "cols.slab"))
    assert slab2.feed_live("feedZ"), "unindexed segment not recovered"
    assert slab2.feed_live("feedA")
    slab2.close()
    # feedA still loads
    fn = file_column_storage_fn(str(tmp_path))
    cc = FeedColumnCache(fn("feedA"), writer="actor00")
    assert np.array_equal(cc.columns().ensure_rows(), want)
    cc.close()
    fn.slab.close()


def test_index_repair_truncates_torn_fragment_first(tmp_path):
    """Crash model: the slab append landed, the index append tore
    mid-entry. Repair-forward must TRUNCATE the torn fragment before
    appending the recovered entries — otherwise every later open parses
    the fragment as a bogus entry, fails the monotonic check, and
    rescans the whole slab forever."""
    want = _fill(tmp_path, names=("feedA",), seed=5)["feedA"]
    slab = CorpusSlab(str(tmp_path / "cols.slab"))
    idx_before = (tmp_path / "cols.slab.idx").read_bytes()
    slab.append(KIND_IMAGE, "feedZ", b"HMc3" + b"\x00" * 16)
    slab.close()
    # torn idx: the old entries plus HALF of feedZ's entry bytes
    idx_after = (tmp_path / "cols.slab.idx").read_bytes()
    frag = idx_after[len(idx_before) : len(idx_before) + 9]
    (tmp_path / "cols.slab.idx").write_bytes(idx_before + frag)

    slab2 = CorpusSlab(str(tmp_path / "cols.slab"))
    assert slab2.feed_live("feedZ"), "unindexed segment not recovered"
    assert slab2.feed_live("feedA")
    slab2.close()

    # the healed index must parse CLEANLY on the next open — all
    # entries usable, no torn fragment, no full-slab rescan
    slab3 = CorpusSlab(str(tmp_path / "cols.slab"))
    entries, usable, torn_at = slab3._read_index(
        os.path.getsize(tmp_path / "cols.slab")
    )
    assert usable and torn_at is None
    assert {name for _k, name, _o, _l in entries} == {"feedA", "feedZ"}
    assert slab3.feed_live("feedZ") and slab3.feed_live("feedA")
    slab3.close()


def test_tombstone_and_compaction_reclaim(tmp_path, monkeypatch):
    monkeypatch.setenv("HM_SLAB_SLACK", "0.01")
    fn = file_column_storage_fn(str(tmp_path))
    history = _history(11, n_mut=40)
    cc = FeedColumnCache(fn("feedA"), writer="actor00")
    for c in history:
        cc.append_change(c)
    for _ in range(4):  # superseded images pile up
        cc.compact()
    want = cc.columns().ensure_rows().copy()
    cc.close()
    cc2 = FeedColumnCache(fn("feedB"), writer="actor00")
    for c in _history(12, n_mut=20):
        cc2.append_change(c)
    cc2.destroy()  # tombstoned
    size_before = os.path.getsize(tmp_path / "cols.slab")
    fn.slab.close()  # compacts: dead images + tombstoned feed drop
    size_after = os.path.getsize(tmp_path / "cols.slab")
    assert size_after < size_before

    fn2 = file_column_storage_fn(str(tmp_path))
    assert not fn2.slab.feed_live("feedB")
    cc3 = FeedColumnCache(fn2("feedA"), writer="actor00")
    assert np.array_equal(cc3.columns().ensure_rows(), want)
    cc3.close()
    fn2.slab.close()


def test_repo_end_to_end_uses_slab(tmp_path):
    """Interactive writes + reopen + bulk load, all through the slab."""
    path = str(tmp_path)
    repo = Repo(path=path)
    urls = [repo.create({"i": i}) for i in range(4)]
    for u in urls:
        repo.change(u, lambda d: d.__setitem__("y", 1))
    want = {u: plainify(repo.doc(u)) for u in urls}
    repo.close()
    assert os.path.exists(os.path.join(path, "feeds", "cols.slab"))
    assert not [
        f
        for _r, _d, fs in os.walk(os.path.join(path, "feeds"))
        for f in fs
        if f.endswith(".cols2")
    ]

    repo2 = Repo(path=path)
    ids = [validate_doc_url(u) for u in urls]
    repo2.back.load_documents_bulk(ids)
    for u in urls:
        assert plainify(repo2.doc(u)) == want[u]
    repo2.close()


def test_slab_disabled_fallback(tmp_path, monkeypatch):
    """HM_SLAB=0 restores the per-feed `.cols2` layout end to end."""
    monkeypatch.setenv("HM_SLAB", "0")
    path = str(tmp_path)
    repo = Repo(path=path)
    url = repo.create({"x": 1})
    want = plainify(repo.doc(url))
    repo.close()
    assert not os.path.exists(os.path.join(path, "feeds", "cols.slab"))
    repo2 = Repo(path=path)
    assert plainify(repo2.doc(url)) == want
    repo2.close()
