"""Fleet-scale discovery suite: the Kademlia-lite DHT (k-bucket
eviction, iterative lookup convergence, announce TTL expiry, bootstrap
churn), the DhtSwarm filling the Swarm seam (repos converge with NO
explicit connect() anywhere), and the bounded gossip relay (20 peers,
HM_GOSSIP_FANOUT=4: per-peer frame counts stay O(fanout) while every
peer still converges through relay hops + the anti-entropy sweep).

Runs fully instrumented: the lockdep + racedep module fixtures verify
the new net.dht*/net.gossip lock classes and guard-manifest rows
against real churn with zero exemptions."""

import json
import os
import time

import pytest

from hypermerge_tpu.net.discovery import (
    DhtNode,
    DhtSwarm,
    GossipSampler,
    RecordStore,
    RoutingTable,
    key_id,
    make_record,
    verify_record,
)
from hypermerge_tpu.net.discovery.dht import (
    Contact,
    _id_hex,
    make_seed_record,
    verify_seed_record,
)
from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
from hypermerge_tpu.net.swarm import JoinOptions, LoopbackHub, LoopbackSwarm
from hypermerge_tpu.repo import Repo

from helpers import wait_until
from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite

_lockdep_suite = lockdep_suite()
_racedep_suite = racedep_suite()

SEED = b"\x07" * 32


@pytest.fixture
def fast_dht(monkeypatch):
    """Test-speed periods: sub-second announce/lookup refresh, fast
    redial, no keepalive thread storm."""
    monkeypatch.setenv("HM_DHT_ANNOUNCE_S", "0.2")
    monkeypatch.setenv("HM_DHT_LOOKUP_S", "0.2")
    monkeypatch.setenv("HM_REDIAL_BASE_MS", "30")
    monkeypatch.setenv("HM_REDIAL_MAX_S", "0.5")
    monkeypatch.setenv("HM_NET_PING_S", "0")


# ---------------------------------------------------------------------------
# records


class TestRecords:
    def test_sign_verify_roundtrip(self):
        rec = make_record("ab" * 20, "10.0.0.1", 4242, SEED, ttl=60)
        assert verify_record(rec)

    def test_tampered_record_rejected(self):
        rec = make_record("ab" * 20, "10.0.0.1", 4242, SEED, ttl=60)
        evil = dict(rec, port=6666)  # redirect the dial target
        assert not verify_record(evil)
        evil2 = dict(rec, sig=rec["sig"][:-4] + "AAA=")
        assert not verify_record(evil2)

    def test_ttl_expiry(self):
        rec = make_record("ab" * 20, "10.0.0.1", 4242, SEED, ttl=5)
        assert verify_record(rec, now=rec["ts"] + 4)
        assert not verify_record(rec, now=rec["ts"] + 6)

    def test_future_stamp_rejected(self):
        rec = make_record("ab" * 20, "10.0.0.1", 4242, SEED, ttl=60)
        assert not verify_record(rec, now=rec["ts"] - 120)

    def test_store_expires_and_freshest_wins(self):
        store = RecordStore()
        key = "cd" * 20
        old = make_record(key, "10.0.0.1", 1111, SEED, ttl=60)
        time.sleep(0.01)
        new = make_record(key, "10.0.0.1", 2222, SEED, ttl=60)
        assert store.put(new) and store.put(old)
        got = store.get(key)  # same announcer pk: freshest ts wins
        assert [r["port"] for r in got] == [2222]
        # an expired record vanishes from reads (lazy expiry)
        short = make_record(key, "10.0.0.1", 3333, os.urandom(32),
                            ttl=0.05)
        assert store.put(short)
        assert len(store.get(key)) == 2
        time.sleep(0.08)
        assert [r["port"] for r in store.get(key)] == [2222]

    def test_store_rejects_invalid(self):
        store = RecordStore()
        assert not store.put({"key": "junk"})
        assert not store.put(None)
        assert store.size() == 0

    def test_seed_record_roundtrip(self):
        rec = make_seed_record("ab" * 20, "doc-xyz", SEED, ttl=60)
        assert verify_seed_record(rec)

    def test_seed_record_tamper_rejected(self):
        rec = make_seed_record("ab" * 20, "doc-xyz", SEED, ttl=60)
        # redirect the replication ask to a different doc
        assert not verify_seed_record(dict(rec, doc="doc-evil"))
        assert not verify_seed_record(dict(rec, key="cd" * 20))
        assert not verify_seed_record(
            dict(rec, sig=rec["sig"][:-4] + "AAA=")
        )

    def test_seed_record_ttl_expiry(self):
        rec = make_seed_record("ab" * 20, "doc-xyz", SEED, ttl=5)
        assert verify_seed_record(rec, now=rec["ts"] + 4)
        assert not verify_seed_record(rec, now=rec["ts"] + 6)


# ---------------------------------------------------------------------------
# k-buckets


def _contact(i):
    return Contact(i, ("127.0.0.1", 10000 + (i % 5000)))


class TestRoutingTable:
    def test_insert_update_and_closest(self):
        t = RoutingTable(self_id=0, k=4)
        for i in (0b1000, 0b1001, 0b1010):
            assert t.observe(i, ("127.0.0.1", 9000 + i)) is None
        assert t.size() == 3
        # re-observe refreshes the address in place, no duplicate
        assert t.observe(0b1000, ("127.0.0.1", 7777)) is None
        assert t.size() == 3
        close = t.closest(0b1001, 2)
        assert close[0].id == 0b1001
        assert {c.id for c in close} == {0b1001, 0b1000}
        # the refreshed address stuck
        assert [
            c.addr for c in t.closest(0b1000, 1)
        ] == [("127.0.0.1", 7777)]

    def test_full_bucket_returns_lru_not_evicts(self):
        """Kademlia's uptime rule: a full bucket NEVER evicts on
        sight — observe returns the LRU for a liveness probe and parks
        the newcomer in the replacement cache."""
        t = RoutingTable(self_id=0, k=3)
        # ids 8..15 share bucket index 3
        for i in (8, 9, 10):
            assert t.observe(i, ("127.0.0.1", 9000 + i)) is None
        lru = t.observe(11, ("127.0.0.1", 9011))
        assert lru is not None and lru.id == 8  # oldest sighting
        assert {c.id for c in t.closest(8)} == {8, 9, 10}  # unchanged

    def test_evict_promotes_replacement(self):
        t = RoutingTable(self_id=0, k=3)
        for i in (8, 9, 10):
            t.observe(i, ("127.0.0.1", 9000 + i))
        lru = t.observe(11, ("127.0.0.1", 9011))
        t.evict(lru)  # the probe timed out: newcomer takes the slot
        assert {c.id for c in t.closest(8)} == {9, 10, 11}

    def test_refresh_keeps_lru_newcomer_stays_parked(self):
        t = RoutingTable(self_id=0, k=3)
        for i in (8, 9, 10):
            t.observe(i, ("127.0.0.1", 9000 + i))
        lru = t.observe(11, ("127.0.0.1", 9011))
        t.refresh(lru)  # the probe answered: long-lived node wins
        assert {c.id for c in t.closest(8)} == {8, 9, 10}
        # and 8 moved to MRU: the next full-bucket probe targets 9
        nxt = t.observe(12, ("127.0.0.1", 9012))
        assert nxt.id == 9

    def test_replacement_cache_bounded_freshest_promoted(self):
        t = RoutingTable(self_id=0, k=2)
        t.observe(8, ("127.0.0.1", 9008))
        t.observe(9, ("127.0.0.1", 9009))
        probes = [t.observe(i, ("127.0.0.1", 9000 + i))
                  for i in (10, 11, 12)]
        # ONE liveness probe per bucket at a time (every sighting from
        # a non-resident would otherwise fire a ping — a storm at
        # fleet scale): the first full-bucket observe returns the LRU,
        # the rest just park in the replacement cache
        assert probes[0] is not None and probes[0].id == 8
        assert probes[1] is None and probes[2] is None
        t.evict(probes[0])
        # the FRESHEST parked newcomer (12) got the slot
        assert {c.id for c in t.closest(8)} == {9, 12}
        # the probe latch cleared: the next full-bucket observe probes
        assert t.observe(13, ("127.0.0.1", 9013)) is not None

    def test_self_never_bucketed(self):
        t = RoutingTable(self_id=42, k=4)
        assert t.observe(42, ("127.0.0.1", 9000)) is None
        assert t.size() == 0

    def test_occupancy(self):
        t = RoutingTable(self_id=0, k=4)
        t.observe(1, ("127.0.0.1", 9001))   # bucket 0
        t.observe(8, ("127.0.0.1", 9008))   # bucket 3
        t.observe(9, ("127.0.0.1", 9009))   # bucket 3
        assert t.occupancy() == {0: 1, 3: 2}


# ---------------------------------------------------------------------------
# nodes: RPC, iterative walks, bootstrap


def _mesh(n, k=None):
    """n nodes all bootstrapped off node 0."""
    nodes = [DhtNode(k=k)]
    for _ in range(n - 1):
        nodes.append(DhtNode(bootstrap=[nodes[0].address], k=k))
    for node in nodes[1:]:
        node.bootstrap_now()
    return nodes


class TestDhtNode:
    def test_ping_populates_both_tables(self):
        a = DhtNode()
        b = DhtNode(bootstrap=[a.address])
        try:
            b.bootstrap_now()
            assert b.table.size() == 1
            wait_until(lambda: a.table.size() == 1)
        finally:
            a.close()
            b.close()

    def test_iterative_lookup_converges(self):
        """An announcer and a looker-up that share only the bootstrap
        node find each other through the iterative walk, and the walk
        counts hops."""
        from hypermerge_tpu import telemetry

        nodes = _mesh(10, k=4)  # small k: forces multi-hop routing
        try:
            key = _id_hex(key_id("some-shared-doc"))
            nodes[3].announce(key, "127.0.0.1", 7333)
            wait_until(
                lambda: any(
                    n.records.get(key) for n in nodes if n is not nodes[3]
                )
            )
            before = telemetry.snapshot().get("dht.lookup_hops", 0)
            found = nodes[9].lookup(key)
            assert [r["port"] for r in found] == [7333]
            assert telemetry.snapshot()["dht.lookup_hops"] > before
        finally:
            for n in nodes:
                n.close()

    def test_multiple_announcers_all_found(self):
        nodes = _mesh(8)
        try:
            key = _id_hex(key_id("popular-doc"))
            for i in (1, 2, 3):
                nodes[i].announce(key, "127.0.0.1", 7000 + i)
            found = nodes[7].lookup(key)
            assert {r["port"] for r in found} == {7001, 7002, 7003}
        finally:
            for n in nodes:
                n.close()

    def test_announce_ttl_expires_fleet_wide(self):
        nodes = _mesh(4)
        try:
            key = _id_hex(key_id("short-lived"))
            nodes[1].announce(key, "127.0.0.1", 7001, ttl=0.3)
            assert [
                r["port"] for r in nodes[3].lookup(key)
            ] == [7001]
            time.sleep(0.4)
            assert nodes[3].lookup(key) == []
        finally:
            for n in nodes:
                n.close()

    def test_bootstrap_churn(self, monkeypatch):
        """A dead bootstrap entry is tolerated (the walk rides the
        live one), and a node that missed its bootstrap window retries
        until the fleet answers."""
        monkeypatch.setenv("HM_DHT_RPC_TIMEOUT_S", "0.2")
        a = DhtNode()
        b = DhtNode(bootstrap=[a.address])
        b.bootstrap_now()
        dead = DhtNode()
        dead_addr = dead.address
        dead.close()
        # dead entry FIRST in the list: must not mask the live one
        c = DhtNode(bootstrap=[dead_addr, b.address])
        try:
            assert c.bootstrap_now() >= 1
            key = _id_hex(key_id("post-churn"))
            a.announce(key, "127.0.0.1", 7100)
            wait_until(lambda: c.lookup(key))
        finally:
            for n in (a, b, c):
                n.close()

    def test_bootstrap_all_dead_returns_zero_then_recovers(
        self, monkeypatch
    ):
        monkeypatch.setenv("HM_DHT_RPC_TIMEOUT_S", "0.2")
        a = DhtNode()
        addr = a.address
        a.close()
        late = DhtNode(bootstrap=[addr])
        try:
            assert late.bootstrap_now() == 0
            # the bootstrap node comes back on the same port: the next
            # retry (DhtSwarm re-runs it every maintenance pass while
            # the table is empty) adopts it
            revived = DhtNode(port=addr[1])
            try:
                assert late.bootstrap_now() == 1
            finally:
                revived.close()
        finally:
            late.close()

    def test_closed_node_fails_fast(self):
        a = DhtNode()
        a.close()
        t0 = time.monotonic()
        assert a.lookup(_id_hex(key_id("x"))) == []
        assert time.monotonic() - t0 < 1.0  # no timeout-per-round wait


# ---------------------------------------------------------------------------
# announce signing cache + push seeding (O(1) steady-state gossip)


class TestSignCache:
    def _counting_sign(self, monkeypatch):
        from hypermerge_tpu.utils import crypto

        calls = []
        real = crypto.sign
        monkeypatch.setattr(
            crypto, "sign",
            lambda payload, seed: calls.append(1) or real(payload, seed),
        )
        return calls

    def test_one_sign_per_half_ttl_window(self, monkeypatch):
        """The steady-state refresher's signature bill: an unchanged
        {key,host,port,ttl} re-announce inside the first half of the
        TTL window reuses the cached record — exactly one Ed25519 sign
        per window, the rest count dht.sign_cache_hits."""
        from hypermerge_tpu import telemetry

        calls = self._counting_sign(monkeypatch)
        node = DhtNode()
        try:
            key = _id_hex(key_id("sign-cache-doc"))
            before = telemetry.snapshot().get("dht.sign_cache_hits", 0)
            node.announce(key, "127.0.0.1", 7001, ttl=60)
            assert len(calls) == 1
            node.announce(key, "127.0.0.1", 7001, ttl=60)
            node.announce(key, "127.0.0.1", 7001, ttl=60)
            assert len(calls) == 1
            got = telemetry.snapshot()["dht.sign_cache_hits"] - before
            assert got == 2
            # a changed endpoint is a different record: re-sign
            node.announce(key, "127.0.0.1", 7002, ttl=60)
            assert len(calls) == 2
        finally:
            node.close()

    def test_resigns_past_half_window(self, monkeypatch):
        """The second half of the TTL window re-signs so the record
        never expires out from under its refresher."""
        calls = self._counting_sign(monkeypatch)
        node = DhtNode()
        try:
            key = _id_hex(key_id("short-ttl-doc"))
            node.announce(key, "127.0.0.1", 7003, ttl=0.12)
            assert len(calls) == 1
            time.sleep(0.08)  # past ttl/2
            node.announce(key, "127.0.0.1", 7003, ttl=0.12)
            assert len(calls) == 2
        finally:
            node.close()

    def test_identity_change_invalidates_cache(self, monkeypatch):
        """set_announce_seed drops cached records — they carry the old
        key's signature and would verify against the wrong identity."""
        calls = self._counting_sign(monkeypatch)
        node = DhtNode()
        try:
            key = _id_hex(key_id("rekeyed-doc"))
            node.announce(key, "127.0.0.1", 7004, ttl=60)
            assert len(calls) == 1
            node.set_announce_seed(os.urandom(32))
            node.announce(key, "127.0.0.1", 7004, ttl=60)
            assert len(calls) == 2
        finally:
            node.close()


class TestPushSeed:
    def test_seed_fires_hook_once_per_doc(self):
        """announce(seed_doc=...) rides the same k-closest walk: every
        receiver's hook fires exactly once per doc — a cached refresh
        re-sends the record but the _seeded dedup never re-opens."""
        from hypermerge_tpu import telemetry
        from hypermerge_tpu.utils import keys as keymod

        nodes = _mesh(4)
        seen = []
        try:
            for n in nodes[1:]:
                n.set_seed_hook(seen.append)
            doc_id = keymod.create().public_key
            key = _id_hex(key_id(keymod.discovery_id(doc_id)))
            before = telemetry.snapshot().get("dht.seeds_rx", 0)
            nodes[0].announce(key, "127.0.0.1", 7100, seed_doc=doc_id)
            wait_until(lambda: len(seen) >= 3)
            assert seen == [doc_id] * 3
            assert telemetry.snapshot()["dht.seeds_rx"] - before >= 3
            nodes[0].announce(key, "127.0.0.1", 7100, seed_doc=doc_id)
            time.sleep(0.2)
            assert len(seen) == 3  # dedup: a refresh never re-opens
        finally:
            for n in nodes:
                n.close()

    def test_key_mismatch_rejected(self):
        """A valid signature is not enough: the record may only ask us
        to replicate the doc whose keyspace position it is stored
        under, or any announcer could push arbitrary docs onto the
        fleet."""
        from hypermerge_tpu.utils import keys as keymod

        node = DhtNode()
        seen = []
        node.set_seed_hook(seen.append)
        try:
            doc_id = keymod.create().public_key
            wrong_key = _id_hex(key_id("not-this-doc"))
            rec = make_seed_record(wrong_key, doc_id, SEED, ttl=60)
            assert not node._handle_seed(rec)
            time.sleep(0.1)
            assert seen == []
        finally:
            node.close()


# ---------------------------------------------------------------------------
# gossip sampler


class _P:
    def __init__(self, i):
        self.id = f"peer{i:03d}"


class TestGossipSampler:
    def test_caps_at_fanout_and_stays_stable(self):
        peers = [_P(i) for i in range(20)]
        g = GossipSampler(fanout=4, reshuffle_s=60, seed=7)
        s1 = g.sample("doc", peers)
        assert len(s1) == 4
        assert [p.id for p in g.sample("doc", peers)] == [
            p.id for p in s1
        ]

    def test_small_peer_sets_pass_through(self):
        peers = [_P(i) for i in range(3)]
        g = GossipSampler(fanout=4, reshuffle_s=60)
        assert g.sample("doc", peers) == peers
        g0 = GossipSampler(fanout=0, reshuffle_s=60)
        assert g0.sample("doc", [_P(i) for i in range(50)]) is not None
        assert len(g0.sample("doc", [_P(i) for i in range(50)])) == 50

    def test_reshuffle_after_period(self):
        peers = [_P(i) for i in range(30)]
        g = GossipSampler(fanout=4, reshuffle_s=0.05, seed=7)
        s1 = {p.id for p in g.sample("doc", peers)}
        time.sleep(0.08)
        seen = set(s1)
        for _ in range(20):
            time.sleep(0.06)
            seen |= {p.id for p in g.sample("doc", peers)}
        assert len(seen) > 4  # rotated through fresh subsets

    def test_departed_peer_triggers_resample(self):
        peers = [_P(i) for i in range(10)]
        g = GossipSampler(fanout=4, reshuffle_s=60, seed=7)
        s1 = g.sample("doc", peers)
        survivors = [p for p in peers if p is not s1[0]]
        s2 = g.sample("doc", survivors)
        assert len(s2) == 4
        assert s1[0].id not in {p.id for p in s2}

    def test_per_key_independent(self):
        peers = [_P(i) for i in range(30)]
        g = GossipSampler(fanout=4, reshuffle_s=60, seed=7)
        a = {p.id for p in g.sample("doc-a", peers)}
        b = {p.id for p in g.sample("doc-b", peers)}
        assert a != b  # overwhelmingly likely with 30C4 per key


# ---------------------------------------------------------------------------
# the swarm seam: repos discover each other through the DHT only


def _dht_fleet(n, boot, fault_plans=None):
    """n memory repos on DhtSwarms bootstrapped off `boot`; optional
    {index: FaultPlan} wraps those swarms for seeded churn."""
    repos, swarms = [], []
    for i in range(n):
        r = Repo(memory=True)
        sw = DhtSwarm(bootstrap=[boot.address])
        if fault_plans and i in fault_plans:
            sw = FaultSwarm(sw, fault_plans[i])
        r.set_swarm(sw)
        repos.append(r)
        swarms.append(sw)
    return repos, swarms


def _teardown(repos, swarms, boot):
    for r in repos:
        r.close()
    for sw in swarms:
        sw.destroy()
    boot.close()


class TestDhtSwarm:
    def test_fleet_converges_dht_only(self, fast_dht):
        """Three repos, zero connect() calls: announce/lookup walks
        find the creator, supervised dials wire the sessions, edits
        converge bidirectionally."""
        boot = DhtNode()
        repos, swarms = _dht_fleet(3, boot)
        try:
            url = repos[0].create({"edits": []})
            handles = [r.open(url) for r in repos[1:]]
            assert all(h.value(timeout=60) is not None for h in handles)
            repos[0].change(url, lambda d: d["edits"].append("a"))
            handles[0].change(lambda d: d["edits"].append("b"))
            wait_until(
                lambda: all(
                    sorted((h.value() or {}).get("edits", []))
                    == ["a", "b"]
                    for h in handles
                )
                and sorted(repos[0].doc(url)["edits"]) == ["a", "b"],
                timeout=60,
            )
        finally:
            _teardown(repos, swarms, boot)

    def test_identity_signs_announces(self, fast_dht):
        """Network.set_swarm wires the repo identity into announce
        records: the published record's pk is the repo's ed25519
        public key, not the ephemeral node key."""
        import base64

        from hypermerge_tpu.utils import crypto

        boot = DhtNode()
        repos, swarms = _dht_fleet(2, boot)
        try:
            url = repos[0].create({"x": 1})
            assert repos[1].open(url).value(timeout=60) is not None
            rep = swarms[0].discovery_report()
            did = next(iter(rep["joined"]))
            key = _id_hex(key_id(did))
            recs = swarms[1].node.lookup(key)
            want = base64.b64encode(
                crypto.public_key(repos[0].back.identity_seed())
            ).decode("ascii")
            assert want in {r["pk"] for r in recs}
        finally:
            _teardown(repos, swarms, boot)

    def test_kill_heal_churn_reconverges(self, fast_dht):
        """The tier-1 slice of the soak: seeded kill mid-burst on one
        peer; the supervised redial + lookup refresh restore it and
        the fleet reconverges bit-identically."""
        plan = FaultPlan(seed=15, events=[(1, "kill"), (2, "heal")])
        boot = DhtNode()
        repos, swarms = _dht_fleet(4, boot, fault_plans={2: plan})
        try:
            url = repos[0].create({"edits": []})
            handles = [r.open(url) for r in repos[1:]]
            assert all(h.value(timeout=60) is not None for h in handles)
            for i in range(12):
                repos[0].change(url, lambda d, i=i: d["edits"].append(i))
                if i == 4:
                    swarms[2].tick()  # kill fires mid-burst
                if i == 8:
                    swarms[2].tick()  # heal
            while plan.tick < 2:
                swarms[2].tick()
            want = list(range(12))
            wait_until(
                lambda: all(
                    (h.value() or {}).get("edits") == want
                    for h in handles
                ),
                timeout=90,
            )
            blobs = {
                json.dumps(h.value(), sort_keys=True) for h in handles
            }
            assert len(blobs) == 1
        finally:
            _teardown(repos, swarms, boot)

    def test_leave_stops_refresh(self, fast_dht):
        boot = DhtNode()
        repos, swarms = _dht_fleet(2, boot)
        try:
            url = repos[0].create({"x": 1})
            assert repos[1].open(url).value(timeout=60) is not None
            rep = swarms[0].discovery_report()
            did = next(iter(rep["joined"]))
            swarms[0].leave(did)
            rep2 = swarms[0].discovery_report()
            assert did not in rep2["joined"]
            assert did not in rep2["targets"]
        finally:
            _teardown(repos, swarms, boot)

    def test_discovery_report_in_telemetry_payload(self, fast_dht):
        boot = DhtNode()
        repos, swarms = _dht_fleet(2, boot)
        try:
            url = repos[0].create({"x": 1})
            assert repos[1].open(url).value(timeout=60) is not None
            payload = repos[0].back.telemetry_payload()
            assert payload["dht"]["node_id"] == swarms[0].node.id_hex
            assert payload["dht"]["nodes"] >= 1
            docs = payload["net"]["docs"]
            ent = next(iter(docs.values()))
            assert ent["announced"] is True
            wait_until(
                lambda: next(
                    iter(
                        repos[0].back.telemetry_payload()["net"][
                            "docs"
                        ].values()
                    )
                )["peers"]
                >= 1,
                timeout=30,
            )
        finally:
            _teardown(repos, swarms, boot)


class TestAnnounceAggregation:
    def test_shared_via_is_one_announce_per_period(
        self, fast_dht, monkeypatch
    ):
        """Two ids joined via the same doc key fold into ONE signed
        announce record and one walk per period — O(docs), not
        O(actor feeds) — and the per-feed keys never hit the DHT."""
        from hypermerge_tpu import telemetry

        # one announce window for the whole test: any extra passes the
        # maintenance loop squeezes in must be provably skip-only
        monkeypatch.setenv("HM_DHT_ANNOUNCE_S", "30")
        boot = DhtNode()
        sw = DhtSwarm(bootstrap=[boot.address])
        try:
            before = telemetry.snapshot().get("dht.announces", 0)
            opts = JoinOptions(announce=True, lookup=False, via="doc-key")
            sw.join("feed-one", opts)
            sw.join("feed-two", opts)
            sw.poke(timeout=5)
            assert telemetry.snapshot()["dht.announces"] - before == 1
            gkey = _id_hex(key_id("doc-key"))
            assert sw.node.records.get(gkey)
            assert not sw.node.records.get(_id_hex(key_id("feed-one")))
            assert not sw.node.records.get(_id_hex(key_id("feed-two")))
        finally:
            sw.destroy()
            boot.close()


# ---------------------------------------------------------------------------
# bounded fanout: 20 peers, HM_GOSSIP_FANOUT=4


class TestBoundedFanout:
    def test_twenty_peers_fanout_four(self, monkeypatch):
        """The satellite claim verbatim: 20 peers on one doc with
        HM_GOSSIP_FANOUT=4 — the creator's replication frames stay
        O(fanout) per edit (an unbounded broadcast would pay ~19 per
        edit), while EVERY peer still converges through relay hops
        plus the anti-entropy sweep."""
        n, fanout, edits = 20, 4, 24
        monkeypatch.setenv("HM_GOSSIP_FANOUT", str(fanout))
        monkeypatch.setenv("HM_GOSSIP_RESHUFFLE_S", "30")
        monkeypatch.setenv("HM_ANTIENTROPY_S", "0")  # sweeps manual
        hub = LoopbackHub()
        repos = []
        try:
            for _ in range(n):
                r = Repo(memory=True)
                r.set_swarm(LoopbackSwarm(hub))
                repos.append(r)
            url = repos[0].create({"edits": []})
            handles = [r.open(url) for r in repos[1:]]
            assert all(
                h.value(timeout=60) is not None for h in handles
            )
            rm = repos[0].back.network.replication
            frames0 = rm.stats["frames_tx"]
            for i in range(edits):
                repos[0].change(url, lambda d, i=i: d["edits"].append(i))
                time.sleep(0.01)  # one flush window per edit: the
                # coalescer must not hide the fanout bound

            want = list(range(edits))

            def converged():
                # anti-entropy path: every NON-creator sweeps (the
                # frames under test are the creator's)
                for r in repos[1:]:
                    r.back.network.replication.sweep_now()
                return all(
                    (h.value() or {}).get("edits") == want
                    for h in handles
                )

            wait_until(converged, timeout=90, interval=0.25)
            frames = rm.stats["frames_tx"] - frames0
            # O(fanout): ~4/edit + straggler pulls; O(peers) would be
            # >= 19/edit = 456
            assert frames <= edits * (fanout + 2) + 60, frames
            blobs = {
                json.dumps(h.value(), sort_keys=True) for h in handles
            }
            assert len(blobs) == 1
        finally:
            for r in repos:
                r.close()

    def test_fanout_zero_broadcasts_to_all(self, monkeypatch):
        monkeypatch.setenv("HM_GOSSIP_FANOUT", "0")
        hub = LoopbackHub()
        repos = []
        try:
            for _ in range(6):
                r = Repo(memory=True)
                r.set_swarm(LoopbackSwarm(hub))
                repos.append(r)
            url = repos[0].create({"edits": []})
            handles = [r.open(url) for r in repos[1:]]
            assert all(
                h.value(timeout=60) is not None for h in handles
            )
            repos[0].change(url, lambda d: d["edits"].append(1))
            wait_until(
                lambda: all(
                    (h.value() or {}).get("edits") == [1]
                    for h in handles
                )
            )
        finally:
            for r in repos:
                r.close()


# the 50-peer churn soak lives in tests/test_fleet_soak.py (-m slow):
# at that scale the lockdep/racedep module instrumentation this suite
# runs under would dominate the wall clock — the guard/lock coverage
# of the discovery classes comes from the tier-1 tests above.
