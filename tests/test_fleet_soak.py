"""The fleet soak (-m slow): 50 in-process daemons joined ONLY through
the DHT (net/discovery/ — no connect() anywhere), a seeded fifth of the
fleet hard-killed mid-burst and healed, every surviving peer converging
BIT-identically, and per-peer frame amplification bounded by the gossip
fanout instead of the peer count. The 100-peer variant runs the same
churn on the async transport (HM_NET_ASYNC=1) with delta cursors on —
the scaling configuration the 1000-peer bench models — and must meet
the SAME amplification gate.

Runs uninstrumented on purpose: at 50+ repos the lockdep/racedep
descriptor overhead dominates the wall clock; the discovery and aio
classes' guard/lock coverage lives in tests/test_discovery.py and
tests/test_aio.py (tier-1, fully instrumented)."""

import json
import time

import pytest

from hypermerge_tpu.net.discovery import DhtNode, DhtSwarm
from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
from hypermerge_tpu.repo import Repo

pytestmark = pytest.mark.slow


def _churn_soak(monkeypatch, n, edits, fanout, env=None):
    """The soak body both fleet sizes share: build the fleet, converge
    discovery, churn a seeded fifth mid-edit, require bit-identical
    state everywhere, then gate per-peer frame amplification on a
    steady-state burst. Returns the measured amplification."""
    monkeypatch.setenv("HM_GOSSIP_FANOUT", str(fanout))
    monkeypatch.setenv("HM_GOSSIP_RESHUFFLE_S", "1")
    monkeypatch.setenv("HM_DHT_ANNOUNCE_S", "10")
    monkeypatch.setenv("HM_DHT_LOOKUP_S", "5")
    monkeypatch.setenv("HM_ANTIENTROPY_S", "3")
    monkeypatch.setenv("HM_REDIAL_BASE_MS", "30")
    monkeypatch.setenv("HM_REDIAL_MAX_S", "0.5")
    monkeypatch.setenv("HM_NET_PING_S", "0")
    for k, v in (env or {}).items():
        monkeypatch.setenv(k, v)
    plans = {
        i: FaultPlan(seed=50 + i, events=[(1, "kill"), (2, "heal")])
        for i in range(1, n, 5)  # a churned fifth, never the creator
    }
    boot = DhtNode()
    repos, swarms = [], []
    try:
        for i in range(n):
            r = Repo(memory=True)
            sw = DhtSwarm(bootstrap=[boot.address])
            if i in plans:
                sw = FaultSwarm(sw, plans[i])
            r.set_swarm(sw)
            repos.append(r)
            swarms.append(sw)
        url = repos[0].create({"edits": []})
        handles = [r.open(url) for r in repos[1:]]
        # pure-DHT discovery: every peer finds the doc through
        # announce/lookup walks + relay + anti-entropy alone
        ready = set()
        deadline = time.monotonic() + 300
        while len(ready) < len(handles):
            assert time.monotonic() < deadline, (
                f"discovery stalled at {len(ready)}/{len(handles)}"
            )
            for i, h in enumerate(handles):
                if i not in ready:
                    try:
                        if h.value(timeout=0.01) is not None:
                            ready.add(i)
                    except TimeoutError:
                        pass
            time.sleep(0.5)
        faulted = [swarms[i] for i in plans]
        third = edits // 3
        for i in range(edits):
            repos[0].change(url, lambda d, i=i: d["edits"].append(i))
            if i == third or i == 2 * third:
                for fs in faulted:
                    fs.tick()
        for fs in faulted:
            while fs.plan.tick < 2:
                fs.tick()
        want = list(range(edits))
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(
                (h.value() or {}).get("edits") == want for h in handles
            ):
                break
            time.sleep(0.5)
        else:
            behind = sum(
                1
                for h in handles
                if (h.value() or {}).get("edits") != want
            )
            raise AssertionError(f"soak never converged: {behind} behind")
        blobs = {json.dumps(h.value(), sort_keys=True) for h in handles}
        blobs.add(json.dumps(repos[0].doc(url), sort_keys=True))
        assert len(blobs) == 1, "diverged doc state across the fleet"
        # frame amplification on a STEADY-STATE burst (the O(fanout)
        # claim): the churn window above accrues discovery + sweep
        # repair frames that would drown the per-edit signal
        frames0 = [
            r.back.network.replication.stats["frames_tx"] for r in repos
        ]
        burst = 20
        for i in range(burst):
            repos[0].change(
                url, lambda d, i=i: d["edits"].append(1000 + i)
            )
            time.sleep(0.01)
        want2 = want + [1000 + i for i in range(burst)]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if all(
                (h.value() or {}).get("edits") == want2
                for h in handles
            ):
                break
            time.sleep(0.25)
        else:
            raise AssertionError("steady-state burst never converged")
        amp = max(
            (r.back.network.replication.stats["frames_tx"] - f0) / burst
            for r, f0 in zip(repos, frames0)
        )
        return amp
    finally:
        for r in repos:
            r.close()
        for sw in swarms:
            sw.destroy()
        boot.close()


def test_fifty_peer_churn_soak(monkeypatch):
    fanout = 4
    amp = _churn_soak(monkeypatch, n=50, edits=30, fanout=fanout)
    # O(fanout) with relay + sweep slack — O(peers) would be >= 49
    assert amp <= 4 * fanout + 8, amp


def test_hundred_peer_async_churn_soak(monkeypatch):
    """The scaling configuration end to end: 100 daemons multiplexed
    onto selector loops (no thread per connection), delta cursors on,
    the same seeded churn — bit-identical convergence and the SAME
    O(fanout) amplification gate as the 50-peer legacy fleet. Double
    the peers must not move the per-edit frame bill.

    Fleet size scales with the host: every daemon lives in THIS
    process, so 100 of them share one GIL and need real cores to
    timeslice their loops (measured: single-core CI reaches 20/99
    discovered in the whole deadline, on either transport). On
    single-digit-core boxes the same configuration soaks at 50 —
    the gates (bit-identical convergence, O(fanout) amplification,
    loop/delta telemetry) are size-independent, and the 1000-peer
    frame bill is modeled by bench config_fleet1000."""
    import os

    from hypermerge_tpu import telemetry

    before = telemetry.snapshot()
    fanout = 4
    n = 100 if (os.cpu_count() or 1) >= 8 else 50
    amp = _churn_soak(
        monkeypatch, n=n, edits=30, fanout=fanout,
        env={"HM_NET_ASYNC": "1", "HM_CURSOR_DELTA": "1"},
    )
    assert amp <= 4 * fanout + 8, amp
    snap = telemetry.snapshot()

    def grew(name):
        return snap.get(name, 0) - before.get(name, 0)

    # the fleet really ran on the loop transport...
    assert grew("net.aio.loop_busy_ms") > 0
    # ...and steady state really ran on delta/suppressed cursor frames
    assert grew("net.cursor.delta_tx") + grew("net.cursor.suppressed") > 0
