"""The fleet soak (-m slow): 50 in-process daemons joined ONLY through
the DHT (net/discovery/ — no connect() anywhere), a seeded fifth of the
fleet hard-killed mid-burst and healed, every surviving peer converging
BIT-identically, and per-peer frame amplification bounded by the gossip
fanout instead of the peer count.

Runs uninstrumented on purpose: at 50 repos the lockdep/racedep
descriptor overhead dominates the wall clock; the discovery classes'
guard/lock coverage lives in tests/test_discovery.py (tier-1, fully
instrumented)."""

import json
import time

import pytest

from hypermerge_tpu.net.discovery import DhtNode, DhtSwarm
from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
from hypermerge_tpu.repo import Repo

pytestmark = pytest.mark.slow


def test_fifty_peer_churn_soak(monkeypatch):
    n, edits, fanout = 50, 30, 4
    monkeypatch.setenv("HM_GOSSIP_FANOUT", str(fanout))
    monkeypatch.setenv("HM_GOSSIP_RESHUFFLE_S", "1")
    monkeypatch.setenv("HM_DHT_ANNOUNCE_S", "10")
    monkeypatch.setenv("HM_DHT_LOOKUP_S", "5")
    monkeypatch.setenv("HM_ANTIENTROPY_S", "3")
    monkeypatch.setenv("HM_REDIAL_BASE_MS", "30")
    monkeypatch.setenv("HM_REDIAL_MAX_S", "0.5")
    monkeypatch.setenv("HM_NET_PING_S", "0")
    plans = {
        i: FaultPlan(seed=50 + i, events=[(1, "kill"), (2, "heal")])
        for i in range(1, n, 5)  # 10 churned peers, never the creator
    }
    boot = DhtNode()
    repos, swarms = [], []
    try:
        for i in range(n):
            r = Repo(memory=True)
            sw = DhtSwarm(bootstrap=[boot.address])
            if i in plans:
                sw = FaultSwarm(sw, plans[i])
            r.set_swarm(sw)
            repos.append(r)
            swarms.append(sw)
        url = repos[0].create({"edits": []})
        handles = [r.open(url) for r in repos[1:]]
        # pure-DHT discovery: all 49 peers find the doc through
        # announce/lookup walks + relay + anti-entropy alone
        ready = set()
        deadline = time.monotonic() + 300
        while len(ready) < len(handles):
            assert time.monotonic() < deadline, (
                f"discovery stalled at {len(ready)}/{len(handles)}"
            )
            for i, h in enumerate(handles):
                if i not in ready:
                    try:
                        if h.value(timeout=0.01) is not None:
                            ready.add(i)
                    except TimeoutError:
                        pass
            time.sleep(0.5)
        faulted = [swarms[i] for i in plans]
        third = edits // 3
        for i in range(edits):
            repos[0].change(url, lambda d, i=i: d["edits"].append(i))
            if i == third or i == 2 * third:
                for fs in faulted:
                    fs.tick()
        for fs in faulted:
            while fs.plan.tick < 2:
                fs.tick()
        want = list(range(edits))
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(
                (h.value() or {}).get("edits") == want for h in handles
            ):
                break
            time.sleep(0.5)
        else:
            behind = sum(
                1
                for h in handles
                if (h.value() or {}).get("edits") != want
            )
            raise AssertionError(f"soak never converged: {behind} behind")
        blobs = {json.dumps(h.value(), sort_keys=True) for h in handles}
        blobs.add(json.dumps(repos[0].doc(url), sort_keys=True))
        assert len(blobs) == 1, "diverged doc state across the fleet"
        # frame amplification on a STEADY-STATE burst (the O(fanout)
        # claim): the churn window above accrues discovery + sweep
        # repair frames that would drown the per-edit signal
        frames0 = [
            r.back.network.replication.stats["frames_tx"] for r in repos
        ]
        burst = 20
        for i in range(burst):
            repos[0].change(
                url, lambda d, i=i: d["edits"].append(1000 + i)
            )
            time.sleep(0.01)
        want2 = want + [1000 + i for i in range(burst)]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if all(
                (h.value() or {}).get("edits") == want2
                for h in handles
            ):
                break
            time.sleep(0.25)
        else:
            raise AssertionError("steady-state burst never converged")
        amp = max(
            (r.back.network.replication.stats["frames_tx"] - f0) / burst
            for r, f0 in zip(repos, frames0)
        )
        # O(fanout) with relay + sweep slack — O(peers) would be >= 49
        assert amp <= 4 * fanout + 8, amp
    finally:
        for r in repos:
            r.close()
        for sw in swarms:
            sw.destroy()
        boot.close()
