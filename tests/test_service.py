"""The service plane (serve/overload.py, ISSUE 20): brownout ladder,
per-tenant quotas, typed Overload refusals, WAL ack pacing.

Deterministic on purpose: the ladder and the controller are driven by
INJECTED signals and a fake clock — no load is generated to test the
state machine. The IPC round-trip pins the typed refusal across the
process boundary (HM_SERVICE_FORCE pins the state so the daemon sheds
without a storm), and the `-m slow` soak runs FaultSwarm kill/heal
DURING a read-storm ramp, asserting bit-identical reconvergence with
every acknowledged write surviving (acked_lost=0).

Runs fully instrumented (HM_LOCKDEP=1 + HM_RACEDEP=1): the
controller's guard rows in analysis/guards.py are exercised by every
test here.
"""

import os
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from hypermerge_tpu import telemetry
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.serve.overload import (
    BROWNOUT,
    HEALTHY,
    SHED,
    BrownoutLadder,
    Overload,
    OverloadController,
    TokenBucket,
)

from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite

_lockdep = lockdep_suite()
_racedep = racedep_suite()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}


def snap():
    return telemetry.snapshot()


# ---------------------------------------------------------------------------
# the ladder: hysteresis, no flapping


class TestBrownoutLadder:
    def test_escalates_after_up_ticks(self):
        lad = BrownoutLadder(hi=1.0, lo=0.5, up_ticks=3, down_ticks=2)
        assert lad.observe(1.2) == HEALTHY
        assert lad.observe(1.2) == HEALTHY
        assert lad.observe(1.2) == BROWNOUT  # third consecutive

    def test_interrupted_streak_does_not_escalate(self):
        lad = BrownoutLadder(hi=1.0, lo=0.5, up_ticks=3, down_ticks=2)
        for _ in range(10):
            lad.observe(1.2)
            lad.observe(1.2)
            assert lad.observe(0.7) == HEALTHY  # dead band resets

    def test_climbs_to_shed_and_recovers(self):
        lad = BrownoutLadder(hi=1.0, lo=0.5, up_ticks=2, down_ticks=3)
        for _ in range(2):
            lad.observe(1.5)
        assert lad.state == BROWNOUT
        for _ in range(2):
            lad.observe(1.5)
        assert lad.state == SHED
        for _ in range(4):
            lad.observe(1.5)
        assert lad.state == SHED  # already at the top rung
        for _ in range(3):
            lad.observe(0.1)
        assert lad.state == BROWNOUT  # one rung per down streak
        for _ in range(3):
            lad.observe(0.1)
        assert lad.state == HEALTHY

    def test_dead_band_holds_rung(self):
        lad = BrownoutLadder(hi=1.0, lo=0.5, up_ticks=1, down_ticks=1)
        lad.observe(1.0)
        assert lad.state == BROWNOUT
        for _ in range(50):
            assert lad.observe(0.75) == BROWNOUT

    def test_oscillation_inside_band_never_flaps(self):
        # a noisy signal bouncing lo..hi exclusive must never move
        # the ladder in EITHER direction
        lad = BrownoutLadder(hi=1.0, lo=0.5, up_ticks=2, down_ticks=2)
        lad.observe(1.0)
        lad.observe(1.0)
        assert lad.state == BROWNOUT
        for i in range(100):
            assert lad.observe(0.55 + 0.4 * (i % 2)) == BROWNOUT

    def test_watermark_order_enforced(self):
        with pytest.raises(ValueError):
            BrownoutLadder(hi=0.5, lo=0.5)


# ---------------------------------------------------------------------------
# token buckets: refill, burst, retry-after (fake clock throughout)


class TestTokenBucket:
    def test_burst_then_refill(self):
        b = TokenBucket(rate=10.0, burst=3.0, now=0.0)
        assert [b.take(0.0) for _ in range(4)] == [
            True, True, True, False,
        ]
        assert b.take(0.1)  # one token back after 100ms at 10/s
        assert not b.take(0.1)

    def test_burst_caps_refill(self):
        b = TokenBucket(rate=100.0, burst=2.0, now=0.0)
        assert b.occupancy(1000.0) == 0.0  # full, not 100k tokens
        assert b.take(1000.0) and b.take(1000.0) and not b.take(1000.0)

    def test_retry_after(self):
        b = TokenBucket(rate=2.0, burst=1.0, now=0.0)
        assert b.take(0.0)
        assert b.retry_after_s(0.0) == pytest.approx(0.5)
        assert b.retry_after_s(0.5) == pytest.approx(0.0)

    def test_occupancy(self):
        b = TokenBucket(rate=1.0, burst=4.0, now=0.0)
        b.take(0.0)
        b.take(0.0)
        assert b.occupancy(0.0) == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# the controller: injected signals drive enforcement deterministically


def _controller(monkeypatch, env=None, **kw):
    for k, v in (env or {}).items():
        monkeypatch.setenv(k, v)
    return OverloadController(**kw)


class TestController:
    def test_pressure_is_max_of_normalized_signals(self, monkeypatch):
        c = _controller(
            monkeypatch, env={"HM_SERVICE_P99_SLO_MS": "100"}
        )
        c.tick({"p99_s": 0.05, "queue_frac": 0.9, "debt_frac": 0.1})
        assert c.report()["pressure"] == pytest.approx(0.9)
        c.tick({"p99_s": 0.2, "queue_frac": 0.1, "debt_frac": 0.0})
        assert c.report()["pressure"] == pytest.approx(2.0)

    def test_signal_feed_walks_the_ladder(self, monkeypatch):
        c = _controller(
            monkeypatch,
            env={
                "HM_BROWNOUT_UP_TICKS": "2",
                "HM_BROWNOUT_DOWN_TICKS": "2",
            },
        )
        hot = {"queue_frac": 1.5}
        cold = {"queue_frac": 0.0}
        assert c.tick(hot) == HEALTHY
        assert c.tick(hot) == BROWNOUT
        assert c.tick(hot) == HEALTHY + 1  # still brownout, streak reset
        assert c.tick(hot) == SHED
        assert c.tick(cold) == SHED
        assert c.tick(cold) == BROWNOUT
        assert c.tick(cold) == BROWNOUT
        assert c.tick(cold) == HEALTHY
        assert c.report()["transitions"] == 4

    def test_healthy_admits_everything(self, monkeypatch):
        c = _controller(monkeypatch)
        assert c.admit_read("t1") is None
        assert c.refuse_overflow("t1") is None
        assert not c.defer_install()
        assert not c.deprioritize()
        assert c.ack_extra_s() == 0.0

    def test_shed_enforces_per_tenant_quota(self, monkeypatch):
        clock = [100.0]
        c = _controller(
            monkeypatch,
            env={
                "HM_QUOTA_READS_S": "10",
                "HM_QUOTA_BURST": "2",
                "HM_SERVICE_FORCE": "shed",
            },
            now=lambda: clock[0],
        )
        assert c.state() == SHED
        assert c.admit_read("a") is None
        assert c.admit_read("a") is None
        refusal = c.admit_read("a")  # burst spent
        assert refusal is not None
        info = refusal["overload"]
        assert info["state"] == "shed"
        assert info["tenant"] == "a"
        assert info["retry_after_s"] > 0
        # tenant isolation: b's bucket is untouched by a's storm
        assert c.admit_read("b") is None
        # refill: 10/s for 0.2s = 2 tokens back
        clock[0] += 0.2
        assert c.admit_read("a") is None
        rep = c.report()
        assert rep["tenants"]["a"]["refused"] == 1
        assert rep["tenants"]["a"]["admitted"] == 3
        assert rep["tenants"]["b"]["admitted"] == 1
        assert rep["shed_reads"] >= 1

    def test_brownout_defers_not_refuses(self, monkeypatch):
        c = _controller(
            monkeypatch, env={"HM_SERVICE_FORCE": "brownout"}
        )
        assert c.admit_read("a") is None  # reads still admitted
        assert c.defer_install(reads=3)
        assert c.deprioritize()
        assert c.ack_extra_s() == 0.0  # backpressure is SHED-only
        rep = c.report()
        assert rep["brownout_reads"] == 3
        assert rep["deferred_installs"] == 1

    def test_shed_stretches_acks(self, monkeypatch):
        c = _controller(
            monkeypatch,
            env={
                "HM_SERVICE_FORCE": "shed",
                "HM_SERVICE_ACK_STRETCH_MS": "40",
            },
        )
        assert c.ack_extra_s() == pytest.approx(0.04)
        assert c.report()["ack_stretch_ms"] == pytest.approx(40.0)

    def test_overflow_refusal_charges_no_token(self, monkeypatch):
        clock = [5.0]
        c = _controller(
            monkeypatch,
            env={
                "HM_QUOTA_READS_S": "10",
                "HM_QUOTA_BURST": "4",
                "HM_SERVICE_FORCE": "shed",
            },
            now=lambda: clock[0],
        )
        for _ in range(8):
            assert c.refuse_overflow("a") is not None
        # the queue was the constraint, not the quota: the bucket is
        # still full, so front-door admission proceeds
        assert c.admit_read("a") is None
        assert c.report()["tenants"]["a"]["refused"] == 8

    def test_tenant_table_is_bounded(self, monkeypatch):
        from hypermerge_tpu.serve.overload import MAX_TENANTS

        c = _controller(
            monkeypatch, env={"HM_SERVICE_FORCE": "shed"}
        )
        for i in range(MAX_TENANTS + 50):
            c.admit_read(f"t{i}")
        assert len(c.report()["tenants"]) == MAX_TENANTS


# ---------------------------------------------------------------------------
# enforcement through a real repo (forced states, no load needed)


def test_front_door_refusal_raises_typed_overload(monkeypatch):
    monkeypatch.setenv("HM_SERVICE_FORCE", "shed")
    monkeypatch.setenv("HM_QUOTA_READS_S", "1")
    monkeypatch.setenv("HM_QUOTA_BURST", "0")
    repo = Repo(memory=True)
    try:
        url = repo.create({"n": 1})
        with pytest.raises(Overload) as exc:
            repo.read(url, {"kind": "lookup", "path": ["n"]})
        assert exc.value.retry_after_s > 0
        assert exc.value.state == "shed"
        # fully attributable: the refusal is on the tenant table AND
        # the counter, never silent
        svc = repo.back.telemetry_payload()["service"]
        assert svc["state_name"] == "shed"
        assert svc["tenants"]["local"]["refused"] >= 1
        assert svc["shed_reads"] >= 1
    finally:
        repo.close()


def test_front_door_refusal_cb_path(monkeypatch):
    monkeypatch.setenv("HM_SERVICE_FORCE", "shed")
    monkeypatch.setenv("HM_QUOTA_READS_S", "1")
    monkeypatch.setenv("HM_QUOTA_BURST", "0")
    repo = Repo(memory=True)
    try:
        url = repo.create({"n": 1})
        got = []
        repo.front.read(url, {"kind": "lookup", "path": ["n"]}, got.append)
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got and isinstance(got[0], dict)
        assert got[0]["_overload"]["retry_after_s"] > 0
    finally:
        repo.close()


def test_brownout_serves_cold_reads_from_host(monkeypatch):
    monkeypatch.setenv("HM_SERVICE_FORCE", "brownout")
    repo = Repo(memory=True)
    try:
        url = repo.create({"n": 77})
        # the read ANSWERS (host memo path) but the device install is
        # deferred — cold installs shed first, reads never error
        assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 77
        svc = repo.back.telemetry_payload()["service"]
        assert svc["deferred_installs"] >= 1
        assert svc["brownout_reads"] >= 1
        assert svc["shed_reads"] == 0  # brownout refuses nothing
    finally:
        repo.close()


def test_healthy_repo_never_touches_the_ladder():
    repo = Repo(memory=True)
    try:
        url = repo.create({"n": 5})
        assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 5
        svc = repo.back.telemetry_payload()["service"]
        assert svc["state_name"] == "healthy"
        assert svc["shed_reads"] == 0
        assert svc["brownout_reads"] == 0
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# WAL ack pacing: backpressured, never dropped


def test_wal_ack_pacing_stretches_commit(tmp_path):
    from hypermerge_tpu.storage.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "wal.log"), tier=2)
    try:
        paced0 = snap().get("storage.wal.paced_commits", 0)
        end = wal.append("feedA", 0, b"x" * 64)
        assert end is not None
        t0 = time.perf_counter()
        wal.commit(end)
        fast = time.perf_counter() - t0
        wal.ack_pacer = lambda: 0.05
        end = wal.append("feedA", 1, b"y" * 64)
        t0 = time.perf_counter()
        wal.commit(end)
        slow = time.perf_counter() - t0
        # lower bound only (upper bounds flake on loaded CI): the
        # paced commit waited at least most of the stretch, and the
        # write is DURABLE — backpressure, not loss
        assert slow >= 0.04
        assert slow > fast
        assert snap()["storage.wal.paced_commits"] == paced0 + 1
    finally:
        wal.close()


# ---------------------------------------------------------------------------
# typed Overload across the IPC seam (the hub front door)


def _start_hub(repo_dir, env_extra):
    sock = tempfile.mktemp(suffix=".sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "hypermerge_tpu.net.ipc", repo_dir, sock,
         "--hub"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env={**ENV, **env_extra},
        cwd=REPO_ROOT,
    )
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(sock):
        if proc.poll() is not None:
            raise AssertionError(proc.stderr.read())
        time.sleep(0.05)
    assert os.path.exists(sock), "daemon socket never appeared"
    return proc, sock


def test_overload_reply_round_trips_ipc(tmp_path):
    from hypermerge_tpu.net.ipc import connect_frontend

    proc, sock = _start_hub(
        str(tmp_path / "repo"),
        {
            "HM_SERVICE_FORCE": "shed",
            "HM_QUOTA_READS_S": "1",
            "HM_QUOTA_BURST": "0",
        },
    )
    try:
        front, close = connect_frontend(sock)
        try:
            url = front.create({"n": 3})
            with pytest.raises(Overload) as exc:
                front.read(url, {"kind": "lookup", "path": ["n"]},
                           timeout=30)
            assert exc.value.retry_after_s > 0
            assert exc.value.state == "shed"
            # the hub tagged the connection as the tenant
            assert (exc.value.tenant or "").startswith("conn")
            # attribution survives the seam: the daemon's Telemetry
            # payload names the tenant and the refusal
            got = []
            front.telemetry(got.append)
            deadline = time.time() + 10
            while not got and time.time() < deadline:
                time.sleep(0.05)
            svc = (got[0] or {}).get("service") or {}
            assert svc.get("state_name") == "shed"
            tenants = svc.get("tenants") or {}
            assert any(
                k.startswith("conn") and v["refused"] >= 1
                for k, v in tenants.items()
            )
        finally:
            close()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        if os.path.exists(sock):
            os.remove(sock)


# ---------------------------------------------------------------------------
# the soak: churn DURING a read storm, acked writes survive (-m slow)


@pytest.mark.slow
def test_read_storm_churn_soak(monkeypatch):
    """FaultSwarm kill/heal mid-ramp while reader threads hammer every
    peer: the fleet reconverges bit-identically, every acknowledged
    write survives (acked_lost=0), and no read ever ERRORS — every
    outcome is a value, a None (not-yet-replicated), or a typed
    Overload."""
    import json

    from hypermerge_tpu.net.discovery import DhtNode, DhtSwarm
    from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm

    monkeypatch.setenv("HM_GOSSIP_FANOUT", "4")
    monkeypatch.setenv("HM_ANTIENTROPY_S", "2")
    monkeypatch.setenv("HM_REDIAL_BASE_MS", "30")
    monkeypatch.setenv("HM_REDIAL_MAX_S", "0.5")
    n = 8
    boot = DhtNode()
    repos, swarms = [], []
    plans = {
        i: FaultPlan(seed=20 + i, events=[(1, "kill"), (2, "heal")])
        for i in (2, 5)
    }
    stop = threading.Event()
    errors = []
    try:
        for i in range(n):
            r = Repo(memory=True)
            sw = DhtSwarm(bootstrap=[boot.address])
            if i in plans:
                sw = FaultSwarm(sw, plans[i])
            r.set_swarm(sw)
            repos.append(r)
            swarms.append(sw)
        url = repos[0].create({"edits": []})
        handles = [r.open(url) for r in repos[1:]]
        deadline = time.monotonic() + 300
        ready = set()
        while len(ready) < len(handles):
            assert time.monotonic() < deadline, "discovery stalled"
            for i, h in enumerate(handles):
                if i not in ready:
                    try:
                        if h.value(timeout=0.01) is not None:
                            ready.add(i)
                    except TimeoutError:
                        pass
            time.sleep(0.25)

        def reader(r):
            # the ramp: back-to-back reads, no pacing — a storm
            while not stop.is_set():
                try:
                    r.read(url, {"kind": "len", "path": ["edits"]})
                except Overload:
                    pass  # typed shed is a legal outcome
                except TimeoutError:
                    pass  # churn window; not an error reply
                except Exception as e:  # anything else is a failure
                    errors.append(repr(e))
                    return

        threads = [
            threading.Thread(target=reader, args=(r,), daemon=True)
            for r in repos
            for _ in range(2)
        ]
        for t in threads:
            t.start()
        acked = []
        edits = 60
        third = edits // 3
        faulted = [swarms[i] for i in plans]
        for i in range(edits):
            repos[0].change(url, lambda d, i=i: d["edits"].append(i))
            acked.append(i)  # change() returned: the write is acked
            if i == third or i == 2 * third:
                for fs in faulted:
                    fs.tick()
        for fs in faulted:
            while fs.plan.tick < 2:
                fs.tick()
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, f"reads errored during the storm: {errors[:3]}"
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(
                (h.value() or {}).get("edits") == acked for h in handles
            ):
                break
            time.sleep(0.5)
        else:
            behind = sum(
                1 for h in handles
                if (h.value() or {}).get("edits") != acked
            )
            raise AssertionError(
                f"acked writes lost on {behind} peers (acked_lost>0)"
            )
        blobs = {json.dumps(h.value(), sort_keys=True) for h in handles}
        blobs.add(json.dumps(repos[0].doc(url), sort_keys=True))
        assert len(blobs) == 1, "diverged under churn + read storm"
    finally:
        stop.set()
        for r in repos:
            r.close()
        for sw in swarms:
            sw.destroy()
        boot.close()
