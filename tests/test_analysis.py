"""Invariant linter + runtime lockdep (hypermerge_tpu/analysis/).

Three layers:
- the tier-1 gate: `lint_repo()` over the real tree must report ZERO
  unsuppressed violations (exactly what `python tools/lint.py` exits
  nonzero on);
- per-rule fixtures: each lint rule on small violating + conforming
  snippets, so a rule regression fails with a readable diff instead of
  "the tree got dirty";
- the runtime detector: an A->B / B->A potential cycle on two threads
  is REPORTED without deadlocking, rank/leaf/blocking violations are
  recorded, and the factories stay plain threading primitives while
  lockdep is off.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from hypermerge_tpu.analysis import envvars, guards, hierarchy, linter, lockdep
from hypermerge_tpu.analysis import suppressions as suppmod

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PKG_PATH = "hypermerge_tpu/_fixture.py"


def _rules(viols, rule=None, suppressed=False):
    return [
        v
        for v in viols
        if (rule is None or v.rule == rule)
        and v.suppressed == suppressed
    ]


# ---------------------------------------------------------------------------
# manifests


def test_manifests_validate():
    hierarchy.validate()
    envvars.validate()
    guards.validate()


def test_guards_manifest_shape():
    """Every guard names a declared lock class; the escapes are the
    documented four; flattening is collision-free (validate raised
    otherwise) and the hot classes the ISSUE names are covered."""
    for entry in guards.BY_CLS_ATTR.values():
        assert entry.guard in hierarchy.BY_NAME
        assert entry.escape in guards.ESCAPES
    for cls in (
        "LiveApplyEngine", "DocBackend", "RepoBackend", "ReadBatcher",
        "ResidencyCache", "SessionSupervisor", "NetworkPeer",
        "CursorStore", "DurabilityManager",
    ):
        assert cls in guards.CLASSES
    assert guards.guard_for("LiveApplyEngine", "_docs").guard == (
        "live.engine"
    )
    assert guards.guard_for("NetworkPeer", "connection").escape == (
        "unguarded"
    )


def test_hierarchy_core_order():
    """The documented core order is what the manifest declares."""
    r = hierarchy.RANKED
    # doc.emit OUTRANKS the engine lock since the write-plane split:
    # an emission path holds its doc's domain first and dips into the
    # engine only for table bookkeeping
    assert r["repo.bulk"] < r["doc.emit"] < r["live.engine"] < r["doc"]
    assert r["doc"] < r["repo"] < r["actor"] < r["store.feed"]
    assert r["store.feed"] < r["store.wal"]  # journal appends run
    # under the feed lock (feed.py append -> durability.journal_append)
    assert r["store.sql"] < r["store.cursors"]  # bulk batches absorb
    # into the mirror with the sql lock held (stores.py)
    assert "store.integrity" in hierarchy.LEAVES
    assert "util.debug" in hierarchy.LEAVES
    # the per-doc emission domain MAY block (a durable ack under it
    # stalls exactly one doc); only the global coordination lock is a
    # no-block class
    assert hierarchy.NO_BLOCK == {"live.engine"}


# ---------------------------------------------------------------------------
# THE tier-1 gate


def test_tree_is_clean():
    """Zero unsuppressed violations over the real tree — the same
    check `python tools/lint.py` runs in CI."""
    viols = linter.unsuppressed(linter.lint_repo(ROOT))
    assert viols == [], "\n" + "\n".join(v.format() for v in viols)


# ---------------------------------------------------------------------------
# lint rule fixtures


FIXTURE_LOCKS = """
from hypermerge_tpu.analysis.lockdep import make_rlock

class Engine:
    def __init__(self):
        self._lock = make_rlock("live.engine")

class Store:
    def __init__(self):
        self._slock = make_rlock("store.feed")
"""


def test_lock_order_rule():
    bad = FIXTURE_LOCKS + """
class User:
    def __init__(self, engine, store):
        self.e, self.s = engine, store
    def broken(self):
        with self.s._slock:
            with self.e._lock:
                pass
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "lock-order")
    assert len(viols) == 1 and "inverts" in viols[0].msg
    good = FIXTURE_LOCKS + """
class User:
    def __init__(self, engine, store):
        self.e, self.s = engine, store
    def fine(self):
        with self.e._lock:
            with self.s._slock:
                pass
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "lock-order") == []


def test_lock_order_leaf_rule():
    bad = """
from hypermerge_tpu.analysis.lockdep import make_rlock

class I:
    def __init__(self):
        self._ilock = make_rlock("store.integrity")
        self._flock = make_rlock("store.feed")
    def broken(self):
        with self._ilock:
            with self._flock:
                pass
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "lock-order")
    assert len(viols) == 1 and "leaf" in viols[0].msg


def test_engine_entrypoint_rule():
    bad = FIXTURE_LOCKS + """
class R:
    def __init__(self, live):
        self._rlock = make_rlock("repo")
        self.live = live
    def broken(self, doc, changes):
        with self._rlock:
            self.live.submit_remote(doc, changes)
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "lock-order")
    assert len(viols) == 1 and "outermost" in viols[0].msg
    good = bad.replace(
        "with self._rlock:\n            self.live.submit_remote",
        "if True:\n            self.live.submit_remote",
    )
    assert _rules(linter.lint_source(good, PKG_PATH), "lock-order") == []


def test_no_block_rule():
    bad = FIXTURE_LOCKS + """
import os

class E2(Engine):
    def broken(self, fh, t):
        with self._lock:
            os.fsync(fh.fileno())
            t.join()
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "no-block")
    assert len(viols) == 2
    # str.join is not a blocking call; outside the lock nothing flags
    good = FIXTURE_LOCKS + """
import os

class E2(Engine):
    def fine(self, fh, t, parts):
        with self._lock:
            x = ", ".join(parts)
        os.fsync(fh.fileno())
        t.join()
        return x
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "no-block") == []


def test_no_block_skips_nested_defs():
    """A closure DEFINED under the lock does not RUN under it."""
    src = FIXTURE_LOCKS + """
import os

class E3(Engine):
    def fine(self, fh):
        with self._lock:
            def later():
                os.fsync(fh.fileno())
        return later
"""
    assert _rules(linter.lint_source(src, PKG_PATH), "no-block") == []


def test_churn_send_rule():
    bad = """
def broadcast(peer, msg):
    if peer.connection is not None:
        peer.connection.send(msg)
        peer.connection.open_channel("doc").send(msg)
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "churn-send")
    assert len(viols) == 2 and "try_send" in viols[0].msg
    good = """
def broadcast(peer, msg):
    peer.try_send("doc", msg)
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "churn-send") == []
    # NetworkPeer itself implements the idiom
    assert (
        _rules(
            linter.lint_source(bad, "hypermerge_tpu/net/peer.py"),
            "churn-send",
        )
        == []
    )


def test_env_registry_rule():
    bad = """
import os
x = os.environ.get("HM_NOT_A_REAL_KNOB", "1")
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "env-registry")
    assert len(viols) == 1 and "undeclared" in viols[0].msg
    drift = """
import os
x = os.environ.get("HM_FSYNC", "2")
"""
    viols = _rules(linter.lint_source(drift, PKG_PATH), "env-registry")
    assert len(viols) == 1 and "drifts" in viols[0].msg
    good = """
import os
x = os.environ.get("HM_FSYNC", "0")
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "env-registry") == []


def test_telemetry_name_rule():
    bad = """
from hypermerge_tpu import telemetry
c = telemetry.counter("Frames_TX")
g = telemetry.gauge("depth")
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "telemetry-name")
    assert len(viols) == 2
    good = """
from hypermerge_tpu import telemetry
c = telemetry.counter("net.tcp.frames_tx")
d = {k: telemetry.counter("live." + k) for k in ("a", "b")}
h = model.counter("NotARegistryCall")
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "telemetry-name") == []


def test_raw_lock_rule():
    bad = """
import threading
a = threading.Lock()
b = threading.RLock()
c = threading.Condition()
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "raw-lock")
    assert len(viols) == 3
    good = """
import threading
from hypermerge_tpu.analysis.lockdep import make_rlock
lk = make_rlock("util.queue")
cv = threading.Condition(lk)
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "raw-lock") == []
    # outside the package (tests, tools) raw locks are fine
    assert _rules(linter.lint_source(bad, "tools/x.py"), "raw-lock") == []


FIXTURE_GUARDED = """
from hypermerge_tpu.analysis.lockdep import make_rlock

class ResidencyCache:
    def __init__(self):
        self._lock = make_rlock("serve.cache")
        self._entries = {}
        self._bytes = 0
"""


def test_guarded_attr_rule():
    bad = FIXTURE_GUARDED + """
    def bad_write(self, k, v):
        self._entries[k] = v

    def bad_mutate(self):
        self._entries.clear()

    def bad_read(self):
        return list(self._entries)

    def bad_bytes_write(self):
        self._bytes = 0
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "guarded-attr")
    msgs_ = [v.msg for v in viols]
    assert len(viols) == 4, msgs_
    assert sum("writes" in m for m in msgs_) == 3
    assert sum("reads" in m for m in msgs_) == 1
    good = FIXTURE_GUARDED + """
    def fine(self, k, v):
        with self._lock:
            self._entries[k] = v
            self._entries.clear()
            return list(self._entries)

    def bytes_snapshot(self):
        return self._bytes  # atomic_read_ok: lone read is declared

    def _note_evicted(self, k):
        # guards.REQUIRES: the whole body runs with serve.cache held
        self._entries.pop(k, None)
"""
    assert _rules(linter.lint_source(good, PKG_PATH), "guarded-attr") == []


def test_guarded_attr_init_only_and_closures():
    bad = """
class ReadBatcher:
    def __init__(self):
        self._cap = 4  # exempt: not shared yet

    def later(self):
        self._cap = 8
"""
    viols = _rules(linter.lint_source(bad, PKG_PATH), "guarded-attr")
    assert len(viols) == 1 and "init-only" in viols[0].msg
    # a closure defined under the `with` does not RUN under it — its
    # guarded writes must still be flagged
    closure = FIXTURE_GUARDED + """
    def leaks(self, k):
        with self._lock:
            def later():
                self._entries.pop(k, None)
        return later
"""
    viols = _rules(linter.lint_source(closure, PKG_PATH), "guarded-attr")
    assert len(viols) == 1 and "writes" in viols[0].msg


def test_guarded_attr_suppression_and_other_classes():
    src = FIXTURE_GUARDED + """
    def noted(self):
        self._entries.clear()  # lint: allow(guarded-attr) — fixture exercising the suppression path
"""
    viols = linter.lint_source(src, PKG_PATH)
    sup = _rules(viols, "guarded-attr", suppressed=True)
    assert len(sup) == 1 and linter.unsuppressed(viols) == []
    # an undeclared class with the same attribute names is untouched
    other = """
class SomethingElse:
    def write(self, k, v):
        self._entries = {k: v}
"""
    assert _rules(linter.lint_source(other, PKG_PATH), "guarded-attr") == []


def test_guards_registry_stale_detection():
    """A manifest entry nothing in the scanned tree accesses is
    flagged stale (the anti-rot twin of the env-registry rule)."""
    out = []
    linter._check_guards_registry(out, set(), linter.repo_root())
    stale = [v for v in out if "stale guard entry" in v.msg]
    assert len(stale) == len(guards.BY_CLS_ATTR)
    out2 = []
    linter._check_guards_registry(
        out2, set(guards.BY_CLS_ATTR), linter.repo_root()
    )
    assert [v for v in out2 if "stale" in v.msg] == []


def test_inline_suppression():
    src = """
import threading
a = threading.Lock()  # lint: allow(raw-lock) — fixture exercising the suppression path
"""
    viols = linter.lint_source(src, PKG_PATH)
    sup = _rules(viols, "raw-lock", suppressed=True)
    assert len(sup) == 1 and "fixture" in sup[0].justification
    assert linter.unsuppressed(viols) == []
    # a justification is REQUIRED
    bare = """
import threading
a = threading.Lock()  # lint: allow(raw-lock)
"""
    viols = linter.lint_source(bare, PKG_PATH)
    assert _rules(viols, "raw-lock") != []
    assert _rules(viols, "suppression") != []


def test_file_suppression_and_stale(monkeypatch):
    entry = suppmod.Suppression(
        "raw-lock", "hypermerge_tpu/_fixture.py", "threading.Lock",
        "fixture: exercising the file-suppression path",
    )
    monkeypatch.setattr(suppmod, "SUPPRESSIONS", (entry,))
    src = "import threading\na = threading.Lock()\n"
    viols = linter.lint_source(src, PKG_PATH)
    assert linter.unsuppressed(viols) == []
    # the same entry against a clean tree is STALE and flagged
    viols = linter.lint_source("x = 1\n", PKG_PATH)
    stale = _rules(viols, "suppression")
    assert len(stale) == 1 and "stale" in stale[0].msg


# ---------------------------------------------------------------------------
# runtime lockdep


@pytest.fixture
def dep():
    """Isolated lockdep session: enabled, empty graph; restored after."""
    was = lockdep.enabled()
    lockdep.enable(True)
    lockdep.reset()
    yield lockdep
    lockdep.enable(was)
    lockdep.reset()


def test_factories_plain_when_disabled():
    was = lockdep.enabled()
    lockdep.enable(False)
    try:
        assert not isinstance(
            lockdep.make_rlock("live.engine"), lockdep.DepLock
        )
        assert not isinstance(lockdep.make_lock("doc"), lockdep.DepLock)
    finally:
        lockdep.enable(was)


def test_lockdep_reports_ab_ba_cycle_without_deadlock(dep):
    """The acceptance fixture: thread 1 nests A->B, thread 2 nests
    B->A — never concurrently, so no deadlock CAN fire — and the
    detector still reports the potential cycle."""
    a = dep.make_rlock("net.network")
    b = dep.make_rlock("net.swarm")
    t1_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        t1_done.set()

    def t2():
        t1_done.wait(5)
        with b:
            with a:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start(); th2.start()
    th1.join(5); th2.join(5)
    assert not th1.is_alive() and not th2.is_alive()
    rep = dep.report()
    assert len(rep["cycles"]) == 1
    cyc = rep["cycles"][0]["cycle"]
    assert set(cyc) == {"net.network", "net.swarm"}
    with pytest.raises(AssertionError):
        dep.assert_clean()


def test_lockdep_order_and_leaf_violations(dep):
    eng = dep.make_rlock("live.engine")
    sql = dep.make_rlock("store.sql")
    with sql:
        with eng:  # store.sql (60) held while taking live.engine (10)
            pass
    leaf = dep.make_rlock("store.integrity")
    feed = dep.make_rlock("store.feed")
    with leaf:
        with feed:
            pass
    kinds = sorted(v["kind"] for v in dep.report()["violations"])
    assert kinds == ["leaf", "order", "order"]  # leaf inversion is both


def test_lockdep_blocking_violation(dep):
    eng = dep.make_rlock("live.engine")
    dep.blocking("fsync")  # nothing held: fine
    assert dep.report()["violations"] == []
    with eng:
        dep.blocking("fsync", "/tmp/x")
    viol = dep.report()["violations"]
    assert len(viol) == 1 and viol[0]["kind"] == "blocking"
    with pytest.raises(AssertionError):
        dep.assert_clean()
    dep.assert_clean(allow_kinds=("blocking",))


def test_lockdep_rlock_reentrancy_no_self_edge(dep):
    lk = dep.make_rlock("repo")
    with lk:
        with lk:
            pass
    rep = dep.report()
    assert rep["edges"] == [] and rep["violations"] == []


def test_lockdep_unknown_class(dep):
    dep.make_rlock("definitely.not.declared")
    viol = dep.report()["violations"]
    assert len(viol) == 1 and viol[0]["kind"] == "unknown-class"


def test_lockdep_condition_wait_releases_held_state(dep):
    """Condition.wait over a DepLock pops the held entry (a waiter
    holds nothing) and re-pushes on wakeup — no phantom edges."""
    cv = dep.make_condition("util.debounce")
    other = dep.make_rlock("util.queue")

    def waiter():
        with cv:
            cv.wait(timeout=2)

    t = threading.Thread(target=waiter)
    with cv:
        t.start()
        # give the waiter time to block; it must NOT hold the lock
        # class while waiting
    t.join(5)
    assert not t.is_alive()
    with other:
        pass
    assert dep.report()["violations"] == []


def test_registry_name_assert_under_lockdep(dep):
    from hypermerge_tpu.telemetry import REGISTRY

    with pytest.raises(ValueError):
        REGISTRY.counter("BadFlatName")
    REGISTRY.counter("live.test_lockdep_name_ok")  # dotted: fine


# ---------------------------------------------------------------------------
# runtime racedep (HM_RACEDEP lockset detection)


@pytest.fixture
def race(dep):
    """Isolated racedep session on top of the `dep` fixture: guard
    descriptors installed, removed (and lockdep restored) after.
    install_racedep() is idempotent and returns only the NEWLY
    wrapped count — 0 when a full-suite HM_RACEDEP=1 run already
    auto-installed the descriptors at an earlier repo construction —
    so the assertion is on the installed STATE."""
    lockdep.install_racedep()
    assert lockdep.racedep_enabled()
    yield dep
    lockdep.uninstall_racedep()


def test_racedep_reports_seeded_violation_without_deadlock(race):
    """Two threads, one takes the declared guard and one does not —
    no deadlock CAN fire (the accesses never block each other), and
    the lockset detector still reports the guard violation with both
    stacks."""
    from hypermerge_tpu.serve.resident import ResidencyCache

    c = ResidencyCache()

    def locked():
        with c._lock:
            c._use += 1

    def unlocked():
        c._use += 1  # violates the declared serve.cache guard

    t1 = threading.Thread(target=locked)
    t1.start(); t1.join(5)
    t2 = threading.Thread(target=unlocked)
    t2.start(); t2.join(5)
    assert not t1.is_alive() and not t2.is_alive()
    viol = [
        v for v in race.report()["violations"] if v["kind"] == "lockset"
    ]
    assert len(viol) == 1
    msg = viol[0]["msg"]
    assert "ResidencyCache._use" in msg and "serve.cache" in msg
    # both stacks in the report, and the first-shared-access witness
    # leads with the ACCESSING code line, not threading internals
    site = msg.split("first shared access at ", 1)[1]
    assert site.split(" <- ", 1)[0].startswith("test_analysis.py:")
    with pytest.raises(AssertionError):
        race.assert_clean(allow_kinds=("blocking",))


def test_racedep_consistent_guard_is_clean(race):
    """The same two-thread churn WITH the guard held everywhere stays
    clean — the candidate lockset never empties."""
    from hypermerge_tpu.serve.resident import ResidencyCache

    c = ResidencyCache()

    def worker():
        for _ in range(20):
            with c._lock:
                c._use += 1

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
    assert [
        v for v in race.report()["violations"] if v["kind"] == "lockset"
    ] == []


def test_racedep_descriptor_preserves_attribute_semantics(race):
    """Instrumented attributes still read/write/delete like plain
    instance attributes (values live in __dict__), and a missing
    attribute still raises AttributeError."""
    from hypermerge_tpu.storage.durability import DurabilityManager

    d = DurabilityManager()
    assert d._closed is False
    with d._lock:
        d._closed = True
    assert d._closed is True
    obj = DurabilityManager.__new__(DurabilityManager)
    with pytest.raises(AttributeError):
        obj._dirty
    lockdep.uninstall_racedep()
    assert d._closed is True  # plain access resumes after uninstall


def test_blocking_seam_accumulates_per_class_debt(dep):
    """`with blocking(...)` charges the blocked wall time to every
    held lock class — the `lock.held_blocking_ms.*` series the
    write-plane split is gated on."""
    import time as _time

    from hypermerge_tpu import telemetry

    eng = dep.make_rlock("live.engine")
    before = telemetry.snapshot().get(
        "lock.held_blocking_ms.live_engine", 0.0
    )
    with eng:
        with dep.blocking("fsync", "fixture"):
            _time.sleep(0.01)
    after = telemetry.snapshot().get(
        "lock.held_blocking_ms.live_engine", 0.0
    )
    assert after - before >= 5.0  # ms
    # the violation (blocking under a no-block lock) is still recorded
    kinds = [v["kind"] for v in dep.report()["violations"]]
    assert "blocking" in kinds


# ---------------------------------------------------------------------------
# regression: the sql<->cursors fix (hydration vs delete)


def test_cursor_hydration_discards_snapshot_a_delete_raced():
    """CursorStore._ensure_hydrated queries SQLite BEFORE taking the
    mirror lock (the lock-order fix); a delete_doc landing between the
    query and the merge must invalidate the snapshot, not be
    resurrected by it."""
    from hypermerge_tpu.storage.sql import SqlDatabase
    from hypermerge_tpu.storage.stores import CursorStore

    db = SqlDatabase(":memory:")
    seed = CursorStore(db)
    seed.update("r", "docX", {"a1": 5})
    seed.update("r", "docY", {"a2": 3})

    store = CursorStore(db)  # fresh mirror, unhydrated
    real_query = db.query
    raced = []

    def racing_query(sql, params=()):
        rows = real_query(sql, params)
        if not raced and "FROM cursors" in sql:
            raced.append(True)
            store.delete_doc("r", "docX")  # lands mid-hydration
        return rows

    db.query = racing_query
    try:
        assert store.get("r", "docX") == {}  # NOT the stale {"a1": 5}
        assert store.get("r", "docY") == {"a2": 3}
        assert store.docs_with_actor("r", "a1") == []
    finally:
        db.query = real_query


# ---------------------------------------------------------------------------
# CLI


def test_lint_cli_json():
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py"), "--json"],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["n_unsuppressed"] == 0


def test_lint_cli_env_table():
    out = subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "lint.py"),
            "--env-table",
        ],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert out.returncode == 0
    assert "HM_LOCKDEP" in out.stdout and "HM_FSYNC" in out.stdout
    assert "HM_RACEDEP" in out.stdout


def test_lint_cli_guards_table():
    out = subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "lint.py"),
            "--guards-table",
        ],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert out.returncode == 0
    assert "`LiveApplyEngine`" in out.stdout
    assert "`live.engine`" in out.stdout
    assert "atomic_read_ok" in out.stdout
    # the README carries exactly this generated table (drift is a
    # lint violation, same contract as the env table)
    with open(os.path.join(ROOT, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    for line in out.stdout.strip().splitlines():
        assert line in readme, f"README guard table drifted: {line}"
