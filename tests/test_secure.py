"""Transport encryption: kx handshake + authenticated frames
(VERDICT r3 missing #2 — reference wraps every socket in noise,
src/PeerConnection.ts:36)."""

import socket
import struct
import time

import pytest

from hypermerge_tpu import native
from hypermerge_tpu.net.secure import SecureSession
from hypermerge_tpu.net.tcp import TcpDuplex, TcpSwarm
from hypermerge_tpu.utils import chacha

_HDR = struct.Struct("<I")


class TestPrimitives:
    def test_pure_x25519_agrees_with_itself(self):
        sk1, sk2 = b"\x01" * 32, b"\x02" * 32
        pk1 = chacha.x25519_base(sk1)
        pk2 = chacha.x25519_base(sk2)
        assert chacha.x25519(sk1, pk2) == chacha.x25519(sk2, pk1)

    def test_rfc7748_vector(self):
        # RFC 7748 §5.2 test vector 1
        k = bytes.fromhex(
            "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
        )
        u = bytes.fromhex(
            "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
        )
        want = bytes.fromhex(
            "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
        )
        assert chacha.x25519(k, u) == want

    def test_aead_roundtrip_and_tamper(self):
        key, nonce = b"k" * 32, b"n" * 12
        ct = chacha.aead_encrypt(key, nonce, b"secret payload")
        assert chacha.aead_decrypt(key, nonce, ct) == b"secret payload"
        bad = ct[:-1] + bytes([ct[-1] ^ 1])
        assert chacha.aead_decrypt(key, nonce, bad) is None

    @pytest.mark.skipif(not native.available(), reason="no native layer")
    def test_pure_interops_with_native(self):
        sk = b"\x07" * 32
        assert chacha.x25519_base(sk) == native.x25519_base(sk)
        key, nonce = b"K" * 32, b"N" * 12
        msg = b"cross-implementation frame"
        assert native.aead_decrypt(
            key, nonce, chacha.aead_encrypt(key, nonce, msg)
        ) == msg
        assert chacha.aead_decrypt(
            key, nonce, native.aead_encrypt(key, nonce, msg)
        ) == msg


class TestSecureSession:
    def _pair(self):
        c, s = SecureSession(True), SecureSession(False)
        c.complete(s.handshake_bytes)
        s.complete(c.handshake_bytes)
        return c, s

    def test_roundtrip_both_directions(self):
        c, s = self._pair()
        assert s.decrypt(c.encrypt(b"hello")) == b"hello"
        assert c.decrypt(s.encrypt(b"world")) == b"world"
        # counters advance: repeated frames differ on the wire
        w1, w2 = c.encrypt(b"same"), c.encrypt(b"same")
        assert w1 != w2
        assert s.decrypt(w1) == b"same" and s.decrypt(w2) == b"same"

    def test_tampered_frame_rejected(self):
        c, s = self._pair()
        wire = bytearray(c.encrypt(b"payload"))
        wire[3] ^= 0x40
        assert s.decrypt(bytes(wire)) is None

    def test_wire_is_not_plaintext(self):
        c, s = self._pair()
        assert b"payload" not in c.encrypt(b'{"x": "payload"}')

    def test_low_order_handshake_key_rejected(self):
        s = SecureSession(False)
        with pytest.raises(ValueError):
            s.complete(b"\x00" * 32)  # neutral-element point -> q = 0


class TestTcpEncrypted:
    def _duplex_pair(self):
        a, b = socket.socketpair()
        import threading

        out = {}

        def server():
            out["s"] = TcpDuplex(b, is_client=False)

        t = threading.Thread(target=server)
        t.start()
        da = TcpDuplex(a, is_client=True)
        t.join()
        return da, out["s"], a, b

    def test_encrypted_roundtrip(self):
        da, db, _a, _b = self._duplex_pair()
        got = []
        db.on_message(got.append)
        da.send({"secret": "value"})
        for _ in range(100):
            if got:
                break
            time.sleep(0.01)
        assert got == [{"secret": "value"}]
        da.close()
        db.close()

    def test_tampered_ciphertext_drops_connection(self):
        da, db, a, _b = self._duplex_pair()
        got = []
        db.on_message(got.append)
        # inject a forged frame directly on the raw socket, bypassing
        # da's session: authentication must fail and db must close
        forged = b"\x00" * 24
        a.sendall(_HDR.pack(len(forged)) + forged)
        for _ in range(200):
            if db.closed:
                break
            time.sleep(0.01)
        assert db.closed
        assert got == []
        da.close()

    def test_two_repos_converge_over_encrypted_tcp(self):
        from hypermerge_tpu.repo import Repo
        from hypermerge_tpu.utils.ids import validate_doc_url

        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"enc": "rypted"})
        doc_id = validate_doc_url(url)
        h = rb.open(url)
        for _ in range(200):
            doc = rb.back.docs.get(doc_id)
            if doc is not None and doc._announced:
                break
            time.sleep(0.02)
        assert h.value()["enc"] == "rypted"
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()


class TestAuthenticatedHandshake:
    """Identity auth (VERDICT r4 missing #1): the repo's static ed25519
    keypair signs the ephemeral handshake transcript — noise-peer's XX
    upgrade over the anonymous NN exchange."""

    def _session_pair(self):
        a, b = SecureSession(True), SecureSession(False)
        a.complete(b.handshake_bytes)
        b.complete(a.handshake_bytes)
        return a, b

    def test_auth_frame_roundtrip_pins_identity(self):
        from hypermerge_tpu.utils import keys as keymod

        pa, pb = keymod.create(), keymod.create()
        sa, sb = self._session_pair()
        seed_a = keymod.decode_pair(pa).secret_key
        seed_b = keymod.decode_pair(pb).secret_key
        assert sb.verify_auth(sa.auth_frame(seed_a))
        assert sa.verify_auth(sb.auth_frame(seed_b))
        assert sb.peer_identity == pa.public_key
        assert sa.peer_identity == pb.public_key

    def test_auth_frame_role_bound(self):
        """A reflected auth frame (our own, or one signed for the wrong
        role) never verifies — mirror attacks fail."""
        from hypermerge_tpu.utils import keys as keymod

        pa = keymod.create()
        seed = keymod.decode_pair(pa).secret_key
        sa, sb = self._session_pair()
        frame = sa.auth_frame(seed)  # signed with role C
        assert not sa.verify_auth(frame)  # reflected back to its maker
        assert sb.verify_auth(frame)

    def test_channel_binding_unique_per_session(self):
        sa, sb = self._session_pair()
        sc, sd = self._session_pair()
        assert sa.channel_binding == sb.channel_binding
        assert sa.channel_binding != sc.channel_binding

    def test_mitm_key_substitution_fails_closed(self):
        """The VERDICT r4 MITM scenario: an active attacker terminates
        the crypto on both legs with its own ephemerals and relays every
        frame (including the victims' auth frames). The signatures cover
        the ephemeral transcript each VICTIM saw — which differs from
        what the far side saw — so verify_auth fails on both ends."""
        from hypermerge_tpu.utils import keys as keymod

        pa, pb = keymod.create(), keymod.create()
        seed_a = keymod.decode_pair(pa).secret_key
        seed_b = keymod.decode_pair(pb).secret_key

        alice = SecureSession(True)     # dials who she thinks is Bob
        mitm_srv = SecureSession(False)  # attacker's leg toward Alice
        mitm_cli = SecureSession(True)   # attacker's leg toward Bob
        bob = SecureSession(False)

        alice.complete(mitm_srv.handshake_bytes)
        mitm_srv.complete(alice.handshake_bytes)
        mitm_cli.complete(bob.handshake_bytes)
        bob.complete(mitm_cli.handshake_bytes)

        # attacker relays the auth frames across its two sessions
        alice_auth = mitm_srv.decrypt(
            alice.encrypt(alice.auth_frame(seed_a))
        )
        relayed_to_bob = bob.decrypt(mitm_cli.encrypt(alice_auth))
        assert not bob.verify_auth(relayed_to_bob)

        bob_auth = mitm_cli.decrypt(bob.encrypt(bob.auth_frame(seed_b)))
        relayed_to_alice = alice.decrypt(mitm_srv.encrypt(bob_auth))
        assert not alice.verify_auth(relayed_to_alice)

    def test_tcp_mitm_relay_drops_both_sides(self):
        """End-to-end over sockets: a crypto-terminating relay between
        two identity-bearing TcpDuplexes; both transports must close
        during the handshake."""
        import threading

        from hypermerge_tpu.utils import keys as keymod

        seed_a = keymod.decode_pair(keymod.create()).secret_key
        seed_b = keymod.decode_pair(keymod.create()).secret_key

        a_sock, m1 = socket.socketpair()
        m2, b_sock = socket.socketpair()

        def relay_leg(sess, sock_in, other_sess, sock_out, n_frames):
            # read n encrypted frames, re-encrypt on the other leg
            def read_exact(s, n):
                buf = b""
                while len(buf) < n:
                    c = s.recv(n - len(buf))
                    if not c:
                        return None
                    buf += c
                return buf

            for _ in range(n_frames):
                hdr = read_exact(sock_in, 4)
                if hdr is None:
                    return
                (size,) = struct.unpack("<I", hdr)
                wire = read_exact(sock_in, size)
                if wire is None:
                    return
                plain = sess.decrypt(wire)
                if plain is None:
                    return
                out = other_sess.encrypt(plain)
                try:
                    sock_out.sendall(struct.pack("<I", len(out)) + out)
                except OSError:
                    return

        def mitm():
            srv = SecureSession(False)  # toward Alice (she dials)
            cli = SecureSession(True)   # toward Bob

            def read_exact(s, n):
                buf = b""
                while len(buf) < n:
                    c = s.recv(n - len(buf))
                    if not c:
                        return None
                    buf += c
                return buf

            # ephemeral exchange, substituting our own keys; the MITM
            # must keep the auth offer bit set — clearing it would
            # downgrade to an anonymous session (the documented
            # HM_NET_AUTH=require tradeoff), not break auth
            hdr = read_exact(m1, 4)
            alice_frame = read_exact(m1, struct.unpack("<I", hdr)[0])
            m1.sendall(struct.pack("<I", 33) + b"\x01" + srv.handshake_bytes)
            srv.complete(alice_frame[-32:])
            m2.sendall(struct.pack("<I", 33) + b"\x01" + cli.handshake_bytes)
            hdr = read_exact(m2, 4)
            bob_frame = read_exact(m2, struct.unpack("<I", hdr)[0])
            cli.complete(bob_frame[-32:])
            # relay the (encrypted) auth frames both ways
            t = threading.Thread(
                target=relay_leg, args=(srv, m1, cli, m2, 4), daemon=True
            )
            t.start()
            relay_leg(cli, m2, srv, m1, 4)
            t.join(timeout=5)

        mt = threading.Thread(target=mitm, daemon=True)
        mt.start()
        out = {}

        def bob_side():
            out["b"] = TcpDuplex(b_sock, is_client=False, identity=seed_b)

        bt = threading.Thread(target=bob_side, daemon=True)
        bt.start()
        da = TcpDuplex(a_sock, is_client=True, identity=seed_a)
        bt.join(timeout=10)
        mt.join(timeout=10)
        assert da.closed
        assert out["b"].closed

    def test_repo_peers_pin_each_others_identity(self):
        """Two repos over authenticated TCP: each peer's transport-proven
        identity IS the other repo's id."""
        from hypermerge_tpu.repo import Repo

        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        try:
            ra.set_swarm(sa)
            rb.set_swarm(sb)
            sb.connect(sa.address)
            for _ in range(200):
                if ra.back.network.peers and rb.back.network.peers:
                    break
                time.sleep(0.02)
            (pa,) = ra.back.network.peers.values()
            (pb,) = rb.back.network.peers.values()
            assert pa.connection.peer_identity == rb.back.id
            assert pb.connection.peer_identity == ra.back.id
        finally:
            ra.close()
            rb.close()
            sa.destroy()
            sb.destroy()

    def test_claimed_peer_id_must_match_proven_identity(self):
        """Network rejects an Info whose peerId differs from the
        transport-authenticated identity (impersonation)."""
        from hypermerge_tpu.net.network import Network

        class FakeDuplex:
            peer_identity = "PROVEN-IDENTITY"

            def __init__(self):
                self.sent = []
                self.closed = False

            def on_message(self, cb):
                self._cb = cb

            def on_close(self, cb):
                pass

            def send(self, msg):
                self.sent.append(msg)

            def close(self):
                self.closed = True

        class FakeBackend:
            id = "ME"

            class feeds:
                @staticmethod
                def known_discovery_ids():
                    return []

        net = Network(FakeBackend())
        from hypermerge_tpu.net.swarm import ConnectionDetails

        dup = FakeDuplex()
        net._on_connection(dup, ConnectionDetails(client=False))
        # the peer CLAIMS a different repo id than it proved
        dup._cb({"ch": "NetworkBus",
                 "m": {"type": "Info", "peerId": "SOMEONE-ELSE"}})
        assert dup.closed
        assert "SOMEONE-ELSE" not in net.peers

        # and a matching claim is accepted
        dup2 = FakeDuplex()
        net._on_connection(dup2, ConnectionDetails(client=False))
        dup2._cb({"ch": "NetworkBus",
                  "m": {"type": "Info", "peerId": "PROVEN-IDENTITY"}})
        assert not dup2.closed
        assert "PROVEN-IDENTITY" in net.peers

    def test_mixed_pair_falls_back_to_anonymous(self):
        """An identity-bearing endpoint still interoperates with an
        identity-less one: the session downgrades to anonymous instead
        of deadlocking or dropping (code-review r5 finding 1)."""
        import threading

        from hypermerge_tpu.utils import keys as keymod

        seed = keymod.decode_pair(keymod.create()).secret_key
        a_sock, b_sock = socket.socketpair()
        out = {}

        def anon_side():
            out["b"] = TcpDuplex(b_sock, is_client=False, identity=None)

        t = threading.Thread(target=anon_side, daemon=True)
        t.start()
        da = TcpDuplex(a_sock, is_client=True, identity=seed)
        t.join(timeout=10)
        db = out["b"]
        assert not da.closed and not db.closed
        assert da.peer_identity is None  # anonymous session
        got = []
        db.on_message(got.append)
        da.send({"mixed": True})
        for _ in range(100):
            if got:
                break
            time.sleep(0.01)
        assert got == [{"mixed": True}]
        da.close()
        db.close()

    def test_require_mode_rejects_unauthenticated_peer(self, monkeypatch):
        """HM_NET_AUTH=require: an identity-less endpoint fails closed
        (no anonymous fallback), and so does the peer talking to it."""
        import threading

        from hypermerge_tpu.utils import keys as keymod

        monkeypatch.setenv("HM_NET_AUTH", "require")
        seed = keymod.decode_pair(keymod.create()).secret_key
        a_sock, b_sock = socket.socketpair()
        out = {}

        def anon_side():
            out["b"] = TcpDuplex(b_sock, is_client=False, identity=None)

        t = threading.Thread(target=anon_side, daemon=True)
        t.start()
        da = TcpDuplex(a_sock, is_client=True, identity=seed)
        t.join(timeout=10)
        assert out["b"].closed  # refuses to run without an identity
        assert da.closed  # its peer drops too (handshake never answered)
