"""Transport encryption: kx handshake + authenticated frames
(VERDICT r3 missing #2 — reference wraps every socket in noise,
src/PeerConnection.ts:36)."""

import socket
import struct
import time

import pytest

from hypermerge_tpu import native
from hypermerge_tpu.net.secure import SecureSession
from hypermerge_tpu.net.tcp import TcpDuplex, TcpSwarm
from hypermerge_tpu.utils import chacha

_HDR = struct.Struct("<I")


class TestPrimitives:
    def test_pure_x25519_agrees_with_itself(self):
        sk1, sk2 = b"\x01" * 32, b"\x02" * 32
        pk1 = chacha.x25519_base(sk1)
        pk2 = chacha.x25519_base(sk2)
        assert chacha.x25519(sk1, pk2) == chacha.x25519(sk2, pk1)

    def test_rfc7748_vector(self):
        # RFC 7748 §5.2 test vector 1
        k = bytes.fromhex(
            "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
        )
        u = bytes.fromhex(
            "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
        )
        want = bytes.fromhex(
            "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
        )
        assert chacha.x25519(k, u) == want

    def test_aead_roundtrip_and_tamper(self):
        key, nonce = b"k" * 32, b"n" * 12
        ct = chacha.aead_encrypt(key, nonce, b"secret payload")
        assert chacha.aead_decrypt(key, nonce, ct) == b"secret payload"
        bad = ct[:-1] + bytes([ct[-1] ^ 1])
        assert chacha.aead_decrypt(key, nonce, bad) is None

    @pytest.mark.skipif(not native.available(), reason="no native layer")
    def test_pure_interops_with_native(self):
        sk = b"\x07" * 32
        assert chacha.x25519_base(sk) == native.x25519_base(sk)
        key, nonce = b"K" * 32, b"N" * 12
        msg = b"cross-implementation frame"
        assert native.aead_decrypt(
            key, nonce, chacha.aead_encrypt(key, nonce, msg)
        ) == msg
        assert chacha.aead_decrypt(
            key, nonce, native.aead_encrypt(key, nonce, msg)
        ) == msg


class TestSecureSession:
    def _pair(self):
        c, s = SecureSession(True), SecureSession(False)
        c.complete(s.handshake_bytes)
        s.complete(c.handshake_bytes)
        return c, s

    def test_roundtrip_both_directions(self):
        c, s = self._pair()
        assert s.decrypt(c.encrypt(b"hello")) == b"hello"
        assert c.decrypt(s.encrypt(b"world")) == b"world"
        # counters advance: repeated frames differ on the wire
        w1, w2 = c.encrypt(b"same"), c.encrypt(b"same")
        assert w1 != w2
        assert s.decrypt(w1) == b"same" and s.decrypt(w2) == b"same"

    def test_tampered_frame_rejected(self):
        c, s = self._pair()
        wire = bytearray(c.encrypt(b"payload"))
        wire[3] ^= 0x40
        assert s.decrypt(bytes(wire)) is None

    def test_wire_is_not_plaintext(self):
        c, s = self._pair()
        assert b"payload" not in c.encrypt(b'{"x": "payload"}')

    def test_low_order_handshake_key_rejected(self):
        s = SecureSession(False)
        with pytest.raises(ValueError):
            s.complete(b"\x00" * 32)  # neutral-element point -> q = 0


class TestTcpEncrypted:
    def _duplex_pair(self):
        a, b = socket.socketpair()
        import threading

        out = {}

        def server():
            out["s"] = TcpDuplex(b, is_client=False)

        t = threading.Thread(target=server)
        t.start()
        da = TcpDuplex(a, is_client=True)
        t.join()
        return da, out["s"], a, b

    def test_encrypted_roundtrip(self):
        da, db, _a, _b = self._duplex_pair()
        got = []
        db.on_message(got.append)
        da.send({"secret": "value"})
        for _ in range(100):
            if got:
                break
            time.sleep(0.01)
        assert got == [{"secret": "value"}]
        da.close()
        db.close()

    def test_tampered_ciphertext_drops_connection(self):
        da, db, a, _b = self._duplex_pair()
        got = []
        db.on_message(got.append)
        # inject a forged frame directly on the raw socket, bypassing
        # da's session: authentication must fail and db must close
        forged = b"\x00" * 24
        a.sendall(_HDR.pack(len(forged)) + forged)
        for _ in range(200):
            if db.closed:
                break
            time.sleep(0.01)
        assert db.closed
        assert got == []
        da.close()

    def test_two_repos_converge_over_encrypted_tcp(self):
        from hypermerge_tpu.repo import Repo
        from hypermerge_tpu.utils.ids import validate_doc_url

        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"enc": "rypted"})
        doc_id = validate_doc_url(url)
        h = rb.open(url)
        for _ in range(200):
            doc = rb.back.docs.get(doc_id)
            if doc is not None and doc._announced:
                break
            time.sleep(0.02)
        assert h.value()["enc"] == "rypted"
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()
