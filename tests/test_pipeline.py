"""Streaming slab pipeline (backend/pipeline.py): equivalence with the
serial twin, failure-path hygiene, and round-robin device dispatch.

The pipeline restructures the bulk cold open from sum(stages) to
~max(stage) by overlapping IO, pack, dispatch, and fetch across slabs
— but it must be a pure SCHEDULING change: `HM_PIPELINE=1` and
`HM_PIPELINE=0` must produce byte-identical summary arrays, identical
summary-memo contents, and identical doc/fast/fallback accounting. A
stage failure must fail the whole load as a unit: no hung worker
threads, no pending device refs, queues drained.
"""

import random
import shutil
import threading
import time

import pytest

from helpers import plainify
from hypermerge_tpu.backend.pipeline import PipelineError
from hypermerge_tpu.models import Counter, Text
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils.ids import validate_doc_url


def _make_corpus(path, n_docs=14, seed=7):
    """Single-writer docs of varied size/shape (maps, text, counters)
    so slabs bucket at different [D, N] shapes and every value lane is
    exercised."""
    r = random.Random(seed)
    repo = Repo(path=str(path))
    urls = []
    for i in range(n_docs):
        u = repo.create({"i": i, "t": Text(f"doc{i}:"), "hits": Counter(0)})
        for k in range(r.randrange(1, 9)):
            kind = r.randrange(3)
            if kind == 0:
                repo.change(
                    u, lambda d, k=k: d.__setitem__(f"k{k}", k * 3)
                )
            elif kind == 1:
                repo.change(
                    u, lambda d, k=k: d["t"].insert(0, f"<{k}>")
                )
            else:
                repo.change(u, lambda d: d.increment("hits", 2))
        urls.append(u)
    want = {u: plainify(repo.doc(u)) for u in urls}
    repo.close()
    return urls, want


def _add_gap_doc(path):
    """One doc with a seq gap in its feed: must fall back to host
    replay in BOTH modes (fallback accounting equivalence)."""
    from hypermerge_tpu.crdt.change import Action, Change, Op, ROOT
    from hypermerge_tpu.storage import block as blockmod

    repo = Repo(path=str(path))
    url = repo.create({"gap": True})
    doc_id = validate_doc_url(url)
    actor = repo.back.actors[doc_id]
    head = actor.seq_head
    max_op = max(
        c.max_op for c in actor.changes_in_window(0, float("inf"))
    )
    actor.feed._append_raw(
        blockmod.pack(
            Change(
                actor=doc_id,
                seq=head + 2,  # head+1 never written
                start_op=max_op + 1,
                deps={},
                ops=(Op(action=Action.SET, obj=ROOT, key="late", value=1),),
            ).to_json()
        )
    )
    repo.close()
    return url


def _doc_summary_bytes(summ, doc_id):
    arrays, j = summ.arrays(doc_id)
    out = {
        k: arrays[k][j].tobytes()
        for k in ("map_winner", "elem_live", "elem_order")
    }
    out["n_live"] = int(arrays["n_live_elems"][j])
    out["n_map"] = int(arrays["n_map_entries"][j])
    out["clock"] = summ.doc(doc_id)["clock"]
    return out


def _memo_snapshot(back):
    out = {}
    for doc_id, m in back._summary_memo.items():
        out[doc_id] = {
            "clock": dict(m["clock"]),
            "N": m["N"],
            "n_live": m["n_live"],
            "n_map": m["n_map"],
            "mw_bits": m["mw_bits"].tobytes(),
            "el_bits": m["el_bits"].tobytes(),
            "order": m["order"].tobytes(),
            "clock_row": m["clock_row"].tobytes(),
        }
    return out


def _load_twice(path, ids, mode, monkeypatch, slab):
    """Two bulk loads in one backend (the second is all memo hits);
    returns per-doc summary bytes for both, the memo snapshot, and the
    stats of each load."""
    monkeypatch.setenv("HM_PIPELINE", mode)
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")  # force device path
    repo = Repo(path=str(path))
    back = repo.back
    back.load_documents_bulk(ids, slab=slab)
    stats1 = dict(back.last_bulk_stats)
    s1 = back.fetch_bulk_summaries()
    first = {d: _doc_summary_bytes(s1, d) for d in s1.doc_ids}
    memo = _memo_snapshot(back)
    for doc_id in ids:
        back.close_doc(doc_id)
    back.load_documents_bulk(ids, slab=slab)
    stats2 = dict(back.last_bulk_stats)
    s2 = back.fetch_bulk_summaries()
    second = {d: _doc_summary_bytes(s2, d) for d in s2.doc_ids}
    repo.close()
    counts = [
        {k: st[k] for k in ("docs", "fast", "memo", "fallback")}
        for st in (stats1, stats2)
    ]
    return first, second, memo, counts


def test_pipeline_serial_equivalence_fuzz(tmp_path, monkeypatch):
    """Fuzzed docs across >=3 slab boundaries: HM_PIPELINE=1 and =0
    produce byte-identical summary arrays, identical memo contents, and
    identical doc/fast/fallback counts — on the first (packed +
    dispatched) AND second (memo-served) loads."""
    src = tmp_path / "src"
    urls, want = _make_corpus(src, n_docs=14)
    gap_url = _add_gap_doc(src)
    ids = [validate_doc_url(u) for u in urls] + [validate_doc_url(gap_url)]

    results = {}
    for mode in ("0", "1"):
        copy = tmp_path / f"repo{mode}"
        shutil.copytree(src, copy)
        results[mode] = _load_twice(
            copy, ids, mode, monkeypatch, slab=4
        )  # 14 fast docs / slab 4 -> 4 slabs (3+ boundaries)

    first0, second0, memo0, counts0 = results["0"]
    first1, second1, memo1, counts1 = results["1"]
    assert counts0 == counts1
    assert counts0[0]["fallback"] == 1
    assert counts0[1]["memo"] == counts0[1]["fast"]  # 2nd load: all memo
    assert set(first0) == set(first1) and len(first0) == 14
    for d in first0:
        assert first0[d] == first1[d], f"first-load summary differs: {d}"
    for d in second0:
        assert second0[d] == second1[d], f"memo-load summary differs: {d}"
    assert memo0 == memo1


def test_pipeline_matches_interactive_state(tmp_path, monkeypatch):
    """Pipelined bulk loads materialize the same doc values the writer
    saw (end-to-end through handles, not just summary arrays)."""
    monkeypatch.setenv("HM_PIPELINE", "1")
    urls, want = _make_corpus(tmp_path / "r", n_docs=9, seed=3)
    repo = Repo(path=str(tmp_path / "r"))
    ids = [validate_doc_url(u) for u in urls]
    repo.back.load_documents_bulk(ids, slab=2)
    summ = repo.back.fetch_bulk_summaries()
    assert len(summ.doc_ids) == 9
    for u in urls:
        assert plainify(repo.doc(u)) == want[u]
    repo.close()


def _assert_pipe_threads_drained(deadline_s=10.0):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        alive = [
            t.name
            for t in threading.enumerate()
            if t.name.startswith("hm-pipe-")
        ]
        if not alive:
            return
        time.sleep(0.02)
    raise AssertionError(f"pipeline workers leaked: {alive}")


def _call_with_timeout(fn, timeout_s=90.0):
    """Run fn on a worker and re-raise its outcome; a hang fails the
    test instead of wedging the whole suite."""
    box = {}

    def runner():
        try:
            box["ret"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            box["exc"] = e

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join(timeout_s)
    assert not t.is_alive(), "bulk load hung"
    if "exc" in box:
        raise box["exc"]
    return box.get("ret")


def test_pipeline_pack_failure_fails_load_cleanly(tmp_path, monkeypatch):
    """A slab whose pack raises must fail the bulk load as a unit: the
    error propagates, every worker drains, and no device refs linger in
    the pending list."""
    import hypermerge_tpu.ops.columnar as columnar

    urls, _want = _make_corpus(tmp_path / "r", n_docs=12, seed=11)
    ids = [validate_doc_url(u) for u in urls]
    monkeypatch.setenv("HM_PIPELINE", "1")

    real = columnar.pack_docs_columns
    calls = {"n": 0}

    def boom(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("boom-pack")
        return real(*a, **kw)

    monkeypatch.setattr(columnar, "pack_docs_columns", boom)
    repo = Repo(path=str(tmp_path / "r"))
    with pytest.raises(PipelineError) as ei:
        _call_with_timeout(
            lambda: repo.back.load_documents_bulk(ids, slab=4)
        )
    assert "boom-pack" in repr(ei.value.__cause__)
    _assert_pipe_threads_drained()
    assert repo.back._pending_summaries == []
    assert repo.back._fetch_ctx is None
    repo.close()

    # the corpus itself is intact: a fresh backend loads it fine
    monkeypatch.setattr(columnar, "pack_docs_columns", real)
    repo2 = Repo(path=str(tmp_path / "r"))
    repo2.back.load_documents_bulk(ids, slab=4)
    summ = repo2.back.fetch_bulk_summaries()
    assert len(summ.doc_ids) == 12
    repo2.close()


def test_pipeline_fetch_failure_fails_cleanly(tmp_path, monkeypatch):
    """A slab whose summary fetch raises must surface the error (at the
    load or at the barrier, wherever the overlap window puts it) and
    leave no hung workers or pending refs."""
    from hypermerge_tpu.backend.repo_backend import RepoBackend

    urls, _want = _make_corpus(tmp_path / "r", n_docs=10, seed=13)
    ids = [validate_doc_url(u) for u in urls]
    monkeypatch.setenv("HM_PIPELINE", "1")
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")  # real device fetches

    real = RepoBackend._fetch_slab
    calls = {"n": 0}

    def boom(self, entry):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("boom-fetch")
        return real(self, entry)

    monkeypatch.setattr(RepoBackend, "_fetch_slab", boom)
    repo = Repo(path=str(tmp_path / "r"))

    def load_and_barrier():
        repo.back.load_documents_bulk(ids, slab=4)
        repo.back.fetch_bulk_summaries()

    with pytest.raises(PipelineError) as ei:
        _call_with_timeout(load_and_barrier)
    assert "boom-fetch" in repr(ei.value.__cause__)
    _assert_pipe_threads_drained()
    assert repo.back._pending_summaries == []
    assert repo.back._fetch_ctx is None
    repo.close()


def test_round_robin_slabs_across_devices(tmp_path, monkeypatch):
    """With >1 visible device and the pipeline on, successive slabs
    land whole on successive devices (rr_slabs accounting), with
    results identical to the interactive state."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >1 (virtual) device")
    monkeypatch.setenv("HM_PIPELINE", "1")
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")
    urls, want = _make_corpus(tmp_path / "r", n_docs=6, seed=5)
    repo = Repo(path=str(tmp_path / "r"))
    ids = [validate_doc_url(u) for u in urls]
    repo.back.load_documents_bulk(ids, slab=2)
    summ = repo.back.fetch_bulk_summaries()
    stats = repo.back.last_bulk_stats
    assert stats.get("rr_slabs") == 3, stats
    assert stats.get("rr_devices") == len(jax.devices()), stats
    assert stats.get("sharded_slabs") is None
    assert len(summ.doc_ids) == 6
    for u in urls:
        assert plainify(repo.doc(u)) == want[u]
    repo.close()


def test_slab_round_robin_cycles_and_bounds_inflight():
    """Unit: the scheduler cycles devices and never holds more than
    `depth` unfetched summaries per device."""
    import jax
    import numpy as np

    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.materialize import fetch_summary
    from hypermerge_tpu.ops.synth import synth_changes
    from hypermerge_tpu.parallel.sharded import SlabRoundRobin

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >1 (virtual) device")
    rr = SlabRoundRobin(devices[:2], depth=1)
    batches = [
        pack_docs([synth_changes(48, n_actors=1, ops_per_change=8, seed=s)])
        for s in range(5)
    ]
    wires = []
    for b in batches:
        _out, wire = rr.dispatch(b, lean=False)
        wires.append((b, wire))
        for q in rr._inflight.values():
            assert len(q) <= 1
    assert rr._next == 5 % 2
    rr.drain()
    # every slab decodes (placement did not corrupt anything)
    for b, wire in wires:
        arrays = fetch_summary(wire, b, lean=False)
        assert int(np.asarray(arrays["n_map_entries"][0])) >= 0


def test_pipeline_per_chip_stats(tmp_path, monkeypatch):
    """Mesh-aware stats: the pipelined bulk load reports per-chip
    dispatch/fetch busy times and slab placement alongside the stage
    totals."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >1 (virtual) device")
    monkeypatch.setenv("HM_PIPELINE", "1")
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")
    urls, want = _make_corpus(tmp_path / "r", n_docs=6, seed=7)
    repo = Repo(path=str(tmp_path / "r"))
    ids = [validate_doc_url(u) for u in urls]
    repo.back.load_documents_bulk(ids, slab=2)
    repo.back.fetch_bulk_summaries()
    stats = repo.back.last_bulk_stats
    n = len(jax.devices())
    assert len(stats["t_dispatch_chips"]) == n, stats
    assert len(stats["slabs_per_chip"]) == n
    assert sum(stats["slabs_per_chip"]) == stats["rr_slabs"] == 3
    # every dispatched slab's busy time is attributed to its chip
    assert sum(
        1 for t in stats["t_dispatch_chips"] if t > 0
    ) == sum(1 for s in stats["slabs_per_chip"] if s > 0)
    assert len(stats.get("t_fetch_chips", [])) == n, stats
    assert sum(stats["t_fetch_chips"]) > 0
    # the PRODUCT scheduler never tracks collective-reduction refs:
    # nothing may pin slab wires beyond the barrier
    rr = repo.back._rr_value
    if hasattr(rr, "track_resident"):
        assert rr.track_resident is False
        assert all(not q for q in rr._resident_wires.values())
        assert all(not q for q in rr._resident_clocks.values())
    for u in urls:
        assert plainify(repo.doc(u)) == want[u]
    repo.close()


def _load_once(path, ids, monkeypatch, slab, workers, device_pack, order):
    """One pipelined bulk load under a given pack-plane config; env vars
    are set in the given order (the routing must not care)."""
    monkeypatch.setenv("HM_PIPELINE", "1")
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")
    pair = (("HM_PACK_WORKERS", workers), ("HM_DEVICE_PACK", device_pack))
    for var, val in pair if order == 0 else pair[::-1]:
        monkeypatch.setenv(var, val)
    repo = Repo(path=str(path))
    back = repo.back
    back.load_documents_bulk(ids, slab=slab)
    stats = dict(back.last_bulk_stats)
    summ = back.fetch_bulk_summaries()
    out = {d: _doc_summary_bytes(summ, d) for d in summ.doc_ids}
    repo.close()
    _assert_pipe_threads_drained()
    return out, stats


def test_pipeline_pack_worker_matrix(tmp_path, monkeypatch):
    """HM_PACK_WORKERS={0,1,4} x HM_DEVICE_PACK={0,1}, both env set
    orders, over a ragged-tail corpus (10 docs / slab 4 -> 4+4+2):
    every pack-plane config produces summaries byte-identical to the
    one-worker host baseline, and the pool reports its shape
    (pack_workers, per-worker busy lanes, lane wall)."""
    from hypermerge_tpu.backend.pipeline import pack_worker_count
    from hypermerge_tpu.ops import pack_kernels

    src = tmp_path / "src"
    urls, _want = _make_corpus(src, n_docs=10, seed=19)
    ids = [validate_doc_url(u) for u in urls]

    results = {}
    matrix = [
        ("1", "0"), ("0", "0"), ("4", "0"),
        ("1", "1"), ("4", "1"), ("0", "1"),
    ]
    for i, (workers, device) in enumerate(matrix):
        copy = tmp_path / f"m{i}"
        shutil.copytree(src, copy)
        packs0 = pack_kernels._M_PACKS.value()
        out, stats = _load_once(
            copy, ids, monkeypatch, 4, workers, device, order=i % 2
        )
        assert stats["pipeline"] == 1
        want_pool = pack_worker_count()  # env still set from _load_once
        assert stats["pack_workers"] == want_pool
        if workers != "0":
            assert stats["pack_workers"] == int(workers)
        assert len(stats["t_pack_busy_per_worker"]) == want_pool
        assert stats["t_pack_wall"] >= 0.0
        assert sum(stats["t_pack_busy_per_worker"]) >= 0.0
        if device == "1":
            # the device kernel actually packed (it never silently
            # falls through on these clean single-writer slabs)
            assert pack_kernels._M_PACKS.value() > packs0
        results[(workers, device)] = out
    base = results[("1", "0")]
    for cfg, out in results.items():
        assert set(out) == set(base), cfg
        for d in base:
            assert out[d] == base[d], (cfg, d)


@pytest.mark.slow
def test_pipeline_pack_pool_large_shape(tmp_path, monkeypatch):
    """Largest-shape tier: a wider corpus across many slabs with the
    full pool (4 workers) and the device kernel — still byte-identical
    to the serial twin, pool accounting intact."""
    src = tmp_path / "src"
    urls, _want = _make_corpus(src, n_docs=42, seed=23)
    ids = [validate_doc_url(u) for u in urls]

    copy0 = tmp_path / "serial"
    shutil.copytree(src, copy0)
    base, _ = _load_once(copy0, ids, monkeypatch, 8, "1", "0", order=0)

    copy1 = tmp_path / "pool"
    shutil.copytree(src, copy1)
    out, stats = _load_once(copy1, ids, monkeypatch, 8, "4", "1", order=1)
    assert stats["pack_workers"] == 4
    assert len(stats["t_pack_busy_per_worker"]) == 4
    assert set(out) == set(base) and len(out) == 42
    for d in base:
        assert out[d] == base[d], d


def test_pipeline_stats_report_busy_and_critical_path(tmp_path, monkeypatch):
    """Pipeline mode reports per-stage busy time (t_*_busy) and the
    overlapped wall critical path alongside the canonical keys."""
    monkeypatch.setenv("HM_PIPELINE", "1")
    urls, _want = _make_corpus(tmp_path / "r", n_docs=5, seed=2)
    repo = Repo(path=str(tmp_path / "r"))
    ids = [validate_doc_url(u) for u in urls]
    repo.back.load_documents_bulk(ids, slab=2)
    repo.back.fetch_bulk_summaries()
    stats = repo.back.last_bulk_stats
    assert stats["pipeline"] == 1
    for k in ("t_io_busy", "t_pack_busy", "t_dispatch_busy"):
        assert k in stats
    assert stats["wall_critical_path"] >= 0.0
    assert "t_fetch" in stats and "t_fetch_busy" in stats
    repo.close()
