"""Test env: force an 8-device virtual CPU mesh before jax backends init.

Multi-chip hardware is unavailable in CI; sharding tests run over
xla_force_host_platform_device_count=8 exactly as the driver's
dryrun_multichip does (see __graft_entry__.py).

Note: this environment pre-registers an `axon` TPU platform via
sitecustomize and overrides JAX_PLATFORMS, so plain env vars are not
enough — we must update jax.config before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

if os.environ.get("HM_TEST_TPU") != "1":
    # CI default: virtual CPU mesh. HM_TEST_TPU=1 leaves the real
    # (tunneled) TPU platform active — slow first compiles, used for
    # occasional hardware validation of the device-equivalence tests.
    jax.config.update("jax_platforms", "cpu")

import random

import pytest


@pytest.fixture
def rng():
    return random.Random(0xC0FFEE)
