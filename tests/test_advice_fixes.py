"""Regression tests for the round-2 advisor findings (ADVICE.md).

Each test reproduces the reported failure before the fix:
- pack_docs_columns key-LUT IndexError when the last feed has no keyed ops
- a columnar sidecar AHEAD of its feed being silently trusted
- a truncated upload being durably recorded as a complete file
- HEAD error responses carrying bodies
- duplicate metadata ledger appends
- bulk load skipping the minimum-clock readiness gate
- bulk clock shortcut trusting an unchecked seq-contiguity invariant
- slab DecodedBatch retention via never-cleared snapshot closures
"""

import os
import socket
import tempfile
import time

import numpy as np
import pytest

from hypermerge_tpu.backend.actor import Actor
from hypermerge_tpu.backend.metadata import Metadata
from hypermerge_tpu.models import Text
from hypermerge_tpu.ops.columnar import pack_docs, pack_docs_columns
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.storage import block as blockmod
from hypermerge_tpu.storage.colcache import (
    ROW_FIELDS,
    FeedColumnCache,
    MemoryColumnStorage,
)
from hypermerge_tpu.storage.feed import Feed, FeedStore, MemoryFeedStorage, memory_storage_fn
from hypermerge_tpu.storage.sql import SqlDatabase
from hypermerge_tpu.storage.stores import KeyStore
from hypermerge_tpu.utils import keys as keymod
from hypermerge_tpu.utils.ids import validate_doc_url

from helpers import Site, plainify, sync
from test_bulk_cold_start import _caches_from_history, _patch_doc

INF = float("inf")


# -- pack_docs_columns: empty key table at the end of the LUT ------------


def test_pack_columns_empty_key_table_last_feed():
    """A collaborator feed containing only keyless ops (text inserts) has
    an empty key table; placed last in the flat LUT its offset equals
    len(klut), and the eager np.where gather used to IndexError."""
    a, b = Site("actorA"), Site("actorB")
    a.change(lambda d: d.__setitem__("t", Text("x")))
    sync(a, b)
    b.change(lambda d: d["t"].insert(1, "y"))
    sync(a, b)
    history = list(a.opset.history)
    caches = _caches_from_history(history)
    # actorB's feed (keyless ops only) must come LAST in the spec
    spec = [
        (caches["actorA"].columns(), 0, INF),
        (caches["actorB"].columns(), 0, INF),
    ]
    batch = pack_docs_columns([spec])  # used to raise IndexError
    ref = pack_docs([history])
    assert batch.n_ops.tolist() == ref.n_ops.tolist()
    assert _patch_doc(batch, 0) == _patch_doc(ref, 0) == plainify(a.doc)


# -- sidecar ahead of feed ----------------------------------------------


def test_sidecar_ahead_of_feed_rebuilds():
    """A sidecar claiming more changes than its feed holds (feed file
    replaced / truncated out-of-band) must be discarded and rebuilt from
    blocks — blocks are the source of truth."""
    site = Site("actorX")
    for i in range(5):
        site.change(lambda d, i=i: d.__setitem__(f"k{i}", i))
    history = list(site.opset.history)

    pair = keymod.create()
    feed = Feed(pair.public_key, MemoryFeedStorage(), pair.secret_key)
    # feed holds only the first 3 blocks...
    for c in history[:3]:
        feed.append(blockmod.pack(c.to_json()))
    # ...but the sidecar committed all 5
    cache = FeedColumnCache(MemoryColumnStorage(), writer=pair.public_key)
    for c in history:
        cache.append_change(c)
    assert cache.n_changes == 5
    feed.colcache = cache

    actor = Actor(feed, lambda e: None)
    fc = actor.columns()
    assert fc.n_changes == 3  # rebuilt to match the block log
    assert fc.changes_in_window(0, INF) == 3
    # and the rebuilt rows equal a from-scratch encode of the same blocks
    ref = FeedColumnCache(MemoryColumnStorage(), writer=pair.public_key)
    for c in history[:3]:
        ref.append_change(c)
    assert np.array_equal(fc.rows, ref.columns().rows)


# -- file server: truncated upload + HEAD errors ------------------------


def _server_path() -> str:
    import uuid

    return os.path.join(
        tempfile.gettempdir(),
        f"hypermerge-tpu-test-{uuid.uuid4().hex[:8]}.sock",
    )


def test_truncated_upload_not_recorded_complete():
    """A client disconnect mid-upload must not append the trailing header
    block: the feed stays an incomplete upload, nothing reaches the
    write log / metadata ledger."""
    repo = Repo(memory=True)
    path = _server_path()
    try:
        repo.start_file_server(path)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(
            b"POST / HTTP/1.1\r\n"
            b"Host: unix\r\n"
            b"Content-Type: text/plain\r\n"
            b"Content-Length: 100000\r\n\r\n" + b"x" * 1000
        )
        s.close()  # disconnect with 99000 bytes unread
        # the handler aborts on the recv EOF; give its thread a beat
        time.sleep(0.25)
        assert repo.back.meta.files == {}
        # the server still works for a subsequent complete upload
        header = repo.files.write(b"ok", "text/plain")
        assert len(repo.back.meta.files) == 1  # only the good one recorded
        _h, body = repo.files.read(header.url)
        assert body == b"ok"
    finally:
        repo.close()


def test_head_error_response_has_no_body():
    """HEAD responses are headers-only even for errors (RFC 9110) — a
    body would desync keep-alive framing."""
    repo = Repo(memory=True)
    path = _server_path()
    try:
        repo.start_file_server(path)
        bogus = keymod.create().public_key
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(
            f"HEAD /hyperfile:/{bogus} HTTP/1.1\r\n"
            f"Host: unix\r\nConnection: close\r\n\r\n".encode()
        )
        raw = b""
        while True:
            chunk = s.recv(4096)
            if not chunk:
                break
            raw += chunk
        s.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        assert b"404" in head.split(b"\r\n")[0]
        assert body == b""
    finally:
        repo.close()


# -- metadata ledger: no duplicate appends ------------------------------


def test_metadata_no_duplicate_ledger_appends():
    feeds = FeedStore(memory_storage_fn)
    key_store = KeyStore(SqlDatabase(":memory:"))
    meta = Metadata(feeds, key_store)
    url = f"hyperfile:/{keymod.create().public_key}"
    meta.add_file(url, 5, "a/b")
    assert meta.ledger.length == 1
    meta.add_file(url, 5, "a/b")  # identical: must not grow the ledger
    assert meta.ledger.length == 1
    meta.add_file(url, 6, "a/b")  # changed: re-recorded
    assert meta.ledger.length == 2


# -- bulk load: minimum-clock gate --------------------------------------


def test_bulk_load_gates_unknown_empty_doc():
    """An unknown doc id with no local history must not announce as an
    empty document — it waits on the root actor's first replicated
    change, like _load_document's minimumClock gate."""
    repo = Repo(memory=True)
    try:
        unknown = keymod.create().public_key
        repo.back.load_documents_bulk([unknown])
        doc = repo.back.docs[unknown]
        assert not doc._announced
        assert doc.minimum_clock == {unknown: 1}
    finally:
        repo.close()


# -- bulk load: seq-contiguity check ------------------------------------


def test_bulk_load_falls_back_on_seq_gap(tmp_path):
    """A sidecar with a seq gap (e.g. restored from a different feed
    generation) must not produce a silently wrong clock — the doc routes
    through the safe per-doc replay path instead."""
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"x": 1})
    repo.change(url, lambda d: d.__setitem__("y", 2))
    repo.change(url, lambda d: d.__setitem__("z", 3))
    want = plainify(repo.doc(url))
    doc_id = validate_doc_url(url)
    want_clock = dict(repo.back.docs[doc_id].clock)
    repo.close()

    # corrupt the sidecar: bump the last change's seq to fake a gap
    # (sidecars live in the corpus slab now — supersede each feed's
    # image with the edited record stream; `.cols2` files are walked
    # too for the HM_SLAB=0 layout)
    from hypermerge_tpu.storage.colcache import (
        FileColumnStorageV2,
        file_column_storage_fn,
        pack_v2_record,
    )

    feeds_dir = os.path.join(path, "feeds")

    def _edit(rows, preds, tables, commits):
        if not len(rows):
            return None
        max_seq = rows[:, 2].max()
        if max_seq < 2:
            return None
        rows = rows.copy()
        rows[rows[:, 2] == max_seq, 2] = max_seq + 1
        # re-frame the same per-change records with the edited rows
        recs = []
        pr = pp = pt = 0
        for tr, tp, tt, flag in commits:
            recs.append(
                pack_v2_record(
                    rows[pr:tr], preds[pp:tp], tables[pt:tt], flag
                )
            )
            pr, pp, pt = tr, tp, tt
        return b"".join(recs)

    edited = False
    fn = file_column_storage_fn(feeds_dir)
    if fn.slab is not None:
        from hypermerge_tpu.storage.slab import KIND_IMAGE

        for name in fn.slab.feed_names():
            blob = _edit(*fn(name).load())
            if blob is not None:
                fn.slab.append(KIND_IMAGE, name, blob)
                edited = True
        fn.slab.close()
    for root, _dirs, files in os.walk(feeds_dir):
        for f in files:
            if not f.endswith(".cols2"):
                continue
            st = FileColumnStorageV2(os.path.join(root, f))
            blob = _edit(*st.load())
            if blob is None:
                continue
            with open(os.path.join(root, f), "wb") as fh:
                fh.write(blob)
            edited = True
    assert edited

    repo2 = Repo(path=path)
    try:
        repo2.back.load_documents_bulk([doc_id])
        doc = repo2.back.docs[doc_id]
        # fallback path replays host-side (opset exists) with the true clock
        assert doc.opset is not None
        assert doc.clock == want_clock
        assert plainify(repo2.doc(url)) == want
    finally:
        repo2.close()


# -- bulk load: snapshot closure released after first use ---------------


def test_bulk_snapshot_fn_released_after_first_ready(tmp_path):
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"a": 1})
    repo.close()

    repo2 = Repo(path=path)
    try:
        doc_id = validate_doc_url(url)
        repo2.back.load_documents_bulk([doc_id])
        doc = repo2.back.docs[doc_id]
        p1 = doc.snapshot_patch()
        assert doc._snapshot_fn is None  # closure (and its slab) released
        assert doc.snapshot_patch() is p1  # later reads serve the cache
        assert doc.opset is None  # still lazy
    finally:
        repo2.close()


def test_noop_change_does_not_strand_queue():
    """ADVICE r5 low (doc_frontend.py): when the echo-paced queue pops a
    change fn that produces no ops, the drain must continue to the next
    queued change instead of stranding until an unrelated patch."""
    from hypermerge_tpu.frontend.doc_frontend import DocFrontend

    sent = []

    class StubRepo:
        class to_backend:
            @staticmethod
            def push(msg):
                pass

        @staticmethod
        def send_request(doc_id, request):
            sent.append(request)

        @staticmethod
        def needs_actor(doc_id):
            pass

    doc_id = "d" * 43
    fe = DocFrontend(StubRepo(), doc_id, actor_id=doc_id)

    fe.change(lambda d: d.__setitem__("a", 1))
    assert len(sent) == 1 and fe._inflight is not None

    # queue while the echo is outstanding: a no-op fn, then a real one
    fe.change(lambda d: None)
    fe.change(lambda d: d.__setitem__("b", 2))
    assert len(sent) == 1  # both queued behind the in-flight echo

    # the echo lands: the no-op pops (produces nothing) and the drain
    # must continue to the real change in the same pass
    req = sent[0]
    fe.on_patch(
        {
            "actor": req.actor,
            "seq": req.seq,
            "diffs": [],
            "deps": {},
            "maxOp": 1,
            "clock": {req.actor: req.seq},
        },
        1,
    )
    assert len(sent) == 2, "queued change stranded behind a no-op fn"
