"""Bulk cold-start path: columnar feed caches + vectorized packing +
lazy DocBackend reconstruction.

This is the north-star path (BASELINE config 4): feeds -> columnar
sidecar -> pack_docs_columns -> device kernel, with the per-op host
loop (`pack_docs`) as the correctness reference and the host OpSet as
ground truth (SURVEY.md §7.3 items 4 & 6: dual paths must agree)."""

import random
import tempfile

import numpy as np
import pytest

from hypermerge_tpu.crdt.frontend_state import FrontendDoc
from hypermerge_tpu.models import Text
from hypermerge_tpu.ops.columnar import pack_docs, pack_docs_columns
from hypermerge_tpu.ops.crdt_kernels import run_batch
from hypermerge_tpu.ops.materialize import DecodedBatch, decode_patch
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.storage.colcache import (
    FeedColumnCache,
    FileColumnStorage,
    MemoryColumnStorage,
)
from hypermerge_tpu.utils.ids import validate_doc_url

from helpers import Site, plainify, random_mutation, sync, wait_until

INF = float("inf")


@pytest.fixture(params=["0", "1"], ids=["serial", "pipeline"])
def pipeline_mode(request, monkeypatch):
    """Env-matrix: every bulk cold-start test runs under BOTH the
    serial twin (HM_PIPELINE=0) and the streaming slab pipeline
    (HM_PIPELINE=1, the product default) — the pipeline is a pure
    scheduling change and must pass the identical contract."""
    monkeypatch.setenv("HM_PIPELINE", request.param)
    return request.param


@pytest.fixture(params=["0", "1"], ids=["host", "live"])
def live_mode(request, monkeypatch):
    """Env-matrix: HM_LIVE=0 is the host-OpSet correctness twin; the
    live apply engine (HM_LIVE=1, the product default) must honor the
    same incremental-change contract without reconstructing an OpSet."""
    monkeypatch.setenv("HM_LIVE", request.param)
    return request.param


def _history(seed: int, n_actors: int = 3, n_mut: int = 40):
    r = random.Random(seed)
    sites = [Site(f"actor{i:02d}") for i in range(n_actors)]
    for _ in range(n_mut):
        random_mutation(r.choice(sites), r)
        if r.random() < 0.3:
            sync(*sites)
    sync(*sites)
    return sites[0], list(sites[0].opset.history)


def _caches_from_history(history):
    caches = {}
    for c in sorted(history, key=lambda c: (c.actor, c.seq)):
        cc = caches.setdefault(
            c.actor, FeedColumnCache(MemoryColumnStorage(), writer=c.actor)
        )
        cc.append_change(c)
    return caches


def _patch_doc(batch, d):
    dec = DecodedBatch(batch, run_batch(batch))
    front = FrontendDoc()
    front.apply_patch(decode_patch(dec, d))
    return plainify(front.materialize())


def test_pack_columns_matches_pack_docs_and_host():
    """Full-window equivalence: vectorized pack == per-op pack == host
    OpSet, over randomized multi-actor histories."""
    for seed in (1, 2, 3):
        site, history = _history(seed)
        caches = _caches_from_history(history)
        spec = [(cc.columns(), 0, INF) for cc in caches.values()]
        b_ref = pack_docs([history])
        b_new = pack_docs_columns([spec])
        assert b_new.n_ops.tolist() == b_ref.n_ops.tolist()
        assert _patch_doc(b_ref, 0) == _patch_doc(b_new, 0) == plainify(
            site.doc
        )


def test_pack_columns_multi_doc_batch():
    sites, specs, hists = [], [], []
    for seed in (10, 11, 12, 13):
        site, history = _history(seed, n_mut=25)
        caches = _caches_from_history(history)
        specs.append([(cc.columns(), 0, INF) for cc in caches.values()])
        hists.append(history)
        sites.append(site)
    b_ref = pack_docs(hists)
    b_new = pack_docs_columns(specs)
    for d, site in enumerate(sites):
        assert _patch_doc(b_ref, d) == _patch_doc(b_new, d) == plainify(
            site.doc
        )


def test_pack_columns_partial_window():
    """Cursor windows (start, end] slice the same changes the host
    Actor.changes_in_window serves."""
    site, history = _history(7)
    caches = _caches_from_history(history)
    # cut each actor's window at half its changes
    spec = []
    sliced = []
    for actor, cc in caches.items():
        fc = cc.columns()
        end = max(1, fc.n_changes // 2)
        spec.append((fc, 0, end))
        sliced.extend(
            c for c in history if c.actor == actor and c.seq <= end
        )
    b_ref = pack_docs([sliced])
    b_new = pack_docs_columns([spec])
    assert _patch_doc(b_ref, 0) == _patch_doc(b_new, 0)


def test_pack_columns_drops_unresolvable_refs():
    """Ops whose container/element lies outside the packed window drop,
    cascading — same as _pack_one's row_of misses."""
    site, history = _history(5)
    caches = _caches_from_history(history)
    # skip the FIRST actor's feed entirely: ops referencing its objects
    # must drop on both paths
    actors = sorted(caches)
    keep = actors[1:]
    spec = [(caches[a].columns(), 0, INF) for a in keep]
    kept_hist = [c for c in history if c.actor in keep]
    b_ref = pack_docs([kept_hist])
    b_new = pack_docs_columns([spec])
    assert b_new.n_ops.tolist() == b_ref.n_ops.tolist()
    assert _patch_doc(b_ref, 0) == _patch_doc(b_new, 0)


def test_colcache_file_persistence_and_torn_tail(tmp_path):
    _site, history = _history(3, n_actors=1, n_mut=15)
    path = str(tmp_path / "feed.cols")
    cc = FeedColumnCache(FileColumnStorage(path), writer=history[0].actor)
    for c in history:
        cc.append_change(c)
    want = cc.columns()
    cc.close()

    # reopen: identical
    cc2 = FeedColumnCache(FileColumnStorage(path), writer=history[0].actor)
    got = cc2.columns()
    assert np.array_equal(got.rows, want.rows)
    assert np.array_equal(got.preds, want.preds)
    assert got.actors == want.actors
    assert got.n_changes == want.n_changes
    cc2.close()

    # torn tail: appending garbage to rows.bin without a commit record
    # must be invisible after reopen
    with open(path + "/rows.bin", "ab") as fh:
        fh.write(b"\x01\x02\x03")
    cc3 = FeedColumnCache(FileColumnStorage(path), writer=history[0].actor)
    got3 = cc3.columns()
    assert np.array_equal(got3.rows, want.rows)
    assert got3.n_changes == want.n_changes
    # and the cache still appends cleanly after healing
    cc3.close()


def test_colcache_v2_persistence_and_torn_tail(tmp_path):
    """Single-file sidecar: reopen-identical, torn tails invisible, and
    appends keep working over a healed tail."""
    from hypermerge_tpu.storage.colcache import FileColumnStorageV2

    _site, history = _history(4, n_actors=1, n_mut=15)
    path = str(tmp_path / "feed.cols2")
    cc = FeedColumnCache(FileColumnStorageV2(path), writer=history[0].actor)
    for c in history[:-1]:
        cc.append_change(c)
    want = cc.columns()
    cc.close()

    cc2 = FeedColumnCache(FileColumnStorageV2(path), writer=history[0].actor)
    got = cc2.columns()
    assert np.array_equal(got.rows, want.rows)
    assert np.array_equal(got.preds, want.preds)
    assert got.actors == want.actors
    assert got.n_changes == want.n_changes
    cc2.close()

    # torn tail: garbage after the last record must be invisible...
    with open(path, "ab") as fh:
        fh.write(b"\x07\x00\x00\x00torn")
    cc3 = FeedColumnCache(FileColumnStorageV2(path), writer=history[0].actor)
    got3 = cc3.columns()
    assert got3.n_changes == want.n_changes
    # ...and the next append overwrites it cleanly
    cc3.append_change(history[-1])
    cc3.close()
    cc4 = FeedColumnCache(FileColumnStorageV2(path), writer=history[0].actor)
    assert cc4.columns().n_changes == want.n_changes + 1
    cc4.close()


def test_colcache_legacy_dir_still_loads(tmp_path):
    """Repos written by the 4-file layout keep loading (read compat)."""
    from hypermerge_tpu.storage.colcache import (
        FileColumnStorage,
        file_column_storage_fn,
    )

    _site, history = _history(6, n_actors=1, n_mut=10)
    root = str(tmp_path)
    actor = history[0].actor
    legacy_path = f"{root}/{actor[:2]}/{actor}.cols"
    cc = FeedColumnCache(FileColumnStorage(legacy_path), writer=actor)
    for c in history:
        cc.append_change(c)
    want = cc.columns()
    cc.close()

    # the factory must route this feed to the legacy reader
    storage = file_column_storage_fn(root)(actor)
    assert isinstance(storage, FileColumnStorage)
    cc2 = FeedColumnCache(storage, writer=actor)
    got = cc2.columns()
    assert np.array_equal(got.rows, want.rows)
    assert got.n_changes == want.n_changes
    cc2.close()


def test_colcache_corrupt_block_clamps_prefix():
    _site, history = _history(9, n_actors=1, n_mut=12)
    cc = FeedColumnCache(MemoryColumnStorage(), writer=history[0].actor)
    n = len(history)
    cut = n // 2
    for c in history[:cut]:
        cc.append_change(c)
    cc.append_change(None)  # corrupt block placeholder
    for c in history[cut:]:
        cc.append_change(c)
    fc = cc.columns()
    assert fc.n_changes == n + 1
    assert fc.ok_prefix_len == cut
    # windows clamp to the ok prefix: the host OpSet can't apply past a
    # seq-continuity gap either
    lo, hi = fc.window(0, INF)
    assert hi == int(fc.row_ends[cut])
    assert fc.changes_in_window(0, INF) == cut


def test_bulk_load_is_lazy_then_reconstructs(pipeline_mode, live_mode):
    """After load_documents_bulk, docs serve clock/snapshot without a
    host OpSet; the first incremental change extends state exactly
    (HM_LIVE=0: by reconstructing the OpSet; HM_LIVE=1: through the
    live apply engine, no reconstruction)."""
    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        urls = []
        for i in range(4):
            url = repo.create({"i": i, "t": Text(f"doc{i}")})
            repo.change(url, lambda d: d["t"].insert(0, ">"))
            urls.append(url)
        want = {u: plainify(repo.doc(u)) for u in urls}
        clocks = {
            u: repo.back.docs[validate_doc_url(u)].clock for u in urls
        }
        hlens = {
            u: repo.back.docs[validate_doc_url(u)].history_len
            for u in urls
        }
        repo.close()

        repo2 = Repo(path=tmp)
        ids = [validate_doc_url(u) for u in urls]
        repo2.back.load_documents_bulk(ids)
        for u in urls:
            doc = repo2.back.docs[validate_doc_url(u)]
            assert doc.opset is None, "bulk load must not replay host-side"
            assert doc.clock == clocks[u]
            assert doc.history_len == hlens[u]
        # reads decode from the device batch
        for u in urls:
            assert plainify(repo2.doc(u)) == want[u]
            assert repo2.back.docs[validate_doc_url(u)].opset is None
        # first local change extends state. HM_LIVE=0 (this test pins
        # the host twin): the OpSet reconstructs exactly; HM_LIVE=1 is
        # pinned by tests/test_live.py (NO reconstruction happens).
        repo2.change(urls[0], lambda d: d.__setitem__("new", True))
        doc0 = repo2.back.docs[ids[0]]
        if live_mode == "0":
            assert doc0.opset is not None
        else:
            assert doc0.opset is None, "live path must not replay"
        got = plainify(repo2.doc(urls[0]))
        assert got["new"] is True
        assert got["t"] == want[urls[0]]["t"]
        repo2.close()


def test_bulk_loaded_doc_applies_replicated_changes(pipeline_mode, live_mode):
    """A replicated block arriving after a bulk (lazy) load must reach
    the doc — host twin: by reconstructing the OpSet on demand; live
    path: through the tick engine, still no OpSet."""
    from hypermerge_tpu.crdt.change import Action, Change, Op, ROOT
    from hypermerge_tpu.storage import block as blockmod

    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        url = repo.create({"x": 1})
        repo.close()

        repo2 = Repo(path=tmp)
        doc_id = validate_doc_url(url)
        repo2.back.load_documents_bulk([doc_id])
        doc = repo2.back.docs[doc_id]
        assert doc.opset is None
        # craft the actor's next change and deliver it like replication
        actor = repo2.back.actors[doc_id]
        head = actor.seq_head
        prev = actor.changes_in_window(0, head)
        max_op = max(c.max_op for c in prev)
        change = Change(
            actor=doc_id,
            seq=head + 1,
            start_op=max_op + 1,
            deps={},
            ops=(Op(action=Action.SET, obj=ROOT, key="x", value=99),),
        )
        # replication appends beyond the cursor; expand it like a
        # CursorMessage would
        repo2.back.cursors.update(
            repo2.back.id, doc_id, {doc_id: head + 1}
        )
        actor.feed._append_raw(blockmod.pack(change.to_json()))
        # replicated-append syncs are debounced: wait for application
        wait_until(lambda: doc.clock.get(doc_id) == head + 1)
        if live_mode == "0":
            assert doc.opset is not None
        else:
            wait_until(lambda: repo2.doc(url)["x"] == 99)
            assert doc.opset is None, "live path must not replay"
        assert repo2.doc(url)["x"] == 99
        repo2.close()


def test_bulk_load_slabs_split_dispatches(pipeline_mode):
    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        urls = [repo.create({"i": i}) for i in range(5)]
        repo.close()
        repo2 = Repo(path=tmp)
        ids = [validate_doc_url(u) for u in urls]
        repo2.back.load_documents_bulk(ids, slab=2)  # 3 dispatches
        for i, u in enumerate(urls):
            assert repo2.doc(u)["i"] == i
        repo2.close()


def test_mixed_contiguity_bulk_load_stays_fast(tmp_path, pipeline_mode):
    """One gap-y doc in a 1000-doc bulk load must NOT drag the other 999
    onto the per-op host replay path — and the fallback count is
    surfaced (VERDICT r3 weak #4 / next-round item 7)."""
    from hypermerge_tpu.crdt.change import Action, Change, Op, ROOT
    from hypermerge_tpu.ops.corpus import make_corpus
    from hypermerge_tpu.storage import block as blockmod

    urls = make_corpus(str(tmp_path), 999, 32, ops_per_change=8, threads=4)
    repo = Repo(path=str(tmp_path))
    gap_url = repo.create({"i": -1})
    # poison the created doc's feed with a seq GAP (skips head+1)
    gap_id = validate_doc_url(gap_url)
    actor = repo.back.actors[gap_id]
    head = actor.seq_head
    max_op = max(
        c.max_op for c in actor.changes_in_window(0, float("inf"))
    )
    change = Change(
        actor=gap_id,
        seq=head + 2,  # gap: head+1 never written
        start_op=max_op + 1,
        deps={},
        ops=(Op(action=Action.SET, obj=ROOT, key="late", value=1),),
    )
    actor.feed._append_raw(blockmod.pack(change.to_json()))
    repo.close()

    repo2 = Repo(path=str(tmp_path))
    ids = [validate_doc_url(u) for u in urls] + [gap_id]
    repo2.back.load_documents_bulk(ids)
    stats = repo2.back.last_bulk_stats
    assert stats["fallback"] == 1 and stats["fast"] == 999, stats
    # every contiguous doc stayed on the lazy fast path
    lazy = sum(
        1
        for u in urls
        if repo2.back.docs[validate_doc_url(u)].opset is None
    )
    assert lazy == 999, f"only {lazy}/999 docs stayed lazy"
    for u in urls[:: 100]:
        assert "t" in plainify(repo2.doc(u))
    # the gap doc host-replayed its applicable prefix
    gap_doc = plainify(repo2.doc(gap_url))
    assert gap_doc["i"] == -1 and "late" not in gap_doc
    repo2.close()


def test_actor_columns_rebuild_from_blocks(tmp_path, pipeline_mode):
    """A feed written without a sidecar (or with a deleted one) rebuilds
    its columns from blocks on first access."""
    import shutil

    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        url = repo.create({"x": 1})
        repo.change(url, lambda d: d.__setitem__("y", 2))
        want = plainify(repo.doc(url))
        repo.close()

        # blow away every sidecar (slab, legacy dirs, and v2 files)
        import os

        for root, dirs, files in os.walk(os.path.join(tmp, "feeds")):
            for d in list(dirs):
                if d.endswith(".cols"):
                    shutil.rmtree(os.path.join(root, d))
            for f in files:
                if f.endswith(".cols2") or f.startswith("cols.slab"):
                    os.remove(os.path.join(root, f))
        repo2 = Repo(path=tmp)
        doc_id = validate_doc_url(url)
        repo2.back.load_documents_bulk([doc_id])
        assert plainify(repo2.doc(url)) == want
        repo2.close()


def test_counter_docs_survive_bulk_and_fast_reopen(tmp_path, monkeypatch, pipeline_mode):
    """INC ops (counters) force the non-lean kernel path; both the bulk
    and single-doc fast opens must materialize accumulated totals."""
    from hypermerge_tpu.models import Counter

    # small batch would normally take the host kernel: force the DEVICE
    # dispatch so the lean/non-lean gate is what's under test
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")

    repo = Repo(path=str(tmp_path))
    urls = []
    for i in range(3):
        u = repo.create({"hits": Counter(0), "i": i})
        for k in range(4):
            repo.change(u, lambda d: d.increment("hits", 2))
        urls.append(u)
    want = {u: plainify(repo.doc(u)) for u in urls}
    assert want[urls[0]]["hits"] == ("__counter__", 8)
    repo.close()

    # bulk cold open
    repo2 = Repo(path=str(tmp_path))
    ids = [validate_doc_url(u) for u in urls]
    repo2.back.load_documents_bulk(ids)
    for u in urls:
        assert plainify(repo2.doc(u)) == want[u]
        assert repo2.back.docs[validate_doc_url(u)].opset is None
    repo2.close()

    # single-doc fast open
    repo3 = Repo(path=str(tmp_path))
    assert plainify(repo3.doc(urls[1])) == want[urls[1]]
    # and increments continue from the materialized total
    repo3.change(urls[1], lambda d: d.increment("hits", 1))
    assert plainify(repo3.doc(urls[1]))["hits"] == ("__counter__", 9)
    repo3.close()


def test_fast_open_uses_sidecar_not_replay():
    """An ordinary cold `open` of a cached doc decodes via the numpy
    kernel twin — no host OpSet replay (VERDICT r2 item 2)."""
    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        url = repo.create({"x": 1, "t": Text("hello")})
        repo.change(url, lambda d: d["t"].insert(5, "!"))
        want = plainify(repo.doc(url))
        repo.close()

        repo2 = Repo(path=tmp)
        h = repo2.open(url)
        doc = repo2.back.docs[validate_doc_url(url)]
        assert doc.opset is None, "fast open must not build an OpSet"
        assert plainify(h.value()) == want
        assert doc.opset is None
        # incremental change still works (lazy OpSet reconstruction)
        repo2.change(url, lambda d: d.__setitem__("y", 2))
        got = plainify(repo2.doc(url))
        assert got["y"] == 2 and got["t"] == want["t"]
        repo2.close()


def test_interactive_churn_during_bulk_load(tmp_path, pipeline_mode):
    """Interactive creates/changes racing a bulk cold open must not
    deadlock (bulk mutex) or lose work (deferred actor syncs)."""
    import threading

    from hypermerge_tpu.ops.corpus import make_corpus

    urls = make_corpus(str(tmp_path), 24, 64, threads=4)
    repo = Repo(path=str(tmp_path))
    made = []
    errors = []

    def churn():
        try:
            for i in range(15):
                u = repo.create({"i": i})
                repo.change(u, lambda d, i=i: d.__setitem__("sq", i * i))
                made.append((u, i))
        except Exception as e:  # pragma: no cover - failure capture
            errors.append(e)

    t = threading.Thread(target=churn)
    t.start()
    handles = repo.open_many(urls)
    t.join(timeout=60)
    assert not t.is_alive(), "churn thread deadlocked against bulk load"
    assert not errors, errors
    summ = repo.back.fetch_bulk_summaries()
    assert len(summ.doc_ids) == 24
    for u, i in made:
        got = plainify(repo.doc(u))
        assert got["i"] == i and got["sq"] == i * i
    for h in handles[::6]:
        v = plainify(h.value())
        assert v and "t" in v  # corpus docs carry their text field
    repo.close()


def test_open_many_lazy_handles(pipeline_mode):
    """open_many: one bulk backend load, snapshots decoded only when a
    handle is actually read; change() on a lazy handle materializes
    first."""
    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        urls = [repo.create({"i": i}) for i in range(6)]
        want = {u: plainify(repo.doc(u)) for u in urls}
        repo.close()

        repo2 = Repo(path=tmp)
        handles = repo2.open_many(urls)
        # backend is ready, but no snapshot decoded yet for unread docs
        for u in urls:
            doc = repo2.back.docs[validate_doc_url(u)]
            assert doc._announced
            assert doc.opset is None
            assert doc._snapshot_cache is None, "decode must be lazy"
        # reading a handle decodes just that doc
        assert plainify(handles[2].value()) == want[urls[2]]
        assert (
            repo2.back.docs[validate_doc_url(urls[2])]._snapshot_cache
            is not None
        )
        assert (
            repo2.back.docs[validate_doc_url(urls[3])]._snapshot_cache
            is None
        )
        # change on an unread lazy handle sees the materialized doc
        handles[4].change(lambda d: d.__setitem__("j", 40))
        got = plainify(handles[4].value())
        assert got["i"] == 4 and got["j"] == 40
        # open_many over already-open docs still yields live handles
        handles2 = repo2.open_many(urls[:2])
        assert plainify(handles2[0].value()) == want[urls[0]]
        repo2.close()


class TestV3Checkpoint:
    """v3 plane checkpoints (storage/colcache.py): one frombuffer load,
    v2 tail replay, auto-compaction, torn-write safety."""

    def _cc(self, tmp_path, name="feedX"):
        from hypermerge_tpu.storage.colcache import FileColumnStorageV2

        return FeedColumnCache(
            FileColumnStorageV2(str(tmp_path / name)), writer="actor00"
        )

    def test_checkpoint_roundtrip_planes(self, tmp_path):
        _site, history = _history(3, n_actors=1, n_mut=20)
        cc = self._cc(tmp_path)
        for c in sorted(history, key=lambda c: (c.actor, c.seq)):
            cc.append_change(c)
        want = cc.columns()
        cc.compact()

        cc2 = self._cc(tmp_path)
        got = cc2.columns()
        assert got.planes is not None  # plane-backed load
        assert np.array_equal(got.ensure_rows(), want.ensure_rows())
        assert np.array_equal(got.preds, want.preds)
        assert got.actors == want.actors and got.keys == want.keys
        assert got.n_changes == want.n_changes
        assert np.array_equal(got.row_ends, want.row_ends)

    def test_tail_after_checkpoint_merges(self, tmp_path):
        _site, history = _history(4, n_actors=1, n_mut=30)
        history = sorted(history, key=lambda c: (c.actor, c.seq))
        half = len(history) // 2
        cc = self._cc(tmp_path)
        for c in history[:half]:
            cc.append_change(c)
        cc.compact()
        for c in history[half:]:
            cc.append_change(c)  # v2 records after the checkpoint

        ref = FeedColumnCache(MemoryColumnStorage(), writer="actor00")
        for c in history:
            ref.append_change(c)

        cc2 = self._cc(tmp_path)
        got, want = cc2.columns(), ref.columns()
        assert np.array_equal(got.ensure_rows(), want.ensure_rows())
        assert np.array_equal(got.preds, want.preds)
        assert got.n_changes == want.n_changes

    def test_auto_compaction_folds_long_tails(self, tmp_path, monkeypatch):
        from hypermerge_tpu.storage.colcache import parse_v3_checkpoint

        monkeypatch.setenv("HM_CKPT_TAIL", "8")
        _site, history = _history(5, n_actors=1, n_mut=30)
        history = sorted(history, key=lambda c: (c.actor, c.seq))
        cc = self._cc(tmp_path)
        for c in history:
            cc.append_change(c)
        want_rows = cc.columns().ensure_rows().copy()
        assert len(history) >= 8

        cc2 = self._cc(tmp_path)  # load triggers auto-compact
        assert np.array_equal(cc2.columns().ensure_rows(), want_rows)
        raw = (tmp_path / "feedX").read_bytes()
        ck = parse_v3_checkpoint(raw)
        assert ck is not None and ck[5] == len(raw)  # no v2 tail left

    def test_torn_checkpoint_falls_back(self, tmp_path):
        """A truncated checkpoint (crash mid-rewrite never leaves one —
        rename is atomic — but disk corruption might) must load as
        empty, not crash; blocks are the source of truth."""
        _site, history = _history(6, n_actors=1, n_mut=15)
        cc = self._cc(tmp_path)
        for c in sorted(history, key=lambda c: (c.actor, c.seq)):
            cc.append_change(c)
        cc.compact()
        raw = (tmp_path / "feedX").read_bytes()
        (tmp_path / "feedX").write_bytes(raw[: len(raw) // 2])

        cc2 = self._cc(tmp_path)
        got = cc2.columns()
        assert got.n_changes == 0 and got.n_rows == 0

    def test_append_after_plane_load(self, tmp_path):
        """Live appends on a checkpoint-loaded cache fold planes into
        rows and keep going (the interactive-writer path)."""
        _site, history = _history(7, n_actors=1, n_mut=25)
        history = sorted(history, key=lambda c: (c.actor, c.seq))
        cc = self._cc(tmp_path)
        for c in history[:-3]:
            cc.append_change(c)
        cc.compact()
        cc2 = self._cc(tmp_path)
        assert cc2.columns().planes is not None
        for c in history[-3:]:
            cc2.append_change(c)
        ref = FeedColumnCache(MemoryColumnStorage(), writer="actor00")
        for c in history:
            ref.append_change(c)
        assert np.array_equal(
            cc2.columns().ensure_rows(), ref.columns().ensure_rows()
        )
