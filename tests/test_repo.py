"""Repo runtime end-to-end: create/change/watch/merge/fork/materialize/
meta/persistence — the repo.test.ts-shaped suite (reference
tests/repo.test.ts scenarios, SURVEY.md §4)."""

import tempfile

import pytest

from hypermerge_tpu.models import Counter, Text
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils.ids import validate_doc_url


@pytest.fixture
def repo():
    r = Repo(memory=True)
    yield r
    r.close()


def test_create_change_watch_sequence(repo):
    """Subscribers observe blank -> preview -> final (reference
    tests/repo.test.ts:8-25)."""
    url = repo.create()
    states = []
    h = repo.open(url).subscribe(lambda doc, _i: states.append(dict(doc)))
    repo.change(url, lambda d: d.__setitem__("title", "hi"))
    assert states[0] == {}  # blank
    assert {"title": "hi"} in states  # preview + final
    assert states[-1] == {"title": "hi"}
    assert repo.doc(url) == {"title": "hi"}
    h.close()


def test_create_with_init(repo):
    url = repo.create({"a": 1, "nested": {"b": [1, 2]}})
    assert repo.doc(url) == {"a": 1, "nested": {"b": [1, 2]}}


def test_open_twice_same_doc(repo):
    url = repo.create({"x": 1})
    h1 = repo.open(url)
    h2 = repo.open(url)
    assert h1.value() == h2.value() == {"x": 1}
    h1.close()
    h2.close()


def test_merge(repo):
    """Merge adopts the target's actors into the url's cursor (reference
    tests/repo.test.ts:47-101)."""
    a = repo.create({"a": 1})
    b = repo.create({"b": 2})
    repo.merge(a, b)
    assert repo.doc(a) == {"a": 1, "b": 2}
    # cursor now includes b's root actor
    a_id, b_id = validate_doc_url(a), validate_doc_url(b)
    cursor = repo.back.cursors.get(repo.back.id, a_id)
    assert b_id in cursor


def test_merge_against_pending_target_times_out(repo):
    """Merging with an unknown (never-replicated) target must not dangle
    silently forever: the pending merge expires, the handle is released,
    and the source doc is untouched (VERDICT r3 weak #7)."""
    import time

    from hypermerge_tpu.utils import keys as keymod
    from hypermerge_tpu.utils.ids import to_doc_url

    a = repo.create({"a": 1})
    bogus = to_doc_url(keymod.create().public_key)
    repo.front.merge(a, bogus, timeout=0.05)
    time.sleep(0.3)
    assert repo.doc(a) == {"a": 1}  # no merge happened, no crash
    a_id = validate_doc_url(a)
    cursor = repo.back.cursors.get(repo.back.id, a_id)
    assert validate_doc_url(bogus) not in cursor


def test_fork(repo):
    """Fork: changes to the fork don't affect the original (reference
    tests/repo.test.ts:103-127)."""
    url = repo.create({"x": 1})
    fork = repo.fork(url)
    repo.change(fork, lambda d: d.__setitem__("y", 2))
    assert repo.doc(fork) == {"x": 1, "y": 2}
    assert repo.doc(url) == {"x": 1}


def test_materialize_time_travel(repo):
    """(reference tests/repo.test.ts:129-164)."""
    url = repo.create({"x": 1})
    repo.change(url, lambda d: d.__setitem__("x", 2))
    repo.change(url, lambda d: d.__setitem__("x", 3))
    out = []
    repo.materialize(url, 2, out.append)
    assert out == [{"x": 2}]
    repo.materialize(url, 1, out.append)
    assert out[-1] == {"x": 1}


def test_meta(repo):
    """(reference tests/repo.test.ts:166-197)."""
    url = repo.create({"x": 1})
    repo.change(url, lambda d: d.__setitem__("y", 2))
    out = []
    repo.meta(url, out.append)
    meta = out[0]
    assert meta["type"] == "Document"
    assert meta["history"] == 2
    doc_id = validate_doc_url(url)
    assert any(s.startswith(doc_id) for s in meta["clock"])


def test_rich_types_through_runtime(repo):
    url = repo.create()
    repo.change(url, lambda d: d.__setitem__("t", Text("abc")))
    repo.change(url, lambda d: d.__setitem__("n", Counter(5)))
    repo.change(url, lambda d: d["t"].insert(3, "!"))
    repo.change(url, lambda d: d.increment("n", 3))
    doc = repo.doc(url)
    assert str(doc["t"]) == "abc!"
    assert int(doc["n"]) == 8


def test_change_before_ready_queues(repo):
    # an Open'd doc is pending until the backend loads it; changes queue
    url = repo.create({"x": 1})
    doc_id = validate_doc_url(url)
    # simulate a fresh frontend state by closing and reopening the doc
    repo.close_doc(url)
    h = repo.open(url)
    h.change(lambda d: d.__setitem__("y", 2))
    assert h.value() == {"x": 1, "y": 2}
    h.close()


def test_destroy(repo):
    url = repo.create({"x": 1})
    doc_id = validate_doc_url(url)
    repo.destroy(url)
    assert doc_id not in repo.back.docs
    assert repo.back.clocks.get(repo.back.id, doc_id) == {}


def test_persistence_across_restart():
    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        url = repo.create({"x": 1})
        repo.change(url, lambda d: d.__setitem__("t", Text("persist")))
        repo.change(url, lambda d: d["t"].insert(7, "!"))
        repo_id = repo.id
        repo.close()

        repo2 = Repo(path=tmp)
        assert repo2.id == repo_id  # same self.repo keypair
        doc = repo2.doc(url)
        assert str(doc["t"]) == "persist!"
        assert doc["x"] == 1
        # and the doc is still writable after restart
        repo2.change(url, lambda d: d.__setitem__("again", True))
        assert repo2.doc(url)["again"] is True
        repo2.close()


def test_bulk_cold_start_matches_incremental():
    with tempfile.TemporaryDirectory() as tmp:
        repo = Repo(path=tmp)
        urls = []
        for i in range(5):
            url = repo.create({"i": i, "t": Text(f"doc{i}")})
            repo.change(url, lambda d: d["t"].insert(0, ">"))
            urls.append(url)
        repo.close()

        repo2 = Repo(path=tmp)
        ids = [validate_doc_url(u) for u in urls]
        repo2.back.load_documents_bulk(ids)
        for i, url in enumerate(urls):
            doc = repo2.doc(url)
            assert doc["i"] == i
            assert str(doc["t"]) == f">doc{i}"
        repo2.close()


def test_clockstore_updates(repo):
    """ClockStore mirrors doc clocks after changes (reference
    tests/repo.test.ts:215-248 ClockStore consistency)."""
    url = repo.create({"x": 1})
    repo.change(url, lambda d: d.__setitem__("x", 2))
    doc_id = validate_doc_url(url)
    # clock rows flush debounced (one executemany per burst): settle it
    repo.back._stores.flush_now()
    stored = repo.back.clocks.get(repo.back.id, doc_id)
    assert stored == {doc_id: 2}


def test_store_debounce_off_writes_cursor_rows_synchronously():
    """HM_STORE_DEBOUNCE=0 is the correctness twin for the r8 store
    coalescing: BOTH clock and cursor rows must land synchronously,
    with nothing left inside the debouncer — otherwise bisecting a
    store-coalescing bug with the knob off doesn't reproduce the
    pre-debounce behavior."""
    import os

    os.environ["HM_STORE_DEBOUNCE"] = "0"
    try:
        repo = Repo(memory=True)
        back = repo.back
        marks = []
        orig_mark = back._stores.mark
        back._stores.mark = lambda *a, **kw: (
            marks.append(a), orig_mark(*a, **kw)
        )
        url = repo.create({"x": 1})
        repo.change(url, lambda d: d.__setitem__("x", 2))
        doc_id = validate_doc_url(url)
        # neither clock ("c") nor cursor ("u") rows went through the
        # debouncer...
        assert marks == []
        # ...and the rows are already durable, no flush needed
        assert back.clocks.get(back.id, doc_id) == {doc_id: 2}
        repo.close()
    finally:
        del os.environ["HM_STORE_DEBOUNCE"]


def test_debug_info(repo):
    url = repo.create({"x": 1})
    info = repo.debug(url)
    assert info["mode"] == "write"
    assert info["seq"] == 2


def test_open_unknown_doc_stays_pending(repo):
    """Opening a doc we have no history for must NOT render an empty doc —
    it waits for replication (minimumClock gate)."""
    from hypermerge_tpu.utils import keys

    ghost_url = "hypermerge:/" + keys.create().public_key
    h = repo.open(ghost_url)
    with pytest.raises(TimeoutError):
        h.value(timeout=0.2)
    h.close()


def test_handle_fork_and_merge_conveniences():
    """Handle.fork()/merge() (reference src/Handle.ts:21-36)."""
    repo = Repo(memory=True)
    h = repo.open(repo.create({"a": 1}))
    h2 = repo.open(h.fork())
    assert h2.value() == {"a": 1}
    h2.change(lambda d: d.__setitem__("b", 2))
    h.merge(h2)
    import time as _t

    deadline = _t.time() + 10
    while _t.time() < deadline and h.value().get("b") != 2:
        _t.sleep(0.02)
    assert h.value() == {"a": 1, "b": 2}
    repo.close()


def test_actor_backfill_callbacks_out_of_order(repo):
    """Replicated blocks whose per-block append callbacks arrive out of
    order — or never — must still become visible. Feed.append_verified
    fires its listeners OUTSIDE the feed lock, so two concurrent
    backfill batches (multi-source repair after churn) can interleave
    their _on_append fan-outs. Regression: the actor's slot list grew
    exactly one slot per callback, so an out-of-order index raised
    IndexError mid-fan-out and left the list short forever — seq_head
    and changes_in_window clamped to the stale head and the doc never
    converged (50-peer churn soak). The feed's block log is
    authoritative; the slot list must re-size from it on every read."""
    from hypermerge_tpu.crdt.change import Action, Change, Op, ROOT
    from hypermerge_tpu.storage import block as blockmod

    url = repo.create({"edits": []})
    doc_id = validate_doc_url(url)
    repo.change(url, lambda d: d["edits"].append(0))
    actor = repo.back.actors[doc_id]
    feed = actor.feed
    head = actor.seq_head
    max_op = max(
        c.max_op for c in actor.changes_in_window(0, float("inf"))
    )
    blocks = [
        blockmod.pack_change(
            Change(
                actor=doc_id,
                seq=head + 1 + k,
                start_op=max_op + 1 + k,
                deps={},
                ops=(
                    Op(action=Action.SET, obj=ROOT, key=f"k{k}", value=k),
                ),
            ).to_json()
        )
        for k in range(3)
    ]
    # the batch lands in the block log first (as append_verified does
    # under the feed lock); the per-block callbacks race in afterwards
    with feed._lock:
        for b in blocks:
            feed._storage.append(b)
    # callbacks arrive newest-first; the third never arrives at all
    # (a concurrent fan-out died mid-batch)
    actor._on_append(head + 1, blocks[1])
    actor._on_append(head, blocks[0])
    assert actor.seq_head == head + 3
    window = actor.changes_in_window(head, float("inf"))
    assert [c.seq for c in window] == [head + 1, head + 2, head + 3]
    # the never-delivered block self-healed via the lazy feed decode
    assert window[-1].ops[0].key == "k2"
