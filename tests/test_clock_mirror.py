"""DeviceClockMirror — the ClockStore's device-resident query twin
(VERDICT r5 item 4: bulk clock queries must not re-upload the matrix).

Consistency is pinned against the sqlite rows through the attach_mirror
write path: every ClockStore mutation (update/update_many/set/delete)
must leave mirror.rows() equal to a host fold of the raw table.
"""

import random

import numpy as np

from hypermerge_tpu.ops.clock_mirror import INT32_INF, DeviceClockMirror
from hypermerge_tpu.storage.sql import SqlDatabase
from hypermerge_tpu.storage.stores import ClockStore


def _host_rows(store, repo_id):
    rows = store.db.query(
        "SELECT doc_id, actor_id, seq FROM clocks WHERE repo_id=?",
        (repo_id,),
    )
    out = {}
    for doc_id, actor, seq in rows:
        out.setdefault(doc_id, {})[actor] = min(seq, INT32_INF)
    return out


class TestMirrorAlgebra:
    def test_update_union_dominated(self):
        m = DeviceClockMirror(capacity_docs=4, capacity_actors=4)
        m.update("d1", {"a": 3, "b": 1})
        m.update("d2", {"a": 1, "c": 5})
        m.update("d1", {"a": 2, "b": 4})  # monotonic: a stays 3
        assert m.union() == {"a": 3, "b": 4, "c": 5}
        assert set(m.dominated({"a": 3, "b": 4, "c": 5})) == {"d1", "d2"}
        assert m.dominated({"a": 3, "b": 4}) == ["d1"]
        assert m.dominated({"a": 1}) == []

    def test_set_overwrites_and_delete_clears(self):
        m = DeviceClockMirror(capacity_docs=2, capacity_actors=2)
        m.update("d1", {"a": 9})
        m.set("d1", {"b": 2})
        assert m.rows() == {"d1": {"b": 2}}
        m.delete_doc("d1")
        assert m.rows() == {}
        assert m.union() == {}

    def test_growth_past_capacity(self):
        m = DeviceClockMirror(capacity_docs=2, capacity_actors=2)
        for i in range(40):
            m.update(f"d{i}", {f"actor{i}": i + 1})
        rows = m.rows()
        assert len(rows) == 40
        assert rows["d39"] == {"actor39": 40}
        assert m.union()["actor7"] == 8

    def test_top_k_dominated(self):
        m = DeviceClockMirror(capacity_docs=8, capacity_actors=4)
        for i in range(6):
            m.update(f"d{i}", {"a": i + 1})
        got = m.top_k_dominated({"a": 4}, k=8)
        # docs with a<=4, highest clock first
        assert got == ["d3", "d2", "d1", "d0"]

    def test_infinity_clamps(self):
        m = DeviceClockMirror(capacity_docs=2, capacity_actors=2)
        m.update("d", {"a": 2**60})
        assert m.rows()["d"]["a"] == INT32_INF


class TestStoreConsistency:
    def test_mirror_tracks_every_store_write(self):
        db = SqlDatabase(":memory:")
        store = ClockStore(db)
        rng = random.Random(7)
        # pre-existing rows are seeded at attach time
        store.update("r", "pre", {"a0": 5})
        m = DeviceClockMirror(capacity_docs=4, capacity_actors=4)
        store.attach_mirror("r", m)
        assert m.rows() == _host_rows(store, "r")

        docs = [f"doc{i}" for i in range(12)]
        actors = [f"actor{i}" for i in range(6)]
        for step in range(120):
            op = rng.random()
            doc = rng.choice(docs)
            clock = {
                rng.choice(actors): rng.randrange(1, 100)
                for _ in range(rng.randrange(1, 4))
            }
            if op < 0.6:
                store.update("r", doc, clock)
            elif op < 0.8:
                store.update_many(
                    "r", {rng.choice(docs): clock for _ in range(3)}
                )
            elif op < 0.9:
                store.set("r", doc, clock)
            else:
                store.delete_doc(doc)
        assert m.rows() == _host_rows(store, "r")

    def test_union_matches_host_fold(self):
        db = SqlDatabase(":memory:")
        store = ClockStore(db)
        m = DeviceClockMirror()
        store.attach_mirror("r", m)
        rng = np.random.default_rng(3)
        for i in range(200):
            store.update(
                "r",
                f"d{i}",
                {f"a{j}": int(rng.integers(1, 1000)) for j in range(8)},
            )
        want = {}
        for clock in _host_rows(store, "r").values():
            for a, s in clock.items():
                want[a] = max(want.get(a, 0), s)
        assert m.union() == want

    def test_mirror_is_repo_scoped(self):
        """Writes for OTHER repo ids sharing the database never touch
        the mirror (set() is a hard per-repo overwrite)."""
        db = SqlDatabase(":memory:")
        store = ClockStore(db)
        store.update("A", "D", {"a1": 7})
        store.update("B", "D", {"a2": 9})
        m = DeviceClockMirror()
        store.attach_mirror("A", m)
        assert m.rows() == {"D": {"a1": 7}}
        store.set("B", "D", {"a2": 1})  # must not erase A's view
        store.update("B", "D2", {"a3": 3})
        assert m.rows() == {"D": {"a1": 7}}
        store.update("A", "D", {"a1": 8})
        assert m.rows() == {"D": {"a1": 8}}

    def test_union_query_routes_through_mirror(self):
        db = SqlDatabase(":memory:")
        store = ClockStore(db)
        m = DeviceClockMirror()
        store.attach_mirror("r", m)
        store.update("r", "d1", {"a": 3})
        store.update("r", "d2", {"b": 5})
        assert store.union_query("r") == {"a": 3, "b": 5}
        assert set(store.dominated_query("r", {"a": 3, "b": 5})) == {
            "d1", "d2",
        }
        assert store.dominated_query("r", {"a": 3}) == ["d1"]
        # doc-subset queries still answer from sqlite (mirror bypassed)
        assert store.union_query("r", ["d1"]) == {"a": 3}


class TestSeedBulk:
    def test_seed_bulk_then_grow(self):
        clocks = np.arange(12, dtype=np.int32).reshape(4, 3) + 1
        m = DeviceClockMirror(capacity_docs=2, capacity_actors=2)
        m.seed_bulk([f"d{i}" for i in range(4)], ["a", "b", "c"], clocks)
        assert m.rows()["d3"] == {"a": 10, "b": 11, "c": 12}
        assert m.union() == {"a": 10, "b": 11, "c": 12}
        # growth after seeding: new doc past the padded capacity
        for i in range(4, 40):
            m.update(f"d{i}", {"z": i})
        assert m.rows()["d39"] == {"z": 39}
        assert m.union()["z"] == 39

    def test_seed_bulk_refuses_non_empty(self):
        import pytest

        m = DeviceClockMirror()
        m.update("d", {"a": 1})
        with pytest.raises(RuntimeError):
            m.seed_bulk(["x"], ["a"], np.ones((1, 1), np.int32))
