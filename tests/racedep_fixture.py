"""Shared racedep-on-for-this-module fixture (test_live,
test_serve_races, test_write_plane) — the lockset sibling of
tests/lockdep_fixture.py.

HM_RACEDEP=1 wraps every non-`unguarded` attribute of the guard
manifest (hypermerge_tpu/analysis/guards.py) in an Eraser-style
lockset descriptor: each access intersects the per-(object, attribute)
candidate lockset with the accessing thread's held locks, so a shared
field that no lock consistently guards is REPORTED without the race
ever needing to fire. The write-plane split relocated the engine-lock
guard rows onto the per-doc classes (`_LiveDoc` under `doc.emit`,
`WriteAheadLog` under `store.wal`) — running the live twin + serve
race suites fully instrumented verifies the relocated map against
real churn; the module teardown asserts a clean lockset report.

`blocking` violations are asserted too (see lockdep_fixture.py): the
only no-block class left is `live.engine`, and any blocking call
under it regresses the zero-lock-debt gate.
"""

import os

import pytest

from hypermerge_tpu.analysis import lockdep


def racedep_suite():
    """Module-scoped autouse fixture factory: instrument the guard
    manifest's attributes for every object created while this module's
    tests run, and assert a clean lockset report at teardown."""

    @pytest.fixture(autouse=True, scope="module")
    def _racedep_suite():
        was_env = os.environ.get("HM_RACEDEP")
        os.environ["HM_RACEDEP"] = "1"
        lockdep.install_racedep()  # implies lockdep enable
        yield
        if was_env is None:
            os.environ.pop("HM_RACEDEP", None)
        else:
            os.environ["HM_RACEDEP"] = was_env
        try:
            lockdep.assert_clean(
                msg="the suite's churn surfaced lockset findings:",
            )
        finally:
            lockdep.uninstall_racedep()

    return _racedep_suite
