"""Clock algebra truth tables — host pure fns and device kernels must agree.

Mirrors the reference's tests/unit.test.ts (cmp/union truth table) and adds a
randomized host==device equivalence sweep the reference lacks.
"""

import math
import random

import jax.numpy as jnp
import pytest

from hypermerge_tpu.crdt import clock as C
from hypermerge_tpu.ops import clock_kernels as K


def test_cmp_truth_table():
    cases = [
        ({}, {}, C.Ordering.EQ),
        ({"a": 1}, {"a": 1}, C.Ordering.EQ),
        ({"a": 2}, {"a": 1}, C.Ordering.GT),
        ({"a": 1}, {"a": 2}, C.Ordering.LT),
        ({"a": 1}, {}, C.Ordering.GT),
        ({}, {"a": 1}, C.Ordering.LT),
        ({"a": 1}, {"b": 1}, C.Ordering.CONCUR),
        ({"a": 2, "b": 1}, {"a": 1, "b": 2}, C.Ordering.CONCUR),
        ({"a": 2, "b": 2}, {"a": 1, "b": 2}, C.Ordering.GT),
        ({"a": 1, "b": 1}, {"a": 1, "b": 1, "c": 1}, C.Ordering.LT),
    ]
    for a, b, expected in cases:
        assert C.cmp(a, b) is expected, (a, b)


def test_union_intersection():
    a = {"a": 3, "b": 1}
    b = {"a": 1, "b": 5, "c": 2}
    assert C.union(a, b) == {"a": 3, "b": 5, "c": 2}
    assert C.intersection(a, b) == {"a": 1, "b": 1}
    assert C.intersection({"a": 1}, {"b": 1}) == {}


def test_gte_equivalent():
    assert C.gte({"a": 2, "b": 2}, {"a": 2})
    assert not C.gte({"a": 2}, {"a": 2, "b": 1})
    assert C.equivalent({"a": 1}, {"a": 1})
    assert not C.equivalent({"a": 1}, {"a": 2})


def test_strs_codec_roundtrip():
    clock = {"actorA": 5, "actorB": C.INFINITY_SEQ}
    strs = C.clock_to_strs(clock)
    assert strs == ["actorA:5", "actorB"]
    assert C.strs_to_clock(strs) == clock
    assert C.clock_to_strs({"x": math.inf}) == ["x"]


def test_add_to_in_place():
    acc = {"a": 1}
    C.add_to(acc, {"a": 3, "b": 2})
    C.add_to(acc, {"a": 2})
    assert acc == {"a": 3, "b": 2}


def test_pack_unpack_roundtrip():
    clocks = [{"a": 1, "c": 7}, {"b": 2}, {}]
    actors = C.actor_axis(clocks)
    rows = C.pack(clocks, actors)
    assert C.unpack(rows, actors) == clocks


_CODE_TO_ORD = {K.EQ: C.Ordering.EQ, K.GT: C.Ordering.GT,
                K.LT: C.Ordering.LT, K.CONCUR: C.Ordering.CONCUR}


def test_device_matches_host_randomized():
    rnd = random.Random(7)
    actors = [f"actor{i}" for i in range(6)]
    clocks = []
    for _ in range(64):
        clocks.append(
            {a: rnd.randint(1, 9) for a in actors if rnd.random() < 0.6}
        )
    rows = K.pack_clocks(C.pack(clocks, actors))
    n = len(clocks)
    # all-pairs cmp on device in one dispatch; single bulk transfer back
    import numpy as np

    a = jnp.repeat(rows, n, axis=0)
    b = jnp.tile(rows, (n, 1))
    codes = np.asarray(K.cmp(a, b))
    unions = np.asarray(K.union(a, b))
    inters = np.asarray(K.intersection(a, b))
    gtes = np.asarray(K.gte(a, b))
    for i in range(n):
        for j in range(n):
            k = i * n + j
            assert _CODE_TO_ORD[int(codes[k])] is C.cmp(clocks[i], clocks[j])
            assert bool(gtes[k]) == C.gte(clocks[i], clocks[j])
            host_u = C.pack([C.union(clocks[i], clocks[j])], actors)[0]
            assert list(map(int, unions[k])) == host_u
            host_i = C.pack([C.intersection(clocks[i], clocks[j])], actors)[0]
            assert list(map(int, inters[k])) == host_i


def test_union_reduce_matches_fold():
    rnd = random.Random(3)
    actors = [f"a{i}" for i in range(4)]
    clocks = [{a: rnd.randint(0, 5) for a in actors} for _ in range(50)]
    rows = K.pack_clocks(C.pack(clocks, actors))
    device = list(map(int, K.union_reduce(rows)))
    host = {}
    for c in clocks:
        C.add_to(host, c)
    assert device == C.pack([host], actors)[0]


def test_satisfied_and_cursor_window():
    doc = K.pack_clocks([[3, 1, 0]])
    minimum = K.pack_clocks([[2, 1, 0]])
    assert bool(K.satisfied(doc, minimum)[0])
    minimum2 = K.pack_clocks([[2, 2, 0]])
    assert not bool(K.satisfied(doc, minimum2)[0])

    cursor = K.pack_clocks([[5, 1, int(K.INT32_INF)]])
    window = K.cursor_window(doc, cursor)
    assert list(map(int, window[0])) == [2, 0, int(K.INT32_INF)]


def test_infinity_clamps_to_int32():
    rows = K.pack_clocks(C.pack([{"a": C.INFINITY_SEQ}], ["a"]))
    assert int(rows[0, 0]) == int(K.INT32_INF)


def test_pack_handles_math_inf():
    rows = C.pack([{"a": math.inf, "b": 2}], ["a", "b"])
    assert rows == [[C.INFINITY_SEQ, 2]]


def test_top_k_dominated_with_inf_entries():
    clocks = K.pack_clocks(
        [[int(K.INT32_INF), int(K.INT32_INF)], [1, 1], [9, 9]]
    )
    query = K.pack_clocks([[int(K.INT32_INF), int(K.INT32_INF)]])[0]
    scores, idx = K.top_k_dominated(clocks, query, 3)
    # all three dominated; the inf-clock doc must rank first, not wrap negative
    assert int(idx[0]) == 0 and int(scores[0]) > 0


def test_inf_and_infinity_seq_compare_equal():
    a = {"x": math.inf}
    b = C.strs_to_clock(C.clock_to_strs(a))
    assert b == {"x": C.INFINITY_SEQ}
    assert C.equivalent(a, b)
    assert C.cmp(a, b) is C.Ordering.EQ
