"""Selector-based async transport (net/aio.py, HM_NET_ASYNC=1): the
thread-per-connection stack's bit-compatible twin on ONE loop thread.

What the 1000-peer claim rests on, verified here at CI scale:

- the Duplex contract holds over the loop (roundtrip, buffering,
  close listeners, shed policy, keepalive half-open detection);
- the two stacks interoperate ON THE WIRE in either direction,
  identity auth included (the =0/=1 twin seam);
- a 50-daemon fleet costs O(daemons + pool) threads, not
  O(connections x 4) — the thread-census regression test;
- the legacy stack's accept path is a BOUNDED handshake pool, not a
  thread per accepted socket (the tcp.py accept-storm fix);
- the async supervisor state machine (dial/backoff/redial with no
  parked session thread) survives failed dials and mid-burst drops;
- seeded kill/heal chaos over FaultDuplex-wrapped aio transports
  reconverges bit-identically to an unfaulted loopback twin, across
  HM_CURSOR_DELTA x HM_NET_ASYNC env combinations (the delta-cursor
  fuzz + the chaos matrix over aio).

Runs fully instrumented: the lockdep + racedep module fixtures verify
the net.aio / net.aio.conn / net.aio.dispatch / net.tcp.accept lock
classes and the AioLoop/AioDuplex guard-manifest rows against real
churn."""

import socket as sockmod
import threading
import time

import pytest

from hypermerge_tpu import telemetry
from hypermerge_tpu.net.aio import AioDuplex, get_loop
from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
from hypermerge_tpu.net.resilience import BACKOFF, CONNECTING, STOPPED
from hypermerge_tpu.net.tcp import TcpDuplex, TcpSwarm
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils import base58, crypto

from helpers import wait_until
from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite

_lockdep_suite = lockdep_suite()
_racedep_suite = racedep_suite()


@pytest.fixture
def fast_redial(monkeypatch):
    monkeypatch.setenv("HM_REDIAL_BASE_MS", "20")
    monkeypatch.setenv("HM_REDIAL_MAX_S", "0.25")


def _counter(name):
    return telemetry.snapshot().get(name, 0)


def _tcp_pair():
    """A real accepted TCP socket pair (socketpair lacks getpeername
    quirks some paths hit)."""
    srv = sockmod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    c = sockmod.socket()
    c.connect(srv.getsockname())
    s, _ = srv.accept()
    srv.close()
    return c, s


class TestAioDuplex:
    def test_roundtrip_both_directions(self):
        a, b = sockmod.socketpair()
        da = AioDuplex(a, is_client=True)
        db = AioDuplex(b, is_client=False)
        got_a, got_b = [], []
        da.on_message(got_a.append)
        db.on_message(got_b.append)
        da.send({"n": 1})
        da.send({"n": 2})
        db.send({"r": 3})
        wait_until(lambda: got_b == [{"n": 1}, {"n": 2}])
        wait_until(lambda: got_a == [{"r": 3}])
        da.close()
        wait_until(lambda: db.closed)

    def test_rx_buffers_until_subscribe(self):
        """utils.queue.Queue contract: frames arriving before the
        subscriber registers are buffered, then delivered in order."""
        a, b = sockmod.socketpair()
        da = AioDuplex(a, is_client=True)
        db = AioDuplex(b, is_client=False)
        for i in range(5):
            da.send({"i": i})
        time.sleep(0.3)  # frames land before anyone subscribes
        got = []
        db.on_message(got.append)
        wait_until(lambda: got == [{"i": i} for i in range(5)])
        da.close()
        db.close()

    def test_identity_auth_pins_peer(self):
        import os

        seed_a = os.urandom(32)
        seed_b = os.urandom(32)
        ready = []
        a, b = _tcp_pair()
        da = AioDuplex(
            a, is_client=True, identity=seed_a,
            on_ready=lambda d, e: ready.append(("a", e)),
        )
        db = AioDuplex(
            b, is_client=False, identity=seed_b,
            on_ready=lambda d, e: ready.append(("b", e)),
        )
        wait_until(lambda: len(ready) == 2)
        assert all(e is None for _s, e in ready), ready
        assert da.peer_identity == base58.encode(
            crypto.public_key(seed_b)
        )
        assert db.peer_identity == base58.encode(
            crypto.public_key(seed_a)
        )
        da.close()
        db.close()

    def test_interop_with_tcp_duplex_both_roles(self):
        """Bit-compatibility on the wire: a loop-driven endpoint talks
        to a thread-per-connection endpoint, with identity auth, in
        BOTH role assignments."""
        import os

        for aio_is_client in (True, False):
            seed_a = os.urandom(32)
            seed_t = os.urandom(32)
            c, s = _tcp_pair()
            ready = []
            da = AioDuplex(
                c if aio_is_client else s,
                is_client=aio_is_client,
                identity=seed_a,
                on_ready=lambda d, e: ready.append(e),
            )
            dt = TcpDuplex(
                s if aio_is_client else c,
                is_client=not aio_is_client,
                identity=seed_t,
            )
            wait_until(lambda: ready == [None])
            got_a, got_t = [], []
            da.on_message(got_a.append)
            dt.on_message(got_t.append)
            da.send({"from": "aio"})
            dt.send({"from": "tcp"})
            wait_until(lambda: got_t == [{"from": "aio"}])
            wait_until(lambda: got_a == [{"from": "tcp"}])
            assert da.peer_identity == base58.encode(
                crypto.public_key(seed_t)
            )
            assert dt.peer_identity == base58.encode(
                crypto.public_key(seed_a)
            )
            da.close()
            wait_until(lambda: dt.closed)

    def test_close_fires_listeners_and_retires_gauge(self):
        before = _counter("net.aio.conns")
        a, b = sockmod.socketpair()
        da = AioDuplex(a, is_client=True)
        db = AioDuplex(b, is_client=False)
        wait_until(lambda: _counter("net.aio.conns") == before + 2)
        closed = []
        db.on_close(lambda: closed.append(True))
        da.close()
        wait_until(lambda: db.closed and closed == [True])
        wait_until(lambda: _counter("net.aio.conns") == before)
        # registering after close fires immediately (TcpDuplex rule)
        late = []
        db.on_close(lambda: late.append(True))
        assert late == [True]

    def test_non_draining_peer_sheds_connection(self, monkeypatch):
        """Same shed policy as TcpDuplex: past the outbox cap with a
        stalled peer the connection sheds instead of growing forever —
        and the loop thread stays responsive for OTHER connections."""
        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_TCP_OUTBOX_MB", "0.01")  # ~10 KB
        monkeypatch.setenv("HM_TCP_STALL_S", "0.2")
        a, b = sockmod.socketpair()
        a.setsockopt(sockmod.SOL_SOCKET, sockmod.SO_SNDBUF, 4096)
        b.setsockopt(sockmod.SOL_SOCKET, sockmod.SO_RCVBUF, 4096)
        d = AioDuplex(a)
        # a healthy bystander pair on the SAME loop
        c1, c2 = sockmod.socketpair()
        h1, h2 = AioDuplex(c1), AioDuplex(c2)
        got = []
        h2.on_message(got.append)
        payload = {"pad": "x" * 4096}
        deadline = time.time() + 10
        while not d.closed and time.time() < deadline:
            d.send(payload)
        assert d.closed, "outbox grew past the cap without shedding"
        h1.send({"still": "alive"})
        wait_until(lambda: got == [{"still": "alive"}])
        b.close()
        h1.close()
        h2.close()

    def test_keepalive_sheds_half_open(self, monkeypatch):
        """The timer-wheel keepalive detects a silent peer within
        2 * HM_NET_PING_S * HM_NET_PING_MISSES — no thread per duplex."""
        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_NET_PING_S", "0.2")
        monkeypatch.setenv("HM_NET_PING_MISSES", "2")
        a, b = sockmod.socketpair()
        t0 = time.monotonic()
        d = AioDuplex(a)
        wait_until(lambda: d.closed, timeout=5)
        assert time.monotonic() - t0 <= 2 * 0.2 * 2 + 0.5
        b.close()

    def test_healthy_idle_pair_stays_up(self, monkeypatch):
        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_NET_PING_S", "0.15")
        monkeypatch.setenv("HM_NET_PING_MISSES", "1")
        a, b = sockmod.socketpair()
        da, db = AioDuplex(a), AioDuplex(b)
        got = []
        db.on_message(got.append)
        time.sleep(1.0)  # ~7 ping periods, miss budget 1
        assert not da.closed and not db.closed
        assert got == []  # keepalive frames never reach subscribers
        da.send({"still": "works"})
        wait_until(lambda: got == [{"still": "works"}])
        da.close()
        db.close()


class TestAsyncSwarm:
    def test_two_repos_over_async_tcp(self, monkeypatch):
        monkeypatch.setenv("HM_NET_ASYNC", "1")
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"over": "aio"})
        assert rb.open(url).value(timeout=10) == {"over": "aio"}
        rb.change(url, lambda d: d.__setitem__("back", True))
        wait_until(lambda: ra.doc(url).get("back") is True)
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()

    def test_async_and_legacy_swarms_interoperate(self, monkeypatch):
        """The =0 / =1 twins are bit-compatible END TO END: a legacy
        swarm and an async swarm converge a doc in both directions."""
        monkeypatch.setenv("HM_NET_ASYNC", "0")
        sa = TcpSwarm()  # legacy listener
        monkeypatch.setenv("HM_NET_ASYNC", "1")
        sb = TcpSwarm()  # async dialer
        ra, rb = Repo(memory=True), Repo(memory=True)
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"mode": "mixed"})
        assert rb.open(url).value(timeout=10) == {"mode": "mixed"}
        rb.change(url, lambda d: d.__setitem__("ok", 1))
        wait_until(lambda: ra.doc(url).get("ok") == 1)
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()

    def test_fifty_daemon_thread_census(self, monkeypatch):
        """THE regression test for the tentpole: 50 dialing swarms plus
        one listener, 100 live connections, and the process pays
        O(daemons + pool) threads — one accepter per swarm, one shared
        loop, a bounded dispatch pool — NOT O(connections x 4). A
        supervised session owns no parked thread either."""
        monkeypatch.setenv("HM_NET_ASYNC", "1")
        monkeypatch.setenv("HM_NET_PING_S", "0")  # census, not liveness
        n = 50
        get_loop()  # pre-created so the census counts swarm cost only
        t0 = threading.active_count()
        conns0 = _counter("net.aio.conns")
        central = TcpSwarm()
        clients = [TcpSwarm() for _ in range(n)]
        try:
            for c in clients:
                c.connect(central.address)
            wait_until(
                lambda: len(central._duplexes) == n
                and all(len(c._duplexes) == 1 for c in clients),
                timeout=60,
            )
            assert _counter("net.aio.conns") >= conns0 + 2 * n
            # (n+1) accept threads + dispatch pool + slack; the legacy
            # stack would sit at >= 4 threads per connection here
            delta = threading.active_count() - t0
            assert delta <= (n + 1) + 12, (
                f"{delta} new threads for {n} daemons"
            )
            # async sessions park no thread (the `_thread` attr is the
            # legacy redial loop's)
            for c in clients:
                for s in c.supervisor.sessions():
                    assert getattr(s, "_thread", None) is None
        finally:
            central.destroy()
            for c in clients:
                c.destroy()
        wait_until(
            lambda: _counter("net.aio.conns") <= conns0, timeout=30
        )

    def test_accept_storm_bounded_thread_pool(self, monkeypatch):
        """tcp.py legacy accept path regression: a storm of 30
        non-handshaking sockets parks in the bounded pool's queue
        (HM_TCP_ACCEPT_POOL) instead of spawning 30 handshake
        threads."""
        monkeypatch.setenv("HM_NET_ASYNC", "0")
        sw = TcpSwarm()
        t0 = threading.active_count()
        socks = []
        try:
            for _ in range(30):
                c = sockmod.socket()
                c.connect(sw.address)
                socks.append(c)
            deadline = time.time() + 2
            worst = 0
            while time.time() < deadline:
                worst = max(worst, threading.active_count() - t0)
                time.sleep(0.05)
            assert worst <= 8 + 3, (
                f"{worst} threads spawned by a 30-socket accept storm"
            )
        finally:
            for c in socks:
                c.close()
            sw.destroy()


class TestAsyncSupervisor:
    def test_failed_dial_backs_off_and_retries(
        self, fast_redial, monkeypatch
    ):
        monkeypatch.setenv("HM_NET_ASYNC", "1")
        port = sockmod.socket()
        port.bind(("127.0.0.1", 0))
        dead = port.getsockname()
        port.close()  # nothing listens here
        sw = TcpSwarm()
        try:
            s = sw.connect(dead)
            wait_until(lambda: sw.supervisor.stats["dials"] >= 2)
            assert s.failures >= 1
            assert s.state in (BACKOFF, CONNECTING)
        finally:
            sw.destroy()
        assert s.state == STOPPED

    def test_redial_after_drop_resumes_replication(
        self, fast_redial, monkeypatch
    ):
        monkeypatch.setenv("HM_NET_ASYNC", "1")
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"v": 1})
        assert rb.open(url).value(timeout=10)["v"] == 1
        for d in list(sb._duplexes):  # hard-drop b's transports
            d.close()
        ra.change(url, lambda d: d.__setitem__("v", 2))
        # the supervised session redials on its own — no connect() here
        wait_until(lambda: rb.doc(url).get("v") == 2, timeout=20)
        assert sb.supervisor.stats["reconnects"] >= 1
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()


def _apply_script(repo_a, repo_b, url, lo, hi):
    for i in range(lo, hi):
        repo_a.change(url, lambda d, i=i: d["a"].append(i))
        repo_b.change(url, lambda d, i=i: d["b"].append(i))


def _loopback_twin_state(n_total):
    """The converged state an UNFAULTED, legacy-transport pair reaches
    on the same edit script — the bit-identical oracle."""
    from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm

    hub = LoopbackHub()
    ra, rb = Repo(memory=True), Repo(memory=True)
    ra.set_swarm(LoopbackSwarm(hub))
    rb.set_swarm(LoopbackSwarm(hub))
    url = ra.create({"a": [], "b": []})
    assert rb.open(url).value(timeout=10) is not None
    _apply_script(ra, rb, url, 0, n_total)
    want = {"a": list(range(n_total)), "b": list(range(n_total))}
    wait_until(lambda: ra.doc(url) == want and rb.doc(url) == want)
    state = ra.doc(url)
    ra.close()
    rb.close()
    return state


class TestChaosMatrixOverAio:
    """Seeded kill/heal chaos (the existing FaultPlan schedules) across
    the HM_CURSOR_DELTA x HM_NET_ASYNC matrix — the (0,0) cell is
    tests/test_chaos.py. FaultDuplex wraps the aio transport through
    the same public Duplex surface it wraps TcpDuplex through."""

    @pytest.mark.parametrize(
        "delta,asyncm", [("1", "1"), ("0", "1"), ("1", "0")]
    )
    def test_kill_heal_reconverges_bit_identical(
        self, delta, asyncm, fast_redial, monkeypatch
    ):
        monkeypatch.setenv("HM_CURSOR_DELTA", delta)
        monkeypatch.setenv("HM_NET_ASYNC", asyncm)
        cur0 = (
            _counter("net.cursor.delta_tx")
            + _counter("net.cursor.suppressed")
        )
        plan = FaultPlan(seed=11, events=[(1, "kill"), (2, "heal")])
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa = TcpSwarm()
        fb = FaultSwarm(TcpSwarm(), plan)
        ra.set_swarm(sa)
        rb.set_swarm(fb)
        fb.connect(sa.address)
        url = ra.create({"a": [], "b": []})
        assert rb.open(url).value(timeout=10) is not None
        n1, n2, n3 = 4, 4, 4
        _apply_script(ra, rb, url, 0, n1)  # healthy phase
        fb.tick()  # kill
        wait_until(lambda: plan.down)
        _apply_script(ra, rb, url, n1, n1 + n2)  # partitioned edits
        fb.tick()  # heal: the supervised redial goes through
        _apply_script(ra, rb, url, n1 + n2, n1 + n2 + n3)
        monkeypatch.setenv("HM_NET_ASYNC", "0")  # oracle on legacy
        want = _loopback_twin_state(n1 + n2 + n3)
        wait_until(
            lambda: ra.doc(url) == want and rb.doc(url) == want,
            timeout=60,
        )
        if delta == "1":
            # steady-state gossip actually ran in delta mode
            assert (
                _counter("net.cursor.delta_tx")
                + _counter("net.cursor.suppressed")
            ) > cur0
        ra.close()
        rb.close()
        sa.destroy()
        fb.destroy()
