"""Hyperfile subsystem: chunking, FileStore, server round trip, ledger.

Parity targets: reference tests/StreamLogic.test.ts (chunk edge cases),
tests/FileStore.test.ts:15-35 (1MiB file -> 17 blocks @62KiB, sha256
header round trip), tests/repo.test.ts:199-213 (file round trip through
the repo facade)."""

import hashlib
import os
import tempfile
import uuid

import pytest

from hypermerge_tpu.backend.metadata import Metadata
from hypermerge_tpu.files.file_store import FileHeader, FileStore
from hypermerge_tpu.files.stream_logic import (
    MAX_BLOCK_SIZE,
    HashCounter,
    iter_chunks,
    rechunk,
)
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.storage.feed import FeedStore, memory_storage_fn
from hypermerge_tpu.utils.ids import url_to_id


class TestRemoteFileFetch:
    """Hyperfile replication end-to-end (VERDICT r5 item 5): a repo
    fetches a file it doesn't hold from a peer over encrypted TCP,
    streaming blocks with progress events (reference
    src/FileStore.ts:33-36 + src/ReplicationManager.ts:71-89)."""

    def _tcp_pair(self):
        from hypermerge_tpu.net.tcp import TcpSwarm

        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        return ra, rb, sa, sb

    def test_one_mib_file_replicates_over_tcp_with_progress(self):
        ra, rb, sa, sb = self._tcp_pair()
        try:
            data = os.urandom(1024 * 1024)
            header = ra.back.get_file_store().write(
                data, "application/octet-stream"
            )
            file_id = url_to_id(header.url)
            fs_b = rb.back.get_file_store()
            progress = []
            fs_b.subscribe_progress(
                file_id, lambda blocks, nbytes: progress.append(
                    (blocks, nbytes)
                )
            )
            got = fs_b.read_bytes(file_id, timeout=60)
            assert got == data
            hdr = fs_b.header_wait(file_id, timeout=10)
            assert hdr.sha256 == header.sha256
            assert hdr.size == len(data)
            assert hdr.mime_type == "application/octet-stream"
            assert hdr.blocks == 17  # 1MiB @ 62KiB
            # progress fired per block: 17 data + 1 header
            assert progress and progress[-1][0] == 18
            assert progress[-1][1] >= len(data)
        finally:
            ra.close()
            rb.close()
            sa.destroy()
            sb.destroy()

    def test_remote_read_times_out_when_no_holder(self):
        from hypermerge_tpu.utils import keys as keymod
        from hypermerge_tpu.utils.ids import to_hyperfile_url

        repo = Repo(memory=True)
        try:
            bogus = keymod.create().public_key
            fs = repo.back.get_file_store()
            with pytest.raises(TimeoutError):
                fs.read_bytes(url_to_id(to_hyperfile_url(bogus)),
                              timeout=0.3)
        finally:
            repo.close()

    def test_http_server_fetches_remote_file(self):
        """GET /hyperfile:/<id> on a swarm-wired file server for a file
        a PEER holds: the server replicates it in and streams it
        (reference: file feeds replicate like any feed)."""
        ra, rb, sa, sb = self._tcp_pair()
        sock = server_path()
        try:
            data = os.urandom(200_000)
            header = ra.back.get_file_store().write(data, "text/plain")
            rb.start_file_server(sock)
            from hypermerge_tpu.files.file_client import FileServerClient

            hdr2, got = FileServerClient(sock).read(header.url)
            assert got == data
            assert hdr2.sha256 == header.sha256
            assert hdr2.mime_type == "text/plain"
        finally:
            ra.close()
            rb.close()
            sa.destroy()
            sb.destroy()
            if os.path.exists(sock):
                os.remove(sock)

    def test_failed_remote_fetch_leaves_no_trace(self):
        """A bogus-id fetch on a SWARM-WIRED store times out AND cleans
        up: no feed stays registered/announced for an id that yielded
        nothing."""
        from hypermerge_tpu.utils import keys as keymod
        from hypermerge_tpu.utils.ids import to_hyperfile_url

        ra, rb, sa, sb = self._tcp_pair()
        try:
            bogus = keymod.create().public_key
            fid = url_to_id(to_hyperfile_url(bogus))
            fs = rb.back.get_file_store()
            with pytest.raises(TimeoutError):
                fs.header_wait(fid, timeout=0.3)
            assert rb.back.feeds.get_feed(fid) is None
            assert fid not in rb.back.feed_info.all_public_ids()
        finally:
            ra.close()
            rb.close()
            sa.destroy()
            sb.destroy()

    def test_local_read_semantics_unchanged(self):
        """timeout=0 keeps the strict local contract: missing feeds
        raise FileNotFoundError immediately."""
        store = FileStore(FeedStore(memory_storage_fn))
        from hypermerge_tpu.utils import keys as keymod

        with pytest.raises(FileNotFoundError):
            store.read_bytes(keymod.create().public_key)


def server_path() -> str:
    return os.path.join(
        tempfile.gettempdir(), f"hypermerge-tpu-test-{uuid.uuid4().hex[:8]}.sock"
    )


# -- stream logic -------------------------------------------------------


def test_rechunk_passthrough_small_chunks():
    chunks = [b"ab", b"cd", b"e"]
    assert list(rechunk(chunks, 4)) == [b"ab", b"cd", b"e"]


def test_rechunk_splits_oversized():
    out = list(rechunk([b"abcdefghij"], 4))
    assert out == [b"abcd", b"efgh", b"ij"]
    assert b"".join(out) == b"abcdefghij"


def test_rechunk_exact_multiple_and_empty():
    assert list(rechunk([b"abcd"], 4)) == [b"abcd"]
    assert list(rechunk([b""], 4)) == []
    assert list(rechunk([], 4)) == []


def test_iter_chunks_normalizes_bytes_and_iterables():
    assert list(iter_chunks(b"xyz")) == [b"xyz"]
    assert list(iter_chunks([b"x", b"yz"])) == [b"x", b"yz"]


def test_hash_counter():
    c = HashCounter()
    data = [b"hello ", b"world"]
    assert list(c.wrap(data)) == data
    assert c.bytes == 11
    assert c.chunks == 2
    assert c.digest_hex == hashlib.sha256(b"hello world").hexdigest()


# -- FileStore ----------------------------------------------------------


@pytest.fixture
def store():
    return FileStore(FeedStore(memory_storage_fn))


def test_one_mib_file_is_17_blocks(store):
    """1MiB at 62KiB chunks = 17 data blocks (reference
    tests/FileStore.test.ts:15-35)."""
    data = os.urandom(1024 * 1024)
    header = store.write(data, "application/octet-stream")
    assert header.blocks == 17
    assert header.size == len(data)
    assert header.sha256 == hashlib.sha256(data).hexdigest()
    file_id = url_to_id(header.url)
    assert store.read_bytes(file_id) == data
    # feed holds data blocks + ONE trailing header block
    feed = store.feeds.get_feed(file_id)
    assert feed.length == 18
    assert max(len(b) for b in feed.read_all()[:-1]) <= MAX_BLOCK_SIZE


def test_header_round_trip(store):
    header = store.write(b"hello", "text/plain")
    got = store.header(url_to_id(header.url))
    assert got == header
    assert got.mime_type == "text/plain"
    assert FileHeader.from_json(header.to_json()) == header


def test_empty_file(store):
    header = store.write(b"", "text/plain")
    assert header.blocks == 0
    assert header.size == 0
    assert store.read_bytes(url_to_id(header.url)) == b""


def test_write_log_announces_completed_uploads(store):
    seen = []
    store.write_log.subscribe(seen.append)
    h = store.write(b"abc", "text/plain")
    assert seen == [h]


# -- server + client through the repo facade ----------------------------


def test_repo_file_round_trip():
    """Write via repo.files, read back, check meta (reference
    tests/repo.test.ts:199-213)."""
    repo = Repo(memory=True)
    path = server_path()
    try:
        repo.start_file_server(path)
        assert repo.files is not None
        data = os.urandom(200 * 1024)
        header = repo.files.write(data, "application/x-test")
        assert header.size == len(data)
        assert header.blocks == 4  # ceil(200KiB / 62KiB)

        got_header, body = repo.files.read(header.url)
        assert body == data
        assert got_header.sha256 == hashlib.sha256(data).hexdigest()
        assert got_header.mime_type == "application/x-test"
        assert repo.files.header(header.url) == got_header

        # meta() resolves hyperfile urls from the ledger
        metas = []
        repo.meta(header.url, metas.append)
        assert metas == [
            {
                "type": "File",
                "bytes": len(data),
                "mimeType": "application/x-test",
            }
        ]
    finally:
        repo.close()
        assert not os.path.exists(path)


def test_file_server_missing_file_404():
    repo = Repo(memory=True)
    path = server_path()
    try:
        repo.start_file_server(path)
        from hypermerge_tpu.utils import keys

        bogus = f"hyperfile:/{keys.create().public_key}"
        with pytest.raises(FileNotFoundError):
            repo.files.header(bogus)
        # a 404 lookup must not create/register a feed for the bogus id
        assert repo.back.feeds.get_feed(url_to_id(bogus)) is None
    finally:
        repo.close()


# -- metadata ledger ----------------------------------------------------


def test_metadata_ledger_persists_across_restart(tmp_path):
    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    sock = server_path()
    try:
        repo.start_file_server(sock)
        header = repo.files.write(b"persistent", "text/plain")
    finally:
        repo.close()

    repo2 = Repo(path=path)
    try:
        file_id = url_to_id(header.url)
        assert repo2.back.meta.file_metadata(file_id) == {
            "type": "File",
            "bytes": 10,
            "mimeType": "text/plain",
        }
        # the file bytes themselves also survive
        assert FileStore(repo2.back.feeds).read_bytes(file_id) == b"persistent"
    finally:
        repo2.close()


def test_metadata_ledger_skips_corrupt_entries():
    from hypermerge_tpu.storage.sql import SqlDatabase
    from hypermerge_tpu.storage.stores import KeyStore

    from hypermerge_tpu.utils import keys

    feeds = FeedStore(memory_storage_fn)
    key_store = KeyStore(SqlDatabase(":memory:"))
    meta = Metadata(feeds, key_store)
    meta.add_file(f"hyperfile:/{keys.create().public_key}", 5, "a/b")
    meta.ledger.append(b"\xff\xfenot json")  # corrupt entry
    meta.add_file(f"hyperfile:/{keys.create().public_key}", 6, "c/d")

    meta2 = Metadata(feeds, key_store)  # replay over the same feed
    assert len(meta2.files) == 2
