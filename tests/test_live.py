"""Live apply engine (backend/live.py) — the HM_LIVE=1/0 twin contract.

The live path routes incremental changes on lazy (bulk-loaded) docs
through per-tick batched kernel dispatches; HM_LIVE=0 is the host-OpSet
correctness twin. Pinned here:

- no host replay: the deferred loader is NEVER invoked for live
  local/remote changes (the acceptance bar for the batched live path);
- fuzz twin: a randomized multi-actor workload (concurrent maps,
  lists, text, counters, deletes, nested objects, cross-site merges)
  delivered in BOTH orders produces bit-identical local patch echoes,
  clocks, snapshot patches, and frontend state across HM_LIVE=1/0;
- LiveColumns: appending a change stream incrementally decodes to the
  same state as packing the full history.
"""

import os
import random
import shutil
import tempfile

import pytest

from helpers import Site, plainify, random_mutation, sync, wait_until
from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite
from hypermerge_tpu.models import Text
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils.ids import validate_doc_url

_lockdep_suite = lockdep_suite()
# the live twin suite doubles as the guard-map verifier: every
# declared shared field races through here fully instrumented
# (tests/racedep_fixture.py), asserted clean at teardown
_racedep_suite = racedep_suite()


@pytest.fixture
def live_env(monkeypatch):
    monkeypatch.setenv("HM_LIVE", "1")


def _seed_dir(tmp, n_changes=6, seed=7):
    """A stored single-writer doc on disk + its history snapshot."""
    repo = Repo(path=tmp)
    url = repo.create({"edits": [], "t": Text("hi")})
    r = random.Random(seed)
    for _ in range(n_changes):
        repo.change(url, lambda d: d["edits"].append(r.randint(0, 99)))
    repo.change(url, lambda d: d["t"].insert(2, "!"))
    doc_id = validate_doc_url(url)
    stored = list(repo.back.docs[doc_id].opset.history)
    repo.close()
    return url, doc_id, stored


def test_live_path_never_invokes_lazy_loader(tmp_path, live_env):
    """Acceptance: no full host replay on the first live change to a
    bulk-loaded doc — local AND remote."""
    from hypermerge_tpu.crdt.change import Action, Change, Op, ROOT

    url, doc_id, stored = _seed_dir(str(tmp_path))
    repo = Repo(path=str(tmp_path))
    repo.back.load_documents_bulk([doc_id])
    doc = repo.back.docs[doc_id]
    assert doc.opset is None and doc._lazy_loader is not None

    calls = []
    orig = doc._lazy_loader

    def spy():
        calls.append(1)
        return orig()

    doc._lazy_loader = spy

    # local change: resolves through the engine, no replay
    repo.change(url, lambda d: d.__setitem__("new", 1))
    assert repo.doc(url)["new"] == 1
    assert doc.opset is None and not calls

    # remote change from another actor: ticks through the engine
    peer = Site("peerpeerpeer0001")
    peer.receive(stored + [c for c in _local_changes(repo, doc_id)])
    ch, _ = peer.change(lambda d: d.__setitem__("remote", 2))
    doc.apply_remote_changes([ch])
    wait_until(lambda: repo.doc(url).get("remote") == 2)
    assert doc.opset is None and not calls

    # explicit history APIs still replay (and don't corrupt live state)
    hist = doc.materialize_at(doc.history_len)
    assert plainify(hist)["new"] == 1
    assert calls, "time travel should use the host replay"
    assert doc.opset is None
    repo.close()


def _local_changes(repo, doc_id):
    """The doc's applied changes as Change objects (from the feeds)."""
    out = []
    for actor_id, end in repo.back.docs[doc_id].clock.items():
        actor = repo.back._get_or_create_actor(actor_id)
        out.extend(actor.changes_in_window(0, end))
    return out


def _gen_remote_script(stored, seed, n_rounds=10):
    """Deterministic multi-actor change batches extending `stored`:
    two peers mutate concurrently and merge periodically."""
    r = random.Random(seed)
    peers = [Site(f"peer{i:1d}0000000000001") for i in range(2)]
    for p in peers:
        p.receive(stored)
    script = []  # [(peer_idx, [Change, ...])]
    for rnd in range(n_rounds):
        idx = r.randrange(2)
        site = peers[idx]
        batch = []
        for _ in range(r.randint(1, 3)):
            before = len(site.opset.history)
            random_mutation(site, r)
            batch.extend(site.opset.history[before:])
        if batch:
            script.append((idx, batch))
        if rnd % 3 == 2:
            sync(*peers)
    return script


def _run_workload(base_dir, live, order_flip, seed=13):
    """Replay the same remote script + local edits against a copy of
    the seeded repo under HM_LIVE=`live`; returns the observable
    outcome (local patch echoes, clock, snapshot, frontend state)."""
    os.environ["HM_LIVE"] = live
    work = tempfile.mkdtemp()
    shutil.rmtree(work)
    shutil.copytree(base_dir, work)
    try:
        repo = Repo(path=work)
        with open(os.path.join(base_dir, "_meta")) as fh:
            url, doc_id = fh.read().split()
        local_patches = []
        orig_push = repo.back.to_frontend.push

        def record(msg):
            if msg.get("type") == "Patch" and msg["patch"].get("actor"):
                local_patches.append(msg["patch"])
            orig_push(msg)

        repo.back.to_frontend.push = record
        h = repo.open(url)
        assert h.value(timeout=20) is not None
        doc = repo.back.docs[doc_id]
        stored = _local_changes(repo, doc_id)
        script = _gen_remote_script(stored, seed)
        if order_flip:
            # deliver each peer's stream order-preserved, but peer 1's
            # batches first — later batches park on unmet deps until
            # the other peer's stream arrives (both paths must park
            # identically)
            script = [b for b in script if b[0] == 1] + [
                b for b in script if b[0] == 0
            ]
        # an OpSet oracle tracks exactly which changes are applicable
        # after each delivery (parking semantics included), so the two
        # modes pause at identical states before each local edit
        from hypermerge_tpu.crdt.opset import OpSet

        oracle = OpSet()
        oracle.apply_changes(stored)
        peer_actors = set()
        for k, (_idx, batch) in enumerate(script):
            oracle.apply_changes(list(batch))
            peer_actors.update(c.actor for c in batch)
            doc.apply_remote_changes(list(batch))
            wait_until(
                lambda: all(
                    doc.clock.get(a, 0) == oracle.clock.get(a, 0)
                    for a in peer_actors
                )
            )
            # interleaved local edits (state-shape-independent)
            repo.change(url, lambda d, k=k: d.__setitem__(f"k{k}", k))
            repo.change(
                url, lambda d, k=k: d["edits"].append(1000 + k)
            )
        if repo.back.live is not None:
            repo.back.live.flush_now()
        import json

        outcome = {
            "snap": doc.snapshot_patch().to_json(),
            "clock": dict(doc.clock),
            "hist": doc.history_len,
            "state": plainify(h.value()),
            "local_patches": local_patches,
        }
        # the writable actor is minted fresh per reopen (its key is not
        # in the doc url): normalize it BEFORE the sorted dump, so key
        # ordering can't differ between runs
        actor_id = doc.actor_id
        repo.close()

        def scrub(v):
            if isinstance(v, str):
                return v.replace(actor_id, "<LOCAL-ACTOR>")
            if isinstance(v, dict):
                return {scrub(k): scrub(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [scrub(x) for x in v]
            return v

        return json.dumps(scrub(outcome), sort_keys=True, default=str)
    finally:
        shutil.rmtree(work, ignore_errors=True)


@pytest.mark.parametrize("order_flip", [False, True], ids=["fwd", "rev"])
def test_live_twin_fuzz_bit_identical(tmp_path, order_flip):
    """HM_LIVE=1 and HM_LIVE=0 produce bit-identical local patch
    echoes, clocks, snapshot patches, and frontend state on a
    randomized multi-actor workload, in both delivery orders."""
    base = str(tmp_path / "seed")
    os.makedirs(base)
    old = os.environ.get("HM_LIVE")
    try:
        os.environ["HM_LIVE"] = "0"
        url, doc_id, _stored = _seed_dir(base)
        with open(os.path.join(base, "_meta"), "w") as fh:
            fh.write(f"{url} {doc_id}")
        host = _run_workload(base, "0", order_flip)
        live = _run_workload(base, "1", order_flip)
    finally:
        if old is None:
            os.environ.pop("HM_LIVE", None)
        else:
            os.environ["HM_LIVE"] = old
    # ONE normalized comparison covers clocks, history length, frontend
    # state, the snapshot patch, and every local patch echo
    # patch-for-patch (the live engine's local resolution mirrors
    # OpSet.apply_local_request; `time` never appears in patches)
    assert live == host


def test_live_columns_append_matches_full_pack():
    """Appending a causal change stream to LiveColumns decodes to the
    same state as adopting the fully packed history (the no-repack
    invariant of the live cache) — and both match the OpSet snapshot."""
    from hypermerge_tpu.backend.live import (
        _decode_state,
        _diff_states,
        _DocState,
    )
    from hypermerge_tpu.ops.columnar import (
        LiveColumns,
        causal_sort,
        pack_docs,
    )

    for seed in range(4):
        r = random.Random(seed * 991)
        sites = [Site(f"s{i}000000000001") for i in range(3)]
        for _ in range(25):
            random_mutation(r.choice(sites), r)
            if r.random() < 0.3:
                sync(*sites)
        sync(*sites)
        changes = causal_sort(
            [c for s in sites for c in s.opset.history]
        )

        incremental = LiveColumns()
        incremental.append_changes(changes)
        batch = pack_docs([changes])
        adopted = LiveColumns.from_batch(batch, 0)

        def state_of(lv):
            return _decode_state(lv, _run_host(lv))

        s_inc = state_of(incremental)
        s_full = state_of(adopted)
        d_inc = [d.to_json() for d in _diff_states(_DocState(), s_inc)]
        d_full = [
            d.to_json() for d in _diff_states(_DocState(), s_full)
        ]
        assert d_inc == d_full
        # ...and both agree with the host OpSet snapshot
        opset = sites[0].opset
        want = [d.to_json() for d in opset.snapshot_patch().diffs]
        assert d_inc == want


def test_diff_states_streams_detached_object_updates():
    """Kernel-tick deltas must include mutations to objects the
    frontend still holds but that are currently DETACHED (a concurrent
    winner displaced their link). The host path streams those diffs
    (FrontendDoc retains detached objects and applies them), so a
    later re-attach links a CURRENT copy — dropping them would leave
    the live frontend stale and diverge from the HM_LIVE=0 twin."""
    from hypermerge_tpu.backend.live import (
        _diff_states,
        _DocState,
        _Obj,
        _Val,
    )
    from hypermerge_tpu.crdt.change import ROOT, OpId

    x = OpId(1, "actorA")

    def mk_state(x_val):
        st = _DocState()
        st.objs[x] = _Obj("map")
        st.objs[x].fields["inner"] = {
            OpId(2, "actorA"): _Val(x_val, False, None)
        }
        # root key 'a' holds the SET that displaced X's link
        st.objs[ROOT].fields["a"] = {
            OpId(3, "actorB"): _Val(5, False, None)
        }
        return st

    old = mk_state("old")
    new = mk_state("new")
    old.reachable = {ROOT, x}  # frontend got X before the detach
    diffs = _diff_states(old, new)
    assert any(
        d.action == "set" and d.obj == str(x) and d.value == "new"
        for d in diffs
    ), [d.to_json() for d in diffs]
    assert x in new.reachable  # successive ticks keep streaming it


def _run_host(lv):
    import numpy as np

    from hypermerge_tpu.ops.host_kernel import _host_doc_kernel

    n = lv.n
    A = max(1, len(lv.actors.items))
    K = max(1, len(lv.keys.items))
    c = lv.cols
    return _host_doc_kernel(
        c["action"][:n], lv.slots(), c["ctr"][:n],
        np.zeros(n, np.int32), c["obj"][:n], c["key"][:n],
        c["ref"][:n], c["insert"][:n], c["value"][:n],
        lv.psrc[: lv.n_preds], lv.ptgt[: lv.n_preds],
        np.arange(A, dtype=np.int32), A, K,
    )


def test_adopt_refused_missing_actor_creates_no_feed(tmp_path, live_env):
    """A refused adoption (the serving clock names an actor we hold no
    feed for) must NOT materialize an empty actor feed on disk — the
    old _get_or_create_actor lookup registered + announced a phantom
    feed (feed_info row, feeds/ directory entry) as a side effect of
    merely refusing."""
    import os as _os

    url, doc_id, _ = _seed_dir(str(tmp_path))
    repo = Repo(path=str(tmp_path))
    repo.back.load_documents_bulk([doc_id])
    doc = repo.back.docs[doc_id]
    assert doc.opset is None and doc._lazy_loader is not None
    bogus = "zzbogusactorzzzzzzzzzzzzzzzzzzzz"
    with doc._lock:
        doc._lazy_clock[bogus] = 3  # feed we can never serve
    # first live change: adoption must refuse (missing feed) and the
    # host path must still apply the change correctly
    repo.change(url, lambda d: d.__setitem__("after", 1))
    assert repo.doc(url)["after"] == 1
    assert repo.back.live.stats["refused"] == 1
    assert doc.opset is not None  # host fallback took over
    # no phantom feed materialized anywhere
    assert bogus not in repo.back.actors
    assert repo.back.feeds.get_feed(bogus) is None
    feed_path = _os.path.join(
        str(tmp_path), "feeds", bogus[:2], bogus
    )
    assert not _os.path.exists(feed_path)
    repo.close()


def test_adoption_reachability_lanes_twin():
    """The adoption path's lane-driven reachability (winner-link forest
    from map_winner/elem_winner) is bit-identical to both the state
    walk and the full snapshot diff walk, on randomized multi-actor
    docs (nested objects, deletes, counters, text)."""
    from hypermerge_tpu.backend.live import (
        _compute_reachable,
        _decode_state,
        _diff_states,
        _DocState,
        _reachable_from_lanes,
    )
    from hypermerge_tpu.ops.columnar import (
        LiveColumns,
        causal_sort,
        pack_docs,
    )

    for seed in range(6):
        r = random.Random(seed * 7919)
        sites = [Site(f"r{i}000000000001") for i in range(3)]
        for _ in range(30):
            random_mutation(r.choice(sites), r)
            if r.random() < 0.3:
                sync(*sites)
        sync(*sites)
        changes = causal_sort(
            [c for s in sites for c in s.opset.history]
        )
        batch = pack_docs([changes])
        lv = LiveColumns.from_batch(batch, 0)
        lanes = _run_host(lv)
        st = _decode_state(lv, lanes)
        from_lanes = _reachable_from_lanes(lv, lanes)
        st_walk = _decode_state(lv, lanes)
        _compute_reachable(st_walk)
        st_diff = _decode_state(lv, lanes)
        _diff_states(_DocState(), st_diff)  # sets reachable
        assert from_lanes == st_walk.reachable == st_diff.reachable, (
            seed,
            sorted(map(str, from_lanes ^ st_diff.reachable)),
        )
        assert st.inc == st_diff.inc


def test_other_docs_tick_during_adoption(tmp_path, live_env):
    """The engine lock is NOT held across an adoption build: while one
    doc's pack+kernel+decode is in flight (a replication thread), a
    different hot doc's remote changes admit AND its tick emits.
    Deterministic — the build blocks until the other doc's edit lands,
    so a regression (build back under the engine lock) stalls the
    admission/tick and fails the wait, instead of flaking on timing."""
    import threading as _th

    repo = Repo(path=str(tmp_path))
    url_a = repo.create({"n": 0})
    url_b = repo.create({"n": 0})
    for k in range(8):
        repo.change(url_a, lambda d, k=k: d.__setitem__("n", k))
        repo.change(url_b, lambda d, k=k: d.__setitem__("n", k))
    ids = [validate_doc_url(u) for u in (url_a, url_b)]
    stored = {i: _local_changes(repo, ids[i]) for i in range(2)}
    repo.close()

    repo2 = Repo(path=str(tmp_path))
    repo2.back.load_documents_bulk(ids)
    eng = repo2.back.live
    doc_a, doc_b = (repo2.back.docs[i] for i in ids)
    peers = []
    for i in range(2):
        p = Site(f"stall{i:1d}000000001")
        p.receive(stored[i])
        peers.append(p)
    # adopt A up front (one remote edit + tick)
    ch_a0, _ = peers[0].change(lambda d: d.__setitem__("r", 0))
    doc_a.apply_remote_changes([ch_a0])
    eng.flush_now()
    wait_until(lambda: repo2.doc(url_a).get("r") == 0)

    started = _th.Event()
    observed = _th.Event()
    orig = eng._adopt_build

    def gated_build(doc):
        out = orig(doc)
        started.set()
        assert observed.wait(20), "ticks stalled during adoption build"
        return out

    eng._adopt_build = gated_build
    ch_b, _ = peers[1].change(lambda d: d.__setitem__("r", 1))
    t = _th.Thread(
        target=lambda: doc_b.apply_remote_changes([ch_b])
    )  # a replication thread adopting doc B
    t.start()
    assert started.wait(20)
    # B's adoption build is mid-flight: A's remote change must still
    # admit (serving clock advances) and its tick must emit
    ch_a1, _ = peers[0].change(lambda d: d.__setitem__("during", 3))
    doc_a.apply_remote_changes([ch_a1])
    wait_until(lambda: repo2.doc(url_a).get("during") == 3)
    observed.set()
    t.join(20)
    assert not t.is_alive()
    eng.flush_now()
    wait_until(lambda: repo2.doc(url_b).get("r") == 1)
    assert eng.stats["adopted"] == 2
    assert eng.stats["refused"] == 0
    repo2.close()


def test_emission_reentry_never_waits_on_adoption_gate(
    tmp_path, live_env
):
    """A thread that already holds the engine (emission) lock — a
    frontend callback re-entering the repo mid-emission — must NOT
    wait on another thread's in-flight adoption gate: the builder
    needs that lock to install, so waiting with it held would wedge
    every emission. The guard answers host-path (None/False)
    immediately instead."""
    import threading as _th

    repo = Repo(path=str(tmp_path))
    url = repo.create({"n": 0})
    for k in range(6):
        repo.change(url, lambda d, k=k: d.__setitem__("n", k))
    doc_id = validate_doc_url(url)
    stored = _local_changes(repo, doc_id)
    repo.close()

    repo2 = Repo(path=str(tmp_path))
    repo2.back.load_documents_bulk([doc_id])
    eng = repo2.back.live
    doc = repo2.back.docs[doc_id]
    peer = Site("reent00000000001")
    peer.receive(stored)
    ch, _ = peer.change(lambda d: d.__setitem__("r", 1))

    started = _th.Event()
    release = _th.Event()
    orig = eng._adopt_build

    def gated_build(d):
        out = orig(d)
        started.set()
        assert release.wait(20)
        return out

    eng._adopt_build = gated_build
    builder = _th.Thread(
        target=lambda: doc.apply_remote_changes([ch])
    )
    builder.start()
    assert started.wait(20)
    # simulate the re-entry: this thread holds the emission lock and
    # submits for the doc whose adoption is mid-build elsewhere
    results = []

    def under_lock():
        with eng._lock:
            results.append(eng.submit_remote(doc, [ch]))

    probe = _th.Thread(target=under_lock)
    probe.start()
    probe.join(5)
    deadlocked = probe.is_alive()
    release.set()  # let the builder finish either way
    builder.join(20)
    probe.join(5)
    assert not deadlocked, (
        "emission-lock holder blocked on the adoption gate"
    )
    assert results == [False]  # host path, answered immediately
    eng.flush_now()
    wait_until(lambda: repo2.doc(url).get("r") == 1)
    repo2.close()


def test_live_reopen_serves_fresh_snapshot(tmp_path, live_env):
    """A handle reopened on a live-adopted doc gets the CURRENT state
    (the engine's snapshot twin), not the stale bulk-load decode."""
    url, doc_id, _ = _seed_dir(str(tmp_path))
    repo = Repo(path=str(tmp_path))
    h1 = repo.open(url)
    assert h1.value(timeout=20) is not None
    repo.change(url, lambda d: d.__setitem__("fresh", True))
    h1.close()
    repo.back.close_doc(doc_id)  # drop doc + live state entirely
    h2 = repo.open(url)
    wait_until(lambda: (h2.value(timeout=5) or {}).get("fresh"))
    repo.close()


def test_live_tick_batches_multiple_docs(tmp_path, live_env):
    """A burst across several lazy docs coalesces into shared ticks
    (the O(ticks) dispatch claim, visible in the engine stats)."""
    repo = Repo(path=str(tmp_path))
    urls = [repo.create({"i": i, "edits": []}) for i in range(6)]
    ids = [validate_doc_url(u) for u in urls]
    stored = {
        i: _local_changes(repo, ids[i]) for i in range(len(urls))
    }
    repo.close()

    repo2 = Repo(path=str(tmp_path))
    repo2.back.load_documents_bulk(ids)
    peers = []
    for i, did in enumerate(ids):
        p = Site(f"burst{i:1d}000000001")
        p.receive(stored[i])
        peers.append(p)
    # one coalesced burst: every doc gets a remote change in the same
    # tick window
    for i, did in enumerate(ids):
        ch, _ = peers[i].change(lambda d, i=i: d.__setitem__("r", i))
        repo2.back.docs[did].apply_remote_changes([ch])
    repo2.back.live.flush_now()
    for i, u in enumerate(urls):
        wait_until(lambda i=i, u=u: repo2.doc(u).get("r") == i)
    stats = repo2.back.live.stats
    assert stats["adopted"] == len(urls)
    assert stats["tick_changes"] >= len(urls)
    assert stats["ticks"] <= stats["tick_changes"], stats
    for did in ids:
        assert repo2.back.docs[did].opset is None
    repo2.close()
