"""Chaos suite: supervised redial, keepalive half-open detection, and
deterministic fault injection (net/resilience.py, net/faults.py).

The availability contract — "the peer redials and resyncs from its
cursor" — is exercised here the only way it can be trusted: with a
SEEDED fault schedule (same seed -> same frame-level fates) driving
kill / heal / partition / drop / duplicate faults against real TCP
repos, a loopback twin pinning the converged state bit-identically, and
no manual re-`connect()` anywhere after the first dial."""

import os
import random
import socket as sockmod
import threading
import time

import pytest

from hypermerge_tpu.net.faults import (
    DELIVER,
    DROP,
    DUP,
    FaultDuplex,
    FaultPlan,
    FaultSwarm,
    parse_fault_spec,
)
from hypermerge_tpu.net.resilience import (
    BACKOFF,
    CONNECTED,
    STOPPED,
    Backoff,
)
from hypermerge_tpu.net.swarm import ConnectionDetails
from hypermerge_tpu.net.tcp import TcpDuplex, TcpSwarm
from hypermerge_tpu.repo import Repo

from helpers import wait_until
from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite

_lockdep_suite = lockdep_suite()
# churn/kill/heal under the lockset detector: the NetworkPeer /
# SessionSupervisor guard rows verified live (tests/racedep_fixture.py)
_racedep_suite = racedep_suite()


@pytest.fixture
def fast_redial(monkeypatch):
    monkeypatch.setenv("HM_REDIAL_BASE_MS", "20")
    monkeypatch.setenv("HM_REDIAL_MAX_S", "0.25")


def _free_port() -> int:
    s = sockmod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestBackoff:
    def test_full_jitter_bounds_and_cap(self):
        b = Backoff(base_s=0.1, max_s=1.0, rng=random.Random(7))
        ceilings = [0.1, 0.2, 0.4, 0.8, 1.0, 1.0, 1.0]
        for ceil in ceilings:
            d = b.next_delay()
            assert 0.0 <= d <= ceil, (d, ceil)
        # deep attempts stay capped (and 2**n never overflows)
        for _ in range(200):
            assert 0.0 <= b.next_delay() <= 1.0

    def test_reset_on_success(self):
        b = Backoff(base_s=0.1, max_s=10.0, rng=random.Random(1))
        for _ in range(6):
            b.next_delay()
        assert b.attempt == 6
        b.reset()
        assert b.attempt == 0
        assert b.next_delay() <= 0.1  # back to the fast first retry

    def test_jitter_is_jittered(self):
        b = Backoff(base_s=1.0, max_s=1.0, rng=random.Random(3))
        ds = {round(b.next_delay(), 6) for _ in range(16)}
        assert len(ds) > 8  # full jitter, not a fixed schedule


class TestFaultPlan:
    def _fates(self, plan, n=400):
        return [plan.frame_fate(tx=True) for _ in range(n)] + [
            plan.frame_fate(tx=False) for _ in range(n)
        ]

    def test_same_seed_same_schedule(self):
        mk = lambda: FaultPlan(
            seed=42, drop_p=0.1, dup_p=0.1, delay_ms=(1, 5)
        )
        assert self._fates(mk()) == self._fates(mk())

    def test_different_seed_different_schedule(self):
        a = FaultPlan(seed=1, drop_p=0.3, dup_p=0.3)
        b = FaultPlan(seed=2, drop_p=0.3, dup_p=0.3)
        assert self._fates(a) != self._fates(b)

    def test_events_fire_in_tick_order(self):
        plan = FaultPlan(events=[(2, "kill"), (4, "heal"), (4, "clean")])
        assert plan.advance() == []
        assert plan.advance() == ["kill"] and plan.down
        assert plan.advance() == []
        assert plan.advance() == ["heal", "clean"]
        assert not plan.down and not plan.lossy

    def test_partition_blocks_one_direction(self):
        plan = FaultPlan(events=[(1, "partition_tx"), (2, "heal")])
        plan.advance()
        assert plan.frame_fate(tx=True)[0] == DROP
        assert plan.frame_fate(tx=False)[0] == DELIVER
        plan.advance()
        assert plan.frame_fate(tx=True)[0] == DELIVER

    def test_partition_consumes_rng(self):
        """A partition window must not SHIFT the post-heal schedule:
        blocked frames still consume the RNG stream."""
        a = FaultPlan(seed=9, drop_p=0.5)
        b = FaultPlan(seed=9, drop_p=0.5, events=[(1, "partition_tx"),
                                                  (2, "heal")])
        b.advance()
        for _ in range(100):  # b's frames drop, but the stream advances
            a.frame_fate(tx=True)
            b.frame_fate(tx=True)
        b.advance()
        assert [a.frame_fate(tx=True) for _ in range(100)] == [
            b.frame_fate(tx=True) for _ in range(100)
        ]

    def test_parse_spec(self):
        plan = parse_fault_spec(
            "seed=7,drop=0.02,dup=0.01,delay=2:8,kill@30,heal@50,tick=250"
        )
        assert plan.seed == 7 and plan.drop_p == 0.02
        assert plan.dup_p == 0.01 and plan.delay_ms == (2.0, 8.0)
        assert plan.tick_ms == 250
        assert plan.events == [(30, "kill"), (50, "heal")]

    def test_parse_spec_rejects_junk(self):
        with pytest.raises(ValueError):
            parse_fault_spec("explode@3")
        with pytest.raises(ValueError):
            parse_fault_spec("warp=9")


class TestFaultDuplex:
    def test_drop_and_dup(self):
        from hypermerge_tpu.net.duplex import duplex_pair

        a, b = duplex_pair()
        got = []
        b.on_message(got.append)
        fa = FaultDuplex(a, FaultPlan(drop_p=1.0))
        fa.send({"x": 1})
        assert got == [] and fa.stats["frames_dropped_injected"] == 1

        a2, b2 = duplex_pair()
        got2 = []
        b2.on_message(got2.append)
        fa2 = FaultDuplex(a2, FaultPlan(dup_p=1.0))
        fa2.send({"x": 2})
        assert got2 == [{"x": 2}, {"x": 2}]

    def test_rx_buffering_until_subscribe(self):
        from hypermerge_tpu.net.duplex import duplex_pair

        a, b = duplex_pair()
        fb = FaultDuplex(b, FaultPlan())
        a.send({"early": True})
        got = []
        fb.on_message(got.append)
        assert got == [{"early": True}]

    def test_delay_never_reorders(self):
        """Injected latency rides a FIFO delay line: frames leave in
        arrival order even when a later frame draws a shorter delay —
        no real transport reorders, so the harness must not either."""
        from hypermerge_tpu.net.duplex import duplex_pair

        a, b = duplex_pair()
        got = []
        b.on_message(got.append)
        fa = FaultDuplex(a, FaultPlan(seed=5, delay_ms=(1, 20)))
        n = 30
        for i in range(n):
            fa.send({"i": i})
        wait_until(lambda: len(got) == n, timeout=10)
        assert [m["i"] for m in got] == list(range(n))


class TestSupervisor:
    def test_failed_dial_enqueues_retry_not_raise(self, fast_redial):
        """The old `connect` raised OSError into the caller; now a dead
        address backs off, surfaces status, and connects as soon as a
        listener appears."""
        port = _free_port()
        sb = TcpSwarm()
        states = []
        sb.supervisor.on_status(
            lambda s, state, info: states.append(state)
        )
        session = sb.connect(("127.0.0.1", port))  # nothing listening
        wait_until(lambda: session.failures >= 2)
        assert BACKOFF in states
        sa = TcpSwarm(port=port)  # listener appears late
        got = []
        sa.on_connection(lambda d, det: got.append(d))
        wait_until(lambda: session.state == CONNECTED)
        assert session.connects == 1 and session.failures >= 2
        sb.destroy()
        sa.destroy()

    def test_redial_after_drop_and_dedup(self, fast_redial):
        """A dropped connection redials with no manual connect; closed
        duplexes leave _duplexes (the churn leak)."""
        sa, sb = TcpSwarm(), TcpSwarm()
        accepted = []
        sa.on_connection(lambda d, det: accepted.append(d))
        session = sb.connect(sa.address)
        cycles = 4
        for i in range(cycles):
            wait_until(lambda i=i: session.connects == i + 1)
            # let the LISTENER finish its inbound handshake before the
            # drop, or that accept never materializes
            wait_until(lambda i=i: len(accepted) == i + 1)
            wait_until(lambda: session.duplex and not session.duplex.closed)
            session.duplex.close()  # hard drop; supervisor redials
        wait_until(lambda: session.connects == cycles + 1)
        assert sb.supervisor.stats["reconnects"] == cycles
        # every closed duplex left the tracking lists
        wait_until(lambda: len(sb._duplexes) <= 1)
        wait_until(lambda: len(sa._duplexes) <= 1)
        wait_until(lambda: len(accepted) == cycles + 1)
        sb.destroy()
        sa.destroy()

    def test_connect_is_idempotent(self, fast_redial):
        sa, sb = TcpSwarm(), TcpSwarm()
        s1 = sb.connect(sa.address)
        s2 = sb.connect(sa.address)
        assert s1 is s2  # one session per address, kicked not duplicated
        sb.destroy()
        sa.destroy()

    def test_reconnect_false_stops_session(self, fast_redial):
        """ConnectionDetails.reconnect(False) — recorded forever, now
        finally consulted: the session stops instead of redialing."""
        sa, sb = TcpSwarm(), TcpSwarm()
        session = sb.connect(sa.address)
        wait_until(lambda: session.details is not None)
        session.details.reconnect(False)
        session.duplex.close()
        wait_until(lambda: session.state == STOPPED)
        assert session.stop_reason == "reconnect disallowed"
        time.sleep(0.2)
        assert session.connects == 1  # no further dials
        sb.destroy()
        sa.destroy()

    def test_reconnect_false_during_backoff_stops(self, fast_redial):
        """reconnect(False) set on session.details while the session is
        between connections (backoff window) must stop the next dial —
        each dial builds fresh details, so the loop head re-consults
        the previous connection's."""
        sa, sb = TcpSwarm(), TcpSwarm()
        session = sb.connect(sa.address)
        wait_until(lambda: session.details is not None)
        sa.destroy()  # server gone: session will drop into backoff
        wait_until(lambda: session.state == BACKOFF, timeout=10)
        session.details.reconnect(False)  # stop signal mid-backoff
        session.kick()
        wait_until(lambda: session.state == STOPPED)
        assert session.stop_reason == "reconnect disallowed"
        sb.destroy()

    def test_self_connection_does_not_redial_loop(self, fast_redial):
        """Network._on_connection rejects a self-connection with
        reconnect(False); the supervisor must honor it — before this
        layer existed the one-shot dial just died, but a naive redial
        loop would hammer the repo's own listener forever."""
        ra = Repo(memory=True)
        sa = TcpSwarm()
        ra.set_swarm(sa)
        session = sa.connect(sa.address)
        wait_until(lambda: session.state == STOPPED)
        assert session.stop_reason == "reconnect disallowed"
        dials = sa.supervisor.stats["dials"]
        time.sleep(0.3)
        assert sa.supervisor.stats["dials"] == dials  # loop is dead
        ra.close()


class TestBan:
    def test_banned_peer_inbound_redial_refused(self, fast_redial):
        """ban() on an inbound connection's details records the proven
        identity; the peer's next inbound redial is dropped at ACCEPT
        time (it used to be accepted unconditionally)."""
        sa = TcpSwarm(identity=os.urandom(32))
        sb = TcpSwarm(identity=os.urandom(32))
        accepted = []

        def on_conn(duplex, details):
            accepted.append((duplex, details))
            if len(accepted) == 1:
                details.ban()  # first contact: ban the peer
                duplex.close()

        sa.on_connection(on_conn)
        session = sb.connect(sa.address)
        wait_until(lambda: len(accepted) == 1)
        assert accepted[0][0].peer_identity in sa._banned_ids
        # the supervisor keeps redialing (B doesn't know it's banned);
        # every redial must die at accept, never reach the callback
        wait_until(lambda: session.connects >= 3)
        assert len(accepted) == 1
        sb.destroy()
        sa.destroy()

    def test_ban_on_outbound_stops_session(self, fast_redial):
        sa, sb = TcpSwarm(), TcpSwarm()
        session = sb.connect(sa.address)
        wait_until(lambda: session.details is not None)
        session.details.ban()  # severs the live connection itself
        wait_until(lambda: session.duplex.closed)
        wait_until(lambda: session.state == STOPPED)
        assert session.stop_reason == "peer banned"
        assert sa.address in sb._banned_addrs
        sb.destroy()
        sa.destroy()

    def test_anonymous_inbound_ban_uses_host(self, fast_redial):
        """Without identity auth the peer host is the only stable key:
        ban() on an anonymous inbound connection must still take
        effect (it recorded nothing before and the redial was accepted
        unconditionally forever)."""
        sa, sb = TcpSwarm(), TcpSwarm()  # no identities
        accepted = []

        def on_conn(duplex, details):
            accepted.append(duplex)
            if len(accepted) == 1:
                details.ban()

        sa.on_connection(on_conn)
        session = sb.connect(sa.address)
        wait_until(lambda: len(accepted) == 1)
        wait_until(lambda: accepted[0].closed)  # ban severed it
        assert "127.0.0.1" in sa._banned_hosts
        # redials die at accept (before any handshake), never reaching
        # the callback
        wait_until(lambda: session.failures + session.connects >= 3)
        assert len(accepted) == 1
        sb.destroy()
        sa.destroy()

    def test_connect_after_stopped_session_starts_fresh(
        self, fast_redial
    ):
        """connect() on an address whose session STOPPED must start a
        fresh session (the old thread exited; kick() would wake
        nobody and the caller would wait forever)."""
        sa, sb = TcpSwarm(), TcpSwarm()
        s1 = sb.connect(sa.address)
        wait_until(lambda: s1.details is not None)
        s1.details.reconnect(False)
        s1.duplex.close()
        wait_until(lambda: s1.state == STOPPED)
        s2 = sb.connect(sa.address)
        assert s2 is not s1
        wait_until(lambda: s2.state == CONNECTED)
        sb.destroy()
        sa.destroy()


class TestKeepalive:
    def test_half_open_detected_within_budget(self, monkeypatch):
        """A peer with the socket open but nothing flowing (machine
        gone, NAT timeout, stalled reader) must be shed within
        2 * HM_NET_PING_S * HM_NET_PING_MISSES — not at the 64MB
        outbox bound."""
        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_NET_PING_S", "0.2")
        monkeypatch.setenv("HM_NET_PING_MISSES", "2")
        a, b = sockmod.socketpair()
        t0 = time.monotonic()
        d = TcpDuplex(a)
        # b: socket open, never reads, never writes
        wait_until(lambda: d.closed, timeout=5)
        elapsed = time.monotonic() - t0
        assert elapsed <= 2 * 0.2 * 2 + 0.5, elapsed
        b.close()

    def test_half_open_bound_holds_at_miss_budget_one(self, monkeypatch):
        """The documented bound (2 * P * M) must hold at M=1 too: shed
        lands ON the Nth unanswered probe, by (M+1)*P."""
        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_NET_PING_S", "0.2")
        monkeypatch.setenv("HM_NET_PING_MISSES", "1")
        a, b = sockmod.socketpair()
        t0 = time.monotonic()
        d = TcpDuplex(a)
        wait_until(lambda: d.closed, timeout=5)
        assert time.monotonic() - t0 <= 2 * 0.2 * 1 + 0.5
        b.close()

    def test_healthy_idle_pair_stays_up(self, monkeypatch):
        """Ping/pong keeps an IDLE but healthy pair alive well past the
        miss budget."""
        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_NET_PING_S", "0.15")
        monkeypatch.setenv("HM_NET_PING_MISSES", "1")
        a, b = sockmod.socketpair()
        da, db = TcpDuplex(a), TcpDuplex(b)
        got = []
        db.on_message(got.append)
        time.sleep(1.2)  # ~8 ping periods, miss budget 1
        assert not da.closed and not db.closed
        assert got == []  # keepalive frames never reach subscribers
        da.send({"still": "works"})
        wait_until(lambda: got == [{"still": "works"}])
        da.close()
        db.close()

    def test_keepalive_shed_redial_resyncs(self, fast_redial, monkeypatch):
        """Integration: an established repo link goes half-open (the
        listener's inbound processing wedges, so it stops answering
        pings); BOTH ends' keepalives shed, the dialer's supervisor
        redials, and replication resyncs from cursors — counted by
        ReplicationManager.stats."""
        monkeypatch.setenv("HM_NET_PING_S", "0.25")
        monkeypatch.setenv("HM_NET_PING_MISSES", "1")
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"v": 1})
        assert rb.open(url).value(timeout=10)["v"] == 1
        wait_until(lambda: len(sa._duplexes) == 1)
        wedged = sa._duplexes[0]
        # wedge the listener side's reader: takes effect on the next
        # inbound frame, after which A never pongs (nor processes) —
        # B's writes pile up unread behind an open socket, the classic
        # half-open shape
        stall = threading.Event()

        def wedge(_n):
            stall.wait(3600)
            return None  # reader sees EOF once the test releases it

        wedged._read_exact = wedge
        t0 = time.monotonic()
        # keepalive sheds the wedged duplex (A's probes go unanswered),
        # NOT the 64MB outbox bound; the dialer sees the close and
        # redials; replication renegotiates from cursors
        wait_until(lambda: wedged.closed, timeout=10)
        assert time.monotonic() - t0 < 2 * 0.25 * 1 + 5
        wait_until(
            lambda: rb.back.network.replication.stats["resyncs"] >= 1,
            timeout=10,
        )
        assert sb.supervisor.stats["reconnects"] >= 1
        # the restored link replicates in the direction the wedge had
        # silenced (B -> A)
        rb.change(url, lambda d: d.__setitem__("v", 2))
        wait_until(lambda: ra.doc(url).get("v") == 2, timeout=15)
        stall.set()
        ra.close()
        rb.close()


def _apply_script(repo_a, repo_b, url, lo, hi):
    for i in range(lo, hi):
        repo_a.change(url, lambda d, i=i: d["a"].append(i))
        repo_b.change(url, lambda d, i=i: d["b"].append(i))


def _wait_converged(ra, rb, url, want, timeout=60):
    """Converge or fail with the full churn state (which side diverged,
    peer/replication state) instead of a bare timeout."""
    try:
        wait_until(
            lambda: ra.doc(url) == want and rb.doc(url) == want,
            timeout=timeout,
        )
    except AssertionError:
        def peers(r):
            return [
                (p.id[:6], p.is_connected)
                for p in r.back.network.peers.values()
            ]

        raise AssertionError(
            f"no reconvergence: want={want}\n"
            f"  ra={ra.doc(url)}\n  rb={rb.doc(url)}\n"
            f"  peers_a={peers(ra)} peers_b={peers(rb)}\n"
            f"  repl_a={ra.back.network.replication.stats} "
            f"repl_b={rb.back.network.replication.stats}"
        )


def _loopback_twin_state(n_total):
    """The converged state an UNFAULTED pair reaches on the same edit
    script — the bit-identical oracle for the chaos runs."""
    from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm

    hub = LoopbackHub()
    ra, rb = Repo(memory=True), Repo(memory=True)
    ra.set_swarm(LoopbackSwarm(hub))
    rb.set_swarm(LoopbackSwarm(hub))
    url = ra.create({"a": [], "b": []})
    assert rb.open(url).value(timeout=10) is not None
    _apply_script(ra, rb, url, 0, n_total)
    want = {"a": list(range(n_total)), "b": list(range(n_total))}
    wait_until(lambda: ra.doc(url) == want and rb.doc(url) == want)
    state = ra.doc(url)
    ra.close()
    rb.close()
    return state


class TestChaosConvergence:
    @pytest.mark.parametrize("live", ["1", "0"])
    def test_kill_heal_reconverges_bit_identical(
        self, live, fast_redial, monkeypatch
    ):
        """The tier-1 deterministic chaos test: a seeded kill-and-heal
        FaultPlan severs the link mid-edit; the supervised redial (no
        manual re-connect) restores replication and both repos
        reconverge bit-identically to the loopback twin."""
        monkeypatch.setenv("HM_LIVE", live)
        plan = FaultPlan(seed=11, events=[(1, "kill"), (2, "heal")])
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa = TcpSwarm()
        fb = FaultSwarm(TcpSwarm(), plan)
        ra.set_swarm(sa)
        rb.set_swarm(fb)
        fb.connect(sa.address)
        url = ra.create({"a": [], "b": []})
        assert rb.open(url).value(timeout=10) is not None

        n1, n2, n3 = 5, 5, 5
        _apply_script(ra, rb, url, 0, n1)  # healthy phase
        fb.tick()  # kill: link down, connection severed
        wait_until(lambda: plan.down)
        _apply_script(ra, rb, url, n1, n1 + n2)  # partitioned edits
        fb.tick()  # heal: the next supervised redial goes through
        _apply_script(ra, rb, url, n1 + n2, n1 + n2 + n3)

        want = _loopback_twin_state(n1 + n2 + n3)
        _wait_converged(ra, rb, url, want)
        assert rb.back.network.replication.stats["resyncs"] >= 1
        ra.close()
        rb.close()

    def test_lossy_then_kill_heal_fuzz(self, fast_redial, monkeypatch):
        """Seeded drop/dup faults during the burst, then a clean
        kill+heal cycle: the reconnect's from-scratch renegotiation
        recovers whatever the lossy window ate, and the final state is
        bit-identical to the loopback twin."""
        monkeypatch.setenv("HM_LIVE", "1")
        plan = FaultPlan(
            seed=1337,
            drop_p=0.05,
            dup_p=0.05,
            events=[(1, "clean"), (2, "kill"), (3, "heal")],
        )
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa = TcpSwarm()
        fb = FaultSwarm(TcpSwarm(), plan)
        ra.set_swarm(sa)
        rb.set_swarm(fb)
        fb.connect(sa.address)
        url = ra.create({"a": [], "b": []})
        assert rb.open(url).value(timeout=10) is not None
        n = 12
        _apply_script(ra, rb, url, 0, n)  # under drop/dup faults
        assert fb.stats["frames_dropped_injected"] >= 0  # counted
        fb.tick()  # clean: loss stops
        fb.tick()  # kill
        fb.tick()  # heal -> redial renegotiates everything
        want = _loopback_twin_state(n)
        _wait_converged(ra, rb, url, want)
        ra.close()
        rb.close()


class TestHalfWired:
    def test_pending_prunes_dead_connections(self):
        """Non-authority side: a connection that died without ever
        receiving ConfirmConnection must leave _pending — otherwise
        len(pending) > 1 forever and the next (only live) connection is
        never optimistically wired: the half-wired wedge the chaos fuzz
        exposed."""
        from hypermerge_tpu.net.connection import PeerConnection
        from hypermerge_tpu.net.duplex import duplex_pair
        from hypermerge_tpu.net.peer import NetworkPeer

        ready = []
        p = NetworkPeer("idA", "idB", ready.append)  # B > A: no authority
        d1a, _d1b = duplex_pair()
        c1 = PeerConnection(d1a, True)
        p.add_connection(c1)
        assert p.connection is c1 and len(ready) == 1
        c1.close()  # dropped before any ConfirmConnection arrived
        assert p.connection is None
        d2a, _d2b = duplex_pair()
        c2 = PeerConnection(d2a, True)
        p.add_connection(c2)
        assert p.is_connected and p.connection is c2
        assert len(ready) == 2

    def test_info_timeout_reaps_half_wired_connection(self, monkeypatch):
        """A connection whose Info exchange never completes (peer's
        frame eaten by a faulty middlebox / injected fault) must be
        closed by the reaper, not idle forever behind healthy
        keepalives."""
        from hypermerge_tpu.net.duplex import duplex_pair

        from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm

        monkeypatch.setenv("HM_INFO_TIMEOUT_S", "0.3")
        repo = Repo(memory=True)
        repo.set_swarm(LoopbackSwarm(LoopbackHub()))  # wires Network
        a, b = duplex_pair()
        b.on_message(lambda m: None)  # swallows Info, never replies
        repo.back.network._on_connection(
            a, ConnectionDetails(client=True)
        )
        wait_until(lambda: a.closed, timeout=5)
        repo.close()


class TestHmFaultEnv:
    def test_hm_fault_wraps_every_swarm(self, fast_redial, monkeypatch):
        """HM_FAULT=<spec> turns fault injection on for bench/soak runs
        with no code change: Network.set_swarm wraps the swarm and the
        ticker advances the plan on a wall clock; the system still
        converges through the scheduled kill/heal cycle."""
        monkeypatch.setenv("HM_FAULT", "seed=3,kill@4,heal@7,tick=50")
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        from hypermerge_tpu.net.faults import FaultSwarm

        assert isinstance(ra.back.network.swarm, FaultSwarm)
        sb.connect(sa.address)
        url = ra.create({"v": 1})
        assert rb.open(url).value(timeout=20)["v"] == 1
        time.sleep(0.5)  # ride through the kill@4/heal@7 window
        # continuous traffic (the soak shape): every edit after the
        # heal must land, whichever one raced the resync window
        for v in range(2, 6):
            ra.change(url, lambda d, v=v: d.__setitem__("v", v))
            time.sleep(0.2)
        wait_until(lambda: rb.doc(url).get("v") == 5, timeout=20)
        ra.close()
        rb.close()


@pytest.mark.slow
class TestChaosSoak:
    def test_churn_soak_many_cycles(self, fast_redial, monkeypatch):
        """Long soak: repeated lossy windows + kill/heal cycles under
        continuous concurrent edits; every cycle must reconverge."""
        monkeypatch.setenv("HM_LIVE", "1")
        events = []
        for c in range(4):
            base = c * 3 + 1
            events += [(base, "lossy"), (base + 1, "kill"),
                       (base + 2, "heal"), (base + 2, "clean")]
        plan = FaultPlan(seed=5, drop_p=0.03, dup_p=0.03, events=events)
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa = TcpSwarm()
        fb = FaultSwarm(TcpSwarm(), plan)
        ra.set_swarm(sa)
        rb.set_swarm(fb)
        fb.connect(sa.address)
        url = ra.create({"a": [], "b": []})
        assert rb.open(url).value(timeout=10) is not None
        n = 0
        for _cycle in range(4):
            _apply_script(ra, rb, url, n, n + 8)
            n += 8
            for _ in range(3):
                fb.tick()
                time.sleep(0.3)
        want = _loopback_twin_state(n)
        _wait_converged(ra, rb, url, want, timeout=90)
        assert rb.back.network.replication.stats["resyncs"] >= 2
        ra.close()
        rb.close()
