"""Bench JSON schema is additive-only.

The driver regression-gates on bench.py's single JSON line; a renamed
or dropped key silently breaks the trajectory comparison. This pins
every key any prior round shipped (plus this round's pack-plane keys)
as present in the source — new keys may be added freely, existing ones
may never be removed or renamed."""

from pathlib import Path

BENCH_SRC = Path(__file__).parent.parent / "bench.py"

# every configs{} key shipped by a prior BASELINE round, plus the
# top-level envelope; frozen — additions only
PINNED_KEYS = (
    # envelope
    "metric",
    "value",
    "unit",
    "vs_baseline",
    "configs",
    "telemetry",
    # primary + stage breakdown
    "cold_open_s_10k_docs",
    "cold_first_process_s",
    "docs",
    "ops_per_doc",
    "stages",
    "host_serial_s",
    "device_s",
    "pipeline",
    "wall_critical_path_s",
    "multichip_8_s",
    "multichip_mode",
    "multichip_devices",
    "multichip_topology",
    "multichip_stages",
    "projection_8chip_reference_s",
    # aux configs
    "config1_change_latency_us",
    "config2_convergence_s",
    "config2_edits_per_s",
    "config2_live",
    "config_churn_s",
    "config_churn_edits_per_s",
    "config_churn",
    "config_swarm_s",
    "config_swarm",
    "config_fleet1000_s",
    "config_fleet1000",
    "config_crash_t_recover_ms",
    "config_crash",
    "config6_live_first_edit_ms",
    "config6_live_burst_edits_per_s",
    "config6_live",
    "config6_live_adopt_decode_ms",
    "config6_demote_readopt_ms",
    "config6_demote",
    "lock_held_blocking_ms",
    "config_writers_edits_per_s",
    "config_writers_scaling",
    "config_writers_scaling_8_32",
    "config_writers_hotdoc_edits_per_s",
    "config_writers_hotdoc_converged",
    "config3_multiactor_ops_per_s",
    "config5_union_100k_ms",
    "config_read_qps",
    "config_read_p50_ms",
    "config_read_p99_ms",
    "config_read_host_qps",
    "config_read_speedup",
    "config_read",
    "config6_text_trace_ops_per_s",
    "device_link_rtt_ms",
    # pack-plane gate (ISSUE 19)
    "config_coldopen",
    "config_coldopen_s",
    "pack_workers",
    "t_pack_busy_per_worker",
    "coldopen_pack_speedup",
    "coldopen_pack_bound",
    # service plane under overload (ISSUE 20): the nested block plus
    # its headline aliases and the gate/attribution keys inside it
    "config_service",
    "config_service_qps",
    "config_service_p50_ms",
    "config_service_p99_ms",
    "config_service_recovery_s",
    "config_service_gated_ok",
    "saturation_qps",
    "recovery_to_slo_s",
    "acked_lost",
    "reads_shed",
    "shed_reads",
    "brownout_reads",
    "deferred_installs",
    "tenants",
    "paced_commits",
    "gates",
    "gated_ok",
    "write_p50_ms",
    "write_p99_ms",
)


def test_bench_json_keys_additive_only():
    src = BENCH_SRC.read_text()
    missing = [k for k in PINNED_KEYS if f'"{k}"' not in src]
    assert not missing, (
        f"bench.py no longer emits pinned JSON keys {missing}: the "
        "bench schema is additive-only — restore the keys (aliases are "
        "fine) instead of renaming/removing"
    )
