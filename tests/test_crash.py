"""Crash-consistent storage: seeded disk faults + kill-anywhere matrix.

The storage twin of tests/test_chaos.py. A CrashRecorder
(storage/faults.py) records the write/fsync/commit schedule of a
workload; every prefix replays as a simulated crash and the recovery
invariants are asserted on reopen:

  - reopen never raises (whatever boundary the crash landed on);
  - recovered state is a gapless PREFIX of acknowledged state;
  - anything acknowledged under the durable tier (HM_FSYNC) survives
    a simulated power cut;
  - a crashed-then-recovered repo reconverges bit-identically to a
    clean twin after resync (HM_LIVE=1/0 both).

Plus deterministic fault-plan units (same seed = same schedule) and
targeted ENOSPC/EIO injection on the append paths.
"""

import os

import pytest

from hypermerge_tpu.storage import faults as F
from hypermerge_tpu.storage.feed import FileFeedStorage

from helpers import plainify, wait_until


def _mk_storage(root, name="feed"):
    return FileFeedStorage(os.path.join(str(root), "ab", name))


# ---------------------------------------------------------------------------
# fault-plan determinism


def test_fault_plan_same_seed_same_schedule():
    def fates(seed):
        plan = F.DiskFaultPlan(
            seed=seed, write_error_p=0.2, torn_write_p=0.2,
            fsync_error_p=0.1, fsync_lie_p=0.2,
        )
        out = []
        for i in range(40):
            out.append(plan.write_fate("a/log", 64 + i))
            out.append(plan.fsync_fate("a/log"))
        return out

    assert fates(7) == fates(7)
    assert fates(7) != fates(8)  # and the seed actually matters


def test_fault_plan_per_path_streams_independent():
    """Which op of a path faults must not depend on how OTHER paths
    interleave (the per-direction-stream property of net FaultPlan)."""
    plan1 = F.DiskFaultPlan(seed=3, write_error_p=0.3)
    solo = [plan1.write_fate("x", 8) for _ in range(20)]
    plan2 = F.DiskFaultPlan(seed=3, write_error_p=0.3)
    mixed = []
    for _ in range(20):
        mixed.append(plan2.write_fate("x", 8))
        plan2.write_fate("y", 8)  # interleaved traffic on another path
    assert solo == mixed


def test_fault_plan_after_grace_period():
    plan = F.DiskFaultPlan(seed=1, write_error_p=1.0, after=3)
    for _ in range(3):
        assert plan.write_fate("p", 4)[0] == "ok"
    assert plan.write_fate("p", 4)[0] == "error"


# ---------------------------------------------------------------------------
# targeted ENOSPC / EIO / torn-write injection


def test_feed_append_enospc_keeps_memory_consistent(tmp_path):
    s = _mk_storage(tmp_path)
    for i in range(3):
        s.append(b"block-%d" % i)
    plan = F.DiskFaultPlan(seed=0, write_error_p=1.0)
    with F.activate(plan=plan):
        with pytest.raises(OSError):
            s.append(b"doomed")
    assert len(s) == 3  # in-memory state did not run ahead
    s.append(b"block-3")  # next append heals the (possibly torn) tail
    s2 = _mk_storage(tmp_path)
    assert len(s2) == 4
    assert [s2.get(i) for i in range(4)] == [
        b"block-0", b"block-1", b"block-2", b"block-3",
    ]


def test_feed_append_torn_write_heals(tmp_path):
    s = _mk_storage(tmp_path)
    s.append(b"healthy")
    plan = F.DiskFaultPlan(seed=5, torn_write_p=1.0)
    with F.activate(plan=plan):
        with pytest.raises(OSError):
            s.append(b"torn-block-payload")
    # torn bytes are on disk past the logical end; a fresh open ignores
    # them and the next append overwrites them
    assert len(_mk_storage(tmp_path)) == 1
    s.append(b"after")
    s3 = _mk_storage(tmp_path)
    assert [s3.get(i) for i in range(2)] == [b"healthy", b"after"]


def test_actor_write_change_enospc_no_phantom(tmp_path):
    """A failed feed append must not leave a phantom change in the
    actor's memory (seq continuity would break for every later write)."""
    from hypermerge_tpu.backend.actor import Actor
    from hypermerge_tpu.crdt.change import Change
    from hypermerge_tpu.storage.feed import Feed
    from hypermerge_tpu.utils import keys as keymod

    pair = keymod.create()
    feed = Feed(
        pair.public_key, _mk_storage(tmp_path), pair.secret_key
    )
    events = []
    actor = Actor(feed, events.append)

    def change(seq):
        return Change(
            actor=pair.public_key, seq=seq, start_op=seq, deps={},
            ops=[], message="",
        )

    actor.write_change(change(1))
    plan = F.DiskFaultPlan(seed=0, write_error_p=1.0)
    with F.activate(plan=plan):
        with pytest.raises(OSError):
            actor.write_change(change(2))
    assert actor.seq_head == 1
    actor.write_change(change(2))  # same seq retries cleanly
    assert actor.seq_head == 2
    assert feed.length == 2


def test_colcache_enospc_requeues_table_lines(tmp_path):
    """Interner table lines taken for a commit that failed must go back
    on the pending queue — otherwise later commits reference table
    indices the file never defines."""
    from hypermerge_tpu.storage.colcache import (
        FeedColumnCache,
        FileColumnStorageV2,
    )
    from hypermerge_tpu.crdt.change import Change, Op, Action, ROOT

    path = str(tmp_path / "ab" / "feed.cols2")
    cc = FeedColumnCache(FileColumnStorageV2(path), writer="w" * 16)

    def change(seq, key, val):
        return Change(
            actor="w" * 16, seq=seq, start_op=seq, deps={},
            ops=[Op(Action.SET, ROOT, key=key, value=val)],
        )

    cc.append_change(change(1, "a", "hello"))
    plan = F.DiskFaultPlan(seed=2, write_error_p=1.0)
    with F.activate(plan=plan):
        with pytest.raises(OSError):
            cc.append_change(change(2, "b", "world"))
    cc.append_change(change(2, "b", "world"))  # retry after space frees
    cc2 = FeedColumnCache(FileColumnStorageV2(path), writer="w" * 16)
    fc = cc2.columns()
    assert fc.n_changes == 2
    assert "world" in fc.strings  # the requeued table line landed


# ---------------------------------------------------------------------------
# per-format crash matrices (every write boundary is a crash point)


def test_feed_crash_matrix(tmp_path):
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    acked = []  # (event index, blocks acked)
    with F.activate(recorder=rec):
        s = FileFeedStorage(str(work / "ab" / "feed"))
        for i in range(6):
            s.append(b"payload-%d-%s" % (i, b"x" * i))
            acked.append((rec.n_points - 1, i + 1))
    n = rec.n_points
    for k in range(n):
        dst = str(tmp_path / f"c{k}")
        rec.materialize(dst, k)
        s2 = FileFeedStorage(os.path.join(dst, "ab", "feed"))
        got = len(s2)  # reopen never raises
        # gapless prefix of acknowledged state
        full_acked = max((m for e, m in acked if e <= k), default=0)
        assert got <= full_acked + 1  # +1: the append being torn
        for i in range(got):
            assert s2.get(i) == b"payload-%d-%s" % (i, b"x" * i)
        s2.append(b"heal")  # the next append always heals the tail
        s3 = FileFeedStorage(os.path.join(dst, "ab", "feed"))
        assert len(s3) == got + 1
        assert s3.get(got) == b"heal"


def test_feed_crash_matrix_intra_write_tears(tmp_path):
    """Crashes INSIDE a write syscall (partial byte prefixes) heal the
    same way as boundary crashes."""
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        s = FileFeedStorage(str(work / "ab" / "feed"))
        for i in range(3):
            s.append(b"0123456789abcdef-%d" % i)
    n = rec.n_points - 1
    for k in range(n):
        for cut in (1, 3):
            dst = str(tmp_path / f"t{k}_{cut}")
            rec.materialize(dst, k, partial_last=cut)
            s2 = FileFeedStorage(os.path.join(dst, "ab", "feed"))
            got = len(s2)
            for i in range(got):
                assert s2.get(i) == b"0123456789abcdef-%d" % i
            s2.append(b"heal")
            assert len(
                FileFeedStorage(os.path.join(dst, "ab", "feed"))
            ) == got + 1


def test_slab_crash_matrix(tmp_path):
    from hypermerge_tpu.storage.slab import (
        CorpusSlab,
        KIND_IMAGE,
        KIND_RECORD,
    )

    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    payloads = {"feedA": [], "feedB": []}
    with F.activate(recorder=rec):
        slab = CorpusSlab(str(work / "cols.slab"))
        for i in range(3):
            for name in ("feedA", "feedB"):
                kind = KIND_IMAGE if i == 0 else KIND_RECORD
                payload = b"%s-%d-%s" % (name.encode(), i, b"y" * 7)
                slab.append(kind, name, payload)
                if kind == KIND_IMAGE:
                    payloads[name] = [payload]
                else:
                    payloads[name].append(payload)
        slab.close()
    n = rec.n_points
    for k in range(n):
        dst = str(tmp_path / f"s{k}")
        rec.materialize(dst, k)
        s2 = CorpusSlab(os.path.join(dst, "cols.slab"))
        names = s2.feed_names()  # loading IS the repair; never raises
        for name in names:
            got = s2.image_bytes(name)
            # the recovered image must be a concatenation of a prefix
            # of that feed's appended segments
            acc = b""
            ok = got == b""
            for p in payloads[name]:
                acc += p
                if got == acc:
                    ok = True
            assert ok, (k, name, got)
        # and the slab stays appendable (heals its torn tail)
        s2.append(KIND_RECORD, "feedA", b"heal")
        assert s2.image_bytes("feedA").endswith(b"heal")
        s2.close()


def test_colcache_commit_matrix(tmp_path):
    import numpy as np

    from hypermerge_tpu.storage.colcache import (
        FileColumnStorageV2,
        PRED_FIELDS,
        ROW_FIELDS,
    )

    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        st = FileColumnStorageV2(str(work / "ab" / "f.cols2"))
        for i in range(5):
            rows = np.full((2, ROW_FIELDS), i, np.int32)
            preds = np.zeros((1, PRED_FIELDS), np.int32)
            st.commit_change(rows, preds, ['{"t":"k","v":"k%d"}' % i], 0)
    n = rec.n_points
    for k in range(n):
        dst = str(tmp_path / f"c{k}")
        rec.materialize(dst, k)
        st2 = FileColumnStorageV2(os.path.join(dst, "ab", "f.cols2"))
        rows, preds, tables, commits = st2.load()  # never raises
        m = len(commits)
        assert m <= 5
        # only COMPLETE commits are honored: rows/preds/tables all
        # consistent with the last commit record
        assert len(rows) == 2 * m
        assert len(preds) == m
        assert len(tables) == m
        if m:
            assert int(rows[-1, 0]) == m - 1


# ---------------------------------------------------------------------------
# durability tiers + power-cut model


def test_powercut_drops_unfsynced_tail_kill9_does_not(tmp_path):
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        s = FileFeedStorage(str(work / "ab" / "feed"))
        s.append(b"first")
        s.sync()  # honest fsync: durable from here
        s.append(b"second")  # flushed, never fsynced
    k = rec.n_points - 1
    rec.materialize(str(tmp_path / "kill9"), k)
    assert len(FileFeedStorage(str(tmp_path / "kill9/ab/feed"))) == 2
    rec.materialize(str(tmp_path / "cut"), k, powercut=True)
    s2 = FileFeedStorage(str(tmp_path / "cut/ab/feed"))
    assert len(s2) == 1  # only the fsynced prefix survived
    assert s2.get(0) == b"first"


def test_fsync_tier2_makes_acked_appends_powercut_durable(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("HM_FSYNC", "2")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    marks = []
    with F.activate(recorder=rec):
        s = FileFeedStorage(str(work / "ab" / "feed"))
        for i in range(4):
            s.append(b"durable-%d" % i)
            marks.append((rec.n_points - 1, i + 1))
    for k, acked in marks:
        dst = str(tmp_path / f"p{k}")
        rec.materialize(dst, k, powercut=True)
        s2 = FileFeedStorage(os.path.join(dst, "ab", "feed"))
        assert len(s2) >= acked  # every acked append survived the cut
        for i in range(acked):
            assert s2.get(i) == b"durable-%d" % i


def test_fsync_lie_is_visible_to_powercut_only(tmp_path, monkeypatch):
    monkeypatch.setenv("HM_FSYNC", "2")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    plan = F.DiskFaultPlan(seed=0, fsync_lie_p=1.0)
    with F.activate(plan=plan, recorder=rec):
        s = FileFeedStorage(str(work / "ab" / "feed"))
        s.append(b"claimed-durable")  # the fsync LIED
    k = rec.n_points - 1
    rec.materialize(str(tmp_path / "cut"), k, powercut=True)
    s2 = FileFeedStorage(str(tmp_path / "cut/ab/feed"))
    assert len(s2) == 0  # the lie dropped the bytes at the cut
    s2.append(b"heal")  # and reopen still heals
    assert len(s2) == 1
    assert plan.stats["fsync_lies"] >= 1


def test_fsync_eio_surfaces(tmp_path, monkeypatch):
    monkeypatch.setenv("HM_FSYNC", "2")
    plan = F.DiskFaultPlan(seed=0, fsync_error_p=1.0)
    s = _mk_storage(tmp_path)
    with F.activate(plan=plan):
        with pytest.raises(OSError):
            s.append(b"x")


def test_group_fsync_tier1_barrier(tmp_path, monkeypatch):
    """Tier 1: appends mark dirty; the durability barrier fsyncs every
    dirty log, so sqlite rows committed after it can never describe
    unfsynced bytes."""
    from hypermerge_tpu.storage.durability import DurabilityManager

    monkeypatch.setenv("HM_FSYNC", "1")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    dm = DurabilityManager()
    with F.activate(recorder=rec):
        s = FileFeedStorage(
            str(work / "ab" / "feed"), durability=dm
        )
        s.append(b"one")
        s.append(b"two")
        dm.barrier()  # the pre-sqlite sync point
        mark = rec.n_points
        s.append(b"three")  # dirty again, not yet synced
    dm.close()
    rec.materialize(str(tmp_path / "cut"), mark, powercut=True)
    s2 = FileFeedStorage(str(tmp_path / "cut/ab/feed"))
    assert len(s2) == 2  # everything before the barrier survived


# ---------------------------------------------------------------------------
# sqlite-vs-feed reconciliation + recovery-on-open wiring


def _mk_repo_with_doc(path, n_edits=5):
    from hypermerge_tpu.repo import Repo

    repo = Repo(path=str(path))
    url = repo.create({"edits": []})
    for i in range(n_edits):
        repo.change(url, lambda d, i=i: d["edits"].append(i))
    if repo.back.live is not None:
        repo.back.live.flush_now()
    return repo, url


def test_clocks_ahead_of_feeds_reconciled_on_open(tmp_path):
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    repo, url = _mk_repo_with_doc(tmp_path / "r")
    doc_id = validate_doc_url(url)
    actor = max(
        repo.back.docs[doc_id].clock.items(), key=lambda kv: kv[1]
    )[0]
    repo.close()

    # clocks-ahead skew: drop the feed's last two blocks out-of-band
    # (the unrecoverable direction a power cut can produce), then mark
    # the repo crashed so recovery runs on open
    feed_path = str(tmp_path / "r" / "feeds" / actor[:2] / actor)
    s = FileFeedStorage(feed_path)
    n = len(s)
    s.truncate_to(n - 2)
    open(str(tmp_path / "r" / "repo.dirty"), "wb").close()

    repo2 = Repo(path=str(tmp_path / "r"))
    try:
        rep = repo2.back.recovery_report
        assert rep is not None and rep["clock_rows_clamped"] >= 1, rep
        assert (
            repo2.back.clocks.get(repo2.back.id, doc_id)[actor] == n - 2
        )
        h = repo2.open(url)
        v = h.value(timeout=30)
        edits = v.get("edits", [])
        # a gapless prefix of the acknowledged edits
        assert list(edits) == list(range(len(edits)))
        from hypermerge_tpu.storage.scrub import last_report

        assert last_report(str(tmp_path / "r")) is not None
    finally:
        repo2.close()


def test_clean_close_skips_recovery(tmp_path):
    from hypermerge_tpu.repo import Repo

    repo, url = _mk_repo_with_doc(tmp_path / "r")
    repo.close()
    assert not os.path.exists(str(tmp_path / "r" / "repo.dirty"))
    repo2 = Repo(path=str(tmp_path / "r"))
    try:
        assert repo2.back.recovery_report is None
        assert os.path.exists(str(tmp_path / "r" / "repo.dirty"))
    finally:
        repo2.close()


def test_actor_keys_persist_across_reopen(tmp_path):
    """Writable actors stay writable across restarts — the crashed
    session's feed can be sealed AND extended (no per-session actor
    churn, no permanently unreplicable unsigned tail)."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    repo, url = _mk_repo_with_doc(tmp_path / "r", n_edits=3)
    doc_id = validate_doc_url(url)
    actors_before = set(repo.back.cursors.get(repo.back.id, doc_id))
    repo.close()
    repo2 = Repo(path=str(tmp_path / "r"))
    try:
        h = repo2.open(url)
        assert h.value(timeout=30) is not None
        repo2.change(url, lambda d: d["edits"].append(99))
        if repo2.back.live is not None:
            repo2.back.live.flush_now()
        doc = repo2.back.docs[doc_id]
        wait_until(lambda: sum(doc.clock.values()) >= 5)
        actors_after = set(
            repo2.back.cursors.get(repo2.back.id, doc_id)
        )
        # the reopened session wrote through an EXISTING actor
        assert actors_after == actors_before
    finally:
        repo2.close()


def test_scrub_seals_unsigned_tail_on_writable_feed(tmp_path):
    """Crash recovery re-signs a writable feed's crash-orphaned lazy-
    signing tail: the next audit is clean with zero block loss."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.storage.integrity import AUDIT_OK

    repo, url = _mk_repo_with_doc(tmp_path / "r", n_edits=4)
    # crash: no close(), no seal — writable feeds keep unsigned tails
    # (sign_interval is 1024). Settle debounced flushers first so the
    # on-disk state is complete, then drop the repo without closing.
    repo.back._stores.flush_now()
    repo.back._cache_syncs.flush_now()
    del repo

    repo2 = Repo(path=str(tmp_path / "r"))
    try:
        rep = repo2.back.recovery_report
        assert rep is not None
        assert rep["unsigned_tails_sealed"] >= 1, rep
        for pk in repo2.back.feed_info.all_public_ids():
            feed = repo2.back.feeds.open_feed(pk)
            if feed.length:
                assert feed.audit_status() == AUDIT_OK, pk
    finally:
        repo2.close()


# ---------------------------------------------------------------------------
# whole-repo kill-anywhere matrix


def _sample_points(n, want=14):
    step = max(1, n // want)
    return sorted(set(range(0, n, step)) | {n})


@pytest.mark.parametrize("live", ["1", "0"])
def test_whole_repo_kill_anywhere(tmp_path, monkeypatch, live):
    """Mixed workload under a CrashRecorder; every sampled prefix
    reopens with zero recovery-invariant violations: reopen (incl.
    recovery) never raises, the doc reads back a gapless prefix of the
    acked edits, and the repo stays writable."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    monkeypatch.setenv("HM_LIVE", live)
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    acked = []
    with F.activate(recorder=rec):
        repo = Repo(path=str(work))
        url = repo.create({"edits": []})
        for i in range(8):
            repo.change(url, lambda d, i=i: d["edits"].append(i))
            if repo.back.live is not None:
                repo.back.live.flush_now()
            repo.back._stores.flush_now()
            repo.back._cache_syncs.flush_now()
            acked.append((rec.n_points - 1, i + 1))
        k_max = rec.n_points - 1
        repo.close()
    doc_id = validate_doc_url(url)
    for k in _sample_points(k_max):
        dst = str(tmp_path / f"c{k}")
        rec.materialize(dst, k)
        repo2 = Repo(path=dst)  # reopen + recovery: must not raise
        try:
            if doc_id not in repo2.back.clocks.all_doc_ids(
                repo2.back.id
            ):
                continue  # crashed before the doc's first commit
            h = repo2.open(url)
            v = h.value(timeout=30)
            edits = list(v.get("edits", []))
            # gapless prefix of acknowledged state, bounded by the
            # crash point's ack level (+1 for the in-flight edit)
            assert edits == list(range(len(edits))), (k, edits)
            hi = max((m for e, m in acked if e <= k), default=0)
            assert len(edits) <= hi + 1, (k, len(edits), hi)
            # the recovered repo stays writable
            repo2.change(url, lambda d: d["edits"].append(777))
            wait_until(
                lambda: 777 in (repo2.doc(url) or {}).get("edits", [])
            )
        finally:
            repo2.close()


@pytest.mark.parametrize("live", ["1", "0"])
def test_crash_recover_reconverges_with_clean_twin(
    tmp_path, monkeypatch, live
):
    """A crashed-then-recovered repo, resynced against a clean twin
    holding the full acked history, reconverges bit-identically —
    including blocks the recovery truncated (they re-replicate)."""
    from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    monkeypatch.setenv("HM_LIVE", live)
    hub = LoopbackHub()
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    rb = Repo(memory=True)
    rb.set_swarm(LoopbackSwarm(hub))
    with F.activate(recorder=rec):
        ra = Repo(path=str(work))
        sa = LoopbackSwarm(hub)
        ra.set_swarm(sa)
        url = ra.create({"edits": []})
        hb = rb.open(url)
        assert hb.value(timeout=30) is not None
        for i in range(6):
            ra.change(url, lambda d, i=i: d["edits"].append(i))
            if i % 2 == 0:
                hb.change(lambda d, i=i: d["edits"].append(100 + i))
        want = 6 + 3
        wait_until(
            lambda: len((rb.doc(url) or {}).get("edits", [])) >= want
            and len((ra.doc(url) or {}).get("edits", [])) >= want,
            timeout=60,
        )
        doc_id = validate_doc_url(url)
        twin = plainify(rb.doc(url))
        twin_clock = dict(rb.back.docs[doc_id].clock)
        k_max = rec.n_points - 1
        sa.destroy()
        ra.close()

    for k in _sample_points(k_max, want=3):
        dst = str(tmp_path / f"c{k}")
        rec.materialize(dst, k)
        r2 = Repo(path=dst)
        s2 = LoopbackSwarm(hub)
        try:
            r2.set_swarm(s2)
            h2 = r2.open(url)
            assert h2.value(timeout=60) is not None

            def converged():
                d2 = r2.back.docs.get(doc_id)
                if d2 is None or dict(d2.clock) != twin_clock:
                    return False
                return plainify(r2.doc(url)) == twin

            wait_until(converged, timeout=60)
        finally:
            r2.close()
            s2.destroy()
    rb.close()


def test_durable_tier_repo_acked_edits_survive_powercut(
    tmp_path, monkeypatch
):
    """HM_FSYNC=2 end to end: every edit acked (change + engine/store
    flush) before the cut is present after a POWER-CUT replay."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    monkeypatch.setenv("HM_FSYNC", "2")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    acked = []
    with F.activate(recorder=rec):
        repo = Repo(path=str(work))
        url = repo.create({"edits": []})
        for i in range(5):
            repo.change(url, lambda d, i=i: d["edits"].append(i))
            if repo.back.live is not None:
                repo.back.live.flush_now()
            repo.back._stores.flush_now()
            repo.back._cache_syncs.flush_now()
            repo.back.durability.flush_now()
            acked.append((rec.n_points - 1, i + 1))
        k_max = rec.n_points - 1
    doc_id = validate_doc_url(url)
    for k, want in [acked[1], acked[3], (k_max, 5)]:
        dst = str(tmp_path / f"p{k}")
        rec.materialize(dst, k, powercut=True)
        repo2 = Repo(path=dst)
        try:
            assert doc_id in repo2.back.clocks.all_doc_ids(
                repo2.back.id
            ), k
            h = repo2.open(url)
            v = h.value(timeout=30)
            edits = list(v.get("edits", []))
            assert edits[:want] == list(range(want)), (k, want, edits)
        finally:
            repo2.close()


@pytest.mark.slow
@pytest.mark.parametrize("live", ["1", "0"])
def test_multi_cycle_crash_recover_soak(tmp_path, monkeypatch, live):
    """Crash -> recover -> keep editing -> crash again, several cycles:
    recovery must compose with itself (a recovered repo is a normal
    repo), and the doc stays a gapless prefix throughout."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    import shutil

    monkeypatch.setenv("HM_LIVE", live)
    path = tmp_path / "r0"
    url = None
    next_val = 0
    for cycle in range(4):
        # snapshot the pre-workload state: cycle N's replay overlays
        # its events onto what cycle N-1's recovery produced
        base = None
        if os.path.exists(str(path)):
            base = str(tmp_path / f"base{cycle}")
            shutil.copytree(str(path), base)
        rec = F.CrashRecorder(str(path))
        with F.activate(recorder=rec):
            repo = Repo(path=str(path))
            if url is None:
                url = repo.create({"edits": []})
            else:
                h = repo.open(url)
                v = h.value(timeout=30)
                edits = list(v.get("edits", []))
                assert edits == list(range(len(edits))), (cycle, edits)
                next_val = len(edits)
            for i in range(5):
                repo.change(
                    url,
                    lambda d, v=next_val + i: d["edits"].append(v),
                )
            if repo.back.live is not None:
                repo.back.live.flush_now()
            repo.back._stores.flush_now()
            repo.back._cache_syncs.flush_now()
            k_max = rec.n_points - 1
            repo.close()
        # crash at a seeded mid-workload boundary; the recovered dir
        # REPLACES the repo for the next cycle — recovery must rewrite
        # any state the truncation invalidated, because cycle N+1
        # starts from what cycle N's recovery produced.
        import random

        k = random.Random(cycle).randrange(k_max // 2, k_max + 1)
        nxt = tmp_path / f"r{cycle + 1}"
        rec.materialize(str(nxt), k, base=base)
        path = nxt
    repo = Repo(path=str(path))
    try:
        h = repo.open(url)
        v = h.value(timeout=30)
        edits = list(v.get("edits", []))
        assert edits == list(range(len(edits)))
        repo.change(url, lambda d: d["edits"].append(999))
        wait_until(
            lambda: 999 in (repo.doc(url) or {}).get("edits", [])
        )
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# anti-entropy sweep (net/replication.py HM_ANTIENTROPY_S)


def test_antientropy_sweep_recovers_lost_tail_frames(monkeypatch):
    """App-layer frame loss on a SURVIVING connection: the gap-driven
    protocol would only recover at the next tail flush or reconnect;
    the anti-entropy FeedLength re-announce bounds it by the sweep."""
    from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
    from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_ANTIENTROPY_S", "3600")  # manual sweeps
    hub = LoopbackHub()
    plan = FaultPlan(seed=1, events=[(1, "partition_rx"), (2, "heal")])
    ra, rb = Repo(memory=True), Repo(memory=True)
    fb = FaultSwarm(LoopbackSwarm(hub), plan)
    try:
        ra.set_swarm(LoopbackSwarm(hub))
        rb.set_swarm(fb)
        url = ra.create({"edits": []})
        hb = rb.open(url)
        assert hb.value(timeout=30) is not None
        ra.change(url, lambda d: d["edits"].append(0))
        wait_until(
            lambda: len((rb.doc(url) or {}).get("edits", [])) == 1
        )
        fb.tick()  # partition_rx: frames TO b silently drop
        for i in range(1, 4):
            ra.change(url, lambda d, i=i: d["edits"].append(i))
        # drain EVERY debounced sender while the partition still eats
        # frames: a gossip flush landing after the heal would recover
        # b without the sweep (and flake this test)
        ra.back.network.replication.flush_now()
        ra.back._gossip.flush_now()
        ra.back._stores.flush_now()
        fb.tick()  # heal — but the tail frames are already lost
        import time

        time.sleep(0.2)
        assert len((rb.doc(url) or {}).get("edits", [])) == 1  # stale
        sent = ra.back.network.replication.sweep_now()
        assert sent >= 1
        wait_until(
            lambda: len((rb.doc(url) or {}).get("edits", [])) == 4,
            timeout=30,
        )
    finally:
        ra.close()
        rb.close()
        fb.destroy()


def test_antientropy_timer_runs_sweeps(monkeypatch):
    from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_ANTIENTROPY_S", "0.05")
    hub = LoopbackHub()
    ra, rb = Repo(memory=True), Repo(memory=True)
    try:
        ra.set_swarm(LoopbackSwarm(hub))
        rb.set_swarm(LoopbackSwarm(hub))
        url = ra.create({"n": 1})
        assert rb.open(url).value(timeout=30) is not None
        wait_until(
            lambda: ra.back.network.replication.stats[
                "antientropy_sweeps"
            ]
            >= 2,
            timeout=30,
        )
    finally:
        ra.close()
        rb.close()


# ---------------------------------------------------------------------------
# review regressions: marker durability, barrier failure, dry-run report


def test_dirty_marker_survives_powercut(tmp_path):
    """The crash marker is fsynced at open: even a power cut cannot
    erase it, so the reopen after one always runs recovery (tier 0
    depends on that to reconcile clocks with feeds)."""
    from hypermerge_tpu.repo import Repo

    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        repo = Repo(path=str(work))
        url = repo.create({"n": 1})
        if repo.back.live is not None:
            repo.back.live.flush_now()
        repo.back._stores.flush_now()
        k_max = rec.n_points - 1
        # crash: no close
    dst = str(tmp_path / "cut")
    rec.materialize(dst, k_max, powercut=True)
    assert os.path.exists(os.path.join(dst, "repo.dirty"))
    repo2 = Repo(path=dst)
    try:
        assert repo2.back.recovery_report is not None
    finally:
        repo2.close()


def test_durability_barrier_raises_on_fsync_error(
    tmp_path, monkeypatch
):
    """A failed group fsync must SURFACE from barrier(): the store
    flusher must not commit clock rows for bytes that never reached
    the platter (the debouncer re-queues and retries)."""
    from hypermerge_tpu.storage.durability import DurabilityManager

    monkeypatch.setenv("HM_FSYNC", "1")
    dm = DurabilityManager()
    s = FileFeedStorage(
        str(tmp_path / "ab" / "feed"), durability=dm
    )
    s.append(b"one")
    plan = F.DiskFaultPlan(seed=0, fsync_error_p=1.0)
    with F.activate(plan=plan):
        with pytest.raises(OSError):
            dm.barrier()
    # the storage stayed dirty: a later barrier (fault cleared)
    # makes it durable
    assert dm.sync_now() >= 1 or dm.barrier() is None
    dm.close()


def test_dry_run_reports_would_do_repairs(tmp_path, monkeypatch):
    """recover_repo(repair=False) must report seals/truncations/sig
    repairs it WOULD perform — without touching disk."""
    from hypermerge_tpu.backend.repo_backend import RepoBackend
    from hypermerge_tpu.storage.scrub import recover_repo

    repo, url = _mk_repo_with_doc(tmp_path / "r", n_edits=4)
    repo.back._stores.flush_now()
    repo.back._cache_syncs.flush_now()
    del repo  # crash: unsigned tails remain

    monkeypatch.setenv("HM_RECOVER", "0")
    back = RepoBackend(path=str(tmp_path / "r"))
    try:
        dry = recover_repo(back, repair=False)
        assert dry["unsigned_tails_sealed"] >= 1, dry
        assert dry["per_feed"], dry
        # nothing was written: a second dry run sees the same damage
        again = recover_repo(back, repair=False)
        assert (
            again["unsigned_tails_sealed"]
            == dry["unsigned_tails_sealed"]
        )
        real = recover_repo(back, repair=True)
        assert real["unsigned_tails_sealed"] >= 1
        after = recover_repo(back, repair=False)
        assert after["unsigned_tails_sealed"] == 0, after
    finally:
        back.close()
