"""HM_SERVE=1/0 twin fuzz: reads are bit-identical across random
edit/read interleavings, run in BOTH env orders (ISSUE 11 acceptance).

One deterministic script of edits + reads runs against a served repo
and against the per-request host-materialization twin; every read's
value must match exactly. Clock reads normalize actor ids (keys are
random per run) but pin the seq multiset.
"""

import random

import pytest

from hypermerge_tpu.models import Counter, Text
from hypermerge_tpu.repo import Repo

KEYS = ["a", "b", "c", "text", "list", "deep"]


def _edit(rng):
    """One random mutation closure + its tag (deterministic given the
    rng stream)."""
    roll = rng.random()
    if roll < 0.25:
        k, v = rng.choice(KEYS[:3]), rng.randrange(100)
        return lambda d: d.__setitem__(k, v)
    if roll < 0.40:
        s = "".join(rng.choice("abcdef") for _ in range(3))
        def set_text(d):
            if not isinstance(d.get("text"), Text):
                d["text"] = Text(s)
            else:
                d["text"].insert(
                    rng.randrange(len(d["text"]) + 1) if len(d["text"])
                    else 0,
                    s,
                )
        return set_text
    if roll < 0.55:
        vals = [rng.randrange(10) for _ in range(rng.randrange(1, 4))]
        return lambda d: d.__setitem__("list", vals)
    if roll < 0.70:
        def bump(d):
            if isinstance(d.get("ctr"), Counter):
                d.increment("ctr", 1)
            else:
                d["ctr"] = Counter(rng.randrange(5))
        return bump
    if roll < 0.85:
        return lambda d: d.__setitem__(
            "deep", {"x": {"y": rng.randrange(50)}}
        )
    k = rng.choice(KEYS[:3])
    def remove(d):
        if k in d:
            del d[k]
    return remove


def _reads(rng):
    return [
        {"kind": "text", "path": ["text"]},
        {"kind": "lookup", "path": [rng.choice(KEYS[:3])]},
        {"kind": "lookup", "path": ["deep", "x", "y"]},
        {"kind": "lookup", "path": ["ctr"]},
        {"kind": "len", "path": []},
        {"kind": "len", "path": ["list"]},
        {"kind": "index", "path": ["list"], "index": rng.randrange(4)},
        {"kind": "history"},
        {"kind": "clock"},
    ]


def _normalize(q, v):
    if q["kind"] == "clock" and isinstance(v, list):
        # actor keys are random per run: pin the seq multiset only
        return sorted(s.rsplit(":", 1)[-1] for s in v)
    return v


def run_script(seed: int, serve: str, monkeypatch) -> list:
    monkeypatch.setenv("HM_SERVE", serve)
    rng = random.Random(seed)
    repo = Repo(memory=True)
    out = []
    try:
        assert (repo.back.serve is None) == (serve == "0")
        urls = [repo.create() for _ in range(3)]
        for step in range(40):
            url = urls[rng.randrange(len(urls))]
            if rng.random() < 0.55:
                repo.change(url, _edit(rng))
            else:
                for q in _reads(rng):
                    out.append(
                        (step, q["kind"], _normalize(q, repo.read(url, q)))
                    )
    finally:
        repo.close()
    return out


@pytest.mark.parametrize("seed", [1, 7])
@pytest.mark.parametrize("order", ["serve-first", "host-first"])
def test_twin_reads_bit_identical(seed, order, monkeypatch):
    first, second = ("1", "0") if order == "serve-first" else ("0", "1")
    a = run_script(seed, first, monkeypatch)
    b = run_script(seed, second, monkeypatch)
    assert a == b


def test_twin_interleaved_invalidation(monkeypatch):
    """Tight edit->read->edit->read alternation: every read observes
    exactly the post-edit state under both modes (the clock-driven
    invalidation can never serve a stale resident entry)."""

    def run(serve):
        monkeypatch.setenv("HM_SERVE", serve)
        repo = Repo(memory=True)
        try:
            url = repo.create()
            repo.change(url, lambda d: d.__setitem__("t", Text("")))
            vals = []
            for i in range(12):
                repo.change(
                    url, lambda d, i=i: d["t"].insert(len(d["t"]), str(i))
                )
                vals.append(repo.read(url, {"kind": "text", "path": ["t"]}))
            return vals
        finally:
            repo.close()

    served, host = run("1"), run("0")
    assert served == host
    assert served[-1] == "".join(str(i) for i in range(12))
