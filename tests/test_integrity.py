"""Feed integrity: signed merkle logs, replication-boundary verification,
on-disk tamper detection (VERDICT r3 missing #1 — the trust model).
Reference anchor: hypercore's signed tree + per-block verification
(src/types/hypercore.d.ts:132-188)."""

import base64
import os
import time

import pytest

from hypermerge_tpu.net.duplex import duplex_pair
from hypermerge_tpu.net.connection import PeerConnection
from hypermerge_tpu.net.peer import NetworkPeer
from hypermerge_tpu.net.replication import ReplicationManager
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.storage.feed import FeedStore, memory_storage_fn
from hypermerge_tpu.storage.integrity import Peaks, signable
from hypermerge_tpu.utils import crypto
from hypermerge_tpu.utils import keys as keymod

from helpers import wait_until


class TestMerklePeaks:
    def test_incremental_root_matches_bulk(self):
        """Writer's O(log n) peak root == bulk recompute at EVERY length."""
        peaks = Peaks()
        leaves = []
        for i in range(40):
            leaf = crypto.leaf_hash(f"block{i}".encode())
            leaves.append(leaf)
            peaks.append(leaf)
            assert peaks.root() == crypto.merkle_root(leaves), i

    def test_empty_root(self):
        assert Peaks().root() == b"\x00" * 32 == crypto.merkle_root([])


def _mgr():
    feeds = FeedStore(memory_storage_fn)
    events = []
    mgr = ReplicationManager(feeds, lambda pk, peer: events.append(pk))
    return feeds, mgr, events


def _connect(mgr_a, mgr_b):
    da, db = duplex_pair()
    ca, cb = PeerConnection(da, True), PeerConnection(db, False)
    pa = NetworkPeer("B", "A", lambda p: None)
    pb = NetworkPeer("A", "B", lambda p: None)
    pa.add_connection(ca)
    pb.add_connection(cb)
    mgr_a.on_peer(pa)
    mgr_b.on_peer(pb)
    return pa, pb


class TestWriterSigning:
    def test_writer_appends_sign_and_audit(self):
        feeds = FeedStore(memory_storage_fn)
        f = feeds.create(keymod.create())
        for i in range(5):
            f.append(f"block{i}".encode())
        # live appends sign lazily; audit seals the head first
        assert f.audit()
        assert f.integrity.signed_length == 5

    def test_lazy_signing_seals_on_close(self, tmp_path):
        """Appends below the sign interval leave no per-append records;
        close() persists one covering the head, and a fresh process
        audits clean (the crash-recovery contract of lazy signing)."""
        from hypermerge_tpu.storage.feed import FeedStore, file_storage_fn
        from hypermerge_tpu.storage.integrity import file_sig_storage_fn

        root = str(tmp_path)
        feeds = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        pair = keymod.create()
        f = feeds.create(pair)
        for i in range(5):
            f.append(f"block{i}".encode())
        assert f.integrity.unsigned_tail
        feeds.close()
        feeds2 = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        f2 = feeds2.create(pair)
        assert f2.integrity.signed_length == 5
        assert f2.audit()
        feeds2.close()

    def test_crash_orphaned_unsigned_tail_distinct_status(self, tmp_path):
        """Lazy signing + crash: a WRITABLE feed reopened with blocks
        beyond its last signed record must report the distinct
        "unsigned_tail" status (recoverable via seal()), not the
        tamper-indistinguishable False/"tampered" — while audit()'s
        strict boolean contract stays False until sealed."""
        from hypermerge_tpu.storage.feed import FeedStore, file_storage_fn
        from hypermerge_tpu.storage.integrity import (
            AUDIT_OK,
            AUDIT_TAMPERED,
            AUDIT_UNSIGNED_TAIL,
            file_sig_storage_fn,
        )

        root = str(tmp_path)
        feeds = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        pair = keymod.create()
        f = feeds.create(pair)
        for i in range(5):
            f.append(f"block{i}".encode())
        f.integrity.record_for(f, 3)  # signed record below the head
        # crash: the process never seals — reopen straight from disk
        feeds2 = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        f2 = feeds2.create(pair)
        assert f2.integrity.signed_length == 3 and f2.length == 5
        assert f2.audit_status() == AUDIT_UNSIGNED_TAIL
        assert f2.audit() is False  # strict boolean stays strict
        # recovery path: seal() signs a fresh head record
        f2.seal()
        assert f2.audit_status() == AUDIT_OK
        assert f2.audit() is True
        feeds2.close()

        # a READ-ONLY holder of the same shape cannot distinguish the
        # tail from a foreign append: must stay "tampered"
        root2 = str(tmp_path / "ro")
        feeds3 = FeedStore(
            file_storage_fn(root2), sig_fn=file_sig_storage_fn(root2)
        )
        g = feeds3.create(pair)
        for i in range(4):
            g.append(f"ro{i}".encode())
        g.integrity.record_for(g, 2)
        feeds4 = FeedStore(
            file_storage_fn(root2), sig_fn=file_sig_storage_fn(root2)
        )
        g2 = feeds4.open_feed(pair.public_key)
        assert not g2.writable
        assert g2.audit_status() == AUDIT_TAMPERED
        assert g2.audit() is False
        feeds4.close()

    def test_unsigned_tail_with_no_records_at_all(self, tmp_path):
        """A writable feed that crashed before its FIRST record is the
        same recoverable shape (whole log is the unsigned tail)."""
        from hypermerge_tpu.storage.feed import FeedStore, file_storage_fn
        from hypermerge_tpu.storage.integrity import (
            AUDIT_OK,
            AUDIT_UNSIGNED_TAIL,
            file_sig_storage_fn,
        )

        root = str(tmp_path)
        feeds = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        pair = keymod.create()
        f = feeds.create(pair)
        f.append(b"only-block")
        feeds2 = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        f2 = feeds2.create(pair)
        assert f2.integrity.signed_length == 0 and f2.length == 1
        assert f2.audit_status() == AUDIT_UNSIGNED_TAIL
        f2.seal()
        assert f2.audit_status() == AUDIT_OK
        feeds2.close()

    def test_on_disk_block_tamper_detected(self, tmp_path):
        repo = Repo(path=str(tmp_path))
        url = repo.create({"x": 1})
        repo.change(url, lambda d: d.__setitem__("y", 2))
        repo.close()

        # find the doc's block log and flip one byte
        feeds = os.path.join(str(tmp_path), "feeds")
        victim = None
        for root, _dirs, files in os.walk(feeds):
            for name in files:
                if "." not in name:
                    victim = os.path.join(root, name)
        assert victim
        data = bytearray(open(victim, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(data))

        repo2 = Repo(path=str(tmp_path))
        doc_id = os.path.basename(victim)
        feed = repo2.back.feeds.open_feed(doc_id)
        assert feed.audit() is False
        repo2.close()

    def test_on_disk_sig_tamper_detected(self, tmp_path):
        repo = Repo(path=str(tmp_path))
        url = repo.create({"x": 1})
        repo.close()
        feeds = os.path.join(str(tmp_path), "feeds")
        victim = None
        for root, _dirs, files in os.walk(feeds):
            for name in files:
                if name.endswith(".sig"):
                    victim = os.path.join(root, name)
        assert victim
        data = bytearray(open(victim, "rb").read())
        data[-1] ^= 0xFF  # corrupt the newest signature
        open(victim, "wb").write(bytes(data))

        repo2 = Repo(path=str(tmp_path))
        feed = repo2.back.feeds.open_feed(
            os.path.basename(victim)[: -len(".sig")]
        )
        assert feed.audit() is False
        repo2.close()

    def test_untampered_disk_audits_clean(self, tmp_path):
        repo = Repo(path=str(tmp_path))
        url = repo.create({"x": 1})
        repo.change(url, lambda d: d.__setitem__("y", 2))
        from hypermerge_tpu.utils.ids import validate_doc_url

        doc_id = validate_doc_url(url)
        repo.close()
        repo2 = Repo(path=str(tmp_path))
        assert repo2.back.feeds.open_feed(doc_id).audit()
        repo2.close()


class TestLazySigningAudit:
    def _file_feeds(self, root):
        from hypermerge_tpu.storage.feed import FeedStore, file_storage_fn
        from hypermerge_tpu.storage.integrity import file_sig_storage_fn

        return FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )

    def test_foreign_tail_block_fails_audit_not_laundered(self, tmp_path):
        """A block appended to the on-disk log beyond the signed chain
        (crash leftovers or attacker) must FAIL the audit on reopen —
        never be sealed into validity by the writer's own key."""
        import struct

        root = str(tmp_path)
        feeds = self._file_feeds(root)
        pair = keymod.create()
        f = feeds.create(pair)
        for i in range(3):
            f.append(b"block%d" % i)
        feeds.close()  # seals at length 3

        log_path = os.path.join(
            root, pair.public_key[:2], pair.public_key
        )
        forged = b"forged!"
        with open(log_path, "ab") as fh:
            fh.write(struct.pack("<I", len(forged)) + forged)
        # .len sidecar now mismatches -> storage rescans and sees 4
        os.remove(log_path + ".len")

        feeds2 = self._file_feeds(root)
        f2 = feeds2.create(pair)  # writable: the dangerous case
        assert f2.length == 4
        assert f2.audit() is False, "foreign tail must not be sealed"
        # and the chain on disk still stops at 3
        assert f2.integrity.signed_length == 3
        feeds2.close()

    def test_in_process_tail_still_audits_clean(self):
        feeds = FeedStore(memory_storage_fn)
        f = feeds.create(keymod.create())
        f.append(b"one")
        f.append(b"two")
        assert f.audit()  # in-process unsigned tail: sealed + verified


class TestSignChain:
    def test_sign_chain_matches_live_writer_records(self, tmp_path):
        """integrity.sign_chain (dense corpus format) and the live
        writer agree on every boundary: a sealed live feed's head record
        equals sign_chain's last record byte-for-byte, and record_for
        reproduces ANY intermediate record of the dense chain."""
        from hypermerge_tpu.storage.feed import FeedStore, file_storage_fn
        from hypermerge_tpu.storage.integrity import (
            _REC,
            file_sig_storage_fn,
            sign_chain,
        )

        root = str(tmp_path)
        feeds = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        pair = keymod.create()
        f = feeds.create(pair)
        blocks = [f"block{i}".encode() for i in range(7)]
        for b in blocks:
            f.append(b)
        f.seal()
        sig_path = os.path.join(
            root, pair.public_key[:2], pair.public_key + ".sig"
        )
        on_disk = open(sig_path, "rb").read()
        dense = sign_chain(blocks, keymod.decode(pair.secret_key))
        assert on_disk == dense[-_REC.size:]  # head record identical
        # every intermediate boundary the dense chain stores is
        # reproducible on demand by the live writer
        for i in range(7):
            want = _REC.unpack_from(dense, i * _REC.size)
            got = f.integrity.record_for(f, i + 1)
            assert got == want, i


class TestReplicationVerification:
    def test_signed_replication_end_to_end(self):
        feeds_a, mgr_a, _ = _mgr()
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        for i in range(5):
            fa.append(f"b{i}".encode())
        fb = feeds_b.open_feed(pair.public_key)
        _connect(mgr_a, mgr_b)
        assert fb.read_all() == fa.read_all()
        # the replica stored verified records it can audit and re-serve
        assert fb.audit()
        # live tail stays verified (batched flush: asynchronous)
        fa.append(b"live")
        wait_until(lambda: fb.length == 6)
        assert fb.read_all()[-1] == b"live"
        assert fb.audit()

    def test_tampered_block_rejected(self):
        """A forged Blocks message (valid-looking bytes, bad signature)
        must be dropped BEFORE storage."""
        feeds_a, mgr_a, _ = _mgr()
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        fa.append(b"real")
        fb = feeds_b.open_feed(pair.public_key)
        pa, pb = _connect(mgr_a, mgr_b)
        assert fb.read_all() == [b"real"]

        # attacker crafts an extension with its OWN key's signature
        evil = keymod.create()
        evil_seed = keymod.decode(evil.secret_key)
        leaves = [crypto.leaf_hash(b"real"), crypto.leaf_hash(b"evil")]
        root = crypto.merkle_root(leaves)
        sig = crypto.sign(signable(2, root), evil_seed)
        mgr_b._on_blocks(
            pb,
            fa.discovery_id,
            1,
            [base64.b64encode(b"evil").decode()],
            2,
            base64.b64encode(sig).decode(),
            2,
        )
        assert fb.read_all() == [b"real"]  # nothing stored

        # altered payload under the real writer's signature also fails
        rec = fa.integrity.latest()
        mgr_b._on_blocks(
            pb,
            fa.discovery_id,
            1,
            [base64.b64encode(b"evil").decode()],
            2,
            base64.b64encode(rec[2]).decode(),
            2,
        )
        assert fb.read_all() == [b"real"]

    def test_discovery_id_alone_cannot_fetch_blocks(self):
        """Capability verification (hypercore-protocol parity): a peer
        that learned a feed's discovery id from announcements but does
        NOT know the feed public key gets no data — its Requests carry
        no valid key-derived capability."""
        feeds_a, mgr_a, _ = _mgr()
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        fa.append(b"secret-block")
        pa, pb = _connect(mgr_a, mgr_b)  # b shares NO feeds with a

        # attacker on b's side: craft Requests with the announced did;
        # spy on everything b's manager receives back
        got = []
        orig = mgr_b._on_message
        mgr_b._on_message = lambda peer, msg: (
            got.append(msg), orig(peer, msg)
        )
        ch = pb.connection.open_channel("Replication")
        did = fa.discovery_id
        ch.send({"type": "Request", "id": did, "from": 0, "cap": "bogus"})
        ch.send({"type": "Request", "id": did, "from": 0})
        assert not any(
            m.get("type") == "Blocks" for m in got if isinstance(m, dict)
        ), got

        # whereas a peer proving the capability (key + A's challenge)
        # does get data
        from hypermerge_tpu.storage.integrity import capability

        challenge = mgr_a._challenge_local[pa]
        ch.send({
            "type": "Request", "id": did, "from": 0,
            # B proves from the server side of the a<->b duplex pair
            "cap": capability(pair.public_key, challenge, b"", False),
        })
        assert any(
            m.get("type") == "Blocks" for m in got if isinstance(m, dict)
        ), got

    def test_capability_not_replayable_across_connections(self):
        """A cap captured on one connection is useless on another: proofs
        bind to the verifier's per-connection random challenge — an
        impersonator armed with a stolen proof still gets nothing."""
        from hypermerge_tpu.storage.integrity import capability

        feeds_a, mgr_a, _ = _mgr()
        feeds_b, mgr_b, _ = _mgr()
        feeds_c, mgr_c, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        fa.append(b"data")
        fb = feeds_b.open_feed(pair.public_key)
        pa, _pb = _connect(mgr_a, mgr_b)
        assert fb.read_all() == [b"data"]  # legit sync worked

        # the cap B proved with on the a<->b connection (bound to the
        # challenge A issued there)
        stale_cap = capability(
            pair.public_key, mgr_a._challenge_local[pa], b"", False
        )
        # attacker C (knows only the discovery id) replays it on a<->c
        _pca, pcc = _connect(mgr_a, mgr_c)
        got = []
        orig = mgr_c._on_message
        mgr_c._on_message = lambda peer, msg: (
            got.append(msg), orig(peer, msg)
        )
        ch = pcc.connection.open_channel("Replication")
        ch.send({
            "type": "Request", "id": fa.discovery_id, "from": 0,
            "cap": stale_cap,
        })
        assert not any(
            m.get("type") == "Blocks" for m in got if isinstance(m, dict)
        ), got

    def test_capability_not_mintable_by_challenge_reflection(self):
        """ADVICE r4 high: an attacker knowing only the discovery id
        sets ITS challenge equal to the one we issued it, then replays
        the proactive proof from our concealed FeedLength as its own.
        The proof MACs the PROVER's transport role, so the mirrored
        value never verifies and blocks stay withheld."""
        feeds_a, mgr_a, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        fa.append(b"secret-block")

        # raw attacker endpoint: a bare PeerConnection, no manager
        da, db = duplex_pair()
        ca, cb = PeerConnection(da, True), PeerConnection(db, False)
        pa = NetworkPeer("X", "A", lambda p: None)
        pa.add_connection(ca)
        mgr_a.on_peer(pa)

        got = []
        cb.open_channel("Replication").subscribe(got.append)
        # A's opener carries the challenge A wants proofs against
        for _ in range(100):
            if got:
                break
            time.sleep(0.01)
        opener = got[0]
        assert opener["type"] == "DiscoveryIds"
        a_challenge = opener["challenge"]

        # reflect: announce the did with challenge := A's own challenge
        cb.open_channel("Replication").send({
            "type": "DiscoveryIds",
            "ids": [fa.discovery_id],
            "challenge": a_challenge,
        })
        # A proactively sends its concealed FeedLength whose cap is
        # capability(pk, a_challenge, binding, A's role)
        for _ in range(100):
            if any(m.get("type") == "FeedLength" for m in got[1:]):
                break
            time.sleep(0.01)
        fl = next(m for m in got[1:] if m.get("type") == "FeedLength")
        assert fl["length"] == 0  # concealed from the unproven peer

        # mirror the cap straight back as our "proof"
        cb.open_channel("Replication").send({
            "type": "Request", "id": fa.discovery_id, "from": 0,
            "cap": fl["cap"],
        })
        time.sleep(0.2)
        assert not any(
            m.get("type") == "Blocks" for m in got if isinstance(m, dict)
        ), got

    def test_unsigned_blocks_dropped_by_default(self):
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fb = feeds_b.open_feed(pair.public_key)
        pa = object.__new__(NetworkPeer)
        pa.id = "X"
        mgr_b._on_blocks(
            pa, fb.discovery_id, 0,
            [base64.b64encode(b"nosig").decode()], -1, None, 1,
        )
        assert fb.read_all() == []

    def test_unsigned_blocks_accepted_with_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("HM_ALLOW_UNSIGNED_FEEDS", "1")
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fb = feeds_b.open_feed(pair.public_key)
        pa = object.__new__(NetworkPeer)
        pa.id = "X"
        mgr_b._on_blocks(
            pa, fb.discovery_id, 0,
            [base64.b64encode(b"nosig").decode()], -1, None, 1,
        )
        assert fb.read_all() == [b"nosig"]

    def test_byte_bounded_chunks_converge(self, monkeypatch):
        """Large blocks shrink the chunk so frames stay bounded in bytes,
        not just block count (a 64KB-block feed must never produce a
        frame past the transport cap)."""
        monkeypatch.setenv("HM_REPL_CHUNK_BYTES", "2500")
        feeds_a, mgr_a, _ = _mgr()
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        for i in range(10):
            fa.append(bytes([i]) * 1000)  # 1KB blocks
        fb = feeds_b.open_feed(pair.public_key)
        sent_sizes = []
        orig = mgr_a._blocks_msg

        def spy(feed, did, start, end):
            sent_sizes.append(end - start)
            return orig(feed, did, start, end)

        mgr_a._blocks_msg = spy
        _connect(mgr_a, mgr_b)
        assert fb.read_all() == fa.read_all()
        assert sent_sizes and max(sent_sizes) <= 2

    def test_chunked_backfill_converges(self, monkeypatch):
        """A 30-block feed replicates in 7-block ack-paced chunks (no
        whole-feed frame; VERDICT r3 missing #6)."""
        monkeypatch.setenv("HM_REPL_CHUNK", "7")
        feeds_a, mgr_a, _ = _mgr()
        feeds_b, mgr_b, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        for i in range(30):
            fa.append(f"blk{i:02d}".encode())
        fb = feeds_b.open_feed(pair.public_key)
        _connect(mgr_a, mgr_b)
        assert fb.read_all() == fa.read_all()
        assert fb.audit()


class TestTamperFuzz:
    def test_random_on_disk_tampering_always_detected(self, tmp_path):
        """Flip random bytes anywhere in a feed's block log or signature
        records: audit() must never report clean."""
        import random

        from hypermerge_tpu.storage.feed import (
            FeedStore,
            file_storage_fn,
        )
        from hypermerge_tpu.storage.integrity import file_sig_storage_fn

        rng = random.Random(7)
        root = str(tmp_path)
        feeds = FeedStore(
            file_storage_fn(root), sig_fn=file_sig_storage_fn(root)
        )
        pair = keymod.create()
        f = feeds.create(pair)
        for i in range(12):
            f.append(rng.randbytes(rng.randint(5, 200)))
        assert f.audit()
        feeds.close()

        pk = pair.public_key
        block_path = os.path.join(root, pk[:2], pk)
        sig_path = block_path + ".sig"
        for trial in range(16):
            victim = block_path if trial % 2 == 0 else sig_path
            orig = open(victim, "rb").read()
            data = bytearray(orig)
            pos = rng.randrange(len(data))
            data[pos] ^= 1 << rng.randrange(8)
            open(victim, "wb").write(bytes(data))
            try:
                fresh = FeedStore(
                    file_storage_fn(root),
                    sig_fn=file_sig_storage_fn(root),
                )
                feed = fresh.open_feed(pk)
                assert feed.audit() is False, (
                    f"trial {trial}: flipped bit {pos} in "
                    f"{os.path.basename(victim)} went undetected"
                )
                fresh.close()
            finally:
                open(victim, "wb").write(orig)

    def test_random_wire_tampering_never_stored(self):
        """Fuzz the verified-append boundary: random corruptions of a
        valid (blocks, length, sig) extension never persist."""
        import random

        rng = random.Random(11)
        feeds_a, _mgr_a, _ = _mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        blocks = [rng.randbytes(rng.randint(10, 80)) for _ in range(6)]
        for b in blocks:
            fa.append(b)
        fa.seal()  # lazy signing: pin a head record to tamper against
        rec = fa.integrity.latest()

        for trial in range(24):
            feeds_b, _mgr_b, _ = _mgr()
            fb = feeds_b.open_feed(pair.public_key)
            send = [bytearray(b) for b in blocks]
            sig = bytearray(rec[2])
            length = rec[0]
            kind = trial % 3
            if kind == 0:  # corrupt one block
                tgt = send[rng.randrange(len(send))]
                tgt[rng.randrange(len(tgt))] ^= 0xFF
            elif kind == 1:  # corrupt the signature
                sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            else:  # lie about the length
                length = rng.randint(1, 5)
            ok = fb.append_verified(
                0, [bytes(b) for b in send], length, bytes(sig)
            )
            assert not ok, f"trial {trial} accepted tampering"
            assert fb.read_all() == [], (
                f"trial {trial}: tampered data persisted"
            )


class TestProgressEvents:
    def test_download_progress_fires_during_sync(self):
        """subscribe_progress callbacks fire while a doc replicates in
        (VERDICT r3 weak #3: the Download pipeline was dead code)."""
        from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm

        hub = LoopbackHub()
        ra, rb = Repo(memory=True), Repo(memory=True)
        ra.set_swarm(LoopbackSwarm(hub))
        rb.set_swarm(LoopbackSwarm(hub))
        url = ra.create({"n": 0})
        events = []
        h = rb.open(url)
        h.subscribe_progress(lambda *a: events.append(a))
        for i in range(5):
            ra.change(url, lambda d: d.__setitem__("n", i))
        wait_until(lambda: rb.doc(url).get("n") == 4)
        assert events, "no Download progress events during sync"
        ra.close()
        rb.close()


class TestProofServer:
    """Satellites: the lock-order fix in the leaf cache and the cached
    proof-level forest (O(range x log n) serving)."""

    def _feed(self, n_blocks=64):
        feeds = FeedStore(memory_storage_fn)
        feed = feeds.create(keymod.create())
        for i in range(n_blocks):
            feed.append(b"blk%d" % i)
        feed.seal()
        return feed

    def test_range_proofs_never_hold_integrity_lock_into_feed(self):
        """Lock-order regression: serving a range with a STALE leaf
        cache must snapshot blocks via the feed lock WITHOUT holding
        the integrity lock (feed -> integrity is the documented order;
        the old code inverted it here)."""
        feed = self._feed(32)
        from hypermerge_tpu.storage.integrity import (
            FeedIntegrity,
            MemorySigStorage,
        )

        # fresh integrity instance over the same records: leaf cache
        # is empty (stale), so range_proofs must rebuild it
        store = MemorySigStorage()
        for rec in feed.integrity.records():
            store.append(*rec)
        integ = FeedIntegrity(store, feed.public_key)
        orig = feed.get_batch
        violations = []

        def checked_get_batch(s, e):
            if integ._lock._is_owned():
                violations.append((s, e))
            return orig(s, e)

        feed.get_batch = checked_get_batch
        try:
            served = integ.range_proofs(feed, 10, 14)
        finally:
            feed.get_batch = orig
        assert served is not None
        assert not violations, (
            "feed.get_batch called while holding the integrity lock "
            f"(deadlock-prone inversion): {violations}"
        )

    def test_stale_leaf_cache_concurrent_with_append_no_deadlock(self):
        """The concrete interleaving the inversion deadlocked on: a
        prover paused inside its block snapshot while a writer appends
        (feed lock -> integrity lock). Exercised under a timeout."""
        import threading

        feeds = FeedStore(memory_storage_fn)
        feed = feeds.create(keymod.create())
        for i in range(8):
            feed.append(b"blk%d" % i)
        feed.seal()
        from hypermerge_tpu.storage.integrity import (
            FeedIntegrity,
            MemorySigStorage,
        )

        store = MemorySigStorage()
        for rec in feed.integrity.records():
            store.append(*rec)
        integ = FeedIntegrity(store, feed.public_key)  # stale leaves
        orig = feed.get_batch
        in_snapshot = threading.Event()
        release = threading.Event()

        def gated_get_batch(s, e):
            if threading.current_thread().name == "prover":
                in_snapshot.set()
                release.wait(5)
            return orig(s, e)

        feed.get_batch = gated_get_batch
        served = []

        def prove():
            served.append(integ.range_proofs(feed, 0, 4))

        prover = threading.Thread(target=prove, name="prover", daemon=True)
        appender = threading.Thread(
            target=lambda: feed.append(b"late"), daemon=True
        )
        try:
            prover.start()
            assert in_snapshot.wait(5), "prover never reached its snapshot"
            appender.start()  # feed lock -> integrity lock
            appender.join(3)
            dead = appender.is_alive()
            release.set()
            prover.join(5)
            appender.join(5)
            assert not dead, (
                "append deadlocked against a proof server holding the "
                "integrity lock across its block snapshot"
            )
            assert not prover.is_alive() and not appender.is_alive()
            assert served and served[0] is not None
        finally:
            release.set()
            feed.get_batch = orig

    def test_repeated_range_proofs_hash_count_bounded(self, monkeypatch):
        """Proof-level cache: the first RequestRange against a record
        pays the one O(n) level build; EVERY later range against the
        same record is pure lookup — zero parent hashes. (The pre-cache
        server rebuilt all levels per request: O(range x n).)"""
        from hypermerge_tpu.storage import integrity as integ_mod

        feed = self._feed(128)
        length = feed.length
        calls = [0]
        orig_parent = integ_mod._parent

        def counting_parent(left, right):
            calls[0] += 1
            return orig_parent(left, right)

        monkeypatch.setattr(integ_mod, "_parent", counting_parent)
        integ = feed.integrity
        integ._proof_cache.clear()
        served = integ.range_proofs(feed, 0, 8)
        assert served is not None
        first_build = calls[0]
        assert first_build <= 2 * length, "level build must be O(n)"
        calls[0] = 0
        for start in (8, 40, 100, 0):
            served = integ.range_proofs(feed, start, start + 8)
            assert served is not None
        assert calls[0] == 0, (
            f"repeat ranges re-hashed {calls[0]} parents; expected the "
            "cached forest to serve them hash-free"
        )
        # and the proofs still verify
        from hypermerge_tpu.storage.integrity import verify_inclusion

        length2, sig, pairs = served
        ok = verify_inclusion(
            feed.public_key,
            crypto.leaf_hash(pairs[0][0]),
            0,
            length2,
            pairs[0][1],
            sig,
        )
        assert ok
