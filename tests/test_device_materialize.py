"""Device batched materialization == host OpSet, for arbitrary histories.

This is the core correctness contract of the framework (SURVEY.md §7.3.6:
determinism across backends — both paths must produce identical state from
the same feeds)."""

import random

import numpy as np
import pytest

from hypermerge_tpu.crdt.frontend_state import FrontendDoc
from hypermerge_tpu.crdt.opset import OpSet
from hypermerge_tpu.models import Counter, Text
from hypermerge_tpu.ops import columnar
from hypermerge_tpu.ops.materialize import (
    decode_columnar,
    decode_patch,
    materialize_batch,
    materialize_docs,
    text_join,
)

from helpers import Site, plainify, random_mutation, sync


def device_docs(*histories):
    dec = materialize_batch([list(h) for h in histories])
    return dec, materialize_docs(dec)


def test_single_doc_map():
    s = Site("alice")
    s.change(lambda d: d.__setitem__("x", 1))
    s.change(lambda d: d.__setitem__("y", "hello"))
    s.change(lambda d: d.__delitem__("x"))
    dec, docs = device_docs(s.opset.history)
    assert plainify(docs[0]) == plainify(s.opset.materialize())
    assert dec.clock_dict(0) == s.opset.clock


def test_nested_and_lists():
    s = Site("alice")
    s.change(
        lambda d: d.__setitem__(
            "cfg", {"deep": {"list": [1, 2, 3]}, "t": Text("hey")}
        )
    )
    s.change(lambda d: d["cfg"]["deep"]["list"].insert(1, 99))
    s.change(lambda d: d["cfg"]["deep"]["list"].__delitem__(0))
    s.change(lambda d: d["cfg"]["t"].insert(3, "!"))
    _, docs = device_docs(s.opset.history)
    assert plainify(docs[0]) == plainify(s.opset.materialize())


def test_concurrent_conflicts_match_host():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("x", 0))
    b.receive(a.opset.history)
    a.change(lambda d: d.__setitem__("x", "A"))
    b.change(lambda d: d.__setitem__("x", "B"))
    sync(a, b)
    dec, docs = device_docs(a.opset.history)
    assert plainify(docs[0]) == plainify(a.opset.materialize())
    # conflicts survive the device path identically to the host snapshot
    host_patch = a.opset.snapshot_patch()
    dev_patch = decode_patch(dec, 0)
    host_x = [d for d in host_patch.diffs if d.key == "x"][0]
    dev_x = [d for d in dev_patch.diffs if d.key == "x"][0]
    assert host_x.value == dev_x.value
    assert [c.op_id for c in host_x.conflicts] == [
        c.op_id for c in dev_x.conflicts
    ]


def test_counters_and_incs():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("n", Counter(10)))
    b.receive(a.opset.history)
    a.change(lambda d: d.increment("n", 5))
    b.change(lambda d: d.increment("n", 7))
    sync(a, b)
    _, docs = device_docs(a.opset.history)
    assert plainify(docs[0]) == plainify(a.opset.materialize())
    assert int(docs[0]["n"]) == 22


def test_rga_concurrent_inserts_match_host():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("l", ["x"]))
    b.receive(a.opset.history)
    for i in range(4):
        a.change(lambda d: d["l"].insert(1, f"a{i}"))
        b.change(lambda d: d["l"].insert(1, f"b{i}"))
    sync(a, b)
    assert plainify(a.doc) == plainify(b.doc)
    _, docs = device_docs(a.opset.history)
    assert plainify(docs[0]) == plainify(a.opset.materialize())


def test_batch_many_docs():
    sites = []
    for i in range(7):
        s = Site(f"actor{i}")
        s.change(lambda d: d.__setitem__("id", i))
        s.change(lambda d: d.__setitem__("l", list(range(i))))
        sites.append(s)
    dec, docs = device_docs(*[s.opset.history for s in sites])
    for s, doc in zip(sites, docs):
        assert plainify(doc) == plainify(s.opset.materialize())
    cols = decode_columnar(dec)
    assert cols["clock"].shape[0] == 7


def test_device_summary_equals_host_decode():
    # summarize_columnar (fused on-device summary, bit-packed transfer)
    # must agree exactly with decode_columnar (host numpy reference)
    from hypermerge_tpu.ops.materialize import summarize_columnar

    rng = random.Random(7)
    sites = [Site(f"s{i}") for i in range(5)]
    for _ in range(60):
        random_mutation(rng.choice(sites), rng)
    for i in range(len(sites) - 1):
        sync(sites[i], sites[i + 1])
    histories = [list(s.opset.history) for s in sites]
    batch = columnar.pack_docs(histories)
    dec = materialize_batch(histories)
    host = decode_columnar(dec)
    dev = summarize_columnar(batch)
    for k in host:
        np.testing.assert_array_equal(
            np.asarray(host[k]), np.asarray(dev[k]), err_msg=k
        )


def test_text_join_fast_path():
    s = Site("alice")
    s.change(lambda d: d.__setitem__("t", Text("hello")))
    s.change(lambda d: d["t"].insert(5, " world"))
    s.change(lambda d: d["t"].delete(0, 1))
    dec, _ = device_docs(s.opset.history)
    # find the text object's row: the MAKE_TEXT op
    act = dec.cols["action"][0]
    row = int(np.nonzero(act == 2)[0][0])
    assert text_join(dec, 0, row) == "ello world"
    assert str(s.opset.materialize()["t"]) == "ello world"


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_fuzz_device_equals_host(seed):
    r = random.Random(seed)
    actors = ["alice", "bob", "carol"]
    sites = [Site(a) for a in actors]
    for _ in range(5):
        for s in sites:
            for _ in range(r.randint(1, 3)):
                random_mutation(s, r)
        if r.random() < 0.6:
            donor, receiver = r.sample(sites, 2)
            receiver.receive(list(donor.opset.history))
    sync(*sites)
    assert plainify(sites[0].doc) == plainify(sites[1].doc)
    _, docs = device_docs(sites[0].opset.history)
    assert plainify(docs[0]) == plainify(sites[0].opset.materialize())


def test_causal_sort_is_valid_linear_extension():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("x", 1))
    b.receive(a.opset.history)
    b.change(lambda d: d.__setitem__("y", 2))
    a.receive(b.opset.history)
    a.change(lambda d: d.__setitem__("z", 3))
    shuffled = list(a.opset.history)
    random.Random(0).shuffle(shuffled)
    ordered = columnar.causal_sort(shuffled)
    seen_clock = {}
    for c in ordered:
        for dep_actor, dep_seq in c.deps.items():
            assert seen_clock.get(dep_actor, 0) >= dep_seq
        assert seen_clock.get(c.actor, 0) == c.seq - 1
        seen_clock[c.actor] = c.seq


def test_pack_roundtrip_values():
    s = Site("alice")
    s.change(
        lambda d: (
            d.__setitem__("i", 42),
            d.__setitem__("big", 2**40),
            d.__setitem__("f", 3.14159),
            d.__setitem__("b", True),
            d.__setitem__("none_later", 1),
            d.__setitem__("s", "string"),
        )
    )
    s.change(lambda d: d.__setitem__("none_later", None))
    _, docs = device_docs(s.opset.history)
    assert plainify(docs[0]) == plainify(s.opset.materialize())
    assert docs[0]["big"] == 2**40
    assert docs[0]["f"] == 3.14159
    assert docs[0]["none_later"] is None


def test_host_kernel_matches_device():
    """ops/host_kernel.py is a bit-exact numpy twin of the device kernel
    (the interactive single-doc open path must agree with bulk slabs)."""
    from hypermerge_tpu.ops.crdt_kernels import run_batch
    from hypermerge_tpu.ops.host_kernel import run_batch_host
    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.synth import synth_batch, synth_changes

    histories = [synth_changes(257, seed=s) for s in range(3)]
    for batch in (pack_docs(histories), synth_batch(5, 192)):
        dev = run_batch(batch)
        host = run_batch_host(batch)
        for f in host._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(dev, f)), getattr(host, f), err_msg=f
            )
