"""Host CRDT path: OpSet (backend) + FrontendDoc (frontend) semantics.

Scenario shapes mirror the reference's repo.test.ts suites (create/change/
merge/materialize) plus property-style convergence fuzzing the reference
lacks (SURVEY.md §4 gaps)."""

import random

import pytest

from hypermerge_tpu.crdt.change import Change
from hypermerge_tpu.crdt.frontend_state import FrontendDoc
from hypermerge_tpu.models import Counter, Table, Text

from helpers import Site, plainify as _plainify, sync


def test_map_set_and_preview():
    s = Site("alice")
    change, preview = s.change(lambda d: d.__setitem__("title", "hello"))
    assert preview == {"title": "hello"}
    assert s.doc == {"title": "hello"}
    assert change.seq == 1 and len(change.ops) == 1
    s.assert_consistent()


def test_nested_deep_assign():
    s = Site("alice")

    def init(d):
        d["config"] = {"theme": {"color": "red"}, "tags": ["a", "b"]}

    s.change(init)
    assert s.doc == {"config": {"theme": {"color": "red"}, "tags": ["a", "b"]}}
    s.assert_consistent()

    def update(d):
        d["config"]["theme"]["color"] = "blue"
        d["config"]["tags"].append("c")

    s.change(update)
    assert s.doc["config"]["theme"]["color"] == "blue"
    assert s.doc["config"]["tags"] == ["a", "b", "c"]
    s.assert_consistent()


def test_delete_key():
    s = Site("alice")
    s.change(lambda d: d.__setitem__("x", 1))
    s.change(lambda d: d.__delitem__("x"))
    assert s.doc == {}
    s.assert_consistent()


def test_lww_concurrent_set_conflict():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("x", 0))
    b.receive(a.opset.history)
    a.change(lambda d: d.__setitem__("x", "from-a"))
    b.change(lambda d: d.__setitem__("x", "from-b"))
    sync(a, b)
    # same winner everywhere: max OpId -> same ctr, 'bob' > 'alice'
    assert a.doc == b.doc == {"x": "from-b"}
    a.assert_consistent()
    b.assert_consistent()
    # the loser surfaces as a conflict on the root cell
    root = a.front.objs["0@_root"]
    assert len(root.data["x"].conflicts) == 1
    assert root.data["x"].conflicts[0].value == "from-a"


def test_concurrent_set_vs_delete_preserves_set():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("x", 0))
    b.receive(a.opset.history)
    a.change(lambda d: d.__delitem__("x"))  # deletes only what alice saw
    b.change(lambda d: d.__setitem__("x", 9))  # concurrent new value
    sync(a, b)
    assert a.doc == b.doc == {"x": 9}


def test_list_concurrent_inserts_converge():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("l", ["x"]))
    b.receive(a.opset.history)
    a.change(lambda d: d["l"].append("a1"))
    a.change(lambda d: d["l"].append("a2"))
    b.change(lambda d: d["l"].append("b1"))
    b.change(lambda d: d["l"].append("b2"))
    sync(a, b)
    assert a.doc == b.doc
    vals = a.doc["l"]
    assert vals[0] == "x"
    assert sorted(vals[1:]) == ["a1", "a2", "b1", "b2"]
    # each writer's run stays contiguous and ordered (RGA no-interleave for
    # same-position inserts is not guaranteed in general, but relative order
    # within one actor must hold)
    assert vals.index("a1") < vals.index("a2")
    assert vals.index("b1") < vals.index("b2")


def test_list_set_and_delete():
    s = Site("alice")
    s.change(lambda d: d.__setitem__("l", [1, 2, 3]))
    s.change(lambda d: d["l"].__setitem__(1, 20))
    assert s.doc["l"] == [1, 20, 3]
    s.change(lambda d: d["l"].__delitem__(0))
    assert s.doc["l"] == [20, 3]
    s.assert_consistent()


def test_text_editing():
    s = Site("alice")

    def init(d):
        d["t"] = Text("helo")

    s.change(init)
    assert str(s.doc["t"]) == "helo"

    def fix(d):
        d["t"].insert(2, "l")

    s.change(fix)
    assert str(s.doc["t"]) == "hello"

    def shout(d):
        d["t"].delete(0, 1)
        d["t"].insert(0, "H")
        d["t"].insert(5, " world")

    s.change(shout)
    assert str(s.doc["t"]) == "Hello world"
    s.assert_consistent()


def test_concurrent_text_converges():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("t", Text("ac")))
    b.receive(a.opset.history)
    a.change(lambda d: d["t"].insert(1, "b"))  # a: "abc"
    b.change(lambda d: d["t"].insert(2, "d"))  # b: "acd"
    sync(a, b)
    assert str(a.doc["t"]) == str(b.doc["t"]) == "abcd"


def test_counter_concurrent_increments_add():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("n", Counter(10)))
    b.receive(a.opset.history)
    a.change(lambda d: d.increment("n", 5))
    b.change(lambda d: d.increment("n", 7))
    sync(a, b)
    assert int(a.doc["n"]) == int(b.doc["n"]) == 22
    assert isinstance(a.doc["n"], Counter)


def test_counter_set_discards_concurrent_increments():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("n", Counter(10)))
    b.receive(a.opset.history)
    a.change(lambda d: d.__setitem__("n", Counter(100)))  # replace counter
    b.change(lambda d: d.increment("n", 7))  # inc on the old counter op
    sync(a, b)
    assert int(a.doc["n"]) == int(b.doc["n"]) == 100


def test_table_rows():
    s = Site("alice")

    def init(d):
        d["t"] = Table({"r1": {"name": "ada"}})

    s.change(init)

    def add(d):
        d["t"].add("r2", {"name": "bob"})

    s.change(add)
    t = s.doc["t"]
    assert t.count == 2 and t.by_id("r2") == {"name": "bob"}

    def remove(d):
        d["t"].remove("r1")

    s.change(remove)
    assert s.doc["t"].ids == ["r2"]
    s.assert_consistent()


def test_out_of_order_delivery_queues():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("x", 1))
    a.change(lambda d: d.__setitem__("x", 2))
    a.change(lambda d: d.__setitem__("x", 3))
    h = list(a.opset.history)
    b.receive([h[2]])  # future change parks
    assert b.doc == {}
    assert b.opset.missing_deps() == {"alice": 2}
    b.receive([h[0]])
    assert b.doc == {"x": 1}
    b.receive([h[1]])  # unblocks the parked change too
    assert b.doc == {"x": 3}
    assert not b.opset._pending


def test_duplicate_changes_ignored():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("x", 1))
    b.receive(a.opset.history)
    b.receive(a.opset.history)
    assert b.doc == {"x": 1}
    assert len(b.opset.history) == 1


def test_change_serialization_roundtrip():
    s = Site("alice")
    change, _ = s.change(lambda d: d.__setitem__("k", {"deep": [1, Text("ab")]}))
    wire = change.to_json()
    assert Change.from_json(wire) == change


def test_time_travel_materialize_at():
    s = Site("alice")
    s.change(lambda d: d.__setitem__("x", 1))
    s.change(lambda d: d.__setitem__("x", 2))
    s.change(lambda d: d.__delitem__("x"))
    assert s.opset.materialize_at(0) == {}
    assert s.opset.materialize_at(1) == {"x": 1}
    assert s.opset.materialize_at(2) == {"x": 2}
    assert s.opset.materialize_at(3) == {}


def test_snapshot_patch_rebuilds_fresh_frontend():
    s = Site("alice")
    s.change(
        lambda d: d.__setitem__(
            "doc", {"list": [1, {"n": 2}], "txt": Text("hi"), "c": Counter(4)}
        )
    )
    s.change(lambda d: d["doc"].increment("c", 1))
    fresh = FrontendDoc()
    fresh.apply_patch(s.opset.snapshot_patch())
    assert _plainify(fresh.materialize()) == _plainify(s.doc)


def test_three_way_fuzz_convergence(rng):
    actors = ["alice", "bob", "carol"]
    sites = [Site(a) for a in actors]

    def random_mutation(site, r):
        def fn(d):
            choice = r.random()
            if choice < 0.3:
                d[r.choice("abc")] = r.randint(0, 99)
            elif choice < 0.45:
                if "l" not in d:
                    d["l"] = []
                lst = d["l"]
                lst.insert(r.randint(0, len(lst)), r.randint(0, 9))
            elif choice < 0.55:
                if "l" in d and len(d["l"]) > 0:
                    del d["l"][r.randint(0, len(d["l"]) - 1)]
            elif choice < 0.7:
                if "t" not in d:
                    d["t"] = Text("")
                d["t"].insert(r.randint(0, len(d["t"])), r.choice("xyz"))
            elif choice < 0.8:
                if "n" not in d or not isinstance(d.get("n"), Counter):
                    d["n"] = Counter(0)
                else:
                    d.increment("n", r.randint(1, 3))
            elif choice < 0.9:
                k = r.choice("abc")
                if k in d:
                    del d[k]
            else:
                d[r.choice("mn")] = {"v": [r.randint(0, 9)]}

        site.change(fn)

    for round_ in range(6):
        for s in sites:
            for _ in range(rng.randint(1, 4)):
                random_mutation(s, rng)
        if rng.random() < 0.5:  # partial gossip mid-run, shuffled delivery
            donor, receiver = rng.sample(sites, 2)
            h = list(donor.opset.history)
            rng.shuffle(h)
            receiver.receive(h)

    # final full sync with shuffled delivery order
    for receiver in sites:
        combined = [
            c
            for donor in sites
            if donor is not receiver
            for c in donor.opset.history
        ]
        rng.shuffle(combined)
        receiver.receive(combined)

    docs = [_plainify(s.doc) for s in sites]
    assert docs[0] == docs[1] == docs[2]
    for s in sites:
        s.assert_consistent()
        assert not s.opset._pending


def test_concurrent_list_set_vs_delete_resurrects_consistently():
    """A deleted elem resurrected by a concurrent set must reach the
    frontend as an *insert* (it already removed the elem)."""
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("l", ["x", "y", "z"]))
    b.receive(a.opset.history)
    a.change(lambda d: d["l"].__delitem__(1))
    b.change(lambda d: d["l"].__setitem__(1, "Y"))
    sync(a, b)
    assert a.doc["l"] == b.doc["l"] == ["x", "Y", "z"]
    a.assert_consistent()
    b.assert_consistent()


def test_failed_intent_does_not_alias_temp_id():
    from hypermerge_tpu.crdt.change import Action, ChangeRequest, OpIntent

    s = Site("alice")
    s.change(lambda d: d.__setitem__("l", []))
    # handcrafted request: first MAKE targets an out-of-range list index
    # (fails to resolve); second MAKE succeeds; the SET addressed to the
    # FAILED temp id must go nowhere — not into the second object
    list_obj = next(
        str(o) for o, st in s.opset.objects.items() if st.type == "list"
    )
    req = ChangeRequest(
        "alice",
        s.seq,
        0,
        "",
        (
            OpIntent(Action.MAKE_MAP, list_obj, index=99, insert=True,
                     temp_id="tmp:0"),
            OpIntent(Action.MAKE_MAP, "_root", key="ok", temp_id="tmp:1"),
            OpIntent(Action.SET, "tmp:0", key="leak", value="bad"),
        ),
    )
    s.opset.apply_local_request(req)
    assert s.opset.materialize()["ok"] == {}  # no leak into the wrong obj


def test_snapshot_includes_elem_conflicts():
    a, b = Site("alice"), Site("bob")
    a.change(lambda d: d.__setitem__("l", ["x"]))
    b.receive(a.opset.history)
    a.change(lambda d: d["l"].__setitem__(0, "A"))
    b.change(lambda d: d["l"].__setitem__(0, "B"))
    sync(a, b)
    snap = a.opset.snapshot_patch()
    ins = [d for d in snap.diffs if d.action == "insert"][0]
    assert len(ins.conflicts) == 1 and ins.conflicts[0].value == "A"
