"""The read-serving tier (serve/): residency, batched query kernels,
degradation ladder, and the facade wiring (ISSUE 11).

Twin-equality fuzz lives in tests/test_serve_twin.py; the lockdep-
instrumented race suite in tests/test_serve_races.py.
"""

import threading

import pytest

from hypermerge_tpu import telemetry
from hypermerge_tpu.models import Counter, Text
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.serve import READ_KINDS, host_read
from hypermerge_tpu.utils import keys as keymod
from hypermerge_tpu.utils.ids import to_doc_url, validate_doc_url


def snap():
    return telemetry.snapshot()


def serve_counter(name):
    return snap().get("serve." + name, 0)


@pytest.fixture
def repo():
    r = Repo(memory=True)
    yield r
    r.close()


def _seed(repo):
    url = repo.create({"title": "hello", "n": 41, "pi": 2.5, "yes": True})
    repo.change(url, lambda d: d.__setitem__("text", Text("hey there")))
    repo.change(url, lambda d: d.__setitem__("list", [1, "x", False]))
    repo.change(
        url, lambda d: d.__setitem__("nested", {"deep": {"v": 7}})
    )
    return url


# ---------------------------------------------------------------------------
# read kinds


def test_read_kinds_against_materialized(repo):
    url = _seed(repo)
    doc = repo.doc(url)
    assert repo.read(url, {"kind": "text", "path": ["text"]}) == str(
        doc["text"]
    )
    assert repo.read(url, {"kind": "lookup", "path": ["title"]}) == "hello"
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    assert repo.read(url, {"kind": "lookup", "path": ["pi"]}) == 2.5
    assert repo.read(url, {"kind": "lookup", "path": ["yes"]}) is True
    assert (
        repo.read(url, {"kind": "lookup", "path": ["nested", "deep", "v"]})
        == 7
    )
    assert repo.read(url, {"kind": "index", "path": ["list"], "index": 1}) == "x"
    assert repo.read(url, {"kind": "index", "path": ["text"], "index": 0}) == "h"
    assert repo.read(url, {"kind": "len", "path": []}) == len(doc)
    assert repo.read(url, {"kind": "len", "path": ["list"]}) == 3
    assert repo.read(url, {"kind": "len", "path": ["text"]}) == len(
        doc["text"]
    )
    assert repo.read(url, {"kind": "history"}) == 4
    clock = repo.read(url, {"kind": "clock"})
    assert isinstance(clock, list) and len(clock) == 1


def test_read_markers_and_misses(repo):
    url = _seed(repo)
    # containers collapse to type markers
    assert repo.read(url, {"kind": "lookup", "path": ["nested"]}) == {
        "_type": "map"
    }
    assert repo.read(url, {"kind": "lookup", "path": ["list"]}) == {
        "_type": "list"
    }
    assert repo.read(url, {"kind": "lookup", "path": ["text"]}) == {
        "_type": "text"
    }
    # broken paths answer None, never an error
    assert repo.read(url, {"kind": "lookup", "path": ["nope"]}) is None
    assert repo.read(url, {"kind": "lookup", "path": ["n", "deeper"]}) is None
    assert repo.read(url, {"kind": "text", "path": ["list"]}) is None
    assert (
        repo.read(url, {"kind": "index", "path": ["list"], "index": 99})
        is None
    )
    assert repo.read(url, {"kind": "len", "path": ["n"]}) is None
    assert repo.read(url, {"kind": "wat", "path": []}) is None


def test_counter_reads_fold_increments(repo):
    url = repo.create()
    repo.change(url, lambda d: d.__setitem__("c", Counter(3)))
    repo.change(url, lambda d: d.increment("c", 4))
    assert repo.read(url, {"kind": "lookup", "path": ["c"]}) == 7


def test_read_unknown_doc_is_none_and_creates_nothing(repo):
    url = to_doc_url(keymod.create().public_key)
    n_docs = len(repo.back.docs)
    assert repo.read(url, {"kind": "lookup", "path": ["a"]}) is None
    assert len(repo.back.docs) == n_docs  # no phantom doc materialized


def test_read_async_callback(repo):
    url = _seed(repo)
    done = threading.Event()
    got = []

    def cb(value):
        got.append(value)
        done.set()

    repo.read(url, {"kind": "lookup", "path": ["n"]}, cb)
    assert done.wait(10)
    assert got == [41]


# ---------------------------------------------------------------------------
# residency lifecycle


def test_install_then_hits(repo):
    url = _seed(repo)
    h0, i0 = serve_counter("hits"), serve_counter("installs")
    for _ in range(3):
        assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    assert serve_counter("installs") == i0 + 1
    assert serve_counter("hits") >= h0 + 2
    assert repo.back.serve.residency_report()["resident"]


def test_write_invalidates_and_rebuilds(repo):
    url = _seed(repo)
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    inv0 = serve_counter("invalidations")
    repo.change(url, lambda d: d.__setitem__("n", 42))
    assert serve_counter("invalidations") == inv0 + 1
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 42


def test_byte_budget_evicts_lru(repo, monkeypatch):
    monkeypatch.setenv("HM_SERVE_MAX_BYTES", "4000")
    urls = [_seed(repo) for _ in range(4)]
    for u in urls:
        assert repo.read(u, {"kind": "lookup", "path": ["n"]}) == 41
    assert serve_counter("evictions") > 0
    rep = repo.back.serve.residency_report()
    assert rep["evicted"]
    assert rep["bytes"] <= 4000
    # evicted docs reinstall on demand, still correct
    assert repo.read(urls[0], {"kind": "lookup", "path": ["title"]}) == (
        "hello"
    )


def test_close_doc_drops_residency(repo):
    url = _seed(repo)
    repo.read(url, {"kind": "lookup", "path": ["n"]})
    doc_id = validate_doc_url(url)
    assert repo.back.serve.residency_report()["resident"]
    repo.close_doc(url)
    rep = repo.back.serve.residency_report()
    assert doc_id not in rep["resident"]


# ---------------------------------------------------------------------------
# degradation ladder


def test_device_oom_evicts_and_retries_once(repo, monkeypatch):
    from hypermerge_tpu.serve import resident

    warm = _seed(repo)
    assert repo.read(warm, {"kind": "lookup", "path": ["n"]}) == 41
    url = _seed(repo)
    real = resident._to_device
    fails = {"n": 1}

    def flaky(arr):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return real(arr)

    monkeypatch.setattr(resident, "_to_device", flaky)
    p0, f0 = serve_counter("evictions_pressure"), serve_counter("fallbacks")
    # first install attempt OOMs -> LRU shed -> retry succeeds
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    assert serve_counter("evictions_pressure") > p0
    assert serve_counter("fallbacks") == f0


def test_device_oom_twice_degrades_to_host(repo, monkeypatch):
    from hypermerge_tpu.serve import resident

    warm = _seed(repo)
    repo.read(warm, {"kind": "lookup", "path": ["n"]})
    url = _seed(repo)

    def dead(arr):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    monkeypatch.setattr(resident, "_to_device", dead)
    f0 = serve_counter("fallbacks")
    # reader still gets the right answer — never an error
    assert repo.read(url, {"kind": "text", "path": ["text"]}) == "hey there"
    assert serve_counter("fallbacks") > f0


def test_unserveable_doc_falls_back_with_host_memo(repo, monkeypatch):
    url = _seed(repo)
    monkeypatch.setattr(
        repo.back, "_serveable_spec", lambda clock: None
    )
    f0, m0 = serve_counter("fallbacks"), serve_counter("host_memo_hits")
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    # clock unmoved: the second degraded read hits the host memo —
    # zero snapshot decode / wire parse
    assert repo.read(url, {"kind": "lookup", "path": ["title"]}) == "hello"
    assert serve_counter("fallbacks") >= f0 + 2
    assert serve_counter("host_memo_hits") >= m0 + 1


def test_admission_overflow_degrades(monkeypatch):
    # queue overflow is TRAFFIC pressure, not a device degradation:
    # it counts serve.overload_shed (the service plane's signal),
    # never serve.fallbacks (ISSUE 20 satellite) — and the read still
    # answers correctly from the host path
    monkeypatch.setenv("HM_SERVE_QUEUE", "0")  # cap reads at tier init
    repo = Repo(memory=True)
    try:
        url = _seed(repo)
        f0 = serve_counter("fallbacks")
        s0 = serve_counter("overload_shed")
        assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
        assert serve_counter("overload_shed") == s0 + 1
        assert serve_counter("fallbacks") == f0
    finally:
        repo.close()


# ---------------------------------------------------------------------------
# batched kernels + program table


def test_program_table_traces_once():
    from hypermerge_tpu.parallel import sharded

    r = Repo(memory=True)
    try:
        urls = [r.create({"i": i}) for i in range(4)]
        for i, u in enumerate(urls):
            r.change(u, lambda d, i=i: d.__setitem__("t", Text(f"x{i}")))
        for _ in range(3):
            for u in urls:
                assert r.read(u, {"kind": "text", "path": ["t"]})
        keys = {
            k: v for k, v in sharded.trace_counts.items()
            if k[0] == "serve"
        }
        assert keys, "serve programs should live in the shared table"
        assert all(v == 1 for v in keys.values()), keys
    finally:
        r.close()


def test_concurrent_reads_batch(repo):
    urls = [_seed(repo) for _ in range(4)]
    b0, r0 = serve_counter("batches"), serve_counter("reads")
    out = {}

    def reader(n):
        for j in range(8):
            u = urls[(n + j) % len(urls)]
            out[(n, j)] = repo.read(u, {"kind": "text", "path": ["text"]})

    ts = [threading.Thread(target=reader, args=(n,)) for n in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(v == "hey there" for v in out.values())
    reads = serve_counter("reads") - r0
    batches = serve_counter("batches") - b0
    assert reads == 64
    # the debounce window must coalesce at least some of the storm
    assert batches < reads


# ---------------------------------------------------------------------------
# memo wiring + introspection surfaces


def test_bulk_summary_memo_feeds_installs(tmp_path):
    path = str(tmp_path / "repo")
    r = Repo(path=path)
    urls = [r.create({"i": i}) for i in range(3)]
    for i, u in enumerate(urls):
        r.change(u, lambda d, i=i: d.__setitem__("t", Text(f"doc{i}")))
    r.close()
    r = Repo(path=path)
    try:
        r.open_many(urls)
        r.back.fetch_bulk_summaries()  # populates the per-doc memo
        m0 = serve_counter("memo_hits")
        for i, u in enumerate(urls):
            assert r.read(u, {"kind": "text", "path": ["t"]}) == f"doc{i}"
        # installs reused the bulk loader's memo'd summary lanes
        # (clock unmoved): no second host kernel run
        assert serve_counter("memo_hits") >= m0 + len(urls)
    finally:
        r.close()


def test_telemetry_query_carries_residency(repo):
    url = _seed(repo)
    repo.read(url, {"kind": "lookup", "path": ["n"]})
    got = []
    repo.telemetry(got.append)
    assert got and "serve" in got[0]
    assert got[0]["serve"]["resident"]
    assert any(
        k.startswith("serve.") for k in got[0]["counters"]
    )


def test_host_read_twin_smoke(repo):
    url = _seed(repo)
    doc = repo.back.docs[validate_doc_url(url)]
    for q in (
        {"kind": "text", "path": ["text"]},
        {"kind": "lookup", "path": ["title"]},
        {"kind": "len", "path": []},
        {"kind": "history"},
    ):
        assert host_read(doc, q) == {"value": repo.read(url, q)}
    assert set(READ_KINDS) == {
        "lookup", "index", "text", "len", "clock", "history"
    }


def test_serve_off_is_host_twin(monkeypatch):
    monkeypatch.setenv("HM_SERVE", "0")
    r = Repo(memory=True)
    try:
        assert r.back.serve is None
        url = r.create({"a": 1})
        r.change(url, lambda d: d.__setitem__("t", Text("plain")))
        assert r.read(url, {"kind": "text", "path": ["t"]}) == "plain"
        assert r.read(url, {"kind": "lookup", "path": ["a"]}) == 1
    finally:
        r.close()


def test_read_after_tier_close_degrades(repo):
    """A read racing (or following) tier shutdown degrades to the host
    path with the right answer — never a dropped callback/timeout."""
    url = _seed(repo)
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    repo.back.serve.close()
    # post-close reads answer inline off the host path (the tier's
    # labeled counters are already retired from the registry)
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    assert repo.read(url, {"kind": "text", "path": ["text"]}) == (
        "hey there"
    )


def test_non_oom_install_failure_does_not_shed(repo, monkeypatch):
    """A deterministic build failure (corrupt sidecar, pack bug) falls
    back to host WITHOUT evicting healthy residents — only genuine
    memory pressure earns the evict-and-retry."""
    from hypermerge_tpu.serve import tier as tiermod

    urls = [_seed(repo) for _ in range(3)]
    for u in urls:
        assert repo.read(u, {"kind": "lookup", "path": ["n"]}) == 41
    n0 = repo.back.serve._cache.resident_docs

    def broken(backend, doc_id, clock):
        raise ValueError("corrupt sidecar (not oom)")

    monkeypatch.setattr(tiermod, "build_entry", broken)
    cold = _seed(repo)
    p0 = serve_counter("evictions_pressure")
    f0 = serve_counter("fallbacks")
    assert repo.read(cold, {"kind": "lookup", "path": ["n"]}) == 41
    assert serve_counter("fallbacks") > f0
    assert serve_counter("evictions_pressure") == p0
    assert repo.back.serve._cache.resident_docs == n0


def test_write_releases_resident_bytes(repo):
    """mark_stale frees the invalidated entry's device arrays at the
    write, not at the next LRU pass."""
    url = _seed(repo)
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 41
    b0 = repo.back.serve._cache.resident_bytes
    assert b0 > 0
    repo.change(url, lambda d: d.__setitem__("n", 99))
    assert repo.back.serve._cache.resident_bytes < b0
    assert repo.read(url, {"kind": "lookup", "path": ["n"]}) == 99
