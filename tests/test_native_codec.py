"""Native change-frame codec (hm_change_encode/decode) vs the twin.

The per-edit hot loop's frame codec has two implementations: the C
scanner/emitter in native/src/hm_native.cpp (GIL-free, the write
daemon's fast path) and the pure-Python twin in crdt/codec.py that
remains both the fallback and the correctness reference. These tests
pin them BIT-identical over fuzzed changes — same frames out of
encode, same canonical JSON out of decode, and agreement on exactly
which shapes are off-canon — in both directions across the
HM_NATIVE_CODEC=1/0 hatch (frames written with either setting read
under the other), plus the pack_drops_gil-style proof that the codec
binding really releases the GIL.
"""

import random
import string

import pytest

from hypermerge_tpu import native
from hypermerge_tpu.crdt import codec
from hypermerge_tpu.storage import block as blockmod
from hypermerge_tpu.utils.json_buffer import bufferify, parse

needs_codec = pytest.mark.skipif(
    native.codec_lib() is None, reason="native codec layer unavailable"
)

_CHARS = (
    string.ascii_letters
    + string.digits
    + ' \t\n"\\/{}[],:éπ☃ '
)


def _rand_str(r, lo=0, hi=24):
    return "".join(
        r.choice(_CHARS) for _ in range(r.randint(lo, hi))
    )


def _rand_opid(r):
    return f"{r.randint(0, 2**40)}@{_rand_str(r, 1, 10)}"


def _rand_value(r, depth=0):
    roll = r.random()
    if roll < 0.25:
        return _rand_str(r)
    if roll < 0.45:
        return r.randint(-(2**50), 2**50)
    if roll < 0.6:
        return r.choice([0.0, -1.5, 3.25, 1e300, 1 / 3, -0.0])
    if roll < 0.7:
        return r.choice([True, False, None])
    if depth >= 2:
        return r.randint(0, 9)
    if roll < 0.85:
        return [_rand_value(r, depth + 1) for _ in range(r.randint(0, 4))]
    return {
        _rand_str(r, 1, 8): _rand_value(r, depth + 1)
        for _ in range(r.randint(0, 4))
    }


def _rand_op(r):
    op = {"a": r.randint(0, 7), "o": _rand_opid(r)}
    if r.random() < 0.6:
        op["k"] = _rand_str(r)
    if r.random() < 0.3:
        op["r"] = _rand_opid(r)
    if r.random() < 0.4:
        op["i"] = True
    if r.random() < 0.6:
        op["v"] = _rand_value(r)
    if r.random() < 0.2:
        op["d"] = r.choice(["counter", "timestamp"])
    if r.random() < 0.5:
        op["p"] = [_rand_opid(r) for _ in range(r.randint(0, 3))]
    return op


def _rand_change(r, n_ops=None):
    return {
        "actor": _rand_str(r, 1, 16),
        "deps": {
            _rand_str(r, 1, 12): r.randint(0, 2**40)
            for _ in range(r.randint(0, 4))
        },
        "message": _rand_str(r, 0, 40),
        "ops": [
            _rand_op(r)
            for _ in range(r.randint(0, 8) if n_ops is None else n_ops)
        ],
        "seq": r.randint(1, 2**40),
        "startOp": r.randint(1, 2**50),
        "time": r.choice([0, r.randint(1, 2**40)]),
    }


def _spoil(r, obj):
    """One off-canon mutation the codec must refuse (both sides)."""
    obj = dict(obj)
    roll = r.randrange(8)
    if roll == 0:
        obj["extra"] = 1
    elif roll == 1:
        obj["seq"] = True  # bool-as-int: serializes as `true`
    elif roll == 2:
        obj["time"] = -r.randint(1, 100)
    elif roll == 3:
        obj["message"] = None
    elif roll == 4:
        obj["deps"] = {_rand_str(r, 1, 6): 1.5}
    elif roll == 5:
        obj["ops"] = [{"a": 1}]  # missing mandatory "o"
    elif roll == 6:
        obj["ops"] = [{"a": 1, "o": _rand_opid(r), "i": False}]
    else:
        obj["startOp"] = 2**63  # one past the varint ceiling
    return obj


def test_twin_roundtrip_fuzz():
    """Twin-only (runs without the native layer): encode->decode is the
    identity on canonical bytes, and the block layer round-trips the
    object through the frame format."""
    r = random.Random(11)
    for _ in range(300):
        obj = _rand_change(r)
        raw = bufferify(obj)
        frame = codec._encode_py(obj)
        assert frame is not None and frame[:2] == codec.MAGIC
        assert codec._decode_py(frame) == raw
        assert parse(codec._decode_py(frame)) == parse(raw)


@needs_codec
def test_native_twin_parity_fuzz(monkeypatch):
    """The pin: native and twin produce byte-identical frames, decode
    byte-identically (including each other's output), and agree on
    which shapes are off-canon."""
    monkeypatch.setenv("HM_NATIVE_CODEC", "1")
    r = random.Random(7)
    refused = 0
    for i in range(400):
        obj = _rand_change(r)
        if i % 4 == 3:
            obj = _spoil(r, obj)
        try:
            raw = bufferify(obj)
        except (TypeError, ValueError):
            continue  # not JSON-serializable: no codec question to ask
        nf = native.change_encode(raw)
        pf = codec._encode_py(obj)
        assert (nf is None) == (pf is None), (
            f"encodability disagreement on {raw!r}: "
            f"native={'ok' if nf else 'refused'} "
            f"twin={'ok' if pf else 'refused'}"
        )
        if nf is None:
            refused += 1
            continue
        assert nf == pf, f"frame mismatch on {raw!r}"
        # both decoders, each on the (shared) frame, back to raw bytes
        assert native.change_decode(nf) == raw
        assert codec._decode_py(nf) == raw
    # the spoiler must actually exercise the refusal paths
    assert refused >= 50


@needs_codec
def test_malformed_frames_rejected():
    """Truncations and bit-flips of real frames must fail loudly (and
    identically: native -1 <=> twin ValueError), never misparse."""
    r = random.Random(23)
    obj = _rand_change(r, n_ops=5)
    frame = codec._encode_py(obj)
    raw = bufferify(obj)
    for cut in range(2, len(frame) - 1, max(1, len(frame) // 40)):
        trunc = frame[:cut]
        assert native.change_decode(trunc) is None
        with pytest.raises(ValueError):
            codec._decode_py(trunc)
    for _ in range(200):
        pos = r.randrange(2, len(frame))
        bad = bytearray(frame)
        bad[pos] ^= 1 << r.randrange(8)
        bad = bytes(bad)
        nd = native.change_decode(bad)
        try:
            pd = codec._decode_py(bad)
        except ValueError:
            pd = None
        assert nd == pd, f"decode disagreement on flip at {pos}"
        if nd is not None and nd != raw:
            # a forged-but-well-formed frame may decode to different
            # JSON bytes — possibly invalid ones (flipped string-token
            # bytes pass through verbatim). The reader contract is
            # fail-loudly, never silent misparse: parse() either
            # succeeds or raises ValueError, nothing else.
            try:
                parse(nd)
            except ValueError:
                pass


def test_hatch_cross_reads(monkeypatch):
    """Blocks written under HM_NATIVE_CODEC=1 and =0 read correctly
    under the OTHER setting, both orders — the hatch only changes what
    new writes look like."""
    r = random.Random(5)
    objs = [_rand_change(r) for _ in range(20)]
    monkeypatch.setenv("HM_NATIVE_CODEC", "1")
    frames = [blockmod.pack_change(o) for o in objs]
    # small interactive blocks become frames; oversized ones keep the
    # compressed JSON path by design — both must cross-read below
    assert any(f[:2] == codec.MAGIC for f in frames)
    monkeypatch.setenv("HM_NATIVE_CODEC", "0")
    jsons = [blockmod.pack_change(o) for o in objs]
    assert not any(j[:2] == codec.MAGIC for j in jsons)
    # codec-off reader on codec-on blocks (twin decode path) ...
    assert [blockmod.unpack(f) for f in frames] == [
        parse(bufferify(o)) for o in objs
    ]
    monkeypatch.setenv("HM_NATIVE_CODEC", "1")
    # ... and codec-on reader on codec-off blocks
    assert [blockmod.unpack(j) for j in jsons] == [
        parse(bufferify(o)) for o in objs
    ]


@needs_codec
def test_codec_releases_gil():
    """The codec bindings must DROP the GIL (ctypes.CDLL foreign-call
    semantics) — the sharded write daemon relies on it so frame
    parsing from N connections overlaps on real threads. Mirrors
    test_native_pack.py::test_pack_releases_gil: (1) a spinner thread
    keeps making progress while the native codec chews a large frame
    batch; (2) with >=2 cores, two concurrent chews on distinct
    buffers overlap in wall time."""
    import os
    import threading
    import time

    assert native.codec_drops_gil()

    r = random.Random(17)
    big = [_rand_change(r, n_ops=1500) for _ in range(8)]
    raws = [bufferify(o) for o in big]
    frames = [native.change_encode(raw) for raw in raws]
    assert all(f is not None for f in frames)

    def one_chew():
        for raw, frame in zip(raws, frames):
            assert native.change_encode(raw) == frame
            assert native.change_decode(frame) == raw

    one_chew()  # warm allocator / code paths

    # -- (1) GIL-progress: a spinner thread must not starve ------------
    stop = [False]
    spins = [0]

    def spinner():
        while not stop[0]:
            spins[0] += 1

    t = threading.Thread(target=spinner, daemon=True)
    t.start()
    time.sleep(0.02)  # let it settle
    spins[0] = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.4:
        one_chew()
    held_spins = spins[0]
    stop[0] = True
    t.join(5)
    assert held_spins > 10_000, (
        f"spinner starved during native codec calls ({held_spins} "
        "iters): is the codec binding holding the GIL?"
    )

    # -- (2) wall-time overlap of two concurrent chews -----------------
    if (os.cpu_count() or 1) < 2:
        pytest.skip("single core: wall-time overlap is unmeasurable")

    def chews(n):
        for _ in range(n):
            one_chew()

    best_serial = best_conc = None
    for _attempt in range(5):
        t0 = time.perf_counter()
        chews(6)
        serial = time.perf_counter() - t0
        ts = [
            threading.Thread(target=chews, args=(3,), daemon=True)
            for _ in range(2)
        ]
        t0 = time.perf_counter()
        for th in ts:
            th.start()
        for th in ts:
            th.join(60)
        conc = time.perf_counter() - t0
        best_serial = min(serial, best_serial or serial)
        best_conc = min(conc, best_conc or conc)
        if best_conc < 0.9 * best_serial:
            break
    ratio = best_conc / max(best_serial, 1e-9)
    if ratio >= 0.9:
        pytest.skip(
            f"GIL release proven by spinner, but no idle core to show "
            f"wall overlap (conc/serial={ratio:.2f})"
        )
