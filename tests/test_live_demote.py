"""Byte-bounded LRU demotion (backend/live.py, HM_LIVE_MAX_BYTES).

Adopted docs' LiveColumns are no longer pinned until close: idle docs
demote back to the lazy path (serving clock synced, columns dropped)
and re-adopt from the sidecars on their next live change. Pinned here:

- twin fuzz: a feed-backed multi-actor workload with FORCED
  demote/re-adopt cycles between deliveries produces bit-identical
  clocks, snapshots, local patch echoes, and frontend state across
  HM_LIVE=1/0, in both delivery orders;
- the byte cap holds: resident live bytes stay under HM_LIVE_MAX_BYTES
  (beyond the one-doc MRU floor) while demoted docs keep serving
  correct values and re-adopt on the next edit;
- a frontend reopened on a DEMOTED doc receives the current state (the
  demoted snapshot closure), not the stale bulk-load decode;
- docs whose admitted changes have no backing feed are never demoted
  (demotion would silently lose them).
"""

import json
import os
import random
import shutil
import tempfile

import pytest

from helpers import Site, plainify, sync, random_mutation, wait_until
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils import keys as keymod
from hypermerge_tpu.utils.ids import validate_doc_url


@pytest.fixture
def live_env(monkeypatch):
    monkeypatch.setenv("HM_LIVE", "1")


def _seed(base):
    repo = Repo(path=base)
    url = repo.create({"edits": [], "k": 0})
    for i in range(5):
        repo.change(url, lambda d, i=i: d["edits"].append(i))
    doc_id = validate_doc_url(url)
    pairs = [keymod.create() for _ in range(2)]
    meta = {
        "url": url,
        "doc_id": doc_id,
        "pairs": [[p.public_key, p.secret_key] for p in pairs],
    }
    with open(os.path.join(base, "_meta"), "w") as fh:
        json.dump(meta, fh)
    repo.close()
    return meta


def _stored_changes(repo, doc_id):
    out = []
    for actor_id, end in repo.back.docs[doc_id].clock.items():
        actor = repo.back._get_or_create_actor(actor_id)
        out.extend(actor.changes_in_window(0, end))
    return out


def _gen_script(stored, pair_ids, seed, n_rounds=8):
    """Deterministic multi-peer batches extending `stored`; peers are
    keyed by REAL feed keypairs so deliveries can be feed-backed."""
    r = random.Random(seed)
    peers = [Site(a) for a in pair_ids]
    for p in peers:
        p.receive(stored)
    script = []
    for rnd in range(n_rounds):
        idx = r.randrange(2)
        site = peers[idx]
        batch = []
        for _ in range(r.randint(1, 3)):
            before = len(site.opset.history)
            random_mutation(site, r)
            batch.extend(site.opset.history[before:])
        if batch:
            script.append((idx, batch))
        if rnd % 3 == 2:
            sync(*peers)
    return script


def _run_demote_workload(base, live, order_flip, seed=23):
    """Replay the same feed-backed remote script + local edits under
    HM_LIVE=`live`, forcing a demote of every idle doc between
    deliveries (live mode). Returns the normalized observable
    outcome."""
    os.environ["HM_LIVE"] = live
    work = tempfile.mkdtemp()
    shutil.rmtree(work)
    shutil.copytree(base, work)
    try:
        repo = Repo(path=work)
        with open(os.path.join(base, "_meta")) as fh:
            meta = json.load(fh)
        url, doc_id = meta["url"], meta["doc_id"]
        local_patches = []
        orig_push = repo.back.to_frontend.push

        def record(msg):
            if msg.get("type") == "Patch" and msg["patch"].get("actor"):
                local_patches.append(msg["patch"])
            orig_push(msg)

        repo.back.to_frontend.push = record
        h = repo.open(url)
        assert h.value(timeout=20) is not None
        back = repo.back
        doc = back.docs[doc_id]
        stored = _stored_changes(repo, doc_id)
        pair_ids = [pk for pk, _sk in meta["pairs"]]
        script = _gen_script(stored, pair_ids, seed)
        if order_flip:
            script = [b for b in script if b[0] == 1] + [
                b for b in script if b[0] == 0
            ]
        # peer feeds are REAL writable feeds in this repo: deliveries
        # go through the feeds + _sync_changes, so a demoted doc can
        # always rebuild from the sidecars
        actors = [
            back._init_actor(keymod.KeyPair(pk, sk))
            for pk, sk in meta["pairs"]
        ]
        for a in actors:
            back.cursors.add_actor(back.id, doc_id, a.id)
        from hypermerge_tpu.crdt.opset import OpSet

        oracle = OpSet()
        oracle.apply_changes(stored)
        peer_actors = set()
        for k, (idx, batch) in enumerate(script):
            oracle.apply_changes(list(batch))
            peer_actors.update(c.actor for c in batch)
            for ch in batch:
                actors[idx].write_change(ch)
            back.cursors.update(
                back.id, doc_id, {actors[idx].id: batch[-1].seq}
            )
            back._sync_changes(actors[idx])
            wait_until(
                lambda: all(
                    doc.clock.get(a, 0) == oracle.clock.get(a, 0)
                    for a in peer_actors
                )
            )
            repo.change(url, lambda d, k=k: d.__setitem__(f"k{k}", k))
            if back.live is not None:
                back.live.flush_now()
                back.live.demote_idle(0)  # force the lifecycle
        # final demote -> one more local edit -> re-adopt
        if back.live is not None:
            back.live.flush_now()
            back.live.demote_idle(0)
        repo.change(url, lambda d: d.__setitem__("fin", 1))
        if back.live is not None:
            back.live.flush_now()
            stats = dict(back.live.stats)
            assert stats["demoted"] > 0, stats
            assert stats["readopted"] > 0, stats
        outcome = {
            "snap": doc.snapshot_patch().to_json(),
            "clock": dict(doc.clock),
            "hist": doc.history_len,
            "state": plainify(h.value()),
            "local_patches": local_patches,
        }
        actor_id = doc.actor_id
        repo.close()

        def scrub(v):
            if isinstance(v, str):
                return v.replace(actor_id, "<LOCAL-ACTOR>")
            if isinstance(v, dict):
                return {scrub(k): scrub(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [scrub(x) for x in v]
            return v

        return json.dumps(scrub(outcome), sort_keys=True, default=str)
    finally:
        shutil.rmtree(work, ignore_errors=True)


@pytest.mark.parametrize("order_flip", [False, True], ids=["fwd", "rev"])
def test_demote_readopt_twin_bit_identical(tmp_path, order_flip):
    """HM_LIVE=1 with forced demote/re-adopt cycles stays bit-identical
    to the HM_LIVE=0 host path, in both delivery orders."""
    base = str(tmp_path / "seed")
    os.makedirs(base)
    old = os.environ.get("HM_LIVE")
    try:
        os.environ["HM_LIVE"] = "0"
        _seed(base)
        host = _run_demote_workload(base, "0", order_flip)
        live = _run_demote_workload(base, "1", order_flip)
    finally:
        if old is None:
            os.environ.pop("HM_LIVE", None)
        else:
            os.environ["HM_LIVE"] = old
    assert live == host


def test_byte_cap_bounds_resident_columns(tmp_path, live_env, monkeypatch):
    """With HM_LIVE_MAX_BYTES set, resident live bytes stay under the
    cap (MRU floor aside), demoted docs re-adopt on their next edit,
    and every doc still serves correct values."""
    repo = Repo(path=str(tmp_path))
    urls = [repo.create({"i": i, "edits": []}) for i in range(6)]
    ids = [validate_doc_url(u) for u in urls]
    for u in urls:
        for k in range(20):
            repo.change(u, lambda d, k=k: d["edits"].append(k))
    repo.close()

    monkeypatch.setenv("HM_LIVE_MAX_BYTES", "40000")  # ~2 docs
    repo2 = Repo(path=str(tmp_path))
    repo2.back.load_documents_bulk(ids)
    eng = repo2.back.live
    r = random.Random(5)
    for step in range(30):
        u = urls[r.randrange(len(urls))]
        repo2.change(u, lambda d, step=step: d.__setitem__("s", step))
        if step % 5 == 4:
            eng.flush_now()
            assert eng.stats["live_bytes"] <= 40000, eng.stats
    eng.flush_now()
    assert eng.stats["demoted"] > 0, eng.stats
    assert eng.stats["readopted"] > 0, eng.stats
    assert eng.stats["live_bytes"] <= 40000, eng.stats
    for i, u in enumerate(urls):
        v = repo2.doc(u)
        assert v["i"] == i and len(v["edits"]) == 20, (i, v)
    # no doc regressed to the host path
    for did in ids:
        assert repo2.back.docs[did].opset is None, did
    repo2.close()


def test_reopen_on_demoted_doc_serves_current_state(
    tmp_path, live_env
):
    """A second frontend handle opened while the doc is DEMOTED gets
    the CURRENT state via the demoted snapshot closure — not the stale
    bulk-load decode the doc was first opened with."""
    repo = Repo(path=str(tmp_path))
    url = repo.create({"v": 0})
    for k in range(6):
        repo.change(url, lambda d, k=k: d.__setitem__("v", k))
    doc_id = validate_doc_url(url)
    repo.close()

    repo2 = Repo(path=str(tmp_path))
    h1 = repo2.open(url)
    assert h1.value(timeout=20) is not None
    repo2.change(url, lambda d: d.__setitem__("fresh", True))
    eng = repo2.back.live
    eng.flush_now()
    assert eng.demote_idle(0) == 1, eng.stats
    doc = repo2.back.docs[doc_id]
    assert doc.opset is None and not doc._live_adopted
    h2 = repo2.open(url)
    wait_until(lambda: (h2.value(timeout=5) or {}).get("fresh"))
    # the doc is STILL lazy afterwards (reads must not force a replay)
    assert doc.opset is None
    repo2.close()


@pytest.mark.slow
def test_adoption_hammer_stress(tmp_path, live_env, monkeypatch):
    """Stress: adoptions hammered from worker threads while other hot
    docs tick, under a byte cap. Asserts (a) the engine lock is never
    held for an adoption-sized window (lock-held install time stays a
    tiny fraction of the lock-free build time), (b) resident bytes
    respect the cap at every flush, (c) every doc converges to the
    right state with no host-path fallbacks."""
    import threading as _th

    from hypermerge_tpu.ops.corpus import make_corpus

    n_docs, n_ops = 12, 2048
    urls = make_corpus(str(tmp_path), n_docs, n_ops, threads=8)
    ids = [validate_doc_url(u) for u in urls]
    # ~3 docs of resident footprint (a 2048-op doc's columns + opid
    # index + decoded-state estimate is ~700KB): the cap clears the
    # one-doc MRU floor but binds well below 12 resident docs
    cap = 2_200_000
    monkeypatch.setenv("HM_LIVE_MAX_BYTES", str(cap))
    repo = Repo(path=str(tmp_path))
    handles = repo.open_many(urls)
    for h in handles:
        assert h.value(timeout=60) is not None
    eng = repo.back.live

    errors = []
    n_workers = 4
    rounds = 6

    def worker(w):
        try:
            r = random.Random(w)
            for step in range(rounds):
                u = urls[(w + step * n_workers) % n_docs]
                repo.change(
                    u,
                    lambda d, w=w, step=step: d.__setitem__(
                        f"w{w}", step
                    ),
                )
                if r.random() < 0.3:
                    eng.flush_now()
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [
        _th.Thread(target=worker, args=(w,)) for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
        assert not t.is_alive(), "stress worker wedged"
    assert not errors, errors
    eng.flush_now()
    stats = eng.stats
    assert stats["live_bytes"] <= cap, stats
    assert stats["adopted"] >= n_docs, stats
    assert stats["refused"] == 0, stats
    assert stats["demoted"] > 0, stats
    # the lock-held install window must be a sliver of the build work:
    # a regression that rebuilds under the engine lock flips this ratio
    assert (
        stats["t_adopt_lock_held"]
        < 0.2 * stats["t_adopt_lock_free"] + 0.01
    ), stats
    for w in range(n_workers):
        for step in range(rounds):
            u = urls[(w + step * n_workers) % n_docs]
            wait_until(
                lambda u=u, w=w: repo.doc(u).get(f"w{w}") is not None
            )
    for did in ids:
        assert repo.back.docs[did].opset is None, did
    repo.close()


def test_unbacked_changes_pin_doc_resident(tmp_path, live_env):
    """Changes injected straight into the engine (no backing feed —
    synthetic peers) make a doc non-demotable: demoting would lose
    them on re-adoption."""
    from test_live import _local_changes, _seed_dir

    url, doc_id, stored = _seed_dir(str(tmp_path))
    repo = Repo(path=str(tmp_path))
    repo.back.load_documents_bulk([doc_id])
    doc = repo.back.docs[doc_id]
    peer = Site("pinpeer000000001")
    peer.receive(stored)
    ch, _ = peer.change(lambda d: d.__setitem__("ghost", 1))
    doc.apply_remote_changes([ch])  # NOT in any feed
    eng = repo.back.live
    eng.flush_now()
    wait_until(lambda: repo.doc(url).get("ghost") == 1)
    assert eng.demote_idle(0) == 0, "unbacked doc must stay resident"
    assert eng.stats["demoted"] == 0
    assert repo.doc(url)["ghost"] == 1
    repo.close()
