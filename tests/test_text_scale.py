"""Text at automerge-perf scale (VERDICT r5 item 6).

The reference's CRDT engine (automerge 0.14, Immutable.js) is publicly
documented to take minutes on the 259,778-op automerge-perf LaTeX
editing trace (BASELINE.md: ~0.4-0.9k ops/s, multi-GB heap). That shape
— ONE text doc, ONE author, one op per change — must go through this
framework's device kernel (and its numpy host twin) at speed, in a jit
bucket no small-doc test ever touches.

Correctness at scale is pinned two ways:
- device kernel == host numpy twin, field-for-field, at 16k ops in
  tier-1 (the largest int16-lane bucket) and at 128k ops behind
  `-m slow` (the int32 wide-lane bucket: XLA:CPU takes tens of minutes
  to compile that program, which is exactly what used to run the tier-1
  verify into its 870s timeout — real accelerators compile it in
  seconds);
- device text == host OpSet text, char-for-char, at 4k ops (OpSet
  replay is quadratic in doc length — which is the point of the
  kernel; the 8k shape rides along under `-m slow`).
"""

import numpy as np
import pytest

from hypermerge_tpu.crdt.opset import OpSet
from hypermerge_tpu.models import Text
from hypermerge_tpu.ops.columnar import pack_docs
from hypermerge_tpu.ops.materialize import (
    materialize_batch,
    text_join,
)
from hypermerge_tpu.ops.synth import synth_changes


def _trace_shaped(n_ops: int, seed: int = 3):
    """automerge-perf trace shape: one author, one op per change, all
    text edits."""
    return synth_changes(
        n_ops, n_actors=1, ops_per_change=1, text_frac=1.0, seed=seed
    )


def _device_text(dec, d: int = 0) -> str:
    c = dec.cols
    from hypermerge_tpu.crdt.change import Action

    n = int(dec.batch.n_ops[d])
    text_rows = np.nonzero(
        c["action"][d][:n] == int(Action.MAKE_TEXT)
    )[0]
    assert len(text_rows) == 1, len(text_rows)
    return text_join(dec, d, int(text_rows[0]))


def _assert_device_matches_host_twin(n_ops: int) -> None:
    from hypermerge_tpu.ops.crdt_kernels import run_batch
    from hypermerge_tpu.ops.host_kernel import run_batch_host

    changes = _trace_shaped(n_ops)
    batch = pack_docs([changes])
    dev = run_batch(batch)
    host = run_batch_host(batch)
    for f in host._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dev, f)), getattr(host, f), err_msg=f
        )


def test_text_16k_device_matches_host_twin():
    # 16_384 rows: the largest bucket on the int16-packed kernel path
    _assert_device_matches_host_twin(16_384)


@pytest.mark.slow
def test_text_128k_device_matches_host_twin():
    # 131_072 rows: the int32 wide-lane path (N >= 2^15). XLA:CPU needs
    # tens of minutes to compile this program — slow-only on CI.
    _assert_device_matches_host_twin(131_072)


def _assert_device_matches_opset_charwise(n_ops: int) -> None:
    changes = _trace_shaped(n_ops)
    opset = OpSet()
    opset.apply_changes(changes)
    doc = opset.materialize()
    want = str(doc["t"])
    assert isinstance(doc["t"], Text) and len(want) > 100

    dec = materialize_batch([changes])
    assert _device_text(dec) == want
    assert dec.clock_dict(0) == opset.clock


def test_text_4k_device_matches_opset_charwise():
    _assert_device_matches_opset_charwise(4_096)


@pytest.mark.slow
def test_text_8k_device_matches_opset_charwise():
    _assert_device_matches_opset_charwise(8_192)
