"""Text at automerge-perf scale (VERDICT r5 item 6).

The reference's CRDT engine (automerge 0.14, Immutable.js) is publicly
documented to take minutes on the 259,778-op automerge-perf LaTeX
editing trace (BASELINE.md: ~0.4-0.9k ops/s, multi-GB heap). That shape
— ONE text doc, ONE author, one op per change — must go through this
framework's device kernel (and its numpy host twin) at speed, in the
N=128k+ jit bucket no small-doc test ever touches.

Correctness at scale is pinned two ways:
- device kernel == host numpy twin, field-for-field, at 128k ops (the
  twin is itself fuzz-equivalent to OpSet — test_device_materialize);
- device text == host OpSet text, char-for-char, at 8k ops (OpSet
  replay is too slow above that — which is the point of the kernel).
"""

import numpy as np

from hypermerge_tpu.crdt.opset import OpSet
from hypermerge_tpu.models import Text
from hypermerge_tpu.ops.columnar import pack_docs
from hypermerge_tpu.ops.materialize import (
    materialize_batch,
    text_join,
)
from hypermerge_tpu.ops.synth import synth_changes


def _trace_shaped(n_ops: int, seed: int = 3):
    """automerge-perf trace shape: one author, one op per change, all
    text edits."""
    return synth_changes(
        n_ops, n_actors=1, ops_per_change=1, text_frac=1.0, seed=seed
    )


def _device_text(dec, d: int = 0) -> str:
    c = dec.cols
    from hypermerge_tpu.crdt.change import Action

    n = int(dec.batch.n_ops[d])
    text_rows = np.nonzero(
        c["action"][d][:n] == int(Action.MAKE_TEXT)
    )[0]
    assert len(text_rows) == 1, len(text_rows)
    return text_join(dec, d, int(text_rows[0]))


def test_text_128k_device_matches_host_twin():
    from hypermerge_tpu.ops.crdt_kernels import run_batch
    from hypermerge_tpu.ops.host_kernel import run_batch_host

    changes = _trace_shaped(131_072)
    batch = pack_docs([changes])
    dev = run_batch(batch)
    host = run_batch_host(batch)
    for f in host._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dev, f)), getattr(host, f), err_msg=f
        )


def test_text_8k_device_matches_opset_charwise():
    changes = _trace_shaped(8_192)
    opset = OpSet()
    opset.apply_changes(changes)
    doc = opset.materialize()
    want = str(doc["t"])
    assert isinstance(doc["t"], Text) and len(want) > 100

    dec = materialize_batch([changes])
    assert _device_text(dec) == want
    assert dec.clock_dict(0) == opset.clock
