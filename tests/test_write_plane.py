"""The many-writer write plane (backend/emission.py + storage/wal.py).

Pins the PR-14 split invariants with the machine checkers ON:

- the two-writer seeded race: disjoint docs edited concurrently from
  separate threads, fully instrumented (HM_LOCKDEP=1 + HM_RACEDEP=1).
  The module teardown asserts a clean graph/lockset report — in
  particular NO same-class `doc.emit` nesting (a thread never holds
  two docs' emission domains, and never any OTHER doc's domain across
  a feed append or push) and NO blocking call under `live.engine`
  (the zero-lock-debt gate as a hard failure, not a counter);
- cross-doc re-entry defers: a frontend callback dispatched
  synchronously from one doc's push that edits ANOTHER doc must not
  drag the first domain into the second doc's handler — the work
  replays on the deferred-emission worker;
- emission-domain bookkeeping units (entered_other / held_by_me).
"""

import threading

from hypermerge_tpu.backend import emission

from helpers import wait_until
from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite

_lockdep = lockdep_suite()
_racedep = racedep_suite()


# ---------------------------------------------------------------------------
# emission-domain units


def test_domain_entry_bookkeeping():
    a = emission.EmissionDomain("docA")
    b = emission.EmissionDomain("docB")
    assert not a.held_by_me()
    with a:
        assert a.held_by_me()
        assert emission.entered_ids() == ["docA"]
        # same-doc re-entry is NOT "other": the re-entrant domain
        # recurses (an in-process frontend's on_patch sending the next
        # change of the SAME doc)
        assert not emission.entered_other("docA")
        # a cross-doc call from inside the emission MUST defer
        assert emission.entered_other("docB")
        with a:  # re-entrant
            assert emission.entered_ids() == ["docA", "docA"]
        assert emission.entered_ids() == ["docA"]
    assert not a.held_by_me()
    assert not emission.entered_other("docB")
    del b


def test_defer_runs_off_thread_in_order():
    got = []
    ev = threading.Event()
    for i in range(8):
        emission.defer(lambda i=i: got.append(i))
    emission.defer(ev.set)
    assert ev.wait(10)
    assert got == list(range(8))  # FIFO, one worker
    assert threading.current_thread().name != "hm-emit-defer"


# ---------------------------------------------------------------------------
# the two-writer seeded race (instrumented; teardown asserts clean)


def test_two_writers_disjoint_docs_instrumented():
    """Two threads, two docs, interleaved ack-paced edits with the
    live engine on: every edit lands exactly once, and the module's
    lockdep/racedep teardown proves no cross-doc domain nesting and
    no blocking under the engine lock happened anywhere in the run."""
    from hypermerge_tpu.repo import Repo

    repo = Repo(memory=True)
    try:
        urls = [repo.create({"edits": []}) for _ in range(2)]
        n_edits = 30
        barrier = threading.Barrier(2)

        def writer(w):
            barrier.wait()  # maximize interleaving (seeded start)
            for i in range(n_edits):
                repo.change(
                    urls[w], lambda d, i=i: d["edits"].append(i)
                )

        ts = [
            threading.Thread(target=writer, args=(w,)) for w in (0, 1)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        if repo.back.live is not None:
            repo.back.live.flush_now()
        for url in urls:
            wait_until(
                lambda url=url: list(
                    (repo.doc(url) or {}).get("edits", [])
                )
                == list(range(n_edits))
            )
    finally:
        repo.close()


def test_cross_doc_reentry_defers_not_nests():
    """A subscriber editing doc B from inside doc A's patch dispatch
    (the emitting thread holds A's domain): the edit must land via the
    deferred-emission worker — both docs converge, and the teardown
    asserts no doc.emit -> doc.emit same-class edge was ever taken."""
    from hypermerge_tpu.repo import Repo

    repo = Repo(memory=True)
    try:
        url_a = repo.create({"n": 0})
        url_b = repo.create({"mirror": -1})
        fired = []

        def mirror(state, _index):
            n = state.get("n", 0)
            if n >= 1 and n not in fired:
                fired.append(n)
                # cross-doc re-entry: this thread may be mid-emission
                # for doc A; doc B's handler must defer, not nest
                repo.change(
                    url_b, lambda d, n=n: d.__setitem__("mirror", n)
                )

        h = repo.watch(url_a, mirror)
        for i in range(1, 4):
            repo.change(url_a, lambda d, i=i: d.__setitem__("n", i))
        wait_until(
            lambda: (repo.doc(url_b) or {}).get("mirror") == 3
        )
        h.close()
    finally:
        repo.close()


def test_open_from_patch_callback_defers_ready():
    """A subscriber that OPENS another doc from inside a patch
    dispatch (the emitting thread holds doc A's domain): the Open's
    Ready emission must defer instead of nesting doc B's domain under
    A's — the instrumented module teardown turns any same-class
    `doc.emit` nesting into a hard failure, and two threads
    cross-opening would be an ABBA deadlock."""
    from hypermerge_tpu.repo import Repo

    repo = Repo(memory=True)
    try:
        url_a = repo.create({"n": 0})
        url_b = repo.create({"other": 1})
        repo.close_doc(url_b)  # B's Ready will be re-sent on re-open
        opened = []

        def open_other(state, _index):
            if state.get("n", 0) >= 1 and not opened:
                opened.append(True)
                # cross-doc re-entry: Open -> _send_ready(B) on a
                # thread that may hold A's domain
                repo.watch(
                    url_b,
                    lambda st, _i: opened.append(dict(st or {})),
                )

        h = repo.watch(url_a, open_other)
        repo.change(url_a, lambda d: d.__setitem__("n", 1))
        wait_until(
            lambda: any(
                isinstance(o, dict) and o.get("other") == 1
                for o in opened
            )
        )
        h.close()
    finally:
        repo.close()


def test_send_ready_defers_under_foreign_domain(monkeypatch):
    """Deterministic pin of the _send_ready escape hatch: invoked on
    a thread holding ANOTHER doc's emission domain (the Open-inside-
    patch-dispatch shape), the Ready must park on the deferred-
    emission worker instead of nesting doc B's domain under doc A's
    (same-class order violation; ABBA with two cross-opening
    threads)."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import url_to_id

    repo = Repo(memory=True)
    try:
        url_a = repo.create({"n": 0})
        url_b = repo.create({"other": 1})
        back = repo.back
        doc_a = back.docs[url_to_id(url_a)]
        doc_b = back.docs[url_to_id(url_b)]
        deferred = []
        monkeypatch.setattr(
            emission, "defer", lambda fn: deferred.append(fn)
        )
        with doc_a.emission:
            back._send_ready(doc_b)
            assert deferred, "Ready nested B's domain under A's"
            assert not doc_b.emission.held_by_me()
        deferred[0]()  # the worker's replay: clean thread, no domains
    finally:
        repo.close()
