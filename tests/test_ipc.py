"""Frontend/backend split across REAL processes (the reference's worker
seam, README.md:160-184): a RepoFrontend in this process drives a
RepoBackend subprocess over the unix-socket message pump."""

import os
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}


def test_frontend_drives_backend_subprocess(tmp_path):
    sock = tempfile.mktemp(suffix=".sock")
    repo_dir = str(tmp_path / "repo")
    proc = subprocess.Popen(
        [sys.executable, "-m", "hypermerge_tpu.net.ipc", repo_dir, sock],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=ENV,
        cwd=REPO_ROOT,
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline and not os.path.exists(sock):
            time.sleep(0.05)
        if not os.path.exists(sock):
            proc.kill()  # before stderr.read(): a live process means
            # read() blocks on an open pipe forever
            raise AssertionError(proc.stderr.read())

        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        states = []
        url = front.create({"title": "split"})
        h = front.watch(url, lambda d, i: states.append(d))
        front.change(url, lambda d: d.__setitem__("n", 7))

        # reads cross the process boundary (Ready/Patch come back async)
        deadline = time.time() + 60
        val = None
        while time.time() < deadline:
            val = h.value()
            if val and val.get("n") == 7 and val.get("title"):
                break
            time.sleep(0.05)
        assert val == {"title": "split", "n": 7}, val
        assert states, "watch callbacks never fired across the boundary"
        h.close()
        close()

        # durability: the BACKEND process owned the storage — a fresh
        # in-process repo over the same dir sees the doc
        deadline = time.time() + 30
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.05)
        from hypermerge_tpu.repo import Repo

        repo = Repo(path=repo_dir)
        assert repo.doc(url)["n"] == 7
        repo.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        if os.path.exists(sock):
            os.remove(sock)
