"""Frontend/backend split across REAL processes (the reference's worker
seam, README.md:160-184): a RepoFrontend in this process drives a
RepoBackend subprocess over the unix-socket message pump.

CI-scale port of the round-4 soak (VERDICT r5 item 8) covering the
three race classes it shook out — stale Ready clobbering write-mode
docs, lazy docs swallowing RemotePatches, duplicate-ActorId seq resets
— plus backend kill/restart durability and a 3-backend TCP relay whose
networking lives entirely in the daemon processes."""

import os
import subprocess
import sys
import tempfile
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}


def _start_backend(repo_arg: str, *extra, env_extra=None):
    """Spawn a backend daemon; returns (proc, sock_path, swarm_addr)."""
    sock = tempfile.mktemp(suffix=".sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "hypermerge_tpu.net.ipc", repo_arg, sock,
         *extra],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env={**ENV, **(env_extra or {})},
        cwd=REPO_ROOT,
    )
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(sock):
        if proc.poll() is not None:
            raise AssertionError(proc.stderr.read())
        time.sleep(0.05)
    if not os.path.exists(sock):
        proc.kill()
        raise AssertionError(proc.stderr.read())
    addr = None
    if "--listen" in extra:
        line = proc.stdout.readline()  # "backend ready on ..."
        while "swarm listening on" not in line:
            line = proc.stdout.readline()
            assert line, "daemon exited before printing swarm address"
        host, _, port = line.strip().rpartition(" ")[2].rpartition(":")
        addr = f"{host}:{port}"
    return proc, sock, addr


def _stop(proc, sock):
    if proc.poll() is None:
        proc.kill()
    proc.wait(timeout=10)
    if os.path.exists(sock):
        os.remove(sock)


def _val(h):
    """Handle.value() without the raise-on-timeout convenience."""
    try:
        return h.value(timeout=0.2)
    except TimeoutError:
        return None


def _wait(fn, timeout=60, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"cross-process wait timed out: {fn}")


def test_frontend_drives_backend_subprocess(tmp_path):
    repo_dir = str(tmp_path / "repo")
    proc, sock, _ = _start_backend(repo_dir)
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        states = []
        url = front.create({"title": "split"})
        h = front.watch(url, lambda d, i: states.append(d))
        front.change(url, lambda d: d.__setitem__("n", 7))

        # reads cross the process boundary (Ready/Patch come back async)
        _wait(lambda: (_val(h) or {}).get("n") == 7)
        assert h.value() == {"title": "split", "n": 7}
        assert states, "watch callbacks never fired across the boundary"

        # durability gate BEFORE teardown: the handle echo alone can be
        # satisfied while the Change message is still in flight to the
        # backend; a meta round-trip on the same ordered channel proves
        # the backend applied (and therefore persisted) both changes
        def backend_history():
            got = []
            front.meta(url, got.append)
            _wait(lambda: got, timeout=10)
            return ((got[0] or {}).get("history")) or 0

        _wait(lambda: backend_history() >= 2, timeout=30)
        h.close()
        close()

        # durability: the BACKEND process owned the storage — a fresh
        # in-process repo over the same dir sees the doc
        _wait(lambda: proc.poll() is not None, timeout=30)
        from hypermerge_tpu.repo import Repo

        repo = Repo(path=repo_dir)
        assert repo.doc(url)["n"] == 7
        repo.close()
    finally:
        _stop(proc, sock)


def test_concurrent_edits_across_the_seam(tmp_path):
    """4 threads hammer 2 docs through ONE frontend/backend socket;
    every edit lands exactly once (r4 race classes: patch-echo pacing +
    in-flight serialization under interleaved Ready/Patch traffic)."""
    proc, sock, _ = _start_backend(":memory:")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        urls = [front.create({"edits": []}) for _ in range(2)]
        handles = [front.open(u) for u in urls]
        for h in handles:
            _wait(lambda h=h: _val(h) is not None)
        n_threads, n_edits = 4, 25

        def churn(t):
            for i in range(n_edits):
                front.change(
                    urls[i % 2],
                    lambda d, t=t, i=i: d["edits"].append(t * 1000 + i),
                )

        ts = [
            threading.Thread(target=churn, args=(t,))
            for t in range(n_threads)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        want = n_threads * n_edits

        def total():
            vals = [_val(h) for h in handles]
            return sum(len(v["edits"]) for v in vals if v) == want

        _wait(total)
        # exactly once: no duplicates across both docs
        seen = []
        for h in handles:
            seen.extend(_val(h)["edits"])
        assert len(seen) == want and len(set(seen)) == want
        close()
    finally:
        _stop(proc, sock)


def test_backend_kill_restart_frontend_resumes(tmp_path):
    """kill -9 the backend mid-session; a restarted backend over the
    same dir serves a new frontend the durable state, and continued
    edits extend the SAME actor feed (duplicate-ActorId seq fix,
    commit-class 742f37d) instead of resetting its counter."""
    repo_dir = str(tmp_path / "repo")
    proc, sock, _ = _start_backend(repo_dir)
    from hypermerge_tpu.net.ipc import connect_frontend

    try:
        front, close = connect_frontend(sock)
        url = front.create({"log": []})
        for i in range(5):
            front.change(url, lambda d, i=i: d["log"].append(i))
        h = front.watch(url, lambda d, i: None)
        _wait(lambda: len((_val(h) or {}).get("log", [])) == 5)
        close()
    finally:
        proc.kill()  # hard kill: no orderly backend close
        proc.wait(timeout=10)
        if os.path.exists(sock):
            os.remove(sock)

    proc2, sock2, _ = _start_backend(repo_dir)
    try:
        front2, close2 = connect_frontend(sock2)
        h2 = front2.open(url)
        _wait(lambda: len((_val(h2) or {}).get("log", [])) == 5)
        # resume writing: the reloaded actor feed continues its seq
        for i in range(5, 8):
            front2.change(url, lambda d, i=i: d["log"].append(i))
        _wait(lambda: len((_val(h2) or {}).get("log", [])) == 8)
        assert list(_val(h2)["log"]) == list(range(8))
        close2()
    finally:
        _stop(proc2, sock2)

    # the doubly-restarted state is clean on disk too
    from hypermerge_tpu.repo import Repo

    repo = Repo(path=repo_dir)
    assert list(repo.doc(url)["log"]) == list(range(8))
    repo.close()


def test_three_backend_tcp_relay_through_ipc_frontends(tmp_path):
    """A<->B<->C line of backend DAEMONS (swarm lives in the daemons,
    frontends only speak the unix socket): a doc created via A's
    frontend reaches C's through the relay, and edits from both ends
    converge everywhere exactly once."""
    pa, sa, addr_a = _start_backend(":memory:", "--listen")
    pb, sb, addr_b = _start_backend(
        ":memory:", "--listen", "--connect", addr_a
    )
    pc, sc, _ = _start_backend(":memory:", "--connect", addr_b)
    from hypermerge_tpu.net.ipc import connect_frontend

    fronts = []
    try:
        for sock in (sa, sb, sc):
            front, close = connect_frontend(sock)
            fronts.append((front, close))
        fa, fb, fc = (f for f, _ in fronts)
        url = fa.create({"edits": []})
        ha = fa.open(url)
        fb.open(url)  # the middle repo replicates + RE-SERVES the doc
        hc = fc.open(url)
        _wait(lambda: _val(hc) is not None, timeout=90)
        for i in range(10):
            fa.change(url, lambda d, i=i: d["edits"].append(i))
        for i in range(10, 15):
            fc.change(url, lambda d, i=i: d["edits"].append(i))

        def converged():
            va, vc = _val(ha), _val(hc)
            return (
                va and vc
                and sorted(va["edits"]) == list(range(15))
                and sorted(vc["edits"]) == list(range(15))
            )

        _wait(converged, timeout=90)
    finally:
        for front, close in fronts:
            try:
                close()
            except Exception:
                pass
        _stop(pa, sa)
        _stop(pb, sb)
        _stop(pc, sc)


def test_probe_connection_does_not_kill_daemon(tmp_path):
    """A stray socket touch (port scanner, health check) that never
    completes the handshake must leave the live backend untouched —
    the real frontend attaches afterwards and everything works."""
    import socket as socketmod

    proc, sock, _ = _start_backend(":memory:")
    try:
        for _ in range(3):  # probes: connect and slam shut
            s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
            for attempt in range(50):
                try:
                    s.connect(sock)
                    break
                except BlockingIOError:
                    # backlog momentarily full on a loaded box — the
                    # scenario under test is a probe that CONNECTS then
                    # slams shut, so wait for a slot
                    time.sleep(0.05)
            s.close()
            time.sleep(0.05)
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        url = front.create({"alive": True})
        h = front.open(url)
        _wait(lambda: (_val(h) or {}).get("alive") is True)
        close()
    finally:
        _stop(proc, sock)


def test_noop_change_does_not_strand_queue(tmp_path):
    """Cross-process echo pacing: a change fn producing NO ops must not
    wedge the queued-change drain (ADVICE r4 low: doc_frontend queue
    stranding)."""
    proc, sock, _ = _start_backend(":memory:")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        url = front.create({"n": 0})
        h = front.open(url)
        _wait(lambda: h.value() is not None)
        front.change(url, lambda d: None)  # no ops
        front.change(url, lambda d: d.__setitem__("n", 1))
        front.change(url, lambda d: None)  # no ops again
        front.change(url, lambda d: d.__setitem__("n", 2))
        _wait(lambda: (_val(h) or {}).get("n") == 2)
        close()
    finally:
        _stop(proc, sock)


def test_reopen_same_doc_while_backend_alive(tmp_path):
    """Close + reopen a handle on a live backend: the second open gets
    a fresh Ready with current state (stale-Ready ordering, commit-class
    c20c2cb) and stays live for further patches."""
    proc, sock, _ = _start_backend(":memory:")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        url = front.create({"v": 1})
        h1 = front.open(url)
        _wait(lambda: (_val(h1) or {}).get("v") == 1)
        h1.close()
        front.change(url, lambda d: d.__setitem__("v", 2))
        h2 = front.open(url)
        _wait(lambda: (_val(h2) or {}).get("v") == 2)
        front.change(url, lambda d: d.__setitem__("v", 3))
        _wait(lambda: (_val(h2) or {}).get("v") == 3)
        close()
    finally:
        _stop(proc, sock)


def test_persistent_backend_reused_across_frontend_cycles(tmp_path):
    """Non-once mode: ONE live backend serves successive frontends —
    state written by frontend A is visible to frontend B without a
    backend rebuild (a :memory: repo would lose everything otherwise),
    and nothing piles up per cycle."""
    import gc

    from hypermerge_tpu.backend.repo_backend import RepoBackend
    from hypermerge_tpu.net.ipc import connect_frontend, serve_backend

    sock = str(tmp_path / "backend.sock")
    server = threading.Thread(
        target=serve_backend,
        kwargs=dict(sock_path=sock, memory=True, once=False),
        daemon=True,
    )
    server.start()
    deadline = time.time() + 30
    while time.time() < deadline and not os.path.exists(sock):
        time.sleep(0.02)
    assert os.path.exists(sock)

    front_a, close_a = connect_frontend(sock)
    url = front_a.create({"cycle": 1})
    ha = front_a.open(url)
    _wait(lambda: (_val(ha) or {}).get("cycle") == 1)
    close_a()
    time.sleep(0.2)  # let the server notice the close

    backends_before = sum(
        isinstance(o, RepoBackend) for o in gc.get_objects()
    )
    front_b, close_b = connect_frontend(sock)
    # the SAME backend answers: frontend A's doc is still there
    hb = front_b.open(url)
    _wait(lambda: (_val(hb) or {}).get("cycle") == 1)
    close_b()
    time.sleep(0.2)
    backends_after = sum(
        isinstance(o, RepoBackend) for o in gc.get_objects()
    )
    assert backends_after <= backends_before, (
        "backends piled up across frontend cycles"
    )


def test_reply_fence_drops_cross_session_replies():
    """Persist-mode swap: a Reply produced by a PREVIOUS frontend's
    in-flight handler must never reach the next frontend (whose queryId
    counter restarts at the same small integers)."""
    from hypermerge_tpu.net.ipc import ReplyFence

    fence = ReplyFence()
    ep1 = fence.advance()  # frontend #1 attaches
    q1 = fence.inbound({"type": "Query", "queryId": 1, "query": {}}, ep1)
    assert q1["queryId"] == [1, 1]
    # frontend #1's reply, delivered while #1 is still attached
    gate1_epoch = fence.epoch
    reply = {"type": "Reply", "queryId": q1["queryId"], "payload": "a"}
    out = fence.outbound(gate1_epoch, dict(reply))
    assert out == {"type": "Reply", "queryId": 1, "payload": "a"}

    ep2 = fence.advance()  # swap: frontend #2 attaches
    gate2_epoch = fence.epoch
    # the late in-flight reply from #1 dies at #2's gate
    assert fence.outbound(gate2_epoch, dict(reply)) is None
    # a STALE reader thread of connection #1 dispatching a frame after
    # the swap tags with its own bound epoch — its reply dies too
    q_stale = fence.inbound(
        {"type": "Query", "queryId": 2, "query": {}}, ep1
    )
    assert q_stale["queryId"] == [1, 2]
    assert (
        fence.outbound(
            gate2_epoch,
            {"type": "Reply", "queryId": q_stale["queryId"], "payload": "x"},
        )
        is None
    )
    # #2's own query round-trips with its raw id restored
    q2 = fence.inbound({"type": "Query", "queryId": 1, "query": {}}, ep2)
    assert q2["queryId"] == [2, 1]
    out2 = fence.outbound(
        gate2_epoch, {"type": "Reply", "queryId": q2["queryId"], "payload": "b"}
    )
    assert out2["queryId"] == 1 and out2["payload"] == "b"
    # non-Reply traffic passes untouched
    patch = {"type": "Patch", "id": "d", "patch": {}, "history": 1}
    assert fence.outbound(gate2_epoch, patch) == patch


def test_persist_mode_queries_survive_frontend_swaps(tmp_path):
    """Persist mode end-to-end: each successive frontend's queries
    resolve correctly through the epoch fence (ids tagged inbound,
    untagged on the reply), even though every frontend restarts its
    queryId counter and the previous one disconnected with queries
    possibly still in flight."""
    from hypermerge_tpu.net.ipc import connect_frontend, serve_backend

    sock = str(tmp_path / "backend.sock")
    server = threading.Thread(
        target=serve_backend,
        kwargs=dict(sock_path=sock, memory=True, once=False),
        daemon=True,
    )
    server.start()
    _wait(lambda: os.path.exists(sock), timeout=30)

    front_a, close_a = connect_frontend(sock)
    url = front_a.create({"gen": 1})
    ha = front_a.open(url)
    _wait(lambda: (_val(ha) or {}).get("gen") == 1)
    # fire a query and disconnect WITHOUT waiting for the reply: its
    # handler may still be in flight across the swap
    front_a.meta(url, lambda _m: None)
    close_a()
    time.sleep(0.2)

    for cycle in range(2, 4):
        front, close = connect_frontend(sock)
        h = front.open(url)
        _wait(lambda: (_val(h) or {}).get("gen") == 1)
        got = []
        front.meta(url, got.append)
        _wait(lambda: got, timeout=15)
        # the reply matches THIS session's query (same doc metadata),
        # not a stale echo delivered across the swap
        assert got[0] and got[0].get("type") == "Document", got
        got2 = []
        front.materialize(url, 1, got2.append)
        _wait(lambda: got2, timeout=15)
        assert got2[0] is not None
        close()
        time.sleep(0.2)


# ---------------------------------------------------------------------------
# hub mode: MANY concurrent frontends, one daemon (the write-plane
# process topology bench config_writers measures)


def test_hub_many_writers_disjoint_docs(tmp_path):
    """4 frontend processes' worth of connections (in-process here, 4
    sockets) each create + edit their OWN doc against one --hub daemon:
    every writer's acked edits land, and interest routing keeps each
    frontend's state correct while all four streams interleave on the
    daemon."""
    proc, sock, _ = _start_backend(str(tmp_path / "repo"), "--hub")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        fronts = [connect_frontend(sock) for _ in range(4)]
        urls, handles = [], []
        for w, (front, _close) in enumerate(fronts):
            url = front.create({"w": w, "edits": []})
            urls.append(url)
            h = front.open(url)
            _wait(lambda h=h: _val(h) is not None)
            handles.append(h)
        n_edits = 15

        def churn(w):
            front = fronts[w][0]
            for i in range(n_edits):
                front.change(
                    urls[w], lambda d, i=i: d["edits"].append(i)
                )

        ts = [
            threading.Thread(target=churn, args=(w,)) for w in range(4)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        for w, h in enumerate(handles):
            _wait(
                lambda h=h: len((_val(h) or {}).get("edits", []))
                == n_edits
            )
            v = _val(h)
            # the writer's own doc: its edits, in its order, and the
            # identity field no other writer's traffic can have touched
            assert v["w"] == w
            assert list(v["edits"]) == list(range(n_edits))
        for _front, close in fronts:
            close()
    finally:
        _stop(proc, sock)


def test_hub_reply_routing_per_connection(tmp_path):
    """Every hub frontend restarts its queryId counter at the same
    small integers; concurrent Materialize/Metadata queries from two
    connections must each resolve on their OWN connection (the
    per-connection tag the hub adds inbound and strips outbound)."""
    proc, sock, _ = _start_backend(str(tmp_path / "repo"), "--hub")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        fa, close_a = connect_frontend(sock)
        fb, close_b = connect_frontend(sock)
        ua = fa.create({"who": "a"})
        ub = fb.create({"who": "b"})
        ha, hb = fa.open(ua), fb.open(ub)
        _wait(lambda: _val(ha) is not None and _val(hb) is not None)
        got_a, got_b = [], []
        for _ in range(5):
            fa.materialize(ua, 1, got_a.append)
            fb.materialize(ub, 1, got_b.append)
        _wait(lambda: len(got_a) == 5 and len(got_b) == 5, timeout=30)
        assert all(g and g.get("who") == "a" for g in got_a), got_a
        assert all(g and g.get("who") == "b" for g in got_b), got_b
        close_a()
        close_b()
    finally:
        _stop(proc, sock)


def test_hub_shared_doc_watcher_sees_writer_patches(tmp_path):
    """A hub frontend WATCHING a doc another connection writes receives
    every patch (interest routing is per doc, not per creator). The
    watcher here never writes, so it stays in read mode on the actor
    the backend granted it (None); test_hub_many_writers_one_hot_doc
    covers the MANY-writer case where every connection mints its own
    actor."""
    proc, sock, _ = _start_backend(str(tmp_path / "repo"), "--hub")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        fa, close_a = connect_frontend(sock)
        fb, close_b = connect_frontend(sock)
        url = fa.create({"edits": []})
        ha = fa.open(url)
        _wait(lambda: "edits" in (_val(ha) or {}))
        hb = fb.open(url)
        # fb may open before fa's init echo reaches the backend — its
        # Ready snapshot is legitimately blank then; the init arrives
        # as a routed Patch (fb is interested now)
        _wait(lambda: "edits" in (_val(hb) or {}))
        for i in range(5):
            fa.change(url, lambda d, i=i: d["edits"].append(i))
        for h in (ha, hb):  # the watcher converges with the writer
            _wait(
                lambda h=h: list(
                    (_val(h) or {}).get("edits", [])
                ) == list(range(5))
            )
        close_a()
        close_b()
    finally:
        _stop(proc, sock)


def test_hub_interest_table_drops_empty_entries():
    """The hub's doc-interest table tracks LIVE interest: Close and
    connection detach must delete a doc's entry once its last watcher
    leaves (a long-lived daemon would otherwise grow one entry per
    doc id ever named, forever)."""
    from types import SimpleNamespace

    from hypermerge_tpu.net.ipc import _FrontendHub

    class _FakeDuplex:
        def on_close(self, cb):
            self.close_cb = cb

        def on_message(self, cb):
            self.msg_cb = cb

    hub = _FrontendHub(SimpleNamespace(receive=lambda _m: None))
    d1, d2 = _FakeDuplex(), _FakeDuplex()
    hub.attach(d1)
    hub.attach(d2)
    d1.msg_cb({"type": "Open", "id": "docX"})
    d2.msg_cb({"type": "Open", "id": "docX"})
    d1.msg_cb({"type": "Open", "id": "docY"})
    assert set(hub._interest) == {"docX", "docY"}
    d1.msg_cb({"type": "Close", "id": "docY"})  # last watcher closes
    assert set(hub._interest) == {"docX"}
    d1.close_cb()  # detach: docX keeps d2's interest
    assert set(hub._interest) == {"docX"}
    d2.close_cb()  # last watcher detaches: table empties
    assert hub._interest == {}
    assert hub._conns == {}


def test_hub_many_writers_one_hot_doc(tmp_path):
    """MANY writers, ONE hot doc: 4 connections all edit the same doc
    through one hub daemon. The hub tags each connection's Create/Open/
    NeedsActorId with its connection key, the backend mints one actor
    PER CONNECTION (so concurrent writers never collide on a shared
    seq counter), and after the herd drains every connection's view is
    bit-identical canonical JSON."""
    import json as _json

    proc, sock, _ = _start_backend(str(tmp_path / "repo"), "--hub")
    try:
        from hypermerge_tpu.net.ipc import connect_frontend

        n_writers, n_edits = 4, 8
        fronts = [connect_frontend(sock) for _ in range(n_writers)]
        url = fronts[0][0].create({"edits": {}})
        handles = []
        for front, _close in fronts:
            h = front.open(url)
            # a blank pre-init snapshot is legal (the init change may
            # still be in flight); wait for the init patch to land
            _wait(lambda h=h: "edits" in (_val(h) or {}))
            handles.append(h)

        def churn(w):
            front = fronts[w][0]
            for i in range(n_edits):
                front.change(
                    url,
                    lambda d, w=w, i=i: d["edits"].__setitem__(
                        f"{w}.{i}", i
                    ),
                )

        ts = [
            threading.Thread(target=churn, args=(w,))
            for w in range(n_writers)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        total = n_writers * n_edits
        for h in handles:  # every writer converges on the full herd
            _wait(
                lambda h=h: len((_val(h) or {}).get("edits", {}))
                == total,
                timeout=90,
            )
        digests = {
            _json.dumps(_val(h), sort_keys=True) for h in handles
        }
        assert len(digests) == 1, "writers diverged on the hot doc"
        for _front, close in fronts:
            close()
    finally:
        _stop(proc, sock)


def test_hub_sharded_workers_route_and_merge_telemetry(tmp_path):
    """HM_WORKERS=2 grows the hub into a router over per-doc-range
    worker PROCESSES: docs land on the worker that owns their shard,
    edits round-trip through the worker's own engine, and a Telemetry
    query fans out to every worker and merges into one fleet payload
    whose `workers` block carries the live per-worker split."""
    proc, sock, _ = _start_backend(
        str(tmp_path / "repo"), "--hub", env_extra={"HM_WORKERS": "2"}
    )
    try:
        assert "ready" in proc.stdout.readline()
        pids = {}
        for _ in range(2):  # "worker <i> pid <pid>" per spawned worker
            parts = proc.stdout.readline().split()
            assert parts[0] == "worker" and parts[2] == "pid", parts
            pids[parts[1]] = int(parts[3])
        assert set(pids) == {"0", "1"}

        from hypermerge_tpu.net.ipc import _shard_of, connect_frontend

        front, close = connect_frontend(sock)
        urls, shards = [], set()
        while len(shards) < 2 or len(urls) < 4:  # cover BOTH shards
            url = front.create({"edits": []})
            urls.append(url)
            shards.add(_shard_of(url[len("hypermerge:/"):], 2))
        handles = [front.open(u) for u in urls]
        for h in handles:
            _wait(lambda h=h: "edits" in (_val(h) or {}))
        for u in urls:
            front.change(u, lambda d: d["edits"].append(1))
        for h in handles:  # edits round-trip through the owning worker
            _wait(lambda h=h: (_val(h) or {}).get("edits") == [1])

        got = []
        front.telemetry(got.append)
        _wait(lambda: got, timeout=15)
        workers = got[0].get("workers")
        assert set(workers) == {"0", "1"}, workers
        for i, w in workers.items():
            assert w["alive"], f"worker {i} missed the telemetry fanout"
            assert w["pid"] == pids[i]
            assert w["respawns"] == 0
        # the per-worker split is mirrored into counters for
        # counter-only consumers (tools/top.py groups, the prom dump)
        assert "workers.0.edits" in got[0]["counters"]
        close()
    finally:
        _stop(proc, sock)
