"""Corpus writer validity: directly-written disk state must be exactly
what the product would persist, and must open through the product's
fast (no-replay) paths."""

import json

from hypermerge_tpu.crdt.change import Change
from hypermerge_tpu.crdt.opset import OpSet
from hypermerge_tpu.ops.corpus import make_corpus
from hypermerge_tpu.ops.synth import synth_changes
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils.ids import validate_doc_url
from hypermerge_tpu.utils.json_buffer import bufferify

from helpers import plainify


def _ground_truth(doc_id: str, n_ops: int, opc: int, seed: int):
    """Host OpSet replay of the template history re-actored to doc_id."""
    tpl = synth_changes(n_ops, n_actors=1, ops_per_change=opc, seed=seed)
    changes = [
        Change.from_json(
            json.loads(
                bufferify(c.to_json())
                .decode("utf-8")
                .replace("actor00", doc_id)
            )
        )
        for c in tpl
    ]
    ops = OpSet()
    ops.apply_changes(changes)
    return plainify(ops.materialize())


def test_corpus_opens_to_replayed_state(tmp_path):
    urls = make_corpus(
        str(tmp_path), 3, 48, ops_per_change=8, distinct=2, seed=5
    )
    repo = Repo(path=str(tmp_path))
    for i, url in enumerate(urls):
        doc_id = validate_doc_url(url)
        want = _ground_truth(doc_id, 48, 8, 5 + (i % 2))
        assert plainify(repo.doc(url)) == want
        # sidecar-backed open: no host OpSet replay happened
        assert repo.back.docs[doc_id].opset is None
    repo.close()


def test_corpus_doc_replicates_to_second_repo(tmp_path):
    """End-to-end: a corpus doc (signed feeds on disk) replicates from a
    disk repo to a fresh peer over encrypted TCP with capability checks
    and chunked verified backfill — the whole trust stack at once."""
    import time

    from hypermerge_tpu.net.tcp import TcpSwarm

    src_dir = str(tmp_path / "src")
    urls = make_corpus(src_dir, 2, 48, ops_per_change=8, distinct=1, seed=3)
    ra = Repo(path=src_dir)
    ra.open_many(urls)  # feeds registered + announced
    rb = Repo(path=str(tmp_path / "dst"))
    sa, sb = TcpSwarm(), TcpSwarm()
    ra.set_swarm(sa)
    rb.set_swarm(sb)
    sb.connect(sa.address)

    url = urls[0]
    doc_id = validate_doc_url(url)
    h = rb.open(url)
    deadline = time.time() + 60
    while time.time() < deadline:
        doc = rb.back.docs.get(doc_id)
        if doc is not None and doc._announced:
            break
        time.sleep(0.05)
    want = _ground_truth(doc_id, 48, 8, 3)
    assert plainify(h.value()) == want
    # the replica can audit what it stored
    assert rb.back.feeds.open_feed(doc_id).audit()
    ra.close()
    rb.close()
    sa.destroy()
    sb.destroy()


def test_corpus_bulk_open_and_block_log_agree(tmp_path):
    urls = make_corpus(
        str(tmp_path), 4, 32, ops_per_change=8, distinct=2, seed=9
    )
    repo = Repo(path=str(tmp_path))
    handles = repo.open_many(urls)
    for i, (url, h) in enumerate(zip(urls, handles)):
        doc_id = validate_doc_url(url)
        want = _ground_truth(doc_id, 32, 8, 9 + (i % 2))
        assert plainify(h.value()) == want
        # the block log (not just the sidecar) holds the same changes:
        # force a host replay from decoded blocks
        actor = repo.back.actors[doc_id]
        changes = actor.changes_in_window(0, float("inf"))
        ops = OpSet()
        ops.apply_changes(changes)
        assert plainify(ops.materialize()) == want
    # an incremental change on a corpus doc still works end-to-end
    handles[0].change(lambda d: d.__setitem__("added", 1))
    assert plainify(handles[0].value())["added"] == 1
    repo.close()
