"""Native C++ bulk pack (hm_pack_prefix) vs the numpy twin.

The cold-open pack stage has two implementations: the C++ batch entry
point that emits the padded column planes straight from the feeds'
checkpoint planes (native/src/hm_native.cpp), and the numpy scatter in
ops/columnar.py that remains both the fallback and the correctness
reference. These tests pin them BIT-identical — same values, same wire
dtypes — over fuzzed histories covering the prefix-single fast path,
every value-kind lane, empty/padded docs, and (through the general
sorted-composite path, which the native entry must leave untouched)
multi-actor tie-break lanes."""

import random

import numpy as np
import pytest

from helpers import Site, random_mutation, sync
from hypermerge_tpu import native
from hypermerge_tpu.models import Counter, Text
from hypermerge_tpu.ops import columnar
from hypermerge_tpu.ops.columnar import COLUMNS, pack_docs_columns
from hypermerge_tpu.storage.colcache import (
    FeedColumnCache,
    FileColumnStorageV2,
    MemoryColumnStorage,
)

INF = float("inf")

needs_pack = pytest.mark.skipif(
    native.pack_lib() is None, reason="native pack layer unavailable"
)


def _single_writer_history(seed, n_mut=30):
    r = random.Random(seed)
    site = Site(f"actor{seed % 7:02d}")
    for _ in range(n_mut):
        random_mutation(site, r)
    # widen value coverage: floats, bools, bigints, >int16 inline ints
    site.change(lambda d: d.__setitem__("f", 3.25 + seed))
    site.change(lambda d: d.__setitem__("b", True))
    site.change(lambda d: d.__setitem__("big", 2**40 + seed))
    site.change(lambda d: d.__setitem__("wide", 2**20 + seed))
    return list(site.opset.history)


def _plane_cache(tmp_path, name, history):
    """A compacted (v3 checkpoint) cache: plane-backed with plane_meta,
    i.e. exactly what a bulk cold open hands the pack."""
    path = str(tmp_path / name)
    writer = history[0].actor
    cc = FeedColumnCache(FileColumnStorageV2(path), writer=writer)
    for c in sorted(history, key=lambda c: (c.actor, c.seq)):
        cc.append_change(c)
    cc.compact()
    cc.close()
    return FeedColumnCache(FileColumnStorageV2(path), writer=writer)


def _assert_batches_identical(a, b):
    for name in COLUMNS:
        assert a.cols[name].dtype == b.cols[name].dtype, name
        assert np.array_equal(a.cols[name], b.cols[name]), name
    assert a.psrc.dtype == b.psrc.dtype
    assert np.array_equal(a.psrc, b.psrc)
    assert np.array_equal(a.ptgt, b.ptgt)
    assert np.array_equal(a.n_ops, b.n_ops)
    assert np.array_equal(a.doc_actors, b.doc_actors)
    assert a.actors == b.actors and a.keys == b.keys
    assert a.strings == b.strings
    assert a.floats == b.floats and a.bigints == b.bigints
    if a.slot is not None or b.slot is not None:
        assert np.array_equal(a.slot, b.slot)


def _pack_both(monkeypatch, specs, counted=True, **kw):
    """(native_batch, numpy_batch, native_call_count)."""
    calls = []
    orig = columnar._native_pack_prefix

    def spy(*a, **k):
        out = orig(*a, **k)
        calls.append(bool(out))
        return out

    monkeypatch.setattr(columnar, "_native_pack_prefix", spy)
    monkeypatch.setenv("HM_NATIVE_PACK", "1")
    b_native = pack_docs_columns(specs, **kw)
    monkeypatch.setenv("HM_NATIVE_PACK", "0")
    b_numpy = pack_docs_columns(specs, **kw)
    if counted:
        assert calls and all(calls), "native entry point was not used"
    return b_native, b_numpy


@needs_pack
def test_prefix_single_fuzz_bit_identical(tmp_path, monkeypatch):
    """The dominant cold-open shape: single-writer plane-backed feeds,
    whole-prefix windows — the native path must be exercised and agree
    bit-for-bit (values AND dtypes) with the numpy twin."""
    caches = [
        _plane_cache(tmp_path, f"f{seed}", _single_writer_history(seed))
        for seed in range(6)
    ]
    specs = [[(cc.columns(), 0, INF)] for cc in caches]
    assert all(s[0][0].planes is not None for s in specs)
    assert all(s[0][0].plane_meta is not None for s in specs)
    b_native, b_numpy = _pack_both(monkeypatch, specs)
    _assert_batches_identical(b_native, b_numpy)
    for cc in caches:
        cc.close()


@needs_pack
def test_prefix_single_padded_and_partial_windows(tmp_path, monkeypatch):
    """Doc-axis padding (slab buckets) and partial end_seq windows."""
    caches = [
        _plane_cache(tmp_path, f"p{seed}", _single_writer_history(seed))
        for seed in (11, 12)
    ]
    fcs = [cc.columns() for cc in caches]
    half = max(1, fcs[1].n_changes // 2)
    specs = [[(fcs[0], 0, INF)], [(fcs[1], 0, half)]]
    b_native, b_numpy = _pack_both(
        monkeypatch, specs, n_docs=8, n_rows=512, n_pred=128
    )
    assert b_native.n_docs == 8
    _assert_batches_identical(b_native, b_numpy)
    for cc in caches:
        cc.close()


@needs_pack
def test_shared_feed_and_empty_doc(tmp_path, monkeypatch):
    """Two docs sharing one feed object, plus a zero-change window."""
    cc = _plane_cache(tmp_path, "s0", _single_writer_history(3))
    fc = cc.columns()
    specs = [[(fc, 0, INF)], [(fc, 0, INF)], [(fc, 0, 0)]]
    b_native, b_numpy = _pack_both(monkeypatch, specs)
    assert int(b_native.n_ops[2]) == 0
    _assert_batches_identical(b_native, b_numpy)
    cc.close()


def test_multi_actor_general_path_unchanged(monkeypatch):
    """Multi-actor histories take the general sorted-composite path; the
    native toggle must not change a single bit there either (the fuzz
    corpus of test_bulk_cold_start runs with the toggle's default)."""
    specs = []
    for seed in (21, 22, 23):
        r = random.Random(seed)
        sites = [Site(f"actor{i:02d}") for i in range(3)]
        for _ in range(30):
            random_mutation(r.choice(sites), r)
            if r.random() < 0.3:
                sync(*sites)
        sync(*sites)
        caches = {}
        for c in sorted(
            sites[0].opset.history, key=lambda c: (c.actor, c.seq)
        ):
            cc = caches.setdefault(
                c.actor,
                FeedColumnCache(MemoryColumnStorage(), writer=c.actor),
            )
            cc.append_change(c)
        specs.append([(cc.columns(), 0, INF) for cc in caches.values()])
    b_native, b_numpy = _pack_both(monkeypatch, specs, counted=False)
    _assert_batches_identical(b_native, b_numpy)


@needs_pack
def test_pack_releases_gil(tmp_path, monkeypatch):
    """The hm_pack_prefix binding must DROP the GIL (ctypes.CDLL
    foreign-call semantics) — the streaming slab pipeline's pack
    worker relies on it to overlap packing with sidecar IO. Two
    checks: (1) a Python thread keeps making progress while packs run
    (GIL actually released — meaningful even on one core); (2) with
    >=2 cores, two concurrent packs on DISTINCT output buffers overlap
    in wall time."""
    import os
    import threading
    import time

    from hypermerge_tpu import native
    from hypermerge_tpu.ops.synth import synth_changes

    assert native.pack_drops_gil()
    monkeypatch.setenv("HM_NATIVE_PACK", "1")

    # one sizeable plane-backed feed; packs of 8 whole-prefix windows
    # of it spend their time inside the native batch entry
    history = synth_changes(
        40_000, n_actors=1, ops_per_change=64, text_frac=0.5, seed=9
    )
    cc = _plane_cache(tmp_path, "gil", history)
    fc = cc.columns()
    assert fc.planes is not None

    def one_pack():
        specs = [[(fc, 0, INF)] for _ in range(8)]
        b = pack_docs_columns(specs)
        assert b.n_rows >= 40_000

    one_pack()  # warm the interner memos / allocator

    # -- (1) GIL-progress: a spinner thread must not starve ------------
    stop = [False]
    spins = [0]

    def spinner():
        while not stop[0]:
            spins[0] += 1

    t = threading.Thread(target=spinner, daemon=True)
    t.start()
    time.sleep(0.02)  # let it settle
    spins[0] = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.4:
        one_pack()
    held_spins = spins[0]
    stop[0] = True
    t.join(5)
    # a GIL-holding native call would leave the spinner almost no
    # iterations; released, it runs freely (other core) or timeslices
    assert held_spins > 10_000, (
        f"spinner starved during native packs ({held_spins} iters): "
        "is the pack binding holding the GIL?"
    )

    # -- (2) wall-time overlap of two concurrent packs -----------------
    if (os.cpu_count() or 1) < 2:
        cc.close()
        pytest.skip("single core: wall-time overlap is unmeasurable")

    def packs(n):
        for _ in range(n):
            one_pack()

    # min serial vs min concurrent across attempts: unrelated machine
    # load inflates both, the minima are what the scheduling allows
    best_serial = best_conc = None
    for _attempt in range(5):
        t0 = time.perf_counter()
        packs(6)
        serial = time.perf_counter() - t0
        ts = [
            threading.Thread(target=packs, args=(3,), daemon=True)
            for _ in range(2)
        ]
        t0 = time.perf_counter()
        for th in ts:
            th.start()
        for th in ts:
            th.join(60)
        conc = time.perf_counter() - t0
        best_serial = min(serial, best_serial or serial)
        best_conc = min(conc, best_conc or conc)
        if best_conc < 0.9 * best_serial:
            break
    cc.close()
    ratio = best_conc / max(best_serial, 1e-9)
    if ratio >= 0.9:
        # the spinner above already PROVED the GIL drops; wall-time
        # overlap additionally needs a genuinely idle second core,
        # which a loaded CI box can't promise — don't flake the suite
        pytest.skip(
            f"GIL release proven by spinner, but no idle core to show "
            f"wall overlap (conc/serial={ratio:.2f})"
        )
    # reaching here means the overlap was actually observed (< 0.9);
    # the hard GIL enforcement is the spinner assert above


# ---------------------------------------------------------------------------
# Device pack kernel (ops/pack_kernels.py) — the third twin
# ---------------------------------------------------------------------------


def _pack_device(monkeypatch, specs, counted=True, native_ref=False, **kw):
    """(device_batch, host_batch). The host reference is the numpy twin
    unless native_ref=True; the device kernel must actually have packed
    (spied), not silently fallen through."""
    pytest.importorskip("jax")
    from hypermerge_tpu.ops import pack_kernels

    calls = []
    orig = pack_kernels.device_pack_prefix

    def spy(*a, **k):
        out = orig(*a, **k)
        calls.append(bool(out))
        return out

    monkeypatch.setattr(pack_kernels, "device_pack_prefix", spy)
    monkeypatch.setenv("HM_DEVICE_PACK", "1")
    monkeypatch.setenv("HM_NATIVE_PACK", "0")
    b_dev = pack_docs_columns(specs, **kw)
    monkeypatch.setenv("HM_DEVICE_PACK", "0")
    monkeypatch.setenv("HM_NATIVE_PACK", "1" if native_ref else "0")
    b_host = pack_docs_columns(specs, **kw)
    if counted:
        assert calls and all(calls), "device pack kernel was not used"
    return b_dev, b_host


def test_device_pack_fuzz_bit_identical(tmp_path, monkeypatch):
    """Three-way pin over fuzzed single-writer plane-backed feeds: the
    jitted device kernel must agree bit-for-bit (values AND dtypes) with
    the numpy twin — and, when the native layer is present, with the C++
    batch entry too. One kernel per [Mp, Dp, N] shape, shared through
    the program table."""
    caches = [
        _plane_cache(tmp_path, f"dv{seed}", _single_writer_history(seed))
        for seed in range(6)
    ]
    specs = [[(cc.columns(), 0, INF)] for cc in caches]
    b_dev, b_numpy = _pack_device(monkeypatch, specs)
    _assert_batches_identical(b_dev, b_numpy)
    if native.pack_lib() is not None:
        b_dev2, b_native = _pack_device(
            monkeypatch, specs, native_ref=True
        )
        _assert_batches_identical(b_dev2, b_native)
        _assert_batches_identical(b_dev, b_dev2)
    for cc in caches:
        cc.close()


def test_device_pack_ragged_padded_and_empty(tmp_path, monkeypatch):
    """Doc-axis padding (ragged slab tails), partial end_seq windows, a
    shared feed, and a zero-change (empty-doc) window: the scatter's
    pad slots must come out exactly as the numpy twin's defaults."""
    caches = [
        _plane_cache(tmp_path, f"dr{seed}", _single_writer_history(seed))
        for seed in (31, 32)
    ]
    fcs = [cc.columns() for cc in caches]
    half = max(1, fcs[1].n_changes // 2)
    specs = [
        [(fcs[0], 0, INF)],
        [(fcs[1], 0, half)],
        [(fcs[0], 0, INF)],  # shared feed object
        [(fcs[1], 0, 0)],  # empty-doc window
    ]
    b_dev, b_numpy = _pack_device(
        monkeypatch, specs, n_docs=8, n_rows=512, n_pred=128
    )
    assert b_dev.n_docs == 8
    assert int(b_dev.n_ops[3]) == 0
    _assert_batches_identical(b_dev, b_numpy)
    for cc in caches:
        cc.close()


def test_device_pack_rows_backed_cache(monkeypatch):
    """Pre-compaction caches carry no checkpoint planes; the marshal
    reads the materialized rows matrix instead — same bits."""
    r = random.Random(17)
    site = Site("actor03")
    for _ in range(25):
        random_mutation(site, r)
    history = list(site.opset.history)
    cc = FeedColumnCache(MemoryColumnStorage(), writer=history[0].actor)
    for c in sorted(history, key=lambda c: (c.actor, c.seq)):
        cc.append_change(c)
    fc = cc.columns()
    assert fc.planes is None
    b_dev, b_numpy = _pack_device(monkeypatch, [[(fc, 0, INF)]])
    _assert_batches_identical(b_dev, b_numpy)


def test_device_pack_env_order_both_ways(tmp_path, monkeypatch):
    """HM_DEVICE_PACK and HM_NATIVE_PACK are read independently at call
    time: whichever order they are set in, the device kernel wins the
    routing race and the bits match the host reference."""
    pytest.importorskip("jax")
    cc = _plane_cache(tmp_path, "de0", _single_writer_history(5))
    specs = [[(cc.columns(), 0, INF)]]
    monkeypatch.setenv("HM_NATIVE_PACK", "0")
    monkeypatch.setenv("HM_DEVICE_PACK", "0")
    b_ref = pack_docs_columns(specs)
    for order in (
        ("HM_DEVICE_PACK", "HM_NATIVE_PACK"),
        ("HM_NATIVE_PACK", "HM_DEVICE_PACK"),
    ):
        for var in order:
            monkeypatch.setenv(var, "1")
        b = pack_docs_columns(specs)
        _assert_batches_identical(b, b_ref)
        for var in order:
            monkeypatch.setenv(var, "0")
    cc.close()


def test_device_pack_falls_back_bit_identical(tmp_path, monkeypatch):
    """Any device-kernel failure must fall through to the host twins —
    identical bits, a counted fallback, never an exception out of the
    pack."""
    pytest.importorskip("jax")
    from hypermerge_tpu.ops import pack_kernels

    cc = _plane_cache(tmp_path, "dfb", _single_writer_history(8))
    specs = [[(cc.columns(), 0, INF)]]
    monkeypatch.setenv("HM_NATIVE_PACK", "0")
    b_ref = pack_docs_columns(specs)

    def boom(*a, **k):
        raise RuntimeError("boom-device")

    monkeypatch.setattr(pack_kernels, "_pack_program", boom)
    before = pack_kernels._M_FALLBACKS.value()
    monkeypatch.setenv("HM_DEVICE_PACK", "1")
    b_fb = pack_docs_columns(specs)
    assert pack_kernels._M_FALLBACKS.value() == before + 1
    _assert_batches_identical(b_fb, b_ref)
    cc.close()


@needs_pack
def test_counter_and_text_kinds_roundtrip(tmp_path, monkeypatch):
    """INC lanes (dt/ref) and text inserts through both twins, then a
    full device-twin decode to pin semantic equality too."""
    from hypermerge_tpu.crdt.frontend_state import FrontendDoc
    from hypermerge_tpu.ops.host_kernel import run_batch_host
    from hypermerge_tpu.ops.materialize import DecodedBatch, decode_patch

    site = Site("actor00")
    site.change(lambda d: d.__setitem__("n", Counter(2)))
    site.change(lambda d: d.increment("n", 5))
    site.change(lambda d: d.__setitem__("t", Text("hey")))
    site.change(lambda d: d["t"].insert(3, "!"))
    cc = _plane_cache(tmp_path, "c0", list(site.opset.history))
    specs = [[(cc.columns(), 0, INF)]]
    b_native, b_numpy = _pack_both(monkeypatch, specs)
    _assert_batches_identical(b_native, b_numpy)
    dec = DecodedBatch(b_native, run_batch_host(b_native))
    front = FrontendDoc()
    front.apply_patch(decode_patch(dec, 0))
    from helpers import plainify

    got = plainify(front.materialize())
    assert got["n"] == ("__counter__", 7)
    assert got["t"] == ("__text__", "hey!")
    cc.close()
