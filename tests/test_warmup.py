"""Speculative compile warmup (ops/warmup.py) + the persistent compile
cache (ops/crdt_kernels._enable_persistent_compile_cache).

VERDICT r4 item 2: cold_first_process must not pay the slab-kernel
compile. Two layers guarantee that — warmup precompiles the exact
executables `open_many` will dispatch (first process), the persistent
cache reloads them from disk (every later process). Both are pinned
here:

- the warmup-then-open test asserts the product bulk load compiles
  ZERO new programs after warmup (jit-cache size is flat);
- the two-process test runs the same kernel in two subprocesses sharing
  one cache dir and asserts the second logs a PERSISTENT COMPILATION
  CACHE HIT for the slab kernel and writes nothing new.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def test_bulk_buckets():
    from hypermerge_tpu.ops.warmup import bulk_buckets

    assert bulk_buckets(10240, 4096) == [4096, 2048]
    assert bulk_buckets(4096, 4096) == [4096]
    assert bulk_buckets(8192, 4096) == [4096]
    assert bulk_buckets(100, 4096) == [128]
    assert bulk_buckets(1, 4096) == [1]


def test_warmup_precompiles_bulk_executables(monkeypatch, tmp_path):
    """After warmup_bulk, the real corpus open dispatches only
    already-compiled executables — the jit cache does not grow."""
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "0")
    monkeypatch.setenv("HM_MESH", "0")  # driver bench topology: 1 chip
    monkeypatch.setenv("HM_BULK_SLAB", "16")

    from hypermerge_tpu.ops import crdt_kernels as ck
    from hypermerge_tpu.ops.corpus import make_corpus
    from hypermerge_tpu.ops.warmup import warmup_bulk
    from hypermerge_tpu.repo import Repo

    warmup_bulk(24, 64, slab=16, background=False)
    size_warm = ck.materialize_full_lean_device._cache_size()
    assert size_warm >= 2  # [16, 64] + [8, 64] doc buckets

    urls = make_corpus(str(tmp_path), 24, 64, threads=2)
    repo = Repo(path=str(tmp_path))
    try:
        repo.open_many(urls)
        s = repo.back.fetch_bulk_summaries()
        assert len(s.doc_ids) == 24
        assert repo.back.last_bulk_stats["fallback"] == 0
        assert (
            ck.materialize_full_lean_device._cache_size() == size_warm
        ), "bulk open compiled a program warmup did not precompile"
    finally:
        repo.close()


_SUBPROC = r"""
import os, sys
sys.path.insert(0, {repo!r})
import jax
# this environment pre-registers a TPU platform via sitecustomize and
# overrides JAX_PLATFORMS — force CPU before any backend initializes
# (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
from hypermerge_tpu.ops.warmup import warmup_bulk
warmup_bulk(8, 64, slab=8, background=False)
print("OK")
"""


def _run_cached(cache_dir, debug=False):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        HM_COMPILE_CACHE=str(cache_dir),
        HM_COMPILE_CACHE_FORCE="1",
        HM_DEVICE_MIN_CELLS="0",
        HM_MESH="0",
    )
    env.pop("XLA_FLAGS", None)
    if debug:
        env["JAX_DEBUG_LOG_MODULES"] = "jax._src.compiler"
    return subprocess.run(
        [sys.executable, "-c", _SUBPROC.format(repo=str(REPO))],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )


def test_second_process_hits_persistent_cache(tmp_path):
    cache_dir = tmp_path / "xla"
    p1 = _run_cached(cache_dir)
    assert p1.returncode == 0, p1.stderr
    entries = set(os.listdir(cache_dir))
    kernel_entries = [e for e in entries if "materialize_full_lean" in e]
    assert kernel_entries, f"first process wrote no kernel entry: {entries}"

    p2 = _run_cached(cache_dir, debug=True)
    assert p2.returncode == 0, p2.stderr
    assert (
        "cache hit for 'jit_materialize_full_lean_device"
        in p2.stderr.lower()
    ), p2.stderr[-2000:]
    assert (
        "cache miss for 'jit_materialize_full_lean_device"
        not in p2.stderr.lower()
    )
    assert set(os.listdir(cache_dir)) == entries, "second process compiled"
