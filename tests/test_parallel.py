"""Sharded execution over the 8-device virtual CPU mesh + graft entries."""

import subprocess
import sys

import jax
import numpy as np
import pytest

from hypermerge_tpu.ops.crdt_kernels import run_batch
from hypermerge_tpu.ops.synth import synth_batch, synth_changes
from hypermerge_tpu.parallel.mesh import make_mesh
from hypermerge_tpu.parallel.sharded import (
    sharded_clock_union,
    sharded_dominated,
    sharded_materialize,
    step,
)

# mesh tests need the 8-device virtual CPU backend; under HM_TEST_TPU=1
# (hardware validation runs) only one real chip is visible
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs >= 8 devices (virtual mesh)"
)


def test_mesh_shapes():
    mesh = make_mesh(8, sp=2)
    assert dict(mesh.shape) == {"dp": 4, "sp": 2}
    mesh1 = make_mesh(4)
    assert dict(mesh1.shape) == {"dp": 4, "sp": 1}
    with pytest.raises(ValueError):
        make_mesh(1000)


def test_sharded_materialize_matches_single_device():
    batch = synth_batch(n_docs=16, n_ops=128)
    single = run_batch(batch)
    mesh = make_mesh(8, sp=1)
    sharded = sharded_materialize(batch, mesh)
    for field in ("visible", "map_winner", "elem_live", "rank", "clock"):
        a = np.asarray(getattr(single, field))
        b = np.asarray(getattr(sharded, field))[: batch.n_docs]
        np.testing.assert_array_equal(a, b, err_msg=field)


def test_sharded_materialize_pads_ragged_doc_axis():
    batch = synth_batch(n_docs=13, n_ops=64)  # not divisible by dp
    mesh = make_mesh(8, sp=1)
    out = sharded_materialize(batch, mesh)
    assert out.rank.shape[0] == 16  # padded to dp multiple
    single = run_batch(batch)
    np.testing.assert_array_equal(
        np.asarray(single.rank), np.asarray(out.rank)[:13]
    )


def test_sharded_clock_union_and_dominated():
    mesh = make_mesh(8, sp=2)
    rng = np.random.default_rng(0)
    clocks = rng.integers(0, 100, (64, 16)).astype(np.int32)
    union = np.asarray(sharded_clock_union(clocks, mesh))
    np.testing.assert_array_equal(union, clocks.max(axis=0))

    query = clocks[7]
    dom = np.asarray(sharded_dominated(clocks, query, mesh))
    np.testing.assert_array_equal(dom, np.all(clocks <= query, axis=-1))


def test_full_step():
    batch = synth_batch(n_docs=8, n_ops=64)
    mesh = make_mesh(8, sp=2)
    out, union = step(batch, mesh)
    assert union.shape[-1] == len(batch.actors)


def test_synth_changes_replay_host():
    """The Change-object form of the synthetic workload is causally valid
    and replays fully on the host OpSet."""
    from hypermerge_tpu.crdt.opset import OpSet

    changes = synth_changes(200, seed=3)
    opset = OpSet()
    opset.apply_changes(changes)
    assert not opset._pending
    doc = opset.materialize()
    assert "t" in doc and len(str(doc["t"])) > 0


def test_synth_columns_equal_synth_changes_on_device():
    """Both generator forms produce the same materialized state."""
    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.materialize import (
        DecodedBatch,
        materialize_docs,
    )
    from hypermerge_tpu.crdt.opset import OpSet
    from helpers import plainify

    changes = synth_changes(150, seed=5)
    opset = OpSet()
    opset.apply_changes(changes)
    dec = DecodedBatch(*_run(pack_docs([changes])))
    docs = materialize_docs(dec)
    assert plainify(docs[0]) == plainify(opset.materialize())


def _run(batch):
    return batch, run_batch(batch)


def test_graft_entry_single_chip():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.rank.shape[0] == 8


def test_graft_dryrun_multichip(monkeypatch):
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    # small corpus in CI; the driver runs the slab-scale default
    monkeypatch.setenv("HM_DRYRUN_DOCS", "64")
    monkeypatch.setenv("HM_DRYRUN_OPS", "96")
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")  # force device slabs
    g.dryrun_multichip(8)
    g.dryrun_multichip(4)
