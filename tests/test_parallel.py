"""Sharded execution over the 8-device virtual CPU mesh + graft entries."""

import subprocess
import sys

import jax
import numpy as np
import pytest

from hypermerge_tpu.ops.crdt_kernels import run_batch
from hypermerge_tpu.ops.synth import synth_batch, synth_changes
from hypermerge_tpu.parallel.mesh import make_mesh
from hypermerge_tpu.parallel import sharded as sharded_mod
from hypermerge_tpu.parallel.sharded import (
    MeshBulkScheduler,
    SlabRoundRobin,
    local_clock_union,
    sharded_clock_union,
    sharded_dominated,
    sharded_full,
    sharded_materialize,
    step,
)

# mesh tests need the 8-device virtual CPU backend; under HM_TEST_TPU=1
# (hardware validation runs) only one real chip is visible
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs >= 8 devices (virtual mesh)"
)


def test_mesh_shapes():
    mesh = make_mesh(8, sp=2)
    assert dict(mesh.shape) == {"dp": 4, "sp": 2}
    mesh1 = make_mesh(4)
    assert dict(mesh1.shape) == {"dp": 4, "sp": 1}
    with pytest.raises(ValueError):
        make_mesh(1000)


def test_sharded_materialize_matches_single_device():
    batch = synth_batch(n_docs=16, n_ops=128)
    single = run_batch(batch)
    mesh = make_mesh(8, sp=1)
    sharded = sharded_materialize(batch, mesh)
    for field in ("visible", "map_winner", "elem_live", "rank", "clock"):
        a = np.asarray(getattr(single, field))
        b = np.asarray(getattr(sharded, field))[: batch.n_docs]
        np.testing.assert_array_equal(a, b, err_msg=field)


def test_sharded_materialize_pads_ragged_doc_axis():
    batch = synth_batch(n_docs=13, n_ops=64)  # not divisible by dp
    mesh = make_mesh(8, sp=1)
    out = sharded_materialize(batch, mesh)
    assert out.rank.shape[0] == 16  # padded to dp multiple
    single = run_batch(batch)
    np.testing.assert_array_equal(
        np.asarray(single.rank), np.asarray(out.rank)[:13]
    )


def test_sharded_clock_union_and_dominated():
    mesh = make_mesh(8, sp=2)
    rng = np.random.default_rng(0)
    clocks = rng.integers(0, 100, (64, 16)).astype(np.int32)
    union = np.asarray(sharded_clock_union(clocks, mesh))
    np.testing.assert_array_equal(union, clocks.max(axis=0))

    query = clocks[7]
    dom = np.asarray(sharded_dominated(clocks, query, mesh))
    np.testing.assert_array_equal(dom, np.all(clocks <= query, axis=-1))


def test_full_step():
    batch = synth_batch(n_docs=8, n_ops=64)
    mesh = make_mesh(8, sp=2)
    out, union = step(batch, mesh)
    assert union.shape[-1] == len(batch.actors)


def test_synth_changes_replay_host():
    """The Change-object form of the synthetic workload is causally valid
    and replays fully on the host OpSet."""
    from hypermerge_tpu.crdt.opset import OpSet

    changes = synth_changes(200, seed=3)
    opset = OpSet()
    opset.apply_changes(changes)
    assert not opset._pending
    doc = opset.materialize()
    assert "t" in doc and len(str(doc["t"])) > 0


def test_synth_columns_equal_synth_changes_on_device():
    """Both generator forms produce the same materialized state."""
    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.materialize import (
        DecodedBatch,
        materialize_docs,
    )
    from hypermerge_tpu.crdt.opset import OpSet
    from helpers import plainify

    changes = synth_changes(150, seed=5)
    opset = OpSet()
    opset.apply_changes(changes)
    dec = DecodedBatch(*_run(pack_docs([changes])))
    docs = materialize_docs(dec)
    assert plainify(docs[0]) == plainify(opset.materialize())


def _run(batch):
    return batch, run_batch(batch)


# -- mesh shapes the fuzz matrix pins: (dp, sp) ------------------------
_MESH_SHAPES = [(8, 1), (4, 2), (2, 2), (1, 1)]


def _mesh_for(dp, sp):
    return make_mesh(dp * sp, sp=sp)


def _host_local_union(clock, doc_actors, n_actors):
    """Numpy twin of the collective local clock union."""
    want = np.zeros(n_actors + 1, np.int64)
    c = np.asarray(clock)
    da = np.asarray(doc_actors)
    np.maximum.at(
        want,
        np.where(da >= 0, da, n_actors).ravel(),
        np.where(da >= 0, c, 0).ravel(),
    )
    return want[:n_actors].astype(np.int32)


def test_mesh_reductions_fuzz_bit_identical_across_shapes():
    """sharded_clock_union / sharded_dominated match the numpy twin on
    every mesh shape, including ragged (non-multiple) doc and actor
    counts that force padding on both axes."""
    rng = np.random.default_rng(7)
    for dp, sp in _MESH_SHAPES:
        mesh = _mesh_for(dp, sp)
        for D, A in [(13, 5), (32, 16), (7, 11), (1, 1), (64, 3)]:
            clocks = rng.integers(0, 1000, (D, A)).astype(np.int32)
            union = np.asarray(sharded_clock_union(clocks, mesh))
            np.testing.assert_array_equal(
                union, clocks.max(axis=0), err_msg=f"{dp}x{sp} {D}x{A}"
            )
            query = clocks[rng.integers(0, D)]
            dom = np.asarray(sharded_dominated(clocks, query, mesh))
            np.testing.assert_array_equal(
                dom,
                np.all(clocks <= query, axis=-1),
                err_msg=f"{dp}x{sp} {D}x{A}",
            )


def test_step_fuzz_bit_identical_to_single_device_across_shapes():
    """The one-program collective merge step (materialize + clock
    union) matches the single-device twin on every mesh shape, ragged
    doc counts included."""
    from hypermerge_tpu.ops.crdt_kernels import bucket_doc_actors

    for seed, (dp, sp) in enumerate(_MESH_SHAPES):
        mesh = _mesh_for(dp, sp)
        for n_docs in (13, 8):
            batch = synth_batch(n_docs=n_docs, n_ops=96, seed=seed)
            single = run_batch(batch)
            da, _A, _K = bucket_doc_actors(batch)
            n_actors = len(batch.actors)
            out, union = step(batch, mesh)
            for field in (
                "visible", "map_winner", "elem_live", "rank", "clock",
            ):
                np.testing.assert_array_equal(
                    np.asarray(getattr(single, field)),
                    np.asarray(getattr(out, field))[:n_docs],
                    err_msg=f"{dp}x{sp} D={n_docs} {field}",
                )
            np.testing.assert_array_equal(
                np.asarray(union),
                _host_local_union(single.clock, da, n_actors),
                err_msg=f"{dp}x{sp} D={n_docs} union",
            )


def test_mesh_programs_cached_no_retrace():
    """Repeated same-shape calls reuse ONE traced program: the program
    table (not a fresh jit closure per call) serves local_clock_union,
    sharded_full, and step — the r5 per-call retrace regression."""
    mesh = make_mesh(8, sp=1)
    batch = synth_batch(n_docs=16, n_ops=64, seed=1)
    n_actors = max(1, len(batch.actors))

    out, da = sharded_mod._materialize_on_mesh(batch, mesh)
    local_clock_union(out.clock, da, n_actors, mesh)
    sharded_full(batch, mesh, lean=False)
    step(batch, mesh)
    sharded_clock_union(
        np.ones((16, 8), np.int32), mesh
    )
    snapshot = dict(sharded_mod.trace_counts)
    assert snapshot, "trace counter never engaged"

    for _ in range(3):
        out, da = sharded_mod._materialize_on_mesh(batch, mesh)
        local_clock_union(out.clock, da, n_actors, mesh)
        sharded_full(batch, mesh, lean=False)
        step(batch, mesh)
        sharded_clock_union(np.ones((16, 8), np.int32), mesh)
    assert dict(sharded_mod.trace_counts) == snapshot, (
        "a mesh program retraced on a repeated same-shape call",
        snapshot,
        sharded_mod.trace_counts,
    )


class _Saturator:
    """Sentinel in-flight entry: popping it (blocking on a saturated
    device) is the failure the least-loaded test pins against."""

    def block_until_ready(self):
        raise AssertionError(
            "dispatch blocked on the saturated device instead of "
            "skipping to an idle one"
        )


def test_least_loaded_skips_saturated_device():
    """HM_RR_LEAST_LOADED: a device at its in-flight depth is skipped
    while any other device has room (FIFO tiebreak otherwise)."""
    from hypermerge_tpu.ops.columnar import pack_docs

    devices = jax.devices()
    rr = SlabRoundRobin(devices, depth=2, least_loaded=True)
    # saturate device 0 (the round-robin cursor's first pick)
    rr._inflight[0] = [_Saturator(), _Saturator()]
    batch = pack_docs(
        [synth_changes(48, n_actors=1, ops_per_change=8, seed=0)]
    )
    _out, wire = rr.dispatch(batch, lean=False)
    assert rr.last_device == 1  # skipped 0, FIFO tiebreak picked 1
    assert next(iter(wire.devices())) == devices[1]
    assert len(rr._inflight[0]) == 2  # untouched
    # strict round-robin twin WOULD have blocked (and popped) device 0
    rr_strict = SlabRoundRobin(devices, depth=2, least_loaded=False)
    rr_strict._inflight[0] = [_Saturator(), _Saturator()]
    with pytest.raises(AssertionError, match="saturated"):
        rr_strict.dispatch(batch, lean=False)


def test_least_loaded_env_gate(monkeypatch):
    monkeypatch.setenv("HM_RR_LEAST_LOADED", "1")
    assert SlabRoundRobin(jax.devices()).least_loaded
    monkeypatch.setenv("HM_RR_LEAST_LOADED", "0")
    assert not SlabRoundRobin(jax.devices()).least_loaded


def test_mesh_scheduler_collective_union_and_gather():
    """MeshBulkScheduler: streaming whole-slab dispatch stays
    bit-identical to per-slab fetch, while the cross-doc reductions
    (clock union, summary gather) run as collective programs whose
    results equal the host-side merge they replace."""
    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.crdt_kernels import bucket_doc_actors
    from hypermerge_tpu.ops.materialize import fetch_summary

    mesh = make_mesh(8, sp=2)
    sch = MeshBulkScheduler(mesh, depth=2)
    batches = [
        pack_docs(
            [synth_changes(48, n_actors=2, ops_per_change=8, seed=s)]
        )
        for s in range(5)
    ]
    outs = []
    for b in batches:
        out, wire = sch.dispatch(b, lean=False)
        outs.append((b, out, wire))
    n_actors = max(len(b.actors) for b in batches)
    want = np.zeros(n_actors, np.int32)
    for b, out, _w in outs:
        da, _A, _K = bucket_doc_actors(b)
        want = np.maximum(
            want, _host_local_union(out.clock, da, n_actors)
        )
    np.testing.assert_array_equal(
        sch.collective_clock_union(n_actors), want
    )
    gathered = sch.gather_summaries()
    assert [g[0] for g in gathered] == list(range(len(batches)))
    for (_seq, _n, host_wire), (b, _out, wire) in zip(gathered, outs):
        np.testing.assert_array_equal(host_wire, np.asarray(wire))
        a = fetch_summary(host_wire, b, lean=False)
        bsl = fetch_summary(wire, b, lean=False)
        for k in a:
            np.testing.assert_array_equal(a[k], bsl[k], err_msg=k)
    # per-chip accounting: every dispatched slab is attributed
    assert sum(sch.slabs_per_chip) == len(batches)
    sch.drain()
    sch.release()
    sch.reset_resident()
    assert sch.gather_summaries() == []


def test_remote_copy_capability_gate(monkeypatch):
    """CPU host-platform meshes never select the Pallas ICI path; the
    env escape hatch forces it off everywhere."""
    from hypermerge_tpu.parallel.sharded import remote_copy_capable

    mesh = make_mesh(8, sp=1)
    assert remote_copy_capable(mesh) is False  # cpu devices
    assert remote_copy_capable() is False
    monkeypatch.setenv("HM_ICI_PALLAS", "0")
    assert remote_copy_capable(mesh) is False


def test_graft_entry_single_chip():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.rank.shape[0] == 8


def test_graft_dryrun_multichip(monkeypatch):
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    # small corpus in CI; the driver runs the slab-scale default
    monkeypatch.setenv("HM_DRYRUN_DOCS", "64")
    monkeypatch.setenv("HM_DRYRUN_OPS", "96")
    monkeypatch.setenv("HM_DEVICE_MIN_CELLS", "1")  # force device slabs
    g.dryrun_multichip(8)
    g.dryrun_multichip(4)
