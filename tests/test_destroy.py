"""destroy() reclaims disk: feed blocks, sidecars, and signature records
of doc-exclusive actors are deleted; shared actors survive (VERDICT r3
missing #7 / next-round item 9)."""

import os

from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils.ids import validate_doc_url

from helpers import plainify


def _feed_files(path, actor_id):
    d = os.path.join(path, "feeds", actor_id[:2])
    out = []
    if os.path.isdir(d):
        for name in os.listdir(d):
            if name.startswith(actor_id):
                out.append(os.path.join(d, name))
    return out


def test_destroy_deletes_disk_state(tmp_path):
    path = str(tmp_path)
    repo = Repo(path=path)
    url = repo.create({"x": 1})
    repo.change(url, lambda d: d.__setitem__("y", 2))
    keep_url = repo.create({"keep": True})
    doc_id = validate_doc_url(url)
    keep_id = validate_doc_url(keep_url)
    assert _feed_files(path, doc_id)  # block log + .cols + .sig on disk

    repo.destroy(url)
    assert _feed_files(path, doc_id) == [], "feed files not reclaimed"
    # store rows gone
    assert repo.back.clocks.get(repo.back.id, doc_id) == {}
    assert repo.back.cursors.get(repo.back.id, doc_id) == {}
    assert (
        repo.back.db.query(
            "SELECT * FROM feeds WHERE public_id=?", (doc_id,)
        )
        == []
    )
    # unrelated doc untouched
    assert _feed_files(path, keep_id)
    assert plainify(repo.doc(keep_url))["keep"] is True
    repo.close()

    # a fresh process sees an empty, never-seen doc (pending until some
    # peer replicates it back in) — not stale content
    repo2 = Repo(path=path)
    h = repo2.open(url)
    doc = repo2.back.docs[doc_id]
    assert not doc._announced
    assert repo2.back.feeds.open_feed(doc_id).length == 0
    assert plainify(repo2.doc(keep_url))["keep"] is True
    repo2.close()


def test_destroy_without_opening_reclaims_disk(tmp_path):
    """destroy() in a FRESH process (doc never opened this session) must
    still delete the prior session's feed files — FeedStore.remove can't
    rely on the in-memory map."""
    path = str(tmp_path)
    repo = Repo(path=path)
    url = repo.create({"x": 1})
    doc_id = validate_doc_url(url)
    repo.close()

    repo2 = Repo(path=path)
    assert _feed_files(path, doc_id)
    repo2.destroy(url)
    assert _feed_files(path, doc_id) == [], "unopened feed not reclaimed"
    repo2.close()


def test_destroy_keeps_shared_actor_feeds(tmp_path):
    """An actor included in two docs (merge) survives destroying one."""
    path = str(tmp_path)
    repo = Repo(path=path)
    a = repo.create({"a_key": 1})
    b = repo.create({"b_key": 2})
    repo.merge(b, a)  # b's cursor now includes a's root actor
    a_id = validate_doc_url(a)
    repo.destroy(a)
    # a's root actor is still in b's cursor -> feed stays
    assert _feed_files(path, a_id), "shared feed wrongly deleted"
    merged = plainify(repo.doc(b))
    assert merged["b_key"] == 2 and merged["a_key"] == 1
    repo.close()
