"""Network layer: duplex pairs, channels, peer dedup, replication, and
two-repo convergence over a loopback swarm (the reference's two test
techniques, SURVEY.md §4: in-memory duplex pairs + whole-repo swarm)."""

import pytest

from hypermerge_tpu.net.connection import PeerConnection
from hypermerge_tpu.net.duplex import duplex_pair
from hypermerge_tpu.net.peer import NetworkPeer
from hypermerge_tpu.net.replication import ReplicationManager
from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.storage.feed import FeedStore, memory_storage_fn
from hypermerge_tpu.utils import keys as keymod

from helpers import wait_until


class TestDuplex:
    def test_roundtrip_and_buffering(self):
        a, b = duplex_pair()
        got = []
        a.send({"n": 1})  # sent before b subscribes: buffers
        b.on_message(got.append)
        a.send({"n": 2})
        assert got == [{"n": 1}, {"n": 2}]

    def test_close_propagates(self):
        a, b = duplex_pair()
        closed = []
        b.on_close(lambda: closed.append(True))
        a.close()
        assert b.closed and closed == [True]


class TestPeerConnection:
    def test_channels_and_remote_first_buffering(self):
        da, db = duplex_pair()
        ca = PeerConnection(da, is_client=True)
        cb = PeerConnection(db, is_client=False)
        # a sends on a channel b hasn't opened yet
        ca.open_channel("late").send({"x": 1})
        got = []
        cb.open_channel("late").subscribe(got.append)
        assert got == [{"x": 1}]
        # reverse direction on another channel
        got2 = []
        ca.open_channel("other").subscribe(got2.append)
        cb.open_channel("other").send("hi")
        assert got2 == ["hi"]


class TestNetworkPeer:
    def test_duplicate_connection_dedup(self):
        ready = []
        pa = NetworkPeer("idB", "idA", ready.append)  # authority (B > A)
        pb = NetworkPeer("idA", "idB", ready.append)
        # two simultaneous dials = two duplex pairs
        d1a, d1b = duplex_pair()
        d2a, d2b = duplex_pair()
        c1a, c1b = (
            PeerConnection(d1a, True), PeerConnection(d1b, False),
        )
        c2a, c2b = (
            PeerConnection(d2a, False), PeerConnection(d2b, True),
        )
        pa.add_connection(c1a)
        pb.add_connection(c1b)
        pa.add_connection(c2a)
        pb.add_connection(c2b)
        # authority picked for both sides; exactly one live connection each
        assert pa.is_connected and pb.is_connected
        assert len(ready) == 2
        live_a = [c for c in (c1a, c2a) if c.is_open]
        live_b = [c for c in (c1b, c2b) if c.is_open]
        assert len(live_a) == 1 and len(live_b) == 1


class TestReplication:
    def _mgr(self):
        feeds = FeedStore(memory_storage_fn)
        events = []
        mgr = ReplicationManager(
            feeds, lambda pk, peer: events.append(pk)
        )
        return feeds, mgr, events

    def _connect(self, mgr_a, mgr_b):
        da, db = duplex_pair()
        ca, cb = PeerConnection(da, True), PeerConnection(db, False)
        ready = []
        pa = NetworkPeer("B", "A", ready.append)
        pb = NetworkPeer("A", "B", ready.append)
        pa.add_connection(ca)
        pb.add_connection(cb)
        mgr_a.on_peer(pa)
        mgr_b.on_peer(pb)
        return pa, pb

    def test_shared_feed_replicates_both_directions(self):
        feeds_a, mgr_a, ev_a = self._mgr()
        feeds_b, mgr_b, ev_b = self._mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        fa.append(b"one")
        fa.append(b"two")
        fb = feeds_b.open_feed(pair.public_key)  # knows the key, no data
        self._connect(mgr_a, mgr_b)
        assert fb.read_all() == [b"one", b"two"]
        assert ev_a and ev_b  # discovery fired on both sides
        # live tail after connect (batched flush: asynchronous)
        fa.append(b"three")
        wait_until(lambda: fb.length == 3)
        assert fb.read_all() == [b"one", b"two", b"three"]

    def test_live_tail_batches_bursts(self):
        """A burst of appends coalesces into O(1) signed frames per
        flush window, not one frame per append (VERDICT r5 item 7 —
        hypercore-protocol's batched block sync)."""
        feeds_a, mgr_a, _ = self._mgr()
        feeds_b, mgr_b, _ = self._mgr()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        fb = feeds_b.open_feed(pair.public_key)
        self._connect(mgr_a, mgr_b)
        frames = []
        orig = mgr_a._send

        def counting_send(peer, msg):
            if msg.get("type") == "Blocks":
                frames.append(len(msg["blocks"]))
            orig(peer, msg)

        mgr_a._send = counting_send
        n = 200
        for i in range(n):
            fa.append(b"blk%d" % i)
        wait_until(lambda: fb.length == n)
        assert fb.read_all() == [b"blk%d" % i for i in range(n)]
        # every block arrived, in far fewer frames than appends
        assert len(frames) <= n // 4, (len(frames), frames)

    def test_unknown_feed_not_replicated(self):
        feeds_a, mgr_a, _ = self._mgr()
        feeds_b, mgr_b, ev_b = self._mgr()
        fa = feeds_a.create(keymod.create())
        fa.append(b"secret")
        self._connect(mgr_a, mgr_b)
        # b never learns the public key, so nothing arrives
        assert not ev_b
        assert feeds_b.known_discovery_ids() == []

    def test_late_feed_announcement(self):
        feeds_a, mgr_a, _ = self._mgr()
        feeds_b, mgr_b, _ = self._mgr()
        self._connect(mgr_a, mgr_b)
        pair = keymod.create()
        fb = feeds_b.open_feed(pair.public_key)
        fa = feeds_a.create(pair)  # created after connection
        mgr_a.announce(fa)
        mgr_b.announce(fb)
        fa.append(b"late")
        wait_until(lambda: fb.length == 1)
        assert fb.read_all() == [b"late"]


class TestTwoRepos:
    """Whole-repo convergence over a loopback swarm (reference
    tests/multiple-repos.test.ts)."""

    def _pair(self):
        hub = LoopbackHub()
        ra, rb = Repo(memory=True), Repo(memory=True)
        ra.set_swarm(LoopbackSwarm(hub))
        rb.set_swarm(LoopbackSwarm(hub))
        return ra, rb

    def test_share_a_doc(self):
        ra, rb = self._pair()
        url = ra.create({"hello": "world"})
        doc = rb.doc(url)
        assert doc == {"hello": "world"}
        ra.close()
        rb.close()

    def test_bidirectional_edits(self):
        ra, rb = self._pair()
        url = ra.create({"from_a": 1})
        assert rb.doc(url)["from_a"] == 1
        rb.change(url, lambda d: d.__setitem__("from_b", 2))
        wait_until(lambda: ra.doc(url) == {"from_a": 1, "from_b": 2})
        ra.change(url, lambda d: d.__setitem__("from_a", 11))
        wait_until(lambda: rb.doc(url) == {"from_a": 11, "from_b": 2})
        ra.close()
        rb.close()

    def test_remote_patch_reaches_lazily_loaded_doc(self):
        """A doc served from the lazy (sidecar/device) path must still
        emit live RemotePatches: the OpSet reconstruction replays only up
        to the served clock, so the incoming window produces a real
        patch (was swallowed as an empty patch before — the frontend
        only looked fresh because re-opens pushed a new Ready)."""
        ra, rb = self._pair()
        url = ra.create({"x": 1})
        states = []
        h = rb.open(url)
        h.subscribe(lambda d, i: states.append(dict(d) if d else d))
        assert states and states[-1]["x"] == 1
        ra.change(url, lambda d: d.__setitem__("x", 2))
        # no re-open: the update must arrive via the live patch stream
        wait_until(lambda: states and states[-1]["x"] == 2)
        assert h.value()["x"] == 2
        h.close()

    def test_stale_ready_does_not_clobber_local_state(self):
        """A Ready snapshot arriving for a doc already in write mode
        (cross-process ordering) is ignored — local optimistic state
        stays ahead (reference DocFrontend.init is pending-only)."""
        from hypermerge_tpu.repo import Repo as _R
        from hypermerge_tpu.utils.ids import validate_doc_url

        repo = _R(memory=True)
        url = repo.create({"a": 1, "log": []})
        df = repo.front.docs[validate_doc_url(url)]
        # simulate a late (stale, empty-doc) Ready crossing the seam
        df.on_ready(df.actor_id, {"clock": {}, "deps": {}, "maxOp": 0,
                                  "diffs": []}, 0)
        # local state intact and still writable
        repo.change(url, lambda d: d["log"].append(7))
        got = repo.doc(url)
        assert got["a"] == 1 and list(got["log"]) == [7]
        repo.close()

    def test_watch_remote_updates(self):
        ra, rb = self._pair()
        url = ra.create({"n": 0})
        seen = []
        h = rb.open(url).subscribe(lambda doc, _i: seen.append(doc.get("n")))
        for i in range(1, 4):
            ra.change(url, lambda d, i=i: d.__setitem__("n", i))
        wait_until(lambda: seen and seen[-1] == 3)
        h.close()
        ra.close()
        rb.close()

    def test_doc_message_ephemeral(self):
        ra, rb = self._pair()
        url = ra.create({"x": 1})
        inbox = []
        h = rb.open(url)
        h.subscribe_message(inbox.append)
        assert h.value() == {"x": 1}  # wait until replicated/connected
        ra.message(url, {"ping": True})
        wait_until(lambda: inbox == [{"ping": True}])
        h.close()
        ra.close()
        rb.close()

    def test_three_repos_converge(self):
        hub = LoopbackHub()
        repos = [Repo(memory=True) for _ in range(3)]
        for r in repos:
            r.set_swarm(LoopbackSwarm(hub))
        url = repos[0].create({"base": True})
        for i, r in enumerate(repos):
            r.change(url, lambda d, i=i: d.__setitem__(f"r{i}", i))
        want = {"base": True, "r0": 0, "r1": 1, "r2": 2}
        wait_until(lambda: all(r.doc(url) == want for r in repos))
        for r in repos:
            r.close()

    def test_three_repo_tcp_relay_exact_convergence(self):
        """Concurrent edits on an A<->B<->C TCP line: every edit lands on
        every repo, exactly once (relay re-serving included). Short CI
        version of the round-4 soak."""
        import threading
        import time as T

        from hypermerge_tpu.net.tcp import TcpSwarm

        repos = [Repo(memory=True) for _ in range(3)]
        swarms = [TcpSwarm() for _ in range(3)]
        for r, s in zip(repos, swarms):
            r.set_swarm(s)
        swarms[1].connect(swarms[0].address)
        swarms[2].connect(swarms[1].address)
        urls = [repos[0].create({"edits": []}) for _ in range(3)]
        for r in repos[1:]:
            for u in urls:
                r.open(u)
        stop = T.time() + 8
        counts = [0, 0, 0]

        def churn(idx):
            import random

            rng = random.Random(idx)
            while T.time() < stop:
                repos[idx].change(
                    rng.choice(urls),
                    lambda d, i=idx: d["edits"].append(i),
                )
                counts[idx] += 1
                T.sleep(rng.random() * 0.01)

        ts = [
            threading.Thread(target=churn, args=(i,)) for i in range(3)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        sent = sum(counts)
        deadline = T.time() + 90
        while T.time() < deadline:
            try:
                totals = [
                    sum(len(r.doc(u)["edits"]) for u in urls)
                    for r in repos
                ]
            except TimeoutError:
                T.sleep(0.2)
                continue
            if totals == [sent] * 3:
                break
            T.sleep(0.2)
        assert totals == [sent] * 3, (totals, sent)
        for r in repos:
            r.close()
        for s in swarms:
            s.destroy()


class TestSparseFetch:
    """Arbitrary-range block fetch with merkle inclusion proofs
    (VERDICT r5 missing #4; hypercore's sparse download — reference
    src/types/hypercore.d.ts:132-188): a peer can pull the TAIL of a
    long feed, verified, without the contiguous prefix."""

    def _pair(self):
        feeds_a = FeedStore(memory_storage_fn)
        feeds_b = FeedStore(memory_storage_fn)
        mgr_a = ReplicationManager(feeds_a, lambda pk, p: None)
        mgr_b = ReplicationManager(feeds_b, lambda pk, p: None)
        # the client opts OUT of contiguous backfill: capability
        # verification still runs, but it never REQUESTS blocks
        # (sparse-only consumer)
        mgr_b._request_msg = lambda *a, **k: None
        from hypermerge_tpu.net.connection import PeerConnection
        from hypermerge_tpu.net.duplex import duplex_pair
        from hypermerge_tpu.net.peer import NetworkPeer

        da, db = duplex_pair()
        ca, cb = PeerConnection(da, True), PeerConnection(db, False)
        pa = NetworkPeer("B", "A", lambda p: None)
        pb = NetworkPeer("A", "B", lambda p: None)
        pa.add_connection(ca)
        pb.add_connection(cb)
        mgr_a.on_peer(pa)
        mgr_b.on_peer(pb)
        return feeds_a, feeds_b, mgr_a, mgr_b, pb

    def test_tail_fetch_without_prefix(self):
        feeds_a, feeds_b, mgr_a, mgr_b, _ = self._pair()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        for i in range(300):
            fa.append(b"blk%d" % i)
        fb = feeds_b.open_feed(pair.public_key)
        mgr_a.announce(fa)
        mgr_b.announce(fb)
        # B holds NOTHING contiguous, then asks for the tail only
        assert fb.length == 0
        wait_until(
            lambda: mgr_b.request_range(fa.discovery_id, 290, 300)
        )
        wait_until(lambda: fb.has_block(299))
        assert fb.length == 0  # still no contiguous prefix
        for i in range(290, 300):
            assert fb.get_sparse(i) == b"blk%d" % i
        assert fb.get_sparse(0) is None

    def test_tampered_sparse_block_rejected(self):
        import base64 as b64mod

        feeds_a, feeds_b, mgr_a, mgr_b, pb = self._pair()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        for i in range(64):
            fa.append(b"blk%d" % i)
        fb = feeds_b.open_feed(pair.public_key)
        mgr_a.announce(fa)
        mgr_b.announce(fb)
        wait_until(
            lambda: mgr_b.request_range(fa.discovery_id, 60, 64)
        )
        wait_until(lambda: fb.has_block(63))
        # now forge a SparseBlocks frame with a swapped block
        served = fa.integrity.range_proofs(fa, 10, 11)
        length, sig, pairs = served
        evil = b"evil"
        mgr_b._on_sparse_blocks(
            pb,
            fa.discovery_id,
            10,
            length,
            b64mod.b64encode(sig).decode(),
            [b64mod.b64encode(evil).decode()],
            [[b64mod.b64encode(h).decode() for h in pairs[0][1]]],
        )
        assert not fb.has_block(10), "forged sparse block stored"

    def test_sparse_buffer_defers_to_contiguous_log(self):
        feeds = FeedStore(memory_storage_fn)
        f = feeds.create(keymod.create())
        f.append(b"real0")
        f.put_sparse(0, b"ignored")  # head already covers index 0
        assert f.get_sparse(0) == b"real0"
        f.put_sparse(5, b"future")
        assert f.get_sparse(5) == b"future"
        f.append(b"real1")
        assert f.get_sparse(1) == b"real1"

    def test_unsolicited_sparse_push_never_lands(self):
        """A push of VALID proof-carrying blocks the receiver never
        requested must neither store blocks nor grow memory — only
        outstanding requested ranges may land."""
        import base64 as b64mod

        feeds_a, feeds_b, mgr_a, mgr_b, pb = self._pair()
        pair = keymod.create()
        fa = feeds_a.create(pair)
        for i in range(64):
            fa.append(b"blk%d" % i)
        fb = feeds_b.open_feed(pair.public_key)
        mgr_a.announce(fa)
        mgr_b.announce(fb)
        # B never called request_range: craft a fully VALID frame
        served = fa.integrity.range_proofs(fa, 10, 14)
        length, sig, pairs = served
        mgr_b._on_sparse_blocks(
            pb,
            fa.discovery_id,
            10,
            length,
            b64mod.b64encode(sig).decode(),
            [b64mod.b64encode(b).decode() for b, _p in pairs],
            [
                [b64mod.b64encode(h).decode() for h in p]
                for _b, p in pairs
            ],
        )
        assert not any(fb.has_block(i) for i in range(10, 14))
        assert len(fb._sparse) == 0, "unsolicited push grew the buffer"

        # a real request keeps working, and indices OUTSIDE it drop
        wait_until(lambda: mgr_b.request_range(fa.discovery_id, 20, 22))
        wait_until(lambda: fb.has_block(21))
        assert fb.get_sparse(20) == b"blk20"
        before = len(fb._sparse)
        mgr_b._on_sparse_blocks(  # replay of the unrequested frame
            pb,
            fa.discovery_id,
            10,
            length,
            b64mod.b64encode(sig).decode(),
            [b64mod.b64encode(b).decode() for b, _p in pairs],
            [
                [b64mod.b64encode(h).decode() for h in p]
                for _b, p in pairs
            ],
        )
        assert len(fb._sparse) == before
        assert not fb.has_block(10)

    def test_sparse_buffer_cap_evicts_furthest(self, monkeypatch):
        """HM_SPARSE_CAP bounds Feed._sparse; eviction drops the entry
        FURTHEST beyond the contiguous head (nearest blocks are about
        to be absorbed by backfill; far ones re-fetch)."""
        monkeypatch.setenv("HM_SPARSE_CAP", "4")
        feeds = FeedStore(memory_storage_fn)
        f = feeds.create(keymod.create())
        for i in range(10, 22):
            f.put_sparse(i, b"s%d" % i)
        assert len(f._sparse) == 4
        assert sorted(f._sparse) == [10, 11, 12, 13]
        # nearer-than-buffered still displaces the furthest
        f.put_sparse(5, b"s5")
        assert sorted(f._sparse) == [5, 10, 11, 12]
        # duplicates of buffered indices never evict
        f.put_sparse(11, b"s11")
        assert sorted(f._sparse) == [5, 10, 11, 12]

    def test_sparse_cap_zero_drops_instead_of_crashing(self, monkeypatch):
        """HM_SPARSE_CAP<=0 disables the buffer: put_sparse must report
        the drop (False), not raise max() on an empty dict."""
        monkeypatch.setenv("HM_SPARSE_CAP", "0")
        feeds = FeedStore(memory_storage_fn)
        f = feeds.create(keymod.create())
        assert f.put_sparse(3, b"s3") is False
        assert f._sparse == {}
        # blocks the contiguous log already holds still report True
        f.append(b"real0")
        assert f.put_sparse(0, b"dup") is True


class TestJoinOptions:
    """Discovery asymmetry (VERDICT r5 item 9; reference
    src/SwarmInterface.ts:22-25): server-ish peers announce, clients
    look up; a lookup-only join is invisible to inbound discovery."""

    def test_lookup_only_finds_announcer(self):
        from hypermerge_tpu.net.swarm import JoinOptions

        hub = LoopbackHub()
        server, client = Repo(memory=True), Repo(memory=True)
        server.set_swarm(
            LoopbackSwarm(hub), JoinOptions(announce=True, lookup=False)
        )
        client.set_swarm(
            LoopbackSwarm(hub), JoinOptions(announce=False, lookup=True)
        )
        url = server.create({"served": True})
        assert client.doc(url) == {"served": True}
        server.close()
        client.close()

    def test_two_lookup_only_peers_never_pair(self):
        from hypermerge_tpu.net.swarm import JoinOptions

        hub = LoopbackHub()
        ra, rb = Repo(memory=True), Repo(memory=True)
        lookup = JoinOptions(announce=False, lookup=True)
        sa, sb = LoopbackSwarm(hub), LoopbackSwarm(hub)
        ra.set_swarm(sa, lookup)
        rb.set_swarm(sb, lookup)
        url = ra.create({"x": 1})
        rb.open(url)
        import time

        time.sleep(0.3)
        # neither accepted inbound discovery: no connection formed
        assert not sa.connected and not sb.connected
        assert not ra.back.network.peers and not rb.back.network.peers
        ra.close()
        rb.close()

    def test_two_announce_only_peers_never_pair(self):
        from hypermerge_tpu.net.swarm import JoinOptions

        hub = LoopbackHub()
        ra, rb = Repo(memory=True), Repo(memory=True)
        ann = JoinOptions(announce=True, lookup=False)
        sa, sb = LoopbackSwarm(hub), LoopbackSwarm(hub)
        ra.set_swarm(sa, ann)
        rb.set_swarm(sb, ann)
        ra.create({"x": 1})
        import time

        time.sleep(0.2)
        assert not sa.connected and not sb.connected
        ra.close()
        rb.close()

    def test_leave_cancels_pending_join(self):
        """Regression: a leave racing a join used to strand a member
        entry. join() records intent (`joined.add`) then registers at
        the hub; with a leave interleaved between the two steps, the
        late hub registration must cancel itself (LoopbackHub.join
        re-checks `joined` inside the hub lock) instead of leaving a
        departed swarm paired forever."""
        from hypermerge_tpu.net.swarm import DEFAULT_JOIN

        hub = LoopbackHub()
        s = LoopbackSwarm(hub)
        did = "race-doc"
        # the racy interleave, step by step: join's first half...
        s.joined.add(did)
        # ...a concurrent leave runs completely...
        s.leave(did)
        # ...then join's second half (the hub registration) lands late
        hub.join(s, did, DEFAULT_JOIN)
        assert not hub._members.get(did), "leave left a member behind"
        # and a member entry stranded this way would actually pair: a
        # fresh looker-up must NOT connect to the departed swarm
        other = LoopbackSwarm(hub)
        got = []
        other.on_connection(lambda d, det: got.append(d))
        other.join(did)
        assert not got and not other.connected

    def test_leave_then_rejoin_still_pairs(self):
        """The leave fix must not eat a genuine re-join."""
        hub = LoopbackHub()
        sa, sb = LoopbackSwarm(hub), LoopbackSwarm(hub)
        conns = []
        sa.on_connection(lambda d, det: conns.append(d))
        sb.on_connection(lambda d, det: conns.append(d))
        sa.join("doc")
        sa.leave("doc")
        sa.join("doc")
        sb.join("doc")
        assert conns and sa.connected

    def test_default_join_is_symmetric(self):
        hub = LoopbackHub()
        ra, rb = Repo(memory=True), Repo(memory=True)
        ra.set_swarm(LoopbackSwarm(hub))
        rb.set_swarm(LoopbackSwarm(hub))
        url = ra.create({"x": 1})
        assert rb.doc(url) == {"x": 1}
        ra.close()
        rb.close()


class TestTcp:
    """Real-socket transport: two repos converge over localhost TCP."""

    def test_two_repos_over_tcp(self):
        import time

        from hypermerge_tpu.net.tcp import TcpSwarm

        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"over": "tcp"})
        doc = rb.open(url).value(timeout=10)
        assert doc == {"over": "tcp"}
        rb.change(url, lambda d: d.__setitem__("back", True))
        deadline = time.time() + 10
        while time.time() < deadline:
            if ra.doc(url).get("back"):
                break
            time.sleep(0.05)
        assert ra.doc(url) == {"over": "tcp", "back": True}
        ra.close()
        rb.close()

    def test_non_draining_peer_sheds_connection(self, monkeypatch):
        """The writer thread removed blocking-send backpressure; a peer
        that stops reading while its socket stays open must shed the
        connection at HM_TCP_OUTBOX_MB, not grow the outbox forever."""
        import socket as sockmod
        import time

        from hypermerge_tpu.net.tcp import TcpDuplex

        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        monkeypatch.setenv("HM_TCP_OUTBOX_MB", "0.01")  # ~10 KB
        monkeypatch.setenv("HM_TCP_STALL_S", "0.2")
        a, b = sockmod.socketpair()
        # tiny kernel buffers so the writer wedges in sendall quickly
        a.setsockopt(sockmod.SOL_SOCKET, sockmod.SO_SNDBUF, 4096)
        b.setsockopt(sockmod.SOL_SOCKET, sockmod.SO_RCVBUF, 4096)
        d = TcpDuplex(a)
        payload = {"pad": "x" * 4096}
        deadline = time.time() + 10
        while not d.closed and time.time() < deadline:
            d.send(payload)
        assert d.closed, "outbox grew past the cap without shedding"
        b.close()

    def test_close_with_wedged_writer_is_prompt(self, monkeypatch):
        """A peer that dies with a frame wedged in sendall must not
        make close() burn its full 5s drain deadline: reader EOF and a
        dead writer both short-circuit the drain wait."""
        import socket as sockmod
        import time

        from hypermerge_tpu.net.tcp import TcpDuplex

        monkeypatch.setenv("HM_TCP_PLAINTEXT", "1")
        a, b = sockmod.socketpair()
        a.setsockopt(sockmod.SOL_SOCKET, sockmod.SO_SNDBUF, 4096)
        b.setsockopt(sockmod.SOL_SOCKET, sockmod.SO_RCVBUF, 4096)
        d = TcpDuplex(a)
        payload = {"pad": "x" * 4096}
        for _ in range(64):  # wedge the writer, queue a backlog
            d.send(payload)
        t0 = time.monotonic()
        b.close()  # peer dies: frames queued + one mid-sendall
        deadline = time.monotonic() + 10
        while not d.closed and time.monotonic() < deadline:
            time.sleep(0.02)
        assert d.closed
        d.close()  # idempotent, and must return promptly too
        assert time.monotonic() - t0 < 3.0, "close stalled on drain"


class TestChurn:
    def test_reconnect_resumes_replication(self):
        """After the transport drops, a redial must renegotiate feeds and
        deliver new changes (per-connection channel wiring + replication
        reset on disconnect)."""
        import time

        from hypermerge_tpu.net.tcp import TcpSwarm

        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sb = TcpSwarm(), TcpSwarm()
        ra.set_swarm(sa)
        rb.set_swarm(sb)
        sb.connect(sa.address)
        url = ra.create({"v": 1})
        assert rb.open(url).value(timeout=10)["v"] == 1

        # hard-drop every transport on b's side
        for d in list(sb._duplexes):
            d.close()
        deadline = time.time() + 5
        while time.time() < deadline:
            peer = next(iter(rb.back.network.peers.values()), None)
            if peer is not None and not peer.is_connected:
                break
            time.sleep(0.05)

        # change while disconnected, then redial
        ra.change(url, lambda d: d.__setitem__("v", 2))
        sb.connect(sa.address)
        deadline = time.time() + 10
        while time.time() < deadline:
            if rb.doc(url).get("v") == 2:
                break
            time.sleep(0.05)
        assert rb.doc(url)["v"] == 2
        ra.close()
        rb.close()

    def test_malformed_peer_messages_survive(self):
        """Garbage on the Msgs/Replication channels must not kill sync."""
        ra, rb = Repo(memory=True), Repo(memory=True)
        hub = LoopbackHub()
        ra.set_swarm(LoopbackSwarm(hub))
        rb.set_swarm(LoopbackSwarm(hub))
        url = ra.create({"x": 1})
        assert rb.doc(url) == {"x": 1}
        # inject malformed frames from a's side toward b
        peer = next(iter(ra.back.network.peers.values()))
        ch = peer.connection.open_channel("Msgs")
        ch.send({"type": "CursorMessage"})  # missing fields
        ch.send({"type": "DocumentMessage"})
        ch.send(42)
        rch = peer.connection.open_channel("Replication")
        rch.send({"type": "Blocks", "id": "nope", "from": "NaN", "blocks": 3})
        rch.send({"type": "FeedLength"})
        # sparse-fetch surface: malformed ranges, bogus proofs, junk b64
        rch.send({"type": "RequestRange", "id": "nope", "from": 0})
        rch.send({"type": "RequestRange", "id": "nope", "from": -5,
                  "to": "many", "cap": 7})
        rch.send({"type": "SparseBlocks", "id": "nope", "from": 0,
                  "len": 1, "sig": "!!notb64!!", "blocks": ["@@"],
                  "proofs": [[]]})
        rch.send({"type": "SparseBlocks", "id": "nope", "from": 0,
                  "len": "x", "sig": None, "blocks": 1, "proofs": {}})
        # sync still works afterwards
        ra.change(url, lambda d: d.__setitem__("x", 2))
        wait_until(lambda: rb.doc(url).get("x") == 2)
        ra.close()
        rb.close()
