"""Fused summary wire + dirty-doc summary memo (tentpole c).

The materialization barrier transfers ONE uint8 buffer per slab — masks
bit-packed, element order at ceil(log2 N) bits per entry, narrow counts,
no clock section on lean runs. These tests pin the bit packing against
its host decoder across widths, the wire against the host reference
summary, and the backend memo that lets clean docs (clock unchanged
since their last fetch) skip pack/dispatch/transfer entirely."""

import numpy as np
import pytest

from helpers import plainify
from hypermerge_tpu.ops import crdt_kernels as ck
from hypermerge_tpu.repo import Repo
from hypermerge_tpu.utils.ids import validate_doc_url


def test_pack_unpack_uint_roundtrip_across_widths():
    rng = np.random.default_rng(7)
    for bits in (1, 2, 3, 7, 8, 10, 15, 16, 17, 18, 20):
        for N in (1, 5, 8, 33, 1024):
            vals = rng.integers(0, 1 << bits, size=(3, N), dtype=np.int64)
            packed = np.asarray(ck._pack_uint(vals, bits))
            assert packed.shape == (3, (N * bits + 7) // 8)
            got = ck._unpack_uint(packed, N, bits)
            assert np.array_equal(got, vals), (bits, N)


def test_wire_spec_totals():
    spec = ck.summary_wire_spec(1024, 4, lean=True)
    # masks 2x128 + order 10 bits x 1024 / 8 + two int16 counts
    assert spec["total"] == 128 + 128 + 1280 + 2 + 2
    spec = ck.summary_wire_spec(1024, 4, lean=False)
    assert spec["total"] == 128 + 128 + 1280 + 2 + 2 + 16


def test_wire_spec_rejects_untruncatable_order_bits():
    """_unpack_uint gathers at most 4 bytes per entry: order entries
    wider than 25 bits (N > 2^25 rows) would decode silently truncated
    — the spec must reject them loudly, exactly at the boundary."""
    # the largest legal bucket: order_bits == 25
    spec = ck.summary_wire_spec(2**25, 4, lean=True)
    assert spec["order_bits"] == 25
    with pytest.raises(ValueError, match="2\\^25"):
        ck.summary_wire_spec(2**25 + 1, 4, lean=True)


def test_wire_matches_host_reference_summary():
    """Device wire -> parse == decode_columnar on the same batch (incl.
    clocks on the non-lean wire)."""
    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.crdt_kernels import run_batch, run_batch_summary
    from hypermerge_tpu.ops.materialize import (
        DecodedBatch,
        decode_columnar,
        fetch_summary,
    )
    from hypermerge_tpu.ops.synth import synth_changes

    histories = [
        synth_changes(96, n_actors=3, ops_per_change=8, seed=s)
        for s in range(4)
    ]
    batch = pack_docs(histories)
    want = decode_columnar(DecodedBatch(batch, run_batch(batch)))
    got = fetch_summary(run_batch_summary(batch), batch)
    for key in ("map_winner", "elem_live", "elem_order"):
        assert np.array_equal(got[key], want[key]), key
    for key in ("n_live_elems", "n_map_entries"):
        assert np.array_equal(
            np.asarray(got[key]), np.asarray(want[key])
        ), key
    assert np.array_equal(np.asarray(got["clock"]), np.asarray(want["clock"]))


def _corpus_repo(tmp_path, n_docs=10, n_ops=48):
    from hypermerge_tpu.ops.corpus import make_corpus

    urls = make_corpus(str(tmp_path), n_docs, n_ops, threads=2)
    return Repo(path=str(tmp_path)), urls


def test_summary_memo_serves_clean_docs(tmp_path):
    repo, urls = _corpus_repo(tmp_path)
    ids = [validate_doc_url(u) for u in urls]
    repo.open_many(urls)
    s1 = repo.back.fetch_bulk_summaries()
    want = {d: s1.doc(d) for d in ids}
    assert repo.back.last_bulk_stats["memo"] == 0

    for u in urls:
        repo.close_doc(u)
    handles = repo.open_many(urls)
    stats = repo.back.last_bulk_stats
    assert stats["memo"] == len(urls), stats
    assert stats["fast"] == len(urls)
    assert stats["t_pack"] == 0.0, "clean docs must not re-pack"
    s2 = repo.back.fetch_bulk_summaries()
    assert sorted(s2.doc_ids) == sorted(ids)
    for d in ids:
        assert s2.doc(d) == want[d]
    # memo-served docs still render (lazy one-doc snapshot decode)
    v = plainify(handles[0].value())
    assert v and "t" in v
    repo.close()


def test_summary_memo_dirty_doc_refetches(tmp_path):
    repo, urls = _corpus_repo(tmp_path, n_docs=6)
    ids = [validate_doc_url(u) for u in urls]
    repo.open_many(urls)
    repo.back.fetch_bulk_summaries()

    # dirty ONE doc (its clock advances), keep the rest clean
    repo.change(urls[0], lambda d: d.__setitem__("extra", 1))
    for u in urls:
        repo.close_doc(u)
    repo.open_many(urls)
    stats = repo.back.last_bulk_stats
    assert stats["memo"] == len(urls) - 1, stats
    s2 = repo.back.fetch_bulk_summaries()
    d0 = s2.doc(ids[0])
    assert d0["clock"][ids[0]] == max(
        s2.doc(d)["clock"][d] for d in ids
    )
    assert plainify(repo.doc(urls[0]))["extra"] == 1
    repo.close()


def test_summary_memo_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("HM_SUMMARY_MEMO_MB", "0")
    repo, urls = _corpus_repo(tmp_path, n_docs=4)
    repo.open_many(urls)
    repo.back.fetch_bulk_summaries()
    for u in urls:
        repo.close_doc(u)
    repo.open_many(urls)
    assert repo.back.last_bulk_stats["memo"] == 0
    s = repo.back.fetch_bulk_summaries()
    assert len(s.doc_ids) == len(urls)
    repo.close()
