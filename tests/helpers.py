"""Shared test fixtures: a wired collaborator Site + doc normalization.

Site couples FrontendDoc + OpSet the way the repo runtime does (request ->
backend -> patch echo) — the in-process analogue of the reference's
frontend/backend wiring in tests (reference tests/repo.test.ts:27-45)."""

from hypermerge_tpu.crdt.frontend_state import FrontendDoc
from hypermerge_tpu.crdt.opset import OpSet
from hypermerge_tpu.models import Counter, Table, Text


class Site:
    def __init__(self, actor: str):
        self.actor = actor
        self.front = FrontendDoc()
        self.opset = OpSet()
        self.seq = 1

    def change(self, fn, message=""):
        req, preview = self.front.change(fn, self.actor, self.seq, message)
        if req is None:
            return None, preview
        self.seq += 1
        change, patch = self.opset.apply_local_request(req)
        self.front.apply_patch(patch)
        return change, preview

    def receive(self, changes):
        patch = self.opset.apply_changes(changes)
        self.front.apply_patch(patch)

    @property
    def doc(self):
        return self.front.materialize()

    def assert_consistent(self):
        assert plainify(self.opset.materialize()) == plainify(self.doc)


def plainify(v):
    if isinstance(v, Text):
        return ("__text__", str(v))
    if isinstance(v, Table):
        return ("__table__", {k: plainify(v.by_id(k)) for k in v.ids})
    if isinstance(v, Counter):
        return ("__counter__", int(v))
    if isinstance(v, dict):
        return {k: plainify(x) for k, x in v.items()}
    if isinstance(v, list):
        return [plainify(x) for x in v]
    return v


def wait_until(fn, timeout=10.0, interval=0.005):
    """Poll until fn() is truthy (live replication tails are batched
    and asynchronous — net/replication.py flush windows), returning the
    value; raise on timeout."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        v = fn()
        if v:
            return v
        if time.monotonic() > deadline:
            raise AssertionError(f"wait_until timed out: {fn}")
        time.sleep(interval)


def sync(*sites):
    for a in sites:
        for b in sites:
            if a is not b:
                a.receive(list(b.opset.history))


def random_mutation(site: Site, r) -> None:
    """One random change covering every op family (maps, lists, text,
    counters, deletes, nested objects)."""

    def fn(d):
        choice = r.random()
        if choice < 0.3:
            d[r.choice("abc")] = r.randint(0, 99)
        elif choice < 0.45:
            if "l" not in d:
                d["l"] = []
            lst = d["l"]
            lst.insert(r.randint(0, len(lst)), r.randint(0, 9))
        elif choice < 0.55:
            if "l" in d and len(d["l"]) > 0:
                del d["l"][r.randint(0, len(d["l"]) - 1)]
        elif choice < 0.7:
            if "t" not in d:
                d["t"] = Text("")
            d["t"].insert(r.randint(0, len(d["t"])), r.choice("xyz"))
        elif choice < 0.8:
            if "n" not in d or not isinstance(d.get("n"), Counter):
                d["n"] = Counter(0)
            else:
                d.increment("n", r.randint(1, 3))
        elif choice < 0.9:
            k = r.choice("abc")
            if k in d:
                del d[k]
        else:
            d[r.choice("mn")] = {"v": [r.randint(0, 9)]}

    site.change(fn)
