"""Serving-tier race drivers under HM_LOCKDEP=1 (ISSUE 11).

Concurrent writers, readers, and eviction churn exercise every serve
lock (serve.cache, serve.batch) against the engine/doc/store locks;
the module teardown asserts the observed lock-order graph is clean —
no potential deadlock cycle, no hierarchy inversion — even though no
deadlock fired. The chaos test also pins the freshness contract: a
read issued after a patch was delivered NEVER returns state older
than that patch.
"""

import threading

import pytest

from hypermerge_tpu.models import Text
from hypermerge_tpu.repo import Repo
from lockdep_fixture import lockdep_suite
from racedep_fixture import racedep_suite

_lockdep = lockdep_suite()
# eviction churn + invalidation races under the lockset detector
# (tests/racedep_fixture.py): the serve-tier guard rows verified live
_racedep = racedep_suite()


@pytest.fixture
def repo():
    r = Repo(memory=True)
    yield r
    r.close()


def test_eviction_churn_race(repo, monkeypatch):
    """Readers over more docs than the byte budget holds: every read
    races installs + LRU evictions of the others. Values must stay
    correct and the lock graph clean."""
    monkeypatch.setenv("HM_SERVE_MAX_BYTES", "4000")
    urls = []
    for i in range(6):
        u = repo.create({"i": i})
        repo.change(u, lambda d, i=i: d.__setitem__("t", Text(f"doc{i}")))
        urls.append(u)
    errors = []

    def reader(n):
        try:
            for j in range(10):
                i = (n + j) % len(urls)
                v = repo.read(urls[i], {"kind": "text", "path": ["t"]})
                assert v == f"doc{i}", v
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    ts = [threading.Thread(target=reader, args=(n,)) for n in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors


def test_invalidation_race(repo):
    """Writers move clocks while readers install/serve: a read may see
    the pre- or post-edit value of a CONCURRENT edit, but never a
    value that contradicts the doc's committed history (values only
    ever grow through the append-only script below)."""
    url = repo.create()
    repo.change(url, lambda d: d.__setitem__("n", 0))
    stop = threading.Event()
    errors = []

    def writer():
        try:
            for i in range(1, 30):
                repo.change(url, lambda d, i=i: d.__setitem__("n", i))
        finally:
            stop.set()

    def reader():
        last = -1
        try:
            while not stop.is_set() or last < 0:
                v = repo.read(url, {"kind": "lookup", "path": ["n"]})
                assert v is not None and v >= last, (v, last)
                last = v
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    ts = [threading.Thread(target=reader) for _ in range(3)]
    w = threading.Thread(target=writer)
    for t in ts:
        t.start()
    w.start()
    w.join()
    for t in ts:
        t.join()
    assert not errors


def test_no_stale_read_past_delivered_patch(repo):
    """The live-edit-during-read chaos test: a watcher records each
    delivered patch's text length; every read issued AFTER a delivery
    must reflect at least that much text (the serving clock moved
    before the patch reached the frontend, so a resident entry built
    earlier can never serve the newer read)."""
    url = repo.create()
    repo.change(url, lambda d: d.__setitem__("t", Text("")))
    seen = [0]  # longest delivered text, updated by the watcher

    def watch(state, _idx):
        t = state.get("t")
        if isinstance(t, Text) and len(t) > seen[0]:
            seen[0] = len(t)

    handle = repo.watch(url, watch)
    errors = []
    stop = threading.Event()

    def writer():
        try:
            for i in range(40):
                repo.change(
                    url,
                    lambda d, i=i: d["t"].insert(len(d["t"]), "x"),
                )
        finally:
            stop.set()

    def reader():
        try:
            while not stop.is_set():
                floor = seen[0]  # delivered BEFORE this read is issued
                v = repo.read(url, {"kind": "text", "path": ["t"]})
                assert v is not None and len(v) >= floor, (len(v), floor)
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    rs = [threading.Thread(target=reader) for _ in range(2)]
    w = threading.Thread(target=writer)
    for t in rs:
        t.start()
    w.start()
    w.join()
    for t in rs:
        t.join()
    handle.close()
    assert not errors
    # the final read observes the full 40-char text
    assert repo.read(url, {"kind": "text", "path": ["t"]}) == "x" * 40
