"""Group-commit WAL (storage/wal.py): counters, crash matrix, bounded
recovery.

The seeded crash matrix of tests/test_crash.py extended to the shared
journal — every byte the WAL writes goes through the storage/faults.py
io seam, so kill -9 / power-cut replays cover journal writes, the
group-commit fsync, fsync LIES, and the checkpoint tmp+rename:

  - a durable commit window is ONE journal fsync however many feeds
    are dirty (the counter-pinned O(1) acceptance gate; legacy group
    flush was O(dirty feeds));
  - power cut at every write/fsync/checkpoint prefix recovers with
    acked_lost=0 at HM_FSYNC>=1: acked bytes the cut dropped from the
    (unfsynced-at-ack) per-feed logs replay from the fsynced journal;
  - a torn journal tail parses as end-of-journal (torn records were
    never acked), and a crash mid-checkpoint leaves either the old
    journal (idempotent replay) or the new one (logs already durable);
  - the generation stamp bounds recovery: a crashed session's scan
    opens only the journal's dirty-name ledger, not every sidecar in
    the repo (counted by test), and a clean-shutdown journal left
    behind with a stale crash marker yields a ZERO-feed scan.
"""

import os

import pytest

from hypermerge_tpu.storage import faults as F
from hypermerge_tpu.storage import wal as walmod
from hypermerge_tpu.storage.durability import DurabilityManager
from hypermerge_tpu.storage.feed import FileFeedStorage
from hypermerge_tpu.storage.wal import WriteAheadLog, read_journal

from helpers import wait_until


def _fsyncs(rec, start=0):
    """Honest FSYNC events per path since event index `start`."""
    out = {}
    for ev in rec.events[start:]:
        if ev[0] == F.FSYNC and not ev[2]:
            out[ev[1]] = out.get(ev[1], 0) + 1
    return out


# ---------------------------------------------------------------------------
# O(1) fsyncs per commit window (the counter-pinned acceptance gate)


@pytest.mark.parametrize("n_feeds", [2, 8])
def test_tier1_window_is_one_journal_fsync(
    tmp_path, monkeypatch, n_feeds
):
    """However many feeds a tier-1 window dirties, durability costs
    ONE journal fsync — and ZERO per-feed log fsyncs (those defer to
    checkpoint, off the ack path)."""
    monkeypatch.setenv("HM_FSYNC", "1")
    monkeypatch.setenv("HM_FSYNC_MS", "10000")  # we drive the flush
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        os.makedirs(str(work))
        dm = DurabilityManager()
        wal = WriteAheadLog(str(work / "wal.log"), tier=1)
        dm.attach_wal(wal)
        stores = [
            FileFeedStorage(
                str(work / "feeds" / "ab" / f"feed{i}"), durability=dm
            )
            for i in range(n_feeds)
        ]
        mark = len(rec.events)
        for s in stores:
            s.append(b"block")  # journal-routed: no per-feed fsync
        assert dm.sync_now() >= 1  # ONE commit window, driven directly
        counts = _fsyncs(rec, mark)
        assert counts.get("wal.log") == 1, counts
        assert not any(p.startswith("feeds/") for p in counts), counts
        dm.close()


def test_tier2_concurrent_commits_share_leader_fsync(
    tmp_path, monkeypatch
):
    """Leader/follower group commit: concurrent committers (disjoint
    docs since the emission split) ride ONE fsync when the gather
    window covers them — strictly fewer fsyncs than appends."""
    import threading

    monkeypatch.setenv("HM_FSYNC", "2")
    monkeypatch.setenv("HM_WAL_MS", "30")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        os.makedirs(str(work))
        dm = DurabilityManager()
        dm.attach_wal(WriteAheadLog(str(work / "wal.log"), tier=2))
        stores = [
            FileFeedStorage(
                str(work / "feeds" / "ab" / f"feed{i}"), durability=dm
            )
            for i in range(8)
        ]
        mark = len(rec.events)
        barrier = threading.Barrier(8)

        def commit_one(s):
            barrier.wait()
            s.append(b"durable-block")  # tier 2: blocks until durable

        ts = [
            threading.Thread(target=commit_one, args=(s,))
            for s in stores
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        counts = _fsyncs(rec, mark)
        assert 1 <= counts.get("wal.log", 0) < 8, counts
        assert not any(p.startswith("feeds/") for p in counts), counts
        dm.close()


# ---------------------------------------------------------------------------
# power-cut matrix over the journal: acked_lost=0 at HM_FSYNC>=1


def _acked_repo_workload(work, monkeypatch, tier="1"):
    """Disk repo, 3 docs, interleaved edits; ack point = durability
    flush. Returns (recorder, url_list, acked list of
    (event_index, edits_per_doc))."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", tier)
    rec = F.CrashRecorder(str(work))
    acked = []
    with F.activate(recorder=rec):
        repo = Repo(path=str(work))
        urls = [repo.create({"edits": []}) for _ in range(3)]
        for i in range(4):
            for url in urls:
                repo.change(url, lambda d, i=i: d["edits"].append(i))
            if repo.back.live is not None:
                repo.back.live.flush_now()
            repo.back._stores.flush_now()
            repo.back._cache_syncs.flush_now()
            repo.back.durability.flush_now()  # the durable ack
            acked.append((len(rec.events), i + 1))
        # one UN-acked trailing edit: gives the torn-tail test a
        # journal append after the last ack to tear into
        repo.change(urls[0], lambda d: d["edits"].append(4))
        if repo.back.live is not None:
            repo.back.live.flush_now()
        # crash: no close
    return rec, repo, urls, acked


def test_powercut_replays_acked_blocks_from_journal(
    tmp_path, monkeypatch
):
    """THE WAL value proposition at tier 1: the per-feed logs are
    page-cache-only at ack time, so a power cut eats them — but every
    acked edit comes back because its bytes are in the fsynced
    journal. acked_lost == 0 at every ack boundary."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    work = tmp_path / "work"
    rec, _repo, urls, acked = _acked_repo_workload(
        work, monkeypatch, tier="1"
    )
    for k, want in [acked[0], acked[2], acked[3]]:
        dst = str(tmp_path / f"cut{k}")
        rec.materialize(dst, k, powercut=True)
        repo2 = Repo(path=dst)
        try:
            rep = repo2.back.recovery_report
            assert rep is not None and rep["wal"]["present"] == 1, rep
            for url in urls:
                doc_id = validate_doc_url(url)
                assert doc_id in repo2.back.clocks.all_doc_ids(
                    repo2.back.id
                ), (k, "doc lost")
                h = repo2.open(url)
                v = h.value(timeout=30)
                edits = list(v.get("edits", []))
                # gapless AND nothing acked lost
                assert edits[:want] == list(range(want)), (
                    k, want, edits,
                )
        finally:
            repo2.close()


def test_powercut_matrix_every_prefix_never_raises(
    tmp_path, monkeypatch
):
    """Kill/power-cut at EVERY sampled journal-era prefix: reopen
    (journal replay included) never raises and each doc reads back a
    gapless prefix of its acked edits."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    work = tmp_path / "work"
    rec, _repo, urls, acked = _acked_repo_workload(
        work, monkeypatch, tier="1"
    )
    n = len(rec.events)
    step = max(1, n // 12)
    for k in range(0, n + 1, step):
        for powercut in (False, True):
            dst = str(tmp_path / f"c{k}_{int(powercut)}")
            rec.materialize(dst, k, powercut=powercut)
            repo2 = Repo(path=dst)  # never raises
            try:
                hi = max((m for e, m in acked if e <= k), default=0)
                for url in urls:
                    doc_id = validate_doc_url(url)
                    if doc_id not in repo2.back.clocks.all_doc_ids(
                        repo2.back.id
                    ):
                        # crashed before this doc's first commit; the
                        # acked_lost gate still applies (hi == 0 then)
                        assert not (powercut and hi), (k, doc_id)
                        continue
                    v = repo2.doc(url)
                    edits = list((v or {}).get("edits", []))
                    assert edits == list(range(len(edits))), (k, edits)
                    if powercut:
                        # acked_lost == 0: everything flushed before
                        # the cut survived it
                        assert len(edits) >= hi, (k, len(edits), hi)
            finally:
                repo2.close()


def test_torn_journal_tail_recovers_acked_prefix(
    tmp_path, monkeypatch
):
    """A crash mid-journal-write (partial record bytes on disk) parses
    as end-of-journal: recovery replays the acked prefix, reports the
    torn bytes, and never raises."""
    from hypermerge_tpu.repo import Repo

    work = tmp_path / "work"
    rec, _repo, urls, acked = _acked_repo_workload(
        work, monkeypatch, tier="1"
    )
    # find a journal APPEND event after the last ack and tear inside it
    k_ack, want = acked[-1]
    torn = None
    for idx in range(k_ack, len(rec.events)):
        ev = rec.events[idx]
        if ev[0] in (F.APPEND, F.WRITE) and ev[1] == "wal.log":
            torn = idx
            break
    if torn is None:
        pytest.skip("no journal append after the last ack")
    dst = str(tmp_path / "torn")
    rec.materialize(dst, torn, partial_last=3)  # 3 bytes of the record
    repo2 = Repo(path=dst)
    try:
        rep = repo2.back.recovery_report
        assert rep is not None, rep
        for url in urls:
            edits = list((repo2.doc(url) or {}).get("edits", []))
            assert edits[:want] == list(range(want)), (want, edits)
    finally:
        repo2.close()


def test_crash_mid_checkpoint_recovers(tmp_path, monkeypatch):
    """HM_WAL_MAX_BYTES small enough that the workload checkpoints:
    crashing at every prefix across the checkpoint's fsync+rotate
    window recovers cleanly — the old journal replays idempotently or
    the new one finds the logs already durable."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_WAL_MAX_BYTES", "2048")
    work = tmp_path / "work"
    rec, _repo, urls, acked = _acked_repo_workload(
        work, monkeypatch, tier="1"
    )
    replaces = [
        i
        for i, ev in enumerate(rec.events)
        if ev[0] == F.REPLACE and ev[2] == "wal.log"
    ]
    assert replaces, "workload never checkpointed — lower the cap"
    points = set()
    for r in replaces:  # bracket every rotation tightly
        points.update(
            p for p in range(r - 3, r + 3) if 0 <= p <= len(rec.events)
        )
    from hypermerge_tpu.utils.ids import validate_doc_url

    for k in sorted(points):
        for powercut in (False, True):
            dst = str(tmp_path / f"ck{k}_{int(powercut)}")
            rec.materialize(dst, k, powercut=powercut)
            repo2 = Repo(path=dst)  # never raises
            try:
                hi = max((m for e, m in acked if e <= k), default=0)
                for url in urls:
                    doc_id = validate_doc_url(url)
                    if doc_id not in repo2.back.clocks.all_doc_ids(
                        repo2.back.id
                    ):
                        assert not (powercut and hi), (k, doc_id)
                        continue
                    edits = list(
                        (repo2.doc(url) or {}).get("edits", [])
                    )
                    assert edits == list(range(len(edits))), (k, edits)
                    if powercut:
                        assert len(edits) >= hi, (k, len(edits), hi)
            finally:
                repo2.close()


def test_fsync_lie_on_journal_loses_only_unacked(
    tmp_path, monkeypatch
):
    """A LYING journal fsync is the worst durable-tier failure: the
    commit claims durability the platter never got. The power-cut
    replay drops those bytes — recovery still never raises and the doc
    stays a gapless prefix (the lie IS data loss; what the WAL must
    guarantee is no corruption and no gap)."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", "1")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    plan = F.DiskFaultPlan(
        seed=11, fsync_lie_p=1.0, path_filter="wal.log", after=1
    )
    with F.activate(plan=plan, recorder=rec):
        repo = Repo(path=str(work))
        url = repo.create({"edits": []})
        for i in range(4):
            repo.change(url, lambda d, i=i: d["edits"].append(i))
        if repo.back.live is not None:
            repo.back.live.flush_now()
        repo.back._stores.flush_now()
        repo.back.durability.flush_now()
        k = len(rec.events)
    dst = str(tmp_path / "cut")
    rec.materialize(dst, k, powercut=True)
    repo2 = Repo(path=dst)
    try:
        edits = list((repo2.doc(url) or {}).get("edits", []))
        assert edits == list(range(len(edits)))  # gapless, no raise
    finally:
        repo2.close()


# ---------------------------------------------------------------------------
# journal parsing units


def test_read_journal_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "wal.log")
    wal = WriteAheadLog(path, tier=1)
    wal.append("feedA", 0, b"alpha")
    wal.append("feedB", 0, b"beta")
    wal.append("feedA", 1, b"gamma")
    header, dirty, records, torn = read_journal(path)
    assert header is not None and header["tier"] == 1
    assert header["session"] == wal.session
    assert dirty == {"feedA", "feedB"}
    assert records == [
        ("feedA", 0, b"alpha"),
        ("feedB", 0, b"beta"),
        ("feedA", 1, b"gamma"),
    ]
    assert torn == 0
    # tear the tail mid-record: the parse stops cleanly before it
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 4)
    _h, dirty2, records2, torn2 = read_journal(path)
    assert records2 == records[:2]
    assert torn2 > 0
    assert "feedA" in dirty2 and "feedB" in dirty2
    # garbage instead of a record header: also end-of-journal
    with open(path, "ab") as fh:
        fh.write(os.urandom(64))
    _h, _d, records3, torn3 = read_journal(path)
    assert records3 == records[:2] and torn3 > 0
    wal.close()


def test_checkpoint_preserves_dirty_ledger_and_carries_tail(tmp_path):
    path = str(tmp_path / "wal.log")
    wal = WriteAheadLog(path, tier=1)

    class _Store:
        synced = 0

        def sync(self):
            type(self).synced += 1

    s = _Store()
    wal.append("feedA", 0, b"a" * 100, storage=s)
    wal.append("feedB", 0, b"b" * 100, storage=s)
    out = wal.checkpoint()
    assert out["synced_feeds"] == 2
    header, dirty, records, torn = read_journal(path)
    # records drained into the (now-synced) logs; the session ledger
    # survives the rotation so recovery bounding still knows the set
    assert records == [] and torn == 0
    assert dirty == {"feedA", "feedB"}
    assert header["session"] == wal.session
    # post-checkpoint appends land in the fresh journal
    wal.append("feedC", 0, b"c", storage=s)
    _h, dirty2, records2, _t = read_journal(path)
    assert ("feedC", 0, b"c") in records2
    assert dirty2 == {"feedA", "feedB", "feedC"}
    wal.close()


# ---------------------------------------------------------------------------
# the generation stamp bounds recovery (the 100k-feed constant)


def _count_recovery_stores(monkeypatch):
    """Counts per-feed storages the NEXT recovery opens."""
    from hypermerge_tpu.storage import scrub

    opened = []
    real = scrub._recover_repo

    def counting(back, repair):
        fn = back.feeds._storage_fn

        def wrapped(name):
            opened.append(name)
            return fn(name)

        monkeypatch.setattr(back.feeds, "_storage_fn", wrapped)
        try:
            return real(back, repair)
        finally:
            monkeypatch.setattr(back.feeds, "_storage_fn", fn)

    monkeypatch.setattr(scrub, "_recover_repo", counting)
    return opened


def test_bounded_recovery_opens_only_session_dirty_feeds(
    tmp_path, monkeypatch
):
    """Session 1 creates MANY docs and closes clean; session 2 edits
    ONE doc and crashes. Recovery must scrub only the crashed
    session's dirty ledger — the untouched sidecars stay unopened
    (generation stamp honored)."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", "1")
    path = str(tmp_path / "r")
    repo = Repo(path=path)
    urls = [repo.create({"n": i}) for i in range(20)]
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.close()  # clean: marker removed, journal reset

    repo2 = Repo(path=path)
    repo2.change(urls[0], lambda d: d.__setitem__("n", 99))
    if repo2.back.live is not None:
        repo2.back.live.flush_now()
    repo2.back._stores.flush_now()
    repo2.back.durability.flush_now()
    del repo2  # crash: marker + journal left behind

    opened = _count_recovery_stores(monkeypatch)
    repo3 = Repo(path=path)
    try:
        rep = repo3.back.recovery_report
        assert rep is not None, "marker gone — no crash simulated"
        assert rep["wal"]["bounded"] == 1, rep["wal"]
        assert rep["feeds_skipped"] >= 19, rep
        # only the crashed session's feeds were opened (the edited
        # doc's actor feed; NOT the other 19 docs' sidecars)
        assert 0 < len(set(opened)) <= 3, sorted(set(opened))
        assert (repo3.doc(urls[0]) or {}).get("n") == 99
    finally:
        repo3.close()


def test_stale_marker_after_clean_shutdown_scans_nothing(
    tmp_path, monkeypatch
):
    """A clean shutdown resets the journal to its bare header exactly
    so that a stale crash marker (close crashed AFTER the final
    checkpoint but before the marker removal) yields a ZERO-feed
    bounded scan instead of a whole-repo sidecar sweep."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", "1")
    path = str(tmp_path / "r")
    repo = Repo(path=path)
    urls = [repo.create({"n": i}) for i in range(10)]
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.close()
    # the clean close left the truncated journal: bare header, same
    # session id
    header, dirty, records, torn = read_journal(
        os.path.join(path, "wal.log")
    )
    assert header is not None and not dirty and not records and not torn
    # resurrect the crash marker as a failed close would leave it
    with open(os.path.join(path, "repo.dirty"), "wb") as fh:
        fh.write(str(header["session"]).encode())

    opened = _count_recovery_stores(monkeypatch)
    repo2 = Repo(path=path)
    try:
        rep = repo2.back.recovery_report
        assert rep is not None and rep["wal"]["bounded"] == 1, rep
        assert rep["feeds_skipped"] >= 10, rep
        assert opened == [], opened  # the whole-repo scan was skipped
        for i, url in enumerate(urls):
            assert (repo2.doc(url) or {}).get("n") == i
    finally:
        repo2.close()


def test_unbounded_when_marker_mismatches_journal(tmp_path, monkeypatch):
    """Bounding must never skip real damage: a journal that does NOT
    provably belong to the crashed session (stamp mismatch) falls back
    to the full scan."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", "1")
    path = str(tmp_path / "r")
    repo = Repo(path=path)
    repo.create({"n": 1})
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.back._stores.flush_now()
    repo.back.durability.flush_now()
    del repo  # crash
    # corrupt the stamp: marker no longer matches the journal header
    with open(os.path.join(path, "repo.dirty"), "wb") as fh:
        fh.write(b"some-other-session")
    repo2 = Repo(path=path)
    try:
        rep = repo2.back.recovery_report
        assert rep is not None
        assert rep["wal"]["bounded"] == 0, rep["wal"]
        assert rep.get("feeds_skipped", 0) == 0, rep
    finally:
        repo2.close()


def test_ack_durable_echo_is_powercut_durable(tmp_path, monkeypatch):
    """HM_ACK_DURABLE=1 at tier 1: the LocalPatch echo IS a durable
    ack — every echoed edit survives a power cut with NO explicit
    flush anywhere (the bench config_writers pacing contract)."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", "1")
    monkeypatch.setenv("HM_ACK_DURABLE", "1")
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        repo = Repo(path=str(work))
        url = repo.create({"edits": []})
        done = []
        h = repo.watch(
            url, lambda d, _i: done.append(len(d.get("edits", [])))
        )
        for i in range(5):
            repo.change(url, lambda d, i=i: d["edits"].append(i))
        if repo.back.live is not None:
            repo.back.live.flush_now()
        wait_until(lambda: done and max(done) == 5)
        repo.back._stores.flush_now()
        h.close()
        k = len(rec.events)
        # crash: NO durability.flush_now() — the echoes were the acks
    dst = str(tmp_path / "cut")
    rec.materialize(dst, k, powercut=True)
    repo2 = Repo(path=dst)
    try:
        edits = list((repo2.doc(url) or {}).get("edits", []))
        assert edits == list(range(5)), edits
    finally:
        repo2.close()


# ---------------------------------------------------------------------------
# hardening regressions: checkpoint/commit/replay failure paths, dry-run
# preview fidelity, and the journal-less stale-stamp hazard


class _SyncProbe:
    """Checkpoint-pending stand-in: counts syncs, optionally fails."""

    def __init__(self, fail=False):
        self.fail = fail
        self.synced = 0

    def sync(self):
        if self.fail:
            raise OSError("EIO")
        self.synced += 1


def test_checkpoint_sync_failure_keeps_all_remaining_pending(tmp_path):
    """A checkpoint aborted by one feed's failed sync must re-add the
    failing feed AND every not-yet-synced one behind it — dropping
    them would let a later successful rotation discard K_APPEND
    records whose logs never reached the platter."""
    wal = WriteAheadLog(str(tmp_path / "wal.log"), tier=1)
    a, b, c = _SyncProbe(), _SyncProbe(fail=True), _SyncProbe()
    assert wal.append("aa", 0, b"x", a) is not None
    assert wal.append("bb", 0, b"x", b) is not None
    assert wal.append("cc", 0, b"x", c) is not None
    out = wal.checkpoint()
    assert out["synced_feeds"] == 1  # only `aa` reached the platter
    assert a.synced == 1 and c.synced == 0
    assert set(wal._ckpt_pending) == {"bb", "cc"}, wal._ckpt_pending
    # the journal was NOT rotated: every record is still replayable
    _h, dirty, records, _t = read_journal(str(tmp_path / "wal.log"))
    assert {n for n, _i, _d in records} == {"aa", "bb", "cc"}
    b.fail = False
    out2 = wal.checkpoint()
    assert out2["synced_feeds"] == 2 and not wal._ckpt_pending


def test_commit_after_failed_close_raises_not_acks(
    tmp_path, monkeypatch
):
    """A committer woken by closure WITHOUT a covering fsync (failed
    close) must raise — returning would grant a durable ack for bytes
    that never reached the platter."""
    wal = WriteAheadLog(str(tmp_path / "wal.log"), tier=2)
    end = wal.append("aa", 0, b"x")
    wal.commit(end)  # healthy baseline: fsync works
    end2 = wal.append("aa", 1, b"y")

    def broken_fsync(_fh):
        raise OSError("EIO")

    monkeypatch.setattr(walmod, "io_fsync", broken_fsync)
    assert wal.close() is False  # final sync failed
    with pytest.raises(OSError):
        wal.commit(end2)


def _bare_back(work, storage_fn):
    """Minimal recover() target: path + feeds._storage_fn."""
    from types import SimpleNamespace

    return SimpleNamespace(
        path=str(work),
        feeds=SimpleNamespace(_storage_fn=storage_fn),
        durability=SimpleNamespace(),
    )


def test_dry_run_replay_preview_matches_repair_on_gap(tmp_path):
    """tools/scrub.py --dry-run must preview exactly what repair will
    append: a journal with a GAP (records for indices the log can
    never reach sequentially) replays only the contiguous extension."""
    work = tmp_path / "w"
    os.makedirs(str(work / "feeds" / "aa"))
    st = FileFeedStorage(str(work / "feeds" / "aa" / "aafeed"))
    st.append(b"b0")
    st.close()
    wal = WriteAheadLog(str(work / "wal.log"), tier=1)
    assert wal.append("aafeed", 1, b"b1") is not None  # contiguous
    assert wal.append("aafeed", 3, b"b3") is not None  # gap: no idx 2
    wal.sync()  # durable journal; no close (crash)

    def fn(name):
        return FileFeedStorage(str(work / "feeds" / "aa" / name))

    dry = walmod.recover(_bare_back(work, fn), repair=False)
    real = walmod.recover(_bare_back(work, fn), repair=True)
    assert dry["replay_would"] == 1, dry
    assert real["replayed"] == 1 and real["skipped"] == 1, real
    assert dry["replay_would"] == real["replayed"]


def test_replay_sync_failure_preserves_journal(tmp_path):
    """recover() must NOT consume the journal when a replayed feed's
    fsync failed: the replayed block exists only in page cache, and
    the journal is its one durable copy until a later recovery (or
    checkpoint) lands it."""
    work = tmp_path / "w"
    os.makedirs(str(work / "feeds" / "aa"))
    wal = WriteAheadLog(str(work / "wal.log"), tier=1)
    assert wal.append("aafeed", 0, b"b0") is not None
    wal.sync()  # crash: no close

    class _FailingSyncStorage(FileFeedStorage):
        def sync(self):
            raise OSError("EIO")

    def failing_fn(name):
        return _FailingSyncStorage(str(work / "feeds" / "aa" / name))

    def ok_fn(name):
        return FileFeedStorage(str(work / "feeds" / "aa" / name))

    rep = walmod.recover(_bare_back(work, failing_fn), repair=True)
    assert rep["replayed"] == 1 and rep.get("replay_sync_failed") == 1
    assert os.path.exists(str(work / "wal.log"))  # NOT consumed
    # a later healthy recovery consumes it (block already in the log)
    rep2 = walmod.recover(_bare_back(work, ok_fn), repair=True)
    assert rep2["skipped"] == 1 and "replay_sync_failed" not in rep2
    assert not os.path.exists(str(work / "wal.log"))


def test_journalless_session_write_invalidates_stale_stamp(
    tmp_path, monkeypatch
):
    """A writable HM_RECOVER=0 session preserves the crashed marker +
    journal for a manual scrub — but its own journal-less writes are
    OUTSIDE that journal's dirty ledger. The first write must break
    the stamp match, so a crash of THIS session recovers with the
    full sidecar scan instead of trusting the stale ledger."""
    from hypermerge_tpu.repo import Repo

    monkeypatch.setenv("HM_FSYNC", "1")
    path = str(tmp_path / "r")
    repo = Repo(path=path)
    url = repo.create({"n": 1})
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.back._stores.flush_now()
    repo.back.durability.flush_now()
    del repo  # crash A: marker(stamp A) + wal.log(A) left behind

    monkeypatch.setenv("HM_RECOVER", "0")
    repo2 = Repo(path=path)
    assert repo2.back.recovery_report is None  # recovery skipped
    assert repo2.back.durability.wal is None  # journal-less session
    with open(os.path.join(path, "repo.dirty"), "rb") as fh:
        stamp_before = fh.read()
    repo2.change(url, lambda d: d.__setitem__("n", 2))
    if repo2.back.live is not None:
        repo2.back.live.flush_now()
    repo2.back._stores.flush_now()
    repo2.back.durability.flush_now()
    with open(os.path.join(path, "repo.dirty"), "rb") as fh:
        stamp_after = fh.read()
    assert stamp_after == stamp_before + b"+journalless"
    del repo2  # crash B: damaged feeds are NOT in A's ledger

    monkeypatch.setenv("HM_RECOVER", "1")
    repo3 = Repo(path=path)
    try:
        rep = repo3.back.recovery_report
        assert rep is not None, "marker gone — no crash simulated"
        # stale ledger refused: full scan, nothing skipped
        assert rep["wal"]["session_match"] == 0, rep["wal"]
        assert rep["wal"]["bounded"] == 0, rep["wal"]
        assert rep.get("feeds_skipped", 0) == 0, rep
        assert (repo3.doc(url) or {}).get("n") == 2
    finally:
        repo3.close()


def test_concurrent_append_and_sync_share_write_handles(tmp_path):
    """The cached write handles are shared between the appender and
    the WAL checkpoint thread's sync(): interleaved use must leave a
    consistent .len sidecar (pre-lock, a seek/write interleaving
    could tear it or close an fd mid-fsync)."""
    import threading

    st = FileFeedStorage(str(tmp_path / "ab" / "feed"))
    stop = threading.Event()
    errs = []

    def syncer():
        while not stop.is_set():
            try:
                st.sync()
            except Exception as e:  # noqa: BLE001 - any escape fails
                errs.append(e)
                return

    t = threading.Thread(target=syncer)
    t.start()
    try:
        for i in range(400):
            st.append(b"b" * (i % 17 + 1))
    finally:
        stop.set()
        t.join(10)
    assert not errs, errs
    st.close()
    fresh = FileFeedStorage(st.path)
    assert fresh._try_count_shortcut(), ".len torn or stale"
    assert len(fresh) == 400
    fresh.close()


def test_commit_ack_covers_unjournaled_legacy_appends(
    tmp_path, monkeypatch
):
    """HM_ACK_DURABLE: commit_ack's journal fsync only vouches for
    blocks the journal holds. An append that fell back to the legacy
    path (broken journal) was mark_dirty'd instead — commit_ack must
    drain the legacy barrier too, or the durable ack covers bytes
    that exist only in page cache."""
    monkeypatch.setenv("HM_FSYNC", "1")
    monkeypatch.setenv("HM_FSYNC_MS", "10000")  # no background flush
    work = tmp_path / "work"
    rec = F.CrashRecorder(str(work))
    with F.activate(recorder=rec):
        os.makedirs(str(work))
        dm = DurabilityManager()
        wal = WriteAheadLog(str(work / "wal.log"), tier=1)
        dm.attach_wal(wal)
        st = FileFeedStorage(
            str(work / "feeds" / "ab" / "feed0"), durability=dm
        )
        # break the journal mid-session: appends now fall back to the
        # legacy per-feed path (mark_dirty), and wal.sync() is a
        # silent no-op (_synced already covers the frozen _end)
        with wal._cv:
            wal._closed = True
        st.append(b"block")
        mark = len(rec.events)
        dm.commit_ack()  # the durable ack point
        counts = _fsyncs(rec, mark)
        assert any(
            p.startswith("feeds/") for p in counts
        ), f"legacy append not fsynced at ack: {counts}"
        dm.close()


# ---------------------------------------------------------------------------
# the crash matrix crossed with the sharded write plane: kill -9 a
# worker PROCESS mid-burst and hold the same gate — acked_lost=0


def test_worker_sigkill_midburst_acked_lost_zero(tmp_path):
    """SIGKILL the worker that OWNS a hot doc's shard mid-burst under
    HM_FSYNC=1 + durable acks: the hub supervises a respawn, the fresh
    worker replays its journal prefix, and every edit whose durable
    ack the writer received survives (acked_lost=0). The one write in
    flight INSIDE the dead worker is allowed to vanish — it was never
    acked — and a brand-new connection both reads the recovered doc
    and writes to it (the backend mints it a fresh actor; grants died
    with the worker and are never resurrected).

    The ack signal is a second OBSERVER connection's watch state: the
    writer's own handle fans out each change preview optimistically,
    but the observer's value moves only when the backend's patch
    broadcast arrives — and under HM_ACK_DURABLE that broadcast is
    gated on the WAL group commit covering the edit."""
    import signal
    import subprocess
    import sys
    import tempfile
    import threading
    import time

    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    sock = tempfile.mktemp(suffix=".sock")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo_root,
        "HM_FSYNC": "1",
        "HM_ACK_DURABLE": "1",
        "HM_WAL_MS": "3",
        "HM_WORKERS": "2",
        "HM_WORKER_RESPAWN_MS": "100",
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "hypermerge_tpu.net.ipc",
         str(tmp_path / "repo"), sock, "--hub"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
        cwd=repo_root,
    )
    lines = []
    threading.Thread(
        target=lambda: lines.extend(iter(proc.stdout.readline, "")),
        daemon=True,
    ).start()

    def _sync(fn, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if fn():
                return True
            time.sleep(0.02)
        return False

    def _val(handle):
        try:
            return handle.value(timeout=0.2)
        except TimeoutError:
            return None

    closers = []
    try:
        assert _sync(lambda: os.path.exists(sock)), "daemon not up"
        assert _sync(
            lambda: sum("worker" in ln for ln in lines) >= 2
        ), lines
        pids = {}
        for ln in list(lines):
            parts = ln.split()
            if parts[:1] == ["worker"] and "respawned" not in parts:
                pids[int(parts[1])] = int(parts[3])

        from hypermerge_tpu.net.ipc import _shard_of, connect_frontend

        front, close = connect_frontend(sock)
        closers.append(close)
        url = front.create({"edits": {}})
        h = front.open(url)
        assert _sync(lambda: "edits" in (_val(h) or {}))
        owner = _shard_of(url[len("hypermerge:/"):], 2)

        # the durable-ack probe: a read-only connection whose value
        # only the backend's (durability-gated) patch pushes can move
        obs, close_obs = connect_frontend(sock)
        closers.append(close_obs)
        hobs = obs.open(url)
        assert _sync(lambda: "edits" in (_val(hobs) or {}))

        def _acked(key, val, timeout=10):
            return _sync(
                lambda: (_val(hobs) or {})
                .get("edits", {}).get(key) == val,
                timeout=timeout,
            )

        acked = []
        for i in range(8):  # ack-paced burst: durable echo gates each
            front.change(
                url, lambda d, i=i: d["edits"].__setitem__(str(i), i)
            )
            assert _acked(str(i), i), f"edit {i} never acked"
            acked.append(str(i))

        os.kill(pids[owner], signal.SIGKILL)  # mid-burst: kill -9
        # the next write races worker-death detection: it either lands
        # after the respawn (hub buffered it) or was swallowed by the
        # dying socket — it only joins the gate if its ack came back
        front.change(
            url, lambda d: d["edits"].__setitem__("post-kill", 1)
        )
        if _acked("post-kill", 1, timeout=5):
            acked.append("post-kill")

        assert _sync(
            lambda: any("respawned" in ln for ln in lines)
        ), "hub never respawned the killed worker"

        # a brand-new connection sees every acked edit: the respawned
        # worker replayed them from the journal prefix (acked_lost=0)
        f2, close2 = connect_frontend(sock)
        closers.append(close2)
        h2 = f2.open(url)
        assert _sync(lambda: "edits" in (_val(h2) or {}))

        def _lost():
            edits = (_val(h2) or {}).get("edits", {})
            return [k for k in acked if k not in edits]

        assert _sync(lambda: not _lost(), timeout=20), (
            f"acked edits lost across worker kill -9: {_lost()}"
        )
        # ...and can WRITE: the backend mints the new connection a
        # fresh actor rather than resurrecting a dead grant
        f2.change(
            url, lambda d: d["edits"].__setitem__("fresh", 1)
        )
        assert _sync(
            lambda: (_val(h2) or {})
            .get("edits", {}).get("fresh") == 1,
            timeout=15,
        ), "respawned worker refuses new writers"
    finally:
        for close in closers:
            try:
                close()
            except Exception:
                pass
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        if os.path.exists(sock):
            os.remove(sock)
