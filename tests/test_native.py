"""Native C++ layer: build/load, crypto parity, brotli block codec.

The native layer replaces the reference's native npm addons (SURVEY.md
§2.4: sodium-native ed25519/blake2b, iltorb brotli). Every capability has
a pure-Python fallback, so these tests assert (a) the native path works
when available, (b) native and fallback agree bit-for-bit, (c) the
framework still functions with the native layer disabled.
"""

import hashlib
import os
import subprocess
import sys

import pytest

from hypermerge_tpu import native
from hypermerge_tpu.storage import block as blockmod
from hypermerge_tpu.utils import crypto
from hypermerge_tpu.utils import ed25519 as pure

needs_native = pytest.mark.skipif(
    not native.available(), reason="native layer did not build/load"
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@needs_native
def test_native_caps_all_present():
    caps = native.caps()
    assert caps & native.CAP_ZLIB
    # this image ships libsodium + libbrotli; if either vanishes the
    # fallbacks still run but we want to notice
    assert caps & native.CAP_SODIUM
    assert caps & native.CAP_BROTLI


@needs_native
def test_ed25519_native_matches_pure_python():
    seed = bytes(range(32))
    msg = b"the quick brown fox"
    pub_n = native.ed25519_public(seed)
    sig_n = native.ed25519_sign(seed, msg)
    assert pub_n == pure.public_key(seed)
    assert sig_n == pure.sign(msg, seed)
    assert native.ed25519_verify(pub_n, msg, sig_n) is True
    assert native.ed25519_verify(pub_n, msg + b"!", sig_n) is False
    assert pure.verify(msg, sig_n, pub_n)


@needs_native
def test_blake2b_native_matches_hashlib():
    for data, key in ((b"", b""), (b"abc", b""), (b"x" * 1000, b"k" * 32)):
        want = hashlib.blake2b(data, key=key, digest_size=32).digest()
        assert native.blake2b(data, key, 32) == want


@needs_native
def test_merkle_root_native_matches_fallback(monkeypatch):
    leaves = [crypto.leaf_hash(bytes([i]) * 10) for i in range(7)]
    want = crypto.merkle_root(leaves)
    # force the pure-Python path and compare
    monkeypatch.setattr(native, "merkle_root", lambda _: None)
    assert crypto.merkle_root(leaves) == want
    assert crypto.merkle_root([]) == b"\x00" * 32
    assert crypto.merkle_root(leaves[:1]) == leaves[0]


@needs_native
def test_block_codec_brotli_roundtrip():
    obj = {"actor": "a" * 44, "ops": [{"k": f"key{i}"} for i in range(50)]}
    data = blockmod.pack(obj)
    assert data[:2] == b"BR"
    assert blockmod.unpack(data) == obj


def test_block_codec_reads_all_formats():
    """zlib-written and raw-JSON blocks stay readable regardless of the
    writer configuration (feed forward/backward compatibility)."""
    import zlib

    from hypermerge_tpu.utils.json_buffer import bufferify

    obj = {"x": [1, 2, 3], "s": "abc" * 100}
    raw = bufferify(obj)
    legacy_zlib = b"ZL" + zlib.compress(raw, level=6)
    assert blockmod.unpack(legacy_zlib) == obj
    assert blockmod.unpack(raw) == obj  # raw JSON (incompressible path)


def test_block_codec_rejects_corrupt_blocks_with_valueerror():
    """Remote blocks are untrusted: every corrupt shape must surface as
    ValueError (what Actor._parse_block catches), never struct.error /
    zlib.error / a giant allocation."""
    import struct

    cases = [
        b"BRxy",  # truncated header
        b"BR" + struct.pack("<I", 0xFFFFFFFF) + b"junk",  # 4GiB claim
        b"BR" + struct.pack("<I", 100) + b"notbrotli",  # bad stream
        b"ZL" + b"notzlib",  # bad zlib stream
    ]
    for data in cases:
        with pytest.raises(ValueError):
            blockmod.unpack(data)


def test_block_codec_forced_zlib(monkeypatch):
    monkeypatch.setenv("HM_BLOCK_CODEC", "zlib")
    obj = {"k": "v" * 600}  # above the small-block raw threshold
    data = blockmod.pack(obj)
    assert data[:2] == b"ZL"
    assert blockmod.unpack(data) == obj


def test_tiny_blocks_stored_raw():
    """Blocks under the compression threshold store as raw JSON —
    framing+cpu beats the handful of saved bytes on interactive
    single-op changes."""
    obj = {"k": "v"}
    data = blockmod.pack(obj)
    assert data[:1] in (b"{", b"[")
    assert blockmod.unpack(data) == obj


def test_crypto_facade_signs_and_verifies():
    seed = os.urandom(32)
    pub = crypto.public_key(seed)
    sig = crypto.sign(b"msg", seed)
    assert crypto.verify(b"msg", sig, pub)
    assert not crypto.verify(b"other", sig, pub)
    assert not crypto.verify(b"msg", sig[:-1] + bytes([sig[-1] ^ 1]), pub)


def test_framework_runs_without_native_layer():
    """HM_NO_NATIVE disables the native path entirely; keys and the
    block codec must degrade to pure Python in a fresh process."""
    code = """
import os
assert os.environ["HM_NO_NATIVE"] == "1"
from hypermerge_tpu import native
assert not native.available()
assert native.caps() == 0
from hypermerge_tpu.utils import keys, crypto
pair = keys.create(seed=bytes(32))
assert pair.public_key  # pure-python ed25519
sig = crypto.sign(b"m", bytes(32))
assert crypto.verify(b"m", sig, keys.decode(pair.public_key))
from hypermerge_tpu.storage import block
data = block.pack({"a": "b" * 600})
assert data[:2] == b"ZL"  # brotli unavailable -> zlib
assert block.unpack(data) == {"a": "b" * 600}
print("OK")
"""
    env = dict(os.environ, HM_NO_NATIVE="1")
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


@needs_native
def test_feed_blocks_use_brotli_end_to_end(tmp_path):
    """Blocks written through the repo runtime pack with the native
    codec and replay identically on reopen."""
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url
    from helpers import plainify

    path = str(tmp_path / "repo")
    repo = Repo(path=path)
    url = repo.create({"text": "hello " * 200})
    repo.change(url, lambda d: d.__setitem__("n", 1))
    want = plainify(repo.doc(url))
    doc_id = validate_doc_url(url)
    feed = repo.back.feeds.get_feed(doc_id)
    assert any(b[:2] == b"BR" for b in feed.read_all())
    repo.close()

    repo2 = Repo(path=path)
    assert plainify(repo2.doc(url)) == want
    repo2.close()
