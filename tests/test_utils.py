"""Utility layer: queue discipline, mapset, base58, ids, ed25519, json."""

import threading

import pytest

from hypermerge_tpu.utils import base58, ed25519, ids, keys
from hypermerge_tpu.utils.json_buffer import bufferify, parse, parse_all_valid
from hypermerge_tpu.utils.mapset import MapSet
from hypermerge_tpu.utils.queue import Queue


class TestQueue:
    def test_buffers_until_subscribe_then_direct(self):
        q = Queue("t")
        q.push(1)
        q.push(2)
        seen = []
        q.subscribe(seen.append)
        assert seen == [1, 2]
        q.push(3)
        assert seen == [1, 2, 3]

    def test_second_subscriber_raises(self):
        q = Queue("t")
        q.subscribe(lambda x: None)
        with pytest.raises(RuntimeError):
            q.subscribe(lambda x: None)

    def test_once(self):
        q = Queue("t")
        seen = []
        q.once(seen.append)
        q.push("a")
        q.push("b")
        assert seen == ["a"]
        # "b" stays buffered for the next subscriber
        out = []
        q.subscribe(out.append)
        assert out == ["b"]

    def test_first_blocks_until_push(self):
        q = Queue("t")
        result = []

        def waiter():
            result.append(q.first(timeout=5))

        th = threading.Thread(target=waiter)
        th.start()
        q.push(42)
        th.join(5)
        assert result == [42]

    def test_reentrant_push_preserves_order(self):
        q = Queue("t")
        seen = []

        def sub(x):
            seen.append(x)
            if x == 1:
                q.push(3)

        q.subscribe(sub)
        q.push(1)
        q.push(2)
        assert seen == [1, 3, 2]

    def test_drain(self):
        q = Queue("t")
        q.push(1)
        q.push(2)
        assert q.drain() == [1, 2]
        assert q.length == 0


class TestMapSet:
    def test_add_get_keyswith(self):
        ms = MapSet()
        assert ms.add("x", 1)
        assert not ms.add("x", 1)
        ms.add("x", 2)
        ms.add("y", 2)
        assert ms.get("x") == {1, 2}
        assert sorted(ms.keys_with(2)) == ["x", "y"]
        assert ms.keys_with(99) == []

    def test_remove_cleans_empty(self):
        ms = MapSet()
        ms.add("x", 1)
        ms.remove("x", 1)
        assert "x" not in ms.keys()


class TestBase58:
    def test_roundtrip(self):
        for data in [b"", b"\x00", b"\x00\x00hello", b"\xff" * 32, bytes(range(32))]:
            assert base58.decode(base58.encode(data)) == data

    def test_known_vector(self):
        # 'hello world' standard base58 vector
        assert base58.encode(b"hello world") == "StV1DL6CwTryKyV"
        assert base58.decode("StV1DL6CwTryKyV") == b"hello world"

    def test_invalid_char(self):
        with pytest.raises(ValueError):
            base58.decode("0OIl")


class TestEd25519:
    def test_rfc8032_vector_1(self):
        # RFC 8032 §7.1 TEST 1 (empty message)
        seed = bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
        )
        pub = bytes.fromhex(
            "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
        )
        sig = bytes.fromhex(
            "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
            "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
        )
        assert ed25519.public_key(seed) == pub
        assert ed25519.sign(b"", seed) == sig
        assert ed25519.verify(b"", sig, pub)

    def test_rfc8032_vector_2(self):
        seed = bytes.fromhex(
            "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
        )
        pub = bytes.fromhex(
            "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c"
        )
        msg = bytes.fromhex("72")
        sig = ed25519.sign(msg, seed)
        assert sig == bytes.fromhex(
            "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
            "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
        )
        assert ed25519.verify(msg, sig, pub)
        assert not ed25519.verify(b"tampered", sig, pub)

    def test_keys_roundtrip_and_discovery(self):
        pair = keys.create()
        buf = keys.decode_pair(pair)
        assert keys.encode_pair(buf) == pair
        assert len(buf.public_key) == 32
        d1 = keys.discovery_id(pair.public_key)
        d2 = keys.discovery_id(pair.public_key)
        assert d1 == d2
        other = keys.create()
        assert keys.discovery_id(other.public_key) != d1
        # signing with the pair's seed verifies under its public key
        sig = ed25519.sign(b"block", buf.secret_key)
        assert ed25519.verify(b"block", sig, buf.public_key)


class TestIds:
    def test_url_roundtrip(self):
        pair = keys.create()
        url = ids.to_doc_url(pair.public_key)
        assert ids.validate_doc_url(url) == pair.public_key
        assert ids.url_to_id(url) == pair.public_key
        furl = ids.to_hyperfile_url(pair.public_key)
        assert ids.validate_file_url(furl) == pair.public_key
        assert ids.is_doc_url(url) and not ids.is_doc_url(furl)

    def test_invalid_urls(self):
        with pytest.raises(ValueError):
            ids.validate_doc_url("hypermerge:/notakey")
        with pytest.raises(ValueError):
            ids.validate_doc_url("http://example.com")
        with pytest.raises(ValueError):
            ids.validate_url("nonsense")

    def test_root_actor_identity(self):
        pair = keys.create()
        assert ids.root_actor_id(ids.DocId(pair.public_key)) == pair.public_key


class TestJsonBuffer:
    def test_roundtrip(self):
        obj = {"b": 1, "a": [1, 2, {"x": None}]}
        assert parse(bufferify(obj)) == obj

    def test_parse_all_valid_skips_corrupt(self):
        bufs = [bufferify({"ok": 1}), b"\xff\xfe garbage", bufferify(2)]
        assert parse_all_valid(bufs) == [{"ok": 1}, 2]


def test_queue_first_with_none_item():
    q = Queue("t")
    q.push(None)
    q.push(7)
    assert q.first(timeout=1) is None


def test_queue_no_deadlock_cross_push():
    # two queues whose subscribers push to each other must not deadlock
    import threading as _t

    q1, q2 = Queue("q1"), Queue("q2")
    seen = []
    q1.subscribe(lambda x: (seen.append(("q1", x)), q2.push(x + 1) if x < 3 else None))
    q2.subscribe(lambda x: (seen.append(("q2", x)), q1.push(x + 1) if x < 3 else None))
    t1 = _t.Thread(target=lambda: q1.push(0))
    t2 = _t.Thread(target=lambda: q2.push(0))
    t1.start(); t2.start()
    t1.join(5); t2.join(5)
    assert not t1.is_alive() and not t2.is_alive()
    assert len(seen) == 8


def test_ed25519_rejects_noncanonical_encoding():
    seed = bytes(32)
    pub = ed25519.public_key(seed)
    sig = ed25519.sign(b"m", seed)
    # y >= p re-encoding of R must be rejected, not verified
    p = 2**255 - 19
    r_int = int.from_bytes(sig[:32], "little")
    y = r_int & ((1 << 255) - 1)
    if y < 19:  # re-encodable; otherwise just assert canonical verify works
        bad = (y + p) | (r_int & (1 << 255))
        bad_sig = bad.to_bytes(32, "little") + sig[32:]
        assert not ed25519.verify(b"m", bad_sig, pub)
    assert ed25519.verify(b"m", sig, pub)


class TestDebouncer:
    def test_coalesces_and_flushes(self):
        import time as _t

        from hypermerge_tpu.utils.debounce import Debouncer

        batches = []
        d = Debouncer(batches.append, window_s=0.01)
        for i in range(50):
            d.mark("k", i)
        d.flush_now()
        assert batches and len(batches) <= 3
        assert batches[0]["k"] == 49  # default merge: latest wins
        d.close()

    def test_merge_fn(self):
        from hypermerge_tpu.utils.debounce import Debouncer

        batches = []
        d = Debouncer(batches.append, window_s=0.01, merge=min)
        d.mark("k", 7)
        d.mark("k", 3)
        d.mark("k", 9)
        d.flush_now()
        assert batches[0]["k"] == 3
        d.close()

    def test_close_drains_pending(self):
        """Marks made before close() still flush — orderly shutdown
        loses nothing (the replication tail relies on this)."""
        from hypermerge_tpu.utils.debounce import Debouncer

        batches = []
        d = Debouncer(batches.append, window_s=5.0)  # huge window
        d.mark("a", 1)
        d.mark("b", 2)
        d.close()  # must not wait the 5s window
        assert {"a": 1, "b": 2} in batches

    def test_flush_now_waits_for_inflight_flush(self):
        """flush_now returns only after flush_fn FINISHED, not merely
        after the pending set was swapped out."""
        import threading as _th

        from hypermerge_tpu.utils.debounce import Debouncer

        started = _th.Event()
        release = _th.Event()
        done = []

        def slow_flush(batch):
            started.set()
            release.wait(5)
            done.append(batch)

        d = Debouncer(slow_flush, window_s=0.0)
        d.mark("k")
        assert started.wait(5)
        waiter_done = _th.Event()

        def waiter():
            d.flush_now(timeout=5)
            waiter_done.set()

        t = _th.Thread(target=waiter)
        t.start()
        assert not waiter_done.wait(0.1), "returned during in-flight flush"
        release.set()
        assert waiter_done.wait(5)
        assert done
        t.join(5)
        d.close()

    def test_flush_now_reports_timeout(self):
        """flush_now returns False when the drain did not finish inside
        the timeout — destroy() relies on this to refuse deleting rows
        a late flush would resurrect — and True once it has."""
        import threading as _th

        from hypermerge_tpu.utils.debounce import Debouncer

        release = _th.Event()

        def stuck_flush(batch):
            release.wait(5)

        d = Debouncer(stuck_flush, window_s=0.0)
        d.mark("k")
        assert d.flush_now(timeout=0.05) is False
        release.set()
        assert d.flush_now(timeout=5) is True
        d.close()


def test_debouncer_adaptive_window_stretches_under_load():
    """With max_window_s set, a slow flush stretches the next window so
    batches grow instead of flush count (the replication live tail's
    self-balancing behavior)."""
    import threading as _th
    import time as _t

    from hypermerge_tpu.utils.debounce import Debouncer

    batches = []

    def slow_flush(batch):
        batches.append(dict(batch))
        _t.sleep(0.05)  # flushing is slower than the floor window

    d = Debouncer(slow_flush, window_s=0.001, max_window_s=0.2)
    stop = _t.monotonic() + 0.5
    i = 0
    while _t.monotonic() < stop:
        d.mark(i % 4, i)
        i += 1
        _t.sleep(0.001)
    d.flush_now(timeout=5)
    d.close()
    total_marks = sum(len(b) for b in batches)
    assert total_marks >= 4  # all keys flushed at least once
    # with ~0.05s flushes over 0.5s, a non-adaptive 1ms window would do
    # hundreds of flushes; adaptation caps it near duration/flush_time
    assert len(batches) <= 14, len(batches)


# ---------------------------------------------------------------------------
# debug namespaces honor RUNTIME changes (round 13: daemons toggle
# namespaces without a restart — the patterns were parsed once at
# import before)


def test_debug_enabled_tracks_env_changes(monkeypatch):
    from hypermerge_tpu.utils import debug

    monkeypatch.setenv("DEBUG", "")
    assert not debug.enabled("live")
    monkeypatch.setenv("DEBUG", "live,net:*")
    assert debug.enabled("live")
    assert debug.enabled("net:tcp")
    assert not debug.enabled("storage")
    monkeypatch.setenv("DEBUG", "storage")
    assert debug.enabled("storage")
    assert not debug.enabled("live")


def test_debug_set_patterns_overrides_env(monkeypatch):
    from hypermerge_tpu.utils import debug

    monkeypatch.setenv("DEBUG", "live")
    debug.set_patterns("repl*")
    try:
        assert debug.enabled("replication")
        assert not debug.enabled("live")  # override wins over env
        debug.set_patterns(["a", "b:*"])
        assert debug.enabled("b:x") and debug.enabled("a")
    finally:
        debug.set_patterns(None)  # back to the env
    assert debug.enabled("live")
