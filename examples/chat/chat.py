"""P2P chat over an encrypted TCP swarm — the reference's flagship
example, rebuilt on hypermerge_tpu (reference examples/chat/channel.js:
a doc with a `messages` list, each peer appending and watching).

Serve (creates the channel doc, prints its url + address):
    python examples/chat/chat.py serve --name alice [--port 9120]

Join from another terminal/machine:
    python examples/chat/chat.py join HOST:PORT 'hypermerge:/<docId>' \
        --name bob

Type lines to send; incoming messages print as they replicate. Each
peer's messages ride its own signed feed; the doc converges via CRDT
merge, so any number of peers can talk with no server.
"""

import argparse
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from hypermerge_tpu.net.tcp import TcpSwarm  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402


def run_chat(repo: Repo, url: str, name: str) -> None:
    seen = [0]
    lock = threading.Lock()

    def on_change(doc, _index):
        if doc is None:
            return
        msgs = doc.get("messages", [])
        with lock:
            for m in list(msgs)[seen[0] :]:
                if isinstance(m, dict) and m.get("from") != name:
                    print(f"\r<{m.get('from')}> {m.get('text')}")
                    print("> ", end="", flush=True)
            seen[0] = len(msgs)

    handle = repo.watch(url, on_change)
    print("connected — type messages, ctrl-d to quit")
    print("> ", end="", flush=True)
    try:
        for line in sys.stdin:
            text = line.rstrip("\n")
            if text:
                repo.change(
                    url,
                    # bind by value: queued change fns run later on a
                    # pending doc, after `text` has been rebound
                    lambda d, text=text: d["messages"].append(
                        {"from": name, "text": text}
                    ),
                )
            print("> ", end="", flush=True)
    except KeyboardInterrupt:
        pass
    handle.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    serve = sub.add_parser("serve", help="create a channel and listen")
    serve.add_argument("--port", type=int, default=9120)
    serve.add_argument("--name", default="host")
    serve.add_argument("--repo", default=None, help="persist to this dir")
    join = sub.add_parser("join", help="join a channel")
    join.add_argument("address", help="HOST:PORT of a serving peer")
    join.add_argument("url", help="the channel doc url")
    join.add_argument("--name", default="guest")
    join.add_argument("--repo", default=None)
    args = ap.parse_args()

    repo = (
        Repo(path=args.repo) if args.repo else Repo(memory=True)
    )
    if args.cmd == "serve":
        swarm = TcpSwarm(port=args.port)
        repo.set_swarm(swarm)
        url = repo.create({"messages": []})
        host, port = swarm.address
        print(f"channel: {url}")
        print(f"peers join with: {host}:{port} '{url}'")
        run_chat(repo, url, args.name)
    else:
        swarm = TcpSwarm()
        repo.set_swarm(swarm)
        host, _, port = args.address.partition(":")
        swarm.connect((host, int(port)))
        run_chat(repo, args.url, args.name)
    repo.close()


if __name__ == "__main__":
    main()
