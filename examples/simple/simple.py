"""Two repos in one process replicating a doc — the reference's
`examples/simple` (examples/simple/src/simple.ts): repoA creates a doc,
both repos watch it, edits from each side converge through the swarm.

    python examples/simple/simple.py
"""

import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from hypermerge_tpu.net.swarm import LoopbackHub, LoopbackSwarm  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402


def main() -> None:
    hub = LoopbackHub()
    repo_a, repo_b = Repo(memory=True), Repo(memory=True)
    repo_a.set_swarm(LoopbackSwarm(hub))
    repo_b.set_swarm(LoopbackSwarm(hub))

    doc_url = repo_a.create({"numbers": [2, 3, 4]})
    done_a, done_b = threading.Event(), threading.Event()

    def watcher(name, done):
        def on_change(state, _i) -> None:
            print(name, state)
            if state and len(state.get("numbers", [])) == 5:
                done.set()

        return on_change

    repo_a.watch(doc_url, watcher("RepoA", done_a))
    repo_b.watch(doc_url, watcher("RepoB", done_b))

    repo_a.change(
        doc_url,
        lambda d: (d["numbers"].append(5), d.__setitem__("foo", "bar")),
    )
    repo_b.change(
        doc_url,
        lambda d: (
            d["numbers"].insert(0, 1),
            d.__setitem__("bar", "foo"),
        ),
    )

    if not (done_a.wait(timeout=15) and done_b.wait(timeout=15)):
        raise SystemExit("did not converge")
    a, b = repo_a.doc(doc_url), repo_b.doc(doc_url)
    assert a == b, (a, b)
    print("converged:", a)
    repo_a.close()
    repo_b.close()


if __name__ == "__main__":
    main()
