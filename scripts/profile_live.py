"""Profile the live engine's adoption path: stored docs -> first live
edit, with the per-stage adoption timeline (pack / kernel / decode /
reach busy vs wall) and the lock-held vs lock-free split, then a
demote -> re-adopt cycle over the same docs.

Usage: [PROF_DOCS=4] [PROF_OPS=8192] [JAX_PLATFORMS=cpu] \
       python scripts/profile_live.py [--cprofile]
"""

import cProfile
import os
import pstats
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

n_docs = int(os.environ.get("PROF_DOCS", "4"))
n_ops = int(os.environ.get("PROF_OPS", "8192"))

from hypermerge_tpu.ops.corpus import make_corpus  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402

ADOPT_KEYS = (
    "t_adopt_pack", "t_adopt_kernel", "t_adopt_decode",
    "t_adopt_reach", "t_adopt_lock_free", "t_adopt_lock_held",
)

tmp = tempfile.mkdtemp(prefix="hmlive")
t0 = time.perf_counter()
urls = make_corpus(tmp, n_docs, n_ops)
print(
    f"corpus: {n_docs} docs x {n_ops} ops in "
    f"{time.perf_counter() - t0:.2f}s"
)

repo = Repo(path=tmp)
handles = repo.open_many(urls)
for h in handles:
    assert h.value(timeout=120) is not None
eng = repo.back.live
assert eng is not None, "HM_LIVE=0: nothing to profile"


def _snap():
    return {k: eng.stats[k] for k in ADOPT_KEYS}


def _delta(before, after):
    return {k: after[k] - before[k] for k in ADOPT_KEYS}


def _timeline(label, d, wall):
    busy = sum(d[k] for k in ADOPT_KEYS[:4])
    print(f"{label} (wall {wall * 1e3:.1f}ms):")
    for k in ADOPT_KEYS[:4]:
        frac = d[k] / wall if wall else 0.0
        print(
            f"  {k[8:]:<10} {d[k] * 1e3:7.1f}ms  "
            f"{'#' * int(frac * 40):<40} {frac * 100:4.0f}%"
        )
    print(
        f"  lock-free  {d['t_adopt_lock_free'] * 1e3:7.1f}ms   "
        f"lock-HELD {d['t_adopt_lock_held'] * 1e3:7.2f}ms   "
        f"(other docs tick through all but the held sliver)"
    )
    print(
        f"  stage busy {busy * 1e3:7.1f}ms vs wall "
        f"{wall * 1e3:.1f}ms"
    )


def adopt_all(label):
    before = _snap()
    t0 = time.perf_counter()
    for u in urls:
        repo.change(u, lambda d: d.__setitem__("hot", 1))
    eng.flush_now()
    wall = time.perf_counter() - t0
    _timeline(label, _delta(before, _snap()), wall)
    return wall


def run():
    adopt_all(f"adoption ({n_docs} docs x {n_ops} ops)")
    demoted = eng.demote_idle(0)
    print(f"demote_idle(0): {demoted} docs demoted")
    before = _snap()
    t0 = time.perf_counter()
    for u in urls:
        repo.change(u, lambda d: d.__setitem__("hot", 2))
    eng.flush_now()
    wall = time.perf_counter() - t0
    _timeline("re-adoption after demote", _delta(before, _snap()), wall)
    s = eng.stats
    print(
        f"engine: adopted={s['adopted']} demoted={s['demoted']} "
        f"readopted={s['readopted']} refused={s['refused']} "
        f"live_bytes={s['live_bytes']:,} live_docs={s['live_docs']}"
    )


if "--cprofile" in sys.argv:
    prof = cProfile.Profile()
    prof.enable()
    run()
    prof.disable()
    stats = pstats.Stats(prof)
    stats.sort_stats("cumulative").print_stats(30)
else:
    run()

repo.close()
import shutil  # noqa: E402

shutil.rmtree(tmp, ignore_errors=True)
