"""M3 verify drive: device materialize at scale on the current platform.

Usage: python scripts/m3_verify.py [--cpu] [--docs N] [--changes N]
"""

import argparse
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def synth_doc(rng, n_changes=60, ops_per_change=8):
    from hypermerge_tpu.crdt.change import Action, ChangeRequest, OpIntent
    from hypermerge_tpu.crdt.opset import OpSet

    opset = OpSet()
    actors = ["alice", "bob", "carol"]
    req = ChangeRequest(
        "alice",
        1,
        0,
        "",
        (OpIntent(Action.MAKE_TEXT, "_root", key="t", temp_id="tmp:0"),),
    )
    opset.apply_local_request(req)
    text_obj = next(str(o) for o in opset.objects if str(o) != "0@_root")
    text_len = 0
    for _ in range(n_changes):
        a = actors[int(rng.integers(0, 3))]
        seq = opset.clock.get(a, 0) + 1
        intents = []
        for _ in range(ops_per_change):
            if rng.random() < 0.8:
                intents.append(
                    OpIntent(
                        Action.SET,
                        text_obj,
                        index=int(rng.integers(0, text_len + 1)),
                        insert=True,
                        value=chr(97 + int(rng.integers(0, 26))),
                    )
                )
                text_len += 1
            else:
                intents.append(
                    OpIntent(
                        Action.SET,
                        "_root",
                        key=f"k{int(rng.integers(0, 10))}",
                        value=int(rng.integers(0, 100)),
                    )
                )
        opset.apply_local_request(ChangeRequest(a, seq, 0, "", tuple(intents)))
    return opset


def plainify(v):
    from hypermerge_tpu.models import Counter, Table, Text

    if isinstance(v, Text):
        return ("t", str(v))
    if isinstance(v, Counter):
        return ("c", int(v))
    if isinstance(v, Table):
        return ("tb", {k: plainify(v.by_id(k)) for k in v.ids})
    if isinstance(v, dict):
        return {k: plainify(x) for k, x in v.items()}
    if isinstance(v, list):
        return [plainify(x) for x in v]
    return v


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--docs", type=int, default=64)
    ap.add_argument("--replicate", type=int, default=16)
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    log(f"devices: {jax.devices()}")

    from hypermerge_tpu.ops.columnar import pack_docs
    from hypermerge_tpu.ops.crdt_kernels import run_batch
    from hypermerge_tpu.ops.materialize import (
        DecodedBatch,
        materialize_docs,
    )

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    opsets = [synth_doc(rng) for _ in range(args.docs)]
    log(f"synth gen {args.docs} docs: {time.perf_counter()-t0:.2f}s, "
        f"max_op={opsets[0].max_op}")

    histories = [o.history for o in opsets]
    t0 = time.perf_counter()
    batch = pack_docs(histories)
    log(f"pack: {time.perf_counter()-t0:.3f}s shape={batch.shape}")

    t0 = time.perf_counter()
    out = run_batch(batch)
    jax.block_until_ready(out)
    log(f"first call (compile+run): {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    out = run_batch(batch)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    total_ops = int(batch.n_ops.sum())
    log(f"steady: {dt*1e3:.1f}ms, {total_ops} ops, "
        f"{total_ops/dt/1e6:.2f}M ops/s")

    dec = DecodedBatch(batch, out)
    docs = materialize_docs(dec)
    sample = [0, args.docs // 2, args.docs - 1]
    ok = all(
        plainify(docs[i]) == plainify(opsets[i].materialize()) for i in sample
    )
    log(f"host==device sampled: {ok}")
    if not ok:
        sys.exit(1)

    if args.replicate > 1:
        big_hist = histories * args.replicate
        t0 = time.perf_counter()
        big = pack_docs(big_hist)
        log(f"pack {len(big_hist)} docs: {time.perf_counter()-t0:.2f}s")
        out2 = run_batch(big)
        jax.block_until_ready(out2)
        t0 = time.perf_counter()
        out2 = run_batch(big)
        jax.block_until_ready(out2)
        dt = time.perf_counter() - t0
        total = int(big.n_ops.sum())
        log(
            f"{len(big_hist)} docs ({total} ops, N={big.n_rows}): "
            f"{dt*1e3:.1f}ms -> {total/dt/1e6:.2f}M ops/s/chip"
        )


if __name__ == "__main__":
    main()
