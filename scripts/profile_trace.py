"""Replay an HM_TRACE file into the busy-vs-wall stage timeline.

Takes the Chrome trace-event JSON a run wrote under HM_TRACE=<path>
(hypermerge_tpu/telemetry/trace.py) and prints the same per-stage
concurrency table scripts/profile_cold.py renders from bulk stats —
busy seconds per span name vs the overlapped wall clock, so a trace
from ANY run (bench, daemon, test) answers "where did the time go"
without re-running it under a profiler.

Usage:
    python scripts/profile_trace.py /tmp/t.json [--by name|cat]
        [--top N] [--threads]

--by cat groups by subsystem (live/pipeline/net/storage/mesh/serve)
instead of span name; --threads adds a per-thread busy breakdown. The
serving tier's spans show up as `serve.read` (per-request latency,
admission to completion) and `serve.batch` (one coalesced kernel
flush) — their count ratio IS the read-batching factor.

Under HM_PACK_WORKERS>1 the pack plane fans out: each pool worker
emits its own `pipeline.pack` spans from an `hm-pipe-pack-<i>` thread,
so `--threads` draws one busy lane per pack worker (their sum past the
`pipeline.pack` row's share of the wall is the pool's realized
speedup; scripts/profile_cold.py prints the same lanes from bulk
stats). Device packs (HM_DEVICE_PACK=1) run inside those same spans —
whether the kernel or the host packed is in the metrics registry, not
the trace: `pack.device_packs` counts kernel-packed slabs and
`pack.device_fallbacks` counts silent host fallbacks.

Instrumented runs (HM_LOCKDEP=1 / HM_RACEDEP=1) add two instants in
the `lock` category: `lock.held_blocking` (a blocking primitive ran
while a no-block emission lock was held — each one is a stall of every
doc's patch pushes) and `lock.racedep_violation` (the lockset detector
observed a guard-manifest violation). Their counts surface in the
instants total; grep the trace JSON for the names to locate them on
the timeline.
"""

import argparse
import json
import sys
from collections import defaultdict


def load_events(path):
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if isinstance(e, dict)]


def timeline(events, by="name"):
    """(rows, wall_s, t0_us): rows are (key, count, busy_s) sorted by
    busy desc, over the complete ("X") events."""
    spans = [e for e in events if e.get("ph") == "X"]
    if not spans:
        return [], 0.0, 0.0
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0) for e in spans)
    wall = (t1 - t0) / 1e6
    busy = defaultdict(lambda: [0, 0.0])
    for e in spans:
        key = e.get("cat", "hm") if by == "cat" else e["name"]
        cell = busy[key]
        cell[0] += 1
        cell[1] += e.get("dur", 0) / 1e6
    rows = sorted(
        ((k, c, s) for k, (c, s) in busy.items()),
        key=lambda r: -r[2],
    )
    return rows, wall, t0


def thread_busy(events, tid_names):
    busy = defaultdict(float)
    for e in events:
        if e.get("ph") == "X":
            busy[e.get("tid")] += e.get("dur", 0) / 1e6
    return sorted(
        ((tid_names.get(t, f"tid {t}"), s) for t, s in busy.items()),
        key=lambda r: -r[1],
    )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help="Chrome trace JSON (HM_TRACE output)")
    ap.add_argument("--by", choices=("name", "cat"), default="name")
    ap.add_argument("--top", type=int, default=24)
    ap.add_argument(
        "--threads", action="store_true",
        help="also print per-thread busy totals",
    )
    args = ap.parse_args()

    events = load_events(args.trace)
    rows, wall, _t0 = timeline(events, by=args.by)
    if not rows:
        print("no complete spans in trace", file=sys.stderr)
        sys.exit(1)
    n_instant = sum(1 for e in events if e.get("ph") == "i")
    print(
        f"trace: {sum(c for _k, c, _s in rows)} spans"
        + (f" + {n_instant} instants" if n_instant else "")
        + f", wall {wall:.3f}s"
    )
    print(f"stage timeline [busy (overlapped)] by {args.by}:")
    busy_total = 0.0
    for key, count, busy_s in rows[: args.top]:
        busy_total += busy_s
        bar = "#" * max(1, int(40 * busy_s / max(wall, 1e-9)))
        print(f"  {key:<26} {busy_s:9.3f}s x{count:<6} |{bar}")
    dropped = rows[args.top:]
    if dropped:
        rest = sum(s for _k, _c, s in dropped)
        busy_total += rest
        print(f"  (+{len(dropped)} more stages, {rest:.3f}s)")
    print(
        f"  wall {wall:.3f}s, busy total {busy_total:.3f}s -> "
        f"{busy_total / max(wall, 1e-9):.2f}x concurrency"
    )
    if args.threads:
        names = {
            e["tid"]: e["args"]["name"]
            for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        print("per-thread busy:")
        for name, s in thread_busy(events, names):
            print(f"  {name:<26} {s:9.3f}s")


if __name__ == "__main__":
    main()
