"""Measure the true XLA compile cost of the bulk-load kernel bucket.

Pads a small synthetic batch to the production slab bucket shape
(default [4096, 1024]) and times the first jit call. Run with
HM_COMPILE_CACHE='' to disable the persistent cache:

    HM_COMPILE_CACHE= python scripts/probe_compile.py [n_docs] [n_rows]
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import numpy as np


def padded_batch(n_docs: int, n_rows: int):
    """A ColumnarBatch of bucket shape [n_docs, n_rows] with one real doc
    (shapes drive compilation; values don't)."""
    from hypermerge_tpu.ops.synth import synth_changes
    from hypermerge_tpu.ops.columnar import PAD, pack_docs

    changes = synth_changes(
        n_rows // 16, n_actors=1, ops_per_change=16, seed=0
    )
    batch = pack_docs([changes], n_rows=n_rows)
    for k, col in batch.cols.items():
        pad_val = PAD if k == "action" else 0
        padded = np.full((n_docs, col.shape[1]), pad_val, dtype=col.dtype)
        padded[: col.shape[0]] = col
        batch.cols[k] = padded
    for name in ("psrc", "ptgt"):
        col = getattr(batch, name)
        padded = np.full((n_docs, col.shape[1]), -1, dtype=col.dtype)
        padded[: col.shape[0]] = col
        setattr(batch, name, padded)
    batch.n_ops = np.concatenate(
        [batch.n_ops, np.zeros(n_docs - batch.n_ops.shape[0], np.int64)]
    )
    batch.doc_actors = None
    batch.slot = None
    return batch


def main():
    n_docs = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    n_rows = int(sys.argv[2]) if len(sys.argv) > 2 else 1024

    from hypermerge_tpu.ops.crdt_kernels import run_batch_full

    t0 = time.perf_counter()
    batch = padded_batch(n_docs, n_rows)
    print(f"pack: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

    t0 = time.perf_counter()
    out, summary = run_batch_full(batch, lean=True)
    np.asarray(summary.ravel()[:1])
    t1 = time.perf_counter() - t0
    print(
        f"first call (compile+run) [{n_docs},{n_rows}]: {t1:.2f}s",
        file=sys.stderr,
    )
    t0 = time.perf_counter()
    out, summary = run_batch_full(batch, lean=True)
    np.asarray(summary.ravel()[:1])
    print(
        f"second call (run only): {time.perf_counter()-t0:.2f}s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
