"""Profile the cold-start product path: make_corpus -> open_many.

Usage: [PROF_DOCS=1024] [PROF_OPS=1024] [JAX_PLATFORMS=cpu] \
       python scripts/profile_cold.py [--cprofile]
"""

import cProfile
import os
import pstats
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

n_docs = int(os.environ.get("PROF_DOCS", "1024"))
n_ops = int(os.environ.get("PROF_OPS", "1024"))

from hypermerge_tpu.ops.corpus import make_corpus  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402

tmp = tempfile.mkdtemp(prefix="hmprof")
t0 = time.perf_counter()
urls = make_corpus(tmp, n_docs, n_ops)
print(f"corpus: {n_docs} docs x {n_ops} ops in {time.perf_counter()-t0:.2f}s")

t0 = time.perf_counter()
repo = Repo(path=tmp)
print(f"repo ctor: {time.perf_counter()-t0:.2f}s")


def run():
    t0 = time.perf_counter()
    handles = repo.open_many(urls)
    summaries = repo.back.fetch_bulk_summaries()  # the honest barrier
    dt = time.perf_counter() - t0
    print(
        f"open_many+summaries: {dt:.2f}s -> {n_docs*n_ops/dt:,.0f} ops/s "
        f"({len(handles)} handles, {len(summaries.doc_ids)} summarized)"
    )


if "--cprofile" in sys.argv:
    prof = cProfile.Profile()
    prof.enable()
    run()
    prof.disable()
    stats = pstats.Stats(prof)
    stats.sort_stats("cumulative").print_stats(35)
else:
    run()
repo.close()
