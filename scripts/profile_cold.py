"""Profile the cold-start product path: make_corpus -> open_many.

Usage: [PROF_DOCS=1024] [PROF_OPS=1024] [JAX_PLATFORMS=cpu] \
       python scripts/profile_cold.py [--cprofile]
"""

import cProfile
import os
import pstats
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

n_docs = int(os.environ.get("PROF_DOCS", "1024"))
n_ops = int(os.environ.get("PROF_OPS", "1024"))

from hypermerge_tpu.ops.corpus import make_corpus  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402

tmp = tempfile.mkdtemp(prefix="hmprof")
t0 = time.perf_counter()
urls = make_corpus(tmp, n_docs, n_ops)
print(f"corpus: {n_docs} docs x {n_ops} ops in {time.perf_counter()-t0:.2f}s")

t0 = time.perf_counter()
repo = Repo(path=tmp)
print(f"repo ctor: {time.perf_counter()-t0:.2f}s")


def run():
    t0 = time.perf_counter()
    handles = repo.open_many(urls)
    summaries = repo.back.fetch_bulk_summaries()  # the honest barrier
    dt = time.perf_counter() - t0
    print(
        f"open_many+summaries: {dt:.2f}s -> {n_docs*n_ops/dt:,.0f} ops/s "
        f"({len(handles)} handles, {len(summaries.doc_ids)} summarized)"
    )
    _stage_timeline(repo.back.last_bulk_stats, dt)


def _stage_timeline(stats, wall):
    """Per-stage concurrency table: busy seconds vs the overlapped wall
    clock. Under HM_PIPELINE=1 the stages run concurrently, so their
    busy times sum past the wall critical path; the concurrency factor
    is how much of the pipeline's overlap actually materialized
    (1.0x = fully serial)."""
    pipelined = bool(stats.get("pipeline", 0))
    # pipeline mode: the barrier's t_fetch is residual WAITING on the
    # fetch worker's t_fetch_busy work — only the busy time counts
    keys = (
        "t_sql", "t_io", "t_spec", "t_pack", "t_narrow", "t_upload",
        "t_dispatch",
    ) + (("t_fetch_busy",) if pipelined else ("t_fetch",))
    mode = "busy (overlapped)" if pipelined else "wall (serial)"
    print(f"stage timeline [{mode}]:")
    busy_total = 0.0
    for k in keys:
        v = stats.get(k)
        if not v:
            continue
        busy_total += v
        bar = "#" * max(1, int(40 * v / max(wall, 1e-9)))
        print(f"  {k:<13} {v:7.3f}s |{bar}")
    cp = stats.get("wall_critical_path", wall)
    print(
        f"  wall critical path {cp:.3f}s, stage busy total "
        f"{busy_total:.3f}s -> {busy_total / max(cp, 1e-9):.2f}x "
        "concurrency"
    )
    _pack_lanes(stats)


def _pack_lanes(stats):
    """Per-worker pack lanes (HM_PACK_WORKERS > 1): each worker's busy
    seconds against the pool's lane wall (first pack start -> last pack
    end). With real overlap sum(busy) exceeds the wall — the ratio is
    the pool's parallel speedup. A single worker (or the serial twin)
    has nothing to show."""
    lanes = stats.get("t_pack_busy_per_worker") or []
    if len(lanes) < 2:
        return
    pack_wall = stats.get("t_pack_wall", 0.0)
    print(f"pack pool [{len(lanes)} workers, lane wall {pack_wall:.3f}s]:")
    for w, b in enumerate(lanes):
        bar = "#" * max(1, int(40 * b / max(pack_wall, 1e-9)))
        print(f"  worker {w:<6} {b:7.3f}s |{bar}")
    busy = sum(lanes)
    print(
        f"  pack busy total {busy:.3f}s -> "
        f"{busy / max(pack_wall, 1e-9):.2f}x pack speedup"
    )


if "--cprofile" in sys.argv:
    prof = cProfile.Profile()
    prof.enable()
    run()
    prof.disable()
    stats = pstats.Stats(prof)
    stats.sort_stats("cumulative").print_stats(35)
else:
    run()
repo.close()
