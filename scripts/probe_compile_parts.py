"""Decompose the bulk-kernel compile cost: which constructs are slow to
compile on this backend, and does fori_loop help?

    HM_COMPILE_CACHE= python scripts/probe_compile_parts.py
"""

import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

D, N = 4096, 1024
ROUNDS = 11


def timed_compile(name, fn, *args):
    t0 = time.perf_counter()
    jax.jit(fn).lower(*args).compile()
    print(f"{name}: {time.perf_counter()-t0:.2f}s", file=sys.stderr)


def climb_unrolled(j16):
    def one(j):
        for _ in range(ROUNDS):
            j = j[j.astype(jnp.int32)]
        return j

    return jax.vmap(one)(j16)


def climb_fori(j16):
    def one(j):
        return jax.lax.fori_loop(
            0, ROUNDS, lambda _, x: x[x.astype(jnp.int32)], j
        )

    return jax.vmap(one)(j16)


def wyllie_unrolled(p):
    def one(p):
        for _ in range(ROUNDS):
            q = p[p & 0xFFFF]
            p = (q & 0xFFFF) | ((p >> 16) + (q >> 16)) << 16
        return p

    return jax.vmap(one)(p)


def wyllie_fori(p):
    def one(p):
        def body(_, p):
            q = p[p & 0xFFFF]
            return (q & 0xFFFF) | ((p >> 16) + (q >> 16)) << 16

        return jax.lax.fori_loop(0, ROUNDS, body, p)

    return jax.vmap(one)(p)


def lexsorts(slot, ctr, gid):
    def one(s, c, g):
        o1 = jnp.lexsort((s, c, g))
        o2 = jnp.lexsort((c, g, s))
        return o1, o2

    return jax.vmap(one)(slot, ctr, gid)


def argsort_only(x):
    return jax.vmap(jnp.argsort)(x)


def scatters(tgt, val):
    def one(t, v):
        a = jnp.zeros(N + 1, jnp.int32).at[t].max(v)
        b = jnp.zeros(N + 1, jnp.int32).at[t].add(v)
        return a[:N], b[:N]

    return jax.vmap(one)(tgt, val)


def main():
    j16 = jnp.zeros((D, N + 1), jnp.int16)
    p32 = jnp.zeros((D, N + 1), jnp.int32)
    slot = jnp.zeros((D, N), jnp.int32)
    timed_compile("climb_unrolled x11 int16", climb_unrolled, j16)
    timed_compile("climb_fori x11 int16", climb_fori, j16)
    timed_compile("wyllie_unrolled x11 int32", wyllie_unrolled, p32)
    timed_compile("wyllie_fori x11 int32", wyllie_fori, p32)
    timed_compile("two lexsorts", lexsorts, slot, slot, slot)
    timed_compile("argsort", argsort_only, slot)
    timed_compile("scatter max+add", scatters, slot, slot)


if __name__ == "__main__":
    main()
