"""Profile the MULTI-CHIP cold-start product path: make_corpus ->
open_many streamed across the device mesh, with a per-chip
busy-vs-wall timeline (the mesh twin of profile_cold.py).

Usage: [PROF_DOCS=2048] [PROF_OPS=512] [PROF_SLAB=512] \
       JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/profile_mesh.py

Needs >1 visible device (the virtual CPU mesh flag above, or real
chips). Prints the stage timeline, then per-chip slab placement and
dispatch/fetch busy bars — the load-balance view that tells you whether
the wall clock is bounded by slab IO (good) or by one hot chip (bad).
"""

import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

n_docs = int(os.environ.get("PROF_DOCS", "2048"))
n_ops = int(os.environ.get("PROF_OPS", "512"))
slab = int(os.environ.get("PROF_SLAB", "512"))

import jax  # noqa: E402

from hypermerge_tpu.ops.corpus import make_corpus  # noqa: E402
from hypermerge_tpu.parallel.mesh import device_topology  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.utils.ids import validate_doc_url  # noqa: E402

topo = device_topology()
print(f"topology: {topo}")
if topo["n_devices"] < 2:
    sys.exit("needs >1 device (set --xla_force_host_platform_device_count)")

tmp = tempfile.mkdtemp(prefix="hmprofmesh")
t0 = time.perf_counter()
urls = make_corpus(tmp, n_docs, n_ops)
print(
    f"corpus: {n_docs} docs x {n_ops} ops in "
    f"{time.perf_counter() - t0:.2f}s"
)

t0 = time.perf_counter()
repo = Repo(path=tmp)
print(f"repo ctor: {time.perf_counter() - t0:.2f}s")

t0 = time.perf_counter()
ids = [validate_doc_url(u) for u in urls]
repo.back.load_documents_bulk(ids, slab=slab)
summaries = repo.back.fetch_bulk_summaries()  # the honest barrier
wall = time.perf_counter() - t0
stats = dict(repo.back.last_bulk_stats)
print(
    f"open_many+summaries: {wall:.2f}s -> "
    f"{n_docs * n_ops / wall:,.0f} ops/s "
    f"({len(summaries.doc_ids)} summarized)"
)


def _bar(v, scale):
    return "#" * max(1, int(40 * v / max(scale, 1e-9))) if v else ""


# stage timeline (same view as profile_cold.py)
keys = (
    "t_sql", "t_io", "t_spec", "t_pack", "t_narrow", "t_upload",
    "t_dispatch", "t_fetch_busy",
)
print("stage timeline [busy (overlapped)]:")
busy_total = 0.0
for k in keys:
    v = stats.get(k) or 0.0
    if not v:
        continue
    busy_total += v
    print(f"  {k:<13} {v:7.3f}s |{_bar(v, wall)}")
cp = stats.get("wall_critical_path", wall)
print(
    f"  wall critical path {cp:.3f}s, stage busy total "
    f"{busy_total:.3f}s -> {busy_total / max(cp, 1e-9):.2f}x concurrency"
)

# per-chip placement + busy timeline: the mesh load-balance view
slabs = stats.get("slabs_per_chip") or []
disp = stats.get("t_dispatch_chips") or []
fetch = stats.get("t_fetch_chips") or [0.0] * len(disp)
if not disp:
    print(
        "no per-chip stats (load below HM_DEVICE_MIN_CELLS, or a "
        "single-device path) — nothing dispatched to the mesh"
    )
else:
    scale = max(max(disp, default=0.0), max(fetch, default=0.0))
    print(f"per-chip timeline ({stats.get('rr_slabs', 0)} slab(s)):")
    for i in range(len(disp)):
        print(
            f"  chip {i}: {slabs[i] if i < len(slabs) else 0} slab(s)  "
            f"dispatch {disp[i]:7.3f}s |{_bar(disp[i], scale):<40}| "
            f"fetch {fetch[i] if i < len(fetch) else 0.0:7.3f}s "
            f"|{_bar(fetch[i] if i < len(fetch) else 0.0, scale)}"
        )
    busiest = max(disp)
    ideal = sum(disp) / len(disp)
    print(
        f"  balance: busiest chip {busiest:.3f}s vs ideal "
        f"{ideal:.3f}s ({busiest / max(ideal, 1e-9):.2f}x skew)"
    )

repo.close()
import shutil  # noqa: E402

shutil.rmtree(tmp, ignore_errors=True)
