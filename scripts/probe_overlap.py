"""Does XLA compile on this backend overlap with host CPU work?

Times: trivial-jit compile, then a big-kernel compile in a background
thread while the main thread does pure-numpy crunching. If the crunch
rate is unaffected, compile is remote/GIL-free and a warmup thread can
hide it behind corpus IO.

    HM_COMPILE_CACHE= python scripts/probe_overlap.py
"""

import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

D, N = 4096, 1024


def main():
    t0 = time.perf_counter()
    jax.jit(lambda x: x + 1).lower(
        jnp.zeros((D, N), jnp.int32)
    ).compile()
    print(
        f"trivial jit compile: {time.perf_counter()-t0:.2f}s",
        file=sys.stderr,
    )

    from scripts.probe_compile import padded_batch
    from hypermerge_tpu.ops.crdt_kernels import run_batch_full

    batch = padded_batch(D, N)

    # crunch baseline: how much numpy work per second, solo
    a = np.random.default_rng(0).integers(0, 100, (2048, 2048))
    def crunch(secs):
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < secs:
            (a * 3 + 1).sum()
            n += 1
        return n / (time.perf_counter() - t0)

    solo = crunch(3.0)
    print(f"crunch solo: {solo:.1f} iters/s", file=sys.stderr)

    done = {}

    def compile_bg():
        t0 = time.perf_counter()
        out, summary = run_batch_full(batch, lean=True)
        np.asarray(summary.ravel()[:1])
        done["t"] = time.perf_counter() - t0

    th = threading.Thread(target=compile_bg)
    t0 = time.perf_counter()
    th.start()
    rates = []
    while th.is_alive():
        rates.append(crunch(2.0))
    th.join()
    print(
        f"compile in bg thread: {done['t']:.2f}s; crunch during: "
        f"{np.mean(rates):.1f} iters/s ({np.mean(rates)/solo*100:.0f}% "
        "of solo)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
