"""Benchmark: CRDT ops applied/sec/chip via batched device materialization.

Workload: BASELINE.json config 4 shape — cold-start re-materialization of
many chat-shaped docs (text RGA + LWW map churn) from packed op logs, in
ONE device dispatch. Baseline = the host incremental OpSet replay of the
same workload (the framework's own Node-CPU-backend equivalent; the
reference publishes no numbers, BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env overrides: BENCH_DOCS (default 4096), BENCH_OPS (default 1024),
BENCH_HOST_DOCS (default 8).
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))


def main() -> None:
    n_docs = int(os.environ.get("BENCH_DOCS", "4096"))
    n_ops = int(os.environ.get("BENCH_OPS", "1024"))
    host_docs = int(os.environ.get("BENCH_HOST_DOCS", "8"))

    import jax

    from hypermerge_tpu.crdt.opset import OpSet
    from hypermerge_tpu.ops.crdt_kernels import run_batch_summary
    from hypermerge_tpu.ops.materialize import summarize_columnar
    from hypermerge_tpu.ops.synth import synth_batch, synth_changes

    dev = jax.devices()[0]
    print(f"# device: {dev}", file=sys.stderr)

    # -- host baseline: incremental OpSet replay ------------------------
    host_histories = [
        synth_changes(n_ops, seed=i) for i in range(host_docs)
    ]
    t0 = time.perf_counter()
    for history in host_histories:
        opset = OpSet()
        opset.apply_changes(history)
    host_dt = time.perf_counter() - t0
    host_rate = host_docs * n_ops / host_dt
    print(
        f"# host baseline: {host_docs} docs x {n_ops} ops in "
        f"{host_dt:.2f}s -> {host_rate:,.0f} ops/s",
        file=sys.stderr,
    )

    # -- device: one batched dispatch ----------------------------------
    batch = synth_batch(n_docs, n_ops)
    total_ops = int(batch.n_ops.sum())
    # warmup: compiles the fused kernel AND the device->host transfer
    # programs (on the tunneled platform each first-fetch of a new
    # shape/dtype compiles a transfer executable; both caches are
    # per-process, steady-state is what we measure)
    t0 = time.perf_counter()
    summarize_columnar(batch)
    compile_dt = time.perf_counter() - t0
    print(f"# warmup (kernel + transfer compiles): {compile_dt:.1f}s",
          file=sys.stderr)

    # kernel-only: dispatch + 1-element sync fetch (block_until_ready
    # returns before compute completes on this platform — a fetch is the
    # only honest barrier)
    import numpy as np

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = run_batch_summary(batch)
        np.asarray(out.clock.ravel()[:1])
        times.append(time.perf_counter() - t0)
    device_dt = min(times)
    device_rate = total_ops / device_dt

    # e2e: one summarize_columnar call = fused kernel+summary dispatch,
    # compact device->host transfer, host bit-unpack
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cols = summarize_columnar(batch)
        times.append(time.perf_counter() - t0)
    e2e_dt = min(times)
    e2e_rate = total_ops / e2e_dt

    print(
        f"# device: {n_docs} docs x {n_ops} ops = {total_ops} ops, "
        f"{device_dt*1e3:.0f}ms kernel-only, {e2e_dt*1e3:.0f}ms e2e "
        f"(incl transfer+unpack) -> {device_rate:,.0f} ops/s kernel, "
        f"{e2e_rate:,.0f} ops/s e2e",
        file=sys.stderr,
    )
    print(
        f"# live elems: {int(cols['n_live_elems'].sum())}, "
        f"map entries: {int(cols['n_map_entries'].sum())}",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": "crdt_ops_materialized_per_sec_per_chip",
                "value": round(e2e_rate),
                "unit": "ops/s",
                "vs_baseline": round(e2e_rate / host_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
