"""Benchmark: the cold-start PRODUCT path, disk -> materialized summaries.

Primary metric (BASELINE configs 3/4): a corpus of BENCH_DOCS docs x
BENCH_OPS ops each — real feeds, sidecars, and sqlite rows on disk
(ops/corpus.py, validated byte-equivalent to the interactive write path
in tests/test_corpus.py) — opened with `Repo.open_many` in a FRESH
RepoBackend and materialized to host through `fetch_bulk_summaries()`
(the bulk path's honest barrier: after it, every doc renders host-side
with no further device work). Nothing is pre-packed or pre-warmed: the
timed region includes sqlite cursor/clock loads, sidecar IO, columnar
packing, device transfer, kernel, and the summary fetch.

Two timed passes:
  cold_first_process — first open in this process (XLA compile overlaps
    the untimed corpus setup via ops/warmup.py; with a warm persistent
    compile cache the warmup is itself a no-op)
  steady_state       — second fresh RepoBackend over the same disk state
    (compile cached; OS page cache warm). This is the headline: it is
    what any long-lived deployment pays per cold open.

Also measured (VERDICT r3 item 6):
  config1_change_latency_us — interactive single-op change latency
  config5_union_100k_ms     — 100k-doc ClockStore clock-union on device
  multichip_8_s             — MEASURED multi-chip cold open of the same
    corpus over the mesh scheduler (config_mesh: in-process when >=2
    devices are visible, else a subprocess on an 8-device virtual CPU
    host platform — the same mesh the tier-1 matrix pins bit-identical).
    Retires the old projection formula, which survives only as the
    clearly-labeled `projection_8chip_reference_s` field.

Baseline = the framework's own host incremental OpSet replay of the same
per-doc histories (the reference publishes no numbers, BASELINE.md; the
reference's own cold start is the same work in Node+Immutable.js).

The timed path runs the streaming slab pipeline (backend/pipeline.py,
the product default): per-slab IO, native pack, device dispatch, and
summary fetch overlap, so the wall clock is the reported
`wall_critical_path` (~max(stage)) and the per-stage numbers are BUSY
times (`t_*_busy` aliases). HM_PIPELINE=0 restores the serial twin,
where the same keys are back-to-back wall times.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"configs": {...}}. Env: BENCH_DOCS (default 10240), BENCH_OPS (1024),
BENCH_HOST_DOCS (8), BENCH_DIR (corpus location, default a fresh tmpdir),
BENCH_COLDOPEN_DOCS / BENCH_COLDOPEN_OPS / BENCH_COLDOPEN_WORKERS (the
config_coldopen pack-plane gate: 10x-corpus cold open, serial vs pooled
pack — see _config_coldopen).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))


def _open_and_materialize(path, urls):
    from hypermerge_tpu.repo import Repo

    t0 = time.perf_counter()
    repo = Repo(path=path)
    handles = repo.open_many(urls)
    summaries = repo.back.fetch_bulk_summaries()
    dt = time.perf_counter() - t0
    n = len(summaries.doc_ids)
    assert n == len(urls), f"only {n}/{len(urls)} docs materialized"
    assert len(handles) == len(urls)
    stats = dict(repo.back.last_bulk_stats)
    # spot-check: summaries carry real content
    probe = summaries.doc(summaries.doc_ids[0])
    assert probe["elems"] > 0 and probe["clock"], probe
    repo.close()
    return dt, stats


_MESH_CHILD = r"""
import json, os, sys, time

# the virtual device count must be in XLA_FLAGS BEFORE any jax backend
# initializes (the parent set JAX_PLATFORMS=cpu and the flag in env)
sys.path.insert(0, sys.argv[1])
tmp = sys.argv[2]
n_pass = int(sys.argv[3])

import jax  # noqa: E402

with open(os.path.join(tmp, "corpus.json")) as fh:
    urls = json.load(fh)["urls"]

from hypermerge_tpu.parallel.mesh import device_topology  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402

best = None
stats = None
for _ in range(n_pass):
    t0 = time.perf_counter()
    repo = Repo(path=tmp)
    handles = repo.open_many(urls)
    summaries = repo.back.fetch_bulk_summaries()
    dt = time.perf_counter() - t0
    assert len(summaries.doc_ids) == len(urls)
    s = dict(repo.back.last_bulk_stats)
    repo.close()
    if best is None or dt < best:
        best, stats = dt, s
print(json.dumps({
    "multichip_s": round(best, 2),
    "devices": len(jax.devices()),
    "topology": device_topology(),
    "stats": stats,
}), flush=True)
"""


def _config_mesh(tmp, n_passes=2):
    """MEASURED multi-chip cold open of the SAME on-disk corpus the
    primary metric used — the number that retires the 8-chip
    projection. With >=2 devices already visible the open runs
    in-process; a single-device box (the tunneled-TPU bench host)
    re-runs it in a subprocess on an 8-device virtual CPU host platform
    (`--xla_force_host_platform_device_count=8` — the same mesh the
    tier-1 test matrix pins bit-identical to the single-device twin).
    Either way the wall clock is a real overlapped run over the mesh
    scheduler (slab streaming + per-chip queues), not a divide-by-N
    formula. Returns (seconds, mode, devices, topology, stats)."""
    import subprocess

    import jax

    from hypermerge_tpu.parallel.mesh import device_topology

    with open(os.path.join(tmp, "corpus.json")) as fh:
        urls = json.load(fh)["urls"]

    def _mesh_slab(n_chips):
        """Slab size that spreads the corpus across every chip:
        docs/chips rounded DOWN to a pow2 (streaming parallelism is
        per-slab — the default 4096 slab would pin a 10k-doc load to
        3 chips). An explicit HM_BULK_SLAB always wins."""
        if os.environ.get("HM_BULK_SLAB"):
            return os.environ["HM_BULK_SLAB"]
        per = max(1, len(urls) // max(1, n_chips))
        return str(max(256, 1 << (per.bit_length() - 1)))

    if len(jax.devices()) >= 2:
        slab_save = os.environ.get("HM_BULK_SLAB")
        os.environ["HM_BULK_SLAB"] = _mesh_slab(len(jax.devices()))
        try:
            best = None
            stats = None
            for _ in range(n_passes):
                dt, s = _open_and_materialize(tmp, urls)
                if best is None or dt < best:
                    best, stats = dt, s
        finally:
            if slab_save is None:
                os.environ.pop("HM_BULK_SLAB", None)
            else:
                os.environ["HM_BULK_SLAB"] = slab_save
        return (
            round(best, 2),
            "in_process",
            len(jax.devices()),
            device_topology(),
            stats,
        )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["HM_BULK_SLAB"] = _mesh_slab(8)
    proc = subprocess.run(
        [
            sys.executable, "-c", _MESH_CHILD,
            str(Path(__file__).parent), tmp, str(n_passes),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"mesh child failed rc={proc.returncode}: "
            f"{proc.stderr[-800:]}"
        )
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    return (
        out["multichip_s"],
        "subprocess_cpu8",
        out["devices"],
        out["topology"],
        out["stats"],
    )


def _config_lockdebt():
    """The write-plane blocking debt, measured: a durable burst of
    local edits across several docs on a disk-backed repo, run with
    lockdep instrumentation on so the blocking seams (fsync, sqlite
    commit, debouncer waits) charge their wall time to every lock
    class held at entry. Returns the per-lock-class
    `lock.held_blocking_ms.*` deltas (ms) for BOTH durable tiers:

      fsync_group      HM_FSYNC=1 — durability debounced off-thread;
                       the engine-lock entry shows what the emission
                       path itself blocks on
      fsync_per_append HM_FSYNC=2 — the inline-durability worst case:
                       every acked append fsyncs under the emission
                       lock

    The `live_engine` entry IS the ROADMAP write-plane gate as a
    number: feed-append / clock-commit time spent under the ONE
    engine lock — the per-doc emission-domain split is gated on the
    tier-1 figure reading zero and judged against the tier-2 figure
    it must dissolve into per-doc domains."""
    import tempfile as _tempfile

    from hypermerge_tpu import telemetry
    from hypermerge_tpu.analysis import lockdep
    from hypermerge_tpu.repo import Repo

    prefix = "lock.held_blocking_ms."

    def snap():
        return {
            k[len(prefix):]: v
            for k, v in telemetry.snapshot().items()
            if k.startswith(prefix)
        }

    def burst(tier: str):
        os.environ["HM_FSYNC"] = tier
        tmp = _tempfile.mkdtemp(prefix="hm-lockdebt-")
        try:
            before = snap()
            repo = Repo(path=os.path.join(tmp, "repo"))
            try:
                urls = [repo.create({"n": 0}) for _ in range(8)]
                for i in range(40):
                    for url in urls:
                        repo.change(
                            url, lambda d: d.__setitem__("n", i)
                        )
                back = repo.back
                if back.live is not None:
                    back.live.flush_now()
                back._stores.flush_now()
                back.durability.flush_now()
            finally:
                repo.close()
            after = snap()
            debt = {
                k: round(after.get(k, 0.0) - before.get(k, 0.0), 3)
                for k in after
                if after.get(k, 0.0) - before.get(k, 0.0) > 0
            }
            # the gate reads zero only when the key exists to read
            debt.setdefault("live_engine", 0.0)
            return debt
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    was = lockdep.enabled()
    env_fsync = os.environ.get("HM_FSYNC")
    lockdep.enable(True)  # fresh repos below get instrumented locks
    try:
        return {
            "fsync_group": burst("1"),
            "fsync_per_append": burst("2"),
        }
    finally:
        lockdep.enable(was)
        if env_fsync is None:
            os.environ.pop("HM_FSYNC", None)
        else:
            os.environ["HM_FSYNC"] = env_fsync


_WRITER_CHILD = r"""
import json, sys, threading, time

sock, n_edits = sys.argv[1], int(sys.argv[2])

from hypermerge_tpu.net.ipc import connect_frontend

front, close = connect_frontend(sock)
url = front.create({"n": 0})
h = front.open(url)
h.value(timeout=60)

latest = [0]
done = threading.Event()
goal = [None]

def on_state(_state, index):
    if index > latest[0]:
        latest[0] = index
    if goal[0] is not None and latest[0] >= goal[0]:
        done.set()

h.subscribe(on_state)
print("ready", flush=True)
sys.stdin.readline()  # the coordinator's "go"

# each change round-trips: the frontend keeps ONE request in flight
# and the backend's LocalPatch echo (with the bumped history index)
# releases the next — so `n_edits` acked edits means the history
# index advances by n_edits over the ready base
base = latest[0]
goal[0] = base + n_edits
t0 = time.perf_counter()
for i in range(n_edits):
    front.change(url, lambda d, _i=i: d.__setitem__("n", _i))
ok = done.wait(timeout=120)
dt = time.perf_counter() - t0
print(json.dumps({"edits": n_edits, "secs": dt, "acked": ok}), flush=True)
close()
"""


_HOTDOC_CHILD = r"""
import hashlib, json, sys, time

sock, url = sys.argv[1], sys.argv[2]
idx, n_edits, n_writers = (
    int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[5])
)

from hypermerge_tpu.net.ipc import connect_frontend

front, close = connect_frontend(sock)
h = front.open(url)

def val(timeout=0.2):
    try:
        return h.value(timeout=timeout)
    except TimeoutError:
        return None

deadline = time.time() + 60
while time.time() < deadline:
    v = val()
    if v is not None and "edits" in v:
        break
    time.sleep(0.02)
else:
    raise SystemExit("shared doc never materialized")

print("ready", flush=True)
sys.stdin.readline()  # the coordinator's "go"

# ack-paced on ONE shared doc: every writer holds its own actor (the
# hub's many-writer plane), writes its own keys, and releases the next
# edit only when the previous one's patch echo landed
t0 = time.perf_counter()
for i in range(n_edits):
    key = "%d.%d" % (idx, i)
    front.change(
        url, lambda d, _k=key, _i=i: d["edits"].__setitem__(_k, _i)
    )
    deadline = time.time() + 120
    while time.time() < deadline:
        v = val()
        if v is not None and key in v["edits"]:
            break
        time.sleep(0.001)
own_secs = time.perf_counter() - t0

# convergence barrier: every writer's view must reach ALL writers'
# edits, then hash the canonical JSON — the coordinator asserts the 8
# digests are BIT-identical
want = n_writers * n_edits
deadline = time.time() + 180
v = None
while time.time() < deadline:
    v = val()
    if v is not None and len(v.get("edits", {})) >= want:
        break
    time.sleep(0.02)
blob = json.dumps(v, sort_keys=True, separators=(",", ":"))
print(
    json.dumps({
        "edits": n_edits,
        "secs": own_secs,
        "acked": v is not None and len(v.get("edits", {})) >= want,
        "digest": hashlib.sha256(blob.encode("utf-8")).hexdigest(),
    }),
    flush=True,
)
close()
"""


def _writer_daemon_env(workers="0"):
    """The config_writers daemon environment: durable acks over the
    group-commit WAL in throughput posture (HM_WAL_MS=30 gather: the
    window, not this container's nearly-free fsync, is the amortized
    unit — so writer-count scaling measures group commit, not the CI
    box's single-core ceiling). `workers` picks the sharded write
    plane (HM_WORKERS worker processes); both knobs yield to the
    caller's env, so a multicore TPU host can run the scaling sweep
    sharded (HM_WORKERS=4) or at interactive latency (HM_WAL_MS=3)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["HM_FSYNC"] = "1"
    env["HM_ACK_DURABLE"] = "1"
    env.setdefault("HM_WAL_MS", "30")
    env.setdefault("HM_WORKERS", workers)
    env["PYTHONPATH"] = str(Path(__file__).parent)
    return env


def _config_writers(n_edits=200, counts=(1, 8, 32)):
    """The many-writer write plane, measured end to end: N frontend
    PROCESSES, each editing its own doc over IPC against ONE hub-mode
    daemon (net/ipc.py --hub) on a disk-backed repo at HM_FSYNC=1 with
    DURABLE acks (HM_ACK_DURABLE=1: every LocalPatch echo waits for
    the WAL group commit covering its append, HM_WAL_MS=3 gather).
    Every writer's edit loop is ack-paced (one request in flight; the
    durable echo releases the next), so a single writer pays the full
    {emission + commit window + fsync} per edit, and aggregate edits/s
    scales with writer count only if (a) disjoint docs' {patch -> feed
    append -> push} pipelines really run concurrently (the per-doc
    emission domains, backend/emission.py — the old engine-lock plane
    serialized them) and (b) concurrent committers share the leader's
    ONE journal fsync per window (storage/wal.py group commit — the
    old group flush was O(dirty feeds)). The daemon runs in-process
    (HM_WORKERS=0) by default so the single-core CI box measures the
    write plane, not the worker-hop IPC tax; export HM_WORKERS=N to
    run the sweep through the sharded plane on a multicore host.
    Returns per-count aggregate durable edits/s, the 1 -> max
    scaling factor (the ROADMAP gate: >= 3x at 8), and the 8 -> 32
    factor (group-commit gate: >= 2.5x — the shared gather window
    must keep amortizing as the herd quadruples)."""
    import tempfile as _tempfile

    results = {}
    per_writer = {}
    for n_writers in counts:
        tmp = _tempfile.mkdtemp(prefix="hm-writers-")
        sock = os.path.join(tmp, "daemon.sock")
        env = _writer_daemon_env()
        daemon = subprocess.Popen(
            [
                sys.executable, "-m", "hypermerge_tpu.net.ipc",
                os.path.join(tmp, "repo"), sock, "--hub",
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        writers = []
        try:
            line = daemon.stdout.readline()
            if "ready" not in line:
                raise RuntimeError(f"daemon failed to start: {line!r}")
            writers = [
                subprocess.Popen(
                    [sys.executable, "-c", _WRITER_CHILD, sock,
                     str(n_edits)],
                    env=env,
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                )
                for _ in range(n_writers)
            ]
            for w in writers:
                if w.stdout.readline().strip() != "ready":
                    raise RuntimeError(
                        f"writer failed: {w.stderr.read()[-500:]}"
                    )
            for w in writers:  # all docs open: release the herd
                w.stdin.write("go\n")
                w.stdin.flush()
            outs = [json.loads(w.stdout.readline()) for w in writers]
            if not all(o["acked"] for o in outs):
                raise RuntimeError("writer timed out waiting for acks")
            wall = max(o["secs"] for o in outs)
            results[n_writers] = round(n_writers * n_edits / wall, 1)
            per_writer[n_writers] = [round(o["secs"], 3) for o in outs]
        finally:
            for w in writers:
                w.kill()
            daemon.terminate()
            try:
                daemon.wait(timeout=10)
            except subprocess.TimeoutExpired:
                daemon.kill()
            shutil.rmtree(tmp, ignore_errors=True)
    lo, hi = min(counts), max(counts)
    out = {
        "edits_per_s": results,
        "scaling": round(results[hi] / max(results[lo], 1e-9), 2),
        "writer_secs": per_writer,
        "n_edits": n_edits,
    }
    if 8 in results and 32 in results:
        # the group-commit gate: the shared gather window must keep
        # amortizing the journal flush as the herd quadruples
        out["scaling_8_32"] = round(
            results[32] / max(results[8], 1e-9), 2
        )
    return out


def _config_writers_hotdoc(n_edits=60, n_writers=8):
    """The many-writer HOT-DOC plane: 8 frontend PROCESSES all editing
    ONE shared doc against one hub daemon (each connection holds its
    OWN actor — the hub tags Create/Open/NeedsActorId with the
    connection key and the backend mints per-connection actors), ack-
    paced, durable acks. Unlike the scaling sweep this one runs the
    SHARDED write plane (HM_WORKERS=2): the gate here is semantic —
    every tagged Ready, per-connection actor grant, and cross-writer
    patch must survive the hub -> worker hop — so the bench exercises
    it end to end. Returns aggregate durable edits/s plus the
    convergence verdict: after the herd drains, every writer hashes
    its canonical JSON view and all digests must be BIT-identical."""
    import tempfile as _tempfile

    tmp = _tempfile.mkdtemp(prefix="hm-hotdoc-")
    sock = os.path.join(tmp, "daemon.sock")
    env = _writer_daemon_env(workers="2")
    daemon = subprocess.Popen(
        [
            sys.executable, "-m", "hypermerge_tpu.net.ipc",
            os.path.join(tmp, "repo"), sock, "--hub",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    writers = []
    close = None
    try:
        line = daemon.stdout.readline()
        if "ready" not in line:
            raise RuntimeError(f"daemon failed to start: {line!r}")
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        url = front.create({"edits": {}})
        # a round-trip on the same ordered channel proves the daemon
        # registered the doc before any child tries to open it
        got = []
        front.materialize(url, 1, got.append)
        deadline = time.time() + 60
        while not got and time.time() < deadline:
            time.sleep(0.02)
        if not got:
            raise RuntimeError("daemon never acked the shared doc")
        writers = [
            subprocess.Popen(
                [sys.executable, "-c", _HOTDOC_CHILD, sock, url,
                 str(idx), str(n_edits), str(n_writers)],
                env=env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for idx in range(n_writers)
        ]
        for w in writers:
            if w.stdout.readline().strip() != "ready":
                raise RuntimeError(
                    f"hotdoc writer failed: {w.stderr.read()[-500:]}"
                )
        for w in writers:  # all views materialized: release the herd
            w.stdin.write("go\n")
            w.stdin.flush()
        outs = [json.loads(w.stdout.readline()) for w in writers]
        if not all(o["acked"] for o in outs):
            raise RuntimeError("hotdoc writer never converged")
        digests = {o["digest"] for o in outs}
        if len(digests) != 1:
            raise RuntimeError(
                f"hotdoc views DIVERGED: {sorted(digests)}"
            )
        wall = max(o["secs"] for o in outs)
        return {
            "edits_per_s": round(n_writers * n_edits / wall, 1),
            "converged": True,
            "digest": next(iter(digests)),
            "n_writers": n_writers,
            "n_edits": n_edits,
        }
    finally:
        if close is not None:
            close()
        for w in writers:
            w.kill()
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _config1_change_latency():
    """Interactive path: µs per single-op change on a live doc."""
    from hypermerge_tpu.repo import Repo

    repo = Repo(memory=True)
    url = repo.create({"n": 0})
    ts = []
    for i in range(300):
        t0 = time.perf_counter()
        repo.change(url, lambda d: d.__setitem__("n", i))
        ts.append(time.perf_counter() - t0)
    repo.close()
    ts.sort()
    return ts[len(ts) // 2] * 1e6  # median µs


def _config2_convergence(n_docs=10, n_edits=50):
    """BASELINE config 2: two repos, concurrent edits on shared docs,
    wall-clock to full convergence over encrypted TCP on localhost."""
    import time as _t

    from hypermerge_tpu.net.tcp import TcpSwarm
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.utils.ids import validate_doc_url

    ra, rb = Repo(memory=True), Repo(memory=True)
    sa, sb = TcpSwarm(), TcpSwarm()
    try:
        return _config2_run(ra, rb, sa, sb, n_docs, n_edits)
    finally:
        # fail-soft callers keep the process alive: never leak live
        # repos/sockets into the remaining configs
        ra.close()
        rb.close()
        sa.destroy()
        sb.destroy()


def _live_stats(*repos):
    """Aggregated live-apply engine stats across repos (zeros when the
    engine is off): ticks, docs/tick, coalesced changes, t_live_*."""
    out = {}
    for r in repos:
        eng = getattr(r.back, "live", None)
        if eng is None:
            continue
        for k, v in eng.stats.items():
            out[k] = round(out.get(k, 0) + v, 6)
    if out.get("ticks"):
        out["docs_per_tick"] = round(out["tick_docs"] / out["ticks"], 2)
        out["changes_per_tick"] = round(
            out["tick_changes"] / out["ticks"], 2
        )
    return out


def _config2_run(ra, rb, sa, sb, n_docs, n_edits):
    import time as _t

    from hypermerge_tpu.utils.ids import validate_doc_url

    ra.set_swarm(sa)
    rb.set_swarm(sb)
    sb.connect(sa.address)
    urls = [ra.create({"edits": []}) for _ in range(n_docs)]
    handles = [rb.open(u) for u in urls]
    ids = [validate_doc_url(u) for u in urls]

    t0 = _t.perf_counter()
    for i in range(n_edits):
        for u in urls:
            ra.change(u, lambda d, i=i: d["edits"].append(i))
        if i % 5 == 0:
            for h in handles:
                h.change(lambda d, i=i: d["edits"].append(1000 + i))
    # converged: every doc on B holds both sides' edits
    want = n_edits + (n_edits + 4) // 5
    deadline = _t.perf_counter() + 120
    while _t.perf_counter() < deadline:
        vals = [h.value() for h in handles]
        if all(
            v is not None and len(v.get("edits", [])) >= want
            for v in vals
        ):
            break
        _t.sleep(0.01)
    else:
        raise AssertionError("config2 did not converge")
    # and A sees B's edits too
    deadline = _t.perf_counter() + 120
    while _t.perf_counter() < deadline:
        if all(
            len(ra.doc(u).get("edits", [])) >= want for u in urls
        ):
            break
        _t.sleep(0.01)
    else:
        raise AssertionError("config2: A never saw B's edits")
    dt = _t.perf_counter() - t0
    total_edits = n_docs * want
    return dt, total_edits / dt, _live_stats(ra, rb)


def _config_churn(n_docs=6, n_edits=40):
    """BASELINE round-10 robustness config: burst edits on shared docs
    over TCP while a seeded FaultPlan (net/faults.py) kills the link
    mid-burst — twice — and the supervised redial (net/resilience.py)
    restores replication with NO manual reconnect. Reports convergence
    wall clock plus the churn counters: supervisor reconnects,
    replication resyncs + t_resync_ms, injected frame drops."""
    import time as _t

    from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
    from hypermerge_tpu.net.tcp import TcpSwarm
    from hypermerge_tpu.repo import Repo

    env_save = {
        k: os.environ.get(k)
        for k in ("HM_REDIAL_BASE_MS", "HM_REDIAL_MAX_S")
    }
    # everything after the env writes sits inside the try: a
    # constructor failure must not leak the redial overrides (or live
    # repos/sockets) into the remaining fail-soft bench configs
    ra = rb = sa = fb = None
    try:
        os.environ["HM_REDIAL_BASE_MS"] = "50"
        os.environ["HM_REDIAL_MAX_S"] = "1"
        plan = FaultPlan(
            seed=10,
            events=[(1, "kill"), (2, "heal"), (3, "kill"), (4, "heal")],
        )
        ra, rb = Repo(memory=True), Repo(memory=True)
        sa, sbi = TcpSwarm(), TcpSwarm()
        fb = FaultSwarm(sbi, plan)
        ra.set_swarm(sa)
        rb.set_swarm(fb)
        fb.connect(sa.address)
        urls = [ra.create({"edits": []}) for _ in range(n_docs)]
        handles = [rb.open(u) for u in urls]
        for h in handles:
            assert h.value(timeout=30) is not None

        t0 = _t.perf_counter()
        quarter = max(1, n_edits // 4)
        for i in range(n_edits):
            for u in urls:
                ra.change(u, lambda d, i=i: d["edits"].append(i))
            if i % 5 == 0:
                for h in handles:
                    h.change(lambda d, i=i: d["edits"].append(1000 + i))
            if i % quarter == quarter - 1:
                fb.tick()  # kill/heal schedule fires mid-burst
        while plan.tick < 4:
            fb.tick()  # link healed for the convergence wait
        want = n_edits + (n_edits + 4) // 5
        deadline = _t.perf_counter() + 120
        while _t.perf_counter() < deadline:
            vals = [h.value() for h in handles]
            if all(
                v is not None and len(v.get("edits", [])) >= want
                for v in vals
            ) and all(
                len(ra.doc(u).get("edits", [])) >= want for u in urls
            ):
                break
            _t.sleep(0.01)
        else:
            raise AssertionError("config_churn did not converge")
        dt = _t.perf_counter() - t0
        ra_stats = ra.back.network.replication.stats
        rb_stats = rb.back.network.replication.stats
        counters = {
            "reconnects": sbi.supervisor.stats["reconnects"],
            "resyncs": round(
                ra_stats["resyncs"] + rb_stats["resyncs"]
            ),
            "t_resync_ms": round(
                ra_stats["t_resync_ms"] + rb_stats["t_resync_ms"], 1
            ),
            "frames_dropped_injected": fb.stats[
                "frames_dropped_injected"
            ],
        }
        assert counters["reconnects"] >= 1, counters
        return dt, n_docs * want / dt, counters
    finally:
        for r in (ra, rb):
            if r is not None:
                r.close()
        for s in (fb, sa):
            if s is not None:
                s.destroy()
        for k, v in env_save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _config_swarm(n_peers=None, n_edits=24):
    """BASELINE round-19 fleet config: N in-process daemons joined
    ONLY through the DHT (net/discovery/ — no explicit connect()
    anywhere), a subset killed and healed by a seeded FaultPlan
    mid-burst, bounded gossip fanout active. Measures the wall from
    first edit to every surviving peer holding the creator's doc
    BIT-IDENTICAL, mean DHT lookup hops, and per-peer frame
    amplification (replication frames sent per edit per peer) — the
    number HM_GOSSIP_FANOUT must bound regardless of peer count."""
    import time as _t

    from hypermerge_tpu import telemetry as _tele
    from hypermerge_tpu.net.discovery import DhtNode, DhtSwarm
    from hypermerge_tpu.net.faults import FaultPlan, FaultSwarm
    from hypermerge_tpu.repo import Repo

    if n_peers is None:
        n_peers = int(os.environ.get("BENCH_SWARM_PEERS", "16"))
    fanout = 4
    env_save = {
        k: os.environ.get(k)
        for k in (
            "HM_REDIAL_BASE_MS", "HM_REDIAL_MAX_S", "HM_DHT_ANNOUNCE_S",
            "HM_DHT_LOOKUP_S", "HM_GOSSIP_FANOUT",
            "HM_GOSSIP_RESHUFFLE_S", "HM_NET_PING_S",
        )
    }
    boot = None
    repos, swarms, faulted = [], [], []
    try:
        os.environ["HM_REDIAL_BASE_MS"] = "50"
        os.environ["HM_REDIAL_MAX_S"] = "1"
        os.environ["HM_DHT_ANNOUNCE_S"] = "0.5"
        os.environ["HM_DHT_LOOKUP_S"] = "0.5"
        os.environ["HM_GOSSIP_FANOUT"] = str(fanout)
        os.environ["HM_GOSSIP_RESHUFFLE_S"] = "0.5"
        os.environ["HM_NET_PING_S"] = "0"  # N^2 keepalive threads off
        boot = DhtNode()
        # ~1/5 of the fleet churns: seeded kill mid-burst, heal after
        n_churn = max(1, n_peers // 5)
        for i in range(n_peers):
            r = Repo(memory=True)
            sw = DhtSwarm(bootstrap=[boot.address])
            if 0 < i <= n_churn:  # never the creator
                plan = FaultPlan(
                    seed=19 + i, events=[(1, "kill"), (2, "heal")]
                )
                sw = FaultSwarm(sw, plan)
                faulted.append(sw)
            r.set_swarm(sw)
            repos.append(r)
            swarms.append(sw)
        url = repos[0].create({"edits": []})
        handles = [r.open(url) for r in repos[1:]]
        for h in handles:
            # pure-DHT discovery: announce/lookup walks find the
            # creator (and each other) with no addresses exchanged
            assert h.value(timeout=120) is not None
        frames0 = [
            r.back.network.replication.stats["frames_tx"] for r in repos
        ]
        snap0 = _tele.snapshot()
        t0 = _t.perf_counter()
        third = max(1, n_edits // 3)
        for i in range(n_edits):
            repos[0].change(url, lambda d, i=i: d["edits"].append(i))
            if i == third:
                for fs in faulted:
                    fs.tick()  # kill fires: churned peers drop
            if i == 2 * third:
                for fs in faulted:
                    fs.tick()  # heal: supervised redial + resync
        for fs in faulted:
            while fs.plan.tick < 2:
                fs.tick()
        deadline = _t.perf_counter() + 180
        want = list(range(n_edits))
        while _t.perf_counter() < deadline:
            vals = [h.value() for h in handles]
            if all(
                v is not None and v.get("edits") == want for v in vals
            ):
                break
            _t.sleep(0.02)
        else:
            raise AssertionError("config_swarm did not converge")
        dt = _t.perf_counter() - t0
        # acked state must be BIT-identical across every peer
        blobs = {
            json.dumps(h.value(), sort_keys=True) for h in handles
        }
        blobs.add(json.dumps(repos[0].doc(url), sort_keys=True))
        assert len(blobs) == 1, "diverged doc state across peers"
        frames = [
            r.back.network.replication.stats["frames_tx"] - f0
            for r, f0 in zip(repos, frames0)
        ]
        amp = [f / n_edits for f in frames]
        snap1 = _tele.snapshot()
        lookups = snap1.get("dht.lookups", 0) - snap0.get(
            "dht.lookups", 0
        )
        hops = snap1.get("dht.lookup_hops", 0) - snap0.get(
            "dht.lookup_hops", 0
        )
        counters = {
            "peers": n_peers,
            "churned": len(faulted),
            "fanout": fanout,
            "frame_amp_max": round(max(amp), 1),
            "frame_amp_mean": round(sum(amp) / len(amp), 1),
            "lookup_hops_mean": round(hops / max(lookups, 1), 2),
            "reconnects": sum(
                sup.stats["reconnects"]
                for sup in (
                    getattr(sw, "supervisor", None) for sw in swarms
                )
                if sup is not None
            ),
        }
        # the fleet claim: per-peer frames stay O(fanout), not O(peers)
        # (generous slack for relay hops + announce/length frames)
        assert counters["frame_amp_max"] <= 4 * fanout + 8, counters
        return dt, counters
    finally:
        for r in repos:
            try:
                r.close()
            except Exception:
                pass
        for sw in swarms:
            try:
                sw.destroy()
            except Exception:
                pass
        if boot is not None:
            boot.close()
        for k, v in env_save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _config_fleet1000():
    """THIS round's scaling config: does the per-peer steady-state
    bill stay flat from 100 to 1000 peers? Two parts:

    1. A REAL mini-fleet on the async transport (HM_NET_ASYNC=1,
       HM_CURSOR_DELTA=1): measures threads per daemon (the selector
       loop must not spend a thread per connection), real cold-join
       walls, and the live delta/suppressed cursor split.
    2. A deterministic SEEDED simulation of the steady-state gossip
       period at N=100 and N=1000 using the production GossipSampler
       + the delta-cursor ledger rule (send only entries the target
       has not acked; all-caught-up suppresses the frame; max-wins
       merge). frames/peer/period must stay flat within 2x across the
       10x fleet — O(fanout), not O(peers). Cold-join p99 at N=1000 is
       extrapolated from the real samples by the Kademlia hop ratio
       log(1000)/log(n_real) (labelled simulated in BASELINE.md)."""
    import math
    import random as _rnd
    import threading as _th
    import time as _t

    from hypermerge_tpu import telemetry as _tele
    from hypermerge_tpu.net.aio import get_loop
    from hypermerge_tpu.net.discovery import (
        DhtNode, DhtSwarm, GossipSampler,
    )
    from hypermerge_tpu.repo import Repo

    t_start = _t.perf_counter()
    fanout = 4
    n_real = int(os.environ.get("BENCH_FLEET_REAL_PEERS", "12"))
    env_save = {
        k: os.environ.get(k)
        for k in (
            "HM_NET_ASYNC", "HM_CURSOR_DELTA", "HM_REDIAL_BASE_MS",
            "HM_REDIAL_MAX_S", "HM_DHT_ANNOUNCE_S", "HM_DHT_LOOKUP_S",
            "HM_GOSSIP_FANOUT", "HM_GOSSIP_RESHUFFLE_S", "HM_NET_PING_S",
        )
    }
    boot = None
    repos, swarms = [], []
    try:
        os.environ["HM_NET_ASYNC"] = "1"
        os.environ["HM_CURSOR_DELTA"] = "1"
        os.environ["HM_REDIAL_BASE_MS"] = "50"
        os.environ["HM_REDIAL_MAX_S"] = "1"
        os.environ["HM_DHT_ANNOUNCE_S"] = "0.5"
        os.environ["HM_DHT_LOOKUP_S"] = "0.5"
        os.environ["HM_GOSSIP_FANOUT"] = str(fanout)
        os.environ["HM_GOSSIP_RESHUFFLE_S"] = "0.5"
        os.environ["HM_NET_PING_S"] = "0"
        # the loop singleton and its dispatch pool are process-wide
        # infra: create them BEFORE the census so the count charges
        # per-daemon cost only
        get_loop()
        boot = DhtNode()
        snap0 = _tele.snapshot()
        threads0 = _th.active_count()
        for _i in range(n_real):
            r = Repo(memory=True)
            sw = DhtSwarm(bootstrap=[boot.address])
            r.set_swarm(sw)
            repos.append(r)
            swarms.append(sw)
        url = repos[0].create({"edits": []})
        t_open = _t.perf_counter()
        handles = [r.open(url) for r in repos[1:]]
        join_s = [None] * len(handles)
        deadline = _t.perf_counter() + 120
        while any(j is None for j in join_s):
            assert _t.perf_counter() < deadline, "cold joins stalled"
            for i, h in enumerate(handles):
                if join_s[i] is not None:
                    continue
                try:
                    if h.value(timeout=0.01) is not None:
                        join_s[i] = _t.perf_counter() - t_open
                except TimeoutError:
                    pass
            _t.sleep(0.02)
        # a short steady-state burst so the cursor split has signal
        for i in range(24):
            repos[0].change(url, lambda d, i=i: d["edits"].append(i))
        want = list(range(24))
        deadline = _t.perf_counter() + 60
        while _t.perf_counter() < deadline:
            if all(
                (h.value() or {}).get("edits") == want for h in handles
            ):
                break
            _t.sleep(0.02)
        else:
            raise AssertionError("config_fleet1000 burst did not converge")
        threads_per_daemon = (_th.active_count() - threads0) / n_real
        snap1 = _tele.snapshot()

        def _grew(name):
            return snap1.get(name, 0) - snap0.get(name, 0)

        aio_conns = snap1.get("net.aio.conns", 0)
        delta_tx = _grew("net.cursor.delta_tx")
        suppressed = _grew("net.cursor.suppressed")
        full_tx = _grew("net.cursor.full_tx")
    finally:
        for r in repos:
            try:
                r.close()
            except Exception:
                pass
        for sw in swarms:
            try:
                sw.destroy()
            except Exception:
                pass
        if boot is not None:
            boot.close()
        for k, v in env_save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # -- part 2: seeded steady-state period model, N=100 vs N=1000 ----
    class _P:
        __slots__ = ("id",)

        def __init__(self, i):
            self.id = f"p{i:04d}"

    def frames_per_peer_period(n, periods=24):
        peers = [_P(i) for i in range(n)]
        others = [peers[:i] + peers[i + 1:] for i in range(n)]
        # reshuffle every round: the production sampler reshuffles its
        # subset every HM_GOSSIP_RESHUFFLE_S — a frozen subset strands
        # any peer outside the writer's reach (exactly what the real
        # anti-entropy sweep + reshuffle exist to repair)
        samplers = [
            GossipSampler(fanout=fanout, reshuffle_s=0.0, seed=1000 + i)
            for i in range(n)
        ]
        clocks = [{} for _ in range(n)]  # actor -> seq (max-wins)
        ledgers = [{} for _ in range(n)]  # target -> {actor: seq} sent
        frames = 0
        counted_from = periods // 2  # let the relay pipeline fill

        def _round(p, count):
            nonlocal frames
            sends = []
            for i in range(n):
                for tgt in samplers[i].sample("doc", others[i]):
                    j = int(tgt.id[1:])
                    sent = ledgers[i].setdefault(j, {})
                    delta = {
                        a: s for a, s in clocks[i].items()
                        if sent.get(a, -1) < s
                    }
                    if not delta:
                        continue  # all caught up: frame suppressed
                    sent.update(delta)
                    sends.append((j, delta))
                    if count:
                        frames += 1
            for j, delta in sends:  # synchronous round: apply after
                for a, s in delta.items():
                    if clocks[j].get(a, -1) < s:
                        clocks[j][a] = s

        for p in range(periods):
            clocks[0]["w"] = p + 1  # one edit per period at the writer
            _round(p, p >= counted_from)
        # drain: no new edits — the fleet must converge BIT-identically
        # (every peer holds the writer's exact clock) within the relay
        # diameter, or the delta ledger dropped an entry somewhere
        for _ in range(30):
            if all(c == clocks[0] for c in clocks):
                break
            _round(periods, False)
        else:
            raise AssertionError(
                f"simulated {n}-peer fleet never converged"
            )
        fpp = frames / (n * (periods - counted_from))
        # one edit per period, so frames/peer/period IS the per-edit
        # frame amplification: the soak's O(fanout) gate must hold at
        # simulated 1000-peer scale too
        assert fpp <= 4 * fanout + 8, fpp
        return fpp

    f100 = frames_per_peer_period(100)
    f1000 = frames_per_peer_period(1000)

    # -- cold-join p99 at N=1000: real samples scaled by hop ratio ----
    rnd = _rnd.Random(1000)
    hop_scale = math.log(1000) / math.log(max(n_real, 2))
    sims = sorted(
        rnd.choice(join_s) * hop_scale * rnd.uniform(0.8, 1.25)
        for _ in range(1000)
    )
    coldjoin_p99 = sims[int(len(sims) * 0.99)]

    out = {
        "real_peers": n_real,
        "threads_per_daemon": round(threads_per_daemon, 2),
        "aio_conns": aio_conns,
        "cursor_full_tx": full_tx,
        "cursor_delta_tx": delta_tx,
        "cursor_suppressed": suppressed,
        "frames_per_peer_period_100": round(f100, 3),
        "frames_per_peer_period_1000": round(f1000, 3),
        "frames_flat_ratio": round(f1000 / max(f100, 1e-9), 2),
        "coldjoin_p99_s": round(coldjoin_p99, 2),
    }
    # the scaling claims: 10x the fleet must not move the per-peer
    # steady-state bill (within 2x), and steady state must run on
    # delta/suppressed frames, not full cursor maps
    assert out["frames_flat_ratio"] <= 2.0, out
    assert delta_tx + suppressed > 0, out
    return round(_t.perf_counter() - t_start, 2), out


_CRASH_CHILD = r"""
import os, sys
sys.path.insert(0, sys.argv[2])
from hypermerge_tpu.repo import Repo

repo = Repo(path=sys.argv[1])
url = repo.create({"edits": []})
print("URL", url, flush=True)
i = 0
while True:
    repo.change(url, lambda d, i=i: d["edits"].append(i))
    if repo.back.live is not None:
        repo.back.live.flush_now()
    repo.back.durability.flush_now()
    print("ACK", i, flush=True)  # durable under HM_FSYNC>=1
    i += 1
"""


def _config_crash(n_acked=150):
    """BASELINE round-11 robustness config: `kill -9` a writer daemon
    mid-burst and measure the reopen+recovery path. A child process
    appends edits to a disk repo under HM_FSYNC=1 (group fsync),
    acking each edit only after the durability flusher settles; the
    parent SIGKILLs it mid-burst, reopens the repo (crash recovery
    runs on open), and verifies the recovered doc holds a gapless
    prefix covering every acked edit. Reports `t_recover_ms` (reopen ->
    doc readable), `blocks_truncated`/`scrub_repairs` from the
    recovery report, and the acked-edit loss bound (must be 0)."""
    import signal
    import subprocess
    import tempfile as _tf
    import time as _t

    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.storage.scrub import last_report

    tmp = _tf.mkdtemp(prefix="hm_crash")
    env = dict(os.environ)
    env["HM_FSYNC"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")  # the child never dispatches
    proc = subprocess.Popen(
        [sys.executable, "-c", _CRASH_CHILD, tmp, str(Path(__file__).parent)],
        stdout=subprocess.PIPE,
        env=env,
        text=True,
    )
    url = None
    acked = -1
    try:
        for line in proc.stdout:
            parts = line.split()
            if parts and parts[0] == "URL":
                url = parts[1]
            elif parts and parts[0] == "ACK":
                acked = int(parts[1])
                if acked + 1 >= n_acked:
                    break
        # mid-burst hard kill: no atexit, no close(), no final flush
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        assert url is not None and acked >= 0, (url, acked)

        t0 = _t.perf_counter()
        repo = Repo(path=tmp)
        try:
            report = repo.back.recovery_report or {}
            h = repo.open(url)
            v = h.value(timeout=60)
            t_recover_ms = (_t.perf_counter() - t0) * 1e3
            edits = v.get("edits", [])
            # gapless prefix, nothing acked lost
            assert list(edits) == list(range(len(edits))), edits[:20]
            assert len(edits) >= acked + 1, (len(edits), acked)
            from hypermerge_tpu.storage import scrub as scrub_mod

            # item-count repairs from the scrub report's own counter
            # list (no hand-copied drift), byte totals kept separate
            byte_keys = ("bytes_truncated", "sig_fragment_bytes")
            counters = {
                "acked": acked + 1,
                "recovered_edits": len(edits),
                "acked_lost": max(0, acked + 1 - len(edits)),
                # whole acked blocks dropped: writable feeds never
                # lose blocks in recovery (the loss bound), so this
                # is expected 0 — it is the invariant, not dead code
                "blocks_truncated": report.get(
                    "tail_blocks_dropped", 0
                ),
                "bytes_truncated": report.get("bytes_truncated", 0),
                "scrub_repairs": sum(
                    report.get(k, 0)
                    for k in scrub_mod._COUNTERS
                    if k != "feeds" and k not in byte_keys
                ),
                "recovery_ran": 1 if repo.back.recovery_report else 0,
            }
            assert counters["recovery_ran"] == 1, counters
            assert last_report(tmp) is not None
            return t_recover_ms, counters
        finally:
            repo.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        shutil.rmtree(tmp, ignore_errors=True)


def _config6_live_burst(n_ops=8192, n_burst=256):
    """Live-apply on ONE hot text-trace doc (the single-doc shape of
    config6, on the LIVE path): a stored n_ops-op doc opens lazily,
    then a remote burst of n_burst single-op edits applies through the
    per-tick engine. Reports first-edit latency (the cliff BENCH_r05
    measured as a full host replay), burst edits/s, and the engine's
    per-stage tick budget. HM_LIVE=0 turns this into a measurement of
    the host replay cliff itself."""
    import tempfile as _tf
    import time as _t

    from hypermerge_tpu.crdt.frontend_state import FrontendDoc
    from hypermerge_tpu.crdt.opset import OpSet
    from hypermerge_tpu.repo import Repo

    tmp = _tf.mkdtemp(prefix="hm_live6")
    try:
        repo = Repo(path=tmp)
        url = repo.create({"t": ""})
        # seed the stored trace in chunked changes (setup, untimed)
        from hypermerge_tpu.models import Text

        repo.change(url, lambda d: d.__setitem__("t", Text("seed")))
        chunk = 64
        for base in range(0, n_ops, chunk):
            repo.change(
                url,
                lambda d, base=base: d["t"].insert(
                    len(d["t"]), "x" * chunk
                ),
            )
        from hypermerge_tpu.utils.ids import validate_doc_url

        doc_id = validate_doc_url(url)
        stored = []
        back_doc = repo.back.docs[doc_id]
        for actor_id, end in back_doc.clock.items():
            actor = repo.back._get_or_create_actor(actor_id)
            stored.extend(actor.changes_in_window(0, end))
        repo.close()

        repo2 = Repo(path=tmp)
        h = repo2.open(url)
        assert h.value(timeout=60) is not None
        doc = repo2.back.docs[doc_id]
        # a synthetic peer continues the doc with single-op edits
        peer_opset = OpSet()
        peer_front = FrontendDoc()
        peer_front.apply_patch(peer_opset.apply_changes(stored))
        peer = "livepeer00000001"
        seqs = [0]

        def peer_edit():
            seqs[0] += 1
            req, _ = peer_front.change(
                lambda d: d["t"].insert(len(d["t"]), "!"),
                peer,
                seqs[0],
            )
            ch, patch = peer_opset.apply_local_request(req)
            peer_front.apply_patch(patch)
            return ch

        first = peer_edit()
        # pre-generate the burst so the timed region measures the
        # APPLY path (the peer-side OpSet generator is O(doc) per edit
        # and would otherwise serialize the stream into 1-change ticks)
        burst = [peer_edit() for _ in range(n_burst)]

        t0 = _t.perf_counter()
        doc.apply_remote_changes([first])
        while doc.clock.get(peer, 0) < 1:
            _t.sleep(0.0005)
        if repo2.back.live is not None:
            repo2.back.live.flush_now()
        first_ms = (_t.perf_counter() - t0) * 1e3

        t0 = _t.perf_counter()
        for base in range(0, n_burst, 32):  # replication-chunk shaped
            doc.apply_remote_changes(burst[base : base + 32])
        while doc.clock.get(peer, 0) < 1 + n_burst:
            _t.sleep(0.0005)
        if repo2.back.live is not None:
            repo2.back.live.flush_now()
        dt = _t.perf_counter() - t0
        stats = _live_stats(repo2)
        repo2.close()
        return first_ms, n_burst / dt, stats
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _config6_demote_readopt(n_ops=4096, n_docs=3, rounds=3):
    """Demote -> re-edit cycle (the HM_LIVE_MAX_BYTES lifecycle): N
    stored text docs open lazily, each takes a live local edit
    (adopt); a byte cap below one doc's footprint demotes every idle
    doc after its tick, so each round-robin edit RE-adopts a demoted
    doc from its sidecars. Reports the median re-adoption edit latency
    (ms) and the engine's demote/readopt counters — the trajectory
    metric for the byte-bounded live engine."""
    import tempfile as _tf
    import time as _t

    from hypermerge_tpu.models import Text
    from hypermerge_tpu.repo import Repo

    tmp = _tf.mkdtemp(prefix="hm_dem6")
    old = os.environ.get("HM_LIVE_MAX_BYTES")
    repo2 = None
    try:
        repo = Repo(path=tmp)
        urls = []
        chunk = 64
        for _i in range(n_docs):
            url = repo.create({"t": ""})
            repo.change(url, lambda d: d.__setitem__("t", Text("seed")))
            for _base in range(0, n_ops, chunk):
                repo.change(
                    url,
                    lambda d: d["t"].insert(len(d["t"]), "x" * chunk),
                )
            urls.append(url)
        repo.close()

        os.environ["HM_LIVE_MAX_BYTES"] = "1"  # only the MRU survives
        repo2 = Repo(path=tmp)
        handles = repo2.open_many(urls)
        for h in handles:
            assert h.value(timeout=60) is not None
        eng = repo2.back.live
        if eng is None:
            return None  # HM_LIVE=0: no lifecycle to measure
        for u in urls:  # round 0: first adoption of every doc
            repo2.change(u, lambda d: d["t"].insert(len(d["t"]), "!"))
            eng.flush_now()
        lats = []
        for _rnd in range(rounds):
            for u in urls:
                t0 = _t.perf_counter()
                repo2.change(
                    u, lambda d: d["t"].insert(len(d["t"]), "?")
                )
                lats.append((_t.perf_counter() - t0) * 1e3)
                eng.flush_now()  # tick + budget pass demotes the rest
        lats.sort()
        stats = _live_stats(repo2)
        assert stats.get("readopted", 0) >= rounds * (n_docs - 1), stats
        return lats[len(lats) // 2], stats
    finally:
        if repo2 is not None:
            repo2.close()
        if old is None:
            os.environ.pop("HM_LIVE_MAX_BYTES", None)
        else:
            os.environ["HM_LIVE_MAX_BYTES"] = old
        shutil.rmtree(tmp, ignore_errors=True)


def _config_coldopen(n_docs, n_ops):
    """Pack-plane scaling gate (ISSUE 19): a cold open at ~10x the
    primary corpus, once with the pack serialized (HM_PACK_WORKERS=1)
    and once with the full pool (=4, BENCH_COLDOPEN_WORKERS), same disk
    state. Reports the pool shape, per-worker busy lanes, the pool's
    lane wall, and two derived gates:

      coldopen_pack_speedup — sum(per-worker busy) / pack lane wall of
        the pooled pass: the pool's REALIZED parallelism. The >=3x
        target applies on a >=4-core host; a 1-2 core box reports its
        honest (lower) number rather than asserting.
      coldopen_pack_bound   — the pooled pack lane wall no longer
        dominates: pack_wall <= max(io busy, dispatch busy), i.e. the
        cold open is bounded by slab IO / device dispatch, not by the
        host pack.

    Scale with BENCH_COLDOPEN_DOCS (default 10x BENCH_DOCS) and
    BENCH_COLDOPEN_OPS (default 256 — ops/doc shrinks so the 10x doc
    axis, which is what shards across pack workers, carries the
    scaling). The serialized pass's pack busy is also reported so
    serial-vs-pool wall math stays possible downstream."""
    from hypermerge_tpu.ops.corpus import make_corpus

    co_docs = int(
        os.environ.get("BENCH_COLDOPEN_DOCS", str(n_docs * 10))
    )
    co_ops = int(os.environ.get("BENCH_COLDOPEN_OPS", "256"))
    workers = int(os.environ.get("BENCH_COLDOPEN_WORKERS", "4"))
    co_tmp = tempfile.mkdtemp(prefix="hm_bench_co")

    def _pass(n):
        old = os.environ.get("HM_PACK_WORKERS")
        os.environ["HM_PACK_WORKERS"] = str(n)
        try:
            return _open_and_materialize(co_tmp, urls)
        finally:
            if old is None:
                os.environ.pop("HM_PACK_WORKERS", None)
            else:
                os.environ["HM_PACK_WORKERS"] = old

    try:
        urls = make_corpus(co_tmp, co_docs, co_ops, threads=16)
        dt_serial, st_serial = _pass(1)
        dt_pool, st_pool = _pass(workers)
        if not st_pool.get("pipeline"):
            return None  # serial twin: no pack plane to measure
        lanes = [
            float(b)
            for b in (st_pool.get("t_pack_busy_per_worker") or [])
        ]
        pack_wall = float(st_pool.get("t_pack_wall", 0.0))
        serial_busy = float(
            st_serial.get("t_pack_busy", st_serial.get("t_pack", 0.0))
        )
        io_b = float(st_pool.get("t_io_busy", st_pool.get("t_io", 0.0)))
        disp_b = float(
            st_pool.get("t_dispatch_busy", st_pool.get("t_dispatch", 0.0))
        )
        return {
            "config_coldopen_s": round(dt_pool, 2),
            "config_coldopen_serial_s": round(dt_serial, 2),
            "docs": co_docs,
            "ops_per_doc": co_ops,
            "cores": os.cpu_count() or 1,
            "pack_workers": st_pool.get("pack_workers"),
            "t_pack_busy_per_worker": lanes,
            "t_pack_wall": round(pack_wall, 3),
            "t_pack_serial_busy": round(serial_busy, 3),
            "t_io_busy": round(io_b, 3),
            "t_dispatch_busy": round(disp_b, 3),
            "coldopen_pack_speedup": (
                round(sum(lanes) / pack_wall, 2) if pack_wall > 0 else None
            ),
            "coldopen_pack_bound": bool(pack_wall <= max(io_b, disp_b)),
        }
    finally:
        shutil.rmtree(co_tmp, ignore_errors=True)


def _config_read(tmp, urls):
    """BASELINE round-15 serving config (ISSUE 11): N concurrent
    reader threads point-read the stored corpus through the
    HBM-resident serving tier — a hot/cold mix (90% of reads over a
    32-doc hot set, 10% uniform over BENCH_READ_DOCS docs). Reports
    read QPS, p50/p99 read latency from the telemetry histogram
    (serve.read_s), the tier's counters, and the measured speedup over
    per-request host materialization of the same mix (the HM_SERVE=0
    cost). Scale with BENCH_READERS / BENCH_READS / BENCH_READ_DOCS
    (corpus size itself rides BENCH_DOCS).

    The speedup is doc-size-sensitive: host materialization is O(doc)
    per read while a served read is ~constant (batcher round trip +
    one shared dispatch), so tiny-doc corpora (BENCH_OPS <~ 256) can
    read below 1x — the tier's regime is the default 1k-op docs and
    up, where same-box runs measure ~13x."""
    import random as _rnd
    import threading as _th

    from hypermerge_tpu import telemetry
    from hypermerge_tpu.repo import Repo
    from hypermerge_tpu.serve.tier import host_value
    from hypermerge_tpu.utils.ids import validate_doc_url

    readers = int(os.environ.get("BENCH_READERS", "8"))
    n_reads = int(os.environ.get("BENCH_READS", "4000"))
    n_sub = int(os.environ.get("BENCH_READ_DOCS", "2048"))
    host_reads = max(64, n_reads // 16)
    repo = Repo(path=tmp)
    try:
        if repo.back.serve is None:
            raise RuntimeError("serving tier off (HM_SERVE=0)")
        sub = urls[: min(len(urls), n_sub)]
        repo.open_many(sub)
        repo.back.fetch_bulk_summaries()
        hot = sub[:32]
        rng = _rnd.Random(0xEAD5)
        mix = [
            hot[rng.randrange(len(hot))]
            if rng.random() < 0.9
            else sub[rng.randrange(len(sub))]
            for _ in range(n_reads)
        ]
        query = {"kind": "len", "path": []}
        for u in hot:  # steady state: hot set resident before timing
            repo.read(u, query)
        hist = repo.back.serve._hist
        h0 = hist.value()
        snap0 = telemetry.snapshot()

        # -- timed: concurrent readers over the served tier ------------
        errs = []

        def reader(n):
            try:
                for i in range(n, n_reads, readers):
                    if repo.read(mix[i], query) is None:
                        raise AssertionError(f"None read for {mix[i]}")
            except Exception as e:  # pragma: no cover - failure surface
                errs.append(e)

        threads = [
            _th.Thread(target=reader, args=(n,)) for n in range(readers)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        h1 = hist.value()
        snap1 = telemetry.snapshot()
        qps = n_reads / dt
        p50 = _hist_quantile(hist.buckets, h0, h1, 0.50)
        p99 = _hist_quantile(hist.buckets, h0, h1, 0.99)
        fallbacks = snap1["serve.fallbacks"] - snap0.get(
            "serve.fallbacks", 0
        )

        # -- baseline: per-request host materialization, same mix, same
        # thread count (what every one of these reads cost pre-tier) --
        docs = {
            u: repo.back.docs[validate_doc_url(u)] for u in set(mix)
        }
        herrs = []

        def host_reader(n):
            try:
                for i in range(n, host_reads, readers):
                    if host_value(docs[mix[i]], query) is None:
                        raise AssertionError("None host read")
            except Exception as e:  # pragma: no cover
                herrs.append(e)

        threads = [
            _th.Thread(target=host_reader, args=(n,))
            for n in range(readers)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        host_dt = time.perf_counter() - t0
        if herrs:
            raise herrs[0]
        host_qps = host_reads / host_dt
        stats = {
            "docs": len(sub),
            "readers": readers,
            "reads": n_reads,
            "hot_docs": len(hot),
            "fallbacks_steady": int(fallbacks),
            "batches": int(
                snap1["serve.batches"] - snap0.get("serve.batches", 0)
            ),
            "installs": int(
                snap1["serve.installs"] - snap0.get("serve.installs", 0)
            ),
            "hits": int(
                snap1["serve.hits"] - snap0.get("serve.hits", 0)
            ),
            "resident_bytes": snap1.get("serve.resident_bytes", 0),
        }
        return qps, p50, p99, host_qps, stats
    finally:
        repo.close()


def _hist_quantile(bounds, before, after, q):
    """Quantile (ms) from the delta of two Histogram.value() snapshots:
    the upper bound of the bucket where the cumulative count crosses
    q (the +Inf tail reports the largest finite bound)."""
    counts = [
        b - a for a, b in zip(before["buckets"], after["buckets"])
    ]
    n = sum(counts)
    if n <= 0:
        return None
    target = q * n
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= target:
            bound = bounds[min(i, len(bounds) - 1)]
            return round(bound * 1e3, 3)
    return round(bounds[-1] * 1e3, 3)


_SERVICE_CHILD = r"""
import bisect, json, random, sys, threading, time

sock, idx = sys.argv[1], int(sys.argv[2])

from hypermerge_tpu.net.ipc import connect_frontend
from hypermerge_tpu.serve.overload import Overload

front, close = connect_frontend(sock)
setup = json.loads(sys.stdin.readline())
read_urls = setup["read_urls"]
own_url = setup["write_urls"][idx]
BOUNDS = setup["bounds"]  # seconds, ascending; +1 overflow slot
query = {"kind": "len", "path": []}

# zipf-ish popularity over the read corpus, identical ordering in
# every client — the aggregate mix concentrates on a shared hot set
# with a long cold tail (the brownout ladder's install-deferral prey)
w = [1.0 / (k + 1) ** 1.2 for k in range(len(read_urls))]
cum, s = [], 0.0
for x in w:
    s += x
    cum.append(s)

h = front.open(own_url)

def val(timeout=0.05):
    try:
        return h.value(timeout=timeout)
    except TimeoutError:
        return None

deadline = time.time() + 60
while time.time() < deadline:
    if val() is not None:
        break
    time.sleep(0.02)
else:
    raise SystemExit("write doc never materialized")

wseq = [0]    # next write sequence (keys are c{idx}.{seq})
wacked = [0]  # contiguous acked prefix: keys 0..wacked-1 observed

def hist_new():
    return [0] * (len(BOUNDS) + 1)

def hist_add(hist, dt):
    hist[bisect.bisect_left(BOUNDS, dt)] += 1

print("ready", flush=True)

for line in sys.stdin:
    cmd = json.loads(line)
    if cmd.get("op") == "quit":
        break
    threads, secs = int(cmd["threads"]), float(cmd["secs"])
    do_write = bool(cmd.get("writes"))
    stop = time.time() + secs
    out = {
        "reads": 0, "shed": 0, "errors": 0, "opens": 0,
        "rhist": hist_new(), "whist": hist_new(),
        "writes": 0, "write_timeouts": 0,
    }
    lock = threading.Lock()

    def reader(seed):
        rng = random.Random((idx << 10) ^ seed)
        n = shed = errs = opens = 0
        hist = hist_new()
        k = 0
        while time.time() < stop:
            u = read_urls[bisect.bisect_left(cum, rng.random() * s)]
            k += 1
            t0 = time.perf_counter()
            try:
                if k % 64 == 0:
                    # the open/watch lane of the mix: (re)open the doc
                    # and read the handle's materialized view
                    if front.open(u).value(timeout=60.0) is None:
                        errs += 1
                    else:
                        opens += 1
                    continue
                v = front.read(u, query, timeout=60.0)
                if v is None:
                    errs += 1
                else:
                    n += 1
                    hist_add(hist, time.perf_counter() - t0)
            except Overload as e:
                # the typed refusal: a well-behaved client backs off
                # for retry_after (capped so the storm stays a storm)
                shed += 1
                time.sleep(min(max(e.retry_after_s, 1e-3), 0.05))
            except Exception:
                errs += 1
        with lock:
            out["reads"] += n
            out["shed"] += shed
            out["errors"] += errs
            out["opens"] += opens
            for i, c in enumerate(hist):
                out["rhist"][i] += c

    def writer():
        # ack-paced durable writes to this tenant's own doc: the next
        # edit is released only when the previous one's patch echo is
        # visible in the handle — under SHED the WAL's stretched
        # gather window paces this loop down instead of refusing it
        n = tmo = 0
        hist = hist_new()
        while time.time() < stop:
            seq = wseq[0]
            key = "c%d.%d" % (idx, seq)
            t0 = time.perf_counter()
            front.change(
                own_url,
                lambda d, _k=key, _s=seq: d["edits"].__setitem__(
                    _k, _s
                ),
            )
            wseq[0] += 1
            lim = time.time() + 30
            acked = False
            while time.time() < lim:
                v = val(timeout=0.02)
                if v is not None and key in v.get("edits", {}):
                    acked = True
                    break
                time.sleep(0.002)
            if acked:
                n += 1
                hist_add(hist, time.perf_counter() - t0)
                if seq == wacked[0]:  # contiguous prefix only
                    wacked[0] = seq + 1
            else:
                tmo += 1
                break  # ack pipeline stalled: stop this phase's writer
        with lock:
            out["writes"] += n
            out["write_timeouts"] += tmo
            for i, c in enumerate(hist):
                out["whist"][i] += c

    t0 = time.perf_counter()
    ts = [
        threading.Thread(target=reader, args=(k,))
        for k in range(threads)
    ]
    if do_write:
        ts.append(threading.Thread(target=writer))
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    out["secs"] = time.perf_counter() - t0
    out["acked"] = wacked[0]
    print(json.dumps(out), flush=True)

close()
"""


def _svc_quantile(bounds, counts, q):
    """Quantile (ms) over a merged client-side histogram: `counts` is
    len(bounds)+1 (overflow last); the overflow tail reports one step
    past the last edge so a saturated histogram still moves."""
    n = sum(counts)
    if n <= 0:
        return None
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= q * n:
            bound = (
                bounds[i] if i < len(bounds) else bounds[-1] * 2
            )
            return round(bound * 1e3, 3)
    return round(bounds[-1] * 2 * 1e3, 3)


def _config_service():
    """THE top-level repo number (ISSUE 20): every plane at once,
    under overload, behind the one front door. A hub daemon
    (net/ipc.py --hub, serve tier on, service plane on, durable acks
    over the group-commit WAL, DHT member) serves a zipf-distributed
    open/read/write/watch mix from BENCH_SERVICE_CLIENTS frontend
    PROCESSES — one IPC connection each, so the hub's per-connection
    tenant tagging makes every client a quota tenant — while an
    in-process DHT peer replicates a slice of the corpus (gossip +
    anti-entropy competing with hot reads, exactly the traffic the
    brownout ladder deprioritizes).

    The driver ramps closed-loop reader threads per client
    (1, 2, 4, ... BENCH_SERVICE_MAX_THREADS) until aggregate read
    throughput plateaus or the daemon starts shedding — that round's
    peak is the SATURATION point — then holds a 2x-saturation storm
    for BENCH_SERVICE_HOLD_S with durable writers running, then drops
    the load and probes until client-observed p99 is back under the
    SLO with zero shed (recovery_to_slo_s). Gates (the `gates` block,
    all must hold):

      reads_never_error   — across ramp+storm+recovery, every read
        either returns a value, is answered from the host memo path
        (indistinguishable from a value, by design), or is refused
        with the TYPED Overload reply. Zero untyped errors.
      acked_lost_zero     — every write a client observed acked is
        present in the final doc state (writes are backpressured via
        WAL ack-pacing under SHED, never dropped).
      recovery_within_gate — p99 back under HM_SERVICE_P99_SLO_MS
        within BENCH_SERVICE_RECOVERY_GATE_S of the storm ending.
      shed_order_ok       — refusals only ever happened AFTER the
        ladder climbed through BROWNOUT (transitions >= 2: the
        documented shed order, cold installs brown out before hot
        reads are refused).
      attributed          — no silent refusals: the daemon's
        service.shed_reads equals both the per-tenant refused sum in
        the service report AND the clients' own Overload count.

    Runs in the config_writers daemon posture (HM_WORKERS rides the
    caller's env: 0 = in-process plane on the CI box, N = sharded);
    scale with BENCH_SERVICE_CLIENTS/DOCS/HOLD_S/SLO_MS."""
    import tempfile as _tempfile

    from hypermerge_tpu.net.discovery import DhtNode, DhtSwarm
    from hypermerge_tpu.repo import Repo

    n_clients = int(os.environ.get("BENCH_SERVICE_CLIENTS", "4"))
    n_docs = int(os.environ.get("BENCH_SERVICE_DOCS", "48"))
    ramp_s = float(os.environ.get("BENCH_SERVICE_RAMP_S", "1.0"))
    hold_s = float(os.environ.get("BENCH_SERVICE_HOLD_S", "3.0"))
    slo_ms = float(os.environ.get("BENCH_SERVICE_SLO_MS", "25"))
    gate_s = float(
        os.environ.get("BENCH_SERVICE_RECOVERY_GATE_S", "10")
    )
    max_threads = int(
        os.environ.get("BENCH_SERVICE_MAX_THREADS", "16")
    )
    # client-side latency buckets (seconds): merged across clients
    # for the p50/p99 SLO gating — sub-ms floor, 2.5s overflow edge
    bounds = [
        0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05,
        0.1, 0.25, 0.5, 1.0, 2.5,
    ]

    tmp = _tempfile.mkdtemp(prefix="hm-service-")
    sock = os.path.join(tmp, "daemon.sock")
    env = _writer_daemon_env()
    env["HM_SERVICE"] = "1"
    env["HM_SERVICE_P99_SLO_MS"] = str(slo_ms)
    env.setdefault("HM_SERVICE_TICK_MS", "25")
    # per-tenant quota low enough that SHED visibly bites on a small
    # box (each tenant still gets a real trickle: no starvation)
    env.setdefault("HM_QUOTA_READS_S", "64")
    env.setdefault("HM_QUOTA_BURST", "16")
    env.setdefault("HM_DHT_ANNOUNCE_S", "0.5")
    env.setdefault("HM_DHT_LOOKUP_S", "0.5")

    boot = DhtNode()
    daemon = subprocess.Popen(
        [
            sys.executable, "-m", "hypermerge_tpu.net.ipc",
            os.path.join(tmp, "repo"), sock, "--hub", "--dht",
            "--dht-bootstrap", f"127.0.0.1:{boot.address[1]}",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    clients = []
    peer = sw = close = None
    try:
        line = daemon.stdout.readline()
        if "ready" not in line:
            raise RuntimeError(f"daemon failed to start: {line!r}")
        from hypermerge_tpu.net.ipc import connect_frontend

        front, close = connect_frontend(sock)
        read_urls = [
            front.create({"k": i, "pad": "x" * 64})
            for i in range(n_docs)
        ]
        write_urls = [
            front.create({"edits": {}}) for _ in range(n_clients)
        ]
        # round-trip on the ordered channel: every doc is registered
        # in the daemon before any client opens or reads one
        got = []
        front.materialize(write_urls[-1], 1, got.append)
        deadline = time.time() + 60
        while not got and time.time() < deadline:
            time.sleep(0.02)
        if not got:
            raise RuntimeError("doc registration never acked")

        # the DHT peer: replicates a slice of the corpus through
        # announce/lookup discovery — live anti-entropy + gossip
        # traffic on the daemon during the storm
        peer = Repo(memory=True)
        sw = DhtSwarm(bootstrap=[boot.address])
        peer.set_swarm(sw)
        for u in read_urls[: min(4, n_docs)]:
            peer.open(u)

        setup = json.dumps({
            "read_urls": read_urls,
            "write_urls": write_urls,
            "bounds": bounds,
        })
        clients = [
            subprocess.Popen(
                [sys.executable, "-c", _SERVICE_CHILD, sock, str(i)],
                env=env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for i in range(n_clients)
        ]
        for c in clients:
            c.stdin.write(setup + "\n")
            c.stdin.flush()
        for c in clients:
            if c.stdout.readline().strip() != "ready":
                raise RuntimeError(
                    f"client failed: {c.stderr.read()[-500:]}"
                )

        def phase(threads, secs, writes):
            cmd = json.dumps({
                "op": "phase", "threads": threads, "secs": secs,
                "writes": 1 if writes else 0,
            })
            for c in clients:
                c.stdin.write(cmd + "\n")
                c.stdin.flush()
            outs = [json.loads(c.stdout.readline()) for c in clients]
            agg = {
                k: sum(o[k] for o in outs)
                for k in ("reads", "shed", "errors", "opens",
                          "writes", "write_timeouts")
            }
            agg["rhist"] = [
                sum(o["rhist"][i] for o in outs)
                for i in range(len(bounds) + 1)
            ]
            agg["whist"] = [
                sum(o["whist"][i] for o in outs)
                for i in range(len(bounds) + 1)
            ]
            agg["secs"] = max(o["secs"] for o in outs)
            agg["acked"] = [o["acked"] for o in outs]
            agg["qps"] = round(agg["reads"] / agg["secs"], 1)
            return agg

        # -- warmup: install the hot set so the steady baseline and
        # the ramp measure serving, not first-touch installs ---------
        ramp, errors, whist = [], 0, [0] * (len(bounds) + 1)
        writes_total = timeouts = shed_total = 0
        w0 = phase(1, 1.0, writes=False)
        errors += w0["errors"]
        shed_total += w0["shed"]
        time.sleep(0.25)  # let the install/replication queues drain

        # the steady-state reference: one reader/client over the warm
        # hot set, no writers — the SLO the recovery gate returns to
        r0 = phase(1, ramp_s, writes=False)
        errors += r0["errors"]
        shed_total += r0["shed"]
        steady = {
            "qps": r0["qps"],
            "read_p50_ms": _svc_quantile(bounds, r0["rhist"], 0.50),
            "read_p99_ms": _svc_quantile(bounds, r0["rhist"], 0.99),
        }

        # -- ramp: closed-loop threads/client double each round until
        # the daemon starts shedding or the thread budget runs out (a
        # throughput plateau alone is too noisy a stop on a small box;
        # the extra rounds cost ~1s each and the peak is the honest
        # saturation point) -----------------------------------------
        t = 1
        while t <= max_threads:
            r = phase(t, ramp_s, writes=True)
            errors += r["errors"]
            writes_total += r["writes"]
            timeouts += r["write_timeouts"]
            whist = [a + b for a, b in zip(whist, r["whist"])]
            ramp.append({
                "threads": t, "qps": r["qps"], "shed": r["shed"],
                "p99_ms": _svc_quantile(bounds, r["rhist"], 0.99),
            })
            if r["shed"] > 0:
                break
            t *= 2
        peak = max(ramp, key=lambda x: x["qps"])
        saturation_qps = peak["qps"]
        sat_threads = peak["threads"]

        # -- the storm: 2x-saturation offered load, writers on ------
        storm_threads = min(2 * sat_threads, 2 * max_threads)
        r = phase(storm_threads, hold_s, writes=True)
        errors += r["errors"]
        writes_total += r["writes"]
        timeouts += r["write_timeouts"]
        whist = [a + b for a, b in zip(whist, r["whist"])]
        storm = {
            "threads_per_client": storm_threads,
            "qps": r["qps"],
            "reads_ok": r["reads"],
            "reads_shed": r["shed"],
            "opens": r["opens"],
            "read_p99_ms": _svc_quantile(bounds, r["rhist"], 0.99),
            "writes_acked": r["writes"],
        }
        shed_total += sum(x["shed"] for x in ramp) + r["shed"]

        # -- recovery: drop to one thread/client, probe until p99 is
        # back under the SLO with zero shed --------------------------
        t_end = time.perf_counter()
        recovery_s = None
        while time.perf_counter() - t_end < gate_s + 5:
            p = phase(1, 0.4, writes=False)
            errors += p["errors"]
            shed_total += p["shed"]
            p99 = _svc_quantile(bounds, p["rhist"], 0.99)
            if (
                p["shed"] == 0
                and p99 is not None
                and p99 <= slo_ms
            ):
                recovery_s = round(time.perf_counter() - t_end, 2)
                break

        # -- drain the clients, then verify the acked ledger --------
        acked = []
        for c in clients:
            c.stdin.write(json.dumps({"op": "quit"}) + "\n")
            c.stdin.flush()
        for i, c in enumerate(clients):
            c.wait(timeout=30)
        # the coordinator's own handles receive every hub-routed
        # patch; poll until each doc shows the client's acked count
        acked_counts = r["acked"]
        acked_lost = 0
        for i, url in enumerate(write_urls):
            want = acked_counts[i]
            h = front.open(url)
            deadline = time.time() + 60
            edits = {}
            while time.time() < deadline:
                try:
                    v = h.value(timeout=0.5)
                except TimeoutError:
                    v = None
                edits = (v or {}).get("edits", {})
                if len(edits) >= want:
                    break
                time.sleep(0.05)
            acked_lost += sum(
                1 for s_ in range(want) if f"c{i}.{s_}" not in edits
            )
            acked.append(want)

        # -- attribution: the daemon's service report must account
        # for every refusal the clients saw --------------------------
        tele = []
        front.telemetry(tele.append)
        deadline = time.time() + 30
        while not tele and time.time() < deadline:
            time.sleep(0.02)
        payload = tele[0] if tele else {}
        svc = payload.get("service") or {}
        counters = payload.get("counters") or {}
        tenants = svc.get("tenants") or {}
        refused_sum = sum(
            row.get("refused", 0) for row in tenants.values()
        )
        shed_reads = int(svc.get("shed_reads", 0))
        transitions = int(svc.get("transitions", 0))

        gates = {
            "reads_never_error": errors == 0,
            "acked_lost_zero": acked_lost == 0 and sum(acked) > 0,
            "recovery_within_gate": (
                recovery_s is not None and recovery_s <= gate_s
            ),
            "shed_order_ok": shed_reads == 0 or transitions >= 2,
            "attributed": (
                refused_sum == shed_reads
                and shed_total == shed_reads
            ),
        }
        return {
            "clients": n_clients,
            "docs": n_docs,
            "slo_ms": slo_ms,
            "steady": steady,
            "ramp": ramp,
            "saturation_qps": saturation_qps,
            "sat_threads_per_client": sat_threads,
            "storm": storm,
            "recovery_to_slo_s": recovery_s,
            "recovery_gate_s": gate_s,
            "writes_acked": writes_total,
            "write_timeouts": timeouts,
            "write_p50_ms": _svc_quantile(bounds, whist, 0.50),
            "write_p99_ms": _svc_quantile(bounds, whist, 0.99),
            "acked_lost": acked_lost,
            "reads_errors": errors,
            "reads_shed": shed_total,
            "service": {
                "state": svc.get("state_name"),
                "transitions": transitions,
                "shed_reads": shed_reads,
                "brownout_reads": int(svc.get("brownout_reads", 0)),
                "deferred_installs": int(
                    svc.get("deferred_installs", 0)
                ),
                "tenants": tenants,
            },
            "paced_commits": int(
                counters.get("storage.wal.paced_commits", 0)
            ),
            "overload_shed": int(
                counters.get("serve.overload_shed", 0)
            ),
            "gates": gates,
            "gated_ok": all(gates.values()),
        }
    finally:
        for c in clients:
            c.kill()
        if close is not None:
            close()
        if peer is not None:
            peer.close()
        if sw is not None:
            sw.destroy()
        boot.close()
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _config5_union(n_docs=100_000, n_actors=64, seed=0, dirty=1000):
    """100k-doc clock union served from the device-RESIDENT ClockStore
    mirror (ops/clock_mirror.py; BASELINE config 5). Setup uploads the
    matrix once (untimed — a live deployment's mirror accretes with
    writes); the timed region is the realistic hot query: `dirty` fresh
    clock writes land (one batched scatter-max) and the union runs as a
    max-reduce over resident HBM + a [actors] fetch. Contrast r4, which
    re-packed and re-uploaded all 25MB per query (915ms)."""
    import numpy as np

    from hypermerge_tpu.ops.clock_mirror import DeviceClockMirror

    rng = np.random.default_rng(seed)
    clocks = rng.integers(
        1, 1000, size=(n_docs, n_actors), dtype=np.int32
    )
    mirror = DeviceClockMirror(
        capacity_docs=n_docs, capacity_actors=n_actors
    )
    actors = [f"a{j}" for j in range(n_actors)]
    mirror.seed_bulk(
        [f"d{i}" for i in range(n_docs)], actors, clocks
    )
    # warm BOTH query programs (with and without pending writes) at the
    # dirty-bucket shape the timed pass uses, and settle the upload
    mirror.union()
    for i in range(dirty):
        mirror.update(f"d{i}", {actors[i % n_actors]: 1})
    mirror.union()

    t0 = time.perf_counter()
    for i in range(dirty):
        mirror.update(f"d{i}", {actors[i % n_actors]: 2000 + i})
    merged = mirror.union()
    dt = time.perf_counter() - t0
    assert len(merged) == n_actors
    assert merged[actors[(dirty - 1) % n_actors]] >= 2000
    return dt * 1e3  # ms


def _config3_multiactor(n_docs=1024, n_ops=512):
    """BASELINE config 3: 1k synthetic docs x 3 concurrent actors x
    ~500 ops (LWW map + RGA list mix), batched through the device
    kernel. Unlike the single-writer corpus (configs 4), this drives
    the GENERAL sorted-composite pack path and the multi-actor
    tie-break lanes. Timed: warm materialize + liveness/clock fetch to
    host (the render barrier). Correctness for this shape is pinned by
    tests/test_device_materialize.py fuzz vs OpSet."""
    import numpy as np

    from hypermerge_tpu.ops.materialize import materialize_batch
    from hypermerge_tpu.ops.synth import synth_changes

    histories = [
        synth_changes(
            n_ops, n_actors=3, ops_per_change=8, text_frac=0.5, seed=s
        )
        for s in range(n_docs)
    ]

    def full_pass():
        dec = materialize_batch(histories)
        np.asarray(dec.elem_live)
        np.asarray(dec.clock)
        return dec

    full_pass()  # compile + warm
    t0 = time.perf_counter()
    dec = full_pass()
    dt = time.perf_counter() - t0
    assert dec.clock_dict(0), "empty clock"
    return dt, n_docs * n_ops / dt


def _tunnel_rtt_ms():
    """The device link's dispatch+fetch round-trip floor, measured on a
    64-int array (payload-independent). On the tunneled bench box this
    is ~70-120ms and floors any single-dispatch metric (config5's union
    IS one round trip); on direct-attached TPU it is ~1ms."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.zeros(64, jnp.int32)
    f = jax.jit(lambda a: a + 1)
    np.asarray(f(x))  # compile + settle
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(f(x))
        dt = (time.perf_counter() - t0) * 1e3
        best = dt if best is None else min(best, dt)
    return best


def _config6_text_trace(n_ops=None):
    """automerge-perf trace shape (BASELINE.md): ONE text doc, ONE
    author, one op per change — 259,778 ops, the published workload the
    reference's engine (automerge 0.14) takes MINUTES on (~0.4-0.9k
    ops/s, multi-GB heap). Timed region: a warm device materialize of
    the full trace + char-joined text extraction to a host string.
    Correctness at this scale is pinned by tests/test_text_scale.py
    (device == numpy twin == OpSet). BENCH_TRACE_OPS shrinks the trace
    (XLA:CPU compiles the 256k bucket in >10 minutes — published-shape
    numbers need the TPU backend)."""
    if n_ops is None:
        n_ops = int(os.environ.get("BENCH_TRACE_OPS", "259778"))
    import numpy as np

    from hypermerge_tpu.crdt.change import Action
    from hypermerge_tpu.ops.materialize import (
        materialize_batch,
        text_join,
    )
    from hypermerge_tpu.ops.synth import synth_changes

    changes = synth_changes(
        n_ops, n_actors=1, ops_per_change=1, text_frac=1.0, seed=3
    )

    def full_pass():
        dec = materialize_batch([changes])
        n = int(dec.batch.n_ops[0])
        rows = np.nonzero(
            dec.cols["action"][0][:n] == int(Action.MAKE_TEXT)
        )[0]
        return text_join(dec, 0, int(rows[0]))

    full_pass()  # compile + warm every program in the 256k bucket

    t0 = time.perf_counter()
    text = full_pass()
    dt = time.perf_counter() - t0
    assert len(text) > 1000, len(text)
    return dt, n_ops / dt


def main() -> None:
    n_docs = int(os.environ.get("BENCH_DOCS", "10240"))
    n_ops = int(os.environ.get("BENCH_OPS", "1024"))
    host_docs = int(os.environ.get("BENCH_HOST_DOCS", "8"))

    import jax

    from hypermerge_tpu.crdt.opset import OpSet
    from hypermerge_tpu.ops.corpus import make_corpus
    from hypermerge_tpu.ops.synth import synth_changes

    print(f"# device: {jax.devices()[0]}", file=sys.stderr)
    total_ops = n_docs * n_ops

    # -- speculative compile warmup (ops/warmup.py): the XLA compile for
    # the slab executables runs on the far side of the device tunnel, so
    # a daemon thread overlaps it with the corpus write + host baseline
    # below (~93% of the single host core stays free). This mirrors what
    # any serving deployment does at startup; on a box whose persistent
    # compile cache is already warm it is a no-op. cold_first_process
    # then measures the product path, not the compiler.
    warm_thread = None
    if jax.default_backend() != "cpu":
        from hypermerge_tpu.ops.warmup import warmup_bulk

        warm_thread = warmup_bulk(n_docs, n_ops)

    # -- corpus on disk (untimed setup; BENCH_DIR reuses a prior one) --
    bench_dir = os.environ.get("BENCH_DIR")
    tmp = bench_dir or tempfile.mkdtemp(prefix="hm_bench")
    manifest = os.path.join(tmp, "corpus.json")
    if bench_dir and os.path.exists(manifest):
        with open(manifest) as fh:
            meta = json.load(fh)
        assert meta["docs"] == n_docs and meta["ops"] == n_ops, meta
        urls = meta["urls"]
        print(f"# corpus: reusing {tmp}", file=sys.stderr)
    else:
        t0 = time.perf_counter()
        urls = make_corpus(tmp, n_docs, n_ops, threads=16)
        with open(manifest, "w") as fh:
            json.dump({"docs": n_docs, "ops": n_ops, "urls": urls}, fh)
        print(
            f"# corpus: {n_docs} docs x {n_ops} ops written in "
            f"{time.perf_counter()-t0:.1f}s -> {tmp}",
            file=sys.stderr,
        )

    # -- host baseline: incremental OpSet replay (best of 2 — the box
    # load that wobbles the device numbers wobbles this too) ----------
    host_dt = None
    for _ in range(2):
        t0 = time.perf_counter()
        for i in range(host_docs):
            OpSet().apply_changes(
                synth_changes(n_ops, n_actors=1, ops_per_change=16, seed=i)
            )
        d = time.perf_counter() - t0
        host_dt = d if host_dt is None else min(host_dt, d)
    host_rate = host_docs * n_ops / host_dt
    print(
        f"# host baseline: {host_docs} docs x {n_ops} ops in "
        f"{host_dt:.2f}s -> {host_rate:,.0f} ops/s",
        file=sys.stderr,
    )

    # -- cold pass 1: fresh process. Join the warmup before timing: on a
    # fresh box it finished during the corpus write (join is instant);
    # with BENCH_DIR reuse there was no cover, and an in-flight warmup
    # compile/execute would otherwise contaminate the timed region. ----
    if warm_thread is not None:
        # bounded: a stalled tunnel compile must fail loudly in the
        # timed pass (which blocks inside jit anyway), not hang here
        warm_thread.join(timeout=180)
        if warm_thread.is_alive():
            print("# warmup still compiling after 180s", file=sys.stderr)
    dt1, stats1 = _open_and_materialize(tmp, urls)
    rate1 = total_ops / dt1
    print(
        f"# cold_first_process: {dt1:.2f}s -> {rate1:,.0f} ops/s "
        f"(stats {stats1})",
        file=sys.stderr,
    )

    # -- steady-state passes: fresh backend each, compile cached.
    # best-of-3: the host shares one CPU core with the device tunnel, so
    # single-pass numbers swing ~2x with unrelated machine load.
    dts = []
    stats_by_dt = {}
    for _ in range(3):
        d, s = _open_and_materialize(tmp, urls)
        dts.append(d)
        stats_by_dt[d] = s
    dt2 = min(dts)
    stats2 = stats_by_dt[dt2]  # stage breakdown of the BEST pass
    rate2 = total_ops / dt2
    print(
        f"# steady_state (best of {len(dts)}: "
        f"{', '.join(f'{d:.1f}s' for d in dts)}): "
        f"{dt2:.2f}s -> {rate2:,.0f} ops/s (stats {stats2})",
        file=sys.stderr,
    )
    assert stats2.get("fallback", 0) == 0, stats2

    # -- stage breakdown + multi-chip projection (VERDICT r5 item 1) --
    # Serial mode (HM_PIPELINE=0): stage keys are wall times that SUM
    # to the cold open, host stages don't divide across chips, so the
    # projection is host + other + device/8.
    # Pipeline mode (default): stage keys are per-stage BUSY times and
    # the stages OVERLAP — the wall clock is `wall_critical_path`
    # (~max(stage), not sum), and the 8-chip projection is the critical
    # path with only the device leg divided: other + max(host stages,
    # device/8). The t_*_busy aliases + wall_critical_path go into the
    # JSON so the driver sees both views.
    pipelined = bool(stats2.get("pipeline", 0))
    host_keys = ("t_sql", "t_io", "t_spec", "t_pack", "t_narrow")
    # fetch accounting: serial mode pays it at the barrier (t_fetch);
    # pipeline mode's fetch WORK is t_fetch_busy and the barrier's
    # t_fetch is residual waiting on that same work — counting both
    # would double-charge the stage
    dev_keys = (
        ("t_upload", "t_dispatch", "t_fetch_busy")
        if pipelined
        else ("t_upload", "t_dispatch", "t_fetch")
    )
    host_s = sum(stats2.get(k, 0.0) for k in host_keys)
    dev_s = sum(stats2.get(k, 0.0) for k in dev_keys)
    wall_cp = stats2.get("wall_critical_path", dt2)
    if pipelined:
        # busy times overlap inside wall_cp, so dt2 - busy would clamp
        # to 0 precisely when the pipeline works; the serial non-stage
        # time (repo ctor, handle build, barrier assembly) is the wall
        # outside the load's critical path
        other_s = max(0.0, dt2 - wall_cp)
    else:
        other_s = max(0.0, dt2 - host_s - dev_s)
    n_proj = 8
    if pipelined:
        # stages overlap: the host-side floor is the single slowest
        # pipelined host stage, reached when every other stage hides
        # behind it. t_sql stays OUTSIDE the max — it runs before the
        # workers start and after they join, so it can never overlap.
        sql_s = stats2.get("t_sql", 0.0)
        host_max = max(
            stats2.get(k, 0.0) for k in host_keys if k != "t_sql"
        )
        proj8 = other_s + sql_s + max(host_max, dev_s / n_proj)
    else:
        proj8 = host_s + other_s + dev_s / n_proj
    stages = {
        k: stats2.get(k, 0.0)
        for k in host_keys + ("t_upload", "t_dispatch", "t_fetch")
    }
    stages["other"] = round(other_s, 3)
    for k, v in stats2.items():
        if k.endswith("_busy"):
            stages[k] = v
    stages["wall_critical_path"] = round(wall_cp, 3)
    stages["pipeline"] = 1 if pipelined else 0
    busy_total = host_s + dev_s
    print(
        f"# stages ({'pipelined busy' if pipelined else 'serial wall'}): "
        f"host {host_s:.2f}s "
        f"({', '.join(f'{k[2:]}={stats2.get(k, 0.0):.2f}' for k in host_keys)}) "
        f"+ device {dev_s:.2f}s "
        f"({', '.join(f'{k[2:]}={stats2.get(k, 0.0):.2f}' for k in dev_keys)}) "
        f"+ other {other_s:.2f}s",
        file=sys.stderr,
    )
    if pipelined:
        overlap = busy_total / wall_cp if wall_cp > 0 else 1.0
        print(
            f"# overlap: wall critical path {wall_cp:.2f}s vs "
            f"{busy_total:.2f}s total stage busy time "
            f"({overlap:.2f}x concurrency)",
            file=sys.stderr,
        )
    print(
        f"# reference projection (superseded by the MEASURED "
        f"config_mesh multichip_8_s below): {n_proj}-chip "
        f"({'overlapped critical path' if pipelined else 'host serial'}, "
        f"device/{n_proj}) = {proj8:.2f}s -> {total_ops/proj8:,.0f} ops/s",
        file=sys.stderr,
    )

    # aux configs are fail-soft: a failure must not cost the driver the
    # primary metric line
    def _soft(name, fn):
        try:
            return fn()
        except Exception as e:  # pragma: no cover - defensive
            print(f"# {name} FAILED: {e}", file=sys.stderr)
            return None

    # -- measured multichip (the projection retirement): the same
    # corpus, cold-opened over a real device mesh --------------------
    cfgmesh = _soft("config_mesh", lambda: _config_mesh(tmp))
    if cfgmesh is not None:
        mc_s, mc_mode, mc_dev, _mc_topo, mc_stats = cfgmesh
        print(
            f"# config_mesh MEASURED multichip cold open: {mc_s:.2f}s "
            f"-> {total_ops / mc_s:,.0f} ops/s on {mc_dev} devices "
            f"({mc_mode}; slabs/chip {mc_stats.get('slabs_per_chip')}, "
            f"dispatch busy/chip {mc_stats.get('t_dispatch_chips')}, "
            f"fetch busy/chip {mc_stats.get('t_fetch_chips')})",
            file=sys.stderr,
        )

    cfg1 = _soft("config1", _config1_change_latency)
    if cfg1 is not None:
        print(f"# config1 change latency: {cfg1:.0f}us", file=sys.stderr)
    cfg2 = _soft("config2", _config2_convergence)
    if cfg2 is not None:
        print(
            f"# config2 2-repo convergence: {cfg2[0]:.2f}s "
            f"({cfg2[1]:,.0f} edits/s replicated+applied)",
            file=sys.stderr,
        )
        if cfg2[2]:
            print(f"# config2 live-apply: {cfg2[2]}", file=sys.stderr)
    cfgch = _soft("config_churn", _config_churn)
    if cfgch is not None:
        print(
            f"# config_churn convergence under kill/heal: "
            f"{cfgch[0]:.2f}s ({cfgch[1]:,.0f} edits/s; "
            f"churn {cfgch[2]})",
            file=sys.stderr,
        )
    cfgsw = _soft("config_swarm", _config_swarm)
    if cfgsw is not None:
        print(
            f"# config_swarm DHT fleet (no explicit connect, seeded "
            f"kill/heal churn): converged in {cfgsw[0]:.2f}s "
            f"({cfgsw[1]['peers']} peers, frame amp "
            f"max {cfgsw[1]['frame_amp_max']}x vs fanout "
            f"{cfgsw[1]['fanout']}, lookup hops "
            f"{cfgsw[1]['lookup_hops_mean']}; {cfgsw[1]})",
            file=sys.stderr,
        )
    cfgfl = _soft("config_fleet1000", _config_fleet1000)
    if cfgfl is not None:
        print(
            f"# config_fleet1000 scaling: {cfgfl[1]['real_peers']}-peer "
            f"async fleet at {cfgfl[1]['threads_per_daemon']} "
            f"threads/daemon; frames/peer/period "
            f"{cfgfl[1]['frames_per_peer_period_100']} @100 vs "
            f"{cfgfl[1]['frames_per_peer_period_1000']} @1000 "
            f"(ratio {cfgfl[1]['frames_flat_ratio']}x, gate <= 2x); "
            f"cold-join p99 {cfgfl[1]['coldjoin_p99_s']}s simulated "
            f"({cfgfl[1]})",
            file=sys.stderr,
        )
    cfgcr = _soft("config_crash", _config_crash)
    if cfgcr is not None:
        print(
            f"# config_crash kill -9 recovery: reopen+readable in "
            f"{cfgcr[0]:.0f}ms, acked_lost={cfgcr[1]['acked_lost']} "
            f"({cfgcr[1]})",
            file=sys.stderr,
        )
    cfg6l = _soft("config6_live", _config6_live_burst)
    if cfg6l is not None:
        st6 = cfg6l[2]
        print(
            f"# config6-live single-doc burst: first edit "
            f"{cfg6l[0]:.0f}ms, burst {cfg6l[1]:,.0f} edits/s "
            f"(live stats {st6})",
            file=sys.stderr,
        )
        print(
            "# config6-live adoption stages (ms): "
            + ", ".join(
                f"{k[8:]}={st6.get(k, 0.0) * 1e3:.1f}"
                for k in (
                    "t_adopt_pack", "t_adopt_kernel", "t_adopt_decode",
                    "t_adopt_reach", "t_adopt_lock_free",
                    "t_adopt_lock_held",
                )
            ),
            file=sys.stderr,
        )
    cfg6d = _soft("config6_demote", _config6_demote_readopt)
    if cfg6d is not None:
        print(
            f"# config6-demote lifecycle: re-adopt edit median "
            f"{cfg6d[0]:.1f}ms (demoted {cfg6d[1].get('demoted', 0)}, "
            f"readopted {cfg6d[1].get('readopted', 0)})",
            file=sys.stderr,
        )
    cfgld = _soft("config_lockdebt", _config_lockdebt)
    if cfgld is not None:
        print(
            f"# config_lockdebt write-plane blocking debt "
            f"(instrumented): live.engine held across blocking calls "
            f"{cfgld['fsync_group'].get('live_engine', 0.0):.1f}ms at "
            f"HM_FSYNC=1, "
            f"{cfgld['fsync_per_append'].get('live_engine', 0.0):.1f}"
            f"ms at HM_FSYNC=2; per class {cfgld}",
            file=sys.stderr,
        )
    cfgwr = _soft("config_writers", _config_writers)
    if cfgwr is not None:
        eps = cfgwr["edits_per_s"]
        print(
            f"# config_writers many-writer plane (IPC procs, disjoint "
            f"docs, HM_FSYNC=1): "
            + ", ".join(f"{k}w {v:,.0f} edits/s" for k, v in eps.items())
            + f" -> {cfgwr['scaling']:.1f}x scaling"
            + (
                f" (8->32 {cfgwr['scaling_8_32']:.1f}x)"
                if "scaling_8_32" in cfgwr
                else ""
            ),
            file=sys.stderr,
        )
    cfghd = _soft("config_writers_hotdoc", _config_writers_hotdoc)
    if cfghd is not None:
        print(
            f"# config_writers_hotdoc {cfghd['n_writers']} writers x "
            f"ONE shared doc (per-connection actors): "
            f"{cfghd['edits_per_s']:,.0f} edits/s, bit-identical "
            f"convergence {cfghd['converged']}",
            file=sys.stderr,
        )
    cfg3 = _soft("config3", _config3_multiactor)
    if cfg3 is not None:
        print(
            f"# config3 1k docs x 3 actors x 512 ops (general pack "
            f"path): {cfg3[0]:.2f}s -> {cfg3[1]:,.0f} ops/s",
            file=sys.stderr,
        )
    cfgco = _soft(
        "config_coldopen", lambda: _config_coldopen(n_docs, n_ops)
    )
    if cfgco is not None:
        print(
            f"# config_coldopen pack-plane gate "
            f"({cfgco['docs']} docs x {cfgco['ops_per_doc']} ops, "
            f"{cfgco['cores']} cores): pooled {cfgco['config_coldopen_s']}s "
            f"(serial {cfgco['config_coldopen_serial_s']}s), "
            f"{cfgco['pack_workers']} workers, lanes "
            f"{cfgco['t_pack_busy_per_worker']} over "
            f"{cfgco['t_pack_wall']}s wall -> "
            f"{cfgco['coldopen_pack_speedup']}x pack speedup, "
            f"pack_bound={cfgco['coldopen_pack_bound']} "
            f"(io {cfgco['t_io_busy']}s, dispatch "
            f"{cfgco['t_dispatch_busy']}s)",
            file=sys.stderr,
        )

    cfgrd = _soft("config_read", lambda: _config_read(tmp, urls))
    if cfgrd is not None:
        print(
            f"# config_read serving tier: {cfgrd[0]:,.0f} reads/s "
            f"(p50 {cfgrd[1]}ms p99 {cfgrd[2]}ms) vs host "
            f"per-request {cfgrd[3]:,.0f} reads/s -> "
            f"{cfgrd[0] / max(cfgrd[3], 1e-9):.1f}x "
            f"(fallbacks {cfgrd[4]['fallbacks_steady']}, "
            f"batches {cfgrd[4]['batches']})",
            file=sys.stderr,
        )
    cfgsvc = _soft("config_service", _config_service)
    if cfgsvc is not None:
        print(
            f"# config_service front door under overload: saturation "
            f"{cfgsvc['saturation_qps']:,.0f} reads/s "
            f"({cfgsvc['clients']} tenants), 2x-saturation storm "
            f"{cfgsvc['storm']['qps']:,.0f} ok reads/s + "
            f"{cfgsvc['storm']['reads_shed']} typed refusals "
            f"(errors {cfgsvc['reads_errors']}), "
            f"{cfgsvc['writes_acked']} durable writes acked "
            f"(lost {cfgsvc['acked_lost']}, paced commits "
            f"{cfgsvc['paced_commits']}), recovery to "
            f"{cfgsvc['slo_ms']:.0f}ms SLO in "
            f"{cfgsvc['recovery_to_slo_s']}s; gates "
            f"{'ALL PASS' if cfgsvc['gated_ok'] else cfgsvc['gates']}",
            file=sys.stderr,
        )
    rtt = _soft("tunnel_rtt", _tunnel_rtt_ms)
    if rtt is not None:
        print(
            f"# device link round-trip floor: {rtt:.0f}ms "
            "(tunneled; ~1ms on direct-attached TPU)",
            file=sys.stderr,
        )
    cfg5 = _soft("config5", _config5_union)
    if cfg5 is not None:
        print(
            f"# config5 100k-doc union (device-resident mirror, 1k "
            f"dirty): {cfg5:.1f}ms"
            + (
                f" (= ONE dispatch; link RTT floor {rtt:.0f}ms)"
                if rtt is not None
                else ""
            ),
            file=sys.stderr,
        )
    cfg6 = _soft("config6", _config6_text_trace)
    if cfg6 is not None:
        print(
            f"# config6 automerge-perf text trace (259,778 ops, 1 doc): "
            f"{cfg6[0]:.2f}s -> {cfg6[1]:,.0f} ops/s "
            f"(reference engine: ~0.4-0.9k ops/s)",
            file=sys.stderr,
        )

    if not bench_dir:
        shutil.rmtree(tmp, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": "cold_open_materialize_ops_per_sec_per_chip",
                "value": round(rate2),
                "unit": "ops/s",
                "vs_baseline": round(rate2 / host_rate, 2),
                "configs": {
                    "cold_open_s_10k_docs": round(dt2, 2),
                    "cold_first_process_s": round(dt1, 2),
                    "config1_change_latency_us": (
                        round(cfg1) if cfg1 is not None else None
                    ),
                    "config2_convergence_s": (
                        round(cfg2[0], 2) if cfg2 is not None else None
                    ),
                    "config2_edits_per_s": (
                        round(cfg2[1]) if cfg2 is not None else None
                    ),
                    "config2_live": (
                        cfg2[2] if cfg2 is not None else None
                    ),
                    "config_churn_s": (
                        round(cfgch[0], 2) if cfgch is not None else None
                    ),
                    "config_churn_edits_per_s": (
                        round(cfgch[1]) if cfgch is not None else None
                    ),
                    "config_churn": (
                        cfgch[2] if cfgch is not None else None
                    ),
                    # DHT fleet: N daemons, discovery-only topology,
                    # seeded churn; frame amplification must stay
                    # O(HM_GOSSIP_FANOUT) regardless of peer count
                    "config_swarm_s": (
                        round(cfgsw[0], 2) if cfgsw is not None else None
                    ),
                    "config_swarm": (
                        cfgsw[1] if cfgsw is not None else None
                    ),
                    # 100->1000 peer scaling: async-transport thread
                    # census (real mini-fleet) + seeded steady-state
                    # period model; frames/peer/period must stay flat
                    "config_fleet1000_s": (
                        cfgfl[0] if cfgfl is not None else None
                    ),
                    "config_fleet1000": (
                        cfgfl[1] if cfgfl is not None else None
                    ),
                    "config_crash_t_recover_ms": (
                        round(cfgcr[0], 1) if cfgcr is not None else None
                    ),
                    "config_crash": (
                        cfgcr[1] if cfgcr is not None else None
                    ),
                    "config6_live_first_edit_ms": (
                        round(cfg6l[0], 1) if cfg6l is not None else None
                    ),
                    "config6_live_burst_edits_per_s": (
                        round(cfg6l[1]) if cfg6l is not None else None
                    ),
                    "config6_live": (
                        cfg6l[2] if cfg6l is not None else None
                    ),
                    "config6_live_adopt_decode_ms": (
                        round(
                            cfg6l[2].get("t_adopt_decode", 0.0) * 1e3, 1
                        )
                        if cfg6l is not None
                        else None
                    ),
                    "config6_demote_readopt_ms": (
                        round(cfg6d[0], 1) if cfg6d is not None else None
                    ),
                    "config6_demote": (
                        cfg6d[1] if cfg6d is not None else None
                    ),
                    # per-lock-class blocking debt (ms) from the
                    # instrumented durable burst; the `live_engine`
                    # entry gates the ROADMAP write-plane split
                    "lock_held_blocking_ms": cfgld,
                    # many-writer plane: N IPC writer processes on
                    # disjoint docs vs ONE hub daemon at HM_FSYNC=1
                    "config_writers_edits_per_s": (
                        cfgwr["edits_per_s"] if cfgwr is not None
                        else None
                    ),
                    "config_writers_scaling": (
                        cfgwr["scaling"] if cfgwr is not None else None
                    ),
                    # group-commit gate: >= 2.5x from 8 to 32 writers
                    "config_writers_scaling_8_32": (
                        cfgwr.get("scaling_8_32")
                        if cfgwr is not None else None
                    ),
                    # 8 writers x ONE shared doc (per-connection
                    # actors); converged == bit-identical final views
                    "config_writers_hotdoc_edits_per_s": (
                        cfghd["edits_per_s"] if cfghd is not None
                        else None
                    ),
                    "config_writers_hotdoc_converged": (
                        cfghd["converged"] if cfghd is not None
                        else None
                    ),
                    "config3_multiactor_ops_per_s": (
                        round(cfg3[1]) if cfg3 is not None else None
                    ),
                    "config5_union_100k_ms": (
                        round(cfg5, 1) if cfg5 is not None else None
                    ),
                    # pack-plane scaling gate (ISSUE 19): 10x corpus,
                    # serial vs pooled pack; the bool is the "cold
                    # opens bounded by slab IO" regression gate
                    "config_coldopen": cfgco,
                    "config_coldopen_s": (
                        cfgco["config_coldopen_s"]
                        if cfgco is not None else None
                    ),
                    "pack_workers": (
                        cfgco["pack_workers"]
                        if cfgco is not None else None
                    ),
                    "t_pack_busy_per_worker": (
                        cfgco["t_pack_busy_per_worker"]
                        if cfgco is not None else None
                    ),
                    "coldopen_pack_speedup": (
                        cfgco["coldopen_pack_speedup"]
                        if cfgco is not None else None
                    ),
                    "coldopen_pack_bound": (
                        cfgco["coldopen_pack_bound"]
                        if cfgco is not None else None
                    ),
                    "config_read_qps": (
                        round(cfgrd[0]) if cfgrd is not None else None
                    ),
                    "config_read_p50_ms": (
                        cfgrd[1] if cfgrd is not None else None
                    ),
                    "config_read_p99_ms": (
                        cfgrd[2] if cfgrd is not None else None
                    ),
                    "config_read_host_qps": (
                        round(cfgrd[3]) if cfgrd is not None else None
                    ),
                    "config_read_speedup": (
                        round(cfgrd[0] / max(cfgrd[3], 1e-9), 1)
                        if cfgrd is not None
                        else None
                    ),
                    "config_read": (
                        cfgrd[4] if cfgrd is not None else None
                    ),
                    "config6_text_trace_ops_per_s": (
                        round(cfg6[1]) if cfg6 is not None else None
                    ),
                    # ISSUE 20: the unified traffic bench — every
                    # plane at once behind the one front door, gated
                    # on shed order / acked_lost=0 / recovery-to-SLO
                    "config_service": cfgsvc,
                    "config_service_qps": (
                        round(cfgsvc["saturation_qps"])
                        if cfgsvc is not None else None
                    ),
                    "config_service_p50_ms": (
                        cfgsvc["steady"]["read_p50_ms"]
                        if cfgsvc is not None else None
                    ),
                    "config_service_p99_ms": (
                        cfgsvc["steady"]["read_p99_ms"]
                        if cfgsvc is not None else None
                    ),
                    "config_service_recovery_s": (
                        cfgsvc["recovery_to_slo_s"]
                        if cfgsvc is not None else None
                    ),
                    "config_service_gated_ok": (
                        cfgsvc["gated_ok"]
                        if cfgsvc is not None else None
                    ),
                    "device_link_rtt_ms": (
                        round(rtt, 1) if rtt is not None else None
                    ),
                    "docs": n_docs,
                    "ops_per_doc": n_ops,
                    "stages": stages,
                    "host_serial_s": round(host_s + other_s, 2),
                    "device_s": round(dev_s, 2),
                    "pipeline": 1 if pipelined else 0,
                    "wall_critical_path_s": round(wall_cp, 2),
                    # MEASURED multi-chip cold open (config_mesh): a
                    # real overlapped run over the mesh scheduler —
                    # this retires the projection formula below
                    "multichip_8_s": (
                        cfgmesh[0] if cfgmesh is not None else None
                    ),
                    "multichip_mode": (
                        cfgmesh[1] if cfgmesh is not None else None
                    ),
                    "multichip_devices": (
                        cfgmesh[2] if cfgmesh is not None else None
                    ),
                    "multichip_topology": (
                        cfgmesh[3] if cfgmesh is not None else None
                    ),
                    "multichip_stages": (
                        {
                            k: v
                            for k, v in cfgmesh[4].items()
                            if k
                            in (
                                "slabs_per_chip",
                                "t_dispatch_chips",
                                "t_fetch_chips",
                                "rr_slabs",
                                "rr_devices",
                                "wall_critical_path",
                                "t_io_busy",
                                "t_pack_busy",
                                "t_dispatch_busy",
                                "t_fetch_busy",
                            )
                        }
                        if cfgmesh is not None
                        else None
                    ),
                    # REFERENCE ONLY — the old single-chip-stage
                    # divide-by-N estimate, kept for continuity with
                    # BENCH_r05 and earlier; multichip_8_s above is
                    # the measured number
                    "projection_8chip_reference_s": round(proj8, 2),
                },
                # round-13 observability: the process-wide registry
                # snapshot for THIS bench run (every subsystem's
                # counters in one block) + trace state. A NEW key —
                # every pre-existing key above is untouched.
                "telemetry": _telemetry_block(),
            }
        )
    )


def _telemetry_block():
    from hypermerge_tpu import telemetry

    return {
        "counters": telemetry.snapshot(),
        "tracing": telemetry.tracing_enabled(),
        "trace_spans": telemetry.event_count(),
        "trace_file": telemetry.trace_path(),
    }


if __name__ == "__main__":
    main()
