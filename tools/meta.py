"""Print a url's metadata: for a document, its actor list, clock, and
history length; for a hyperfile, its size and mime type (reference
tools/Meta.ts — `repo.meta(url, cb)` surfaced on the command line).

    python tools/meta.py /path/to/repo 'hypermerge:/<docId>'
    python tools/meta.py /path/to/repo 'hyperfile:/<fileId>'
    python tools/meta.py --devices
    python tools/meta.py /path/to/repo --stats
    python tools/meta.py --dht [--bootstrap host:port,host:port]

Output is one JSON object. Documents are opened first (metadata queries
answer from the open doc's backend state); unknown urls print null and
exit non-zero.

`--devices` prints the visible-device/mesh topology instead (no repo
needed): device count, platform/kind, (dp, sp) mesh shape, and whether
the Pallas ICI remote-copy path is live — the same object the bench
embeds as `multichip_topology`, so a bench JSON line is auditable
against the box it ran on.

`--dht` probes a running DHT fleet from outside: boots an EPHEMERAL
node (net/discovery/dht.py), bootstraps it from `--bootstrap` or
`HM_DHT_BOOTSTRAP`, walks toward its own id, and prints the node id
and per-bucket occupancy JSON — "is the fleet reachable and how big
does it look from here" in one command. `nodes` is the routing-table
size after the walk; an empty table means no bootstrap answered.

`--stats` opens the repo (and its docs) and prints the process-wide
telemetry snapshot JSON — the registry every subsystem now reports
into (hypermerge_tpu/telemetry/) instead of the per-object stats
dicts it replaced. Same counter names as bench.py's `telemetry`
block and tools/top.py.
"""

import argparse
import json
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.utils.ids import is_doc_url  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", nargs="?", help="repo directory")
    ap.add_argument(
        "url", nargs="?",
        help="hypermerge:/ doc url or hyperfile:/ url",
    )
    ap.add_argument(
        "--timeout", type=float, default=30.0,
        help="seconds to wait for the doc to come up (default 30)",
    )
    ap.add_argument(
        "--devices", action="store_true",
        help="print visible device / mesh topology JSON and exit",
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="open the repo and print the telemetry registry snapshot",
    )
    ap.add_argument(
        "--dht", action="store_true",
        help="probe the DHT fleet with an ephemeral node and print "
        "node id + bucket occupancy JSON",
    )
    ap.add_argument(
        "--bootstrap", default=None,
        help="host:port[,host:port] DHT bootstrap list for --dht "
        "(default: HM_DHT_BOOTSTRAP)",
    )
    args = ap.parse_args()

    if args.dht:
        from hypermerge_tpu.net.discovery import DhtNode

        bootstrap = None
        if args.bootstrap:
            bootstrap = []
            for part in args.bootstrap.split(","):
                host, _, port = part.strip().rpartition(":")
                bootstrap.append((host, int(port)))
        node = DhtNode(bootstrap=bootstrap)
        try:
            node.bootstrap_now()
            print(json.dumps({
                "node_id": node.id_hex,
                "dht_address": list(node.address),
                "nodes": node.table.size(),
                "buckets": node.table.occupancy(),
                "records": node.records.size(),
            }, sort_keys=True), flush=True)
            sys.exit(0 if node.table.size() else 1)
        finally:
            node.close()
    if args.devices:
        from hypermerge_tpu.parallel.mesh import device_topology

        print(json.dumps(device_topology(), sort_keys=True), flush=True)
        return
    if args.stats:
        if args.repo is None:
            ap.error("--stats requires a repo directory")
        from hypermerge_tpu import telemetry

        payload = telemetry.snapshot_repo(args.repo)
        print(
            json.dumps(payload["counters"], sort_keys=True), flush=True
        )
        return
    if args.repo is None or args.url is None:
        ap.error("repo and url are required (or use --devices)")

    repo = Repo(path=args.repo)
    try:
        if is_doc_url(args.url):
            # metadata answers from the open doc: materialize it first
            try:
                repo.open(args.url).value(timeout=args.timeout)
            except TimeoutError:
                # unknown doc (nothing local, no peer): same contract
                # as an unknown hyperfile — null, non-zero exit
                print("null", flush=True)
                sys.exit(1)
        got = {}
        done = threading.Event()

        def on_meta(payload) -> None:
            got["meta"] = payload
            done.set()

        repo.meta(args.url, on_meta)
        if not done.wait(args.timeout):
            print("timed out waiting for metadata", file=sys.stderr)
            sys.exit(2)
        meta = got["meta"]
        print(json.dumps(meta, default=str, sort_keys=True), flush=True)
        if meta is None:
            sys.exit(1)
    finally:
        repo.close()


if __name__ == "__main__":
    main()
