"""Run the static invariant linter over the tree.

    python tools/lint.py [--json] [--all] [--rule RULE] [--env-table]
                         [paths...]

Checks the concurrency rules the repo used to enforce by comment
(analysis/linter.py): the declared lock hierarchy
(analysis/hierarchy.py), no blocking calls under the emission locks,
the NetworkPeer.try_send churn-safe-send idiom, the HM_* env-var
registry (analysis/envvars.py), the `subsystem.metric` telemetry
naming convention, and factory-made locks (so HM_LOCKDEP=1 runtime
lockdep sees every lock).

Exit status is nonzero when any UNSUPPRESSED violation exists —
tier-1 runs exactly this via tests/test_analysis.py. `--all` also
prints suppressed violations with their justifications; `--env-table`
prints the README markdown table generated from the registry.
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.analysis import envvars, guards, linter  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "paths", nargs="*",
        help="files to lint (default: the whole tree)",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--all", action="store_true",
        help="also show suppressed violations (with justifications)",
    )
    ap.add_argument(
        "--rule", action="append", default=None, metavar="RULE",
        help=f"restrict to rule(s): {', '.join(linter.RULES)}",
    )
    ap.add_argument(
        "--env-table", action="store_true",
        help="print the README HM_* env-var markdown table and exit",
    )
    ap.add_argument(
        "--guards-table", action="store_true",
        help="print the README guard-map markdown table "
             "(analysis/guards.py) and exit",
    )
    args = ap.parse_args()

    if args.env_table:
        print(envvars.markdown_table())
        return 0
    if args.guards_table:
        print(guards.markdown_table())
        return 0

    root = linter.repo_root()
    if args.paths:
        viols = linter.lint_files(
            [str(Path(p).resolve()) for p in args.paths], root
        )
    else:
        viols = linter.lint_repo(root)
    if args.rule:
        viols = [v for v in viols if v.rule in args.rule]
    open_viols = linter.unsuppressed(viols)
    shown = viols if args.all else open_viols

    if args.json:
        print(
            json.dumps(
                {
                    "violations": [v._asdict() for v in shown],
                    "n_unsuppressed": len(open_viols),
                    "n_suppressed": len(viols) - len(open_viols),
                },
                indent=2,
            )
        )
    else:
        for v in sorted(shown, key=lambda v: (v.path, v.line)):
            print(v.format())
            if v.suppressed and v.justification:
                print(f"    justification: {v.justification}")
        n_sup = len(viols) - len(open_viols)
        print(
            f"{len(open_viols)} violation(s), {n_sup} suppressed"
            + ("" if args.all or not n_sup else " (--all to show)")
        )
    return 1 if open_viols else 0


if __name__ == "__main__":
    sys.exit(main())
