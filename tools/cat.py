"""Print a doc's metadata + state, or dump a hyperfile's bytes to
stdout (reference tools/Cat.ts + tools/Meta.ts).

    python tools/cat.py /path/to/repo 'hypermerge:/<docId>'
    python tools/cat.py /path/to/repo 'hyperfile:/<fileId>' > out.bin
"""

import argparse
import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.models.plain import to_plain  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.utils.ids import is_file_url  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument("url", help="doc or hyperfile url")
    args = ap.parse_args()

    repo = Repo(path=args.repo)
    if is_file_url(args.url):
        repo.start_file_server(tempfile.mktemp(suffix=".sock"))
        header, data = repo.files.read(args.url)
        print(
            f"# {header.mime_type}  {header.size} bytes",
            file=sys.stderr,
        )
        sys.stdout.buffer.write(data)
    else:
        meta = {}
        repo.meta(args.url, lambda m: meta.update(m or {}))
        print("META", json.dumps(meta, default=str), file=sys.stderr)
        print(json.dumps(to_plain(repo.doc(args.url)), default=str))
    repo.close()


if __name__ == "__main__":
    main()
