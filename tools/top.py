"""Live per-subsystem telemetry for a running hypermerge daemon.

Polls the backend's ``Telemetry`` query over the IPC/serve seam
(net/ipc.py unix socket) and renders per-subsystem counter RATES — the
"what is this daemon doing right now" view ISSUE 9 asked for: live
ticks/s, replication frames/s, TCP bytes/s, fsync barriers/s, mesh
dispatches/s, pipeline queue depths — and, since ISSUE 11, the
read-serving tier's serve.* block: reads/s, batched dispatches/s,
residency hit/install/eviction rates, fallbacks/s (the [serve] group;
`python tools/serve.py --ipc <sock>` exposes the same socket). Since
ISSUE 14 the ``[wal]`` group renders the group-commit journal's
``storage.wal.*`` rates — appends/s vs fsyncs/s (the O(1)-fsync-per-
window claim as a live ratio), checkpoints/s, journal bytes/s, and
replayed blocks (recovery).

Since ISSUE 15 DHT-discovered daemons (net/discovery/) show the
``[dht]`` group — announce/lookup/RPC rates plus ``dht.lookup_hops``
(hops/lookup = ``lookup_hops`` rate over ``lookups`` rate) and
``dht.stale_evictions`` (k-bucket liveness churn) — and the
``[gossip]`` group: ``gossip.sent`` vs ``gossip.suppressed`` is the
bounded-fanout claim as a live ratio (suppressed counts the peers the
``HM_GOSSIP_FANOUT`` cap skipped per broadcast; anti-entropy sweeps
never appear here because they are deliberately unsampled).

ISSUE 16's sharded write plane (``--hub`` + ``HM_WORKERS=N``) adds the
``[workers]`` fleet table: one row per worker PROCESS with pid,
liveness, durable-edit rate (``storage.wal.appends`` per worker),
outbound queue depth, and supervisor respawn count — the same split
the merged payload mirrors into ``workers.<i>.*`` counters for the
Prometheus dump.

This ISSUE's async transport (``HM_NET_ASYNC=1``) folds into the
``[net]`` group: ``net.aio.conns`` (live multiplexed-connection
gauge), ``net.aio.loop_busy_ms`` (cumulative non-idle loop-thread
time — its rate over wall time is the loop saturation ratio the
1000-peer bench watches), frame/byte/ping rates, and
``net.aio.sheds``. The O(1) steady-state gossip counters land next
to them: ``net.cursor.full_tx`` vs ``net.cursor.delta_tx`` vs
``net.cursor.suppressed`` (the delta-cursor win as a live ratio),
plus ``dht.sign_cache_hits`` and ``dht.seeds_tx``/``dht.seeds_rx``
(announce-signing amortization and push-seeding) in ``[dht]``.

ISSUE 20's service plane (``HM_SERVICE``, serve/overload.py) renders
the ``[service]`` group from the payload's ``service`` report block:
the brownout ladder's live rung + pressure + ack-pacing stretch on
one line, the controller's counter rates (``service.shed_reads``/s is
the refusal rate, ``service.brownout_reads``/s the host-memo
degradation rate, ``service.transitions`` the ladder's movement), and
one row per quota tenant — admitted/refused totals plus current
token-bucket occupancy (1.0 = exhausted).

Instrumented daemons (HM_LOCKDEP=1 / HM_RACEDEP=1) additionally show
the ``[lock]`` group: ``lock.held_blocking_ms.<class>`` rates — the
per-lock-class blocking-debt series whose ``live_engine`` row is the
write-plane split gate (ms of blocking calls under that lock, per
second) — and ``lock.racedep_violations``, the lockset race detector's
finding counter (any nonzero rate means a guard-manifest violation was
just observed; pull the daemon's lockdep report for the stacks).

    # against a daemon (python -m hypermerge_tpu.net.ipc repo sock --persist)
    python tools/top.py --sock /tmp/backend.sock [--interval 1.0]

    # one shot, machine-readable
    python tools/top.py --sock /tmp/backend.sock --once --json

    # one in-process snapshot of a repo on disk (no daemon needed)
    python tools/top.py /path/to/repo --once [--prom]

Counter names are ``<subsystem>.<metric>`` (see
hypermerge_tpu/telemetry/__init__.py); the left column groups by the
prefix. Rates are exact deltas between polls of the merged per-thread
shards — no sampling.
"""

import argparse
import json
import socket
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


class IpcTelemetry:
    """A minimal Telemetry-query client on the backend's unix socket —
    the same framed duplex a RepoFrontend uses, without needing one
    (top must not open docs or mutate frontend state)."""

    def __init__(self, sock_path: str) -> None:
        from hypermerge_tpu.net.tcp import TcpDuplex

        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(sock_path)
        self._duplex = TcpDuplex(sock, is_client=True)
        if self._duplex.closed:
            raise ConnectionError(
                f"handshake with backend at {sock_path} failed"
            )
        self._lock = threading.Lock()
        self._next_qid = 0
        self._waiting = {}
        self._duplex.on_message(self._on_msg)

    def _on_msg(self, msg) -> None:
        if not isinstance(msg, dict) or msg.get("type") != "Reply":
            return  # patches/gossip from the live daemon: not ours
        with self._lock:
            slot = self._waiting.pop(msg.get("queryId"), None)
        if slot is not None:
            slot["payload"] = msg.get("payload")
            slot["event"].set()

    def poll(self, timeout: float = 10.0) -> dict:
        from hypermerge_tpu import msgs

        slot = {"event": threading.Event(), "payload": None}
        with self._lock:
            qid = self._next_qid
            self._next_qid += 1
            self._waiting[qid] = slot
        self._duplex.send(msgs.query_msg(qid, msgs.telemetry_query()))
        if not slot["event"].wait(timeout):
            with self._lock:  # retries must not leak a slot per miss
                self._waiting.pop(qid, None)
            raise TimeoutError("telemetry query timed out")
        payload = slot["payload"]
        if not isinstance(payload, dict):
            raise RuntimeError(
                "backend does not answer Telemetry queries "
                "(pre-round-13 daemon?)"
            )
        return payload

    def close(self) -> None:
        self._duplex.close()


def format_rows(prev: dict, cur: dict, dt: float) -> str:
    """The rendered table: counters grouped by subsystem prefix, with
    per-second deltas against the previous poll (blank on the first)."""
    counters = cur.get("counters", {})
    prev_counters = (prev or {}).get("counters", {})
    workers = cur.get("workers") or {}
    svc = cur.get("service") or {}
    by_sub = {}
    for name, v in counters.items():
        sub = name.split(".", 1)[0]
        if name.startswith("storage.wal."):
            # the group-commit journal gets its own rate group: one
            # glance shows appends vs fsyncs (the O(1)-per-window
            # claim as a live ratio) plus checkpoint/byte flow
            sub = "wal"
        if workers and name.startswith("workers."):
            continue  # rendered as the [workers] fleet table below
        if svc and name.startswith("service."):
            continue  # rendered as the [service] group below
        by_sub.setdefault(sub, []).append((name, v))
    lines = []
    for sub in sorted(by_sub):
        rows = [
            (n, v, v - prev_counters.get(n, 0))
            for n, v in sorted(by_sub[sub])
        ]
        if not any(v or d for _n, v, d in rows):
            continue  # a fully idle subsystem earns no screen space
        lines.append(f"[{sub}]")
        for name, v, delta in rows:
            if not v and not delta:
                continue
            rate = ""
            if prev and dt > 0 and delta:
                # signed: a draining queue gauge shows a negative rate
                rate = f"  ({delta / dt:+,.1f}/s)"
            if isinstance(v, float):
                v = round(v, 3)
            lines.append(f"  {name:<32} {v:>14,}{rate}")
    if workers:
        # the sharded write plane (HM_WORKERS daemons): one row per
        # worker process — liveness, durable-edit rate, outbound queue
        # depth, and how often the supervisor had to respawn it
        lines.append("[workers]")
        for i in sorted(workers, key=int):
            w = workers[i]
            delta = w.get("edits", 0) - prev_counters.get(
                f"workers.{i}.edits", 0
            )
            rate = ""
            if prev and dt > 0 and delta:
                rate = f"  ({delta / dt:+,.1f}/s)"
            state = "up" if w.get("alive") else "DOWN"
            lines.append(
                f"  worker {i}  pid {w.get('pid')}  {state:<4} "
                f"edits {w.get('edits', 0):>10,}{rate}  "
                f"queue {w.get('queue', 0):,}  "
                f"respawns {w.get('respawns', 0):,}"
            )
    if svc:
        # the overload controller (serve/overload.py): ladder rung +
        # live pressure + write ack-pacing on one line, refusal/
        # degradation rates below, then the per-tenant quota table
        lines.append("[service]")
        lines.append(
            f"  state {svc.get('state_name', '?'):<9} "
            f"pressure {float(svc.get('pressure', 0.0)):.2f}  "
            f"ack_stretch {svc.get('ack_stretch_ms', 0)}ms  "
            f"transitions {svc.get('transitions', 0):,}"
        )
        skip = ("service.state", "service.pressure",
                "service.ack_stretch_ms")
        for name in sorted(
            n for n in counters
            if n.startswith("service.") and n not in skip
        ):
            v = counters[name]
            delta = v - prev_counters.get(name, 0)
            if not v and not delta:
                continue
            rate = ""
            if prev and dt > 0 and delta:
                rate = f"  ({delta / dt:+,.1f}/s)"
            if isinstance(v, float):
                v = round(v, 3)
            lines.append(f"  {name:<32} {v:>14,}{rate}")
        for t, row in sorted((svc.get("tenants") or {}).items()):
            lines.append(
                f"  tenant {t:<14} "
                f"admitted {row.get('admitted', 0):>10,}  "
                f"refused {row.get('refused', 0):>10,}  "
                f"quota {float(row.get('quota_occupancy', 0.0)):.2f}"
            )
    if cur.get("tracing"):
        lines.append(
            f"[trace] {cur.get('trace_spans', 0)} spans buffered"
            + (
                f" -> {cur['trace_path']}"
                if cur.get("trace_path")
                else " (in-memory ring)"
            )
        )
    return "\n".join(lines)


def _in_process_payload(repo_path: str) -> dict:
    """Open the repo in-process and snapshot its registry (no daemon:
    the numbers describe THIS process' open, not a running server).
    Shares the exact recipe with tools/meta.py --stats."""
    from hypermerge_tpu import telemetry

    return telemetry.snapshot_repo(repo_path)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", nargs="?", help="repo directory (in-process)")
    ap.add_argument("--sock", help="daemon unix socket (net/ipc.py)")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument(
        "--once", action="store_true", help="print one snapshot and exit"
    )
    ap.add_argument("--json", action="store_true", help="raw JSON payload")
    ap.add_argument(
        "--prom", action="store_true",
        help="Prometheus text snapshot (in-process mode only)",
    )
    ap.add_argument(
        "--no-clear", action="store_true",
        help="append instead of redrawing the screen",
    )
    args = ap.parse_args()
    if not args.sock and not args.repo:
        ap.error("need --sock SOCKPATH or a repo directory")

    if args.sock is None:
        payload = _in_process_payload(args.repo)
        if args.prom:
            from hypermerge_tpu import telemetry

            print(telemetry.prometheus_text(), end="")
        elif args.json:
            print(json.dumps(payload, sort_keys=True))
        else:
            print(format_rows({}, payload, 0.0))
        return
    if args.prom:
        ap.error("--prom needs in-process mode (repo directory)")

    client = IpcTelemetry(args.sock)
    try:
        prev = {}
        while True:
            try:
                cur = client.poll()
            except TimeoutError:
                # backend busy (bulk cold open, big tick): skip the
                # frame, keep watching
                print("… backend busy, retrying", file=sys.stderr)
                if args.once:
                    sys.exit(2)
                time.sleep(args.interval)
                continue
            if args.json:
                print(json.dumps(cur, sort_keys=True), flush=True)
            else:
                dt = cur.get("time", 0) - prev.get("time", 0)
                if not args.no_clear and prev:
                    print("\x1b[2J\x1b[H", end="")
                print(
                    f"hm top — {args.sock} — "
                    + time.strftime("%H:%M:%S"),
                )
                print(format_rows(prev, cur, dt), flush=True)
            if args.once:
                return
            prev = cur
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        client.close()


if __name__ == "__main__":
    main()
