"""Audit + repair a repo directory after a crash (or on suspicion).

    python tools/scrub.py /path/to/repo [--dry-run] [--audit] [--json]

Drives the whole-repo recovery pass (storage/scrub.py recover_repo):
feed torn-tail truncation, signature-chain repair (torn fragments;
records claiming blocks the log lost), sealing writable feeds'
crash-orphaned unsigned tails, truncating read-only feeds'
unverifiable tails (they re-replicate from peers), columnar-sidecar
reset when a sidecar ran ahead of its block log, corpus-slab
repair-forward, and sqlite clock reconciliation against feed reality.

The same pass runs automatically when a repo whose previous session
crashed (the repo.dirty marker) is reopened; this CLI exists to run it
on demand, to preview it (--dry-run), and to add the full merkle
re-hash (--audit) that open-time recovery skips for speed.
"""

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.backend.repo_backend import RepoBackend  # noqa: E402
from hypermerge_tpu.storage.integrity import AUDIT_OK  # noqa: E402
from hypermerge_tpu.storage.scrub import recover_repo  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument(
        "--dry-run", action="store_true",
        help="report what a repair would do without writing anything",
    )
    ap.add_argument(
        "--audit", action="store_true",
        help="additionally re-hash every feed against its signed "
        "merkle chain (O(bytes); open-time recovery skips this)",
    )
    ap.add_argument(
        "--json", action="store_true", help="print the report as JSON"
    )
    args = ap.parse_args()

    if not os.path.isdir(args.repo):
        print(f"no such repo directory: {args.repo}", file=sys.stderr)
        raise SystemExit(2)

    # HM_RECOVER=0: the backend must not run its own recovery pass
    # first — this CLI is the driver (and --dry-run must see the
    # damage, not the already-repaired state)
    os.environ["HM_RECOVER"] = "0"
    # a dry run must not eat the crash marker: closing the backend
    # below marks the repo clean, which would skip the automatic
    # recovery on the next real open. Its CONTENT (the crashed
    # session's generation stamp, which bounds the recovery scan to
    # the journal's dirty ledger) must survive byte-for-byte too.
    marker = os.path.join(args.repo, "repo.dirty")
    was_dirty = os.path.exists(marker)
    marker_bytes = b""
    if was_dirty:
        with open(marker, "rb") as fh:
            marker_bytes = fh.read()
    back = RepoBackend(path=args.repo)
    try:
        report = recover_repo(back, repair=not args.dry_run)
        if args.audit:
            audits = {}
            for name in sorted(
                set(back.feed_info.all_public_ids())
                | {r for r in report.get("per_feed", ())}
            ):
                feed = back.feeds.open_feed(name)
                audits[name] = feed.audit_status()
            report["audit"] = {
                "feeds": len(audits),
                "not_ok": {
                    n: s for n, s in audits.items() if s != AUDIT_OK
                },
            }
        if args.json:
            print(json.dumps(report))
        else:
            verb = "would repair" if args.dry_run else "repaired"
            print(
                f"scrub {args.repo}: {report['feeds']} feed(s), "
                f"{verb}: "
                f"{report['bytes_truncated']}B torn feed tails, "
                f"{report['sig_records_dropped']} orphaned sig "
                f"record(s), "
                f"{report['unsigned_tails_sealed']} tail(s) sealed, "
                f"{report['tail_blocks_dropped']} unverifiable "
                f"block(s) dropped, "
                f"{report['colcache_reset']} sidecar(s) reset, "
                f"{report['clock_rows_clamped']} clock row(s) "
                f"clamped "
                f"({report['t_recover_ms']}ms)"
            )
            wal = report.get("wal") or {}
            if wal.get("present"):
                replayed = wal.get(
                    "replay_would" if args.dry_run else "replayed", 0
                )
                rverb = "would replay" if args.dry_run else "replayed"
                print(
                    f"  journal: {wal['records']} record(s) over "
                    f"{wal['dirty_feeds']} dirty feed(s), {rverb} "
                    f"{replayed} block(s), "
                    f"{wal.get('skipped', 0)} already in the logs, "
                    f"{wal['torn_bytes']}B torn tail"
                    + (
                        f"; scan bounded to the session ledger "
                        f"({report.get('feeds_skipped', 0)} sidecar(s) "
                        "skipped)"
                        if wal.get("bounded")
                        else "; stamp mismatch: full scan"
                    )
                )
            for name, entry in sorted(
                report.get("per_feed", {}).items()
            ):
                print(f"  {name[:12]}…  {entry}")
            if args.audit and report["audit"]["not_ok"]:
                for n, s in sorted(report["audit"]["not_ok"].items()):
                    print(f"  AUDIT {n[:12]}…  {s}")
            elif args.audit:
                print(
                    f"  audit: all {report['audit']['feeds']} "
                    "feed(s) verify"
                )
    finally:
        back.close()
        if args.dry_run and was_dirty:
            with open(marker, "wb") as fh:
                fh.write(marker_bytes)


if __name__ == "__main__":
    main()
