"""Watch a document: print its materialized state as JSON on every
change (reference tools/Watch.ts:31-34).

    python tools/watch.py /path/to/repo 'hypermerge:/<docId>'
    python tools/watch.py /path/to/repo 'hypermerge:/<docId>' \
        --connect HOST:PORT        # also join a peer and watch live
"""

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.models.plain import to_plain as _plain  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument("url", help="doc url to watch")
    ap.add_argument("--connect", help="HOST:PORT of a peer to join")
    ap.add_argument(
        "--once", action="store_true", help="print current state and exit"
    )
    args = ap.parse_args()

    repo = Repo(path=args.repo)
    if args.connect:
        from hypermerge_tpu.net.tcp import TcpSwarm

        swarm = TcpSwarm()
        repo.set_swarm(swarm)
        host, _, port = args.connect.partition(":")
        swarm.connect((host, int(port)))

    def show(doc, index):
        print(
            json.dumps(
                {"history": index, "doc": _plain(doc)}, default=str
            ),
            flush=True,
        )

    if args.once:
        show(repo.doc(args.url), -1)
        repo.close()
        return
    repo.watch(args.url, show)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        repo.close()


if __name__ == "__main__":
    main()
