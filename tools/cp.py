"""Copy a local file into the repo as a hyperfile (prints its url), or
a hyperfile back out to disk (reference tools/Cp.ts).

    python tools/cp.py /path/to/repo ./photo.png            # -> url
    python tools/cp.py /path/to/repo 'hyperfile:/<id>' out.png
"""

import argparse
import io
import mimetypes
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.utils.ids import is_file_url  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument("src", help="local file, or a hyperfile url")
    ap.add_argument("dst", nargs="?", help="output path (url src only)")
    args = ap.parse_args()

    repo = Repo(path=args.repo)
    repo.start_file_server(tempfile.mktemp(suffix=".sock"))
    if is_file_url(args.src):
        header, data = repo.files.read(args.src)
        out = args.dst or "out.bin"
        with open(out, "wb") as fh:
            fh.write(data)
        print(f"{header.size} bytes ({header.mime_type}) -> {out}")
    else:
        mime = (
            mimetypes.guess_type(args.src)[0]
            or "application/octet-stream"
        )
        with open(args.src, "rb") as fh:
            header = repo.files.write(io.BytesIO(fh.read()), mime)
        print(header.url)
    repo.close()


if __name__ == "__main__":
    main()
