"""List every document in a repo directory: url, actor count, clock
total, feed bytes on disk, read-serving residency, and per-doc
crash/scrub status. (Reference tools/* ship six ts-node scripts; this
is the inventory one.)

    python tools/ls.py /path/to/repo [--audit] [--sock /tmp/serve.sock]

The `residency=` column comes from the backend's Telemetry query (the
serve block tools/top.py also sees): `resident(<bytes>B)` — the doc's
summary columns are pinned in device memory and reads batch through
the query kernels; `evicted` — it was resident until the
HM_SERVE_MAX_BYTES LRU shed it (the next read reinstalls); `host` —
reads take per-request host materialization (tier off or never read).
Without --sock the column describes THIS in-process open — a fresh
open has served no reads, so everything shows `host`. Point --sock at
a RUNNING daemon's query socket (`tools/serve.py --ipc <sock>` or
`net/ipc.py`) to list the residency the daemon is actually serving
from.

The `peers=`/`announce=` columns (shown when the backend has a swarm)
come from the Telemetry payload's `net` block: how many connected
peers replicate each doc right now, and whether the doc's feeds are
joined for discovery (announced/looked-up). Against a DHT-discovered
daemon (net/discovery/ DhtSwarm) a `dht:` header line adds the node
id, routing-table size, and stored announce-record count — the same
block `tools/meta.py --dht` probes from outside.

The `workers=` column (shown when --sock points at a sharded hub
daemon, `net/ipc.py --hub` + `HM_WORKERS=N`) names the worker process
that OWNS each doc's shard as `workers=<shard>/<N>` — every Change for
the doc routes through that worker's engine and WAL — and a `workers:`
header line summarizes the fleet from the Telemetry payload: how many
workers are up, their summed durable edits, and supervisor respawns.
A sharded daemon's docs live in per-worker shard repos
(`<repo>/shard-<k>`); ls walks those too, one `shard-k  N docs`
section each.

The `service:` header line (shown when the backend runs the overload
controller, HM_SERVICE=1, serve/overload.py) is the service plane at
a glance: brownout-ladder rung (healthy/brownout/shed), live pressure,
refusal and host-degradation totals, and how many quota tenants the
front door has seen — the same `service` block tools/top.py renders
as the [service] group.

The `scrub=` column surfaces crash damage without a full scrub
(storage/scrub.py doc_status): `ok`, `recovered` (the last crash
recovery repaired something for this doc's feeds — torn tails,
sidecar resets, seals), `truncated-N-blocks` (recovery dropped N
unverifiable blocks; they re-replicate from peers), or
`unsigned_tail` (blocks currently beyond the last signature record).

The `wal=` column is the group-commit journal's per-doc verdict from
the persisted scrub report (storage/scrub.py wal_status): `replayed`
(the last recovery re-appended journaled blocks into this doc's feeds
— a power cut had dropped unfsynced log pages), `checkpointed` (the
crashed session touched this doc but its blocks were already durable
in the logs), or `clean` (untouched by the crashed session, or no
journal ran).

--audit additionally re-hashes each feed against its signed merkle
records (storage/integrity.py) and flags tampering. A writable feed
whose process crashed between an append and the periodic signature
(lazy signing, HM_SIGN_INTERVAL) shows the distinct UNSIGNED-TAIL
status instead of TAMPERED: the signed prefix verifies and the tail is
locally authored — recoverable by sealing (any open of the repo that
appends, or `Feed.seal()`, signs a fresh head record; the next audit
is clean). TAMPERED is reserved for evidence that cannot be explained
by a crash: hash/signature mismatches, records covering blocks the log
lost, or uncovered blocks on a read-only feed.
"""

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.storage.integrity import (  # noqa: E402
    AUDIT_TAMPERED,
    AUDIT_UNSIGNED_TAIL,
)
from hypermerge_tpu.storage.scrub import (  # noqa: E402
    doc_status,
    last_report,
    wal_status,
)
from hypermerge_tpu.utils.ids import to_doc_url  # noqa: E402


def _feed_bytes(path: str, actor_id: str) -> int:
    d = os.path.join(path, "feeds", actor_id[:2])
    total = 0
    if os.path.isdir(d):
        for name in os.listdir(d):
            if name.startswith(actor_id):
                p = os.path.join(d, name)
                if os.path.isfile(p):
                    total += os.path.getsize(p)
                elif os.path.isdir(p):
                    for f in os.listdir(p):
                        total += os.path.getsize(os.path.join(p, f))
    return total


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument(
        "--audit", action="store_true",
        help="verify each feed's signed merkle chain",
    )
    ap.add_argument(
        "--sock", default=None,
        help="query a running daemon's Telemetry socket for the LIVE "
        "residency column (tools/serve.py --ipc / net/ipc.py)",
    )
    args = ap.parse_args()

    repo = Repo(path=args.repo)
    back = repo.back
    doc_ids = back.clocks.all_doc_ids(back.id)
    report = last_report(args.repo)
    recovered = back.recovery_report is not None
    print(
        f"repo {back.id[:8]}…  {len(doc_ids)} docs"
        + ("  (crash recovery ran on this open)" if recovered else "")
    )
    # telemetry summary for THIS open (registry-sourced — the
    # per-object stats dicts this used to require are gone):
    # what opening the repo cost in recoveries/fsyncs so far
    from hypermerge_tpu import telemetry

    snap = telemetry.snapshot()
    tele_keys = (
        "storage.recoveries", "storage.fsyncs", "storage.barriers",
        "pipeline.slabs", "mesh.dispatches", "live.adopted",
        "serve.reads", "serve.fallbacks",
    )
    tele = " ".join(
        f"{k.split('.', 1)[1]}={snap[k]}"
        for k in tele_keys
        if snap.get(k)
    )
    if tele:
        print(f"telemetry: {tele}")
    # per-doc read-serving residency, sourced from the Telemetry query
    # (the same payload tools/top.py polls): --sock asks the RUNNING
    # daemon which docs it serves from HBM; otherwise the column
    # describes this in-process open (cold => host everywhere)
    if args.sock:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "hm_top", str(Path(__file__).resolve().parent / "top.py")
        )
        top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(top)
        client = top.IpcTelemetry(args.sock)
        try:
            payload = client.poll()
        finally:
            client.close()
    else:
        tq = []
        repo.telemetry(tq.append)
        payload = (tq[0] or {}) if tq else {}
    serve = payload.get("serve")
    net = (payload.get("net") or {}).get("docs", {})
    dht = payload.get("dht")
    svc = payload.get("service")
    if svc is not None:
        # service plane (serve/overload.py): one status line — ladder
        # rung, live pressure, refusal/degradation totals, tenant
        # count — from the same Telemetry payload tools/top.py polls
        print(
            f"service: {svc.get('state_name', '?')} "
            f"pressure={float(svc.get('pressure', 0.0)):.2f} "
            f"shed={svc.get('shed_reads', 0)} "
            f"brownout={svc.get('brownout_reads', 0)} "
            f"deferred={svc.get('deferred_installs', 0)} "
            f"tenants={len(svc.get('tenants') or {})}"
        )
    if dht is not None:
        # DHT-discovered daemon: one header line of swarm truth (the
        # per-doc peers=/announce= columns below come from the same
        # payload)
        print(
            f"dht: node {dht['node_id'][:12]}… "
            f"nodes={dht['nodes']} records={dht['records']} "
            f"joined={len(dht['joined'])}"
        )

    workers = payload.get("workers") or {}
    if workers:
        # sharded hub daemon: one fleet summary line; the per-doc
        # workers= column below names each doc's owning shard
        up = sum(1 for w in workers.values() if w.get("alive"))
        print(
            f"workers: {up}/{len(workers)} up "
            f"edits={sum(w.get('edits', 0) for w in workers.values())} "
            f"respawns="
            f"{sum(w.get('respawns', 0) for w in workers.values())}"
        )

    def swarm_cols(doc_id):
        ent = net.get(doc_id)
        if ent is None:
            return ""
        ann = "yes" if ent.get("announced") else "no"
        return f"peers={ent.get('peers', 0)} announce={ann} "

    def worker_col(doc_id):
        if not workers:
            return ""
        from hypermerge_tpu.net.ipc import _shard_of

        return f"workers={_shard_of(doc_id, len(workers))}/{len(workers)} "

    def residency(doc_id):
        if serve is None:
            return "host"
        ent = serve["resident"].get(doc_id)
        if ent is not None:
            return f"resident({ent['bytes']}B)"
        if doc_id in serve["evicted"]:
            return "evicted"
        return "host"

    def list_docs(b, path, rep):
        for doc_id in b.clocks.all_doc_ids(b.id):
            cursor = b.cursors.get(b.id, doc_id)
            clock = b.clocks.get(b.id, doc_id)
            total_changes = sum(clock.values())
            nbytes = sum(_feed_bytes(path, a) for a in cursor)
            line = (
                f"{to_doc_url(doc_id)}  actors={len(cursor)} "
                f"changes={total_changes} bytes={nbytes} "
                f"{swarm_cols(doc_id)}"
                f"{worker_col(doc_id)}"
                f"residency={residency(doc_id)} "
                f"scrub={doc_status(b, doc_id, rep)} "
                f"wal={wal_status(rep, cursor)}"
            )
            if args.audit:
                # three-way status: OK / UNSIGNED-TAIL (crash-orphaned
                # lazy-signing tail, recoverable via seal()) / TAMPERED
                statuses = {
                    b.feeds.open_feed(a).audit_status() for a in cursor
                }
                if AUDIT_TAMPERED in statuses:
                    line += "  integrity=TAMPERED"
                elif AUDIT_UNSIGNED_TAIL in statuses:
                    line += (
                        "  integrity=UNSIGNED-TAIL (seal() to re-sign)"
                    )
                else:
                    line += "  integrity=OK"
            print(line)

    list_docs(back, args.repo, report)
    repo.close()
    # a sharded hub daemon's docs live in per-worker shard repos
    # (<repo>/shard-<k>, net/ipc.py _ShardRouter) — the top-level dir
    # holds no feeds of its own, so list each shard's inventory too
    for name in sorted(os.listdir(args.repo)):
        spath = os.path.join(args.repo, name)
        if not (name.startswith("shard-") and os.path.isdir(spath)):
            continue
        srepo = Repo(path=spath)
        sids = srepo.back.clocks.all_doc_ids(srepo.back.id)
        print(f"{name}  {len(sids)} docs")
        list_docs(srepo.back, spath, last_report(spath))
        srepo.close()


if __name__ == "__main__":
    main()
