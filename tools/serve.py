"""Serve a repo to the network: listen on TCP, replicate every feed to
any peer that proves knowledge of the docs (reference tools/Serve.ts —
with encrypted transport and capability checks instead of an open
relay).

    python tools/serve.py /path/to/repo [--port 9130] \
        [--open 'hypermerge:/<docId>' ...]

Peers connect with TcpSwarm.connect((host, port)) — e.g. the chat
example's `join`, or tools/watch.py --connect.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu.net.tcp import TcpSwarm  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.utils.ids import to_doc_url  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument("--port", type=int, default=9130)
    ap.add_argument(
        "--open",
        nargs="*",
        default=None,
        help="doc urls to open (default: every doc in the repo)",
    )
    args = ap.parse_args()

    repo = Repo(path=args.repo)
    swarm = TcpSwarm(port=args.port)
    repo.set_swarm(swarm)
    urls = args.open or [
        to_doc_url(d)
        for d in repo.back.clocks.all_doc_ids(repo.back.id)
    ]
    repo.open_many(urls)
    host, port = swarm.address
    print(f"serving {len(urls)} docs on {host}:{port}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        repo.close()
        swarm.destroy()


if __name__ == "__main__":
    main()
