"""Serve a repo to the network: listen on TCP, replicate every feed to
any peer that proves knowledge of the docs (reference tools/Serve.ts —
with encrypted transport and capability checks instead of an open
relay).

    python tools/serve.py /path/to/repo [--port 9130] \
        [--open 'hypermerge:/<docId>' ...] [--ipc /tmp/serve.sock]

Peers connect with TcpSwarm.connect((host, port)) — e.g. the chat
example's `join`, or tools/watch.py --connect.

--ipc additionally listens on a unix socket speaking the framed Query
protocol (msgs.query_msg): `Read` queries route through the HBM
read-serving tier (serve/tier.py, HM_SERVE=1) and `Telemetry` queries
feed tools/top.py — so one daemon replicates to peers AND serves
thousands of concurrent point reads without materializing docs
host-side per request.
"""

import argparse
import os
import socket
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from hypermerge_tpu import msgs  # noqa: E402
from hypermerge_tpu.net.tcp import TcpDuplex, TcpSwarm  # noqa: E402
from hypermerge_tpu.repo import Repo  # noqa: E402
from hypermerge_tpu.utils.ids import to_doc_url  # noqa: E402


class QueryServer:
    """The read/telemetry socket: accepts framed-duplex clients and
    answers Query messages straight off the backend — Read through the
    serving tier (its batcher coalesces concurrent clients into one
    kernel dispatch), Telemetry with the registry snapshot + per-doc
    residency. Everything else on the socket is ignored; doc state
    never mutates through this seam."""

    def __init__(self, backend, sock_path: str) -> None:
        self._back = backend
        if os.path.exists(sock_path):
            os.remove(sock_path)
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(sock_path)
        self._server.listen(8)
        self._closed = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="hm-serve-ipc", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return  # closed
            duplex = TcpDuplex(conn, is_client=False)
            if duplex.closed:
                continue
            duplex.on_message(
                lambda msg, d=duplex: self._on_msg(d, msg)
            )

    def _on_msg(self, duplex, msg) -> None:
        if not isinstance(msg, dict) or msg.get("type") != "Query":
            return
        qid = msg.get("queryId")
        query = msg.get("query") or {}
        t = query.get("type")
        if t == "Read":
            self._back.read_doc(
                query.get("id"),
                query.get("query") or {},
                lambda payload: duplex.send(
                    msgs.reply_msg(qid, payload)
                ),
            )
        elif t == "Telemetry":
            duplex.send(
                msgs.reply_msg(qid, self._back.telemetry_payload())
            )
        else:
            duplex.send(msgs.reply_msg(qid, None))

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("repo", help="repo directory")
    ap.add_argument("--port", type=int, default=9130)
    ap.add_argument(
        "--open",
        nargs="*",
        default=None,
        help="doc urls to open (default: every doc in the repo)",
    )
    ap.add_argument(
        "--ipc",
        default=None,
        help="unix socket answering Read/Telemetry queries "
        "(tools/top.py, read clients)",
    )
    args = ap.parse_args()

    repo = Repo(path=args.repo)
    swarm = TcpSwarm(port=args.port)
    repo.set_swarm(swarm)
    urls = args.open or [
        to_doc_url(d)
        for d in repo.back.clocks.all_doc_ids(repo.back.id)
    ]
    repo.open_many(urls)
    qserver = None
    if args.ipc:
        qserver = QueryServer(repo.back, args.ipc)
        print(f"read queries on {args.ipc}", flush=True)
    host, port = swarm.address
    print(f"serving {len(urls)} docs on {host}:{port}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        if qserver is not None:
            qserver.close()
        repo.close()
        swarm.destroy()


if __name__ == "__main__":
    main()
