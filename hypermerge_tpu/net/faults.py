"""Deterministic fault injection over any Duplex/Swarm transport.

Convergence-under-churn was untestable before this module: the only way
to provoke churn was wall-clock-dependent socket surgery. `FaultPlan`
is a SEEDED schedule — per-frame fates (drop / duplicate / delay) drawn
from private per-direction RNG streams in frame order, plus tick-driven
link events (hard-kill, one-way partition, heal) advanced explicitly by
tests or by a timer in bench/soak runs — so the same seed reproduces
the same frame-level fault schedule on every run.

The wrappers sit at the OBJECT-message layer (above net/secure.py's
per-frame encryption, below net/connection.py's channels): dropping a
frame here models a lossy/partitioned link without desyncing the cipher
nonce counters, exactly the layer the replication protocol must survive
at.

  FaultDuplex  — wraps one side's duplex; every outbound (`tx`) and
                 inbound (`rx`) frame consults the plan.
  FaultSwarm   — wraps a swarm; every emitted connection is wrapped in
                 a FaultDuplex sharing the swarm's plan. While the link
                 is down (kill ... heal window) new connections are
                 killed at emission, so a supervised dialer
                 (net/resilience.py) backs off and retries until heal.

Env activation for bench/soak runs (parsed by `parse_fault_spec`,
applied to every swarm in `Network.set_swarm` when `HM_FAULT` is set):

  HM_FAULT="seed=7,drop=0.01,dup=0.005,delay=2:8,kill@30,heal@50"

Grammar: comma-separated `key=value` knobs (`seed`, `drop`, `dup`,
`delay` in ms as `N` or `MIN:MAX`, `tick` = auto-ticker period in ms,
default 100) and `event@tick` entries (`kill`, `heal`, `partition_tx`,
`partition_rx`). Ticks count from the swarm's construction.
"""

from __future__ import annotations

import random
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.lockdep import make_condition, make_lock
from ..utils.debug import log
from .swarm import ConnectionDetails, Swarm

DELIVER = "deliver"
DROP = "drop"
DUP = "dup"

KILL = "kill"
HEAL = "heal"
PARTITION_TX = "partition_tx"
PARTITION_RX = "partition_rx"
CLEAN = "clean"  # disable drop/dup/delay from this tick on
LOSSY = "lossy"  # re-enable them

_EVENTS = (KILL, HEAL, PARTITION_TX, PARTITION_RX, CLEAN, LOSSY)


class FaultPlan:
    """Seeded frame-fate schedule + tick-driven link events.

    Frame fates consume per-direction RNG streams in frame order, so a
    single-threaded driver reproduces the exact schedule; under real
    concurrency the fate SEQUENCE per direction is still fixed by the
    seed (which message lands on which frame index is the only part
    timing decides). Events fire when `advance()` crosses their tick."""

    def __init__(
        self,
        seed: int = 0,
        drop_p: float = 0.0,
        dup_p: float = 0.0,
        delay_ms: Tuple[float, float] = (0.0, 0.0),
        events: Optional[List[Tuple[int, str]]] = None,
        tick_ms: float = 100.0,
    ) -> None:
        self.seed = seed
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.delay_ms = delay_ms
        self.tick_ms = tick_ms
        # stable sort by tick ONLY: same-tick events fire in the order
        # the plan listed them (heal@4,clean@4 means heal THEN clean)
        self.events = sorted(events or [], key=lambda e: e[0])
        for _t, ev in self.events:
            if ev not in _EVENTS:
                raise ValueError(f"unknown fault event {ev!r}")
        self._tx_rng = random.Random((seed << 1) ^ 0xFA17)
        self._rx_rng = random.Random((seed << 1) | 1)
        self._lock = make_lock("net.fault.plan")
        self.tick = 0
        self._next_event = 0
        # link state (event-driven)
        self.down = False  # kill..heal window: no connection survives
        self.tx_blocked = False
        self.rx_blocked = False
        self.lossy = True  # drop/dup/delay active (CLEAN disables)

    def frame_fate(self, tx: bool) -> Tuple[str, float]:
        """(fate, delay_s) for the next frame in one direction. The RNG
        stream advances even for blocked/clean frames so a partition or
        clean window doesn't shift the rest of the schedule."""
        with self._lock:
            rng = self._tx_rng if tx else self._rx_rng
            r = rng.random()
            lo, hi = self.delay_ms
            delay = (rng.uniform(lo, hi) if hi > 0 else 0.0) / 1e3
            if self.down or (self.tx_blocked if tx else self.rx_blocked):
                return DROP, 0.0
            if not self.lossy:
                return DELIVER, 0.0
            if r < self.drop_p:
                return DROP, 0.0
            if r < self.drop_p + self.dup_p:
                return DUP, delay
            return DELIVER, delay

    def advance(self, n: int = 1) -> List[str]:
        """Advance `n` ticks; returns the events that fired, in order."""
        fired: List[str] = []
        with self._lock:
            for _ in range(n):
                self.tick += 1
                while (
                    self._next_event < len(self.events)
                    and self.events[self._next_event][0] <= self.tick
                ):
                    ev = self.events[self._next_event][1]
                    self._next_event += 1
                    fired.append(ev)
                    if ev == KILL:
                        self.down = True
                    elif ev == HEAL:
                        self.down = False
                        self.tx_blocked = False
                        self.rx_blocked = False
                    elif ev == PARTITION_TX:
                        self.tx_blocked = True
                    elif ev == PARTITION_RX:
                        self.rx_blocked = True
                    elif ev == CLEAN:
                        self.lossy = False
                    elif ev == LOSSY:
                        self.lossy = True
        return fired


def parse_fault_spec(spec: str) -> FaultPlan:
    """Parse the HM_FAULT grammar (module docstring) into a FaultPlan."""
    seed = 0
    drop = dup = 0.0
    delay = (0.0, 0.0)
    tick_ms = 100.0
    events: List[Tuple[int, str]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.fullmatch(r"([a-z_]+)@(\d+)", part)
        if m:
            events.append((int(m.group(2)), m.group(1)))
            continue
        if "=" not in part:
            raise ValueError(f"bad HM_FAULT entry {part!r}")
        key, val = part.split("=", 1)
        if key == "seed":
            seed = int(val)
        elif key == "drop":
            drop = float(val)
        elif key == "dup":
            dup = float(val)
        elif key == "delay":
            if ":" in val:
                lo, hi = val.split(":", 1)
                delay = (float(lo), float(hi))
            else:
                delay = (float(val), float(val))
        elif key == "tick":
            tick_ms = float(val)
        else:
            raise ValueError(f"unknown HM_FAULT knob {key!r}")
    return FaultPlan(
        seed=seed, drop_p=drop, dup_p=dup, delay_ms=delay,
        events=events, tick_ms=tick_ms,
    )


class _DelayLine:
    """FIFO delayed delivery for one direction: frames leave in
    ARRIVAL order, each no earlier than its due time. Independent
    timers would reorder frames — a failure mode no real transport
    (TCP, the in-memory trampoline) exhibits — so injected latency
    must not either; a later frame drawn a shorter delay simply waits
    behind the earlier one."""

    def __init__(self, deliver: Callable[[Any, int], None]) -> None:
        self._deliver = deliver
        self._cv = make_condition("net.fault.delay")
        self._q: deque = deque()  # (due_monotonic, msg, copies)
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    def pending(self) -> bool:
        return bool(self._q)

    def push(self, msg: Any, copies: int, delay_s: float) -> None:
        with self._cv:
            if self._closed:
                return
            self._q.append((time.monotonic() + delay_s, msg, copies))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="fault-delay"
                )
                self._thread.start()
            self._cv.notify()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._q.clear()
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                due, msg, copies = self._q[0]
                wait = due - time.monotonic()
                if wait > 0:
                    self._cv.wait(wait)
                    continue  # re-check head: close may have landed
                self._q.popleft()
            self._deliver(msg, copies)


class FaultDuplex:
    """One side's duplex behind a FaultPlan. `tx` = frames this side
    sends, `rx` = frames delivered to this side; a one-way partition
    blocks exactly one of them. Close/identity/binding delegate to the
    wrapped transport. Delayed frames ride per-direction FIFO delay
    lines (latency never reorders)."""

    def __init__(
        self,
        inner: Any,
        plan: FaultPlan,
        stats: Optional[Dict[str, int]] = None,
    ) -> None:
        self._inner = inner
        self.plan = plan
        self.stats = stats if stats is not None else _new_stats()
        from ..utils.queue import Queue

        # rx delivery rides the stack's single-subscriber Queue: items
        # buffered before subscribe drain IN ORDER and callbacks are
        # never concurrent — a hand-rolled buffer replayed outside a
        # lock can interleave a live frame ahead of buffered ones
        self._rx_q: "Queue" = Queue("fault:rx")
        self._tx_line = _DelayLine(self._tx_now)
        self._rx_line = _DelayLine(self._rx_now)
        inner.on_close(self._on_inner_close)
        inner.on_message(self._on_rx)

    @property
    def closed(self) -> bool:
        return self._inner.closed

    @property
    def peer_identity(self):
        return getattr(self._inner, "peer_identity", None)

    @property
    def channel_binding(self):
        return getattr(self._inner, "channel_binding", None)

    def on_message(self, cb: Callable[[Any], None]) -> None:
        self._rx_q.subscribe(cb)

    def on_close(self, cb: Callable[[], None]) -> None:
        self._inner.on_close(cb)

    def close(self) -> None:
        self._inner.close()

    def kill(self) -> None:
        """Hard-kill: close the underlying transport (the supervised
        dialer sees a drop and redials)."""
        self.stats["kills"] += 1
        self._inner.close()

    # -- fault application ---------------------------------------------

    def _on_inner_close(self) -> None:
        self._tx_line.close()
        self._rx_line.close()

    def send(self, msg: Any) -> None:
        fate, delay = self.plan.frame_fate(tx=True)
        if fate == DROP:
            self.stats["frames_dropped_injected"] += 1
            return
        if fate == DUP:
            self.stats["frames_duplicated"] += 1
        n = 2 if fate == DUP else 1
        if delay > 0 or self._tx_line.pending():
            # pending() keeps FIFO across a clean transition: an
            # undelayed frame must not overtake queued delayed ones
            if delay > 0:
                self.stats["frames_delayed"] += 1
            self._tx_line.push(msg, n, delay)
        else:
            self._tx_now(msg, n)

    def _tx_now(self, msg: Any, n: int) -> None:
        for _ in range(n):
            self._inner.send(msg)

    def _on_rx(self, msg: Any) -> None:
        fate, delay = self.plan.frame_fate(tx=False)
        if fate == DROP:
            self.stats["frames_dropped_injected"] += 1
            return
        if fate == DUP:
            self.stats["frames_duplicated"] += 1
        n = 2 if fate == DUP else 1
        if delay > 0 or self._rx_line.pending():
            if delay > 0:
                self.stats["frames_delayed"] += 1
            self._rx_line.push(msg, n, delay)
        else:
            self._rx_now(msg, n)

    def _rx_now(self, msg: Any, n: int) -> None:
        for _ in range(n):
            self._rx_q.push(msg)


def _new_stats() -> Dict[str, int]:
    return {
        "frames_dropped_injected": 0,
        "frames_duplicated": 0,
        "frames_delayed": 0,
        "kills": 0,
    }


class FaultSwarm(Swarm):
    """Swarm wrapper: every connection rides a FaultDuplex on the
    shared plan. `tick()` advances the plan deterministically (tests);
    `start_ticker()` advances it on a wall-clock timer (bench/soak,
    started automatically when the plan came from HM_FAULT)."""

    def __init__(self, inner: Swarm, plan: FaultPlan) -> None:
        self.inner = inner
        self.plan = plan
        self.stats = _new_stats()
        self._lock = make_lock("net.fault.swarm")
        self._live: List[FaultDuplex] = []
        self._cb: Optional[Callable] = None
        self._ticker: Optional[threading.Thread] = None
        self._destroyed = threading.Event()
        inner.on_connection(self._on_inner_connection)

    # -- passthrough ----------------------------------------------------

    @property
    def address(self):
        return self.inner.address

    def set_identity(self, seed) -> None:
        self.inner.set_identity(seed)

    def set_need_hook(self, fn) -> None:
        """Demand-driven lookup passthrough (DhtSwarm under faults)."""
        inner = getattr(self.inner, "set_need_hook", None)
        if inner is not None:
            inner(fn)

    def set_seed_hook(self, fn) -> None:
        """Push-seed receiver passthrough (DhtSwarm under faults)."""
        inner = getattr(self.inner, "set_seed_hook", None)
        if inner is not None:
            inner(fn)

    def discovery_report(self):
        """DHT introspection passthrough (DhtSwarm under faults)."""
        fn = getattr(self.inner, "discovery_report", None)
        return fn() if fn is not None else None

    @property
    def supervisor(self):
        """Redial-supervisor passthrough (Tcp/DhtSwarm under faults)."""
        return getattr(self.inner, "supervisor", None)

    def join(self, discovery_id: str, options=None) -> None:
        if options is None:
            self.inner.join(discovery_id)
        else:
            self.inner.join(discovery_id, options)

    def leave(self, discovery_id: str) -> None:
        self.inner.leave(discovery_id)

    def connect(self, *args: Any, **kwargs: Any):
        return self.inner.connect(*args, **kwargs)

    def on_connection(self, cb) -> None:
        self._cb = cb

    def destroy(self) -> None:
        self._destroyed.set()
        self.inner.destroy()

    # -- fault wiring ---------------------------------------------------

    def _on_inner_connection(
        self, duplex: Any, details: ConnectionDetails
    ) -> None:
        fd = FaultDuplex(duplex, self.plan, self.stats)
        with self._lock:
            self._live.append(fd)
        fd.on_close(lambda: self._untrack(fd))
        if self.plan.down:
            # the link is dead this window: the connection dies before
            # the stack sees it, and the supervisor's backoff retries
            log("net:faults", "link down: killing new connection")
            fd.kill()
            return
        if self._cb is not None:
            self._cb(fd, details)

    def _untrack(self, fd: FaultDuplex) -> None:
        with self._lock:
            try:
                self._live.remove(fd)
            except ValueError:
                pass

    def live_connections(self) -> List[FaultDuplex]:
        with self._lock:
            return list(self._live)

    def tick(self, n: int = 1) -> List[str]:
        """Advance the plan `n` ticks and apply fired link events."""
        fired = self.plan.advance(n)
        if KILL in fired:
            for fd in self.live_connections():
                fd.kill()
        return fired

    def start_ticker(self) -> None:
        """Wall-clock tick advancement (plan.tick_ms) for bench/soak."""
        if self._ticker is not None:
            return

        def run() -> None:
            while not self._destroyed.wait(self.plan.tick_ms / 1e3):
                self.tick()

        self._ticker = threading.Thread(
            target=run, daemon=True, name="fault-ticker"
        )
        self._ticker.start()
