"""Network layer: peer connections, replication, pluggable discovery
(SURVEY.md §1.5)."""
