"""Connection resilience: supervised redial with backoff + jitter.

The availability contract the rest of the stack already assumes — "the
peer redials and resyncs from its cursor" (net/tcp.py send() docstring,
net/network.py per-connection channel re-wiring) — lived nowhere until
now: `TcpSwarm.connect` dialed exactly once on the caller's thread and
a shed/crashed/partitioned connection stayed dead forever. The
reference delegates this to hyperswarm's reconnect loop; this module is
that loop for explicit-address swarms.

`SessionSupervisor` owns every outbound address:

- dial + handshake run on a supervisor thread (never the caller's),
  with the bounded dial timeout `HM_DIAL_TIMEOUT_S`;
- a failed dial or a dropped connection schedules a redial after
  exponential backoff with FULL jitter (`HM_REDIAL_BASE_MS`,
  `HM_REDIAL_MAX_S`), reset once a connection survives
  `HM_REDIAL_RESET_S` (instant drops keep escalating);
- retries are UNBOUNDED unless the connection's `ConnectionDetails`
  recorded `reconnect(False)` or `ban()` (the two signals net/swarm.py
  always carried but nothing consulted), or the swarm banned the
  address — then the session stops;
- a status hook surfaces every transition (connecting / connected /
  backoff / stopped) instead of raising into the caller.

Resync after the redial comes for free: `Network._on_peer_active` fires
for every replacement connection and renegotiates replication from
cursors (net/replication.py counts those resyncs in `stats`).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..analysis.lockdep import make_rlock
from ..utils.debug import log


def _base_s() -> float:
    return float(os.environ.get("HM_REDIAL_BASE_MS", "250")) / 1e3


def _max_s() -> float:
    return float(os.environ.get("HM_REDIAL_MAX_S", "30"))


def _reset_uptime_s() -> float:
    """A connection must SURVIVE this long before its success resets
    the backoff: a peer that accepts and instantly drops (crash loop,
    post-handshake refusal) must keep escalating, not get hammered at
    the base rate forever."""
    return float(os.environ.get("HM_REDIAL_RESET_S", "1"))


def dial_timeout_s() -> float:
    return float(os.environ.get("HM_DIAL_TIMEOUT_S", "10"))


class Backoff:
    """Exponential backoff with FULL jitter: attempt n (0-based) sleeps
    uniform(0, min(max_s, base_s * 2**n)). Full jitter (vs equal or
    none) is what keeps a herd of peers redialing a recovered server
    from re-arriving in lockstep. `reset()` on success restores the
    fast first retry."""

    def __init__(
        self,
        base_s: Optional[float] = None,
        max_s: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.base_s = _base_s() if base_s is None else base_s
        self.max_s = _max_s() if max_s is None else max_s
        self._rng = rng if rng is not None else random.Random()
        self.attempt = 0

    def next_delay(self) -> float:
        ceiling = min(self.max_s, self.base_s * (2 ** self.attempt))
        # past the cap, 2**n overflows usefulness; clamp the exponent
        if self.attempt < 63:
            self.attempt += 1
        return self._rng.uniform(0.0, ceiling)

    def reset(self) -> None:
        self.attempt = 0


# session states surfaced through the status hook
CONNECTING = "connecting"
CONNECTED = "connected"
BACKOFF = "backoff"
STOPPED = "stopped"


class Session:
    """One supervised outbound address."""

    def __init__(self, address: Any) -> None:
        self.address = address
        self.state = CONNECTING
        self.duplex = None
        self.details = None
        self.backoff = Backoff()
        self.connects = 0  # successful dial+handshakes
        self.failures = 0  # failed dial attempts
        self.stop_reason: Optional[str] = None
        self._wake = threading.Event()  # interrupts a backoff sleep

    def kick(self) -> None:
        """Skip the current backoff sleep (idempotent re-`connect`)."""
        self._wake.set()
        hook = getattr(self, "_kick_hook", None)
        if hook is not None:  # async mode: cancel the backoff timer
            hook()


class SessionSupervisor:
    """Redial loop over a swarm's dial primitive.

    `dial(address)` must return a CONNECTED duplex (handshake done) or
    raise OSError; `deliver(duplex, details)` hands the connection to
    the swarm's on_connection callback. `banned(address)` lets the
    swarm veto an address (see TcpSwarm's ban registry).

    Async mode (`HM_NET_ASYNC=1`): pass `connector` (the shared
    net/aio.py loop, or anything with `call_soon`/`call_later`) and a
    `dial(address, cb)` primitive that starts a NON-blocking dial and
    fires `cb(duplex, exc)` exactly once when the handshake settles.
    Sessions then run as callback state machines — the same
    CONNECTING/CONNECTED/BACKOFF/STOPPED transitions, counters and
    ban/reconnect consults as the thread mode, but a supervised
    address no longer owns a parked thread: backoff waits live on the
    loop's timer wheel, so 1000 supervised peers cost 1000 heap
    entries instead of 1000 threads."""

    def __init__(
        self,
        dial: Callable[..., Any],
        deliver: Callable[[Any, Any], None],
        banned: Optional[Callable[[Any], bool]] = None,
        on_status: Optional[Callable[[Session, str, dict], None]] = None,
        connector: Optional[Any] = None,
    ) -> None:
        self._dial = dial
        self._deliver = deliver
        self._banned = banned if banned is not None else lambda a: False
        self._on_status = on_status
        self._connector = connector
        self._lock = make_rlock("net.sup")
        self._sessions: Dict[Any, Session] = {}
        self._stopped = False
        # registry-backed (one labeled series per supervisor); the
        # `stats` property keeps the historical dict shape
        from .. import telemetry

        inst = str(telemetry.next_instance())
        self._m = {
            k: telemetry.counter("net.sup." + k, inst=inst)
            for k in ("dials", "reconnects")
        }

    @property
    def stats(self) -> Dict[str, int]:
        return {
            "dials": int(self._m["dials"].value()),
            "reconnects": int(self._m["reconnects"].value()),
        }

    def on_status(
        self, cb: Callable[[Session, str, dict], None]
    ) -> None:
        self._on_status = cb

    def session(self, address: Any) -> Optional[Session]:
        with self._lock:
            return self._sessions.get(address)

    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions.values())

    def connect(self, address: Any) -> Session:
        """Register (or kick) the supervised session for `address`.
        Returns immediately; the dial runs on the session thread."""
        with self._lock:
            if self._stopped:
                raise RuntimeError("supervisor stopped")
            s = self._sessions.get(address)
            if s is not None and s.state != STOPPED:
                s.kick()
                return s
            # no session, or a STOPPED one (its thread exited — kick
            # would wake nobody): an explicit connect() is a fresh
            # instruction, so start a fresh session. A still-banned
            # address stops again immediately, via the status hook
            # rather than silence.
            s = Session(address)
            self._sessions[address] = s
        if self._connector is not None:
            # async mode: no parked thread — the session advances via
            # dial callbacks and loop timers
            s._dialing = False
            s._timer = None
            s._kick_hook = lambda: self._a_kick(s)
            self._a_attempt(s)
            return s
        t = threading.Thread(
            target=self._run, args=(s,), daemon=True,
            name=f"redial:{address}",
        )
        s._thread = t  # stop() joins before retiring the counters
        t.start()
        return s

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            sessions = list(self._sessions.values())
        for s in sessions:
            s.kick()
            # a session parked on a LIVE connection waits on the
            # connection-done event, not the backoff wake: set it too,
            # or every stop() pays the full join timeout per connected
            # session (at fleet scale that is the whole teardown)
            done = getattr(s, "_conn_done", None)
            if done is not None:
                done.set()
            # async sessions have no thread to observe _stopped and
            # retire themselves: the kick above cancelled the backoff
            # timer, so finish the transition here (the callback chain
            # re-checks _stopped before any further step)
            if self._connector is not None and s.state != STOPPED:
                self._stop_session(s, "supervisor stopped")
        # bounded join before retiring the series: a session thread
        # bumping `dials` after the fold would land on a dropped
        # handle (kick() already interrupts backoff sleeps; only a
        # dial mid-flight can outlive the bound, and it re-checks
        # stopped before any further counting)
        for s in sessions:
            t = getattr(s, "_thread", None)
            if t is not None and t is not threading.current_thread():
                t.join(timeout=1.0)
        # registry hygiene (idempotent): fold this supervisor's series
        # into the closed aggregate; stats stays handle-readable
        from .. import telemetry

        telemetry.REGISTRY.retire(*self._m.values())

    # ------------------------------------------------------------------

    def _status(self, s: Session, state: str, **info: Any) -> None:
        s.state = state
        if self._on_status is not None:
            try:
                self._on_status(s, state, info)
            except Exception as e:  # a hook bug must not kill the loop
                log("net:redial", f"status hook error: {e}")

    def _sleep(self, s: Session, delay: float) -> bool:
        """Backoff sleep; True when the supervisor stopped meanwhile."""
        s._wake.wait(delay)
        s._wake.clear()
        return self._stopped

    def _stop_session(self, s: Session, reason: str) -> None:
        s.stop_reason = reason
        self._status(s, STOPPED, reason=reason)
        log("net:redial", f"session {s.address} stopped: {reason}")

    # ------------------------------------------------------------------
    # async session state machine (connector mode): one step per
    # callback, mirroring _run()'s sequence exactly — same consults,
    # same counter points, same "details exposed after deliver" rule

    def _a_kick(self, s: Session) -> None:
        t = getattr(s, "_timer", None)
        if t is not None:
            t.cancel()
        if s.state == BACKOFF and not self._stopped:
            self._connector.call_soon(lambda: self._a_attempt(s))

    def _a_attempt(self, s: Session) -> None:
        with self._lock:
            if self._stopped or s.state == STOPPED or s._dialing:
                return
            s._dialing = True
        if self._banned(s.address):
            s._dialing = False
            self._stop_session(s, "banned address")
            return
        # re-consult the stop signals set during a backoff window
        # (same rule as the thread loop's top-of-iteration check)
        d = s.details
        if d is not None:
            if d.banned:
                s._dialing = False
                self._stop_session(s, "peer banned")
                return
            if not d._reconnect_allowed:
                s._dialing = False
                self._stop_session(s, "reconnect disallowed")
                return
        self._status(s, CONNECTING, attempt=s.backoff.attempt)
        self._m["dials"].add(1)
        try:
            self._dial(
                s.address,
                lambda duplex, exc: self._a_dialed(s, duplex, exc),
            )
        except OSError as e:
            self._a_failed(s, e)

    def _a_failed(self, s: Session, e: BaseException) -> None:
        s._dialing = False
        if self._stopped:
            return
        s.failures += 1
        delay = s.backoff.next_delay()
        self._status(
            s, BACKOFF, error=str(e), delay=delay,
            attempt=s.backoff.attempt,
        )
        s._timer = self._connector.call_later(
            delay, lambda: self._a_attempt(s)
        )

    def _a_dialed(self, s: Session, duplex: Any, exc) -> None:
        if exc is not None:
            self._a_failed(s, exc)
            return
        s._dialing = False
        if self._stopped or self._banned(s.address):
            # stop()/ban landed while the dial was in flight: never
            # hand a live connection to a torn-down swarm
            duplex.close()
            if self._stopped:
                return
            self._stop_session(s, "banned address")
            return
        from .swarm import ConnectionDetails

        details = ConnectionDetails(client=True)
        s.duplex = duplex
        t_up = time.monotonic()
        s.connects += 1
        if s.connects > 1:
            self._m["reconnects"].add(1)
        self._status(s, CONNECTED, connects=s.connects)
        try:
            self._deliver(duplex, details)
        except Exception as e:  # callback bug: treat as a drop
            log("net:redial", f"deliver failed for {s.address}: {e}")
            duplex.close()
        # expose the details only once deliver wired its hooks
        s.details = details
        # register AFTER deliver: the stack's own close listeners run
        # (peer inactive -> replication reset) before the redial
        duplex.on_close(lambda: self._a_closed(s, details, t_up))

    def _a_closed(self, s: Session, details: Any, t_up: float) -> None:
        if self._stopped:
            return
        if details.banned:
            self._stop_session(s, "peer banned")
            return
        if not details._reconnect_allowed:
            self._stop_session(s, "reconnect disallowed")
            return
        if time.monotonic() - t_up >= _reset_uptime_s():
            s.backoff.reset()  # a STABLE connection earns the fast
            # first redial; instant drops keep escalating
        delay = s.backoff.next_delay()
        self._status(
            s, BACKOFF, delay=delay, attempt=s.backoff.attempt
        )
        s._timer = self._connector.call_later(
            delay, lambda: self._a_attempt(s)
        )

    def _run(self, s: Session) -> None:
        while not self._stopped:
            if self._banned(s.address):
                self._stop_session(s, "banned address")
                return
            # a caller may set reconnect(False)/ban() on s.details
            # DURING a backoff window (the documented stop signal);
            # the previous connection's post-close check already
            # passed, so re-consult before dialing again
            d = s.details
            if d is not None:
                if d.banned:
                    self._stop_session(s, "peer banned")
                    return
                if not d._reconnect_allowed:
                    self._stop_session(s, "reconnect disallowed")
                    return
            self._status(s, CONNECTING, attempt=s.backoff.attempt)
            self._m["dials"].add(1)
            try:
                duplex = self._dial(s.address)
            except OSError as e:
                s.failures += 1
                delay = s.backoff.next_delay()
                self._status(
                    s, BACKOFF, error=str(e), delay=delay,
                    attempt=s.backoff.attempt,
                )
                if self._sleep(s, delay):
                    return
                continue
            if self._stopped or self._banned(s.address):
                # stop()/ban landed while the dial was in flight (up
                # to the dial timeout): never hand a live connection
                # to a torn-down swarm
                duplex.close()
                if self._stopped:
                    return
                self._stop_session(s, "banned address")
                return
            from .swarm import ConnectionDetails

            details = ConnectionDetails(client=True)
            s.duplex = duplex
            t_up = time.monotonic()
            s.connects += 1
            if s.connects > 1:
                self._m["reconnects"].add(1)
            self._status(s, CONNECTED, connects=s.connects)
            try:
                self._deliver(duplex, details)
            except Exception as e:  # callback bug: treat as a drop
                log("net:redial", f"deliver failed for {s.address}: {e}")
                duplex.close()
            # expose the details only once deliver wired its hooks
            # (e.g. the swarm's ban recorder): a caller acting on
            # s.details must never beat the attachment
            s.details = details
            # register AFTER deliver: the connection stack's own close
            # listeners must run (peer inactive -> replication reset)
            # BEFORE the supervisor wakes to redial, or the replacement
            # races the teardown accounting. A duplex that closed in
            # between fires the listener immediately.
            closed = threading.Event()
            s._conn_done = closed  # stop() sets it (see above): a
            # supervisor teardown must not wait out a healthy link
            duplex.on_close(closed.set)
            closed.wait()
            if self._stopped:
                return
            # the two recorded-but-never-consulted signals, consulted:
            if details.banned:
                self._stop_session(s, "peer banned")
                return
            if not details._reconnect_allowed:
                self._stop_session(s, "reconnect disallowed")
                return
            if time.monotonic() - t_up >= _reset_uptime_s():
                s.backoff.reset()  # a STABLE connection earns the
                # fast first redial; instant drops keep escalating
            delay = s.backoff.next_delay()
            self._status(s, BACKOFF, delay=delay, attempt=s.backoff.attempt)
            if self._sleep(s, delay):
                return
