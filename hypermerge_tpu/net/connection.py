"""PeerConnection — one transport with named multiplexed channels.

Parity: reference src/PeerConnection.ts:14-86 + src/MessageBus.ts — one
socket carrying noise-encrypted multiplexed substreams with a
`NetworkBus` channel always open, and channels opened by the remote side
first buffering until locally opened (the reference's pending-channel
hack, src/PeerConnection.ts:64-73).

Encryption lives at the Duplex transport layer: the in-memory test pair
needs none; the TCP adapter (net/tcp.py) encrypts every frame under an
X25519 kx handshake + ChaCha20-Poly1305 (net/secure.py, libsodium via
native/ with a pure fallback) — the reference's noise wrapping
(src/PeerConnection.ts:36).
"""

from __future__ import annotations

import threading
import uuid
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..analysis.lockdep import make_lock
from ..utils.queue import Queue
from .duplex import Duplex

NETWORK_BUS = "NetworkBus"


class Channel:
    def __init__(self, conn: "PeerConnection", name: str) -> None:
        self._conn = conn
        self.name = name
        self.receive_q: Queue = Queue(f"ch:{name}")

    def send(self, msg: Any) -> None:
        self._conn._send_on(self.name, msg)

    def subscribe(self, cb: Callable[[Any], None]) -> None:
        self.receive_q.subscribe(cb)


class PeerConnection:
    def __init__(self, duplex: Duplex, is_client: bool) -> None:
        self.id = uuid.uuid4().hex
        self.is_client = is_client
        self._duplex = duplex
        self._channels: Dict[str, Channel] = {}
        self.is_open = True
        self._close_listeners = []
        self._close_lock = make_lock("net.conn")
        self.network_bus = self.open_channel(NETWORK_BUS)
        duplex.on_message(self._on_raw)
        duplex.on_close(self._on_transport_close)

    @property
    def peer_identity(self):
        """The peer's transport-proven ed25519 identity (base58), or
        None on unauthenticated transports (in-memory pairs, legacy
        anonymous TCP). See net/secure.py auth frames."""
        return getattr(self._duplex, "peer_identity", None)

    @property
    def channel_binding(self):
        """Session-unique exporter over the encrypted transport's
        ephemeral handshake transcript (None on plaintext transports).
        Replication MACs it into capability proofs so a proof minted on
        one connection is worthless on any other."""
        return getattr(self._duplex, "channel_binding", None)

    def open_channel(self, name: str) -> Channel:
        ch = self._channels.get(name)
        if ch is None:
            ch = Channel(self, name)
            self._channels[name] = ch
        return ch

    def _send_on(self, name: str, msg: Any) -> None:
        if self.is_open:
            self._duplex.send({"ch": name, "m": msg})

    def _on_raw(self, raw: Any) -> None:
        try:
            name, msg = raw["ch"], raw["m"]
        except (TypeError, KeyError):
            return  # malformed frame: drop
        # channels opened by the remote first buffer in their queue
        self.open_channel(name).receive_q.push(msg)

    def on_close(self, cb: Callable[[], None]) -> None:
        """A listener registered after the connection already closed
        fires immediately: under churn the transport can die between a
        caller's `is_open` check and its registration, and a silently
        dropped listener leaves the peer wired to a dead connection
        (NetworkPeer would never fire on_inactive -> replication never
        resets -> the redialed connection renegotiates against stale
        associations). The lock makes check-then-append atomic against
        the close path's listener snapshot — without it, a listener
        appended between the snapshot and is_open flipping is silently
        lost, the exact failure this method exists to prevent."""
        with self._close_lock:
            if self.is_open:
                self._close_listeners.append(cb)
                return
        cb()

    def _on_transport_close(self) -> None:
        with self._close_lock:
            if not self.is_open:
                return
            self.is_open = False
            listeners = list(self._close_listeners)
        for cb in listeners:
            cb()

    def close(self) -> None:
        with self._close_lock:
            if not self.is_open:
                return
            self.is_open = False
            listeners = list(self._close_listeners)
        self._duplex.close()
        for cb in listeners:
            cb()
