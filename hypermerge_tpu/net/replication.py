"""ReplicationManager — feed sync between peers.

Parity: reference src/ReplicationManager.ts:25-137 — peers exchange the
discovery ids of every feed they know (never the public keys: a peer only
replicates a feed it already knows the key for), intersect, replicate
shared feeds, announce newly-created feeds, and surface Discovery events
so the repo can send cursor gossip (reference :56-112).

Wire protocol on the "Replication" channel (replaces hypercore-protocol,
with hypercore's trust model: every extension arrives under an ed25519
signature over the feed's merkle root and is verified against the feed
public key BEFORE storage — storage/integrity.py, reference
src/types/hypercore.d.ts:132-188):

  DiscoveryIds {ids}                      full/delta announcement
  FeedLength   {id, length}               my block count for a shared feed
  Request      {id, from}                 send me blocks starting at `from`
  Blocks       {id, from, blocks(b64),
                len, sig(b64), total}     one verified chunk: blocks fill
                                          [from, len); sig covers the
                                          merkle root at `len`; `total` is
                                          the sender's head, so a receiver
                                          still behind re-requests — an
                                          ack-paced stream with one
                                          bounded chunk in flight (no
                                          whole-feed frames; VERDICT r3
                                          missing #6)

Backfill chunking: a sender slices at its stored signature records
(HM_REPL_CHUNK blocks per chunk, default 1024). Unsigned legacy blocks
are dropped unless HM_ALLOW_UNSIGNED_FEEDS=1.

Live tail: local appends push one signed Blocks msg to every peer
replicating the feed.
"""

from __future__ import annotations

import base64
import os
import threading
from typing import Callable, Dict, List, Optional, Set

from ..storage.feed import Feed, FeedStore
from ..storage.integrity import allow_unsigned
from ..utils.debug import log
from ..utils.mapset import MapSet
from .peer import NetworkPeer

CHANNEL = "Replication"


def _chunk_blocks() -> int:
    return int(os.environ.get("HM_REPL_CHUNK", "1024"))


def _chunk_bytes() -> int:
    # well under tcp.py's 64MB frame cap even after base64+JSON framing
    return int(os.environ.get("HM_REPL_CHUNK_BYTES", str(8 * 1024 * 1024)))


class ReplicationManager:
    def __init__(
        self,
        feeds: FeedStore,
        on_discovery: Callable[[str, NetworkPeer], None],
    ) -> None:
        self.feeds = feeds
        self._on_discovery = on_discovery
        self._lock = threading.RLock()
        self._peers: Set[NetworkPeer] = set()
        # discovery_id -> peers replicating it with us
        self._replicating: MapSet = MapSet()
        self._tailed: Set[str] = set()  # feeds we attached appenders to

    # ------------------------------------------------------------------

    def on_peer(self, peer: NetworkPeer) -> None:
        with self._lock:
            self._peers.add(peer)
        ch = peer.connection.open_channel(CHANNEL)
        ch.subscribe(lambda msg: self._on_message(peer, msg))
        ch.send(
            {"type": "DiscoveryIds", "ids": self.feeds.known_discovery_ids()}
        )

    def on_peer_closed(self, peer: NetworkPeer) -> None:
        with self._lock:
            self._peers.discard(peer)
            for did in self._replicating.keys_with(peer):
                self._replicating.remove(did, peer)

    def announce(self, feed: Feed) -> None:
        """A newly created/opened feed: tell every connected peer
        (reference's late-feed announcement, ReplicationManager.ts:91-96)."""
        self._tail(feed)
        with self._lock:
            peers = list(self._peers)
        for peer in peers:
            if peer.is_connected:
                peer.connection.open_channel(CHANNEL).send(
                    {"type": "DiscoveryIds", "ids": [feed.discovery_id]}
                )

    def peers_with_feed(self, discovery_id: str) -> List[NetworkPeer]:
        with self._lock:
            return [
                p for p in self._replicating.get(discovery_id)
                if p.is_connected
            ]

    # ------------------------------------------------------------------

    def _on_message(self, peer: NetworkPeer, msg: Dict) -> None:
        if not isinstance(msg, dict):
            return
        try:
            t = msg.get("type")
            if t == "DiscoveryIds":
                self._on_discovery_ids(peer, list(msg["ids"]))
            elif t == "FeedLength":
                self._on_feed_length(peer, msg["id"], int(msg["length"]))
            elif t == "Request":
                self._on_request(peer, msg["id"], int(msg["from"]))
            elif t == "Blocks":
                self._on_blocks(
                    peer,
                    msg["id"],
                    int(msg["from"]),
                    list(msg["blocks"]),
                    int(msg.get("len", -1)),
                    msg.get("sig"),
                    int(msg.get("total", -1)),
                )
        except (KeyError, TypeError, ValueError) as e:
            log("replication", f"malformed msg from {peer.id[:6]}: {e}")

    def _start_replicating(
        self, peer: NetworkPeer, feed: Feed, announce_length: bool
    ) -> bool:
        """First association of (feed, peer): tail the feed, optionally
        announce our length, and fire the Discovery event. Returns True
        if this was the first association."""
        newly = self._replicating.add(feed.discovery_id, peer)
        if newly:
            self._tail(feed)
            if announce_length:
                self._send(peer, {
                    "type": "FeedLength",
                    "id": feed.discovery_id,
                    "length": feed.length,
                })
            self._on_discovery(feed.public_key, peer)
        return newly

    def _on_discovery_ids(self, peer: NetworkPeer, ids: List[str]) -> None:
        for did in ids:
            feed = self.feeds.by_discovery_id(did)
            if feed is None:
                continue  # we don't know this feed's key — can't replicate
            self._start_replicating(peer, feed, announce_length=True)

    def _on_feed_length(
        self, peer: NetworkPeer, did: str, their_len: int
    ) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None:
            return
        self._start_replicating(peer, feed, announce_length=False)
        if feed.length < their_len:
            self._send(peer, {
                "type": "Request", "id": did, "from": feed.length,
            })
        elif feed.length > their_len:
            self._send(peer, {
                "type": "FeedLength", "id": did, "length": feed.length,
            })

    def _pick_boundary(self, feed: Feed, start: int) -> int:
        """End of the next backfill chunk, bounded in BLOCKS and BYTES
        (a frame must stay far below tcp.py's 64MB cap): the largest
        signed-record length within both budgets, else the first record
        past `start`, else the head (legacy unsigned feeds)."""
        have = feed.length
        if feed.integrity is None:
            return have
        lengths = [r[0] for r in feed.integrity.records() if r[0] > start]
        if not lengths:
            return have
        # shrink the block budget until the byte budget holds
        want = min(have, start + _chunk_blocks())
        budget = _chunk_bytes()
        total = 0
        count = 0
        for b in feed.get_batch(start, want):
            total += len(b)
            count += 1
            if total > budget and count > 1:
                count -= 1
                break
        want = start + max(count, 1)
        within = [l for l in lengths if l <= want]
        if within:
            return max(within)
        end = min(lengths)
        if end - start > _chunk_blocks():
            log(
                "replication",
                f"sparse signature records on {feed.public_key[:6]}: "
                f"serving an oversized chunk {start}..{end}",
            )
        return end

    def _blocks_msg(self, feed: Feed, did: str, start: int, end: int):
        rec = (
            feed.integrity.record_at(end)
            if feed.integrity is not None
            else None
        )
        return {
            "type": "Blocks",
            "id": did,
            "from": start,
            "blocks": [
                base64.b64encode(b).decode("ascii")
                for b in feed.get_batch(start, end)
            ],
            "len": end,
            "sig": (
                base64.b64encode(rec[2]).decode("ascii") if rec else None
            ),
            "total": feed.length,
        }

    def _on_request(self, peer: NetworkPeer, did: str, start: int) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None or start >= feed.length:
            return
        end = self._pick_boundary(feed, start)
        self._send(peer, self._blocks_msg(feed, did, start, end))

    def _on_blocks(
        self,
        peer: NetworkPeer,
        did: str,
        start: int,
        blocks: List[str],
        length: int,
        sig_b64: Optional[str],
        total: int,
    ) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None:
            return
        if start > feed.length:
            # gap: re-request from our actual head
            self._send(peer, {
                "type": "Request", "id": did, "from": feed.length,
            })
            return
        raw = [base64.b64decode(b) for b in blocks]
        if sig_b64 is not None and length >= 0:
            ok = feed.append_verified(
                start, raw, length, base64.b64decode(sig_b64)
            )
            if not ok:
                log(
                    "replication",
                    f"REJECTED unverified extension of "
                    f"{feed.public_key[:6]} from {peer.id[:6]} "
                    f"(len {length})",
                )
                return
        elif allow_unsigned():
            for i, b in enumerate(raw):
                index = start + i
                if index < feed.length:
                    continue  # duplicate
                feed._append_raw(b)
        else:
            log(
                "replication",
                f"DROPPED unsigned blocks for {feed.public_key[:6]} "
                f"from {peer.id[:6]} (set HM_ALLOW_UNSIGNED_FEEDS=1 "
                "to accept legacy feeds)",
            )
            return
        if total > feed.length:
            # ack-paced stream: pull the next chunk
            self._send(peer, {
                "type": "Request", "id": did, "from": feed.length,
            })

    def _tail(self, feed: Feed) -> None:
        with self._lock:
            if feed.public_key in self._tailed:
                return
            self._tailed.add(feed.public_key)
        did = feed.discovery_id

        def on_extended(start: int, end: int) -> None:
            # one push per extension (a verified backfill chunk is ONE
            # event, not per-block) — relays don't amplify chunk traffic
            rec = (
                feed.integrity.record_at(end)
                if feed.integrity is not None
                else None
            )
            if rec is not None:
                payload = self._blocks_msg(feed, did, start, end)
            else:
                # no signature at this exact length: announce and let
                # peers pull a chunk we CAN sign for
                payload = {
                    "type": "FeedLength", "id": did, "length": feed.length,
                }
            for peer in self.peers_with_feed(did):
                self._send(peer, payload)

        feed.on_extended(on_extended)

    def _send(self, peer: NetworkPeer, msg: Dict) -> None:
        if peer.is_connected:
            peer.connection.open_channel(CHANNEL).send(msg)
