"""ReplicationManager — feed sync between peers.

Parity: reference src/ReplicationManager.ts:25-137 — peers exchange the
discovery ids of every feed they know (never the public keys: a peer only
replicates a feed it already knows the key for), intersect, replicate
shared feeds, announce newly-created feeds, and surface Discovery events
so the repo can send cursor gossip (reference :56-112).

Wire protocol on the "Replication" channel (replaces hypercore-protocol,
with hypercore's trust model: every extension arrives under an ed25519
signature over the feed's merkle root and is verified against the feed
public key BEFORE storage — storage/integrity.py, reference
src/types/hypercore.d.ts:132-188):

  DiscoveryIds {ids}                      full/delta announcement
  FeedLength   {id, length}               my block count for a shared feed
  Request      {id, from}                 send me blocks starting at `from`
  RequestRange {id, from, to}             sparse fetch: arbitrary range,
                                          out of order (hypercore's
                                          sparse download; VERDICT r5
                                          missing #4 — prioritize the
                                          tail of a long feed)
  SparseBlocks {id, from, len, sig,
                blocks(b64), proofs}      ranged reply: each block
                                          carries a merkle INCLUSION
                                          proof against the signed
                                          root at `len` (verified
                                          without the prefix; landed in
                                          the feed's sparse buffer)
  Blocks       {id, from, blocks(b64),
                len, sig(b64), total}     one verified chunk: blocks fill
                                          [from, len); sig covers the
                                          merkle root at `len`; `total` is
                                          the sender's head, so a receiver
                                          still behind re-requests — an
                                          ack-paced stream with one
                                          bounded chunk in flight (no
                                          whole-feed frames; VERDICT r3
                                          missing #6)

Backfill chunking: a sender slices at its stored signature records
(HM_REPL_CHUNK blocks per chunk, default 1024). Unsigned legacy blocks
are dropped unless HM_ALLOW_UNSIGNED_FEEDS=1.

Live tail: local appends mark the feed dirty; a flusher thread
coalesces every append that lands within one flush window
(HM_REPL_FLUSH_MS, default 2ms) into ONE signed Blocks msg per feed —
a burst of N interactive edits costs O(1) frames, not N (the batched
block sync of hypercore-protocol; reference
src/ReplicationManager.ts:114-136). Frames still respect the
chunk block/byte budgets via _pick_boundary.
"""

from __future__ import annotations

import base64
import hmac
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ..analysis.lockdep import make_rlock
from ..storage.feed import Feed, FeedStore
from ..storage.integrity import allow_unsigned, capability
from ..utils.debug import log
from ..utils.mapset import MapSet
from .. import telemetry
from .peer import NetworkPeer

CHANNEL = "Replication"


def _chunk_blocks() -> int:
    return int(os.environ.get("HM_REPL_CHUNK", "1024"))


def _chunk_bytes() -> int:
    # well under tcp.py's 64MB frame cap even after base64+JSON framing
    return int(os.environ.get("HM_REPL_CHUNK_BYTES", str(8 * 1024 * 1024)))


def _flush_window_s() -> float:
    return float(os.environ.get("HM_REPL_FLUSH_MS", "2")) / 1e3


def _flush_window_max_s() -> float:
    return float(os.environ.get("HM_REPL_FLUSH_MAX_MS", "25")) / 1e3


def _antientropy_s() -> float:
    """Anti-entropy sweep period (0 disables). The gap-driven protocol
    only recovers a LOST replication frame at the next tail flush or a
    reconnect renegotiation; a periodic FeedLength re-announce bounds
    that staleness by the sweep interval — and a crash-recovered
    (truncated) peer re-advertises its true lengths promptly instead
    of waiting for new local writes."""
    return float(os.environ.get("HM_ANTIENTROPY_S", "30"))


class ReplicationManager:
    def __init__(
        self,
        feeds: FeedStore,
        on_discovery: Callable[[str, NetworkPeer], None],
        sampler=None,
    ) -> None:
        self.feeds = feeds
        self._on_discovery = on_discovery
        # bounded gossip relay (net/discovery/gossip.py GossipSampler
        # or None = broadcast): live-tail flushes target a per-feed
        # sampled peer subset so a hot doc's frame cost stays
        # O(fanout), not O(peers); receivers relay to THEIR samples
        # (their on_extended marks their flusher), and the unsampled
        # anti-entropy sweep bounds any straggler by one period
        self._sampler = sampler
        self._lock = make_rlock("net.repl")
        self._peers: Set[NetworkPeer] = set()
        # discovery_id -> peers replicating it with us. Membership
        # requires CAPABILITY verification: a peer only enters (and so
        # only ever receives blocks/tails/gossip for the feed) after
        # proving knowledge of the feed public key — learning a
        # discovery id from announcements must not unlock data
        # (hypercore-protocol's capability check).
        self._replicating: MapSet = MapSet()
        self._verified: MapSet = MapSet()  # did -> peers that proved
        self._tailed: Set[str] = set()  # feeds we attached appenders to
        # per-connection random capability challenges: ours (what peers
        # must prove against) and theirs (what we prove against)
        self._challenge_local: Dict[NetworkPeer, bytes] = {}
        self._challenge_remote: Dict[NetworkPeer, bytes] = {}
        # outstanding sparse-fetch indices per feed: only blocks WE
        # asked for may land in the sparse buffer — an unsolicited
        # SparseBlocks push (even with valid proofs) must not grow
        # memory on a peer that never requested it
        self._sparse_wanted: Dict[str, Set[int]] = {}
        # churn accounting: a peer re-activating after a close is a
        # RESYNC (the supervised redial restored it); t_resync_ms sums
        # redial -> first post-reconnect replication data frame.
        # Series live on the process telemetry registry (labeled per
        # manager); `stats` rebuilds the historical dict. The sharded
        # counter closes the old unlocked `stats["t_resync_ms"] +=`
        # read-modify-write race from reader threads.
        inst = str(telemetry.next_instance())
        self._m = {
            k: telemetry.counter("net.repl." + k, inst=inst)
            for k in (
                "resyncs", "t_resync_ms", "antientropy_sweeps",
                "frames_tx", "frames_rx",
            )
        }
        self._seen_closed: Set[str] = set()
        self._resync_t0: Dict[str, float] = {}
        # live-tail coalescing: public_key -> earliest unflushed block,
        # adaptive window (batches grow under sustained load instead of
        # frame count), drained on close
        from ..utils.debounce import Debouncer

        self._flusher = Debouncer(
            self._flush_batch,
            window_s=_flush_window_s(),
            max_window_s=_flush_window_max_s(),
            merge=min,
            name="repl-flush",
        )
        # anti-entropy sweep: periodic FeedLength re-announce to every
        # verified peer (thread starts lazily on the first peer; a
        # peerless manager never pays for it)
        self._ae_interval = _antientropy_s()
        self._ae_stop = threading.Event()
        self._ae_thread: Optional[threading.Thread] = None
        # sweep-time cursor repair hook: called (peer, public_keys)
        # once per peer per sweep (Network wires it to
        # RepoBackend.send_sweep_cursors). Set before traffic flows.
        self.on_sweep: Optional[Callable] = None
        # service-plane hook (same wiring window): an
        # OverloadController whose BROWNOUT+ states skip the periodic
        # sweep — repair is deferrable, foreground reads are not
        self.overload_ctl = None

    @property
    def stats(self) -> Dict[str, float]:
        """The historical stats dict shape (registry-backed,
        read-only): resyncs, t_resync_ms, antientropy_sweeps."""
        m = self._m
        return {
            "resyncs": int(m["resyncs"].value()),
            "t_resync_ms": round(m["t_resync_ms"].value(), 6),
            "antientropy_sweeps": int(
                m["antientropy_sweeps"].value()
            ),
            "frames_tx": int(m["frames_tx"].value()),
            "frames_rx": int(m["frames_rx"].value()),
        }

    # ------------------------------------------------------------------

    def _challenge_for(self, peer: NetworkPeer) -> bytes:
        with self._lock:
            c = self._challenge_local.get(peer)
            if c is None:
                c = os.urandom(32)
                self._challenge_local[peer] = c
            return c

    def on_peer(self, peer: NetworkPeer) -> None:
        conn = peer.connection
        if conn is None:  # torn down while the activation was in flight
            return
        with self._lock:
            self._peers.add(peer)
            if peer.id in self._seen_closed:
                self._m["resyncs"].add(1)
                self._resync_t0[peer.id] = time.monotonic()
            if self._ae_thread is None and self._ae_interval > 0:
                self._ae_thread = threading.Thread(
                    target=self._ae_loop, daemon=True, name="antientropy"
                )
                self._ae_thread.start()
        ch = conn.open_channel(CHANNEL)
        ch.subscribe(lambda msg: self._on_message(peer, msg))
        ch.send({
            "type": "DiscoveryIds",
            "ids": self.feeds.known_discovery_ids(),
            "challenge": base64.b64encode(
                self._challenge_for(peer)
            ).decode("ascii"),
        })

    def on_peer_closed(self, peer: NetworkPeer) -> None:
        with self._lock:
            self._peers.discard(peer)
            self._seen_closed.add(peer.id)
            self._resync_t0.pop(peer.id, None)
            for did in self._replicating.keys_with(peer):
                self._replicating.remove(did, peer)
            for did in self._verified.keys_with(peer):
                self._verified.remove(did, peer)
            self._challenge_local.pop(peer, None)
            self._challenge_remote.pop(peer, None)

    def announce(self, feed: Feed) -> None:
        """A newly created/opened feed: tell every connected peer
        (reference's late-feed announcement, ReplicationManager.ts:91-96)."""
        self._tail(feed)
        with self._lock:
            peers = list(self._peers)
        for peer in peers:
            self._send(peer, {
                "type": "DiscoveryIds",
                "ids": [feed.discovery_id],
                "challenge": base64.b64encode(
                    self._challenge_for(peer)
                ).decode("ascii"),
            })

    def peers_with_feed(self, discovery_id: str) -> List[NetworkPeer]:
        with self._lock:
            return [
                p for p in self._replicating.get(discovery_id)
                if p.is_connected
            ]

    # ------------------------------------------------------------------

    def _on_message(self, peer: NetworkPeer, msg: Dict) -> None:
        if not isinstance(msg, dict):
            return
        self._m["frames_rx"].add(1)
        try:
            t = msg.get("type")
            if t != "DiscoveryIds" and self._resync_t0:
                # the reconnect's opener is DiscoveryIds; the first
                # DATA-path frame after it closes the resync window.
                # The unlocked emptiness pre-check keeps the steady-
                # state data path lock-free (the dict is almost always
                # empty); a window nothing ever closed (no shared
                # feeds, idle link) must not charge the whole idle gap
                # to a late unrelated frame: past 60s the resync is
                # moot
                with self._lock:
                    t0 = self._resync_t0.pop(peer.id, None)
                if t0 is not None:
                    elapsed = time.monotonic() - t0
                    if elapsed < 60:
                        self._m["t_resync_ms"].add(elapsed * 1e3)
                        telemetry.instant(
                            "net.resync", cat="net",
                            ms=round(elapsed * 1e3, 1),
                        )
            if t == "DiscoveryIds":
                if "challenge" in msg:
                    with self._lock:
                        self._challenge_remote[peer] = base64.b64decode(
                            msg["challenge"]
                        )
                self._on_discovery_ids(peer, list(msg["ids"]))
            elif t == "FeedLength":
                self._on_feed_length(
                    peer, msg["id"], int(msg["length"]), msg.get("cap")
                )
            elif t == "Request":
                self._on_request(
                    peer, msg["id"], int(msg["from"]), msg.get("cap")
                )
            elif t == "RequestRange":
                self._on_request_range(
                    peer,
                    msg["id"],
                    int(msg["from"]),
                    int(msg["to"]),
                    msg.get("cap"),
                )
            elif t == "SparseBlocks":
                self._on_sparse_blocks(
                    peer,
                    msg["id"],
                    int(msg["from"]),
                    int(msg["len"]),
                    msg["sig"],
                    list(msg["blocks"]),
                    list(msg["proofs"]),
                )
            elif t == "Blocks":
                self._on_blocks(
                    peer,
                    msg["id"],
                    int(msg["from"]),
                    list(msg["blocks"]),
                    int(msg.get("len", -1)),
                    msg.get("sig"),
                    int(msg.get("total", -1)),
                )
        except (KeyError, TypeError, ValueError) as e:
            log("replication", f"malformed msg from {peer.id[:6]}: {e}")

    def _session_binding(self, peer: NetworkPeer) -> tuple:
        """(channel binding, our transport role) for the peer's CURRENT
        connection — the two session-unique values capability proofs MAC
        in (storage/integrity.capability). Plaintext/in-memory
        transports have no binding; proofs there are challenge+role-only."""
        conn = peer.connection
        if conn is None:  # connection torn down with messages in flight
            return (b"", None)
        return (conn.channel_binding or b"", conn.is_client)

    def _feed_length_msg(
        self, feed: Feed, peer: NetworkPeer, conceal: bool = False
    ) -> Optional[Dict]:
        """Our proof + length for a peer. `conceal` hides the real
        length from peers that haven't proven key knowledge yet (feed
        size is metadata the capability gates too). None when the peer's
        challenge hasn't arrived (its DiscoveryIds opener is in flight —
        the exchange resumes off their reply)."""
        with self._lock:
            challenge = self._challenge_remote.get(peer)
        if challenge is None:
            return None
        binding, we_are_client = self._session_binding(peer)
        return {
            "type": "FeedLength",
            "id": feed.discovery_id,
            "length": 0 if conceal else feed.length,
            "cap": capability(
                feed.public_key, challenge, binding, we_are_client
            ),
        }

    def _request_msg(
        self, feed: Feed, peer: NetworkPeer, start: int
    ) -> Optional[Dict]:
        with self._lock:
            challenge = self._challenge_remote.get(peer)
        if challenge is None:
            return None
        binding, we_are_client = self._session_binding(peer)
        return {
            "type": "Request",
            "id": feed.discovery_id,
            "from": start,
            "cap": capability(
                feed.public_key, challenge, binding, we_are_client
            ),
        }

    def _check_cap(
        self, peer: NetworkPeer, feed: Feed, cap
    ) -> bool:
        """Verify the sender's capability proof against OUR random
        per-connection challenge + the transport session binding + the
        sender's role (see storage/integrity.capability for what each
        binds against); on first success mark the peer
        replication-eligible for the feed (and reply with our own proof
        so both directions activate). Returns eligibility.

        Peers already verified for the feed short-circuit: follow-up
        messages (e.g. live-tail FeedLengths for unsigned feeds, which
        broadcast without per-peer caps) must not stall or log spurious
        failures."""
        if peer in self._verified.get(feed.discovery_id):
            return True
        binding, we_are_client = self._session_binding(peer)
        want = capability(
            feed.public_key,
            self._challenge_for(peer),
            binding,
            # the PROVER here is the peer (None = torn-down connection:
            # the compare below fails and the message is moot anyway)
            None if we_are_client is None else not we_are_client,
        )
        if not isinstance(cap, str) or not hmac.compare_digest(cap, want):
            log(
                "replication",
                f"capability check FAILED for {feed.public_key[:6]} "
                f"from {peer.id[:6]}: withholding blocks",
            )
            return False
        newly = self._verified.add(feed.discovery_id, peer)
        if newly:
            self._replicating.add(feed.discovery_id, peer)
            self._tail(feed)
            self._on_discovery(feed.public_key, peer)
            # prove ourselves back so the peer activates us too (the
            # exchange terminates: replies only fire on FIRST proof)
            reply = self._feed_length_msg(feed, peer)
            if reply is not None:
                self._send(peer, reply)
        return True

    def _on_discovery_ids(self, peer: NetworkPeer, ids: List[str]) -> None:
        for did in ids:
            feed = self.feeds.by_discovery_id(did)
            if feed is None:
                continue  # we don't know this feed's key — can't replicate
            self._tail(feed)
            # announce with our capability proof but CONCEAL the length:
            # the peer gets data (and metadata) only after proving its own
            msg = self._feed_length_msg(feed, peer, conceal=True)
            if msg is not None:
                self._send(peer, msg)

    def _on_feed_length(
        self, peer: NetworkPeer, did: str, their_len: int, cap
    ) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None:
            return
        if not self._check_cap(peer, feed, cap):
            return
        if feed.length < their_len:
            msg = self._request_msg(feed, peer, feed.length)
        elif feed.length > their_len:
            msg = self._feed_length_msg(feed, peer)
        else:
            return
        if msg is not None:
            self._send(peer, msg)

    def _pick_boundary(self, feed: Feed, start: int) -> int:
        """End of the next backfill chunk, bounded in BLOCKS and BYTES
        (a frame must stay far below tcp.py's 64MB cap). A feed we hold
        the secret key of can sign ANY boundary on demand
        (integrity.record_for), so the budgeted end is used directly;
        otherwise the largest STORED signed-record length within both
        budgets, else the first record past `start`, else the head
        (legacy unsigned feeds)."""
        have = feed.length
        if feed.integrity is None:
            return have
        writable = feed.secret_key is not None
        if not writable:
            lengths = [
                r[0] for r in feed.integrity.records() if r[0] > start
            ]
            if not lengths:
                return have
        # shrink the block budget until the byte budget holds
        want = min(have, start + _chunk_blocks())
        budget = _chunk_bytes()
        total = 0
        count = 0
        for b in feed.get_batch(start, want):
            total += len(b)
            count += 1
            if total > budget and count > 1:
                count -= 1
                break
        want = start + max(count, 1)
        if writable:
            return want
        within = [l for l in lengths if l <= want]
        if within:
            return max(within)
        end = min(lengths)
        if end - start > _chunk_blocks():
            log(
                "replication",
                f"sparse signature records on {feed.public_key[:6]}: "
                f"serving an oversized chunk {start}..{end}",
            )
        return end

    def _blocks_msg(self, feed: Feed, did: str, start: int, end: int):
        rec = (
            feed.integrity.record_for(feed, end)
            if feed.integrity is not None
            else None
        )
        return {
            "type": "Blocks",
            "id": did,
            "from": start,
            "blocks": [
                base64.b64encode(b).decode("ascii")
                for b in feed.get_batch(start, end)
            ],
            "len": end,
            "sig": (
                base64.b64encode(rec[2]).decode("ascii") if rec else None
            ),
            "total": feed.length,
        }

    def _on_request(
        self, peer: NetworkPeer, did: str, start: int, cap
    ) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None:
            return
        if not self._check_cap(peer, feed, cap):
            return  # no key knowledge proven: no data
        if start >= feed.length:
            return
        end = self._pick_boundary(feed, start)
        self._send(peer, self._blocks_msg(feed, did, start, end))

    def _on_blocks(
        self,
        peer: NetworkPeer,
        did: str,
        start: int,
        blocks: List[str],
        length: int,
        sig_b64: Optional[str],
        total: int,
    ) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None:
            return
        # an unverified peer's Blocks may still be appended (the merkle
        # signature chain is the real gate), but it earns no re-request
        # replies: a Request's `from` field is feed.length, metadata
        # _feed_length_msg deliberately conceals from peers that haven't
        # proven key knowledge
        verified = peer in self._verified.get(did)
        if start > feed.length:
            # gap: re-request from our actual head
            if verified:
                msg = self._request_msg(feed, peer, feed.length)
                if msg is not None:
                    self._send(peer, msg)
            return
        raw = [base64.b64decode(b) for b in blocks]
        if sig_b64 is not None and length >= 0:
            ok = feed.append_verified(
                start, raw, length, base64.b64decode(sig_b64)
            )
            if not ok:
                log(
                    "replication",
                    f"REJECTED unverified extension of "
                    f"{feed.public_key[:6]} from {peer.id[:6]} "
                    f"(len {length})",
                )
                return
        elif allow_unsigned():
            for i, b in enumerate(raw):
                index = start + i
                if index < feed.length:
                    continue  # duplicate
                feed._append_raw(b)
        else:
            log(
                "replication",
                f"DROPPED unsigned blocks for {feed.public_key[:6]} "
                f"from {peer.id[:6]} (set HM_ALLOW_UNSIGNED_FEEDS=1 "
                "to accept legacy feeds)",
            )
            return
        if total > feed.length and verified:
            # ack-paced stream: pull the next chunk
            msg = self._request_msg(feed, peer, feed.length)
            if msg is not None:
                self._send(peer, msg)

    def request_range(
        self, discovery_id: str, start: int, end: int
    ) -> bool:
        """Ask a verified peer for blocks [start, end) out of order
        (sparse fetch — e.g. prioritize the tail of a long feed for a
        progress UI while contiguous backfill catches up). ONE bounded
        chunk per call: the server clamps the reply to its block+byte
        budgets (HM_REPL_CHUNK / HM_REPL_CHUNK_BYTES) and serves
        contiguously from `start`, so watch the feed's sparse buffer
        and re-issue from the first missing index for more. Returns
        False when no verified peer holds the feed."""
        feed = self.feeds.by_discovery_id(discovery_id)
        if feed is None:
            return False
        for peer in self.peers_with_feed(discovery_id):
            with self._lock:
                challenge = self._challenge_remote.get(peer)
            if challenge is None:
                continue
            binding, we_are_client = self._session_binding(peer)
            with self._lock:
                w = self._sparse_wanted.setdefault(discovery_id, set())
                w.update(range(start, end))
                # unanswered requests must not leak for the process
                # lifetime (a peer may vanish before serving): bound the
                # outstanding set, shedding the indices FURTHEST out —
                # the same near-head-first policy as the sparse buffer
                cap = int(
                    os.environ.get("HM_SPARSE_WANTED_CAP", "8192")
                )
                if len(w) > cap:
                    for i in sorted(w, reverse=True)[: len(w) - cap]:
                        w.discard(i)
            self._send(peer, {
                "type": "RequestRange",
                "id": discovery_id,
                "from": start,
                "to": end,
                "cap": capability(
                    feed.public_key, challenge, binding, we_are_client
                ),
            })
            return True
        return False

    def _on_request_range(
        self, peer: NetworkPeer, did: str, start: int, end: int, cap
    ) -> None:
        feed = self.feeds.by_discovery_id(did)
        if feed is None or feed.integrity is None:
            return
        if not self._check_cap(peer, feed, cap):
            return  # no key knowledge proven: no data
        start = max(0, start)
        end = min(end, feed.length, start + _chunk_blocks())
        if start >= end:
            return
        # byte budget too: a frame must stay far below the transport cap
        budget = _chunk_bytes()
        total = 0
        count = 0
        for b in feed.get_batch(start, end):
            total += len(b)
            count += 1
            if total > budget and count > 1:
                count -= 1
                break
        end = start + max(count, 1)
        served = feed.integrity.range_proofs(feed, start, end)
        if served is None:
            return  # no signed record covers the range
        length, sig, pairs = served
        self._send(peer, {
            "type": "SparseBlocks",
            "id": did,
            "from": start,
            "len": length,
            "sig": base64.b64encode(sig).decode("ascii"),
            "blocks": [
                base64.b64encode(b).decode("ascii") for b, _p in pairs
            ],
            "proofs": [
                [base64.b64encode(h).decode("ascii") for h in p]
                for _b, p in pairs
            ],
        })

    def _on_sparse_blocks(
        self,
        peer: NetworkPeer,
        did: str,
        start: int,
        length: int,
        sig_b64: str,
        blocks: List[str],
        proofs: List[List[str]],
    ) -> None:
        from ..storage.integrity import verify_inclusion
        from ..utils import crypto

        feed = self.feeds.by_discovery_id(did)
        if feed is None or len(blocks) != len(proofs):
            return
        with self._lock:
            wanted = self._sparse_wanted.get(did)
        if not wanted:
            log(
                "replication",
                f"DROPPED unsolicited sparse blocks for "
                f"{feed.public_key[:6]} from {peer.id[:6]}",
            )
            return
        sig = base64.b64decode(sig_b64)
        for i, (b64, proof64) in enumerate(zip(blocks, proofs)):
            index = start + i
            with self._lock:
                if index not in wanted:
                    continue  # not an index we asked for: never lands
            raw = base64.b64decode(b64)
            ok = verify_inclusion(
                feed.public_key,
                crypto.leaf_hash(raw),
                index,
                length,
                [base64.b64decode(h) for h in proof64],
                sig,
            )
            if not ok:
                log(
                    "replication",
                    f"REJECTED sparse block {index} of "
                    f"{feed.public_key[:6]} from {peer.id[:6]}: "
                    "bad inclusion proof",
                )
                return
            if not feed.put_sparse(index, raw):
                continue  # sparse cap dropped it: stays outstanding so
                # a later re-serve of the re-issued request is accepted
            with self._lock:
                wanted.discard(index)
                # only retire the mapping if OUR set still backs it — a
                # concurrent request_range may have installed a fresh
                # set that must keep accepting its own response
                if not wanted and self._sparse_wanted.get(did) is wanted:
                    self._sparse_wanted.pop(did, None)

    def _tail(self, feed: Feed) -> None:
        with self._lock:
            if feed.public_key in self._tailed:
                return
            self._tailed.add(feed.public_key)

        def on_extended(start: int, end: int) -> None:
            # mark dirty and let the flusher coalesce: a burst of
            # appends within one flush window rides ONE signed frame
            self._flusher.mark(feed.public_key, start)

        feed.on_extended(on_extended)

    def _flush_batch(self, batch: Dict[str, int]) -> None:
        with telemetry.span("net.repl.flush", "net", feeds=len(batch)):
            for pk, start in batch.items():
                feed = self.feeds.get_feed(pk)
                if feed is None:
                    continue
                try:
                    self._flush_feed(feed, start)
                except Exception as e:  # a bad feed must not kill tails
                    log(
                        "replication", f"tail flush failed {pk[:6]}: {e}"
                    )

    def _flush_feed(self, feed: Feed, start: int) -> None:
        did = feed.discovery_id
        peers = self.peers_with_feed(did)
        if self._sampler is not None:
            # bounded fanout: the tail rides to a sampled subset; the
            # rest converge via relay hops and the anti-entropy sweep
            peers = self._sampler.sample(did, peers)
        if not peers:
            return
        head = feed.length
        while start < head:
            # _pick_boundary keeps each frame inside the chunk block +
            # byte budgets even when a window coalesced a huge range
            end = self._pick_boundary(feed, start)
            rec = (
                feed.integrity.record_for(feed, end)
                if feed.integrity is not None
                else None
            )
            if rec is None:
                # no signature at this length (mid-chunk race on a
                # relayed feed, or unsigned legacy): announce and let
                # peers pull a chunk we CAN sign for. Built per peer so
                # each frame carries that peer's capability proof —
                # receivers run _check_cap on every FeedLength, and
                # already-verified peers short-circuit either way
                for peer in peers:
                    msg = self._feed_length_msg(feed, peer)
                    if msg is not None:
                        self._send(peer, msg)
                return
            payload = self._blocks_msg(feed, did, start, end)
            for peer in peers:
                self._send(peer, payload)
            start = end

    def flush_now(self, timeout: float = 5.0) -> bool:
        """Block until every currently-dirty tail has FINISHED
        flushing (tests and orderly shutdown)."""
        return self._flusher.flush_now(timeout)

    # -- anti-entropy ---------------------------------------------------

    def _ae_loop(self) -> None:
        while not self._ae_stop.wait(self._ae_interval):
            ctl = self.overload_ctl
            if ctl is not None and ctl.deprioritize():
                # brownout: the sweep yields this period (the NEXT
                # healthy period repairs everything it would have —
                # idempotent latest-state, just one period later)
                ctl.note_skipped_sweep()
                continue
            try:
                self.sweep_now()
            except Exception as e:  # a bad peer must not kill the sweep
                log("replication", f"anti-entropy sweep failed: {e}")

    def sweep_now(self) -> int:
        """One anti-entropy pass NOW (the timer's body; tests call it
        directly): re-announce our length for every feed each verified
        peer replicates with us, and re-fire the discovery hook so the
        repo re-sends its CURSORS for the docs those feeds belong to.
        Both are idempotent latest-state — a peer that already matches
        ignores them; a peer that lost a tail frame (app-layer loss on
        a surviving connection), truncated in crash recovery, or
        missed a SAMPLED cursor gossip (the bounded-fanout relay,
        net/discovery/gossip.py — a one-shot broadcast a peer wasn't
        sampled into would otherwise be lost forever) requests the gap
        within one sweep period. Returns frames sent."""
        with self._lock:
            peers = list(self._peers)
        sent = 0
        for peer in peers:
            if not peer.is_connected:
                continue
            with self._lock:
                dids = list(self._verified.keys_with(peer))
            pks = []
            for did in dids:
                feed = self.feeds.by_discovery_id(did)
                if feed is None:
                    continue
                pks.append(feed.public_key)
                if feed.length == 0:
                    # nothing to repair FROM us: a zero-length feed's
                    # holder side announces (a fleet doc carries one
                    # empty placeholder feed per peer — re-announcing
                    # them all every sweep is O(peers^2) noise)
                    continue
                msg = self._feed_length_msg(feed, peer)
                if msg is not None:
                    self._send(peer, msg)
                    sent += 1
            if self.on_sweep is not None and pks:
                # cursor repair (ONE pass per peer, not per feed): a
                # bounded-fanout cursor gossip the peer wasn't sampled
                # into is one-shot — this bounds that staleness by the
                # sweep period (RepoBackend.send_sweep_cursors)
                try:
                    self.on_sweep(peer, pks)
                except Exception as e:  # repo-side hook bug: keep sweeping
                    log("replication", f"sweep cursor hook failed: {e}")
        self._m["antientropy_sweeps"].add(1)
        return sent

    def close(self) -> None:
        self._ae_stop.set()
        # drains: tails marked before close still reach peers
        self._flusher.close()
        # join the sweep thread BEFORE retiring the series: a sweep
        # finishing after the fold would bump a dropped handle and the
        # process snapshot would undercount rm.stats forever. The join
        # is bounded by one in-flight sweep (the stop flag already
        # short-circuits the next wait).
        t = self._ae_thread
        if t is not None:
            t.join(timeout=10.0)
        # registry hygiene: fold this manager's series into the closed
        # aggregate (stats stays readable — it is handle-based)
        telemetry.REGISTRY.retire(*self._m.values())

    def _send(self, peer: NetworkPeer, msg: Dict) -> None:
        self._m["frames_tx"].add(1)
        peer.try_send(CHANNEL, msg)
