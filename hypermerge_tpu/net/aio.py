"""Selector-based async transport: every TCP connection of a process
multiplexed onto ONE event-loop thread (`HM_NET_ASYNC=1`).

The thread-per-connection stack (net/tcp.py) spends 2 threads per
duplex (reader + writer) plus a keepalive thread per duplex plus a
parked session thread per supervised address plus a thread per accepted
handshake — ~4-5 threads per peer, which is exactly the wall the
50-daemon fleet hit. This module is the `=1` twin behind the SAME
`Duplex`/`Swarm`/`SessionSupervisor` seams:

- `AioLoop` — one lazily-created loop thread per process: a
  `selectors` poll over every non-blocking socket, a timer heap
  (keepalives fold into one wheel instead of a thread per duplex), a
  self-pipe wakeup, and a bounded dispatch pool (`HM_AIO_DISPATCH`)
  that runs user-facing callbacks OFF the loop so a blocking
  subscriber cannot stall every connection in the process.
- `AioDuplex` — the TcpDuplex contract (send never blocks / on_message
  single-subscriber queue / on_close multi-listener / outbox shed
  semantics / keepalive probes) driven entirely by loop callbacks: the
  handshake is an incremental state machine over the same wire frames
  (flags+key hello, optional encrypted ed25519 auth, net/secure.py),
  so the two stacks are bit-compatible on the wire and a process may
  run either side of a connection in either mode.

Ordering guarantees survive the multiplexing: per-direction nonce
counters stay strictly ordered because the single loop thread performs
every encrypt (tx) and decrypt (rx); inbound dispatch keeps the
`utils.queue.Queue` never-concurrent / never-reordered contract via a
per-connection pending deque drained by exactly one pool worker at a
time.

Wrappers (net/faults.py FaultDuplex) see only the public Duplex
surface — send/on_message/on_close/close/closed — so the chaos harness
wraps this transport unchanged.
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

from ..analysis.lockdep import make_condition, make_lock, make_rlock
from ..utils.debug import log
from .. import telemetry
from .tcp import (
    _HDR,
    _MAX_FRAME,
    _PING,
    _PONG,
    _outbox_cap,
    _ping_misses,
    _ping_s,
)

# process-wide async-transport telemetry (tools/top.py [net] group):
# `conns` is the live multiplexed-connection gauge, `loop_busy_ms` the
# cumulative non-idle time of the loop thread — busy/wall is the loop
# saturation ratio the 1000-peer bench watches.
_M_CONNS = telemetry.gauge("net.aio.conns")
_M_BUSY_MS = telemetry.counter("net.aio.loop_busy_ms")
_M_FRAMES_TX = telemetry.counter("net.aio.frames_tx")
_M_FRAMES_RX = telemetry.counter("net.aio.frames_rx")
_M_BYTES_TX = telemetry.counter("net.aio.bytes_tx")
_M_BYTES_RX = telemetry.counter("net.aio.bytes_rx")
_M_PINGS = telemetry.counter("net.aio.pings_tx")
_M_SHEDS = telemetry.counter("net.aio.sheds")

# per-event fairness budgets: one hot connection must not starve the
# rest of the loop (level-triggered polling re-fires what remains)
_RX_BUDGET = 1 << 20
_TX_FRAME_BUDGET = 64


def _dispatch_n() -> int:
    return int(os.environ.get("HM_AIO_DISPATCH", "8"))


class _Timer:
    """One timer-wheel entry; `cancel` is a monotonic latch (the heap
    lazily drops cancelled entries when they surface)."""

    __slots__ = ("deadline", "fn", "cancelled")

    def __init__(self, deadline: float, fn: Callable[[], None]) -> None:
        self.deadline = deadline
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class AioLoop:
    """The process event loop: selector + timer heap + dispatch pool.

    All selector mutation happens on the loop thread (callers schedule
    through `call_soon`); timers and ready callbacks are submitted from
    any thread. `offload(fn)` runs `fn` on a bounded pool worker — the
    ONLY place user-facing callbacks (message subscribers, close
    listeners, deliver hooks) ever run, so they may block freely."""

    def __init__(self) -> None:
        self._sel = selectors.DefaultSelector()
        self._lock = make_lock("net.aio")
        self._ready: deque = deque()
        self._timers: list = []  # heap of (deadline, seq, _Timer)
        self._timer_seq = itertools.count()
        # self-pipe: a submit from off-loop interrupts the poll
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        # bounded dispatch pool, demand-spawned up to HM_AIO_DISPATCH
        self._dispatch_cv = make_condition("net.aio.dispatch")
        self._dispatch_q: deque = deque()
        self._dispatch_idle = 0
        self._workers = 0
        self._worker_cap = _dispatch_n()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="aio-loop"
        )
        self._thread.start()

    # -- submission (any thread) ---------------------------------------

    def on_loop(self) -> bool:
        return threading.current_thread() is self._thread

    def call_soon(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._ready.append(fn)
        self._wakeup()

    def call_later(self, delay: float, fn: Callable[[], None]) -> _Timer:
        t = _Timer(time.monotonic() + max(0.0, delay), fn)
        with self._lock:
            heapq.heappush(
                self._timers, (t.deadline, next(self._timer_seq), t)
            )
        self._wakeup()
        return t

    def offload(self, fn: Callable[[], None]) -> None:
        """Run `fn` on a dispatch worker (never the loop thread)."""
        spawn = False
        with self._dispatch_cv:
            self._dispatch_q.append(fn)
            if self._dispatch_idle > 0:
                self._dispatch_cv.notify()
            elif self._workers < self._worker_cap:
                self._workers += 1
                spawn = True
        if spawn:
            threading.Thread(
                target=self._dispatch_run, daemon=True,
                name="aio-dispatch",
            ).start()

    def _wakeup(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass  # pipe full: a wakeup is already pending

    # -- the loop thread -----------------------------------------------

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._ready:
                    timeout = 0.0
                elif self._timers:
                    timeout = max(
                        0.0, self._timers[0][0] - time.monotonic()
                    )
                else:
                    timeout = None
            events = self._sel.select(timeout)
            t0 = time.monotonic()
            for key, mask in events:
                if key.fileobj is self._wake_r:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                    continue
                try:
                    key.data(mask)
                except Exception as e:  # a conn bug must not kill the loop
                    log("net:aio", f"io handler error: {e}")
            now = time.monotonic()
            due: List[_Timer] = []
            with self._lock:
                while self._timers and self._timers[0][0] <= now:
                    _d, _s, t = heapq.heappop(self._timers)
                    if not t.cancelled:
                        due.append(t)
            for t in due:
                try:
                    t.fn()
                except Exception as e:
                    log("net:aio", f"timer error: {e}")
            while True:
                with self._lock:
                    if not self._ready:
                        break
                    fn = self._ready.popleft()
                try:
                    fn()
                except Exception as e:
                    log("net:aio", f"callback error: {e}")
            _M_BUSY_MS.add((time.monotonic() - t0) * 1e3)

    def _dispatch_run(self) -> None:
        while True:
            with self._dispatch_cv:
                while not self._dispatch_q:
                    self._dispatch_idle += 1
                    self._dispatch_cv.wait()
                    self._dispatch_idle -= 1
                fn = self._dispatch_q.popleft()
            try:
                fn()
            except Exception as e:  # user callback bug: log, keep pool
                log("net:aio", f"dispatch error: {e}")

    # -- loop-side socket helpers (loop thread only) --------------------

    def register(self, sock, events, cb) -> None:
        self._sel.register(sock, events, cb)

    def modify(self, sock, events, cb) -> None:
        self._sel.modify(sock, events, cb)

    def unregister(self, sock) -> None:
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass

    # -- non-blocking dial ----------------------------------------------

    def dial(
        self,
        address: Tuple[str, int],
        timeout: float,
        cb: Callable[[Optional[socket.socket], Optional[OSError]], None],
    ) -> None:
        """Start a non-blocking connect; `cb(sock, exc)` fires exactly
        once on the LOOP thread (connected socket, or None + OSError on
        refusal/timeout). Keep `cb` cheap — offload real work."""

        def start() -> None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setblocking(False)
            try:
                err = sock.connect_ex(address)
            except OSError as e:
                sock.close()
                cb(None, e)
                return
            if err not in (0, 115, 36, 10035):  # EINPROGRESS variants
                sock.close()
                cb(None, OSError(err, os.strerror(err)))
                return
            state = {"done": False}

            def settle(exc: Optional[OSError]) -> None:
                if state["done"]:
                    return
                state["done"] = True
                timer.cancel()
                self.unregister(sock)
                if exc is not None:
                    sock.close()
                    cb(None, exc)
                else:
                    cb(sock, None)

            def on_writable(_mask: int) -> None:
                err = sock.getsockopt(
                    socket.SOL_SOCKET, socket.SO_ERROR
                )
                if err:
                    settle(OSError(err, os.strerror(err)))
                else:
                    settle(None)

            timer = self.call_later(
                timeout, lambda: settle(OSError("dial timed out"))
            )
            try:
                self.register(sock, selectors.EVENT_WRITE, on_writable)
            except (OSError, ValueError) as e:
                settle(OSError(str(e)))

        self.call_soon(start)


_BOOT_LOCK = make_lock("net.aio")
_LOOP: Optional[AioLoop] = None


def get_loop() -> AioLoop:
    """The process's shared loop, created on first use."""
    global _LOOP
    with _BOOT_LOCK:
        if _LOOP is None:
            _LOOP = AioLoop()
        return _LOOP


class AioDuplex:
    """TcpDuplex's contract over a non-blocking socket on the shared
    loop. Constructible from any thread; `on_ready(duplex, exc)` fires
    exactly once on a dispatch worker when the handshake completes
    (exc None) or fails/closes first (exc set) — the accept and async
    supervisor paths key off it instead of a blocking constructor."""

    def __init__(
        self,
        sock: socket.socket,
        is_client: bool = False,
        identity: Optional[bytes] = None,
        on_ready: Optional[Callable[["AioDuplex", Optional[BaseException]], None]] = None,
        loop: Optional[AioLoop] = None,
    ) -> None:
        from ..utils.queue import Queue

        self._loop = loop if loop is not None else get_loop()
        self._sock = sock
        sock.setblocking(False)
        self._identity = identity
        self._on_ready = on_ready
        self._lock = make_rlock("net.aio.conn")
        self._outbox: deque = deque()  # plaintext frames
        self._out_bytes = 0
        self._out_inflight = False  # loop holds a partially-sent frame
        self._out_cap = _outbox_cap()
        self._stall_s = float(os.environ.get("HM_TCP_STALL_S", "10"))
        self._last_progress = time.monotonic()
        self._drained = threading.Event()
        self._drained.set()
        self._shed = False
        self._rx_eof = False
        self._inbox: "Queue" = Queue("aio:inbox")
        self._close_cbs: List[Callable[[], None]] = []
        self._rx_pending: deque = deque()
        self._rx_scheduled = False
        self._ready_fired = False
        self.closed = False
        self._last_rx = time.monotonic()
        # loop-confined state (only the loop thread touches these)
        self._rbuf = bytearray()
        self._wbuf = b""
        self._registered = False
        self._events = 0
        self._tx_scheduled = False
        self._counted = False
        self._hs_timer: Optional[_Timer] = None
        self._ka_timer: Optional[_Timer] = None
        self._ka_misses = 0
        self._ka_probe = float("-inf")
        self._session = None
        self._hs_phase = "done"
        self._hs_offer = False
        if os.environ.get("HM_TCP_PLAINTEXT") != "1":
            from .secure import SecureSession

            self._session = SecureSession(is_client)
            self._hs_phase = "hello"
        self._loop.call_soon(self._start)

    # -- public Duplex surface -----------------------------------------

    @property
    def channel_binding(self) -> Optional[bytes]:
        return self._session.channel_binding if self._session else None

    @property
    def peer_identity(self) -> Optional[str]:
        return self._session.peer_identity if self._session else None

    def on_message(self, cb: Callable[[Any], None]) -> None:
        self._inbox.subscribe(cb)

    def on_close(self, cb: Callable[[], None]) -> None:
        """Multi-listener, TcpDuplex contract: registering after close
        fires immediately (on the caller's thread)."""
        fire_now = False
        with self._lock:
            if self.closed:
                fire_now = True
            else:
                self._close_cbs.append(cb)
        if fire_now:
            cb()

    def send(self, msg: Any) -> None:
        """Queue a frame; never blocks on the socket. Same shed policy
        as TcpDuplex.send: past the outbox cap with no completed frame
        for HM_TCP_STALL_S, or past 4x the cap regardless, the
        connection sheds and the supervised peer redials."""
        if self.closed:
            return
        data = json.dumps(msg, separators=(",", ":")).encode("utf-8")
        kick = False
        with self._lock:
            if self.closed:
                return
            if not self._outbox and not self._out_inflight:
                # idle -> active: stall clock measures THIS burst
                self._last_progress = time.monotonic()
            self._outbox.append(data)
            self._out_bytes += len(data)
            over = self._out_bytes > self._out_cap
            self._drained.clear()
            if not self._tx_scheduled:
                self._tx_scheduled = True
                kick = True
        if kick:
            self._loop.call_soon(self._tx_kick)
        if over and (
            self._out_bytes > 4 * self._out_cap
            or time.monotonic() - self._last_progress > self._stall_s
        ):
            log(
                "net:aio",
                f"outbox over cap ({self._out_bytes}B) with a stalled "
                "peer: shedding connection",
            )
            _M_SHEDS.add(1)
            self._shed = True
            self.close()

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            drain = (
                not self._shed
                and not self._rx_eof
                and not self._loop.on_loop()
                and bool(self._outbox or self._out_inflight)
            )
        if drain:
            # orderly close loses nothing: bounded drain window (the
            # loop keeps flushing until the outbox empties)
            self._drained.wait(5.0)
        with self._lock:
            if self.closed:
                return
            self.closed = True
            listeners = list(self._close_cbs)
            self._close_cbs.clear()
        self._finish_ready(OSError("closed before handshake completed"))
        self._rx_enqueue(("close", listeners))
        self._loop.call_soon(self._teardown)

    # -- loop-thread machinery -----------------------------------------

    def _start(self) -> None:
        """First loop callback: register, count, open the handshake."""
        if self.closed:
            return
        try:
            self._events = selectors.EVENT_READ
            self._loop.register(self._sock, self._events, self._on_io)
            self._registered = True
        except (OSError, ValueError) as e:
            self._fail(OSError(f"register failed: {e}"))
            return
        _M_CONNS.add(1)
        self._counted = True
        if self._session is None:
            self._hs_complete()
            return
        offer, mode = self._hs_posture()
        if mode == "require" and self._identity is None:
            self._fail(ValueError(
                "HM_NET_AUTH=require but no identity set"
            ))
            return
        self._hs_offer = offer
        frame = (
            bytes([1 if offer else 0]) + self._session.handshake_bytes
        )
        self._wbuf += _HDR.pack(len(frame)) + frame
        self._want_write(True)
        self._hs_timer = self._loop.call_later(
            10.0, lambda: self._fail(OSError("handshake timed out"))
        )

    def _hs_posture(self) -> Tuple[bool, str]:
        mode = os.environ.get("HM_NET_AUTH", "1")
        return (self._identity is not None and mode != "0", mode)

    def _on_io(self, mask: int) -> None:
        if self.closed:
            return
        if mask & selectors.EVENT_READ:
            self._handle_readable()
        if self.closed:
            return
        if mask & selectors.EVENT_WRITE:
            self._handle_writable()

    def _want_write(self, on: bool) -> None:
        if not self._registered:
            return
        events = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if on else 0
        )
        if events != self._events:
            self._events = events
            try:
                self._loop.modify(self._sock, events, self._on_io)
            except (OSError, ValueError, KeyError):
                pass  # torn down concurrently

    def _tx_kick(self) -> None:
        with self._lock:
            self._tx_scheduled = False
        if self.closed or not self._registered:
            return
        self._handle_writable()

    def _handle_writable(self) -> None:
        budget = _TX_FRAME_BUDGET
        while budget > 0:
            if not self._wbuf:
                if self._hs_phase != "done":
                    self._want_write(False)
                    return  # app frames wait for the handshake
                with self._lock:
                    if not self._outbox:
                        self._out_inflight = False
                        self._drained.set()
                        self._want_write(False)
                        return
                    data = self._outbox.popleft()
                    self._out_bytes -= len(data)
                    self._out_inflight = True
                # the single loop thread orders encryption: nonce
                # counters stay strictly per-direction sequential
                if self._session is not None:
                    data = self._session.encrypt(data)
                self._wbuf = _HDR.pack(len(data)) + data
                budget -= 1
                _M_FRAMES_TX.add(1)
                _M_BYTES_TX.add(len(self._wbuf))
            try:
                n = self._sock.send(self._wbuf)
            except (BlockingIOError, InterruptedError):
                self._want_write(True)
                return
            except OSError:
                self._wire_dead()
                return
            self._wbuf = self._wbuf[n:]
            if self._wbuf:
                self._want_write(True)
                return  # socket buffer full: resume on writable
            self._last_progress = time.monotonic()
        self._want_write(True)  # budget spent, more queued: re-fire

    def _handle_readable(self) -> None:
        got = 0
        while got < _RX_BUDGET:
            try:
                chunk = self._sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                chunk = b""
            if not chunk:
                self._rx_eof = True
                self.close()
                return
            got += len(chunk)
            self._rbuf += chunk
            self._last_rx = time.monotonic()
        while True:
            if len(self._rbuf) < _HDR.size:
                return
            (size,) = _HDR.unpack(bytes(self._rbuf[: _HDR.size]))
            if size > _MAX_FRAME:
                log("net:aio", f"oversized frame {size}, closing")
                self._rx_eof = True
                self.close()
                return
            if len(self._rbuf) < _HDR.size + size:
                return
            payload = bytes(self._rbuf[_HDR.size:_HDR.size + size])
            del self._rbuf[: _HDR.size + size]
            if self._hs_phase != "done":
                self._hs_frame(payload)
                if self.closed:
                    return
                continue
            _M_FRAMES_RX.add(1)
            _M_BYTES_RX.add(_HDR.size + size)
            if self._session is not None:
                payload = self._session.decrypt(payload)
                if payload is None:
                    # tampering or desync: fatal, never skippable
                    log("net:aio", "ciphertext auth failed, closing")
                    self._rx_eof = True
                    self.close()
                    return
            try:
                msg = json.loads(payload.decode("utf-8"))
            except ValueError:
                continue  # corrupt frame: skip
            if isinstance(msg, dict):
                # keepalive frames stop here, never reach subscribers
                if _PING in msg:
                    self.send({_PONG: msg[_PING]})
                    continue
                if _PONG in msg:
                    continue
            self._rx_enqueue(("msg", msg))

    # -- handshake state machine (loop thread) --------------------------

    def _hs_frame(self, payload: bytes) -> None:
        if self._hs_phase == "hello":
            if len(payload) == 33:
                peer_offers = bool(payload[0] & 1)
                peer_pk = payload[1:]
            elif len(payload) == 32:
                peer_offers = False  # legacy anonymous endpoint
                peer_pk = payload
            else:
                self._fail(ValueError(
                    f"bad handshake frame size {len(payload)}"
                ))
                return
            self._session.complete(peer_pk)
            _offer, mode = self._hs_posture()
            if self._hs_offer and peer_offers:
                auth = self._session.encrypt(
                    self._session.auth_frame(self._identity)
                )
                self._wbuf += _HDR.pack(len(auth)) + auth
                self._want_write(True)
                self._hs_phase = "auth"
            elif mode == "require":
                self._fail(ValueError(
                    "peer did not offer identity auth "
                    "(HM_NET_AUTH=require)"
                ))
            else:
                self._hs_complete()
        elif self._hs_phase == "auth":
            if len(payload) > 1024:
                self._fail(ValueError(
                    f"bad auth frame size {len(payload)}"
                ))
                return
            frame = self._session.decrypt(payload)
            if frame is None or not self._session.verify_auth(frame):
                self._fail(ValueError(
                    "peer identity authentication FAILED "
                    "(MITM key substitution or signature over a "
                    "different transcript)"
                ))
                return
            self._hs_complete()

    def _hs_complete(self) -> None:
        self._hs_phase = "done"
        if self._hs_timer is not None:
            self._hs_timer.cancel()
            self._hs_timer = None
        self._finish_ready(None)
        ping = _ping_s()
        if ping > 0:
            self._ka_timer = self._loop.call_later(ping, self._ka_tick)
        with self._lock:
            pending = bool(self._outbox)
        if pending:
            self._handle_writable()

    def _fail(self, exc: BaseException) -> None:
        log("net:aio", f"handshake failed: {exc}")
        self._finish_ready(exc)
        self._rx_eof = True  # no point draining a dead negotiation
        self.close()

    def _finish_ready(self, exc: Optional[BaseException]) -> None:
        with self._lock:
            if self._ready_fired:
                return
            self._ready_fired = True
        if self._on_ready is not None:
            self._rx_enqueue(("ready", exc))

    # -- keepalive on the shared timer wheel (loop thread) --------------

    def _ka_tick(self) -> None:
        if self.closed:
            return
        now = time.monotonic()
        # a miss is "nothing arrived since my last probe" — NOT "idle
        # at check time" (same rule as TcpDuplex._keepalive_loop)
        if self._last_rx >= self._ka_probe:
            self._ka_misses = 0
        else:
            self._ka_misses += 1
            if self._ka_misses >= _ping_misses():
                log(
                    "net:aio",
                    f"keepalive: {self._ka_misses} unanswered probes: "
                    "half-open, shedding",
                )
                _M_SHEDS.add(1)
                self._shed = True
                self.close()
                return
        if now - self._last_rx >= _ping_s():
            self.send({_PING: self._ka_misses})
            _M_PINGS.add(1)
            self._ka_probe = now
        self._ka_timer = self._loop.call_later(_ping_s(), self._ka_tick)

    # -- teardown -------------------------------------------------------

    def _wire_dead(self) -> None:
        with self._lock:
            self._out_inflight = False
        self._drained.set()  # the outbox will never drain: wake closers
        self._rx_eof = True
        self.close()

    def _teardown(self) -> None:
        """Final loop callback: unregister, close the socket, retire
        the timers and the conns gauge."""
        for t in (self._hs_timer, self._ka_timer):
            if t is not None:
                t.cancel()
        if self._registered:
            self._loop.unregister(self._sock)
            self._registered = False
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._drained.set()
        if self._counted:
            self._counted = False
            _M_CONNS.add(-1)

    # -- ordered inbound dispatch (any thread -> one pool worker) -------

    def _rx_enqueue(self, item: Tuple[str, Any]) -> None:
        with self._lock:
            self._rx_pending.append(item)
            if self._rx_scheduled:
                return
            self._rx_scheduled = True
        self._loop.offload(self._rx_drain)

    def _rx_drain(self) -> None:
        """Dispatch-pool drainer; the `_rx_scheduled` latch makes it
        exactly one worker at a time per connection, preserving the
        inbox Queue's never-concurrent / never-reordered contract."""
        while True:
            with self._lock:
                if not self._rx_pending:
                    self._rx_scheduled = False
                    return
                kind, payload = self._rx_pending.popleft()
            if kind == "msg":
                try:
                    self._inbox.push(payload)
                except Exception as e:  # subscriber bug: drop, log
                    log("net:aio", f"inbound handler error: {e}")
            elif kind == "ready":
                cb = self._on_ready
                if cb is not None:
                    try:
                        cb(self, payload)
                    except Exception as e:
                        log("net:aio", f"ready hook error: {e}")
            else:  # close listeners, after every queued message
                for cb in payload:
                    try:
                        cb()
                    except Exception as e:
                        log("net:aio", f"close listener error: {e}")
