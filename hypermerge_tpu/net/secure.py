"""SecureSession — transport encryption (+ identity auth) for sockets.

Parity: the reference wraps every raw peer socket in a noise-encrypted
stream before multiplexing (noise-peer, reference
src/PeerConnection.ts:36). Here the equivalent is libsodium's kx
pattern, upgraded to mutual authentication when the caller supplies a
static ed25519 identity (noise-peer's XX mode; the repo's own keypair
plays the static role):

  handshake  each side sends a fresh ephemeral X25519 public key (one
             32-byte frame, the only plaintext on the wire)
  keys       q = X25519(own_sk, peer_pk);
             rx||tx = BLAKE2b-512(q || client_pk || server_pk)
             (client takes rx first — libsodium crypto_kx key schedule)
  auth       (when an identity is set) the FIRST encrypted frame each
             direction is identity_pk(32) || ed25519 signature over
             "hm-auth-v1" || client_pk || server_pk || role. Signing
             the ephemeral transcript binds the session keys to the
             identity: an active MITM that substitutes its own
             ephemerals cannot re-sign the victims' transcripts, so
             `verify_auth` fails closed and the transport drops.
  frames     ChaCha20-Poly1305-IETF per frame; the 12-byte nonce is a
             per-direction little-endian counter (strictly ordered
             stream over TCP, so counters never repeat or reorder)

Threat model, stated precisely: WITHOUT an identity the handshake is an
anonymous NN exchange — per-frame integrity holds inside the session,
but an active MITM can terminate both sides and read/modify traffic.
WITH identities both peers are mutually authenticated and the claimed
repo id is pinned to the transport (net/network.py rejects an Info
whose peerId differs from the proven identity). Auth is negotiated in
the plaintext flags byte (net/tcp.py), so by default a MITM can strip
the offer and downgrade both sides to anonymous — deployments that
must exclude that set HM_NET_AUTH=require, which refuses
unauthenticated peers outright. Either way
`channel_binding` exports a value unique to this session's ephemeral
transcript; the replication capability layer MACs it into every proof
(storage/integrity.py `capability`), so proofs can never be replayed
across connections even in anonymous mode.

A tampered ciphertext fails authentication; the transport MUST treat
that as fatal and drop the connection (net/tcp.py does).

Crypto routes through the native layer (libsodium) with the pure-Python
RFC 7748/8439 fallback in utils/chacha.py — both produce identical
wire bytes, so mixed endpoints interoperate.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

from .. import native
from ..utils import chacha


def _x25519_base(sk: bytes) -> bytes:
    pk = native.x25519_base(sk)
    return pk if pk is not None else chacha.x25519_base(sk)


def _x25519(sk: bytes, pk: bytes) -> bytes:
    out = native.x25519(sk, pk)
    return out if out is not None else chacha.x25519(sk, pk)


def _aead_encrypt(key: bytes, nonce: bytes, msg: bytes) -> bytes:
    ct = native.aead_encrypt(key, nonce, msg)
    return ct if ct is not None else chacha.aead_encrypt(key, nonce, msg)


def _aead_decrypt(key: bytes, nonce: bytes, ct: bytes) -> Optional[bytes]:
    out = native.aead_decrypt(key, nonce, ct)
    if out is None:  # native unavailable
        return chacha.aead_decrypt(key, nonce, ct)
    if out is native._AEAD_FAIL:
        return None
    return out


class SecureSession:
    """One connection's encryption state. Usage:

        s = SecureSession(is_client)
        send_frame(s.handshake_bytes)        # 32-byte ephemeral pk
        s.complete(recv_frame())             # peer's 32 bytes
        wire = s.encrypt(plaintext_frame)
        plain = s.decrypt(wire)              # None = TAMPERED: drop conn
    """

    def __init__(self, is_client: bool) -> None:
        self.is_client = is_client
        self._sk = os.urandom(32)
        self.handshake_bytes = _x25519_base(self._sk)
        self._tx_key: Optional[bytes] = None
        self._rx_key: Optional[bytes] = None
        self._tx_n = 0
        self._rx_n = 0
        # session-unique exporter over the ephemeral transcript (set in
        # complete); MAC'd into replication capability proofs so they
        # cannot be replayed on another connection
        self.channel_binding: Optional[bytes] = None
        # peer's proven ed25519 identity (base58), set by verify_auth
        self.peer_identity: Optional[str] = None
        self._transcript: Optional[bytes] = None

    @property
    def ready(self) -> bool:
        return self._tx_key is not None

    def complete(self, peer_pk: bytes) -> None:
        if len(peer_pk) != 32:
            raise ValueError("bad handshake frame")
        q = _x25519(self._sk, peer_pk)
        if q == b"\x00" * 32:
            # low-order peer point: the shared secret is public data
            # (libsodium rejects these; the pure path must too)
            raise ValueError("low-order handshake key rejected")
        if self.is_client:
            client_pk, server_pk = self.handshake_bytes, peer_pk
        else:
            client_pk, server_pk = peer_pk, self.handshake_bytes
        keys = hashlib.blake2b(
            q + client_pk + server_pk, digest_size=64
        ).digest()
        if self.is_client:
            self._rx_key, self._tx_key = keys[:32], keys[32:]
        else:
            self._tx_key, self._rx_key = keys[:32], keys[32:]
        self._transcript = client_pk + server_pk
        self.channel_binding = hashlib.blake2b(
            b"hm-cb-v1" + self._transcript, digest_size=32
        ).digest()
        del self._sk

    # -- identity authentication (XX upgrade) --------------------------

    def _signable(self, as_client: bool) -> bytes:
        role = b"C" if as_client else b"S"
        return b"hm-auth-v1" + self._transcript + role

    def auth_frame(self, identity_seed: bytes) -> bytes:
        """identity_pk(32) || sig(64) over this session's transcript +
        OUR role. Must be sent encrypted, before any user frame."""
        from ..utils import crypto

        pub = crypto.public_key(identity_seed)
        sig = crypto.sign(self._signable(self.is_client), identity_seed)
        return pub + sig

    def verify_auth(self, frame: bytes) -> bool:
        """Verify the peer's auth frame (their role in the transcript);
        pins `peer_identity` on success. False = impersonation/MITM —
        the transport must drop the connection."""
        from ..utils import base58, crypto

        if len(frame) != 96:
            return False
        pub, sig = frame[:32], frame[32:]
        if not crypto.verify(
            self._signable(not self.is_client), sig, pub
        ):
            return False
        self.peer_identity = base58.encode(pub)
        return True

    def _nonce(self, n: int) -> bytes:
        return n.to_bytes(12, "little")

    def encrypt(self, frame: bytes) -> bytes:
        ct = _aead_encrypt(self._tx_key, self._nonce(self._tx_n), frame)
        self._tx_n += 1
        return ct

    def decrypt(self, wire: bytes) -> Optional[bytes]:
        """Plaintext frame, or None when authentication fails (tampering
        or desync) — the caller must close the connection."""
        out = _aead_decrypt(self._rx_key, self._nonce(self._rx_n), wire)
        if out is not None:
            self._rx_n += 1
        return out
